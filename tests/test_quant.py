"""int8 weight-only quantization + scaled int8 KV cache.

Parity target: the reference's default serving format is quantized (q4 GGUF
via llama.cpp, aio/cpu/text-to-text.yaml; GPTQ/EXL2 via the autogptq and
exllama2 Python backends). The TPU design keeps weights int8 in HBM and
dequantizes inside the matmul epilogue (models/quant.py).
"""

import dataclasses

import numpy as np
import pytest

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models.quant import (
    QuantizedTensor,
    dequantize_tensor,
    quantize_params,
    quantize_tensor,
)
from localai_tpu.models.registry import resolve_model


@pytest.fixture(scope="module")
def small():
    return resolve_model("debug:small")


def test_roundtrip_error_bounded(small):
    w = np.asarray(small.params["layers"]["w_gate"], np.float32)
    qt = quantize_tensor(small.params["layers"]["w_gate"], axis=1)
    err = np.abs(np.asarray(dequantize_tensor(qt)) - w)
    # symmetric per-channel int8: error ≤ scale/2 per element
    per_col_scale = np.abs(w).max(axis=1, keepdims=True) / 127.0
    assert (err <= per_col_scale / 2 + 1e-6).all()


def test_quantized_pytree_shapes(small):
    qp = quantize_params(small.params)
    cfg = small.cfg
    qt = qp["layers"]["wq"]
    assert isinstance(qt, QuantizedTensor)
    assert qt.q.dtype == np.int8
    assert qt.q.shape == (cfg.num_layers, cfg.hidden_size,
                          cfg.num_heads * cfg.hd)
    assert qt.scale.shape == (cfg.num_layers, cfg.num_heads * cfg.hd)
    # embed is per-row so both gather and tied logits stay per-channel
    assert qp["embed"].scale.shape == (cfg.vocab_size,)
    # norms stay unquantized
    assert not isinstance(qp["final_norm"], QuantizedTensor)


def test_greedy_decode_parity_int8_weights_and_kv(small):
    """int8 weights + scaled int8 KV must track bf16 greedy decode on the
    debug model (weight-only quantization is near-lossless at this scale)."""
    prompt = list(range(1, 60))
    r_bf = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=256,
                       prefill_buckets=[64])
    qp = quantize_params(small.params)
    r_q = ModelRunner(small.cfg, qp, num_slots=2, max_ctx=256,
                      prefill_buckets=[64], kv_dtype="int8")
    s_bf = r_bf.acquire_slot()
    s_q = r_q.acquire_slot()
    t_bf = [r_bf.admit(s_bf, prompt, temperature=0.0)]
    t_q = [r_q.admit(s_q, prompt, temperature=0.0)]
    for _ in range(16):
        t_bf.append(int(r_bf.step()[s_bf]))
        t_q.append(int(r_q.step()[s_q]))
    assert t_bf == t_q


def test_w8a8_greedy_parity(small):
    """The native-int8-dot mode (dynamic activation quant) must track bf16
    greedy decode on the debug model."""
    prompt = list(range(1, 60))
    r_bf = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=256,
                       prefill_buckets=[64])
    qp = quantize_params(small.params, "int8_w8a8")
    assert qp["layers"]["wq"].mode == "w8a8"
    r_q = ModelRunner(small.cfg, qp, num_slots=2, max_ctx=256,
                      prefill_buckets=[64], kv_dtype="int8")
    s_bf, s_q = r_bf.acquire_slot(), r_q.acquire_slot()
    a = [r_bf.admit(s_bf, prompt, temperature=0.0)]
    b = [r_q.admit(s_q, prompt, temperature=0.0)]
    for _ in range(12):
        a.append(int(r_bf.step()[s_bf]))
        b.append(int(r_q.step()[s_q]))
    assert a == b


def test_w8a8_matmul_numerics():
    """Direct check of the int8×int8 dot + dual-scale epilogue against the
    f32 reference, including the transposed (tied lm_head) path."""
    import jax
    import jax.numpy as jnp

    from localai_tpu.models.quant import matmul, matmul_t, quantize_tensor

    k = jax.random.key(0)
    x = jax.random.normal(k, (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    qt = dataclasses.replace(quantize_tensor(w, axis=0), mode="w8a8")
    ref = np.asarray(x @ w)
    got = np.asarray(matmul(x, qt), np.float32)
    # per-channel weight + per-token activation int8: ~1% relative error
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.02

    wt = jax.random.normal(jax.random.key(2), (32, 64), jnp.float32)
    qtt = dataclasses.replace(quantize_tensor(wt, axis=1), mode="w8a8")
    ref_t = np.asarray(x @ wt.T)
    got_t = np.asarray(matmul_t(x, qtt), np.float32)
    assert np.abs(got_t - ref_t).max() / np.abs(ref_t).max() < 0.02


def test_int8_kv_cache_is_scaled_not_cast(small):
    """The int8 KV path stores real scales — a raw dtype cast would zero
    out sub-unit activations and diverge immediately."""
    qp = quantize_params(small.params)
    r = ModelRunner(small.cfg, qp, num_slots=2, max_ctx=256,
                    prefill_buckets=[64], kv_dtype="int8")
    assert r.kv.quantized
    assert r.kv.k.dtype == np.int8
    assert r.kv.k_scale is not None
    s = r.acquire_slot()
    r.admit(s, list(range(1, 30)), temperature=0.0)
    ks = np.asarray(r.kv.k_scale, np.float32)
    # scales for the written positions are populated (non-zero)
    assert (ks[:, s, :, :29] > 0).all()
    # and the quantized values actually use the int8 range
    kq = np.asarray(r.kv.k[:, s, :, :29])
    assert np.abs(kq).max() > 32


def test_multi_step_and_frozen_dispatch_with_quantized(small):
    qp = quantize_params(small.params)
    r = ModelRunner(small.cfg, qp, num_slots=2, max_ctx=256,
                    prefill_buckets=[64], kv_dtype="int8")
    s = r.acquire_slot()
    r.admit(s, [1, 2, 3], temperature=0.0)
    toks = r.step_n(4)
    assert toks.shape == (4, 2)
    frozen = np.zeros(2, bool)
    frozen[s] = True
    toks = r.step_frozen_n(frozen, 4)
    assert toks.shape == (4, 2)


def test_quantized_under_mesh(small):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from localai_tpu.parallel import sharding as shd
    from localai_tpu.parallel.mesh import MeshPlan, build_mesh

    mesh = build_mesh(MeshPlan(data=2, model=4))
    qp = quantize_params(small.params)
    sp = shd.shard_params(qp, small.cfg, mesh)
    # vocab 512 divides tp=4: embed/lm_head scales must be model-sharded
    spec = sp["embed"].q.sharding.spec
    assert tuple(spec)[0] == "model"
    assert tuple(sp["embed"].scale.sharding.spec)[0] == "model"
    r = ModelRunner(small.cfg, sp, num_slots=4, max_ctx=256,
                    prefill_buckets=[64], mesh=mesh, kv_dtype="int8")
    s = r.acquire_slot()
    first = r.admit(s, list(range(1, 40)), temperature=0.0)
    seq = [first] + [int(r.step()[s]) for _ in range(6)]

    # parity vs unsharded bf16
    r_bf = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=256,
                       prefill_buckets=[64])
    s2 = r_bf.acquire_slot()
    ref = [r_bf.admit(s2, list(range(1, 40)), temperature=0.0)]
    ref += [int(r_bf.step()[s2]) for _ in range(6)]
    assert seq == ref


def test_engine_config_quantization_wires_through(tmp_path):
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.models.manager import build_serving_model

    mcfg = ModelConfig(
        name="q", model="debug:tiny", context_size=128,
        engine={"quantization": "int8", "kv_dtype": "int8", "max_slots": 2,
                "prefill_buckets": [32]},
    )
    sm = build_serving_model(mcfg, AppConfig(model_path=str(tmp_path)))
    try:
        assert isinstance(sm.runner.params["layers"]["wq"], QuantizedTensor)
        assert sm.runner.kv.quantized
        from localai_tpu.engine.scheduler import GenRequest

        h = sm.scheduler.submit(GenRequest(
            prompt=sm.tokenizer.encode("hi"), max_new_tokens=4, temperature=0.0,
        ))
        out = h.result(timeout=60)
        assert out.finish_reason in ("stop", "length")
    finally:
        sm.scheduler.shutdown()


def test_int4_roundtrip_error_bounded(small):
    from localai_tpu.models.quant import quantize_tensor4

    w = np.asarray(small.params["layers"]["w_gate"], np.float32)
    qt = quantize_tensor4(small.params["layers"]["w_gate"], axis=1, group=64)
    assert str(qt.q.dtype) == "int4"
    assert qt.mode == "w4"
    L, K, N = w.shape
    assert qt.scale.shape == (L, K // 64, N)
    deq = np.asarray(dequantize_tensor(qt), np.float32)
    err = np.abs(deq - w)
    # symmetric group-wise int4: per-element error ≤ group scale / 2
    scale = np.abs(w.reshape(L, K // 64, 64, N)).max(axis=2) / 7.0
    bound = np.repeat(scale, 64, axis=1) / 2 + 1e-6
    assert (err <= bound).all()


def test_int4_matmul_numerics():
    import jax
    import jax.numpy as jnp

    from localai_tpu.models.quant import matmul, matmul_t, quantize_tensor4

    x = jax.random.normal(jax.random.key(0), (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    qt = quantize_tensor4(w, axis=0, group=16)
    # the grouped-einsum path must be exact against the dequantized weight
    # (the quantization error itself is the roundtrip test's concern)
    ref = np.asarray(x @ dequantize_tensor(qt))
    got = np.asarray(matmul(x, qt), np.float32)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    # matmul_t deliberately has no w4 path (embedding tables stay int8 in
    # int4 mode); axis=0 grouping also covers the untied lm_head layout
    wh = jax.random.normal(jax.random.key(2), (64, 128), jnp.float32)
    qth = quantize_tensor4(wh, axis=0, group=32)
    ref_h = np.asarray(x @ dequantize_tensor(qth))
    got_h = np.asarray(matmul(x, qth), np.float32)
    np.testing.assert_allclose(got_h, ref_h, rtol=1e-5, atol=1e-5)


def test_int4_serving_matches_dequantized_reference(small):
    """The int4 serving path must faithfully represent its own quantized
    weights: final-hidden embeddings under the grouped-einsum path track a
    runner fed the explicitly dequantized params (random gaussian debug
    weights are the quantization worst case, so bf16-vs-int4 closeness is
    the roundtrip test's concern — this pins the compute path)."""
    import jax

    from localai_tpu.models.quant import QuantizedTensor

    prompt = list(range(1, 60))
    qp = quantize_params(small.params, "int4", group=64)
    assert qp["layers"]["wq"].mode == "w4"
    assert qp["layers"]["wq"].group == 64
    deq = jax.tree.map(
        lambda a: (dequantize_tensor(a, small.cfg.dtype)
                   if isinstance(a, QuantizedTensor) else a),
        qp, is_leaf=lambda a: isinstance(a, QuantizedTensor),
    )
    r_q = ModelRunner(small.cfg, qp, num_slots=2, max_ctx=256,
                      prefill_buckets=[64], kv_dtype="int8")
    r_d = ModelRunner(small.cfg, deq, num_slots=2, max_ctx=256,
                      prefill_buckets=[64], kv_dtype="int8")
    e_q = r_q.embed(prompt)
    e_d = r_d.embed(prompt)
    cos = float(np.dot(e_q, e_d) /
                (np.linalg.norm(e_q) * np.linalg.norm(e_d) + 1e-9))
    assert cos > 0.999


def test_int4_greedy_decode_runs(small):
    """int4 weights + int8 KV serve end to end (greedy, multi-step)."""
    qp = quantize_params(small.params, "int4", group=64)
    r = ModelRunner(small.cfg, qp, num_slots=2, max_ctx=256,
                    prefill_buckets=[64], kv_dtype="int8")
    s = r.acquire_slot()
    first = r.admit(s, list(range(1, 40)), temperature=0.0)
    toks = [first] + [int(t[s]) for t in r.step_n(6)]
    assert all(0 <= t < small.cfg.vocab_size for t in toks)


def test_int4_under_mesh(small):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from localai_tpu.parallel import sharding as shd
    from localai_tpu.parallel.mesh import MeshPlan, build_mesh

    mesh = build_mesh(MeshPlan(data=2, model=4))
    qp = quantize_params(small.params, "int4", group=64)
    sp = shd.shard_params(qp, small.cfg, mesh)
    # group-wise scales keep the contraction axis: spec mirrors the weight
    wq = sp["layers"]["wq"]
    assert wq.scale.shape[1] == small.cfg.hidden_size // 64
    r = ModelRunner(small.cfg, sp, num_slots=4, max_ctx=256,
                    prefill_buckets=[64], mesh=mesh, kv_dtype="int8")
    s = r.acquire_slot()
    first = r.admit(s, list(range(1, 40)), temperature=0.0)
    seq = [first] + [int(r.step()[s]) for _ in range(4)]
    assert all(0 <= t < small.cfg.vocab_size for t in seq)


def test_kernel_block_is_per_tensor_not_process_global(monkeypatch):
    """ADVICE r5 #1: a meshed runner blocks the Pallas kernel for ITS OWN
    weights only — tensors quantized afterwards keep the env opt-in."""
    import jax.numpy as jnp

    from localai_tpu.models import quant as qnt
    from localai_tpu.ops import qmatmul

    monkeypatch.setenv("LOCALAI_W8_KERNEL", "interpret")
    calls = []
    real = qmatmul.w8_matmul

    def spy(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    monkeypatch.setattr(qmatmul, "w8_matmul", spy)
    rng = np.random.default_rng(7)
    w = rng.normal(size=(128, 128)).astype(np.float32) * 0.02
    x = jnp.asarray(rng.normal(size=(4, 128)), jnp.float32)
    qt = quantize_tensor(w, axis=0)
    blocked = qnt.block_w8_kernel_params({"w": qt}, "meshed runner")["w"]
    assert not blocked.kernel_ok and qt.kernel_ok

    ref = np.asarray(qnt.matmul(x, blocked))      # blocked → XLA path
    assert calls == []
    out = np.asarray(qnt.matmul(x, qt))           # fresh tensor → kernel
    assert calls, "unblocked tensor did not take the Pallas kernel"
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_meshed_runner_blocks_only_its_own_params(small):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from localai_tpu.models.quant import QuantizedTensor
    from localai_tpu.parallel import sharding as shd
    from localai_tpu.parallel.mesh import MeshPlan, build_mesh

    mesh = build_mesh(MeshPlan(data=2, model=4))
    qp = shd.shard_params(quantize_params(small.params, "int8"),
                          small.cfg, mesh)
    meshed = ModelRunner(small.cfg, qp, num_slots=4, max_ctx=256,
                         prefill_buckets=[64], mesh=mesh, kv_dtype="int8")
    leaves = jax.tree.leaves(
        meshed.params,
        is_leaf=lambda x: isinstance(x, QuantizedTensor))
    qts = [l for l in leaves if isinstance(l, QuantizedTensor)]
    assert qts and all(not t.kernel_ok for t in qts)
    # a LATER single-device runner keeps the kernel opt-in on its weights
    single = ModelRunner(small.cfg, quantize_params(small.params, "int8"),
                         num_slots=2, max_ctx=256, prefill_buckets=[64],
                         kv_dtype="int8")
    leaves = jax.tree.leaves(
        single.params,
        is_leaf=lambda x: isinstance(x, QuantizedTensor))
    assert all(t.kernel_ok for t in leaves
               if isinstance(t, QuantizedTensor))
