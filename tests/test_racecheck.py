"""tools.racecheck: the instrumented-lock lock-order harness.

Cycle detection on a synthetic ABBA inversion, clean runs on ordered
acquisition, RLock reentrancy, same-site instance-pair semantics, and
the install/uninstall patching contract.
"""

import subprocess
import sys
import threading
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.racecheck import LockMonitor  # noqa: E402


def run_in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(10)
    assert not t.is_alive()


def make_locks(mon, n=2, rlock=False):
    """n traced locks, each from a DISTINCT creation site."""
    with mon:
        if rlock:
            out = [threading.RLock() for _ in range(1)]  # site A
            out += [threading.RLock() for _ in range(n - 1)]  # site B
        else:
            out = [threading.Lock() for _ in range(1)]
            out += [threading.Lock() for _ in range(n - 1)]
    return out


def test_abba_inversion_detected():
    mon = LockMonitor()
    a, b = make_locks(mon)

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    run_in_thread(t1)
    run_in_thread(t2)
    inv = mon.inversions()
    assert len(inv) == 1
    report = mon.report()
    assert "1 inversion" in report
    # the report names both edges of the cycle with a stack each
    assert report.count("first acquired at") == 2


def test_ordered_acquisition_is_clean():
    mon = LockMonitor()
    a, b = make_locks(mon)

    def worker():
        with a:
            with b:
                pass

    for _ in range(3):
        run_in_thread(worker)
    assert mon.inversions() == []
    assert ("tests/test_racecheck.py" in next(iter(mon.edges()))[0])


def test_three_lock_cycle_detected():
    # A->B, B->C, C->A: no single ABBA pair, still a deadlock cycle
    mon = LockMonitor()
    with mon:
        a = threading.Lock()
        b = threading.Lock()
        c = threading.Lock()

    for first, second in ((a, b), (b, c), (c, a)):
        def nest(first=first, second=second):
            with first:
                with second:
                    pass
        run_in_thread(nest)
    inv = mon.inversions()
    assert len(inv) == 1
    assert len(inv[0].cycle) == 4  # three nodes, closed back to the anchor


def test_rlock_reentrancy_is_not_an_edge():
    mon = LockMonitor()
    (lk,) = make_locks(mon, n=1, rlock=True)

    def worker():
        with lk:
            with lk:  # reentrant re-acquire cannot block
                pass

    run_in_thread(worker)
    assert mon.inversions() == []
    assert mon.edges() == {}


def test_same_site_consistent_order_is_clean_but_inversion_flags():
    # two instances from ONE construction site: nesting them in a
    # consistent order is legal; both orders is the per-instance ABBA
    mon = LockMonitor()
    with mon:
        locks = [threading.Lock() for _ in range(2)]
    i1, i2 = locks

    def consistent():
        with i1:
            with i2:
                pass

    run_in_thread(consistent)
    run_in_thread(consistent)
    assert mon.inversions() == []

    def inverted():
        with i2:
            with i1:
                pass

    run_in_thread(inverted)
    inv = mon.inversions()
    assert len(inv) == 1
    assert "instance" in inv[0].cycle[0]


def test_install_uninstall_restores_primitives():
    real_lock = threading.Lock
    real_rlock = threading.RLock
    mon = LockMonitor()
    mon.install()
    try:
        assert threading.Lock is not real_lock
        traced = threading.Lock()
    finally:
        mon.uninstall()
    assert threading.Lock is real_lock
    assert threading.RLock is real_rlock
    # locks created while installed keep working after uninstall
    with traced:
        assert traced.locked()
    assert not traced.locked()
    assert mon.locks_created >= 1


def test_nonblocking_acquire_records_no_edge():
    mon = LockMonitor()
    a, b = make_locks(mon)

    def worker():
        with a:
            # a try-lock cannot deadlock this thread: must not add a->b
            assert b.acquire(blocking=False)
            b.release()

    run_in_thread(worker)
    assert mon.edges() == {}


def test_event_and_queue_still_work_under_instrumentation():
    # Condition/Event/Queue are built ON the patched primitives — the
    # wrapper must satisfy their duck-typed lock contract
    import queue

    mon = LockMonitor()
    with mon:
        ev = threading.Event()
        q = queue.Queue()
        cond = threading.Condition()

    def producer():
        q.put(1)
        ev.set()
        with cond:
            cond.notify_all()

    run_in_thread(producer)
    assert ev.wait(5)
    assert q.get(timeout=5) == 1
    with cond:
        pass
    assert mon.inversions() == []


def test_condition_wait_on_recursively_held_rlock_keeps_tracking():
    # Condition.wait() fully releases a recursively-held RLock and then
    # restores the full depth: the monitor must re-add EVERY level, or
    # the first post-wait release() forgets the lock while the thread
    # still owns it and edges acquired afterwards are silently dropped
    mon = LockMonitor()
    with mon:
        rl = threading.RLock()
        cond = threading.Condition(rl)
        other = threading.Lock()

    woke = threading.Event()

    def waiter():
        with rl:           # depth 1
            with cond:     # depth 2 (Condition shares rl)
                cond.wait(5)
            # depth back to 1: rl is STILL held here
            with other:    # must record the rl -> other edge
                pass
        woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    # let the waiter reach wait(), then wake it
    import time
    for _ in range(100):
        time.sleep(0.02)
        with cond:
            cond.notify_all()
        if woke.is_set():
            break
    t.join(10)
    assert not t.is_alive()
    assert any("test_racecheck" in a and "test_racecheck" in b
               for a, b in mon.edges())
    assert mon.inversions() == []


def test_same_site_pairs_key_on_serials_not_ids():
    # instance identity must survive GC: serials are process-unique, so
    # a recycled id() can never pair two locks that never coexisted
    mon = LockMonitor()
    with mon:
        locks = [threading.Lock() for _ in range(3)]
    serials = [lk.serial for lk in locks]
    assert len(set(serials)) == 3
    del locks
    with mon:
        fresh = [threading.Lock() for _ in range(3)]
    assert not set(serials) & {lk.serial for lk in fresh}


def test_edges_survive_exceptions_in_critical_section():
    mon = LockMonitor()
    a, b = make_locks(mon)

    def worker():
        try:
            with a:
                with b:
                    raise RuntimeError("boom")
        except RuntimeError:
            pass

    run_in_thread(worker)
    # the with-blocks released both locks despite the raise
    assert not a.locked() and not b.locked()
    assert len(mon.edges()) == 1


def test_lazy_threadpool_import_under_monitor():
    # concurrent.futures.thread registers lock._at_fork_reinit with
    # os.register_at_fork at IMPORT time, so a monitor-created lock must
    # answer it — or the first lazy ThreadPoolExecutor import while the
    # monitor is installed (fleetview's concurrent telemetry harvest
    # during the --racecheck smoke) dies with "cannot import name".
    # A subprocess guarantees the module is genuinely not yet imported.
    code = (
        "import sys\n"
        f"sys.path.insert(0, {str(REPO)!r})\n"
        "assert 'concurrent.futures.thread' not in sys.modules\n"
        "from tools.racecheck import LockMonitor\n"
        "mon = LockMonitor()\n"
        "mon.install()\n"
        "from concurrent.futures import ThreadPoolExecutor\n"
        "with ThreadPoolExecutor(max_workers=1) as ex:\n"
        "    assert ex.submit(int, '7').result() == 7\n"
        "mon.uninstall()\n"
        "print('OK')\n"
    )
    res = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "OK" in res.stdout
