"""FLUX-class DiT verification (VERDICT r4 #6).

The MMDiT forward is checked against an INDEPENDENT torch implementation
written here from the diffusers FluxTransformer2DModel semantics, driven
off the same diffusers-named state dict that the repo loader consumes —
one fixture checkpoint verifies both the tensor-name mapping and the math.
The T5 encoder is checked against transformers' real T5EncoderModel.
diffusers itself is not installed in this environment (zero egress).
"""

import json
import math

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from localai_tpu.image import mmdit  # noqa: E402


CFG = dict(in_channels=16, num_layers=2, num_single_layers=2,
           attention_head_dim=8, num_attention_heads=3,
           joint_attention_dim=24, pooled_projection_dim=20,
           guidance_embeds=True, axes_dims_rope=(2, 4, 2))


def _state_dict(cfg, seed=0):
    """Random diffusers-named FluxTransformer2DModel state dict (torch)."""
    g = torch.Generator().manual_seed(seed)
    D = cfg["attention_head_dim"] * cfg["num_attention_heads"]
    F = 4 * D
    sd = {}

    def lin(name, i, o):
        sd[f"{name}.weight"] = torch.randn(o, i, generator=g) * 0.05
        sd[f"{name}.bias"] = torch.randn(o, generator=g) * 0.02

    lin("x_embedder", cfg["in_channels"], D)
    lin("context_embedder", cfg["joint_attention_dim"], D)
    for stem, i in (("timestep_embedder", 256),
                    ("guidance_embedder", 256),
                    ("text_embedder", cfg["pooled_projection_dim"])):
        lin(f"time_text_embed.{stem}.linear_1", i, D)
        lin(f"time_text_embed.{stem}.linear_2", D, D)
    lin("norm_out.linear", D, 2 * D)
    lin("proj_out", D, cfg["in_channels"])
    for i in range(cfg["num_layers"]):
        B = f"transformer_blocks.{i}"
        lin(f"{B}.norm1.linear", D, 6 * D)
        lin(f"{B}.norm1_context.linear", D, 6 * D)
        for n in ("to_q", "to_k", "to_v", "to_out.0",
                  "add_q_proj", "add_k_proj", "add_v_proj", "to_add_out"):
            lin(f"{B}.attn.{n}", D, D)
        for n in ("norm_q", "norm_k", "norm_added_q", "norm_added_k"):
            sd[f"{B}.attn.{n}.weight"] = \
                1 + torch.randn(cfg["attention_head_dim"], generator=g) * 0.1
        lin(f"{B}.ff.net.0.proj", D, F)
        lin(f"{B}.ff.net.2", F, D)
        lin(f"{B}.ff_context.net.0.proj", D, F)
        lin(f"{B}.ff_context.net.2", F, D)
    for i in range(cfg["num_single_layers"]):
        B = f"single_transformer_blocks.{i}"
        lin(f"{B}.norm.linear", D, 3 * D)
        for n in ("to_q", "to_k", "to_v"):
            lin(f"{B}.attn.{n}", D, D)
        for n in ("norm_q", "norm_k"):
            sd[f"{B}.attn.{n}.weight"] = \
                1 + torch.randn(cfg["attention_head_dim"], generator=g) * 0.1
        lin(f"{B}.proj_mlp", D, F)
        lin(f"{B}.proj_out", D + F, D)
    return sd


# -- independent torch reference (diffusers FluxTransformer2DModel math) ----

def _t_emb(t, dim=256):
    half = dim // 2
    freqs = torch.exp(-math.log(10000.0) * torch.arange(half) / half)
    args = t[:, None].float() * freqs[None]
    return torch.cat([args.cos(), args.sin()], dim=-1)


def _mlp2(sd, p, x):
    x = torch.nn.functional.silu(x @ sd[f"{p}.linear_1.weight"].T
                                 + sd[f"{p}.linear_1.bias"])
    return x @ sd[f"{p}.linear_2.weight"].T + sd[f"{p}.linear_2.bias"]


def _ln(x):
    return torch.nn.functional.layer_norm(x, x.shape[-1:], eps=1e-6)


def _rms(x, w):
    v = (x.float() ** 2).mean(-1, keepdim=True)
    return x * torch.rsqrt(v + 1e-6) * w


def _rope(cfg, ids):
    cos_p, sin_p = [], []
    for ax, dim in enumerate(cfg["axes_dims_rope"]):
        freqs = 1.0 / (10000.0 ** (torch.arange(0, dim, 2).float() / dim))
        ang = ids[:, ax].float()[:, None] * freqs[None]
        cos_p.append(ang.cos().repeat_interleave(2, dim=-1))
        sin_p.append(ang.sin().repeat_interleave(2, dim=-1))
    return torch.cat(cos_p, -1), torch.cat(sin_p, -1)


def _apply_rope_t(x, cos, sin):
    xr = x.reshape(*x.shape[:-1], -1, 2)
    rot = torch.stack([-xr[..., 1], xr[..., 0]], dim=-1).reshape(x.shape)
    return x * cos + rot * sin


def _attn(q, k, v):
    hd = q.shape[-1]
    s = torch.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
    return torch.einsum("bhqk,bhkd->bhqd", s.softmax(-1), v)


def _heads(x, H):
    B, N, _ = x.shape
    return x.reshape(B, N, H, -1).permute(0, 2, 1, 3)


def _unheads(x):
    B, H, N, hd = x.shape
    return x.permute(0, 2, 1, 3).reshape(B, N, H * hd)


def _qkv(sd, p, x, H, qn, kn):
    q = _heads(x @ sd[f"{p}.to_q.weight"].T + sd[f"{p}.to_q.bias"], H)
    k = _heads(x @ sd[f"{p}.to_k.weight"].T + sd[f"{p}.to_k.bias"], H)
    v = _heads(x @ sd[f"{p}.to_v.weight"].T + sd[f"{p}.to_v.bias"], H)
    return _rms(q, sd[qn]), _rms(k, sd[kn]), v


def torch_flux_forward(cfg, sd, img, txt, pooled, t, img_ids, txt_ids,
                       guidance):
    H = cfg["num_attention_heads"]
    Ntxt = txt.shape[1]
    temb = _mlp2(sd, "time_text_embed.timestep_embedder", _t_emb(t * 1000))
    temb = temb + _mlp2(sd, "time_text_embed.guidance_embedder",
                        _t_emb(guidance * 1000))
    temb = temb + _mlp2(sd, "time_text_embed.text_embedder", pooled)
    semb = torch.nn.functional.silu(temb)

    x = img @ sd["x_embedder.weight"].T + sd["x_embedder.bias"]
    c = txt @ sd["context_embedder.weight"].T + sd["context_embedder.bias"]
    cos, sin = _rope(cfg, torch.cat([txt_ids, img_ids], dim=0))

    for i in range(cfg["num_layers"]):
        B = f"transformer_blocks.{i}"
        mx = (semb @ sd[f"{B}.norm1.linear.weight"].T
              + sd[f"{B}.norm1.linear.bias"])[:, None]
        mc = (semb @ sd[f"{B}.norm1_context.linear.weight"].T
              + sd[f"{B}.norm1_context.linear.bias"])[:, None]
        shx, scx, gx, shmx, scmx, gmx = mx.chunk(6, dim=-1)
        shc, scc, gc, shmc, scmc, gmc = mc.chunk(6, dim=-1)
        xn = _ln(x) * (1 + scx) + shx
        cn = _ln(c) * (1 + scc) + shc
        qx, kx, vx = _qkv(sd, f"{B}.attn", xn, H,
                          f"{B}.attn.norm_q.weight",
                          f"{B}.attn.norm_k.weight")
        qc = _heads(cn @ sd[f"{B}.attn.add_q_proj.weight"].T
                    + sd[f"{B}.attn.add_q_proj.bias"], H)
        kc = _heads(cn @ sd[f"{B}.attn.add_k_proj.weight"].T
                    + sd[f"{B}.attn.add_k_proj.bias"], H)
        vc = _heads(cn @ sd[f"{B}.attn.add_v_proj.weight"].T
                    + sd[f"{B}.attn.add_v_proj.bias"], H)
        qc = _rms(qc, sd[f"{B}.attn.norm_added_q.weight"])
        kc = _rms(kc, sd[f"{B}.attn.norm_added_k.weight"])
        q = _apply_rope_t(torch.cat([qc, qx], dim=2), cos, sin)
        k = _apply_rope_t(torch.cat([kc, kx], dim=2), cos, sin)
        att = _unheads(_attn(q, k, torch.cat([vc, vx], dim=2)))
        ac, ax_ = att[:, :Ntxt], att[:, Ntxt:]
        x = x + gx * (ax_ @ sd[f"{B}.attn.to_out.0.weight"].T
                      + sd[f"{B}.attn.to_out.0.bias"])
        xm = _ln(x) * (1 + scmx) + shmx
        h1 = torch.nn.functional.gelu(
            xm @ sd[f"{B}.ff.net.0.proj.weight"].T
            + sd[f"{B}.ff.net.0.proj.bias"], approximate="tanh")
        x = x + gmx * (h1 @ sd[f"{B}.ff.net.2.weight"].T
                       + sd[f"{B}.ff.net.2.bias"])
        c = c + gc * (ac @ sd[f"{B}.attn.to_add_out.weight"].T
                      + sd[f"{B}.attn.to_add_out.bias"])
        cm = _ln(c) * (1 + scmc) + shmc
        h2 = torch.nn.functional.gelu(
            cm @ sd[f"{B}.ff_context.net.0.proj.weight"].T
            + sd[f"{B}.ff_context.net.0.proj.bias"], approximate="tanh")
        c = c + gmc * (h2 @ sd[f"{B}.ff_context.net.2.weight"].T
                       + sd[f"{B}.ff_context.net.2.bias"])

    s = torch.cat([c, x], dim=1)
    for i in range(cfg["num_single_layers"]):
        B = f"single_transformer_blocks.{i}"
        m = (semb @ sd[f"{B}.norm.linear.weight"].T
             + sd[f"{B}.norm.linear.bias"])[:, None]
        sh, sc, gt = m.chunk(3, dim=-1)
        sn = _ln(s) * (1 + sc) + sh
        q, k, v = _qkv(sd, f"{B}.attn", sn, H,
                       f"{B}.attn.norm_q.weight", f"{B}.attn.norm_k.weight")
        att = _unheads(_attn(_apply_rope_t(q, cos, sin),
                             _apply_rope_t(k, cos, sin), v))
        mlp = torch.nn.functional.gelu(
            sn @ sd[f"{B}.proj_mlp.weight"].T + sd[f"{B}.proj_mlp.bias"],
            approximate="tanh")
        s = s + gt * (torch.cat([att, mlp], dim=-1)
                      @ sd[f"{B}.proj_out.weight"].T
                      + sd[f"{B}.proj_out.bias"])
    x = s[:, Ntxt:]
    om = (semb @ sd["norm_out.linear.weight"].T
          + sd["norm_out.linear.bias"])[:, None]
    scale, shift = om.chunk(2, dim=-1)
    x = _ln(x) * (1 + scale) + shift
    return x @ sd["proj_out.weight"].T + sd["proj_out.bias"]


def _write_transformer(sd, d, cfg):
    from safetensors.torch import save_file

    d.mkdir(parents=True, exist_ok=True)
    save_file(sd, d / "diffusion_pytorch_model.safetensors")
    (d / "config.json").write_text(json.dumps(cfg))


def test_mmdit_matches_torch_reference(tmp_path):
    """Fixture state dict → repo loader → mmdit.forward vs the independent
    torch implementation above."""
    import jax.numpy as jnp

    from localai_tpu.image.flux import _load_transformer

    sd = _state_dict(CFG)
    td = tmp_path / "transformer"
    _write_transformer(sd, td, CFG)
    cfg = mmdit.FluxConfig.from_hf(CFG)
    params = _load_transformer(td, cfg)

    rng = np.random.default_rng(0)
    B, Ni, Nt = 2, 6, 4
    img = rng.normal(size=(B, Ni, CFG["in_channels"])).astype(np.float32)
    txt = rng.normal(size=(B, Nt, CFG["joint_attention_dim"])) \
        .astype(np.float32)
    pooled = rng.normal(size=(B, CFG["pooled_projection_dim"])) \
        .astype(np.float32)
    ids = np.zeros((Ni, 3), np.float32)
    ids[:, 1] = np.arange(Ni) // 3
    ids[:, 2] = np.arange(Ni) % 3
    t = np.asarray([1.0, 0.5], np.float32)
    guid = np.asarray([3.5, 3.5], np.float32)

    ours = np.asarray(mmdit.forward(
        cfg, params, jnp.asarray(img), jnp.asarray(txt),
        jnp.asarray(pooled), jnp.asarray(t), jnp.asarray(ids),
        jnp.zeros((Nt, 3)), guidance=jnp.asarray(guid),
    ))
    with torch.no_grad():
        ref = torch_flux_forward(
            CFG, sd, torch.tensor(img), torch.tensor(txt),
            torch.tensor(pooled), torch.tensor(t), torch.tensor(ids),
            torch.zeros(Nt, 3), torch.tensor(guid),
        ).numpy()
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-4)


def test_t5_encoder_matches_transformers(tmp_path):
    from transformers import T5Config as HFT5Config
    from transformers import T5EncoderModel

    from localai_tpu.image import t5

    torch.manual_seed(0)
    hf = HFT5Config(
        vocab_size=99, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=16, feed_forward_proj="gated-gelu",
    )
    m = T5EncoderModel(hf).eval()
    d = tmp_path / "t5"
    m.save_pretrained(d, safe_serialization=True)
    cfg, params = t5.load_hf_t5(d)

    import jax.numpy as jnp

    ids = [3, 9, 1, 42, 7, 0, 0, 0]
    ours = np.asarray(t5.encode(cfg, params, jnp.asarray([ids], jnp.int32)))
    with torch.no_grad():
        ref = m(torch.tensor([ids]),
                attention_mask=torch.ones(1, 8, dtype=torch.long)
                ).last_hidden_state.numpy()
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-4)


def test_flux_debug_pipeline_generates():
    from localai_tpu.image import resolve_image_model

    p = resolve_image_model("debug:flux-tiny")
    r = p.generate("a lighthouse at dusk", width=64, height=64,
                   steps=2, seed=11)
    assert r.image.shape == (64, 64, 3) and r.image.dtype == np.uint8
    r2 = p.generate("a lighthouse at dusk", width=64, height=64,
                    steps=2, seed=11)
    np.testing.assert_array_equal(r.image, r2.image)


def test_flow_sigmas_schedule():
    s = mmdit.flow_sigmas(4, 256)
    assert s[0] == pytest.approx(1.0) and s[-1] == 0.0
    assert np.all(np.diff(s) < 0)
    # higher resolution shifts sigmas up (more time at high noise)
    s_hi = mmdit.flow_sigmas(4, 4096)
    assert np.all(s_hi[1:-1] > s[1:-1])


def test_flux_layout_loader_end_to_end(tmp_path):
    """Full FLUX directory layout (transformer/ vae/ text_encoder/ CLIP +
    text_encoder_2/ T5) resolves through resolve_image_model and
    generates."""
    import shutil

    from transformers import T5Config as HFT5Config
    from transformers import T5EncoderModel

    from test_image import _write_diffusers_fixture

    from localai_tpu.image import resolve_image_model

    root = tmp_path / "flux-ckpt"
    _write_diffusers_fixture(root)           # supplies vae/ + text_encoder/
    shutil.rmtree(root / "unet")             # flux has no unet

    fcfg = dict(CFG)
    fcfg["joint_attention_dim"] = 32         # match the tiny T5 below
    fcfg["pooled_projection_dim"] = 64       # CLIP hidden of the fixture
    fcfg["in_channels"] = 16                 # 4 latent ch x 2x2 patch
    _write_transformer(_state_dict(fcfg), root / "transformer", fcfg)

    torch.manual_seed(2)
    t5m = T5EncoderModel(HFT5Config(
        vocab_size=99, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=16, feed_forward_proj="gated-gelu",
    )).eval()
    t5m.save_pretrained(root / "text_encoder_2", safe_serialization=True)
    (root / "model_index.json").write_text(
        json.dumps({"_class_name": "FluxPipeline"}))

    # vae config gains flux-style shift/scale factors
    vae_cfg = json.loads((root / "vae" / "config.json").read_text())
    vae_cfg.update({"shift_factor": 0.1, "scaling_factor": 0.36})
    (root / "vae" / "config.json").write_text(json.dumps(vae_cfg))

    p = resolve_image_model(str(root))
    assert type(p).__name__ == "FluxPipeline"
    assert p.vae_shift == 0.1 and p.vae_scale == 0.36
    r = p.generate("tiny prompt", width=64, height=64, steps=2, seed=3)
    assert r.image.shape == (64, 64, 3) and r.image.dtype == np.uint8


def test_flux_loader_honors_scheduler_shift(tmp_path):
    """A schnell-style scheduler_config (use_dynamic_shifting=false,
    shift=1.0) must disable the dev dynamic shift."""
    import shutil

    from transformers import T5Config as HFT5Config
    from transformers import T5EncoderModel

    from test_image import _write_diffusers_fixture

    from localai_tpu.image import resolve_image_model

    root = tmp_path / "flux-s"
    _write_diffusers_fixture(root)
    shutil.rmtree(root / "unet")
    fcfg = dict(CFG)
    fcfg.update(joint_attention_dim=32, pooled_projection_dim=64,
                in_channels=16)
    _write_transformer(_state_dict(fcfg), root / "transformer", fcfg)
    torch.manual_seed(2)
    T5EncoderModel(HFT5Config(
        vocab_size=99, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=16, feed_forward_proj="gated-gelu",
    )).eval().save_pretrained(root / "text_encoder_2",
                              safe_serialization=True)
    (root / "scheduler").mkdir()
    (root / "scheduler" / "scheduler_config.json").write_text(json.dumps(
        {"use_dynamic_shifting": False, "shift": 1.0}))

    p = resolve_image_model(str(root))
    assert p.dynamic_shift is False and p.shift == 1.0
    s = mmdit.flow_sigmas(4, 1024, dynamic=False, shift=1.0)
    np.testing.assert_allclose(s, [1.0, 0.75, 0.5, 0.25, 0.0], atol=1e-6)
    # a dev-style shift=3 static schedule bends the sigmas upward
    s3 = mmdit.flow_sigmas(4, 1024, dynamic=False, shift=3.0)
    assert np.all(s3[1:-1] > s[1:-1])


def test_flux_pack_roundtrip():
    """_encode_img packing is the exact inverse of _decode_fn's unpack."""
    import jax.numpy as jnp

    from localai_tpu.image.flux import debug_flux_pipeline

    p = debug_flux_pipeline()
    rng = np.random.default_rng(0)
    h = w = 16
    cz = p.vae_cfg.latent_channels
    zm = jnp.asarray(rng.normal(size=(1, h, w, cz)), jnp.float32)
    # pack (inverse route through _encode_img's reshape) then unpack via
    # the decode layout and compare
    x = zm.reshape(1, h // 2, 2, w // 2, 2, cz).transpose(
        0, 1, 3, 5, 2, 4).reshape(1, (h // 2) * (w // 2), 4 * cz)
    back = x.reshape(1, h // 2, w // 2, cz, 2, 2).transpose(
        0, 1, 4, 2, 5, 3).reshape(1, h, w, cz)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(zm))


def test_flux_img2img():
    """img2img: strength near 0 stays close to the init image; higher
    strength diverges further (rectified-flow partial-noise start)."""
    from localai_tpu.image import resolve_image_model

    p = resolve_image_model("debug:flux-tiny")
    rng = np.random.default_rng(7)
    init = (rng.random((64, 64, 3)) * 255).astype(np.uint8)
    low = p.generate("shift it", width=64, height=64, steps=4, seed=3,
                     init_image=init, strength=0.25)
    high = p.generate("shift it", width=64, height=64, steps=4, seed=3,
                      init_image=init, strength=1.0)
    d_low = np.mean(np.abs(low.image.astype(float) - init.astype(float)))
    d_high = np.mean(np.abs(high.image.astype(float) - init.astype(float)))
    assert d_low < d_high
    assert low.image.shape == (64, 64, 3)


def test_flux_img2img_latent_inversion_exact():
    """_encode_img composed with _decode_fn's latent reconstruction is the
    identity on raw VAE latents — pins the shift/scale bookkeeping (two
    diverging scale sources would break low-strength img2img silently)."""
    import jax.numpy as jnp

    from localai_tpu.image import vae as vae_mod
    from localai_tpu.image.flux import debug_flux_pipeline

    p = debug_flux_pipeline()
    rng = np.random.default_rng(2)
    img = jnp.asarray(rng.normal(size=(1, 64, 64, 3)) * 0.5, jnp.float32)
    packed = p._encode_img(img)
    z_raw = (vae_mod.encode(p.vae_cfg, p.vae_params, img)
             / p.vae_cfg.scaling_factor)
    h, w = z_raw.shape[1], z_raw.shape[2]
    cz = p.vae_cfg.latent_channels
    x = np.asarray(packed).reshape(1, h // 2, w // 2, cz, 2, 2)
    x = x.transpose(0, 1, 4, 2, 5, 3).reshape(1, h, w, cz)
    z_back = x / p.vae_scale + p.vae_shift
    # bf16 VAE: jitted vs eager encode round differently (~1e-2); a scale-
    # source divergence would be a ~5x error and still fail loudly
    np.testing.assert_allclose(z_back, np.asarray(z_raw),
                               atol=5e-2, rtol=5e-2)
