"""LoRA adapter merging for the diffusion pipeline (parity:
/root/reference/backend/python/diffusers/backend.py:300-381 — kohya and
diffusers/peft safetensors layouts folded into base weights at load)."""


import numpy as np
import pytest
from safetensors.numpy import save_file

from localai_tpu.image.loader import load_diffusers_pipeline, load_unet
from localai_tpu.image.lora import (
    apply_lora,
    read_lora_file,
    unet_sites,
)
from test_image import _write_diffusers_fixture


def _kohya_lora(path, modules, r=4, alpha=2.0, seed=0):
    """Write a kohya-format LoRA safetensors for given (name, din, dout)."""
    rng = np.random.default_rng(seed)
    t = {}
    for name, din, dout in modules:
        key = "lora_unet_" + name.replace(".", "_")
        t[f"{key}.lora_down.weight"] = rng.standard_normal(
            (r, din)).astype(np.float32)
        t[f"{key}.lora_up.weight"] = rng.standard_normal(
            (dout, r)).astype(np.float32)
        t[f"{key}.alpha"] = np.asarray(alpha, np.float32)
    save_file(t, str(path))
    return t


MID_Q = "mid_block.attentions.0.transformer_blocks.0.attn1.to_q"


def test_read_lora_file_formats(tmp_path):
    # kohya
    _kohya_lora(tmp_path / "k.safetensors", [(MID_Q, 64, 64)])
    layers = read_lora_file(tmp_path / "k.safetensors")
    ((comp, name),) = layers.keys()
    assert comp == "unet"
    assert name == MID_Q.replace(".", "_")
    layer = layers[(comp, name)]
    assert layer.down.shape == (4, 64)
    assert layer.up.shape == (64, 4)
    assert layer.alpha == 2.0
    # diffusers/peft
    rng = np.random.default_rng(1)
    save_file({
        f"unet.{MID_Q}.lora_A.weight":
            rng.standard_normal((4, 64)).astype(np.float32),
        f"unet.{MID_Q}.lora_B.weight":
            rng.standard_normal((64, 4)).astype(np.float32),
        "text_encoder.text_model.encoder.layers.0.mlp.fc1.lora_A.weight":
            rng.standard_normal((4, 64)).astype(np.float32),
        "text_encoder.text_model.encoder.layers.0.mlp.fc1.lora_B.weight":
            rng.standard_normal((128, 4)).astype(np.float32),
    }, str(tmp_path / "p.safetensors"))
    layers = read_lora_file(tmp_path / "p.safetensors")
    assert ("unet", MID_Q.replace(".", "_")) in layers
    assert ("te",
            "text_model_encoder_layers_0_mlp_fc1") in layers


def test_apply_lora_merges_expected_delta(tmp_path):
    root = tmp_path / "model"
    _write_diffusers_fixture(root)
    _, params = load_unet(root / "unet")
    before = np.array(
        params["mid"]["attn"]["blocks"][0]["attn1"]["wq"])
    t = _kohya_lora(tmp_path / "l.safetensors", [(MID_Q, 64, 64)],
                    r=4, alpha=2.0)
    n = apply_lora(params, None, tmp_path / "l.safetensors", scale=1.0)
    assert n == 1
    after = params["mid"]["attn"]["blocks"][0]["attn1"]["wq"]
    key = "lora_unet_" + MID_Q.replace(".", "_")
    want = (2.0 / 4.0) * (
        t[f"{key}.lora_up.weight"] @ t[f"{key}.lora_down.weight"]
    )
    np.testing.assert_allclose(after - before, want.T, rtol=1e-5)


def test_apply_lora_shape_mismatch_raises(tmp_path):
    root = tmp_path / "model"
    _write_diffusers_fixture(root)
    _, params = load_unet(root / "unet")
    _kohya_lora(tmp_path / "bad.safetensors", [(MID_Q, 32, 32)])
    with pytest.raises(ValueError, match="does not match target"):
        apply_lora(params, None, tmp_path / "bad.safetensors")


def test_apply_lora_skips_unknown_targets(tmp_path, caplog):
    root = tmp_path / "model"
    _write_diffusers_fixture(root)
    _, params = load_unet(root / "unet")
    _kohya_lora(tmp_path / "na.safetensors",
                [("down_blocks.9.attentions.0.transformer_blocks.0."
                  "attn1.to_q", 64, 64), (MID_Q, 64, 64)])
    n = apply_lora(params, None, tmp_path / "na.safetensors")
    assert n == 1  # the real target merged, the bogus one skipped


def test_unet_sites_cover_attention_and_resnets(tmp_path):
    root = tmp_path / "model"
    _write_diffusers_fixture(root)
    _, params = load_unet(root / "unet")
    sites = unet_sites(params)
    assert MID_Q in sites
    assert "down_blocks.0.resnets.0.conv1" in sites
    assert "mid_block.attentions.0.transformer_blocks.0.ff.net.0.proj" \
        in sites


def test_pipeline_output_changes_with_lora(tmp_path):
    root = tmp_path / "model"
    _write_diffusers_fixture(root)
    _kohya_lora(tmp_path / "l.safetensors", [(MID_Q, 64, 64)], seed=3)
    base = load_diffusers_pipeline(root, default_steps=2)
    tuned = load_diffusers_pipeline(
        root, default_steps=2,
        lora_adapter=str(tmp_path / "l.safetensors"), lora_scale=1.0,
    )
    a = base.generate("a cat", width=64, height=64, seed=7).image
    b = tuned.generate("a cat", width=64, height=64, seed=7).image
    assert a.shape == b.shape
    assert not np.array_equal(a, b)


def test_peft_alpha_joins_group(tmp_path):
    """diffusers/peft-layout alpha tensors group with their lora_A/B
    (previously dropped → merge at the wrong scale)."""
    rng = np.random.default_rng(5)
    save_file({
        f"unet.{MID_Q}.lora_A.weight":
            rng.standard_normal((4, 64)).astype(np.float32),
        f"unet.{MID_Q}.lora_B.weight":
            rng.standard_normal((64, 4)).astype(np.float32),
        f"unet.{MID_Q}.alpha": np.asarray(2.0, np.float32),
    }, str(tmp_path / "pa.safetensors"))
    layers = read_lora_file(tmp_path / "pa.safetensors")
    layer = layers[("unet", MID_Q.replace(".", "_"))]
    assert layer.alpha == 2.0
