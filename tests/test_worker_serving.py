"""Worker tier wired into serving: backend routing, crash isolation,
external backends, and image models under lifecycle management.

Parity: the reference's central lifecycle property — model crash ≠ API
crash (/root/reference/pkg/model/initializers.go:271-407,
loader.go:170-206) — plus backend monitor/watchdog coverage for every
loaded model (watchdog.go:19-156).
"""

import time

import pytest

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.loader import ConfigLoader
from localai_tpu.engine.scheduler import GenRequest
from localai_tpu.models.manager import ImageServingModel, ModelManager

WORKER_YAML = """\
name: wtiny
backend: worker
model: debug:tiny
context_size: 480
parameters:
  temperature: 0.0
  max_tokens: 8
engine:
  max_slots: 2
  prefill_buckets: [16, 32]
  dtype: float32
  kv_dtype: float32
"""

IMAGE_YAML = """\
name: imgdebug
model: "debug:sd-tiny"
backend: diffusers
diffusers:
  steps: 2
known_usecases: [image]
"""


def _manager(tmp_path, *yamls, **app_kw) -> ModelManager:
    for i, y in enumerate(yamls):
        (tmp_path / f"m{i}.yaml").write_text(y)
    app = AppConfig(model_path=str(tmp_path),
                    worker_env={"JAX_PLATFORMS": "cpu"}, **app_kw)
    loader = ConfigLoader(tmp_path)
    loader.load_from_path(context_size=app.context_size)
    return ModelManager(app, loader)


@pytest.mark.slow
def test_worker_backend_serving_and_crash_isolation(tmp_path):
    """`backend: worker` spawns a gRPC worker; generation flows through it;
    killing the process fails only the in-flight request, and the next
    request is served by a respawned worker."""
    from localai_tpu.worker.serving import WorkerServingModel

    mgr = _manager(tmp_path, WORKER_YAML)
    try:
        sm = mgr.get("wtiny")
        assert isinstance(sm, WorkerServingModel)
        # generation round-trips through the worker process
        h = sm.scheduler.submit(GenRequest(
            prompt=sm.tokenizer.encode("hello"), max_new_tokens=4,
            temperature=0.0,
        ))
        h.result(timeout=240)
        assert h.finish_reason in ("stop", "length")
        first_text = h.text

        # metrics come from the worker's engine
        m = sm.engine_metrics()
        assert m.get("total_generated_tokens", 0) > 0

        # kill the worker mid-request (on the first streamed delta) →
        # that request errors, the API process survives
        wp = mgr.pool()._workers["wtiny"]
        h2 = sm.scheduler.submit(GenRequest(
            prompt=sm.tokenizer.encode("again"), max_new_tokens=450,
            temperature=0.0, ignore_eos=True,
        ))
        killed = False
        for item in h2:
            if not killed and item.delta:
                wp.proc.kill()
                killed = True
        assert killed
        h2.result(timeout=120)
        assert h2.finish_reason == "error"

        # next request: manager respawns (alive() is false) and serves
        sm2 = mgr.get("wtiny")
        h3 = sm2.scheduler.submit(GenRequest(
            prompt=sm2.tokenizer.encode("hello"), max_new_tokens=4,
            temperature=0.0,
        ))
        h3.result(timeout=240)
        assert h3.finish_reason in ("stop", "length")
        assert h3.text == first_text  # deterministic greedy, same engine cfg
    finally:
        mgr.shutdown_all()


@pytest.mark.slow
def test_external_backend_routing(tmp_path):
    """A model whose name appears in external_backends is served over the
    registered address instead of a spawned process (parity:
    external_backends.json)."""
    from localai_tpu.worker.process import WorkerProcess
    from localai_tpu.worker.serving import WorkerServingModel

    # externally managed worker (spawned by "someone else")
    ext = WorkerProcess("ext", env={"JAX_PLATFORMS": "cpu"})
    client = ext.start()
    try:
        mgr = _manager(tmp_path, WORKER_YAML.replace(
            "backend: worker", "backend: ''"
        ))
        mgr.app.external_backends["wtiny"] = client.address
        sm = mgr.get("wtiny")
        assert isinstance(sm, WorkerServingModel)
        assert sm.external_address == client.address
        h = sm.scheduler.submit(GenRequest(
            prompt=sm.tokenizer.encode("hi"), max_new_tokens=4,
            temperature=0.0,
        ))
        h.result(timeout=240)
        assert h.finish_reason in ("stop", "length")
        # no process was spawned by the manager's own pool
        assert "wtiny" not in mgr.pool()._workers
        mgr.shutdown_all()
    finally:
        ext.stop()


def test_image_model_under_lifecycle(tmp_path):
    """Image pipelines live in ModelManager: monitor sees them, metrics
    count them, eviction works, the idle watchdog reaps them."""
    mgr = _manager(tmp_path, IMAGE_YAML)
    try:
        sm = mgr.get_image("imgdebug")
        assert isinstance(sm, ImageServingModel)
        out = sm.generate("a red square", width=64, height=64, steps=2,
                          seed=1)
        assert out.image.shape == (64, 64, 3)
        assert not sm.busy

        mon = mgr.monitor("imgdebug")
        assert mon["loaded"] and mon["images_generated"] == 1
        assert mgr.metrics()["imgdebug"]["type"] == "image"

        # idle watchdog eviction: backdate last_used past the timeout and
        # let a real sweeper thread reap it
        mgr.app.watchdog_idle = True
        mgr.app.watchdog_idle_timeout = 0.1
        sm.last_used -= 1.0
        from localai_tpu.models.manager import _Watchdog

        wd = _Watchdog(mgr)
        wd.INTERVAL = 0.05
        wd.start()
        try:
            deadline = time.monotonic() + 10
            while mgr.is_loaded("imgdebug") and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            wd.stop()
        assert not mgr.is_loaded("imgdebug")

        # next get_image reloads cleanly
        sm2 = mgr.get_image("imgdebug")
        assert sm2 is not sm
    finally:
        mgr.shutdown_all()


def test_single_active_backend_spans_modalities(tmp_path):
    """single_active_backend evicts the idle LLM when an image model loads
    (the old private image cache never participated)."""
    tiny = WORKER_YAML.replace("backend: worker", "backend: ''").replace(
        "name: wtiny", "name: tiny"
    )
    mgr = _manager(tmp_path, tiny, IMAGE_YAML, single_active_backend=True)
    try:
        mgr.get("tiny")
        assert mgr.is_loaded("tiny")
        mgr.get_image("imgdebug")
        assert mgr.is_loaded("imgdebug")
        assert not mgr.is_loaded("tiny")
    finally:
        mgr.shutdown_all()
