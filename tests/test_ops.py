"""Pallas flash-attention kernels vs the XLA reference implementation.

Run in interpreter mode on CPU (real Mosaic compilation happens on TPU);
numerical agreement with models.llama._grouped_attn is the contract.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.engine import kvcache as kvc
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models import llama as mdl
from localai_tpu.models.llama import LlamaConfig
from localai_tpu.models.registry import resolve_model
from localai_tpu.ops import attention as ops_attn


def _cfg(Hq=8, Hkv=4, hd=16, window=None):
    return LlamaConfig(num_heads=Hq, num_kv_heads=Hkv, head_dim=hd,
                       hidden_size=Hq * hd, sliding_window=window)


@pytest.mark.parametrize("window", [None, 24])
def test_decode_attention_matches_xla(window):
    cfg = _cfg(window=window)
    S, C = 4, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(S, cfg.num_heads, cfg.hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(S, cfg.num_kv_heads, C, cfg.hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(S, cfg.num_kv_heads, C, cfg.hd)), jnp.float32)
    pos = jnp.asarray([0, 5, 31, 63], jnp.int32)

    ref = mdl._grouped_attn(cfg, q[:, None], k, v,
                            kvc.decode_mask(cfg, pos, C))[:, 0]
    out = ops_attn.decode_attention(q, k, v, pos, sliding_window=window,
                                    block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [None, 10])
@pytest.mark.parametrize("length", [1, 17, 48])
def test_prefill_attention_matches_xla(window, length):
    cfg = _cfg(Hq=4, Hkv=2, window=window)
    T = 48
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(T, cfg.num_heads, cfg.hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(cfg.num_kv_heads, T, cfg.hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(cfg.num_kv_heads, T, cfg.hd)), jnp.float32)

    ref = mdl._grouped_attn(cfg, q[None], k[None], v[None],
                            kvc.prefill_mask(cfg, T, jnp.int32(length)))[0]
    out = ops_attn.prefill_attention(q, k, v, jnp.int32(length),
                                     sliding_window=window,
                                     block_q=16, block_k=16, interpret=True)
    # rows past `length` attend to nothing real; compare only the valid rows
    np.testing.assert_allclose(np.asarray(out)[:length],
                               np.asarray(ref)[:length],
                               rtol=2e-5, atol=2e-5)


def test_runner_pallas_matches_xla_end_to_end():
    """Greedy generation must be bit-identical between attention impls."""
    model = resolve_model("debug:tiny", dtype="float32")
    outs = {}
    for impl in ("xla", "pallas_interpret"):
        r = ModelRunner(model.cfg, model.params, num_slots=2, max_ctx=64,
                        prefill_buckets=[16], kv_dtype="float32",
                        attn_impl=impl)
        s = r.acquire_slot()
        toks = [r.admit(s, list(b"pallas parity"), temperature=0.0)]
        for _ in range(6):
            toks.append(int(r.step()[s]))
        outs[impl] = toks
    assert outs["xla"] == outs["pallas_interpret"]


@pytest.mark.parametrize("window", [None, 24])
def test_decode_attention_int8_kv_matches_dequant_xla(window):
    """Fused int8-KV dequant in the flash decode kernel: scales applied to
    score/prob columns must equal attention over the dequantized cache."""
    cfg = _cfg(window=window)
    S, C = 4, 64
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(S, cfg.num_heads, cfg.hd)), jnp.float32)
    kq = jnp.asarray(rng.integers(-127, 128, (S, cfg.num_kv_heads, C, cfg.hd)),
                     jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (S, cfg.num_kv_heads, C, cfg.hd)),
                     jnp.int8)
    ks = jnp.asarray(rng.uniform(0.005, 0.02, (S, cfg.num_kv_heads, C)),
                     jnp.float32)
    vs = jnp.asarray(rng.uniform(0.005, 0.02, (S, cfg.num_kv_heads, C)),
                     jnp.float32)
    pos = jnp.asarray([0, 5, 31, 63], jnp.int32)

    k = kq.astype(jnp.float32) * ks[..., None]
    v = vq.astype(jnp.float32) * vs[..., None]
    ref = mdl._grouped_attn(cfg, q[:, None], k, v,
                            kvc.decode_mask(cfg, pos, C))[:, 0]
    out = ops_attn.decode_attention(q, kq, vq, pos, ks, vs,
                                    sliding_window=window,
                                    block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_runner_int8_kv_pallas_matches_xla_end_to_end():
    """int8-KV serving must run the flash decode kernel (no XLA fallback)
    and agree with the fused-XLA int8 path on greedy output."""
    model = resolve_model("debug:tiny", dtype="float32")
    outs = {}
    for impl in ("xla", "pallas_interpret"):
        r = ModelRunner(model.cfg, model.params, num_slots=2, max_ctx=64,
                        prefill_buckets=[16], kv_dtype="int8",
                        attn_impl=impl)
        if impl.startswith("pallas"):
            assert r.decode_attn_impl == "pallas"
        s = r.acquire_slot()
        toks = [r.admit(s, list(b"int8 kv parity"), temperature=0.0)]
        for _ in range(8):
            toks.append(int(r.step()[s]))
        outs[impl] = toks
    assert outs["xla"] == outs["pallas_interpret"]
