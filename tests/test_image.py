"""Image generation tests: pipeline, schedulers, diffusers-layout loader,
worker servicer, HTTP endpoint (debug preset — no downloads, SURVEY.md §4
fixture strategy)."""

import base64
import json

import numpy as np
import pytest

from localai_tpu.image import resolve_image_model
from localai_tpu.image import schedulers as sch


@pytest.fixture(scope="module")
def pipe():
    return resolve_image_model("debug:sd-tiny")


def test_txt2img_shape_and_determinism(pipe):
    a = pipe.generate("a red square", width=64, height=64, steps=3, seed=7)
    b = pipe.generate("a red square", width=64, height=64, steps=3, seed=7)
    assert a.image.shape == (64, 64, 3)
    assert a.image.dtype == np.uint8
    assert (a.image == b.image).all()
    c = pipe.generate("a red square", width=64, height=64, steps=3, seed=8)
    assert (a.image != c.image).any()


def test_size_bucketing(pipe):
    r = pipe.generate("x", width=70, height=100, steps=2, seed=1)
    # 70→128, 100→128 (64-quantum buckets bound XLA recompiles)
    assert r.image.shape == (128, 128, 3)


@pytest.mark.parametrize("name", ["ddim", "euler", "euler_a", "dpmpp_2m",
                                  "k_euler", "k_dpmpp_2m"])
def test_schedulers_run(pipe, name):
    r = pipe.generate("s", width=64, height=64, steps=3, seed=3,
                      scheduler=name)
    assert r.image.shape == (64, 64, 3)


def test_scheduler_aliases_resolve():
    # every reference scheduler name maps onto a supported rule
    for name in ("ddim", "pndm", "heun", "unipc", "euler", "euler_a", "lms",
                 "k_lms", "dpm_2", "k_dpm_2", "dpm_2_a", "k_dpm_2_a",
                 "dpmpp_2m", "k_dpmpp_2m", "dpmpp_sde", "k_dpmpp_sde",
                 "dpmpp_2m_sde", "k_dpmpp_2m_sde"):
        rule, _karras = sch.resolve(name)
        assert rule in ("ddim", "euler", "euler_a", "dpmpp_2m")
    assert sch.resolve(None) == ("euler", False)
    with pytest.raises(ValueError):
        sch.resolve("nonsense")


def test_sigma_schedules():
    sigmas, ts = sch.build_sigmas(10)
    assert sigmas.shape == (11,) and ts.shape == (10,)
    assert sigmas[-1] == 0.0
    assert (np.diff(sigmas) < 0).all()
    ks, kts = sch.build_sigmas(10, karras=True)
    assert ks[-1] == 0.0 and (np.diff(ks) < 0).all()
    assert not np.allclose(ks[:-1], sigmas[:-1])


def test_img2img(pipe):
    base = pipe.generate("base", width=64, height=64, steps=3, seed=5)
    out = pipe.generate("restyle", width=64, height=64, steps=4, seed=6,
                        init_image=base.image, strength=0.5)
    assert out.image.shape == (64, 64, 3)


def test_negative_prompt_changes_output(pipe):
    a = pipe.generate("castle", width=64, height=64, steps=3, seed=9)
    b = pipe.generate("castle", negative_prompt="blurry", width=64,
                      height=64, steps=3, seed=9)
    assert (a.image != b.image).any()


# ---------------------------------------------------------------------------
# diffusers-layout loader
# ---------------------------------------------------------------------------

def _write_diffusers_fixture(root):
    """Emit a tiny random checkpoint in the diffusers directory layout
    (torch OIHW convs / [out,in] linears under diffusers key names) so the
    loader's mapping is exercised end to end."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(0)

    def t(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05

    def conv(cin, cout, k=3):
        return t(cout, cin, k, k)

    # -- unet: block_out [32,64], 1 res block, attn on level 0 only
    u = {}
    u["conv_in.weight"], u["conv_in.bias"] = conv(4, 32), t(32)
    u["time_embedding.linear_1.weight"] = t(128, 32)
    u["time_embedding.linear_1.bias"] = t(128)
    u["time_embedding.linear_2.weight"] = t(128, 128)
    u["time_embedding.linear_2.bias"] = t(128)

    def res(prefix, cin, cout):
        u[f"{prefix}.norm1.weight"], u[f"{prefix}.norm1.bias"] = t(cin), t(cin)
        u[f"{prefix}.conv1.weight"], u[f"{prefix}.conv1.bias"] = conv(cin, cout), t(cout)
        u[f"{prefix}.time_emb_proj.weight"] = t(cout, 128)
        u[f"{prefix}.time_emb_proj.bias"] = t(cout)
        u[f"{prefix}.norm2.weight"], u[f"{prefix}.norm2.bias"] = t(cout), t(cout)
        u[f"{prefix}.conv2.weight"], u[f"{prefix}.conv2.bias"] = conv(cout, cout), t(cout)
        if cin != cout:
            u[f"{prefix}.conv_shortcut.weight"] = conv(cin, cout, 1)
            u[f"{prefix}.conv_shortcut.bias"] = t(cout)

    def st(prefix, ch, ctx=64):
        u[f"{prefix}.norm.weight"], u[f"{prefix}.norm.bias"] = t(ch), t(ch)
        u[f"{prefix}.proj_in.weight"] = conv(ch, ch, 1)
        u[f"{prefix}.proj_in.bias"] = t(ch)
        u[f"{prefix}.proj_out.weight"] = conv(ch, ch, 1)
        u[f"{prefix}.proj_out.bias"] = t(ch)
        b = f"{prefix}.transformer_blocks.0"
        for ln in ("norm1", "norm2", "norm3"):
            u[f"{b}.{ln}.weight"], u[f"{b}.{ln}.bias"] = t(ch), t(ch)
        for attn, kv in (("attn1", ch), ("attn2", ctx)):
            u[f"{b}.{attn}.to_q.weight"] = t(ch, ch)
            u[f"{b}.{attn}.to_k.weight"] = t(ch, kv)
            u[f"{b}.{attn}.to_v.weight"] = t(ch, kv)
            u[f"{b}.{attn}.to_out.0.weight"] = t(ch, ch)
            u[f"{b}.{attn}.to_out.0.bias"] = t(ch)
        inner = ch * 4
        u[f"{b}.ff.net.0.proj.weight"] = t(inner * 2, ch)
        u[f"{b}.ff.net.0.proj.bias"] = t(inner * 2)
        u[f"{b}.ff.net.2.weight"] = t(ch, inner)
        u[f"{b}.ff.net.2.bias"] = t(ch)

    res("down_blocks.0.resnets.0", 32, 32)
    st("down_blocks.0.attentions.0", 32)
    u["down_blocks.0.downsamplers.0.conv.weight"] = conv(32, 32)
    u["down_blocks.0.downsamplers.0.conv.bias"] = t(32)
    res("down_blocks.1.resnets.0", 32, 64)
    res("mid_block.resnets.0", 64, 64)
    st("mid_block.attentions.0", 64)
    res("mid_block.resnets.1", 64, 64)
    # up level 1 (deepest first): skips are [64, 32]
    res("up_blocks.0.resnets.0", 64 + 64, 64)
    res("up_blocks.0.resnets.1", 64 + 32, 64)
    u["up_blocks.0.upsamplers.0.conv.weight"] = conv(64, 64)
    u["up_blocks.0.upsamplers.0.conv.bias"] = t(64)
    res("up_blocks.1.resnets.0", 64 + 32, 32)
    st("up_blocks.1.attentions.0", 32)
    res("up_blocks.1.resnets.1", 32 + 32, 32)
    st("up_blocks.1.attentions.1", 32)
    u["conv_norm_out.weight"], u["conv_norm_out.bias"] = t(32), t(32)
    u["conv_out.weight"], u["conv_out.bias"] = conv(32, 4), t(4)

    (root / "unet").mkdir(parents=True)
    save_file(u, str(root / "unet" / "model.safetensors"))
    (root / "unet" / "config.json").write_text(json.dumps({
        "block_out_channels": [32, 64], "layers_per_block": 1,
        "down_block_types": ["CrossAttnDownBlock2D", "DownBlock2D"],
        "cross_attention_dim": 64, "attention_head_dim": 4,
        "in_channels": 4, "out_channels": 4,
    }))

    # -- vae: block_out [32, 64], 1 res block
    v = {}

    def vres(prefix, cin, cout):
        v[f"{prefix}.norm1.weight"], v[f"{prefix}.norm1.bias"] = t(cin), t(cin)
        v[f"{prefix}.conv1.weight"], v[f"{prefix}.conv1.bias"] = conv(cin, cout), t(cout)
        v[f"{prefix}.norm2.weight"], v[f"{prefix}.norm2.bias"] = t(cout), t(cout)
        v[f"{prefix}.conv2.weight"], v[f"{prefix}.conv2.bias"] = conv(cout, cout), t(cout)
        if cin != cout:
            v[f"{prefix}.conv_shortcut.weight"] = conv(cin, cout, 1)
            v[f"{prefix}.conv_shortcut.bias"] = t(cout)

    def vattn(prefix, ch):
        v[f"{prefix}.group_norm.weight"], v[f"{prefix}.group_norm.bias"] = t(ch), t(ch)
        for n in ("to_q", "to_k", "to_v", "to_out.0"):
            v[f"{prefix}.{n}.weight"] = t(ch, ch)
            v[f"{prefix}.{n}.bias"] = t(ch)

    v["encoder.conv_in.weight"], v["encoder.conv_in.bias"] = conv(3, 32), t(32)
    vres("encoder.down_blocks.0.resnets.0", 32, 32)
    v["encoder.down_blocks.0.downsamplers.0.conv.weight"] = conv(32, 32)
    v["encoder.down_blocks.0.downsamplers.0.conv.bias"] = t(32)
    vres("encoder.down_blocks.1.resnets.0", 32, 64)
    vres("encoder.mid_block.resnets.0", 64, 64)
    vattn("encoder.mid_block.attentions.0", 64)
    vres("encoder.mid_block.resnets.1", 64, 64)
    v["encoder.conv_norm_out.weight"], v["encoder.conv_norm_out.bias"] = t(64), t(64)
    v["encoder.conv_out.weight"], v["encoder.conv_out.bias"] = conv(64, 8), t(8)
    v["quant_conv.weight"], v["quant_conv.bias"] = conv(8, 8, 1), t(8)
    v["post_quant_conv.weight"], v["post_quant_conv.bias"] = conv(4, 4, 1), t(4)
    v["decoder.conv_in.weight"], v["decoder.conv_in.bias"] = conv(4, 64), t(64)
    vres("decoder.mid_block.resnets.0", 64, 64)
    vattn("decoder.mid_block.attentions.0", 64)
    vres("decoder.mid_block.resnets.1", 64, 64)
    for j in range(2):
        vres(f"decoder.up_blocks.0.resnets.{j}", 64, 64)
    v["decoder.up_blocks.0.upsamplers.0.conv.weight"] = conv(64, 64)
    v["decoder.up_blocks.0.upsamplers.0.conv.bias"] = t(64)
    vres("decoder.up_blocks.1.resnets.0", 64, 32)
    vres("decoder.up_blocks.1.resnets.1", 32, 32)
    v["decoder.conv_norm_out.weight"], v["decoder.conv_norm_out.bias"] = t(32), t(32)
    v["decoder.conv_out.weight"], v["decoder.conv_out.bias"] = conv(32, 3), t(3)

    (root / "vae").mkdir()
    save_file(v, str(root / "vae" / "model.safetensors"))
    (root / "vae" / "config.json").write_text(json.dumps({
        "block_out_channels": [32, 64], "layers_per_block": 1,
        "latent_channels": 4, "in_channels": 3,
    }))

    # -- text encoder: 2 layers, width = unet cross_attention_dim
    c = {}
    C, I = 64, 128
    c["text_model.embeddings.token_embedding.weight"] = t(100, C)
    c["text_model.embeddings.position_embedding.weight"] = t(16, C)
    for i in range(2):
        b = f"text_model.encoder.layers.{i}"
        for ln in ("layer_norm1", "layer_norm2"):
            c[f"{b}.{ln}.weight"], c[f"{b}.{ln}.bias"] = t(C), t(C)
        for p in ("q_proj", "k_proj", "v_proj", "out_proj"):
            c[f"{b}.self_attn.{p}.weight"] = t(C, C)
            c[f"{b}.self_attn.{p}.bias"] = t(C)
        c[f"{b}.mlp.fc1.weight"], c[f"{b}.mlp.fc1.bias"] = t(I, C), t(I)
        c[f"{b}.mlp.fc2.weight"], c[f"{b}.mlp.fc2.bias"] = t(C, I), t(C)
    c["text_model.final_layer_norm.weight"] = t(C)
    c["text_model.final_layer_norm.bias"] = t(C)

    (root / "text_encoder").mkdir()
    save_file(c, str(root / "text_encoder" / "model.safetensors"))
    (root / "text_encoder" / "config.json").write_text(json.dumps({
        "vocab_size": 100, "hidden_size": C, "intermediate_size": I,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "max_position_embeddings": 16, "eos_token_id": 99,
    }))
    (root / "model_index.json").write_text(json.dumps(
        {"_class_name": "StableDiffusionPipeline"}
    ))


def test_diffusers_layout_loader(tmp_path):
    from localai_tpu.image.loader import load_diffusers_pipeline

    _write_diffusers_fixture(tmp_path / "ckpt")
    pipe = load_diffusers_pipeline(tmp_path / "ckpt")
    assert pipe.unet_cfg.model_channels == 32
    assert pipe.unet_cfg.attn_levels == (0,)
    r = pipe.generate("fixture", width=64, height=64, steps=2, seed=11)
    assert r.image.shape == (64, 64, 3)


# ---------------------------------------------------------------------------
# worker servicer
# ---------------------------------------------------------------------------

def test_image_worker_servicer():
    from localai_tpu.worker import backend_pb2 as pb
    from localai_tpu.worker.server import ImageServicer

    s = ImageServicer()
    res = s.LoadModel(pb.ModelOptions(model="debug:sd-tiny"), None)
    assert res.success, res.message
    out = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="worker image", width=64, height=64, step=2, seed=4,
    ), None)
    assert out.success
    assert out.image[:8] == b"\x89PNG\r\n\x1a\n"


# ---------------------------------------------------------------------------
# HTTP endpoint
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def image_server(tmp_path_factory):
    import httpx

    from localai_tpu.api.server import AppState
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.loader import ConfigLoader
    from tests.test_api import _ServerThread

    models = tmp_path_factory.mktemp("img_models")
    imgs = tmp_path_factory.mktemp("generated")
    (models / "sd.yaml").write_text(
        "name: sd\nbackend: diffusers\nmodel: 'debug:sd-tiny'\n"
        "diffusers:\n  steps: 2\n"
    )
    (models / "tiny.yaml").write_text(
        "name: tiny\nmodel: 'debug:tiny'\ncontext_size: 64\n"
    )
    cfg = AppConfig(model_path=str(models), image_path=str(imgs))
    loader = ConfigLoader(cfg.model_path)
    loader.load_from_path()
    srv = _ServerThread(AppState(cfg, loader))
    with httpx.Client(base_url=srv.base, timeout=300.0) as c:
        yield c
    srv.stop()


def test_images_generations_b64(image_server):
    r = image_server.post("/v1/images/generations", json={
        "model": "sd", "prompt": "a cat|ugly", "size": "64x64",
        "response_format": "b64_json", "seed": 3,
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert len(body["data"]) == 1
    png = base64.b64decode(body["data"][0]["b64_json"])
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


def test_images_generations_url_and_fetch(image_server):
    r = image_server.post("/v1/images/generations", json={
        "model": "sd", "prompt": "a dog", "size": "64x64", "n": 2, "seed": 5,
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert len(body["data"]) == 2
    url = body["data"][0]["url"]
    assert "/generated-images/" in url
    got = image_server.get("/generated-images/" +
                           url.rsplit("/", 1)[-1])
    assert got.status_code == 200
    assert got.content[:8] == b"\x89PNG\r\n\x1a\n"


def test_images_usecase_gating(image_server):
    r = image_server.post("/v1/images/generations", json={
        "model": "tiny", "prompt": "nope", "size": "64x64",
    })
    assert r.status_code == 400


def test_images_img2img_base64_file(image_server):
    first = image_server.post("/v1/images/generations", json={
        "model": "sd", "prompt": "seed image", "size": "64x64",
        "response_format": "b64_json", "seed": 1,
    })
    b64 = first.json()["data"][0]["b64_json"]
    r = image_server.post("/v1/images/generations", json={
        "model": "sd", "prompt": "variation", "size": "64x64",
        "response_format": "b64_json", "seed": 2, "file": b64,
    })
    assert r.status_code == 200, r.text
    png = base64.b64decode(r.json()["data"][0]["b64_json"])
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


def test_generated_images_path_traversal_guarded(image_server):
    got = image_server.get("/generated-images/..%2Fsd.yaml")
    assert got.status_code in (400, 404)


def test_images_size_resized_to_request(image_server):
    # 100x100 buckets to 128 latents internally; API returns the asked size
    r = image_server.post("/v1/images/generations", json={
        "model": "sd", "prompt": "exact size", "size": "100x100",
        "response_format": "b64_json", "seed": 1,
    })
    assert r.status_code == 200, r.text
    import io

    from PIL import Image

    png = base64.b64decode(r.json()["data"][0]["b64_json"])
    assert Image.open(io.BytesIO(png)).size == (100, 100)


def test_images_size_limit(image_server):
    r = image_server.post("/v1/images/generations", json={
        "model": "sd", "prompt": "too big", "size": "4096x4096",
    })
    assert r.status_code == 400
