"""Function-calling pipeline tests: regex FSM, JSON-schema compiler, token
constraints, tools→grammar, and output parsing.

Modeled on the reference's pkg/functions test coverage
(/root/reference/pkg/functions/parse_test.go,
grammars/json_schema_test.go) — same behaviors, asserted against the FSM
pipeline instead of BNF text.
"""

import json

import numpy as np
import pytest

from localai_tpu.config.model_config import FunctionsConfig
from localai_tpu.functions import (
    FSMConstraint,
    build_tool_constraint,
    build_tool_regex,
    compile_dfa,
    constraint_for_regex,
    constraint_for_schema,
    inject_no_action,
    normalize_tools,
    parse_function_call,
    parse_json_objects,
    parse_text_content,
    cleanup_llm_result,
    schema_to_regex,
    select_function,
)
from localai_tpu.utils.tokenizer import ByteTokenizer


# ---------------------------------------------------------------------------
# fsm


@pytest.mark.parametrize("pattern,text,expect", [
    (r"abc", "abc", True),
    (r"abc", "abx", False),
    (r"a(b|c)*d", "abcbcd", True),
    (r"a(b|c)*d", "ad", True),
    (r"[0-9]{2,4}", "123", True),
    (r"[0-9]{2,4}", "1", False),
    (r"[0-9]{2,4}", "12345", False),
    (r"[^abc]+", "xyz", True),
    (r"[^abc]+", "xaz", False),
    (r"\{\}", "{}", True),
    (r".*", "anything at all", True),
])
def test_dfa_matches(pattern, text, expect):
    assert compile_dfa(pattern).matches(text) is expect


def test_dfa_dead_state_pruning():
    d = compile_dfa(r"ab")
    s = d.step_bytes(d.start, b"ax")
    assert s == d.DEAD
    s = d.step_bytes(d.start, b"ab")
    assert d.accept[s]
    assert d.forced_end(s)


# ---------------------------------------------------------------------------
# jsonschema


def _matches(schema, text, **kw):
    return compile_dfa(schema_to_regex(schema, **kw)).matches(text)


def test_schema_object_round_trip():
    schema = {
        "type": "object",
        "properties": {
            "name": {"type": "string"},
            "n": {"type": "integer"},
            "ok": {"type": "boolean"},
        },
    }
    assert _matches(schema, '{"name":"x","n":3,"ok":true}')
    assert _matches(schema, '{ "name" : "x" , "n" : -1 , "ok" : false }')
    assert not _matches(schema, '{"n":3,"name":"x","ok":true}')  # order fixed
    assert not _matches(schema, '{"name":"x"}')  # all-required default


def test_schema_optional_properties():
    schema = {
        "type": "object",
        "properties": {"a": {"type": "integer"}, "b": {"type": "string"}},
        "required": ["a"],
    }
    assert _matches(schema, '{"a":1}')
    assert _matches(schema, '{"a":1,"b":"x"}')
    assert not _matches(schema, '{"b":"x"}')


def test_schema_enum_const_refs():
    schema = {
        "type": "object",
        "properties": {
            "unit": {"enum": ["celsius", "fahrenheit"]},
            "p": {"$ref": "#/$defs/point"},
        },
        "$defs": {"point": {"type": "number"}},
    }
    assert _matches(schema, '{"unit":"celsius","p":1.5}')
    assert not _matches(schema, '{"unit":"kelvin","p":1.5}')


def test_schema_arrays_and_nested():
    schema = {
        "type": "array",
        "items": {
            "type": "object",
            "properties": {"x": {"type": "integer"}},
        },
        "minItems": 1,
    }
    assert _matches(schema, '[{"x":1},{"x":2}]')
    assert not _matches(schema, "[]")


def test_schema_free_form_depth():
    assert _matches({}, '{"a":{"b":[1,"x",null]}}')
    assert _matches({}, "[1,2,3]")
    assert _matches({}, "true")


def test_schema_recursive_ref_rejected():
    schema = {"$ref": "#/$defs/n",
              "$defs": {"n": {"type": "object",
                              "properties": {"next": {"$ref": "#/$defs/n"}}}}}
    with pytest.raises(ValueError):
        schema_to_regex(schema)


# ---------------------------------------------------------------------------
# constraint: masked greedy decode stays inside the grammar


def _constrained_greedy(constraint: FSMConstraint, tok: ByteTokenizer,
                        prefer: str, limit: int = 200) -> str:
    """Greedy walk: at each step pick the preferred next byte if allowed,
    else the lowest allowed token — must always yield a grammar match."""
    out = []
    want = prefer.encode()
    i = 0
    while len(out) < limit and not constraint.done:
        mask = constraint.allowed_mask()
        if mask is None:
            break
        allowed = np.nonzero(mask == 0.0)[0]
        assert allowed.size, "grammar wedged with nothing allowed"
        if i < len(want) and mask[want[i]] == 0.0:
            t = int(want[i])
            i += 1
        else:
            non_eos = [a for a in allowed if a not in tok.eos_ids]
            if not non_eos:
                break
            t = int(non_eos[0])
        if t in tok.eos_ids:
            break
        out.append(t)
        constraint.advance(t)
    return tok.decode(out)


def test_constraint_forces_valid_json():
    tok = ByteTokenizer()
    schema = {
        "type": "object",
        "properties": {"name": {"const": "get_weather"},
                       "arguments": {
                           "type": "object",
                           "properties": {"city": {"type": "string"}},
                       }},
    }
    c = constraint_for_schema(schema, tok)
    text = _constrained_greedy(
        c, tok, '{"name":"get_weather","arguments":{"city":"Kyiv"}}'
    )
    obj = json.loads(text)
    assert obj["name"] == "get_weather"
    assert obj["arguments"]["city"] == "Kyiv"


def test_constraint_rejects_offgrammar_bytes():
    tok = ByteTokenizer()
    c = constraint_for_regex(r"(yes|no)", tok)
    mask = c.allowed_mask()
    assert mask[ord("y")] == 0.0
    assert mask[ord("n")] == 0.0
    assert mask[ord("x")] < -1e29
    c.advance(ord("y"))
    mask = c.allowed_mask()
    assert mask[ord("e")] == 0.0
    assert mask[ord("o")] < -1e29
    c.advance(ord("e"))
    c.advance(ord("s"))
    assert c.done  # forced end: no continuation


def test_constraint_eos_only_at_accept():
    tok = ByteTokenizer()
    c = constraint_for_regex(r"ab?", tok)
    assert c.allowed_mask()[tok.EOS] < -1e29  # not accepting yet
    c.advance(ord("a"))
    mask = c.allowed_mask()
    assert mask[tok.EOS] == 0.0  # "a" is a full match
    assert mask[ord("b")] == 0.0  # but may continue
    c.advance(tok.EOS)
    assert c.done


def test_constraint_mask_cache_reused():
    tok = ByteTokenizer()
    c = constraint_for_regex(r"[ab]*", tok)
    c.advance(ord("a"))
    m1 = c.allowed_mask()
    c.advance(ord("b"))
    m2 = c.allowed_mask()
    assert m1 is m2  # self-loop state → identical cached row


# ---------------------------------------------------------------------------
# tools → grammar


WEATHER = {
    "name": "get_weather",
    "parameters": {
        "type": "object",
        "properties": {"city": {"type": "string"}},
        "required": ["city"],
    },
}


def test_normalize_and_inject():
    tools = [{"type": "function", "function": WEATHER}]
    fns = normalize_tools(tools)
    assert fns[0]["name"] == "get_weather"
    cfg = FunctionsConfig()
    with_na = inject_no_action(fns, cfg)
    assert with_na[-1]["name"] == "answer"
    cfg2 = FunctionsConfig(disable_no_action=True)
    assert inject_no_action(fns, cfg2) == fns
    assert select_function(with_na, "get_weather") == [WEATHER]


def test_tool_regex_single_call():
    built = build_tool_regex([WEATHER], FunctionsConfig())
    d = compile_dfa(built.pattern)
    assert d.matches('{"name":"get_weather","arguments":{"city":"Oslo"}}')
    assert not d.matches('{"name":"nope","arguments":{"city":"Oslo"}}')


def test_tool_regex_parallel_and_mixed():
    cfg = FunctionsConfig(grammar={"parallel_calls": True, "mixed_mode": True})
    built = build_tool_regex([WEATHER], cfg)
    d = compile_dfa(built.pattern)
    one = '{"name":"get_weather","arguments":{"city":"Oslo"}}'
    assert d.matches(one)
    assert d.matches(f"[{one},\n{one}]")
    assert d.matches("plain text answer")  # mixed mode


def test_tool_regex_prefix_and_name_key():
    cfg = FunctionsConfig(
        function_name_key="function",
        grammar={"prefix": "TOOL: "},
    )
    built = build_tool_regex([WEATHER], cfg)
    d = compile_dfa(built.pattern)
    assert d.matches('TOOL: {"function":"get_weather","arguments":{"city":"x"}}')
    assert not d.matches('{"function":"get_weather","arguments":{"city":"x"}}')


def test_tool_regex_llama31():
    cfg = FunctionsConfig(grammar={"schema_type": "llama3.1"})
    built = build_tool_regex([WEATHER], cfg)
    d = compile_dfa(built.pattern)
    assert d.matches('<function=get_weather>{"city":"Rome"}</function>')
    assert not d.matches('{"name":"get_weather","arguments":{"city":"Rome"}}')


def test_tool_constraint_end_to_end():
    tok = ByteTokenizer()
    cfg = FunctionsConfig(disable_no_action=True)
    constraint, built = build_tool_constraint([WEATHER], cfg, tok)
    text = _constrained_greedy(
        constraint, tok,
        '{"name":"get_weather","arguments":{"city":"Paris"}}',
    )
    calls = parse_function_call(text, cfg)
    assert calls and calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "Paris"}


def test_tool_constraint_disabled_grammar():
    tok = ByteTokenizer()
    cfg = FunctionsConfig(grammar={"disable": True})
    constraint, built = build_tool_constraint([WEATHER], cfg, tok)
    assert constraint is None
    assert built.pattern


# ---------------------------------------------------------------------------
# parse (reference parse_test.go behaviors)


def test_parse_single_call():
    cfg = FunctionsConfig()
    res = parse_function_call(
        '{"name":"add","arguments":{"x":1,"y":2}}', cfg
    )
    assert len(res) == 1
    assert res[0].name == "add"
    assert json.loads(res[0].arguments) == {"x": 1, "y": 2}


def test_parse_multiple_and_garbage():
    cfg = FunctionsConfig()
    res = parse_function_call(
        'noise {"name":"a","arguments":{}} mid {"name":"b","arguments":{"k":1}}',
        cfg,
    )
    assert [r.name for r in res] == ["a", "b"]


def test_parse_top_level_array():
    cfg = FunctionsConfig()
    res = parse_function_call(
        '[{"name":"a","arguments":{}},{"name":"b","arguments":{}}]', cfg
    )
    assert [r.name for r in res] == ["a", "b"]


def test_parse_custom_keys():
    cfg = FunctionsConfig(function_name_key="function",
                          function_arguments_key="args")
    res = parse_function_call('{"function":"f","args":{"q":"z"}}', cfg)
    assert res[0].name == "f"
    assert json.loads(res[0].arguments) == {"q": "z"}


def test_parse_json_regex_match():
    cfg = FunctionsConfig(
        json_regex_match=[r"```json\n?(.*?)```"],
    )
    res = parse_function_call(
        'prose ```json\n{"name":"f","arguments":{}}``` more', cfg
    )
    assert res[0].name == "f"


def test_parse_response_regex():
    cfg = FunctionsConfig(
        response_regex=[r"call=(?P<name>\w+) args=(?P<arguments>\{.*\})"],
    )
    res = parse_function_call('call=go args={"a":1}', cfg)
    assert res[0].name == "go"
    assert json.loads(res[0].arguments) == {"a": 1}


def test_parse_llama31_tags():
    cfg = FunctionsConfig()
    res = parse_function_call(
        '<function=get_weather>{"city":"Rome"}</function>', cfg
    )
    assert res[0].name == "get_weather"
    assert json.loads(res[0].arguments) == {"city": "Rome"}


def test_parse_replacements_and_capture():
    cfg = FunctionsConfig(
        replace_function_results=[{"key": r"'", "value": '"'}],
        replace_llm_results=[{"key": r"<think>.*?</think>", "value": ""}],
        capture_llm_results=[r"<answer>(.*?)</answer>"],
    )
    # single quotes replaced by the regex before JSON decode
    res = parse_function_call("{'name':'f','arguments':{}}", cfg)
    assert res and res[0].name == "f"
    assert cleanup_llm_result("<think>hmm</think>ok", cfg) == "ok"
    assert parse_text_content("<answer>42</answer>", cfg) == "42"
    assert parse_text_content("nothing here", cfg) == ""


def test_parse_json_objects_tolerant():
    objs = parse_json_objects('{"a":1} x {"b":2} [{"c":3}]')
    assert objs == [{"a": 1}, {"b": 2}, {"c": 3}]
    assert parse_json_objects("no json") == []
    assert parse_json_objects('{"broken": ') == []


def test_review_fixes_regression():
    """Fixes from review: pattern grouping, $defs merge, allOf siblings,
    response_regex None args, empty replacement keys, DFA cache."""
    # string pattern with top-level alternation must stay contained
    schema = {"type": "object",
              "properties": {"s": {"type": "string", "pattern": "yes|no"}}}
    d = compile_dfa(schema_to_regex(schema))
    assert d.matches('{"s":"yes"}')
    assert not d.matches('{"s":"yes')
    # $defs from EVERY tool are available
    t1 = {"name": "t1", "parameters": {
        "type": "object", "properties": {"a": {"$ref": "#/$defs/d1"}},
        "$defs": {"d1": {"type": "integer"}}}}
    t2 = {"name": "t2", "parameters": {
        "type": "object", "properties": {"b": {"$ref": "#/$defs/d2"}},
        "$defs": {"d2": {"type": "boolean"}}}}
    built = build_tool_regex([t1, t2], FunctionsConfig(disable_no_action=True))
    d = compile_dfa(built.pattern)
    assert d.matches('{"name":"t2","arguments":{"b":true}}')
    # allOf merges with sibling keys instead of being overwritten
    schema = {"allOf": [{"type": "object",
                         "properties": {"a": {"type": "integer"}}}],
              "properties": {"b": {"type": "string"}}}
    d = compile_dfa(schema_to_regex(schema))
    assert d.matches('{"a":1,"b":"x"}')
    assert not d.matches('{"b":"x"}')
    # optional named group yields "" not None
    cfg = FunctionsConfig(
        response_regex=[r"call=(?P<name>\w+)( args=(?P<arguments>\{.*\}))?"])
    res = parse_function_call("call=go", cfg)
    assert res[0].arguments == ""
    # malformed replacement entries are skipped
    cfg = FunctionsConfig(replace_llm_results=[{"value": "X"}])
    assert cleanup_llm_result("ab", cfg) == "ab"
    # DFA cache: same pattern → same object and shared mask rows
    from localai_tpu.functions.constraint import cached_dfa
    assert cached_dfa(r"[ab]+") is cached_dfa(r"[ab]+")
    tok = ByteTokenizer()
    c1 = constraint_for_regex(r"xy?z", tok)
    m1 = c1.allowed_mask()
    c2 = constraint_for_regex(r"xy?z", tok)
    assert c2.allowed_mask() is m1


def test_llama31_defs_and_hyphen_names():
    """Review fixes: llama3.1 keeps per-tool $defs; hyphenated tool names
    survive both grammar and parse."""
    tool = {"name": "get-weather", "parameters": {
        "type": "object",
        "properties": {"c": {"$ref": "#/$defs/city"}},
        "required": ["c"],
        "$defs": {"city": {"type": "string"}}}}
    cfg = FunctionsConfig(disable_no_action=True,
                          grammar={"schema_type": "llama3.1"})
    built = build_tool_regex([tool], cfg)
    d = compile_dfa(built.pattern)
    text = '<function=get-weather>{"c":"Nice"}</function>'
    assert d.matches(text)
    res = parse_function_call(text, cfg)
    assert res and res[0].name == "get-weather"
    assert json.loads(res[0].arguments) == {"c": "Nice"}
