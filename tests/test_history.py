"""Multi-resolution metrics history (obs.history).

The unit half of the round-18 persistence surface: bucket boundary
alignment across the 1s/10s/5m rings, counter-vs-gauge downsampling
semantics (max-of-cumulative vs mean), ring wraparound/retention,
out-of-order merge, and the snapshot/restore lifecycle a serving restart
exercises (atomic write, corrupt-file tolerance, env-driven install).
The scrape-time feeds (observe_engine/observe_ledger) and the HTTP
surface (/debug/history) are covered in test_api.py and the smoke.
"""

import json
import os
import threading

import pytest

from localai_tpu.obs.history import (
    CAPACITY,
    RESOLUTIONS,
    SNAPSHOT_FILE,
    History,
    install_from_env,
)

# -- bucket alignment --------------------------------------------------------


def test_points_in_one_second_share_a_bucket():
    h = History()
    h.record("g", 4.0, ts=100.0)
    h.record("g", 6.0, ts=100.9)
    h.record("g", 10.0, ts=101.0)
    q = h.query("g", res=1)
    assert [(p["ts"], p["value"], p["count"]) for p in q["points"]] == [
        (100.0, 5.0, 2),        # gauge bucket = mean of the 2 points
        (101.0, 10.0, 1),
    ]


def test_buckets_align_to_resolution_boundaries():
    h = History()
    h.record("g", 1.0, ts=109.9)
    h.record("g", 3.0, ts=110.0)
    ten = h.query("g", res=10)["points"]
    assert [p["ts"] for p in ten] == [100.0, 110.0]   # floor(ts/res)*res
    five = h.query("g", res=300)["points"]
    assert [p["ts"] for p in five] == [0.0]           # both inside [0,300)
    assert five[0]["count"] == 2


def test_query_snaps_unknown_resolution_to_nearest():
    h = History()
    h.record("g", 1.0, ts=50.0)
    assert h.query("g", res=2)["resolution_s"] == 1
    assert h.query("g", res=7)["resolution_s"] == 10
    assert h.query("g", res=9999)["resolution_s"] == 300


# -- counter vs gauge downsampling -------------------------------------------


def test_counter_bucket_keeps_max_cumulative_total():
    h = History()
    h.record("c", 100.0, kind="counter", ts=20.0)
    h.record("c", 120.0, kind="counter", ts=23.0)
    h.record("c", 115.0, kind="counter", ts=27.0)   # a stale re-export
    p = h.query("c", res=10)["points"]
    assert p == [{"ts": 20.0, "value": 120.0, "count": 3}]


def test_gauge_bucket_reports_mean():
    h = History()
    for v in (1.0, 2.0, 9.0):
        h.record("g", v, ts=40.0)
    p = h.query("g", res=10)["points"]
    assert p[0]["value"] == pytest.approx(4.0)
    assert p[0]["count"] == 3


# -- retention / wraparound --------------------------------------------------


def test_fine_ring_wraps_while_coarse_ring_retains():
    h = History()
    n = CAPACITY[1] + 50
    for i in range(n):
        h.record("c", float(i), kind="counter", ts=float(i))
    fine = h.query("c", res=1)["points"]
    assert len(fine) == CAPACITY[1]                  # capacity bound
    assert fine[0]["ts"] == float(n - CAPACITY[1])   # oldest dropped
    assert fine[-1]["value"] == float(n - 1)
    coarse = h.query("c", res=10)["points"]
    assert len(coarse) == n // 10                    # still has the past
    assert coarse[0]["ts"] == 0.0


def test_out_of_order_point_merges_into_resident_bucket():
    h = History()
    h.record("g", 1.0, ts=100.0)
    h.record("g", 5.0, ts=200.0)
    h.record("g", 3.0, ts=100.4)     # late arrival, bucket still resident
    one = {p["ts"]: p for p in h.query("g", res=1)["points"]}
    assert one[100.0]["count"] == 2
    assert one[100.0]["value"] == pytest.approx(2.0)


def test_out_of_order_point_past_retention_is_dropped():
    h = History()
    h.record("g", 1.0, ts=100.0)
    h.record("g", 2.0, ts=200.0)
    h.record("g", 9.0, ts=150.0)     # bucket 150 never existed: dropped
    assert [p["ts"] for p in h.query("g", res=1)["points"]] == [100.0,
                                                                200.0]


def test_query_since_and_unknown_series():
    h = History()
    h.record("g", 1.0, ts=100.0)
    h.record("g", 2.0, ts=200.0)
    assert h.query("missing") is None
    pts = h.query("g", res=1, since=150.0)["points"]
    assert [p["ts"] for p in pts] == [200.0]


# -- snapshot / restore ------------------------------------------------------


def _seed(h):
    h.record("tenant_tokens.t-abc", 40.0, kind="counter", ts=100.0)
    h.record("tenant_tokens.t-abc", 55.0, kind="counter", ts=160.0)
    h.record("occupancy.m", 0.5, ts=100.0)


def test_snapshot_restores_across_restart(tmp_path):
    h = History()
    _seed(h)
    path = h.save(str(tmp_path))
    assert path and os.path.basename(path) == SNAPSHOT_FILE

    restarted = History()                   # the next process boots clean
    assert restarted.load(str(tmp_path))
    assert restarted.series_names() == h.series_names()
    for name in h.series_names():
        for res in RESOLUTIONS:
            assert (restarted.query(name, res=res)
                    == h.query(name, res=res)), (name, res)
    # restored rings keep accepting points with the original bounds
    restarted.record("tenant_tokens.t-abc", 70.0, kind="counter", ts=170.0)
    pts = restarted.query("tenant_tokens.t-abc", res=1)["points"]
    assert pts[-1]["value"] == 70.0


def test_save_without_directory_is_a_noop():
    assert History().save() is None


def test_load_missing_and_corrupt_snapshots_are_warnings(tmp_path):
    h = History()
    assert not h.load(str(tmp_path))                     # nothing there
    (tmp_path / SNAPSHOT_FILE).write_text("{not json")
    assert not h.load(str(tmp_path))                     # corrupt ≠ crash
    malformed = {"version": 1, "series": {"g": {"kind": "gauge",
                                                "rings": {"1": [[1, 2]]}}}}
    (tmp_path / SNAPSHOT_FILE).write_text(json.dumps(malformed))
    assert h.load(str(tmp_path))                         # short cells skip
    assert h.query("g", res=1)["points"] == []


def test_snapshot_write_is_atomic(tmp_path):
    h = History()
    _seed(h)
    h.save(str(tmp_path))
    assert not (tmp_path / (SNAPSHOT_FILE + ".tmp")).exists()
    doc = json.loads((tmp_path / SNAPSHOT_FILE).read_text())
    assert doc["version"] == 1 and "tenant_tokens.t-abc" in doc["series"]


def test_configure_restores_and_starts_writer(tmp_path):
    h = History()
    _seed(h)
    h.save(str(tmp_path))

    h2 = History()
    h2.configure(str(tmp_path), snapshot_s=3600.0)
    try:
        assert h2.series_names() == h.series_names()     # boot restore
        writers = [t for t in threading.enumerate()
                   if t.name == "history-writer" and t.is_alive()]
        assert writers
    finally:
        h2.stop()


def test_flush_writes_synchronously(tmp_path):
    h = History()
    h.configure(str(tmp_path), snapshot_s=3600.0)
    try:
        h.record("g", 1.0, ts=10.0)
        assert h.flush() == str(tmp_path / SNAPSHOT_FILE)
        assert (tmp_path / SNAPSHOT_FILE).exists()
    finally:
        h.stop()


def test_install_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("LOCALAI_HISTORY_DIR", raising=False)
    assert not install_from_env(History())
    monkeypatch.setenv("LOCALAI_HISTORY_DIR", str(tmp_path))
    monkeypatch.setenv("LOCALAI_HISTORY_SNAPSHOT_S", "junk")
    h = History()
    try:
        assert install_from_env(h)
        assert h.snapshot_s == 30.0                      # junk → default
    finally:
        h.stop()


# -- scrape-time feeds -------------------------------------------------------


def test_observe_engine_records_curated_series():
    h = History()
    h.observe_engine("m", {"occupancy": 0.5, "queue_depth": 3,
                           "total_generated_tokens": 120})
    names = h.series_names()
    assert "occupancy.m" in names and "queue_depth.m" in names
    assert "tokens_generated.m" in names
    assert h.query("tokens_generated.m", res=1)["kind"] == "counter"
    h.observe_engine("w", {"error": "unreachable"})      # worker pane
    assert "occupancy.w" not in h.series_names()


def test_observe_ledger_records_tenant_and_waste_series():
    from localai_tpu.obs.ledger import TenantLedger

    led = TenantLedger(max_tenants=8)
    led.note_request(tenant="t-abc", model="m", lane="interactive",
                     reason="stop", tokens=10, prompt_tokens=4,
                     dispatch_ms=5.0, queue_wait_ms=1.0, kv_block_s=2.0)
    led.note_waste("spec_rejected", model="m", tokens=3)
    h = History()
    h.observe_ledger(led)
    assert "tenant_tokens.t-abc" in h.series_names()
    assert "tenant_requests.t-abc" in h.series_names()
    assert "goodput_tokens.m" in h.series_names()
    assert "waste_tokens.spec_rejected" in h.series_names()
    q = h.query("tenant_tokens.t-abc", res=1)
    assert q["kind"] == "counter"
    assert q["points"][-1]["value"] == 10.0
