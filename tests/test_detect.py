"""Backend auto-detection (the greedy-loader/guesser collapse — parity:
/root/reference/pkg/model/initializers.go:271-407 ordered backend chain +
core/config/guesser.go): a bare `model:` YAML routes to the right engine
by checkpoint sniffing."""

import json

from localai_tpu.config.loader import ConfigLoader
from localai_tpu.config.model_config import Usecase
from localai_tpu.models.detect import detect_backend


def test_detect_debug_presets():
    assert detect_backend("debug:sd-tiny") == "diffusers"
    assert detect_backend("debug:whisper-tiny") == "whisper"
    assert detect_backend("debug:reranker-tiny") == "reranker"
    assert detect_backend("debug:bert-tiny") == "bert-embeddings"
    assert detect_backend("debug:tiny") is None


def test_detect_dir_layouts(tmp_path):
    sd = tmp_path / "sd"
    (sd / "unet").mkdir(parents=True)
    assert detect_backend("sd", tmp_path) == "diffusers"

    w = tmp_path / "w"
    w.mkdir()
    (w / "config.json").write_text(json.dumps({"model_type": "whisper"}))
    assert detect_backend("w", tmp_path) == "whisper"

    # bert splits on the scoring head: classifier → cross-encoder
    # reranker, trunk-only → sentence embedder
    import numpy as np
    from safetensors.numpy import save_file

    ce = tmp_path / "ce"
    ce.mkdir()
    (ce / "config.json").write_text(json.dumps({"model_type": "bert"}))
    save_file({"classifier.weight": np.zeros((1, 4), np.float32)},
              ce / "model.safetensors")
    assert detect_backend("ce", tmp_path) == "reranker"

    st = tmp_path / "st"
    st.mkdir()
    (st / "config.json").write_text(json.dumps({"model_type": "bert"}))
    save_file({"embeddings.word_embeddings.weight":
               np.zeros((4, 4), np.float32)}, st / "model.safetensors")
    assert detect_backend("st", tmp_path) == "bert-embeddings"

    llm = tmp_path / "llm"
    llm.mkdir()
    (llm / "config.json").write_text(json.dumps({"model_type": "llama"}))
    assert detect_backend("llm", tmp_path) is None

    # not-yet-downloaded ref: no decision (detection re-runs post-install)
    assert detect_backend("missing", tmp_path) is None


def test_bare_yaml_routes_to_detected_backend(tmp_path):
    """A config with only `model:` serves the right usecases."""
    sd = tmp_path / "sd-ckpt"
    (sd / "unet").mkdir(parents=True)
    (tmp_path / "img.yaml").write_text("model: sd-ckpt\n")
    (tmp_path / "llm.yaml").write_text("model: 'debug:tiny'\n")
    (tmp_path / "stt.yaml").write_text("model: 'debug:whisper-tiny'\n")
    loader = ConfigLoader(tmp_path)
    loader.load_from_path()

    img = loader.get("img")
    assert img.backend == "diffusers"
    assert img.has_usecase(Usecase.IMAGE)
    assert not img.has_usecase(Usecase.CHAT)

    llm = loader.get("llm")
    assert llm.backend == ""
    assert llm.has_usecase(Usecase.CHAT)

    stt = loader.get("stt")
    assert stt.backend == "whisper"
    assert stt.has_usecase(Usecase.TRANSCRIPT)


def test_explicit_backend_wins(tmp_path):
    sd = tmp_path / "sd-ckpt"
    (sd / "unet").mkdir(parents=True)
    (tmp_path / "m.yaml").write_text(
        "model: sd-ckpt\nbackend: worker\n")
    loader = ConfigLoader(tmp_path)
    loader.load_from_path()
    assert loader.get("m").backend == "worker"


def test_cross_family_load_error_names_the_engine(tmp_path):
    """Loading a diffusers checkpoint through the LLM path fails with an
    actionable error naming the detected family."""
    import pytest

    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.models.manager import ModelManager

    sd = tmp_path / "sd-ckpt"
    (sd / "unet").mkdir(parents=True)
    (tmp_path / "m.yaml").write_text("model: sd-ckpt\nbackend: ''\n")
    app = AppConfig(model_path=str(tmp_path))
    loader = ConfigLoader(tmp_path)
    loader.load_from_path()
    # force the LLM path despite detection (explicit empty backend is
    # overridden by autodetect; simulate a stale config object)
    loader.get("m").backend = ""
    mgr = ModelManager(app, loader)
    with pytest.raises(RuntimeError, match="diffusers checkpoint"):
        mgr.get("m")
