"""RWKV models: numerical parity against transformers' torch reference
on tiny random checkpoints, recurrent-state decode equivalence, and
serving through the normal endpoints (SURVEY item 47)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import RwkvConfig as HFRwkvConfig  # noqa: E402
from transformers import RwkvForCausalLM  # noqa: E402

from localai_tpu.models.rwkv import (  # noqa: E402
    RwkvConfig,
    RwkvLM,
    forward,
    resolve_rwkv,
)

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    attention_hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    context_length=64,
)


def _torch_model(seed=0):
    torch.manual_seed(seed)
    hf_cfg = HFRwkvConfig(**TINY)
    model = RwkvForCausalLM(hf_cfg).eval()
    return hf_cfg, model


def _params_from(model):
    import jax.numpy as jnp

    return {k: jnp.asarray(v.detach().numpy())
            for k, v in model.state_dict().items()}


def test_prefill_logits_match_torch():
    hf_cfg, model = _torch_model()
    cfg = RwkvConfig.from_hf(hf_cfg.to_dict())
    params = _params_from(model)
    ids = torch.tensor([[3, 14, 15, 9, 26, 5]])
    with torch.no_grad():
        want = model(ids).logits.numpy()
    got = np.asarray(forward(params, cfg, ids.numpy())[0])
    np.testing.assert_allclose(got, want, atol=3e-4)


def test_step_matches_prefill():
    """Carrying the recurrent state is equivalent to re-running the full
    prefix."""
    hf_cfg, model = _torch_model(seed=2)
    cfg = RwkvConfig.from_hf(hf_cfg.to_dict())
    params = _params_from(model)
    prefix = np.asarray([[7, 21, 3, 44]])
    _, states = forward(params, cfg, prefix)
    nxt = np.asarray([[11]])
    step_logits, _ = forward(params, cfg, nxt, states)
    full = forward(params, cfg, np.concatenate([prefix, nxt], 1))[0]
    np.testing.assert_allclose(
        np.asarray(step_logits)[0, -1], np.asarray(full)[0, -1],
        atol=3e-4)


def test_generate_greedy_matches_torch():
    hf_cfg, model = _torch_model(seed=3)
    cfg = RwkvConfig.from_hf(hf_cfg.to_dict())
    lm = RwkvLM(cfg, _params_from(model), tokenizer=None)
    prompt = [5, 9, 13]
    with torch.no_grad():
        want = model.generate(
            torch.tensor([prompt]), max_new_tokens=8, do_sample=False,
        ).numpy()[0][len(prompt):]
    got = lm.generate(prompt, max_new_tokens=8, temperature=0.0,
                      eos_ids=set())
    assert got == [int(t) for t in want]


def test_serving_via_http(tmp_path):
    import httpx
    from test_api import _ServerThread, make_state

    (tmp_path / "r.yaml").write_text(
        "name: r\nmodel: 'debug:rwkv-tiny'\n"
        "parameters: {temperature: 0.0, max_tokens: 6}\n"
    )
    srv = _ServerThread(make_state(tmp_path))
    try:
        assert srv.state.loader.get("r").backend == "rwkv"
        with httpx.Client(base_url=srv.base, timeout=120.0) as c:
            r = c.post("/v1/completions", json={
                "model": "r", "prompt": "hi", "max_tokens": 6,
            })
            assert r.status_code == 200, r.text
            assert r.json()["choices"][0]["finish_reason"] in (
                "stop", "length")
    finally:
        srv.stop()
