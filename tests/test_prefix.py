"""KV prefix-cache reuse (parity: llama.cpp common_part slot reuse,
/root/reference/backend/cpp/llama/grpc-server.cpp:67-74 + slot
cache_tokens; prompt-cache config backend_config.go:120-122)."""

import pytest

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import GenRequest, Scheduler
from localai_tpu.models.quant import quantize_params
from localai_tpu.models.registry import resolve_model

SYS = list(range(1, 60))  # 59-token shared "system prompt"


@pytest.fixture(scope="module")
def small():
    return resolve_model("debug:small")


def _runner(small, **kw):
    return ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=256,
                       prefill_buckets=[16, 64, 128], **kw)


def _generate(r, slot, n=8):
    return [int(r.step()[slot]) for _ in range(n)]


def test_resume_matches_full_prefill(small):
    p1 = SYS + [100, 101, 102]
    p2 = SYS + [110, 111, 112, 113]

    ra = _runner(small)
    s = ra.acquire_slot()
    ref = [ra.admit(s, p2, temperature=0.0)] + _generate(ra, s)
    assert ra.last_prefix_reused == 0

    rb = _runner(small)
    s2 = rb.acquire_slot()
    gen = [rb.admit(s2, p1, temperature=0.0)] + _generate(rb, s2, 4)
    rb.release(s2)
    s2 = rb.acquire_slot(s2)
    out = [rb.admit(s2, p2, temperature=0.0, resident=p1 + gen)]
    assert rb.last_prefix_reused == len(SYS)
    out += _generate(rb, s2)
    assert out == ref


def test_resume_matches_with_int8_kv(small):
    qp = quantize_params(small.params)
    p1 = SYS + [100, 101]
    p2 = SYS + [120, 121, 122]

    ra = ModelRunner(small.cfg, qp, num_slots=2, max_ctx=256,
                     prefill_buckets=[16, 64, 128], kv_dtype="int8")
    s = ra.acquire_slot()
    ref = [ra.admit(s, p2, temperature=0.0)] + _generate(ra, s)

    rb = ModelRunner(small.cfg, qp, num_slots=2, max_ctx=256,
                     prefill_buckets=[16, 64, 128], kv_dtype="int8")
    s2 = rb.acquire_slot()
    gen = [rb.admit(s2, p1, temperature=0.0)] + _generate(rb, s2, 3)
    rb.release(s2)
    s2 = rb.acquire_slot(s2)
    out = [rb.admit(s2, p2, temperature=0.0, resident=p1 + gen)]
    assert rb.last_prefix_reused == len(SYS)
    out += _generate(rb, s2)
    assert out == ref


def test_short_prefix_not_reused(small):
    r = _runner(small)
    s = r.acquire_slot()
    r.admit(s, [1, 2, 3, 4, 5], temperature=0.0)
    r.release(s)
    s = r.acquire_slot(s)
    r.admit(s, [1, 2, 3, 4, 99], temperature=0.0,
            resident=[1, 2, 3, 4, 5])
    assert r.last_prefix_reused == 0  # below prefix_reuse_min


def test_identical_prompt_recomputes_last_token(small):
    p = SYS + [100]
    r = _runner(small)
    s = r.acquire_slot()
    first = r.admit(s, p, temperature=0.0)
    gen = [first] + _generate(r, s, 3)
    r.release(s)
    s = r.acquire_slot(s)
    again = r.admit(s, p, temperature=0.0, resident=p + gen)
    # reuse capped at n-1: the last token is recomputed for its logits
    assert r.last_prefix_reused == len(p) - 1
    assert again == first


def test_divergent_prompt_not_reused(small):
    r = _runner(small)
    s = r.acquire_slot()
    r.admit(s, SYS + [1], temperature=0.0)
    r.release(s)
    s = r.acquire_slot(s)
    different = [9] * 40
    r.admit(s, different, temperature=0.0, resident=SYS + [1])
    assert r.last_prefix_reused == 0


def test_scheduler_routes_to_matching_slot(small):
    """Second request sharing the system prompt lands on the slot that
    holds it and reuses the prefix (metrics prove it); output equals a
    cold scheduler's."""
    sched = Scheduler(ModelRunner(small.cfg, small.params, num_slots=2,
                                  max_ctx=256,
                                  prefill_buckets=[16, 64, 128]),
                      small.tokenizer, multi_step=2, pipeline_depth=1)
    try:
        r1 = sched.submit(GenRequest(prompt=SYS + [100, 101],
                                     max_new_tokens=4, temperature=0.0))
        r1.result(60)
        r2 = sched.submit(GenRequest(prompt=SYS + [110, 111],
                                     max_new_tokens=6, temperature=0.0))
        r2.result(60)
        reused = sched.metrics()["prefix_tokens_reused"]
        assert reused >= len(SYS)
        warm_text = r2.text
    finally:
        sched.shutdown()

    cold = Scheduler(ModelRunner(small.cfg, small.params, num_slots=2,
                                 max_ctx=256,
                                 prefill_buckets=[16, 64, 128]),
                     small.tokenizer, multi_step=2, pipeline_depth=1)
    try:
        rc = cold.submit(GenRequest(prompt=SYS + [110, 111],
                                    max_new_tokens=6, temperature=0.0))
        rc.result(60)
        assert rc.text == warm_text
    finally:
        cold.shutdown()


def test_resume_bucket_respects_context_bound(small):
    r = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=128,
                    prefill_buckets=[64])
    s = r.acquire_slot()
    p1 = list(range(1, 100))  # 99 tokens; bucket 128 (max_ctx)
    r.admit(s, p1, temperature=0.0)
    r.release(s)
    s = r.acquire_slot(s)
    # lcp would be 99, tail bucket 64 → 99+64 > 128: falls back to full
    p2 = p1 + [120, 121]
    r.admit(s, p2, temperature=0.0, resident=p1 + [5])
    assert r.last_prefix_reused == 0
