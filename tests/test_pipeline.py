"""Pipeline (layer-sharded) parallelism over the 'pipe' axis — the
llama.cpp layer-split-mode analogue (HBM capacity scaling). VERDICT r4
weak #6: the axis finally has a consumer, verified against the
single-device engine."""

import jax
import pytest

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models.registry import resolve_model
from localai_tpu.parallel.mesh import MeshPlan, build_mesh
from localai_tpu.parallel.pipeline import shard_params_pp

PROMPT = list(range(1, 40))


@pytest.fixture(scope="module")
def small():
    return resolve_model("debug:small", dtype="float32")


@pytest.fixture(scope="module")
def pipe_mesh():
    # 'small' has 4 layers → 2 stages of 2; Mesh doesn't need every device
    return build_mesh(MeshPlan(pipe=2), devices=jax.devices()[:2])


def _greedy(runner, n=7):
    s = runner.acquire_slot()
    out = [runner.admit(s, PROMPT, temperature=0.0)]
    while len(out) < n:
        out.append(int(runner.step()[s]))
    return out


def test_pp_weights_and_kv_are_layer_sharded(small, pipe_mesh):
    sp = shard_params_pp(small.params, small.cfg, pipe_mesh)
    wq = sp["layers"]["wq"]
    L = small.cfg.num_layers
    assert wq.shape[0] == L
    assert wq.addressable_shards[0].data.shape[0] == L // 2, \
        "layer axis not sharded over 'pipe'"
    r = ModelRunner(small.cfg, sp, num_slots=2, max_ctx=256,
                    prefill_buckets=[64], kv_dtype="float32",
                    mesh=pipe_mesh)
    assert r.pp_enabled and r.attn_impl == "xla"
    assert r.kv.k.addressable_shards[0].data.shape[0] == L // 2, \
        "KV cache layer axis not sharded over 'pipe'"


def test_pp_greedy_matches_single_device(small, pipe_mesh):
    """Prefill + decode through the stage chain equals the unsharded
    engine exactly (greedy)."""
    sp = shard_params_pp(small.params, small.cfg, pipe_mesh)
    r = ModelRunner(small.cfg, sp, num_slots=2, max_ctx=256,
                    prefill_buckets=[64], kv_dtype="float32",
                    mesh=pipe_mesh)
    rx = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=256,
                     prefill_buckets=[64], kv_dtype="float32")
    assert _greedy(r) == _greedy(rx)


def test_pp_prefix_resume_and_release(small, pipe_mesh):
    """The resume path (suffix prefill over kept KV) works through the
    pipeline forward too."""
    sp = shard_params_pp(small.params, small.cfg, pipe_mesh)
    r = ModelRunner(small.cfg, sp, num_slots=2, max_ctx=256,
                    prefill_buckets=[64], kv_dtype="float32",
                    mesh=pipe_mesh)
    s = r.acquire_slot()
    first = r.admit(s, PROMPT, temperature=0.0)
    toks = [int(t[s]) for t in r.step_n(2)]
    resident = PROMPT + [first] + toks
    r.release(s)
    s2 = r.acquire_slot(s)
    r.admit(s2, PROMPT + [77, 78], resident=resident, temperature=0.0)
    assert r.last_prefill_path == "resume"
    assert r.last_prefix_reused >= 16

    rx = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=256,
                     prefill_buckets=[64], kv_dtype="float32")
    sx = rx.acquire_slot()
    fx = rx.admit(sx, PROMPT, temperature=0.0)
    tx = [int(t[sx]) for t in rx.step_n(2)]
    rx.release(sx)
    sx2 = rx.acquire_slot(sx)
    rx.admit(sx2, PROMPT + [77, 78], resident=PROMPT + [fx] + tx,
             temperature=0.0)
    assert int(r.step()[s2]) == int(rx.step()[sx2])


def test_pp_int8_kv(small, pipe_mesh):
    """Quantized KV works under the pipe-sharded cache."""
    sp = shard_params_pp(small.params, small.cfg, pipe_mesh)
    r = ModelRunner(small.cfg, sp, num_slots=2, max_ctx=256,
                    prefill_buckets=[64], kv_dtype="int8", mesh=pipe_mesh)
    rx = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=256,
                     prefill_buckets=[64], kv_dtype="int8")
    assert _greedy(r, 5) == _greedy(rx, 5)


def test_pp_gates(small):
    mesh = build_mesh(MeshPlan(data=2, pipe=2),
                      devices=jax.devices()[:4])
    sp = shard_params_pp(small.params, small.cfg, mesh)
    with pytest.raises(ValueError, match="no other axis"):
        ModelRunner(small.cfg, sp, num_slots=4, max_ctx=256,
                    prefill_buckets=[64], kv_dtype="float32", mesh=mesh)

    import dataclasses

    mesh2 = build_mesh(MeshPlan(pipe=3), devices=jax.devices()[:3])
    cfg3 = dataclasses.replace(small.cfg)  # 4 layers % 3 != 0
    with pytest.raises(ValueError, match="divisible"):
        ModelRunner(cfg3, small.params, num_slots=2, max_ctx=256,
                    prefill_buckets=[64], kv_dtype="float32", mesh=mesh2)


def test_pp_through_build_serving_model(tmp_path):
    """pipeline_parallel_size in the YAML opens the pipe mesh end-to-end
    through the scheduler."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.models.manager import build_serving_model

    mcfg = ModelConfig(
        name="pp", model="debug:small", context_size=256,
        sharding={"pipeline_parallel_size": 2},
        engine={"max_slots": 2, "prefill_buckets": [64]},
    )
    sm = build_serving_model(mcfg, AppConfig(model_path=str(tmp_path)))
    try:
        assert sm.runner.pp_enabled
        assert sm.runner.mesh.shape["pipe"] == 2
        h = sm.scheduler.submit(GenRequest(
            prompt=PROMPT, max_new_tokens=4, temperature=0.0))
        h.result(timeout=120)
        assert h.finish_reason in ("stop", "length")
    finally:
        sm.scheduler.shutdown()


def test_ep_through_build_serving_model(tmp_path):
    """expert_parallel_size in the YAML builds an expert mesh (previously
    the manager ignored it entirely)."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.models.manager import build_serving_model

    mcfg = ModelConfig(
        name="moe-ep", model="debug:tiny-moe", context_size=256,
        sharding={"expert_parallel_size": 2},
        engine={"max_slots": 4, "prefill_buckets": [32]},
    )
    sm = build_serving_model(mcfg, AppConfig(model_path=str(tmp_path)))
    try:
        assert sm.runner.mesh is not None
        assert sm.runner.mesh.shape["expert"] == 2
        wg = sm.runner.params["layers"]["w_gate"]
        E = sm.runner.cfg.num_experts
        assert wg.addressable_shards[0].data.shape[1] == E // 2
    finally:
        sm.scheduler.shutdown()
