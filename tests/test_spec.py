"""Block-native speculative decoding (localai_tpu.spec, ISSUE 11).

The paged draft lane: drafters propose through one Drafter protocol
(self-drafting n-gram lookup, co-located draft model), ONE verify-k
target dispatch scores the window through the block-table mirror, and
the accept scan rolls each slot's frontier back independently. Emitted
tokens come from the target's own sampler chain, so greedy paged+spec
output must equal greedy non-spec paged output exactly — on one device
and under a mesh."""

import numpy as np
import pytest

from localai_tpu.engine.runner import SKIP, ModelRunner
from localai_tpu.models.registry import resolve_model
from localai_tpu.spec import ModelDrafter, NGramDrafter, SpecEngine

REPEAT = list(b"abcabcabcabcabcabc")


@pytest.fixture(scope="module")
def tiny():
    return resolve_model("debug:tiny", dtype="float32")


@pytest.fixture(scope="module")
def small():
    return resolve_model("debug:small", dtype="float32")


def _mk(model, *, paged=True, num_slots=2, max_ctx=128, **kw):
    kw.setdefault("prefill_buckets", [32])
    kw.setdefault("kv_dtype", "float32")
    if paged:
        kw.setdefault("kv_block_tokens", 16)
    return ModelRunner(model.cfg, model.params, num_slots=num_slots,
                       max_ctx=max_ctx, paged=paged, **kw)


def _plain_tokens(runner, prompt, n, slot=None):
    s = runner.acquire_slot(slot)
    out = [runner.admit(s, prompt, temperature=0.0)]
    for _ in range(n):
        out.append(int(runner.step()[s]))
    return out


def _spec_tokens(eng, prompt, n, max_windows=60):
    """Drive the engine like the scheduler: spec window when the drafter
    has proposals, plain decode otherwise."""
    slot = eng.acquire_slot()
    out = [eng.admit(slot, prompt, temperature=0.0)]
    windows = 0
    while len(out) <= n and windows < max_windows:
        windows += 1
        rows = eng.step_spec_async()
        if rows is None:  # drafter declined — plain fallback
            tok = int(eng.target.step()[slot])
            out.append(tok)
            eng.drafter.observe(slot, [tok])
            continue
        host = np.asarray(rows)
        eng.observe_window(host)
        for t in range(host.shape[0]):
            if host[t, slot] != SKIP:
                out.append(int(host[t, slot]))
    return out[:n + 1]


class PlannedDrafter:
    """Deterministic test drafter: proposes scripted windows (slot 0)."""

    name = "planned"
    device_proposals = False

    def __init__(self, num_slots, gamma, windows):
        self.num_slots = num_slots
        self.gamma = gamma
        self.windows = list(windows)   # each: list[gamma] proposals

    def propose(self, tokens, positions):
        if not self.windows:
            return None
        props = np.zeros((self.num_slots, self.gamma), np.int32)
        props[0] = self.windows.pop(0)
        return props

    def admit(self, slot, prompt, first, positions):
        pass

    def observe(self, slot, emitted):
        pass

    def resync(self, slot, resident, positions):
        pass

    def release(self, slot):
        pass

    def reinit(self):
        self.windows.clear()

    def stats(self):
        return {"drafter": self.name}


# ---------------------------------------------------------------------------
# drafters
# ---------------------------------------------------------------------------


def test_ngram_lookup_proposes_continuation():
    d = NGramDrafter(num_slots=2, gamma=3)
    d.admit(0, [1, 2, 3, 4, 1, 2], 3, None)   # history ..., 1, 2, 3
    props = d.propose(None, None)
    assert props is not None
    # frontier trigram [1, 2, 3] occurred before, followed by 4, 1, 2
    assert props[0].tolist() == [4, 1, 2]
    # no history for slot 1 → zero filler row, but the window still fires
    assert props[1].tolist() == [0, 0, 0]


def test_ngram_declines_without_repetition():
    d = NGramDrafter(num_slots=1, gamma=3)
    d.admit(0, [5, 9, 2, 7], 11, None)  # no repeated n-gram
    assert d.propose(None, None) is None
    assert d.stats()["lookup_misses"] > 0


def test_ngram_resync_and_release():
    d = NGramDrafter(num_slots=1, gamma=2)
    d.admit(0, [1, 2], 3, None)
    d.resync(0, [7, 8, 7, 8], None)
    props = d.propose(None, None)
    assert props is not None and props[0][0] == 7
    d.release(0)
    assert d.propose(None, None) is None


# ---------------------------------------------------------------------------
# greedy parity: paged+spec == paged plain (the acceptance criterion)
# ---------------------------------------------------------------------------


def test_paged_ngram_greedy_parity(tiny):
    ref = _plain_tokens(_mk(tiny), REPEAT, 24)
    eng = SpecEngine(_mk(tiny), NGramDrafter(2, gamma=4), gamma=4)
    got = _spec_tokens(eng, REPEAT, 24)
    assert got == ref
    # the verify-k dispatch actually amortized: >1 token per window once
    # the stream cycles (the perf_smoke spec gate pins this too)
    assert eng.tokens_per_dispatch > 1.0
    assert eng.accept_rate > 0.0
    assert not eng.target.allocator.check_invariants()


def test_paged_model_drafter_greedy_parity(small, tiny):
    """Stub draft model (different weights — imperfect proposals) over a
    paged target: emitted tokens still come from the target's sampler."""
    ref = _plain_tokens(_mk(small), REPEAT, 16)
    target = _mk(small)
    draft = _mk(tiny, paged=False)
    eng = SpecEngine(target, ModelDrafter(draft, gamma=3), gamma=3)
    got = _spec_tokens(eng, REPEAT, 16)
    assert got == ref
    assert not target.allocator.check_invariants()


def test_paged_spec_int8_kv(tiny):
    """Verify writes ride the scaled-int8 pool (values + scale rows) and
    stay byte-identical to plain int8 paged decode."""
    ref = _plain_tokens(_mk(tiny, kv_dtype="int8"), REPEAT, 16)
    eng = SpecEngine(_mk(tiny, kv_dtype="int8"),
                     NGramDrafter(2, gamma=3), gamma=3)
    got = _spec_tokens(eng, REPEAT, 16)
    assert got == ref


def test_meshed_paged_spec_greedy_parity(tiny):
    """2-virtual-device data mesh: the sharded table mirror + pool serve
    the same verify windows token-for-token as the single-device lane."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    from localai_tpu.parallel import sharding as shd
    from localai_tpu.parallel.mesh import MeshPlan, build_mesh

    ref = _plain_tokens(_mk(tiny), REPEAT, 16)
    mesh = build_mesh(MeshPlan(data=2), devices=jax.devices()[:2])
    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    target = ModelRunner(tiny.cfg, params, num_slots=2, max_ctx=128,
                         prefill_buckets=[32], kv_dtype="float32",
                         paged=True, kv_block_tokens=16, mesh=mesh)
    eng = SpecEngine(target, NGramDrafter(2, gamma=4), gamma=4)
    got = _spec_tokens(eng, REPEAT, 16)
    assert got == ref


def test_meshed_model_drafter_parity(small, tiny):
    """Co-located draft model sharing the mesh's data axis (ISSUE 11
    tentpole b): dp-sharded target AND draft reproduce the single-device
    stream."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    from localai_tpu.parallel import sharding as shd
    from localai_tpu.parallel.mesh import MeshPlan, build_mesh

    ref_eng = SpecEngine(_mk(small), ModelDrafter(_mk(tiny, paged=False),
                                                  gamma=3), gamma=3)
    ref = _spec_tokens(ref_eng, REPEAT, 12)

    mesh = build_mesh(MeshPlan(data=2), devices=jax.devices()[:2])

    def mk_mesh(model, paged):
        params = shd.shard_params(model.params, model.cfg, mesh)
        return ModelRunner(model.cfg, params, num_slots=2, max_ctx=128,
                           prefill_buckets=[32], kv_dtype="float32",
                           paged=paged, mesh=mesh,
                           **({"kv_block_tokens": 16} if paged else {}))

    eng = SpecEngine(mk_mesh(small, True),
                     ModelDrafter(mk_mesh(tiny, False), gamma=3), gamma=3)
    got = _spec_tokens(eng, REPEAT, 12)
    assert got == ref


# ---------------------------------------------------------------------------
# rollback + reservation accounting
# ---------------------------------------------------------------------------


def test_rollback_after_partial_accept_block_accounting(tiny):
    """Scripted windows: full reject then partial accept. Output must
    equal plain decode (corrections are the target's own samples), the
    frontier rolls back per window, and the allocator's speculation
    reservation conserves blocks throughout."""
    ref = _plain_tokens(_mk(tiny), REPEAT, 8)
    target = _mk(tiny)
    v = tiny.cfg.vocab_size
    windows = [
        [(ref[1] + 1) % v] * 3,           # all wrong → emit 1 (correction)
        [ref[2], (ref[3] + 1) % v, 0],    # 1 accepted + correction → emit 2
        [ref[4], ref[5], (ref[6] + 1) % v],  # 2 accepted + correction
    ]
    eng = SpecEngine(target, PlannedDrafter(2, 3, windows), gamma=3)
    slot = eng.acquire_slot()
    out = [eng.admit(slot, REPEAT, temperature=0.0,
                     reserve_tokens=len(REPEAT) + 32)]
    p0 = len(REPEAT)
    expect_emitted = [1, 2, 3]
    for want in expect_emitted:
        rows = eng.step_spec()
        got = int((rows[:, slot] != SKIP).sum())
        assert got == want
        out.extend(int(x) for x in rows[:, slot][rows[:, slot] != SKIP])
        p0 += got
        # per-slot rollback: the frontier advanced by exactly the emitted
        # count, never by the full window width
        assert eng.slot_position(slot) == p0
        assert not target.allocator.check_invariants()
    assert out == ref[:len(out)]
    eng.release(slot)
    st = target.allocator.stats()
    assert st.free + st.cached == st.total  # nothing leaked
    assert st.spec_reserved == 0


def test_spec_reservation_accounting(tiny):
    """begin_admit(spec_tokens=) records speculation blocks separately
    and check_invariants audits them (tail-of-table, never pool-shared)."""
    r = _mk(tiny, max_ctx=128)
    adm = r.begin_admit(0, list(range(1, 20)), reserve_tokens=33,
                        spec_tokens=16, temperature=0.0)
    assert adm is not None
    while adm.step_chunk() is None:
        pass
    alloc = r.allocator
    # 33 base rows → 3 blocks of 16; +16 spec rows → 1 more block
    assert alloc.spec_blocks[0] == 1
    assert alloc.stats().spec_reserved == 1
    assert not alloc.check_invariants()
    # corrupting the reservation record is caught
    alloc.spec_blocks[0] = len(alloc.tables[0]) + 7
    assert any("speculation" in p for p in alloc.check_invariants())
    alloc.spec_blocks[0] = 1
    r.release(0)
    assert alloc.stats().spec_reserved == 0
    assert not alloc.check_invariants()


def test_pool_exhaustion_with_spec_reservation(tiny):
    """A pool whose remaining blocks cover the base reservation but not
    base+spec holds the admission (returns None) instead of admitting a
    slot whose draft windows could overrun — and the hold clears when
    the co-resident's speculation blocks free."""
    # 9 allocatable blocks of 16 rows
    r = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                    prefill_buckets=[32], kv_dtype="float32", paged=True,
                    kv_block_tokens=16, kv_num_blocks=10)
    prompt = list(range(1, 30))
    # slot 0: 65 base + 16 spec rows → 6 blocks (1 of them speculation)
    adm = r.begin_admit(0, prompt, reserve_tokens=65, spec_tokens=16,
                        temperature=0.0)
    assert adm is not None
    while adm.step_chunk() is None:
        pass
    assert r.allocator.stats().spec_reserved == 1
    # slot 1 (distinct prompt — no pool sharing): base 33 rows → 3
    # blocks would fit the 3 free ones, but the +16-row speculation
    # lookahead needs a 4th → held (None), no leak
    p2 = list(range(100, 129))
    assert r.begin_admit(1, p2, reserve_tokens=33, spec_tokens=16,
                         temperature=0.0) is None
    assert 1 not in r.allocator.tables
    assert r.begin_admit(1, p2, reserve_tokens=33,
                         temperature=0.0) is not None
    r.release(1)
    r.release(0)
    st = r.allocator.stats()
    assert st.free + st.cached == st.total
    assert st.spec_reserved == 0
    assert not r.allocator.check_invariants()


def test_nan_guard_in_verify_window(tiny):
    """The accept scan carries the per-row NaN/inf guard (speculation is
    the default lane — skipping it would reopen the silent-poison class
    the plain decode path closed): a non-finite logits row emits the
    NAN_TOKEN sentinel, ends the slot's window, and never enters the
    drafter history or the emitted telemetry."""
    from localai_tpu.engine.runner import NAN_TOKEN

    target = _mk(tiny)
    eng = SpecEngine(target, PlannedDrafter(2, 3, [[1, 2, 3]]), gamma=3)
    slot = eng.acquire_slot()
    eng.admit(slot, REPEAT, temperature=0.0)
    eng.set_bias(slot, np.full(tiny.cfg.vocab_size, np.nan, np.float32))
    rows = eng.step_spec()
    col = rows[:, slot].tolist()
    assert col[0] == NAN_TOKEN
    assert all(t < 0 for t in col[1:])  # window ended at the sentinel
    assert eng.total_emitted == 0       # sentinels are not tokens


def test_scheduler_spec_nan_fault_fails_only_target(tiny):
    """decode.nan chaos through a spec-enabled scheduler: the poisoned
    request fails with a clean error (caught inside the verify window or
    the plain fallback — both guard), the engine keeps serving."""
    from localai_tpu import faults
    from localai_tpu.engine.scheduler import GenRequest

    target = _mk(tiny)
    spec = SpecEngine(target, NGramDrafter(2, gamma=4))
    sched = _sched(target, tiny.tokenizer, spec=spec)
    try:
        faults.arm(faults.FaultSpec(site="decode.nan", mode="nan",
                                    match="spec-poison", times=1))
        h = sched.submit(GenRequest(prompt=REPEAT,
                                    correlation_id="spec-poison",
                                    **CYCLIC))
        h.result(120)
        assert h.finish_reason == "error"
        assert sched.nan_rows >= 1
        # the engine survives and keeps serving correct output
        h2 = sched.generate(GenRequest(prompt=REPEAT, **CYCLIC),
                            timeout=120)
        assert h2.finish_reason in ("stop", "length")
        assert not target.allocator.check_invariants()
    finally:
        faults.clear()
        sched.shutdown()


def test_extend_spec_accounting(tiny):
    """extend() records the speculation reservation only when blocks were
    actually added, and drops it when the retained table subsumes the
    new reservation (the audit must never point at unrelated old tail
    blocks)."""
    from localai_tpu.engine.paged import BlockAllocator

    alloc = BlockAllocator(num_blocks=10, block_tokens=16,
                           max_blocks_per_seq=8)
    assert alloc.allocate(0, 33) == 0          # 3 blocks
    assert alloc.extend(0, 33, spec_tokens=16)  # +1 spec block
    assert alloc.spec_blocks[0] == 1
    assert not alloc.check_invariants()
    # retained table (4 blocks) already covers a smaller reservation:
    # the speculation record is dropped, not pointed at old blocks
    assert alloc.extend(0, 17, spec_tokens=16)
    assert 0 not in alloc.spec_blocks
    assert not alloc.check_invariants()
    # exhaustion must not leave a phantom reservation behind
    assert alloc.allocate(1, 65) == 0          # 5 blocks → pool full
    assert not alloc.extend(0, 129, spec_tokens=16)
    assert 0 not in alloc.spec_blocks
    assert alloc.stats().spec_reserved == 0
    assert not alloc.check_invariants()


def test_acceptance_backoff_suppresses_windows(tiny):
    """A drafter whose proposals never get accepted trips the
    acceptance-floor backoff: speculation self-suppresses for the
    cooldown instead of paying a gamma+1-wide verify per emitted token."""

    class AlwaysWrong(PlannedDrafter):
        def __init__(self, num_slots, gamma, vocab):
            super().__init__(num_slots, gamma, [])
            self.vocab = vocab

        def propose(self, tokens, positions):
            # proposals the target can never greedily sample: outside
            # the model's actual argmax by construction is impossible to
            # guarantee, so just rotate the whole vocab — acceptance is
            # ~1/vocab per position, effectively zero
            props = np.full((self.num_slots, self.gamma),
                            self.vocab - 1, np.int32)
            return props

    target = _mk(tiny)
    eng = SpecEngine(target, AlwaysWrong(2, 3, tiny.cfg.vocab_size),
                     gamma=3, min_accept=0.5, cooldown=10)
    slot = eng.acquire_slot()
    out = [eng.admit(slot, REPEAT, temperature=0.0)]
    suppressed_seen = 0
    for _ in range(40):
        rows = eng.step_spec_async()
        if rows is None:
            suppressed_seen += 1
            tok = int(target.step()[slot])
            out.append(tok)
            continue
        host = np.asarray(rows)
        eng.observe_window(host)
        out.extend(int(x) for x in host[:, slot][host[:, slot] != SKIP])
    # the recent-window tracker (16 windows) filled, the floor tripped,
    # and the cooldown routed dispatches to plain decode
    assert eng.total_suppressed > 0
    assert suppressed_seen == eng.total_suppressed
    # output still exactly the plain greedy stream
    ref = _plain_tokens(_mk(tiny), REPEAT, len(out) - 1)
    assert out == ref


# ---------------------------------------------------------------------------
# scheduler end-to-end (the default paged hot path)
# ---------------------------------------------------------------------------


def _sched(runner, tokenizer, **kw):
    from localai_tpu.engine.scheduler import Scheduler

    kw.setdefault("multi_step", 4)
    return Scheduler(runner, tokenizer, **kw)


# greedy decode under the scheduler's padded-vocab ban takes a while to
# enter a cycle; a huge logit bias forces one immediately, making the
# n-gram lane's acceptance deterministic for the telemetry asserts
CYCLIC = dict(logit_bias={97: 1e4}, max_new_tokens=24, temperature=0.0,
              ignore_eos=True)


def test_scheduler_paged_spec_matches_plain(tiny):
    """End-to-end: a paged+spec scheduler's greedy byte stream equals the
    non-spec paged scheduler's (spec windows and plain fallbacks both),
    and the spec telemetry is live."""
    from localai_tpu.engine.scheduler import GenRequest

    req = dict(prompt=REPEAT, max_new_tokens=24, temperature=0.0,
               ignore_eos=True)
    plain = _sched(_mk(tiny), tiny.tokenizer)
    try:
        ref = plain.generate(GenRequest(**req), timeout=120)
        ref_cyc = plain.generate(GenRequest(prompt=REPEAT, **CYCLIC),
                                 timeout=120)
    finally:
        plain.shutdown()

    target = _mk(tiny)
    spec = SpecEngine(target, NGramDrafter(2, gamma=4), gamma=4)
    sched = _sched(target, tiny.tokenizer, spec=spec)
    try:
        got = sched.generate(GenRequest(**req), timeout=120)
        assert got.token_ids == ref.token_ids
        assert got.text == ref.text
        # a forced-cyclic stream makes the lookup hit deterministically
        got_cyc = sched.generate(GenRequest(prompt=REPEAT, **CYCLIC),
                                 timeout=120)
        assert got_cyc.token_ids == ref_cyc.token_ids
        m = sched.metrics()
        assert m["spec_windows"] > 0
        assert m["spec_draft_tokens"] > 0
        assert m["spec_accepted_tokens"] > 0
        assert m["spec_accept_rate"] > 0.0
        assert m["spec_tokens_per_dispatch"] > 1.0
        assert m["spec_drafter"] == "ngram"
        # per-dispatch accept counts land in the flight ring
        recs = sched.flight.snapshot()
        spec_recs = [x for x in recs if x["program"] == "spec"]
        assert spec_recs and any(x["spec_proposed"] > 0 for x in spec_recs)
        assert any(x["spec_accepted"] > 0 for x in spec_recs)
        # spec dispatches feed the step-time percentiles (steps > 0)
        assert all(x["steps"] > 0 for x in spec_recs)
        assert m["kv_blocks_spec_reserved"] >= 0
    finally:
        sched.shutdown()


def test_scheduler_spec_metrics_exported(tiny):
    """update_engine_gauges renders the localai_spec_* series from the
    scheduler's metrics surface."""
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.obs.metrics import Registry, update_engine_gauges

    target = _mk(tiny)
    spec = SpecEngine(target, NGramDrafter(2, gamma=3), gamma=3)
    sched = _sched(target, tiny.tokenizer, spec=spec)
    try:
        sched.generate(GenRequest(prompt=REPEAT, **CYCLIC), timeout=120)
        reg = Registry()
        update_engine_gauges("m", sched.metrics(), registry=reg)
        text = reg.render()
        assert 'localai_spec_accept_rate{model="m"}' in text
        assert 'localai_spec_draft_tokens_total{model="m"}' in text
        assert 'localai_spec_accepted_tokens_total{model="m"}' in text
        assert 'localai_spec_tokens_per_dispatch{model="m"}' in text
    finally:
        sched.shutdown()


def test_spec_draft_fault_garbles_but_stays_correct(tiny):
    """spec.draft chaos site: garbled proposals collapse acceptance but
    the greedy stream stays byte-identical (corrections are the target's
    own samples) and blocks conserve."""
    from localai_tpu import faults
    from localai_tpu.engine.scheduler import GenRequest

    req = dict(prompt=REPEAT, **CYCLIC)
    plain = _sched(_mk(tiny), tiny.tokenizer)
    try:
        ref = plain.generate(GenRequest(**req), timeout=120)
    finally:
        plain.shutdown()

    target = _mk(tiny)
    spec = SpecEngine(target, NGramDrafter(2, gamma=4), gamma=4)
    sched = _sched(target, tiny.tokenizer, spec=spec)
    try:
        faults.arm(faults.FaultSpec(site="spec.draft", mode="garble",
                                    times=0))
        got = sched.generate(GenRequest(**req), timeout=120)
        assert got.token_ids == ref.token_ids
        assert not target.allocator.check_invariants()
        assert any(s["site"] == "spec.draft" and s["fired"] > 0
                   for s in faults.snapshot())
    finally:
        faults.clear()
        sched.shutdown()


def test_build_spec_engine_knobs(tiny, monkeypatch):
    from localai_tpu.spec import build_spec_engine

    monkeypatch.setenv("LOCALAI_SPEC_GAMMA", "6")
    eng = build_spec_engine(_mk(tiny), drafter="ngram")
    assert eng.gamma == 6 and eng.drafter.name == "ngram"
    with pytest.raises(ValueError, match="draft_model"):
        build_spec_engine(_mk(tiny), drafter="model")
    with pytest.raises(ValueError, match="unknown drafter"):
        build_spec_engine(_mk(tiny), drafter="bogus")


def test_manager_spec_default_on_for_paged(tmp_path):
    """Config → engine: a plain paged model gets the n-gram lane by
    default; LOCALAI_SPEC=0 kills it."""
    import os

    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.models.manager import build_serving_model

    mcfg = ModelConfig.model_validate({
        "name": "spec-default",
        "model": "debug:tiny",
        "context_size": 128,
        "parameters": {"max_tokens": 16},
        "engine": {
            "max_slots": 2,
            "prefill_buckets": [32],
            "dtype": "float32",
            "kv_dtype": "float32",
            "kv_block_tokens": 16,
        },
    })
    app = AppConfig(model_path=str(tmp_path))
    old = os.environ.pop("LOCALAI_SPEC", None)
    try:
        sm = build_serving_model(mcfg, app)
        try:
            assert sm.scheduler.spec is not None
            assert sm.scheduler.spec.drafter.name == "ngram"
            assert sm.scheduler.spec.paged
        finally:
            sm.scheduler.shutdown()
        os.environ["LOCALAI_SPEC"] = "0"
        sm = build_serving_model(mcfg, app)
        try:
            assert sm.scheduler.spec is None
        finally:
            sm.scheduler.shutdown()
    finally:
        if old is None:
            os.environ.pop("LOCALAI_SPEC", None)
        else:
            os.environ["LOCALAI_SPEC"] = old
