"""Anomaly-triggered profiler capture (obs.profiler, ISSUE 15).

Everything runs against an injected clock + fake capture_fn — the
trigger / rate-limit / cooldown / single-flight state machine is the
unit under test, not jax.profiler (the CI telemetry smoke exercises the
real capture)."""

import json
import threading
import time

from localai_tpu.obs.flight import FlightRecorder
from localai_tpu.obs.metrics import Registry
from localai_tpu.obs.profiler import ProfileManager
from localai_tpu.obs.slo import SLOTracker
from localai_tpu.obs.trace import TraceStore
from localai_tpu.obs.watchdog import Watchdog


def make_pm(tmp_path, clock, caps, **kw):
    kw.setdefault("enabled", True)
    kw.setdefault("seconds", 0.01)
    kw.setdefault("max_per_hour", 4)
    kw.setdefault("cooldown_s", 10.0)
    return ProfileManager(
        out_dir=str(tmp_path), registry=kw.pop("registry", Registry()),
        clock=lambda: clock["now"],
        capture_fn=lambda path, s: caps.append(path), **kw)


def test_disabled_never_captures(tmp_path):
    caps = []
    pm = make_pm(tmp_path, {"now": 0.0}, caps, enabled=False)
    assert not pm.maybe_capture("stall", sync=True)
    assert caps == [] and pm.entries() == []


def test_capture_manifest_and_receipts(tmp_path):
    clock = {"now": 1000.0}
    caps = []
    reg = Registry()
    pm = make_pm(tmp_path, clock, caps, registry=reg)
    assert pm.maybe_capture("stall", trace_id="stall-abc",
                            reason="channel went dark", sync=True)
    assert len(caps) == 1
    entry = pm.entries()[0]
    assert entry["trigger"] == "stall"
    assert entry["trace_id"] == "stall-abc"
    assert entry["ok"] is True
    man = json.loads((tmp_path / "manifest.json").read_text())
    assert [p["id"] for p in man["profiles"]] == [entry["id"]]
    assert ('localai_profiles_captured_total{trigger="stall"} 1'
            in reg.render())


def test_cooldown_blocks_second_capture(tmp_path):
    clock = {"now": 1000.0}
    caps = []
    pm = make_pm(tmp_path, clock, caps, cooldown_s=30.0)
    assert pm.maybe_capture("stall", sync=True)
    clock["now"] += 5.0
    assert not pm.maybe_capture("stall", sync=True)
    assert pm.report()["skipped"]["cooldown"] == 1
    clock["now"] += 30.0  # cooldown over
    assert pm.maybe_capture("stall", sync=True)
    assert len(caps) == 2


def test_hourly_cap_and_refill(tmp_path):
    clock = {"now": 0.0}
    caps = []
    pm = make_pm(tmp_path, clock, caps, max_per_hour=2, cooldown_s=0.0)
    assert pm.maybe_capture("stall", sync=True)
    assert pm.maybe_capture("slo_shed", sync=True)
    assert not pm.maybe_capture("stall", sync=True)  # budget spent
    assert pm.report()["skipped"]["hourly_cap"] == 1
    clock["now"] += 3601.0  # the hour window slides
    assert pm.maybe_capture("stall", sync=True)
    assert len(caps) == 3


def test_single_flight_shared_lock(tmp_path):
    clock = {"now": 0.0}
    caps = []
    pm = make_pm(tmp_path, clock, caps, cooldown_s=0.0)
    # the manual-trace path (POST /backend/trace) holds the same lock
    assert pm.acquire_capture()
    try:
        assert not pm.maybe_capture("stall", sync=True)
        assert pm.report()["skipped"]["in_flight"] == 1
    finally:
        pm.release_capture()
    assert pm.maybe_capture("stall", sync=True)


def test_single_flight_concurrent_trigger(tmp_path):
    clock = {"now": 0.0}
    started = threading.Event()
    release = threading.Event()
    done = []

    def slow_capture(path, seconds):
        started.set()
        release.wait(5.0)
        done.append(path)

    reg = Registry()
    pm = ProfileManager(enabled=True, seconds=0.01, out_dir=str(tmp_path),
                        max_per_hour=10, cooldown_s=0.0, registry=reg,
                        clock=lambda: clock["now"],
                        capture_fn=slow_capture)
    assert pm.maybe_capture("stall")          # async capture holds the lock
    assert started.wait(5.0)
    assert not pm.maybe_capture("stall")      # second trigger mid-capture
    release.set()
    assert pm.wait_idle(5.0)
    assert len(done) == 1 and len(pm.entries()) == 1


def test_failed_capture_is_a_receipt_and_releases(tmp_path):
    clock = {"now": 0.0}

    def broken(path, seconds):
        raise RuntimeError("no backend")

    pm = ProfileManager(enabled=True, seconds=0.01, out_dir=str(tmp_path),
                        cooldown_s=0.0, registry=Registry(),
                        clock=lambda: clock["now"], capture_fn=broken)
    assert pm.maybe_capture("stall", sync=True)
    entry = pm.entries()[0]
    assert entry["ok"] is False and "no backend" in entry["error"]
    # the lock was released — the next trigger can run
    assert pm.acquire_capture()
    pm.release_capture()


def test_watchdog_stall_trigger(tmp_path):
    caps = []
    reg = Registry()
    store = TraceStore(8)
    wd = Watchdog(deadline=0.05, registry=reg, store=store,
                  poll_interval=0.01)
    pm = ProfileManager(enabled=True, seconds=0.01, out_dir=str(tmp_path),
                        cooldown_s=0.0, registry=reg,
                        capture_fn=lambda p, s: caps.append(p))
    pm.install(watchdog=wd, slo=SLOTracker(registry=reg, targets={}))
    wd.start()
    release = threading.Event()

    def hung():
        with wd.guard("pm-stall"):
            release.wait(5.0)

    t = threading.Thread(target=hung, daemon=True)
    t.start()
    deadline = time.monotonic() + 5.0
    while not pm.entries() and time.monotonic() < deadline:
        time.sleep(0.02)
    release.set()
    t.join(5.0)
    pm.wait_idle(5.0)
    wd.stop()
    pm.stop()
    entries = pm.entries()
    assert entries and entries[0]["trigger"] == "stall"
    # the capture is joined to the watchdog's forensic stall trace
    assert entries[0]["trace_id"].startswith("stall-")
    # recovery events never trigger
    assert all(e["trigger"] == "stall" for e in entries)


def test_shed_onset_trigger_fires_once(tmp_path):
    caps = []
    reg = Registry()
    clock = {"now": 1000.0}
    slo = SLOTracker(registry=reg, clock=lambda: clock["now"],
                     targets={"ttft_ms": 0.001}, burn_threshold=1.0,
                     recover_burn=1.0, min_events=3)
    pm = ProfileManager(enabled=True, seconds=0.01, out_dir=str(tmp_path),
                        cooldown_s=0.0, registry=reg,
                        clock=lambda: clock["now"],
                        capture_fn=lambda p, s: caps.append(p))
    pm.install(slo=slo, watchdog=Watchdog(deadline=60, registry=reg,
                                          store=TraceStore(4)))
    for _ in range(4):
        slo.observe("hot", ttft_ms=50.0)
    assert slo.should_shed("hot")
    assert slo.should_shed("hot")  # standing shed: onset already fired
    pm.wait_idle(5.0)
    pm.stop()
    sheds = [e for e in pm.entries() if e["trigger"] == "slo_shed"]
    assert len(sheds) == 1 and sheds[0]["model"] == "hot"


def test_regression_detector(tmp_path):
    caps = []
    pm = ProfileManager(enabled=True, seconds=0.01, out_dir=str(tmp_path),
                        cooldown_s=0.0, max_per_hour=100,
                        regression_ratio=2.0, registry=Registry(),
                        capture_fn=lambda p, s: caps.append(p))
    rec = FlightRecorder(256)

    def feed(n, ms):
        for _ in range(n):
            rec.record(program="decode_n", steps=8, dispatch_ms=ms,
                       occupancy=0.5, queue_depth=0, kv_utilization=0.1,
                       tokens=8)

    pm.watch_flight("m", rec)
    feed(64, 16.0)                      # 2 ms/step baseline
    assert pm.check_regressions() == []  # healthy: no trigger
    feed(32, 20.0)                      # 2.5 ms/step: below the 2x ratio
    assert pm.check_regressions() == []
    feed(32, 80.0)                      # 10 ms/step: 4-5x regression
    assert pm.check_regressions() == ["m"]
    pm.wait_idle(5.0)
    assert pm.entries()[0]["trigger"] == "step_p99_regression"
    assert pm.entries()[0]["model"] == "m"
    # the same records never re-trigger (wait for a fresh window)
    assert pm.check_regressions() == []
    # compile-bearing rows are excluded from both windows
    rec2 = FlightRecorder(256)
    pm.watch_flight("m2", rec2)
    for _ in range(80):
        rec2.record(program="decode_n", steps=8, dispatch_ms=16.0,
                    occupancy=0.5, queue_depth=0, kv_utilization=0.1,
                    tokens=8)
    for _ in range(32):
        rec2.record(program="decode_n", steps=8, dispatch_ms=400.0,
                    occupancy=0.5, queue_depth=0, kv_utilization=0.1,
                    tokens=8, compile=True)
    assert "m2" not in pm.check_regressions()


def test_watch_flight_weakref_drops_dead_ring(tmp_path):
    pm = make_pm(tmp_path, {"now": 0.0}, [])
    rec = FlightRecorder(8)
    pm.watch_flight("gone", rec)
    del rec
    import gc

    gc.collect()
    assert pm.check_regressions() == []
    with pm._lock:
        assert "gone" not in pm._flights


def test_install_idempotent_and_stop_deregisters(tmp_path):
    reg = Registry()
    wd = Watchdog(deadline=60, registry=reg, store=TraceStore(4))
    slo = SLOTracker(registry=reg, targets={})
    pm = make_pm(tmp_path, {"now": 0.0}, [], registry=reg)
    pm.install(watchdog=wd, slo=slo)
    pm.install(watchdog=wd, slo=slo)  # second install is a no-op
    assert len(wd._callbacks) == 1
    assert len(slo._shed_callbacks) == 1
    # stop() DEREGISTERS: an install after stop registers exactly once
    # (a leaked hook would fire two captures per stall)
    pm.stop()
    assert wd._callbacks == [] and slo._shed_callbacks == []
    pm.install(watchdog=wd, slo=slo)
    assert len(wd._callbacks) == 1 and len(slo._shed_callbacks) == 1
    pm.stop()
