"""gRPC worker tier: in-process servicer, spawned subprocess, pool/watchdog.

The reference's backend-worker contract (SURVEY.md §2.2/§2.5) exercised the
way its integration tests spawn the real local-store binary
(/root/reference/tests/integration/stores_test.go): a real server process,
a real client, over localhost gRPC.
"""

import os
import time

import numpy as np
import pytest

from localai_tpu.worker import WorkerClient, WorkerPool, Watchdog
from localai_tpu.worker import backend_pb2 as pb
from localai_tpu.worker.server import serve_worker

TINY_YAML = """\
name: tiny
model: "debug:tiny"
context_size: 96
engine:
  max_slots: 2
  prefill_buckets: [16]
  dtype: float32
  kv_dtype: float32
"""

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture(scope="module")
def worker():
    """In-process worker server + client (fast path for RPC semantics)."""
    server, port = serve_worker("127.0.0.1:0", block=False)
    client = WorkerClient(f"127.0.0.1:{port}")
    yield client
    client.close()
    server.stop(grace=None)


def test_health_before_load(worker):
    assert worker.health()
    st = worker.status()
    assert st.state == pb.StatusResponse.UNINITIALIZED


def test_predict_before_load_fails(worker):
    import grpc

    with pytest.raises(grpc.RpcError) as e:
        worker.predict(pb.PredictOptions(prompt="x", max_tokens=2))
    assert e.value.code() == grpc.StatusCode.FAILED_PRECONDITION


def test_load_predict_stream_embed(worker):
    res = worker.load_model(config_yaml=TINY_YAML)
    assert res.success, res.message

    rep = worker.predict(pb.PredictOptions(
        prompt="hello", max_tokens=6, temperature=0.0))
    assert rep.tokens == 6
    assert rep.prompt_tokens > 0
    assert rep.finish_reason in ("stop", "length")

    deltas = list(worker.predict_stream(pb.PredictOptions(
        prompt="hi", max_tokens=4, temperature=0.0)))
    assert deltas[-1].finish_reason in ("stop", "length")
    text = b"".join(d.message for d in deltas)
    assert isinstance(text, bytes)

    # determinism across RPC boundaries at temperature 0
    rep2 = worker.predict(pb.PredictOptions(
        prompt="hello", max_tokens=6, temperature=0.0))
    assert rep2.message == rep.message

    vec = worker.embedding("embed me")
    assert len(vec) == 64  # debug:tiny hidden size
    assert np.isfinite(vec).all()

    ids = worker.tokenize("abc")
    assert ids == [97, 98, 99]

    st = worker.status()
    assert st.state in (pb.StatusResponse.READY, pb.StatusResponse.BUSY)
    m = worker.metrics()
    assert m["num_slots"] == 2


def test_unimplemented_modalities(worker):
    import grpc

    with pytest.raises(grpc.RpcError) as e:
        worker.tts("say this")
    assert e.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_constrained_predict(worker):
    schema = '{"type": "object", "properties": {"a": {"type": "integer"}}}'
    rep = worker.predict(pb.PredictOptions(
        prompt="give json", max_tokens=24, temperature=0.0,
        constraint_schema=schema))
    text = rep.message.decode("utf-8", "replace")
    assert text.lstrip().startswith("{")


@pytest.mark.slow
def test_worker_pool_spawn_and_respawn(tmp_path):
    """Real subprocess: spawn, use, kill -9, auto-respawn (parity:
    loader.go:170-206 health-check-and-respawn)."""
    pool = WorkerPool()
    try:
        client = pool.get("w1", env=CPU_ENV)
        assert client.health()
        res = client.load_model(config_yaml=TINY_YAML)
        assert res.success, res.message
        rep = client.predict(pb.PredictOptions(
            prompt="x", max_tokens=2, temperature=0.0))
        assert rep.tokens == 2

        # hard-kill the process; next get() must respawn a fresh worker
        proc = pool._workers["w1"].proc
        proc.kill()
        proc.wait(10)
        client2 = pool.get("w1", env=CPU_ENV)
        assert client2.health()
        assert client2.address != client.address or True  # new port likely
    finally:
        pool.shutdown_all()


def test_watchdog_kills_idle():
    wd = Watchdog(busy_timeout=0, idle_timeout=0.2, interval=0.05)
    killed = []
    wd.register("addr:1", lambda: killed.append("addr:1"))
    wd.start()
    try:
        time.sleep(0.8)
        assert killed == ["addr:1"]
    finally:
        wd.stop()


def test_watchdog_busy_timeout():
    wd = Watchdog(busy_timeout=0.2, idle_timeout=0, interval=0.05)
    killed = []
    wd.register("addr:2", lambda: killed.append("addr:2"))
    wd.mark("addr:2")  # request in flight, never completes
    wd.start()
    try:
        time.sleep(0.8)
        assert killed == ["addr:2"]
    finally:
        wd.stop()


def test_external_backend_registration():
    server, port = serve_worker("127.0.0.1:0", block=False)
    pool = WorkerPool()
    try:
        client = pool.register_external("ext", f"127.0.0.1:{port}")
        assert pool.get("ext") is client
        assert client.health()
        assert "ext" in pool.names()
        assert pool.shutdown("ext")
    finally:
        pool.shutdown_all()
        server.stop(grace=None)
