"""int4 KV pool: pack/unpack, fused-dequant parity, engine wiring.

Tolerance note (pinned by the parity tests): symmetric per-(position,
head) int4 rounds to 15 levels, so the worst-case dequant error per
element is scale/2 = amax/14 — at unit-normal K/V that is ~0.22 absolute
on raw cache rows, and post-softmax attention outputs stay within ~0.2
absolute / a few percent relative of the f32 reference. The Pallas
interpret kernel must match the lax ref to ~1e-5 (same int4 math, only
the schedule differs); int4-vs-f32 carries the quantization error.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from localai_tpu import ops
from localai_tpu.engine import kvcache as kvc
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models.quant import (
    quantize_lastdim4,
    unpack_int4_lastdim,
)
from localai_tpu.models.registry import resolve_model


def test_int4_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 5, 16)), jnp.float32)
    packed, scale = quantize_lastdim4(x)
    assert packed.shape == (3, 5, 8) and packed.dtype == jnp.int8
    assert scale.shape == (3, 5)
    unpacked = unpack_int4_lastdim(packed)
    # the packed bytes decode to EXACTLY the quantized int values
    q = jnp.clip(jnp.round(x / scale[..., None]), -7, 7).astype(jnp.int8)
    np.testing.assert_array_equal(np.asarray(unpacked), np.asarray(q))
    # and the dequant error is bounded by half a quantization step
    deq = unpacked.astype(jnp.float32) * scale[..., None]
    err = np.abs(np.asarray(deq - x))
    assert err.max() <= float(np.asarray(scale).max()) / 2 + 1e-6


def test_int4_pack_odd_lastdim_rejected():
    # odd trailing dims cannot split into nibble halves
    with pytest.raises(Exception):
        quantize_lastdim4(jnp.ones((2, 15)))


def _paged_problem(rng, ctx):
    S, Hq, Hkv, hd, bt = 3, 4, 2, 16, 8
    mb = -(-ctx // bt)
    n = S * mb + 1
    q = jnp.asarray(rng.normal(size=(S, Hq, hd)), jnp.float32)
    kf = jnp.asarray(rng.normal(size=(n, Hkv, bt, hd)), jnp.float32)
    vf = jnp.asarray(rng.normal(size=(n, Hkv, bt, hd)), jnp.float32)
    tables = jnp.asarray(
        np.arange(1, n).reshape(S, mb), jnp.int32)
    positions = jnp.asarray(
        rng.integers(1, ctx - 1, size=(S,)), jnp.int32)
    return q, kf, vf, tables, positions


@pytest.mark.parametrize("ctx", [24, 112])  # two lengths (multi-block)
def test_paged_int4_vs_f32_parity_ref_and_interpret(ctx):
    rng = np.random.default_rng(1)
    q, kf, vf, tables, positions = _paged_problem(rng, ctx)
    ref_f32 = ops.paged_decode_attention_ref(
        q, kf, vf, tables, positions)
    kq, ks = quantize_lastdim4(kf)
    vq, vs = quantize_lastdim4(vf)
    # lax ref with the int4 pool: carries only the quantization error
    ref_i4 = ops.paged_decode_attention_ref(
        q, kq, vq, tables, positions, ks, vs)
    assert float(jnp.max(jnp.abs(ref_i4 - ref_f32))) < 0.25
    np.testing.assert_allclose(
        np.asarray(ref_i4), np.asarray(ref_f32), rtol=0.2, atol=0.2)
    # Pallas interpret vs the lax ref: identical int4 math, ~fp32 exact
    pal_i4 = ops.paged_decode_attention(
        q, kq, vq, tables, positions, ks, vs, interpret=True)
    np.testing.assert_allclose(
        np.asarray(pal_i4), np.asarray(ref_i4), rtol=1e-5, atol=1e-5)


def test_paged_int4_buffer_depths_identical():
    rng = np.random.default_rng(2)
    q, kf, vf, tables, positions = _paged_problem(rng, 64)
    kq, ks = quantize_lastdim4(kf)
    vq, vs = quantize_lastdim4(vf)
    d2 = ops.paged_decode_attention(
        q, kq, vq, tables, positions, ks, vs, interpret=True,
        num_buffers=2)
    d3 = ops.paged_decode_attention(
        q, kq, vq, tables, positions, ks, vs, interpret=True,
        num_buffers=3)
    np.testing.assert_array_equal(np.asarray(d2), np.asarray(d3))


def test_init_paged_cache_int4_layout():
    model = resolve_model("debug:tiny", dtype="float32")
    kv = kvc.init_paged_cache(model.cfg, 8, 16, "int4")
    hd = model.cfg.hd
    assert kv.k.dtype == jnp.int8
    assert kv.k.shape[-1] == hd // 2      # nibble-packed along head_dim
    assert kv.k_scale is not None
    assert kv.k_scale.shape == kv.k.shape[:-1]
    assert kv.quantized


def _greedy_tokens(kv_dtype, attn_impl="auto", steps=12):
    model = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(
        model.cfg, model.params, num_slots=2, max_ctx=128,
        prefill_buckets=[64], kv_dtype=kv_dtype, paged=True,
        kv_block_tokens=16, attn_impl=attn_impl)
    slot = runner.acquire_slot()
    toks = [runner.admit(slot, list(range(1, 40)), temperature=0.0)]
    for _ in range(steps // 4):
        toks.extend(np.asarray(runner.step_n(4))[:, slot].tolist())
    return toks


def test_int4_engine_greedy_parity():
    """End-to-end: int4 paged decode (lax ref AND Pallas interpret) emits
    the same greedy stream; on the well-conditioned debug model it also
    matches the f32 stream (KV quantization noise is far below the
    greedy argmax margins there — real models document drift instead)."""
    f32 = _greedy_tokens("float32")
    i4 = _greedy_tokens("int4")
    i4_pallas = _greedy_tokens("int4", attn_impl="pallas_interpret")
    assert i4 == i4_pallas
    assert i4 == f32


def test_int4_verify_write_spec_lane():
    """Speculative verify over an int4 pool: paged_verify_write scatters
    packed rows + scales; greedy verify parity vs f32 holds on the debug
    model."""
    from localai_tpu.spec import NGramDrafter, SpecEngine

    def run(kv_dtype):
        model = resolve_model("debug:tiny", dtype="float32")
        runner = ModelRunner(
            model.cfg, model.params, num_slots=2, max_ctx=256,
            prefill_buckets=[64], kv_dtype=kv_dtype, paged=True,
            kv_block_tokens=16)
        eng = SpecEngine(runner, NGramDrafter(2, gamma=4))
        slot = eng.acquire_slot()
        out = [eng.admit(slot, list(b"abc abc abc abc abc"),
                         temperature=0.0)]
        for _ in range(30):
            if eng.total_emitted >= 24:
                break
            rows = eng.step_spec_async()
            if rows is None:
                tok = int(runner.step()[slot])
                eng.drafter.observe(slot, [tok])
                out.append(tok)
                continue
            arr = np.asarray(rows)
            eng.observe_window(arr)
            out.extend(int(t) for t in arr[:, slot] if t >= 0)
        assert not runner.allocator.check_invariants()
        return out[:24]

    assert run("int4") == run("float32")


def test_int4_snapshot_export_roundtrip():
    """export_prefix/load_prefix round-trips the packed int4 rows: a
    fresh runner loads the snapshot and resumes with identical greedy
    output."""
    model = resolve_model("debug:tiny", dtype="float32")
    prompt = list(range(1, 50))

    def mk():
        return ModelRunner(
            model.cfg, model.params, num_slots=2, max_ctx=128,
            prefill_buckets=[64], kv_dtype="int4", paged=True,
            kv_block_tokens=16)

    a = mk()
    slot = a.acquire_slot()
    first = a.admit(slot, prompt, temperature=0.0)
    snap = a.export_prefix(slot, len(prompt))
    assert snap["k"].shape[-1] == model.cfg.hd // 2  # stays packed
    cont_a = [first] + [int(a.step()[slot]) for _ in range(6)]

    b = mk()
    slot_b = b.acquire_slot()
    assert b.load_prefix(slot_b, snap, len(prompt))
    first_b = b.admit(slot_b, prompt + [first],
                      resident=prompt, temperature=0.0)
    assert b.last_prefill_path == "paged_resume"
    cont_b = [first_b] + [int(b.step()[slot_b]) for _ in range(5)]
    # stream a decoded [first, x1, x2...]; stream b prefilled prompt+first
    # then decodes [x1, x2...]
    assert cont_a[1:] == cont_b[:6]


def test_int4_requires_paged():
    model = resolve_model("debug:tiny", dtype="float32")
    with pytest.raises(ValueError, match="int4"):
        ModelRunner(model.cfg, model.params, num_slots=2, max_ctx=128,
                    prefill_buckets=[64], kv_dtype="int4", paged=False)


def test_select_paged_attn_impl_int4_gate():
    """Hardware gate pin: the nibble-packed pool needs hd%256==0 for the
    Pallas kernel on real TPU (packed lane dim = hd/2); interpret mode
    and the xla fallback are unaffected."""
    impl, interpret, why = ops.select_paged_attn_impl(
        "pallas", num_heads=32, num_kv_heads=8, head_dim=128,
        block_tokens=64, kv_dtype="int4", backend="tpu")
    assert impl == "xla" and "int4" in why
    impl, interpret, why = ops.select_paged_attn_impl(
        "pallas", num_heads=32, num_kv_heads=8, head_dim=256,
        block_tokens=64, kv_dtype="int4", backend="tpu")
    assert impl == "pallas" and not interpret and why == ""
    impl, interpret, _ = ops.select_paged_attn_impl(
        "pallas_interpret", num_heads=32, num_kv_heads=8, head_dim=128,
        block_tokens=64, kv_dtype="int4", backend="tpu")
    assert impl == "pallas" and interpret
