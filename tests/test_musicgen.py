"""MusicGen-class generative audio: numerical parity against the torch
reference implementations (transformers MusicgenForCausalLM + EncodecModel)
on tiny random checkpoints — the same strategy test_vits.py uses. Parity
target: /root/reference/backend/python/transformers-musicgen/backend.py."""

import numpy as np
import pytest

def jnp_asarray(x):
    import jax.numpy as jnp
    return jnp.asarray(x)


torch = pytest.importorskip("torch")

from localai_tpu.audio.musicgen import (  # noqa: E402
    MusicGenerator,
    MusicgenConfig,
    encodec_decode,
    encodec_params_from_torch,
    generate_codes,
    lm_forward,
    lm_params_from_torch,
)

CFG = MusicgenConfig(
    vocab_size=64, num_codebooks=2, hidden_size=32, num_layers=2,
    num_heads=2, ffn_dim=64, codebook_dim=8, num_filters=4,
    upsampling_ratios=(4, 2), num_residual_layers=1, num_lstm_layers=1,
    kernel_size=3, last_kernel_size=3, residual_kernel_size=3,
)


@pytest.fixture(scope="module")
def torch_lm():
    from transformers import MusicgenDecoderConfig, MusicgenForCausalLM

    torch.manual_seed(0)
    cfg = MusicgenDecoderConfig(
        vocab_size=CFG.vocab_size, hidden_size=CFG.hidden_size,
        num_hidden_layers=CFG.num_layers, num_attention_heads=CFG.num_heads,
        ffn_dim=CFG.ffn_dim, num_codebooks=CFG.num_codebooks,
        max_position_embeddings=256, dropout=0.0, attention_dropout=0.0,
        activation_dropout=0.0, activation_function="gelu",
    )
    return MusicgenForCausalLM(cfg).eval()


@pytest.fixture(scope="module")
def torch_encodec():
    from transformers import EncodecConfig, EncodecModel

    torch.manual_seed(1)
    cfg = EncodecConfig(
        sampling_rate=16000, audio_channels=1, num_filters=CFG.num_filters,
        num_residual_layers=CFG.num_residual_layers,
        upsampling_ratios=list(CFG.upsampling_ratios),
        codebook_size=CFG.vocab_size, codebook_dim=CFG.codebook_dim,
        hidden_size=CFG.codebook_dim, num_lstm_layers=CFG.num_lstm_layers,
        kernel_size=CFG.kernel_size, last_kernel_size=CFG.last_kernel_size,
        residual_kernel_size=CFG.residual_kernel_size,
        dilation_growth_rate=CFG.dilation_growth_rate,
        compress=CFG.compress, use_causal_conv=True, norm_type="weight_norm",
    )
    return EncodecModel(cfg).eval()


def test_lm_forward_matches_torch(torch_lm):
    state = {k: v.detach().numpy() for k, v in torch_lm.state_dict().items()}
    params = lm_params_from_torch(state, CFG)

    rng = np.random.default_rng(0)
    T, K = 9, CFG.num_codebooks
    codes = rng.integers(0, CFG.vocab_size, (K, T))
    memory = rng.normal(size=(5, CFG.hidden_size)).astype(np.float32)

    with torch.no_grad():
        out = torch_lm(
            input_ids=torch.tensor(codes.reshape(1 * K, T)),
            encoder_hidden_states=torch.tensor(memory)[None],
        ).logits  # [1, K, T, V]
    ref = out[0].numpy() if out.ndim == 4 else out.numpy()

    got = np.asarray(lm_forward(CFG, params, codes, memory))
    np.testing.assert_allclose(got, ref.reshape(K, T, -1),
                               rtol=2e-4, atol=2e-4)


def test_encodec_decode_matches_torch(torch_encodec):
    state = {k: v.detach().numpy()
             for k, v in torch_encodec.state_dict().items()}
    dparams = encodec_params_from_torch(state, CFG)

    rng = np.random.default_rng(2)
    T = 17
    codes = rng.integers(0, CFG.vocab_size, (CFG.num_codebooks, T))
    with torch.no_grad():
        ref = torch_encodec.decode(
            torch.tensor(codes)[None, None],  # [1, 1, K, T]
            audio_scales=[None],
        ).audio_values[0, 0].numpy()

    got = np.asarray(encodec_decode(CFG, dparams, codes))
    n = min(len(got), len(ref))
    np.testing.assert_allclose(got[:n], ref[:n], rtol=2e-4, atol=2e-4)


def test_generate_codes_respects_delay_and_shape():
    gen = MusicGenerator(CFG, seed=3)
    mem, mask = gen.text_memory("drum loop")
    codes = np.asarray(generate_codes(
        CFG, gen.lm, mem, __import__("jax").random.key(0), frames=16,
        temperature=0.7, memory_mask=mask,
    ))
    assert codes.shape == (CFG.num_codebooks, 16)
    assert (codes >= 0).all() and (codes < CFG.vocab_size).all()


def test_greedy_generation_consistent_with_teacher_forcing():
    """Greedy scan generation must agree with re-scoring the emitted codes
    through the teacher-forced forward (KV-cache correctness check)."""
    import jax

    gen = MusicGenerator(CFG, seed=4)
    mem, mask = gen.text_memory("check")
    frames = 8
    codes = np.asarray(generate_codes(
        CFG, gen.lm, mem, jax.random.key(0), frames=frames, temperature=0.0,
        memory_mask=mask,
    ))
    K = CFG.num_codebooks
    T_total = frames + K
    # rebuild the delayed input sequence and re-score it in one pass
    seq = np.full((K, T_total), CFG.pad_id, np.int64)
    for k in range(K):
        seq[k, k + 1: k + 1 + frames] = codes[k]
    mem_real = np.asarray(mem)[np.asarray(mask)]
    logits = np.asarray(lm_forward(CFG, gen.lm, seq.astype(np.int32),
                                   jnp_asarray(mem_real)))
    for k in range(K):
        for f in range(frames):
            t = f + k  # step that sampled codebook k frame f
            assert int(logits[k, t].argmax()) == codes[k, f]


def test_generator_end_to_end_audio():
    gen = MusicGenerator(seed=5)
    audio = gen.generate("warm pad", duration=0.3, temperature=0.8)
    assert audio.dtype == np.float32
    n_expected = int(0.3 * gen.cfg.frame_rate) * int(
        np.prod(gen.cfg.upsampling_ratios))
    assert abs(len(audio) - n_expected) <= int(np.prod(
        gen.cfg.upsampling_ratios))
    assert np.abs(audio).max() <= 0.71
    # model output, not a deterministic sine bank: different prompts differ
    other = gen.generate("harsh noise", duration=0.3, temperature=0.8)
    assert not np.allclose(audio[:1000], other[:1000])
