"""Stall watchdog + device health + JSON logging (obs introspection).

The unit half of the round-6 obs surfaces: watchdog trip/recover semantics
with the thread-stack forensic span, the timeout-guarded device probe, the
live-array HBM census, the compiled-program cost catalog, and the JSON log
formatter's contextvar trace-id binding. The HTTP halves (/debug/devices,
/debug/programs, stall spans at /v1/traces) live in test_api.py.
"""

import json
import logging
import threading
import time

import pytest

from localai_tpu.obs import Registry, TraceStore, Watchdog
from localai_tpu.obs import compile as obs_compile
from localai_tpu.obs import device as obs_device
from localai_tpu.obs import logging as obs_logging

# -- watchdog ---------------------------------------------------------------


@pytest.fixture()
def wd_parts():
    reg, store = Registry(), TraceStore()
    wd = Watchdog(deadline=0.08, registry=reg, store=store,
                  poll_interval=0.02)
    yield wd, reg, store
    wd.stop()


def test_idle_channel_never_stalls(wd_parts):
    wd, reg, _store = wd_parts
    wd.pulse("idle")                      # known but nothing armed
    time.sleep(0.12)
    assert wd.check() == []
    assert not wd.stalled()


def test_armed_silence_trips_and_recovery_clears(wd_parts):
    wd, reg, store = wd_parts
    events = []
    wd.on_stall(events.append)
    wd.arm("engine")
    time.sleep(0.12)                      # silence past the deadline
    trips = wd.check()
    assert [e.kind for e in trips] == ["stall"]
    assert wd.stalled("engine")
    text = reg.render()
    assert 'localai_engine_stalled{channel="engine"} 1' in text
    assert 'localai_stalls_total{channel="engine"} 1' in text
    # forensic span: kind="stall", one thread event per live thread, each
    # carrying a formatted stack
    stall = [t for t in store.recent() if t.kind == "stall"]
    assert stall, "no forensic trace recorded"
    spans = stall[0].spans()
    assert spans and all("stack" in s.attrs for s in spans)
    assert any("test_armed_silence" in s.attrs["stack"] for s in spans), (
        "the dump must contain this very test frame")
    assert stall[0].trace_id == trips[0].trace_id
    # progress clears the stall (gauge → 0) and fires the recovery event
    wd.pulse("engine")
    assert not wd.stalled("engine")
    assert 'localai_engine_stalled{channel="engine"} 0' in reg.render()
    assert [e.kind for e in events] == ["stall", "recovered"]
    # steady state afterwards: no re-trip without new silence
    assert wd.check() == []
    wd.disarm("engine")


def test_guard_context_manager_and_background_thread(wd_parts):
    wd, reg, store = wd_parts
    tripped = threading.Event()
    wd.on_stall(lambda e: e.kind == "stall" and tripped.set())
    wd.start()
    release = threading.Event()

    def hung_dispatch():
        with wd.guard("device"):
            release.wait(5.0)             # the simulated dead tunnel

    t = threading.Thread(target=hung_dispatch, daemon=True)
    t.start()
    assert tripped.wait(2.0), "background checker never tripped"
    assert wd.stalled("device")
    status = wd.status()["device"]
    assert status["armed"] == 1 and status["stalled"]
    release.set()                         # tunnel comes back
    t.join(2.0)
    deadline = time.monotonic() + 2.0
    while wd.stalled("device") and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not wd.stalled("device")


def test_stall_dump_includes_flight_snapshot(wd_parts):
    """The round-7 forensic upgrade: a registered flight-ring context
    provider attaches the preceding engine timeline to every stall dump,
    and a broken provider degrades to an error marker instead of killing
    the dump."""
    from localai_tpu.obs import FlightRecorder

    wd, _reg, store = wd_parts
    fl = FlightRecorder(8)
    fl.record(program="decode_n", steps=4, dispatch_ms=8.0, occupancy=0.5,
              queue_depth=2, kv_utilization=0.25, tokens=16)
    fl.record(program="decode_n", steps=4, dispatch_ms=12.0, occupancy=0.5,
              queue_depth=3, kv_utilization=0.3, tokens=16)
    wd.add_context("flight:engine", lambda: {
        "records": fl.snapshot(limit=32), **fl.percentiles()})
    wd.add_context("broken", lambda: 1 / 0)
    try:
        wd.arm("engine")
        time.sleep(0.12)
        trips = wd.check()
        assert [e.kind for e in trips] == ["stall"]
        stall = [t for t in store.recent() if t.kind == "stall"][0]
        ctx = {s.attrs.get("source"): s for s in stall.spans()
               if s.name == "context"}
        assert set(ctx) == {"flight:engine", "broken"}
        flight = ctx["flight:engine"].attrs
        assert [r["queue_depth"] for r in flight["records"]] == [2, 3]
        assert flight["step_ms_p50"] == pytest.approx(2.5)
        assert flight["samples"] == 2
        assert ctx["broken"].attrs["error"] == "provider failed"
        # the stack half of the dump still stands next to the contexts
        assert any(s.name == "thread" for s in stall.spans())
    finally:
        wd.disarm("engine")
        wd.remove_context("flight:engine")
        wd.remove_context("broken")


def test_remove_context_stops_attaching(wd_parts):
    wd, _reg, store = wd_parts
    wd.add_context("gone", lambda: {"x": 1})
    wd.remove_context("gone")
    wd.arm("engine")
    time.sleep(0.12)
    wd.check()
    wd.disarm("engine")
    stall = [t for t in store.recent() if t.kind == "stall"][0]
    assert not [s for s in stall.spans() if s.name == "context"]


def test_check_refreshes_progress_age_gauge(wd_parts):
    wd, reg, _store = wd_parts
    wd.arm("rpc")
    time.sleep(0.03)
    wd.check()
    assert 'localai_last_progress_age_seconds{channel="rpc"}' in reg.render()
    wd.disarm("rpc")


# -- device probe + census --------------------------------------------------


def test_probe_device_ok_sets_gauges():
    reg = Registry()
    res = obs_device.probe_device(timeout=30.0, registry=reg)
    assert res.ok and res.seconds > 0
    text = reg.render()
    assert "localai_device_ok 1" in text
    assert "localai_device_probe_seconds" in text


def test_probe_device_timeout_path():
    reg = Registry()
    res = obs_device.probe_device(
        timeout=0.1, registry=reg, fn=lambda: time.sleep(10))
    assert not res.ok
    assert "timeout" in res.error
    assert "localai_device_ok 0" in reg.render()


def test_probe_device_error_path():
    def boom():
        raise RuntimeError("tunnel reset")

    res = obs_device.probe_device(timeout=5.0, registry=Registry(), fn=boom)
    assert not res.ok and "tunnel reset" in res.error


def test_hbm_census_attributes_categories():
    import jax.numpy as jnp

    reg = Registry()
    kv = jnp.zeros((8, 16), jnp.float32)
    w = jnp.ones((32,), jnp.float32)
    out = obs_device.hbm_census(
        {"kv_cache": [kv], "weights": [w]}, registry=reg)
    assert out["by_category"]["kv_cache"] >= kv.nbytes
    assert out["by_category"]["weights"] >= w.nbytes
    assert out["arrays"] >= 2
    assert 'localai_hbm_live_bytes{category="kv_cache"}' in reg.render()


def test_known_arrays_from_runner_shape():
    class FakeCache:
        def stacked(self):
            import jax.numpy as jnp

            return (jnp.zeros((2, 2)), jnp.zeros((2, 2)))

    class FakeRunner:
        kv = FakeCache()
        params = {"w": __import__("jax.numpy", fromlist=["zeros"]).zeros(4)}

    known = obs_device.known_arrays([FakeRunner()])
    assert len(known["kv_cache"]) == 2 and len(known["weights"]) == 1


def test_roofline_env_override(monkeypatch):
    monkeypatch.setenv("LOCALAI_PEAK_GBPS", "123.5")
    monkeypatch.setenv("LOCALAI_PEAK_TFLOPS", "9")
    rl = obs_device.roofline()
    assert rl["peak_gbps"] == 123.5 and rl["source"] == "env"


def test_roofline_assumed_on_cpu(monkeypatch):
    monkeypatch.delenv("LOCALAI_PEAK_GBPS", raising=False)
    monkeypatch.delenv("LOCALAI_PEAK_TFLOPS", raising=False)
    rl = obs_device.roofline()
    assert rl["peak_gbps"] > 0 and rl["source"] in ("assumed", "table")


# -- program cost catalog ---------------------------------------------------


def test_catalog_reports_cost_and_fractions():
    import jax
    import jax.numpy as jnp

    reg = Registry()
    watched = obs_compile.watch(
        jax.jit(lambda x, *, n: (x @ x) * n, static_argnames=("n",)),
        "toyprog", registry=reg)
    x = jnp.ones((16, 16), jnp.float32)
    watched(x, n=2)
    watched(x, n=2)
    obs_compile.note_latency("toyprog", 0.004, steps=2)
    rep = obs_compile.CATALOG.report(
        roofline={"peak_gbps": 100.0, "peak_tflops": 1.0})
    rows = [r for r in rep if r["program"] == "toyprog"]
    assert rows, "watched program missing from the catalog"
    row = rows[0]
    assert row["dispatches"] == 2
    assert row["flops"] > 0 and row["bytes_accessed"] > 0
    assert row["dispatch_seconds_ema"] == pytest.approx(0.004)
    assert row["achieved_gbps"] > 0
    assert 0 <= row["bandwidth_fraction"] <= 1


def test_catalog_survives_dead_program():
    import jax
    import jax.numpy as jnp

    watched = obs_compile.watch(jax.jit(lambda x: x + 1), "ephemeral",
                                registry=Registry())
    watched(jnp.ones(4))
    del watched
    import gc

    gc.collect()
    rep = obs_compile.CATALOG.report(harvest=True)
    rows = [r for r in rep if r["program"] == "ephemeral"]
    # either collected (error noted) or still cached — never a crash
    assert rows and (rows[0].get("cost_error") or "flops" in rows[0])


# -- JSON logging -----------------------------------------------------------


def _one_record(logger_name="t", msg="hello", exc=False, **extra):
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    logger = logging.getLogger(logger_name)
    logger.propagate = False
    h = Capture()
    h.setFormatter(obs_logging.JsonFormatter())
    logger.addHandler(h)
    logger.setLevel(logging.INFO)
    try:
        if exc:
            try:
                raise ValueError("kaboom")
            except ValueError:
                logger.exception(msg, extra=extra)
        else:
            logger.info(msg, extra=extra)
    finally:
        logger.removeHandler(h)
    return json.loads(records[0])


def test_json_formatter_basic_shape():
    out = _one_record(msg="engine up", component="scheduler")
    assert out["message"] == "engine up"
    assert out["level"] == "info"
    assert out["logger"] == "t"
    assert out["component"] == "scheduler"   # extra= passthrough
    assert out["ts"].endswith("Z")
    assert "trace_id" not in out             # nothing bound


def test_json_formatter_binds_and_unbinds_trace_id():
    token = obs_logging.bind_trace_id("trace-json-1")
    try:
        assert obs_logging.current_trace_id() == "trace-json-1"
        assert _one_record()["trace_id"] == "trace-json-1"
    finally:
        obs_logging.unbind_trace_id(token)
    assert obs_logging.current_trace_id() == ""
    assert "trace_id" not in _one_record()


def test_json_formatter_exceptions_and_threads():
    out = _one_record(msg="boom", exc=True)
    assert "kaboom" in out["exc"]
    # contextvars do NOT leak across threads: a fresh thread logs without
    # the caller's trace id
    token = obs_logging.bind_trace_id("outer")
    try:
        seen = {}

        def run():
            seen["tid"] = obs_logging.current_trace_id()

        t = threading.Thread(target=run)
        t.start()
        t.join()
        assert seen["tid"] == ""
    finally:
        obs_logging.unbind_trace_id(token)


def test_setup_configures_root(capsys):
    import io

    buf = io.StringIO()
    obs_logging.setup("json", logging.INFO, stream=buf)
    try:
        logging.getLogger("setup-test").info("structured")
        line = buf.getvalue().strip().splitlines()[-1]
        assert json.loads(line)["message"] == "structured"
    finally:
        obs_logging.setup("text", logging.WARNING)


def test_context_executor_propagates_trace_id():
    """run_in_executor does not copy contextvars; the API's ContextExecutor
    must, so executor-side log lines keep the request trace id."""
    from concurrent.futures import ThreadPoolExecutor

    from localai_tpu.api.server import ContextExecutor

    token = obs_logging.bind_trace_id("ctx-exec-1")
    try:
        with ContextExecutor(max_workers=1) as pool:
            assert pool.submit(
                obs_logging.current_trace_id).result(5) == "ctx-exec-1"
        with ThreadPoolExecutor(max_workers=1) as plain:
            assert plain.submit(
                obs_logging.current_trace_id).result(5) == ""
    finally:
        obs_logging.unbind_trace_id(token)


def test_trip_recovery_race_never_latches_gauge(wd_parts):
    """A recovery racing the trip emission (progress lands between check()
    marking the channel stalled and the gauge write) must still leave
    engine_stalled at 0 — the emission re-reads current state."""
    wd, reg, _store = wd_parts
    wd.arm("race")
    time.sleep(0.12)
    # replicate the racy interleaving deterministically: mark stalled (what
    # check() does under the lock) ...
    with wd._lock:
        wd._channels["race"].stalled = True
    wd.pulse("race")            # ... recovery emits FIRST (gauge -> 0)
    wd._emit_stall("race", 1.0)  # ... then the trip's late emission
    assert 'localai_engine_stalled{channel="race"} 0' in reg.render()
    wd.disarm("race")


def test_catalog_same_program_name_two_watchers_do_not_collide():
    """Two runners watch same-named programs whose top-level args are
    pytrees (identical shape keys); entries must not overwrite."""
    import jax
    import jax.numpy as jnp

    reg = Registry()
    f1 = obs_compile.watch(jax.jit(lambda d: d["x"] + 1), "dupprog",
                           registry=reg)
    f2 = obs_compile.watch(jax.jit(lambda d: d["x"] * 2), "dupprog",
                           registry=reg)
    arg = {"x": jnp.ones(4)}
    f1(arg)
    f1(arg)
    f2(arg)
    rows = [r for r in obs_compile.CATALOG.report(harvest=False)
            if r["program"] == "dupprog"]
    assert len(rows) == 2, rows
    assert sorted(r["dispatches"] for r in rows) == [1, 2]
    assert rows[0]["instance"] != rows[1]["instance"]


def test_probe_single_flight_does_not_leak_threads_per_call():
    """Against a wedged device, repeated default probes must join the ONE
    in-flight probe thread instead of parking a new thread per call."""
    import localai_tpu.obs.device as dev

    block = threading.Event()
    counts = {"n": 0}

    def wedged():
        counts["n"] += 1
        block.wait(30.0)

    # install the wedged probe as the DEFAULT (fn=None path uses the
    # latch); restore afterwards
    real = dev._default_probe
    dev._default_probe = wedged
    try:
        with dev._probe_lock:
            prior = dict(dev._probe_inflight)
            dev._probe_inflight.update(thread=None, box=None)
        r1 = dev.probe_device(timeout=0.1, registry=Registry())
        r2 = dev.probe_device(timeout=0.1, registry=Registry())
        assert not r1.ok and not r2.ok
        assert counts["n"] == 1, "second probe spawned a new thread"
    finally:
        block.set()
        time.sleep(0.05)
        dev._default_probe = real
        with dev._probe_lock:
            dev._probe_inflight.update(**prior)
