"""Config-system unit tests (parity model:
/root/reference/core/config/backend_config_test.go — pure-logic YAML tests)."""

import textwrap

from localai_tpu.config import ConfigLoader, ModelConfig, Usecase, load_config_file


def write(p, text):
    p.write_text(textwrap.dedent(text))
    return p


def test_load_single_config(tmp_models_dir):
    f = write(
        tmp_models_dir / "gpt4.yaml",
        """
        name: gpt-4
        backend: jax-llm
        model: meta-llama/Llama-3-8B-Instruct
        context_size: 8192
        parameters:
          temperature: 0.2
          top_k: 50
        stopwords: ["<|eot_id|>"]
        """,
    )
    cfg = load_config_file(f)
    assert cfg.name == "gpt-4"
    assert cfg.parameters.temperature == 0.2
    assert cfg.parameters.top_k == 50
    assert cfg.context_size == 8192
    assert cfg.stopwords == ["<|eot_id|>"]


def test_defaults_applied(tmp_models_dir):
    cfg = ModelConfig(name="m", model="x")
    cfg.set_defaults(context_size=2048)
    assert cfg.parameters.temperature == 0.9
    assert cfg.parameters.top_p == 0.95
    assert cfg.parameters.max_tokens == 2048
    assert cfg.context_size == 2048


def test_dir_scan_names_and_skip(tmp_models_dir):
    write(tmp_models_dir / "a.yaml", "model: modelA\n")
    write(tmp_models_dir / "b.yaml", "name: bee\nmodel: modelB\n")
    write(tmp_models_dir / "notes.md", "not a config\n")
    (tmp_models_dir / "loose.gguf").write_bytes(b"\x00")
    (tmp_models_dir / "plainmodel").write_bytes(b"\x00")
    cl = ConfigLoader(tmp_models_dir)
    cl.load_from_path()
    assert cl.names() == ["a", "bee"]
    assert cl.loose_files() == ["loose.gguf", "plainmodel"]


def test_reference_yaml_compat(tmp_models_dir):
    """A reference-style YAML (aio/cpu/text-to-text.yaml shape) must parse;
    CUDA-era knobs are accepted and mapped."""
    f = write(
        tmp_models_dir / "ref.yaml",
        """
        name: gpt-4
        mmap: true
        f16: true
        gpu_layers: 90
        parameters:
          model: Hermes-2-Pro-Llama-3-8B.Q4_K_M.gguf
          temperature: 0.7
        template:
          chat: chat-template
          use_tokenizer_template: false
        function:
          disable_no_action: true
        stopwords:
        - <|im_end|>
        """,
    )
    cfg = load_config_file(f)
    assert cfg.name == "gpt-4"
    assert cfg.parameters.temperature == 0.7
    assert cfg.template.chat == "chat-template"
    assert cfg.function.disable_no_action is True
    assert cfg.stopwords == ["<|im_end|>"]


def test_usecase_guessing():
    llm = ModelConfig(name="x", backend="jax-llm")
    assert llm.has_usecase(Usecase.CHAT)
    assert not llm.has_usecase(Usecase.IMAGE)
    emb = ModelConfig(name="e", backend="jax-llm", embeddings=True)
    assert emb.has_usecase(Usecase.EMBEDDINGS)
    whisper = ModelConfig(name="w", backend="whisper")
    assert whisper.has_usecase(Usecase.TRANSCRIPT)
    explicit = ModelConfig(name="k", known_usecases=[Usecase.CHAT])
    assert explicit.has_usecase(Usecase.CHAT)
    assert not explicit.has_usecase(Usecase.COMPLETION)


def test_request_merge():
    cfg = ModelConfig(name="m")
    cfg.set_defaults()
    merged = cfg.parameters.merged_with({"temperature": 0.1, "max_tokens": 5})
    assert merged.temperature == 0.1
    assert merged.max_tokens == 5
    assert merged.top_p == 0.95  # config default survives


def test_tp_compat_mapping():
    cfg = ModelConfig(name="m", tensor_parallel_size=4)
    assert cfg.sharding.tensor_parallel_size == 4


def test_path_traversal_rejected():
    cfg = ModelConfig(name="evil", model="../../etc/passwd")
    assert not cfg.validate_config()
