"""Ring attention / sequence-parallel prefill vs the single-device trunk."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from localai_tpu.engine import kvcache as kvc
from localai_tpu.models import llama as mdl
from localai_tpu.models.llama import LlamaConfig
from localai_tpu.models.registry import resolve_model
from localai_tpu.parallel.mesh import MeshPlan, build_mesh
from localai_tpu.parallel.ring import ring_attention, sp_prefill_forward
from localai_tpu.utils.jaxcompat import shard_map


@pytest.fixture(scope="module")
def seq_mesh():
    return build_mesh(MeshPlan(seq=8))


def _reference_forward(model_cfg, params, tokens, length):
    """Single-device full-attention trunk; returns (hidden, (k, v) stacks)."""
    T = tokens.shape[0]
    rope = mdl.rope_table(model_cfg, T)
    mask = kvc.prefill_mask(model_cfg, T, length)

    def write(layer_kv, k, v):
        # pass the fresh chunk through (head-major for _grouped_attn) and
        # stack the token-major chunk as the per-layer output
        return (k[0], v[0]), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3)

    hidden, kvs = mdl.forward(
        model_cfg, params, tokens[None],
        jnp.arange(T, dtype=jnp.int32)[None], write, None, mask, rope,
    )
    return hidden, kvs


@pytest.mark.parametrize("length", [64, 37])
def test_sp_prefill_matches_single_device(seq_mesh, length):
    model = resolve_model("debug:tiny", dtype="float32")
    T = 64
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, model.cfg.vocab_size, T), jnp.int32)

    hidden, (k, v) = sp_prefill_forward(
        model.cfg, model.params, tokens, jnp.int32(length), seq_mesh,
        mdl.rope_table(model.cfg, T),
    )
    ref, (ref_k, ref_v) = _reference_forward(
        model.cfg, model.params, tokens, jnp.int32(length)
    )

    assert hidden.shape == ref.shape
    np.testing.assert_allclose(
        np.asarray(hidden)[0, :length], np.asarray(ref)[0, :length],
        rtol=2e-4, atol=2e-4,
    )
    # K/V values (not just shapes) must match — they feed the slot cache.
    # Positions < length see identical inputs in both runs.
    np.testing.assert_allclose(np.asarray(k)[:, :length],
                               np.asarray(ref_k)[:, :length],
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(v)[:, :length],
                               np.asarray(ref_v)[:, :length],
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("window", [None, 12])
def test_ring_attention_matches_full(seq_mesh, window):
    """The bare primitive against unsharded masked attention."""
    cfg = LlamaConfig(num_heads=4, num_kv_heads=2, head_dim=8,
                      hidden_size=32, sliding_window=window)
    T, n = 32, 8
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(T, 4, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(T, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(T, 2, 8)), jnp.float32)
    length = jnp.int32(29)

    ref = mdl._grouped_attn(cfg, q[None], k.transpose(1, 0, 2)[None],
                            v.transpose(1, 0, 2)[None],
                            kvc.prefill_mask(cfg, T, length))[0]

    def local(q_c, k_c, v_c):
        return ring_attention(q_c, k_c, v_c, length, n_chunks=n,
                              sliding_window=window)

    out = shard_map(
        local, mesh=seq_mesh,
        in_specs=(P("seq"), P("seq"), P("seq")),
        out_specs=P("seq"),
        check_vma=False,
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out)[:29], np.asarray(ref)[:29],
                               rtol=2e-5, atol=2e-5)


def test_sp_prefill_sliding_window_model(seq_mesh):
    """A sliding-window config must produce window-masked hidden states."""
    base = resolve_model("debug:tiny", dtype="float32")
    cfg = dataclasses.replace(base.cfg, sliding_window=8)
    T, length = 64, 64
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, T), jnp.int32)

    hidden, _ = sp_prefill_forward(
        cfg, base.params, tokens, jnp.int32(length), seq_mesh,
        mdl.rope_table(cfg, T),
    )
    ref, _ = _reference_forward(cfg, base.params, tokens, jnp.int32(length))
    np.testing.assert_allclose(np.asarray(hidden)[0], np.asarray(ref)[0],
                               rtol=2e-4, atol=2e-4)


def test_sp_prefill_tp_composition():
    """TP×SP (VERDICT r4 #4): weights 'model'-sharded (Megatron layout),
    activations 'seq'-sharded, ring attention per local head group — must
    match the single-device trunk."""
    from localai_tpu.parallel import sharding as shd

    mesh = build_mesh(MeshPlan(seq=4, model=2))
    model = resolve_model("debug:tiny", dtype="float32")
    sp = shd.shard_params(model.params, model.cfg, mesh)
    T, length = 64, 57
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(rng.integers(0, model.cfg.vocab_size, T), jnp.int32)

    hidden, (k, v) = sp_prefill_forward(
        model.cfg, sp, tokens, jnp.int32(length), mesh,
        mdl.rope_table(model.cfg, T),
    )
    ref, (ref_k, ref_v) = _reference_forward(
        model.cfg, model.params, tokens, jnp.int32(length)
    )
    np.testing.assert_allclose(
        np.asarray(hidden)[0, :length], np.asarray(ref)[0, :length],
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_allclose(np.asarray(k)[:, :length],
                               np.asarray(ref_k)[:, :length],
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(v)[:, :length],
                               np.asarray(ref_v)[:, :length],
                               rtol=2e-4, atol=2e-4)


def test_sp_prefill_tp_requires_divisible_heads():
    mesh = build_mesh(MeshPlan(seq=4, model=2))
    cfg = LlamaConfig(num_heads=3, num_kv_heads=3, head_dim=8,
                      hidden_size=24, vocab_size=64, num_layers=1,
                      intermediate_size=32, dtype="float32")
    params = mdl.init_params(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="divisible"):
        sp_prefill_forward(cfg, params, jnp.zeros(16, jnp.int32),
                           jnp.int32(16), mesh, mdl.rope_table(cfg, 16))
