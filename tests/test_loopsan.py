"""tools.loopsan: the runtime event-loop stall sanitizer.

A 200 ms blocking callback is caught with its owner and a mid-stall
stack; a clean concurrent async workload stays clean; the patching
contract (install/uninstall restores ``Handle._run``); reset/snapshot
semantics; and the ``--demo`` CLI exits nonzero on its provoked stall —
the same contract shape as test_racecheck.py for the lock harness.
"""

import asyncio
import asyncio.events
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.loopsan import _REAL_HANDLE_RUN, LoopSanitizer  # noqa: E402


def test_blocking_callback_caught():
    san = LoopSanitizer(threshold_ms=50.0)

    async def blocking_handler():
        time.sleep(0.2)     # the bug class: sync sleep on the loop

    with san:
        asyncio.run(blocking_handler())
    stalls = san.stalls()
    assert len(stalls) == 1
    s = stalls[0]
    assert s.duration_ms >= 150.0
    assert "blocking_handler" in s.label
    assert s.label.startswith("task ")
    report = san.report()
    assert "1 stall(s)" in report
    assert "blocking_handler" in report


def test_clean_async_workload_is_clean():
    san = LoopSanitizer(threshold_ms=50.0)

    async def worker(i):
        for _ in range(3):
            await asyncio.sleep(0.005 * (i % 3))

    async def main():
        await asyncio.gather(*(worker(i) for i in range(6)))

    with san:
        asyncio.run(main())
    assert san.stalls() == []
    # the patch observed the workload — a zero count would mean the
    # sanitizer watched nothing and "clean" proves nothing
    assert san.callbacks_seen > 0
    assert "0 stall(s)" in san.report()


def test_mid_stall_stack_names_the_blocking_line():
    # the sampler snapshots the thread DURING the stall: the stack must
    # point into this file's blocker, not just name the handle
    san = LoopSanitizer(threshold_ms=50.0, poll_ms=2.0)

    async def blocker():
        time.sleep(0.15)

    with san:
        asyncio.run(blocker())
    (s,) = san.stalls()
    assert any("test_loopsan" in line for line in s.stack)


def test_call_soon_callback_is_labeled_and_caught():
    san = LoopSanitizer(threshold_ms=50.0)

    async def main():
        loop = asyncio.get_running_loop()
        loop.call_soon(time.sleep, 0.12)
        await asyncio.sleep(0.2)

    with san:
        asyncio.run(main())
    (s,) = san.stalls()
    assert s.label == "callback sleep"


def test_install_uninstall_restores_dispatch():
    san = LoopSanitizer()
    assert asyncio.events.Handle._run is _REAL_HANDLE_RUN
    san.install()
    try:
        assert asyncio.events.Handle._run is not _REAL_HANDLE_RUN
        assert san._sampler is not None and san._sampler.is_alive()
    finally:
        san.uninstall()
    assert asyncio.events.Handle._run is _REAL_HANDLE_RUN
    assert san._sampler is None
    # loops still work after uninstall
    asyncio.run(asyncio.sleep(0))


def test_reset_keeps_patch_but_drops_history():
    san = LoopSanitizer(threshold_ms=50.0)

    async def blocker():
        time.sleep(0.1)

    with san:
        asyncio.run(blocker())
        assert len(san.stalls()) == 1
        san.reset()
        assert san.stalls() == [] and san.callbacks_seen == 0
        # still installed: traffic after the reset is observed
        asyncio.run(asyncio.sleep(0))
        assert san.callbacks_seen > 0
    snap = san.snapshot()
    assert snap["threshold_ms"] == 50.0
    assert snap["stalls"] == []


def test_snapshot_carries_stall_details():
    san = LoopSanitizer(threshold_ms=50.0)

    async def blocker():
        time.sleep(0.12)

    with san:
        asyncio.run(blocker())
    snap = san.snapshot()
    assert len(snap["stalls"]) == 1
    entry = snap["stalls"][0]
    assert entry["duration_ms"] >= 100.0
    assert "blocker" in entry["label"]
    assert isinstance(entry["stack"], list) and entry["stack"]


def test_demo_cli_exits_nonzero_on_its_stall():
    res = subprocess.run(
        [sys.executable, "tools/loopsan.py", "--demo"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
        env={"PATH": "/usr/bin:/bin"},
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "blocking_handler" in res.stdout
    assert "clean_handler" not in res.stdout
