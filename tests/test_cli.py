"""CLI tests: version/models/tokenize inline, plus a real subprocess boot
of `run` with an HTTP round-trip (the reference's e2e black-box pattern,
SURVEY.md §4)."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from localai_tpu.cli.main import main

TINY_YAML = """\
name: tiny
model: "debug:tiny"
context_size: 96
parameters:
  max_tokens: 8
engine:
  max_slots: 2
  prefill_buckets: [16, 32]
  dtype: float32
  kv_dtype: float32
"""


@pytest.fixture()
def models_dir(tmp_path):
    d = tmp_path / "models"
    d.mkdir()
    (d / "tiny.yaml").write_text(TINY_YAML)
    return d


def test_version(capsys):
    assert main(["version"]) == 0
    from localai_tpu.version import __version__

    assert capsys.readouterr().out.strip() == __version__


def test_models_list(models_dir, capsys):
    assert main(["models", "list", "--models-path", str(models_dir)]) == 0
    assert capsys.readouterr().out.split() == ["tiny"]


def test_tokenize(models_dir, capsys):
    assert main([
        "tokenize", "hi", "--model", "tiny",
        "--models-path", str(models_dir),
    ]) == 0
    assert json.loads(capsys.readouterr().out) == [104, 105]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_run_server_subprocess(models_dir, tmp_path):
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=".")
    logf = open(tmp_path / "server.log", "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "localai_tpu.cli.main", "run",
         "--address", "127.0.0.1", "--port", str(port),
         "--models-path", str(models_dir), "--platform", "cpu"],
        stdout=logf, stderr=logf, env=env, cwd="/root/repo",
    )
    try:
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 60
        while True:
            try:
                with urllib.request.urlopen(f"{base}/readyz", timeout=2) as r:
                    assert json.load(r)["status"] == "ok"
                    break
            except Exception:
                if time.monotonic() > deadline:
                    logf.flush()
                    raise AssertionError(
                        "server did not come up:\n"
                        + (tmp_path / "server.log").read_text()[-3000:]
                    )
                time.sleep(0.5)
        req = urllib.request.Request(
            f"{base}/v1/chat/completions",
            data=json.dumps({
                "model": "tiny",
                "messages": [{"role": "user", "content": "hello"}],
                "max_tokens": 4,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            body = json.load(r)
        assert body["choices"][0]["message"]["role"] == "assistant"
    finally:
        proc.terminate()
        proc.wait(10)
        logf.close()


def test_tts_writes_wav(tmp_path, capsys):
    out = tmp_path / "speech.wav"
    assert main(["tts", "hello", "world", "-o", str(out)]) == 0
    data = out.read_bytes()
    assert data[:4] == b"RIFF" and data[8:12] == b"WAVE"
    assert len(data) > 1000


def test_sound_generation_writes_wav(tmp_path):
    out = tmp_path / "snd.wav"
    assert main(["sound-generation", "rain on a roof",
                 "-d", "0.5", "-o", str(out)]) == 0
    assert out.read_bytes()[:4] == b"RIFF"


def test_transcript_debug_model(tmp_path, capsys):
    from localai_tpu.audio import write_wav
    import numpy as np

    wav = tmp_path / "in.wav"
    wav.write_bytes(write_wav(np.zeros(16000, np.float32)))
    d = tmp_path / "models"
    d.mkdir()
    (d / "w.yaml").write_text(
        "name: w\nmodel: 'debug:whisper-tiny'\n"
        "known_usecases: [transcript]\n"
    )
    assert main(["transcript", str(wav), "--models-path", str(d)]) == 0
    # debug whisper produces deterministic (possibly empty) text; the
    # command must print the transcript line without error
    assert capsys.readouterr().out is not None


def test_util_checkpoint_info(tmp_path, capsys):
    import numpy as np
    from safetensors.numpy import save_file

    d = tmp_path / "ck"
    d.mkdir()
    save_file({"w": np.zeros((4, 8), np.float32),
               "b": np.zeros((8,), np.float32)},
              d / "model.safetensors")
    (d / "config.json").write_text('{"model_type": "test"}')
    assert main(["util", "checkpoint-info", str(d), "--header"]) == 0
    out = capsys.readouterr().out
    assert "w\tF32\t[4, 8]" in out
    assert "total parameters: 40" in out
    assert "model_type" in out


def test_util_scan_flags_pickle(tmp_path, capsys):
    d = tmp_path / "models"
    (d / "sub").mkdir(parents=True)
    (d / "ok.safetensors").write_bytes(b"")
    (d / "sub" / "evil.bin").write_bytes(b"")
    assert main(["util", "scan", "--models-path", str(d)]) == 1
    out = capsys.readouterr().out
    assert "evil.bin" in out and "1 finding(s)" in out


def test_util_usecase_heuristic(models_dir, capsys):
    assert main(["util", "usecase-heuristic", "tiny",
                 "--models-path", str(models_dir)]) == 0
    out = capsys.readouterr().out.split()
    assert "chat" in out and "completion" in out
