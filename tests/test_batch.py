"""Offline batch subsystem tests: the unified file registry, the durable
job store's state machine, and the executor's end-to-end drain through
the scheduler's background lane — on the tiny debug model (no downloads;
SURVEY.md §4 fixture strategy)."""

import json
import time
from types import SimpleNamespace

import pytest

from localai_tpu.batch.executor import BatchExecutor, parse_line
from localai_tpu.batch.store import BatchStore, FileRegistry
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import Scheduler
from localai_tpu.models.registry import resolve_model
from localai_tpu.obs.metrics import Registry
from localai_tpu.obs.slo import SLOTracker
from localai_tpu.obs.trace import TraceStore
from localai_tpu.utils.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def sched():
    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(
        tiny.cfg, tiny.params, num_slots=4, max_ctx=96,
        prefill_buckets=[16, 32], kv_dtype="float32",
    )
    s = Scheduler(runner, ByteTokenizer())
    yield s
    s.shutdown()


@pytest.fixture()
def upload_dir(tmp_path):
    d = tmp_path / "uploads"
    d.mkdir()
    return d


def make_serving(sched, tmp_path):
    """The (ServingModel, ModelConfig) pair the executor resolves per
    model name — the shape the API tier's AppState provides."""
    from localai_tpu.templates.cache import TemplateCache

    sm = SimpleNamespace(
        tokenizer=ByteTokenizer(),
        scheduler=sched,
        templates=TemplateCache(str(tmp_path)),
    )
    mcfg = ModelConfig(name="tiny")
    return lambda name: (sm, mcfg)


def write_input(registry, n=5, model="tiny", endpoint="/v1/chat/completions",
                max_tokens=4, extra_lines=()):
    lines = []
    for i in range(n):
        if endpoint == "/v1/chat/completions":
            body = {"model": model, "max_tokens": max_tokens,
                    "temperature": 0.0,
                    "messages": [{"role": "user", "content": f"line {i}"}]}
        else:
            body = {"model": model, "max_tokens": max_tokens,
                    "temperature": 0.0, "prompt": f"line {i}"}
        lines.append(json.dumps({
            "custom_id": f"req-{i}", "method": "POST", "url": endpoint,
            "body": body,
        }))
    lines.extend(extra_lines)
    return registry.register_bytes(
        "input.jsonl", ("\n".join(lines) + "\n").encode(), "batch"
    )


def wait_for(pred, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# FileRegistry (the unified /v1/files store)


def test_file_registry_purpose_and_roundtrip(upload_dir):
    reg = FileRegistry(upload_dir)
    f = reg.register_bytes("a.jsonl", b"hello", "batch")
    g = reg.register_bytes("b.txt", b"notes", "assistants")
    assert f["purpose"] == "batch" and f["bytes"] == 5
    assert {x["id"] for x in reg.list()} == {f["id"], g["id"]}
    assert [x["id"] for x in reg.list("batch")] == [f["id"]]
    assert reg.content_path(f["id"]).read_bytes() == b"hello"
    # duplicate filename refused; traversal-guarded basename only
    with pytest.raises(ValueError):
        reg.register_bytes("a.jsonl", b"x", "batch")
    evil = reg.register_bytes("../../evil.txt", b"x", "batch")
    assert evil["filename"] == "evil.txt"
    assert reg.delete(f["id"]) is True
    assert reg.get(f["id"]) is None
    assert not (upload_dir / "a.jsonl").exists()


def test_file_registry_ids_survive_reload(upload_dir):
    reg = FileRegistry(upload_dir)
    f1 = reg.register_bytes("one.txt", b"1", "assistants")
    reg2 = FileRegistry(upload_dir)  # reload from disk
    f2 = reg2.register_bytes("two.txt", b"2", "assistants")
    assert f2["id"] != f1["id"]
    assert reg2.get(f1["id"])["filename"] == "one.txt"


def test_assistant_store_shares_registry(upload_dir, tmp_path):
    from localai_tpu.api.assistants import AssistantStore

    reg = FileRegistry(upload_dir)
    f = reg.register_bytes("shared.txt", b"x", "assistants")
    store = AssistantStore(tmp_path / "configs", upload_dir, registry=reg)
    assert store.file(f["id"]) == f
    assert store.files is reg.files


# ---------------------------------------------------------------------------
# BatchStore state machine + durability


def test_batch_store_transitions(upload_dir):
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    job = store.create(endpoint="/v1/chat/completions",
                       input_file_id="file-1")
    assert job["status"] == "validating"
    with pytest.raises(ValueError):
        store.transition(job["id"], "completed")  # must pass in_progress
    store.transition(job["id"], "in_progress")
    assert store.get(job["id"])["in_progress_at"] is not None
    store.transition(job["id"], "completed")
    with pytest.raises(ValueError):
        store.transition(job["id"], "in_progress")  # terminal is terminal
    # terminal cancel is a no-op, unknown is None
    assert store.cancel(job["id"])["status"] == "completed"
    assert store.cancel("batch_999") is None


def test_batch_store_reload_and_done_set(upload_dir):
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    job = store.create(endpoint="/v1/completions", input_file_id="file-1")
    store.transition(job["id"], "in_progress")
    store.append_line(store.output_path(job),
                      {"custom_id": "req-0", "response": {}})
    store.append_line(store.error_path(job),
                      {"custom_id": "req-1", "error": {}})
    # reload from disk: state + the durable done-set survive
    store2 = BatchStore(upload_dir, reg)
    j2 = store2.get(job["id"])
    assert j2["status"] == "in_progress"
    assert store2.done_custom_ids(j2) == {"req-0", "req-1"}
    j3 = store2.create(endpoint="/v1/completions", input_file_id="file-1")
    assert j3["id"] != job["id"]  # id counter continues past persisted


def test_batch_store_expiry(upload_dir):
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg, expiry_h=1.0)
    job = store.create(endpoint="/v1/completions", input_file_id="f")
    assert store.expire_due(now=time.time() + 3599) == []
    expired = store.expire_due(now=time.time() + 3700)
    assert [j["id"] for j in expired] == [job["id"]]
    assert store.get(job["id"])["status"] == "expired"
    assert store.runnable() is None


# ---------------------------------------------------------------------------
# line validation


def test_parse_line_errors():
    seen = set()
    ok = json.dumps({"custom_id": "a", "url": "/v1/completions",
                     "body": {"model": "m", "prompt": "x"}})
    cid, req, _body = parse_line(ok, 1, "/v1/completions", seen)
    assert cid == "a" and req.model == "m" and req.stream is False
    seen.add("a")
    for bad, msg in [
        ("not json", "invalid JSON"),
        (json.dumps(["list"]), "not a JSON object"),
        (json.dumps({"body": {}}), "custom_id is required"),
        (json.dumps({"custom_id": "a", "body": {}}), "duplicate"),
        (json.dumps({"custom_id": "b", "method": "GET", "body": {}}),
         "method must be POST"),
        (json.dumps({"custom_id": "b", "url": "/v1/nope", "body": {}}),
         "does not match"),
        (json.dumps({"custom_id": "b", "url": "/v1/completions",
                     "body": []}), "body must be"),
        (json.dumps({"custom_id": "b", "url": "/v1/completions",
                     "body": {"prompt": ["a", "b"]}}), "list prompts"),
    ]:
        with pytest.raises(ValueError, match=msg):
            parse_line(bad, 2, "/v1/completions", seen)


# ---------------------------------------------------------------------------
# executor end-to-end (real engine, background lane)


def run_executor(store, sched, tmp_path, **kw):
    ex = BatchExecutor(
        store, make_serving(sched, tmp_path),
        poll_s=0.02,
        registry=kw.pop("registry", Registry()),
        slo=kw.pop("slo", SLOTracker(registry=Registry(), targets={})),
        trace_store=kw.pop("trace_store", TraceStore()),
        **kw,
    )
    ex.start()
    return ex


def test_batch_job_runs_to_completed(sched, upload_dir, tmp_path):
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    f = write_input(reg, n=5)
    job = store.create(endpoint="/v1/chat/completions",
                       input_file_id=f["id"])
    metrics = Registry()
    traces = TraceStore()
    ex = run_executor(store, sched, tmp_path, registry=metrics,
                      trace_store=traces)
    try:
        assert wait_for(
            lambda: store.get(job["id"])["status"] == "completed")
    finally:
        ex.stop()
    job = store.get(job["id"])
    assert job["request_counts"] == {"total": 5, "completed": 5,
                                     "failed": 0}
    # per-line output file registered for download (purpose=batch_output)
    out_file = reg.get(job["output_file_id"])
    assert out_file["purpose"] == "batch_output"
    records = [json.loads(l) for l in
               reg.content_path(out_file["id"]).read_text().splitlines()]
    assert {r["custom_id"] for r in records} == {f"req-{i}"
                                                for i in range(5)}
    for r in records:
        body = r["response"]["body"]
        assert r["response"]["status_code"] == 200
        assert body["object"] == "chat.completion"
        assert body["choices"][0]["message"]["role"] == "assistant"
        assert body["usage"]["prompt_tokens"] > 0
    # metrics: lines counted, jobs gauge at the terminal state
    text = metrics.render()
    assert 'localai_batch_lines_total{result="completed"} 5' in text
    assert 'localai_batch_jobs{state="completed"} 1' in text
    assert "localai_batch_lane_paused 0" in text
    # per-job trace recorded with validate/run spans
    tr = [t for t in traces.recent(limit=10, kind="batch")]
    assert tr and tr[0].attrs["status"] == "completed"
    assert {s.name for s in tr[0].spans()} >= {"validate", "run"}


def test_batch_invalid_lines_become_error_records(sched, upload_dir,
                                                  tmp_path):
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    f = write_input(reg, n=2, endpoint="/v1/completions", extra_lines=[
        "not json at all",
        json.dumps({"method": "POST", "url": "/v1/completions",
                    "body": {"prompt": "no custom id"}}),
        json.dumps({"custom_id": "wrong-url", "method": "POST",
                    "url": "/v1/chat/completions",
                    "body": {"prompt": "mismatched endpoint"}}),
    ])
    job = store.create(endpoint="/v1/completions", input_file_id=f["id"])
    ex = run_executor(store, sched, tmp_path)
    try:
        assert wait_for(
            lambda: store.get(job["id"])["status"] == "completed")
    finally:
        ex.stop()
    job = store.get(job["id"])
    assert job["request_counts"] == {"total": 5, "completed": 2,
                                     "failed": 3}
    errs = [json.loads(l) for l in
            store.error_path(job).read_text().splitlines()]
    assert len(errs) == 3
    assert all(e["error"]["code"] == "400" for e in errs)
    # a line that declared a custom_id keeps it in its error record, so
    # clients can reconcile failures against the ids they submitted
    assert "wrong-url" in {e["custom_id"] for e in errs}
    err_file = reg.get(job["error_file_id"])
    assert err_file["purpose"] == "batch_output"


def test_batch_all_invalid_fails(sched, upload_dir, tmp_path):
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    f = reg.register_bytes("bad.jsonl", b"nope\nstill nope\n", "batch")
    job = store.create(endpoint="/v1/completions", input_file_id=f["id"])
    ex = run_executor(store, sched, tmp_path)
    try:
        assert wait_for(lambda: store.get(job["id"])["status"] == "failed")
    finally:
        ex.stop()
    assert store.get(job["id"])["request_counts"]["failed"] == 2


def test_batch_crash_resume_continues_from_durable_lines(sched, upload_dir,
                                                         tmp_path):
    """Kill mid-job, reload, job continues from the last durable line:
    lines already in the output file are NOT re-run, the rest complete,
    and no custom_id appears twice."""
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    f = write_input(reg, n=5)
    job = store.create(endpoint="/v1/chat/completions",
                       input_file_id=f["id"])
    # simulate the pre-crash session: the job went in_progress and two
    # lines landed durably in the output file before the process died
    store.transition(job["id"], "in_progress")
    for i in range(2):
        store.append_line(store.output_path(job), {
            "id": f"pre-crash-{i}", "custom_id": f"req-{i}",
            "response": {"status_code": 200, "body": {}}, "error": None,
        })
    # fresh store (reload from disk) + fresh executor = restarted process
    store2 = BatchStore(upload_dir, FileRegistry(upload_dir))
    assert store2.get(job["id"])["status"] == "in_progress"
    ex = run_executor(store2, sched, tmp_path)
    try:
        assert wait_for(
            lambda: store2.get(job["id"])["status"] == "completed")
    finally:
        ex.stop()
    job = store2.get(job["id"])
    records = [json.loads(l) for l in
               store2.output_path(job).read_text().splitlines()]
    cids = [r["custom_id"] for r in records]
    assert sorted(cids) == [f"req-{i}" for i in range(5)]
    assert len(set(cids)) == 5  # no duplicates: resume skipped done lines
    # the pre-crash records were preserved verbatim, not overwritten
    assert [r["id"] for r in records[:2]] == ["pre-crash-0", "pre-crash-1"]
    assert job["request_counts"]["completed"] == 5


def test_batch_cancel_stops_job(sched, upload_dir, tmp_path):
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    f = write_input(reg, n=50, max_tokens=64)
    job = store.create(endpoint="/v1/chat/completions",
                       input_file_id=f["id"])
    ex = run_executor(store, sched, tmp_path, concurrency=1)
    try:
        assert wait_for(
            lambda: store.get(job["id"])["status"] == "in_progress")
        store.cancel(job["id"])
        assert wait_for(lambda: not ex.store.runnable())
    finally:
        ex.stop()
    job = store.get(job["id"])
    assert job["status"] == "cancelled"
    assert job["cancelled_at"] is not None
    # whatever completed before the cancel stays durable; nothing more runs
    done = len(store.done_custom_ids(job))
    time.sleep(0.3)
    assert len(store.done_custom_ids(job)) == done


def test_file_registry_rejects_reserved_names(upload_dir):
    reg = FileRegistry(upload_dir)
    with pytest.raises(ValueError, match="reserved"):
        reg.register_bytes("uploadedFiles.json", b"[]", "batch")
    with pytest.raises(ValueError, match="reserved"):
        reg.register_bytes("batch_jobs", b"x", "batch")


def test_upload_cannot_poison_batch_output(sched, upload_dir, tmp_path):
    """Job artifacts live under batch_jobs/ where the basename-only
    upload path cannot reach: a crafted upload named like a job's output
    file must not pre-seed the done-set or become the downloadable
    result."""
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    # forged "output" claiming every line already done
    forged = "\n".join(json.dumps({"custom_id": f"req-{i}",
                                   "response": {"status_code": 200,
                                                "body": {"forged": True}}})
                       for i in range(3))
    reg.register_bytes("batch_1_output.jsonl", forged.encode(), "batch")
    f = write_input(reg, n=3)
    job = store.create(endpoint="/v1/chat/completions",
                       input_file_id=f["id"])
    assert job["id"] == "batch_1"
    ex = run_executor(store, sched, tmp_path)
    try:
        assert wait_for(
            lambda: store.get(job["id"])["status"] == "completed")
    finally:
        ex.stop()
    job = store.get(job["id"])
    assert job["request_counts"]["completed"] == 3  # really ran
    recs = [json.loads(l) for l in
            reg.content_path(job["output_file_id"]).read_text().splitlines()]
    assert all("forged" not in r["response"]["body"] for r in recs)


def test_synthetic_error_id_does_not_shadow_real_custom_id(sched,
                                                           upload_dir,
                                                           tmp_path):
    """An invalid line's made-up line-N id must not block a REAL
    custom_id that spells 'line-N' — and error line numbers refer to
    PHYSICAL file lines (blank lines count)."""
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    content = "\n".join([
        "",                # physical line 1: blank
        "not json",        # physical line 2: invalid → synthetic line-2
        json.dumps({"custom_id": "line-2", "method": "POST",
                    "url": "/v1/completions",
                    "body": {"model": "tiny", "max_tokens": 4,
                             "temperature": 0.0, "prompt": "really run"}}),
    ])
    f = reg.register_bytes("shadow.jsonl", (content + "\n").encode(),
                           "batch")
    job = store.create(endpoint="/v1/completions", input_file_id=f["id"])
    ex = run_executor(store, sched, tmp_path)
    try:
        assert wait_for(
            lambda: store.get(job["id"])["status"] == "completed")
    finally:
        ex.stop()
    job = store.get(job["id"])
    assert job["request_counts"] == {"total": 2, "completed": 1,
                                     "failed": 1}
    outs = [json.loads(l) for l in
            store.output_path(job).read_text().splitlines()]
    assert [r["custom_id"] for r in outs] == ["line-2"]  # really ran
    errs = [json.loads(l) for l in
            store.error_path(job).read_text().splitlines()]
    assert errs[0]["custom_id"] == "line-2"  # physical line number
    assert errs[0]["synthetic_id"] is True


def test_batch_duplicate_custom_id_runs_first_occurrence(sched, upload_dir,
                                                         tmp_path):
    """A duplicate custom_id fails only the DUPLICATE line: its error
    record carries a synthetic id, so the valid first occurrence is not
    poisoned out of the pending set via the done-set."""
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    f = write_input(reg, n=2, endpoint="/v1/completions", extra_lines=[
        json.dumps({"custom_id": "req-0", "method": "POST",
                    "url": "/v1/completions",
                    "body": {"prompt": "duplicate id"}}),
    ])
    job = store.create(endpoint="/v1/completions", input_file_id=f["id"])
    ex = run_executor(store, sched, tmp_path)
    try:
        assert wait_for(
            lambda: store.get(job["id"])["status"] == "completed")
    finally:
        ex.stop()
    job = store.get(job["id"])
    assert job["request_counts"] == {"total": 3, "completed": 2,
                                     "failed": 1}
    outs = [json.loads(l) for l in
            store.output_path(job).read_text().splitlines()]
    # the valid req-0 line really ran (exactly once)
    assert sorted(r["custom_id"] for r in outs) == ["req-0", "req-1"]
    errs = [json.loads(l) for l in
            store.error_path(job).read_text().splitlines()]
    assert len(errs) == 1 and errs[0]["custom_id"] != "req-0"


def test_batch_line_deadline_records_timeout(sched, upload_dir, tmp_path):
    """A line that outlives the per-line deadline is cancelled and
    recorded as a 504 error — a wedged generation must not pin the
    executor (and the rest of the job still completes)."""
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    lines = [json.dumps({
        "custom_id": "slow", "method": "POST",
        "url": "/v1/chat/completions",
        "body": {"model": "tiny", "max_tokens": 2048, "temperature": 0.0,
                 "ignore_eos": True,
                 "messages": [{"role": "user", "content": "decode forever"}]},
    })]
    f = reg.register_bytes("slow.jsonl", ("\n".join(lines) + "\n").encode(),
                           "batch")
    job = store.create(endpoint="/v1/chat/completions",
                       input_file_id=f["id"])
    # far below one generation's wall time (≥ tens of ms for ~80 tokens)
    ex = run_executor(store, sched, tmp_path, deadline_s=0.01)
    try:
        assert wait_for(
            lambda: store.get(job["id"])["status"] == "completed",
            timeout=30)
    finally:
        ex.stop()
    job = store.get(job["id"])
    assert job["request_counts"] == {"total": 1, "completed": 0,
                                     "failed": 1}
    errs = [json.loads(l) for l in
            store.error_path(job).read_text().splitlines()]
    assert errs[0]["custom_id"] == "slow"
    assert errs[0]["error"]["code"] == "504"
    assert wait_for(lambda: not sched.busy, timeout=30)  # slot freed


# ---------------------------------------------------------------------------
# SLO isolation: batch-lane requests never count against interactive SLOs


def test_background_requests_never_become_slo_events():
    """The lane's core invariant, telemetry side: a batch-lane completion
    must not become an SLO event or land in the interactive TTFT/TPOT/
    queue-wait histograms — its queue wait is unbounded BY DESIGN, and
    counting it would let an offline job shed the interactive traffic
    the lane exists to protect."""
    from localai_tpu.engine.scheduler import GenHandle, GenRequest
    from localai_tpu.obs.engine import EngineTelemetry

    reg = Registry()
    tracker = SLOTracker(registry=reg, targets={"ttft_ms": 1.0})
    tel = EngineTelemetry(model="m", registry=reg, store=TraceStore(),
                          slo=tracker)

    def finish_one(priority):
        h = GenHandle(GenRequest(prompt=[1, 2], priority=priority), 0)
        tr = tel.queued(h)
        tel.admitted(tr, slot=0, queue_wait=99.0,
                     background=priority > 0)
        tel.prefill_done(tr)
        h._emit("x", 5)
        h._emit("y", 6)
        tel.finished(tr, h, "stop")
        h._finish("stop")

    from localai_tpu.engine.scheduler import PRIORITY_BATCH as PB

    finish_one(PB)
    assert tracker.windows("m")["1m"]["count"] == 0  # no SLO event
    text = reg.render()
    assert 'localai_ttft_seconds_count{model="m"}' not in text
    assert 'localai_queue_wait_seconds_count{model="m"}' not in text
    assert 'localai_requests_total{finish_reason="stop",model="m"} 1' \
        in text  # still counted as a finished request
    # an interactive completion DOES feed both
    finish_one(0)
    assert tracker.windows("m")["1m"]["count"] == 1
    text = reg.render()
    assert 'localai_ttft_seconds_count{model="m"} 1' in text
    assert 'localai_queue_wait_seconds_count{model="m"} 1' in text


# ---------------------------------------------------------------------------
# configurable request deadline (satellite)


def test_request_deadline_resolution(monkeypatch):
    from localai_tpu.api import inference as inf
    from localai_tpu.config.app_config import AppConfig

    monkeypatch.delenv("LOCALAI_REQUEST_DEADLINE_S", raising=False)
    assert inf.request_deadline_s() == 600.0
    assert inf.request_deadline_s(AppConfig(request_deadline_s=5.0)) == 5.0
    monkeypatch.setenv("LOCALAI_REQUEST_DEADLINE_S", "7.5")
    assert inf.request_deadline_s() == 7.5
    # zero/garbage falls back to the default, not "no deadline"
    monkeypatch.setenv("LOCALAI_REQUEST_DEADLINE_S", "0")
    assert inf.request_deadline_s() == 600.0


def test_run_choices_deadline_cancels_generation(sched, tmp_path):
    """Deadline expiry must CANCEL the GenHandle so the decode slot frees
    instead of generating into the void to max_tokens."""
    from localai_tpu.api import inference as inf
    from localai_tpu.api import schema as sc

    sm, cfg = make_serving(sched, tmp_path)("tiny")
    req = sc.OpenAIRequest(model="tiny", prompt="hold", max_tokens=2048,
                           temperature=0.0, ignore_eos=True)
    cfg = inf.merge_request(cfg, req)
    # timeout far below even one prefill dispatch, so the generation
    # cannot finish first on a fast machine (warm compiled shapes)
    with pytest.raises(TimeoutError):
        inf.run_choices(sm, cfg, req, "hold this slot", timeout=0.001)
    # the cancelled request leaves its slot on the next engine step —
    # far sooner than the 2048-token run it was asked for
    assert wait_for(lambda: not sched.busy, timeout=30)


def test_batch_lane_pauses_under_shedding_and_recovers(sched, upload_dir,
                                                       tmp_path):
    """Forced shed→recover cycle: while the SLO observatory sheds the
    model, the batch lane pauses ENTIRELY (gauge=1, in-flight lines
    requeued — never failed); once the fast window slides past the burst
    the lane resumes and the job completes with zero failures."""
    reg = FileRegistry(upload_dir)
    store = BatchStore(upload_dir, reg)
    f = write_input(reg, n=4)
    job = store.create(endpoint="/v1/chat/completions",
                       input_file_id=f["id"])
    t = {"now": 1000.0}
    slo = SLOTracker(registry=Registry(), clock=lambda: t["now"],
                     targets={"ttft_ms": 0.001}, burn_threshold=1.0,
                     recover_burn=1.0, min_events=3)
    for _ in range(4):  # trip shedding for the job's model
        slo.observe("tiny", ttft_ms=50.0, e2e_ms=80.0)
    assert slo.shedding("tiny")
    metrics = Registry()
    ex = run_executor(store, sched, tmp_path, slo=slo, registry=metrics)
    try:
        assert wait_for(lambda: ex.paused, timeout=30)
        assert "localai_batch_lane_paused 1" in metrics.render()
        # paused means paused: no output lines land while shedding
        n_before = len(store.done_custom_ids(store.get(job["id"])))
        time.sleep(0.3)
        assert len(store.done_custom_ids(store.get(job["id"]))) == n_before
        assert store.get(job["id"])["status"] == "in_progress"
        # recovery: the fast window slides past the violation burst
        t["now"] += 120.0
        assert wait_for(
            lambda: store.get(job["id"])["status"] == "completed")
    finally:
        ex.stop()
    job = store.get(job["id"])
    # requeued, never failed: every line completed exactly once
    assert job["request_counts"] == {"total": 4, "completed": 4,
                                     "failed": 0}
    assert "localai_batch_lane_paused 0" in metrics.render()
    text = metrics.render()
    assert 'localai_batch_lines_total{result="completed"} 4' in text
