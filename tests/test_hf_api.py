"""HuggingFace Inference-API backend against a mock endpoint (parity:
/root/reference/pkg/langchain/huggingface.go + backend/go/llm/langchain —
remote hosted models served through the normal endpoints)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import httpx
import pytest

from localai_tpu.engine.scheduler import GenRequest
from localai_tpu.models.hf_api import HFApiScheduler
from localai_tpu.utils.tokenizer import ByteTokenizer


class _MockHF:
    """Minimal text-generation Inference API."""

    def __init__(self):
        self.requests: list[dict] = []
        mock = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                body = json.loads(self.rfile.read(
                    int(self.headers["Content-Length"])))
                body["_auth"] = self.headers.get("Authorization", "")
                body["_path"] = self.path
                mock.requests.append(body)
                out = json.dumps([{
                    "generated_text": "echo: " + body["inputs"][-20:],
                }]).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

        self._httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    @property
    def base(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self):
        self._httpd.shutdown()


@pytest.fixture()
def mock_hf():
    m = _MockHF()
    yield m
    m.close()


def test_scheduler_round_trip(mock_hf):
    sched = HFApiScheduler("org/model", "tok-123", mock_hf.base)
    tok = ByteTokenizer()
    h = sched.submit(GenRequest(
        prompt=tok.encode("hello remote"), max_new_tokens=16,
        temperature=0.7, top_p=0.9, stop=("END",),
    ))
    h.result(timeout=30)
    assert h.finish_reason == "stop"
    assert h.text == "echo: hello remote"
    sent = mock_hf.requests[0]
    assert sent["_path"] == "/org/model"
    assert sent["_auth"] == "Bearer tok-123"
    assert sent["inputs"] == "hello remote"
    p = sent["parameters"]
    assert p["max_new_tokens"] == 16
    assert p["temperature"] == 0.7
    assert p["return_full_text"] is False
    assert p["stop"] == ["END"]


def test_token_required(tmp_path, monkeypatch):
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.models.hf_api import HFApiServingModel

    for env in ("HUGGINGFACEHUB_API_TOKEN", "HF_TOKEN"):
        monkeypatch.delenv(env, raising=False)
    with pytest.raises(ValueError, match="token"):
        HFApiServingModel(
            ModelConfig(name="r", model="org/m", backend="huggingface"),
            AppConfig(model_path=str(tmp_path)),
        )


def test_chat_through_remote_backend(tmp_path, mock_hf):
    """End-to-end: `backend: huggingface` serves /v1/chat/completions via
    the remote API through the normal model lifecycle."""
    from test_api import _ServerThread, make_state

    (tmp_path / "remote.yaml").write_text(
        "name: remote\nmodel: org/model\nbackend: huggingface\n"
        f"api_token: tok-xyz\napi_base: {mock_hf.base}\n"
    )
    srv = _ServerThread(make_state(tmp_path))
    try:
        with httpx.Client(base_url=srv.base, timeout=60.0) as c:
            r = c.post("/v1/chat/completions", json={
                "model": "remote",
                "messages": [{"role": "user", "content": "ping"}],
            })
            assert r.status_code == 200, r.text
            content = r.json()["choices"][0]["message"]["content"]
            assert content.startswith("echo: ")
        assert srv.state.manager.loaded_names() == ["remote"]
        assert mock_hf.requests[0]["_auth"] == "Bearer tok-xyz"
    finally:
        srv.stop()


def test_remote_failure_surfaces_502(tmp_path):
    """A backend that fails before emitting anything must NOT produce a
    successful empty completion."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from test_api import _ServerThread, make_state

    class Deny(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            out = _json.dumps({"error": "model is loading"}).encode()
            self.send_response(503)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

    httpd = HTTPServer(("127.0.0.1", 0), Deny)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    (tmp_path / "bad.yaml").write_text(
        "name: bad\nmodel: org/m\nbackend: huggingface\n"
        f"api_token: t\napi_base: http://127.0.0.1:{httpd.server_address[1]}\n"
    )
    srv = _ServerThread(make_state(tmp_path))
    try:
        with httpx.Client(base_url=srv.base, timeout=60.0) as c:
            r = c.post("/v1/chat/completions", json={
                "model": "bad",
                "messages": [{"role": "user", "content": "x"}],
            })
            assert r.status_code == 502, r.text
    finally:
        srv.stop()
        httpd.shutdown()
