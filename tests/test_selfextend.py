"""Self-extend / group attention (VERDICT r4 #7; parity: llama.cpp
ga_n/ga_w, grpc-server.cpp:210-211,1870-1895)."""

import dataclasses

import numpy as np
import pytest

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models import llama as mdl
from localai_tpu.models.registry import resolve_model

PROMPT = list(range(1, 40))


@pytest.fixture(scope="module")
def tiny():
    return resolve_model("debug:tiny", dtype="float32")


def _greedy(runner, prompt, n):
    s = runner.acquire_slot()
    out = [runner.admit(s, list(prompt), temperature=0.0)]
    while len(out) < n:
        out.append(int(runner.step()[s]))
    return out


def test_identity_within_window(tiny):
    """With total length < ga_w, self-extend IS normal attention — greedy
    output must match the plain runner exactly (the neighbor branch covers
    every (q, k) pair)."""
    base = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                       prefill_buckets=[64], kv_dtype="float32")
    se = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                     prefill_buckets=[64], kv_dtype="float32",
                     ga_n=4, ga_w=128)
    assert se.attn_impl == "xla"
    assert _greedy(se, PROMPT, 12) == _greedy(base, PROMPT, 12)


def test_serves_past_trained_context(tiny):
    """A runner with ga_n=4 admits prompts LONGER than the model's
    max_position_embeddings and keeps generating valid tokens (the whole
    point of self-extend: grpc-server.cpp:1884-1886)."""
    cfg = dataclasses.replace(tiny.cfg, max_position_embeddings=64)
    r = ModelRunner(cfg, tiny.params, num_slots=2, max_ctx=256,
                    prefill_buckets=[64, 128, 256], kv_dtype="float32",
                    ga_n=4, ga_w=32)
    prompt = [(i * 7) % cfg.vocab_size for i in range(100)]  # > trained 64
    toks = _greedy(r, prompt, 8)
    assert all(0 <= t < cfg.vocab_size for t in toks)
    # grouped positions stay within the trained window: max effective
    # position = ga_w + (len - ga_w) / ga_n < trained ctx
    eff = r.ga_w - r.ga_w // r.ga_n + (100 + 8) // r.ga_n
    assert eff < cfg.max_position_embeddings


def test_matches_dense_reference(tiny):
    """Prefill logits equal a dense numpy-built self-extend reference:
    forward with explicit per-pair position remapping."""
    import jax.numpy as jnp

    from localai_tpu.engine import kvcache as kvc
    from localai_tpu.engine import selfextend as se

    cfg = tiny.cfg
    ga_n, ga_w, T = 2, 8, 24
    rope = mdl.rope_table(cfg, T)
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, T, cfg.num_heads, cfg.hd)),
                    jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, cfg.num_kv_heads, T, cfg.hd)),
                    jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, cfg.num_kv_heads, T, cfg.hd)),
                    jnp.float32)
    mask = kvc.prefill_mask(cfg, T, jnp.int32(T))
    pos = jnp.arange(T, dtype=jnp.int32)
    attend = se.build_attend(cfg, rope, ga_n, ga_w, pos[None], pos)
    ours = np.asarray(attend(q, k, v, mask))

    # dense reference: rotate per score set, merge by distance, softmax
    cos_t, sin_t = np.asarray(rope[0]), np.asarray(rope[1])

    def rot(x, p):  # x [*, hd]
        half = cfg.hd // 2
        c, s = cos_t[p], sin_t[p]
        x1, x2 = x[..., :half], x[..., half:]
        return np.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1)

    g = cfg.num_heads // cfg.num_kv_heads
    ref = np.zeros((T, cfg.num_heads, cfg.hd), np.float32)
    qn, kn, vn = (np.asarray(a[0]) for a in (q, k, v))
    shift = ga_w - ga_w // ga_n
    for h in range(cfg.num_heads):
        kv_h = h // g
        scores = np.full((T, T), -1e30, np.float32)
        for i in range(T):
            for j in range(i + 1):
                if i - j < ga_w:
                    qi, kj = rot(qn[i, h], i), rot(kn[kv_h, j], j)
                else:
                    qi = rot(qn[i, h], i // ga_n + shift)
                    kj = rot(kn[kv_h, j], j // ga_n)
                scores[i, j] = qi @ kj / np.sqrt(cfg.hd)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref[:, h] = p @ vn[kv_h]
    np.testing.assert_allclose(ours[0], ref, atol=2e-4, rtol=2e-4)


def test_prompt_cache_rope_flavor_guard(tiny, tmp_path):
    """A self-extend (unroped) KV export must not load into a roped-cache
    runner, and vice versa."""
    se_r = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                       prefill_buckets=[64], kv_dtype="float32",
                       ga_n=2, ga_w=64)
    s = se_r.acquire_slot()
    se_r.admit(s, PROMPT, temperature=0.0)
    exported = se_r.export_prefix(s)
    assert str(exported["kv_rope"]) == "raw"

    plain = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                        prefill_buckets=[64], kv_dtype="float32")
    assert not plain.load_prefix(0, exported, len(PROMPT))
    se2 = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                      prefill_buckets=[64], kv_dtype="float32",
                      ga_n=2, ga_w=64)
    assert se2.load_prefix(0, exported, len(PROMPT))


def test_config_plumbing(tmp_path):
    """grp_attn_n in the engine YAML reaches the runner and lifts the
    context ceiling past max_position_embeddings."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.models.manager import build_serving_model

    mcfg = ModelConfig(
        name="se", model="debug:tiny", context_size=1024,
        engine={"max_slots": 2, "prefill_buckets": [64],
                "grp_attn_n": 2, "grp_attn_w": 64},
    )
    sm = build_serving_model(mcfg, AppConfig(model_path=str(tmp_path)))
    try:
        assert sm.runner.ga_n == 2
        # debug:tiny trains at 512; ga_n=2 allows up to 1024
        assert sm.runner.max_ctx == 1024
        h = sm.scheduler.submit(GenRequest(
            prompt=PROMPT, max_new_tokens=4, temperature=0.0))
        h.result(timeout=120)
        assert h.finish_reason in ("stop", "length")
    finally:
        sm.scheduler.shutdown()


def test_ga_w_divisibility_validated(tiny):
    with pytest.raises(ValueError, match="multiple"):
        ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                    prefill_buckets=[64], ga_n=3, ga_w=64)


def test_selfextend_with_int8_kv(tiny):
    """The unroped cache quantizes like any other: int8-KV self-extend
    serves and matches its own float32-KV greedy stream within the
    quantization-noise-free window (short prompt, identical argmax)."""
    se8 = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                      prefill_buckets=[64], kv_dtype="int8",
                      ga_n=2, ga_w=64)
    toks = _greedy(se8, PROMPT, 6)
    assert all(0 <= t < tiny.cfg.vocab_size for t in toks)
    exported = se8.export_prefix(0)
    assert str(exported["kv_rope"]) == "raw"
    assert "k_scale" in exported
