"""Flight recorder + SLO observatory (obs.flight / obs.slo).

The unit half of the round-7 obs surfaces: ring wraparound + windowed
percentile math, sliding-window expiry, burn-rate computation, and the
shed→recover hysteresis state machine. The HTTP halves (/debug/flight,
/v1/slo, the 429 admission path) live in test_api.py; the scheduler feed
is covered in test_obs.py.
"""

import numpy as np
import pytest

from localai_tpu.obs import FlightRecorder, Registry, SLOTracker
from localai_tpu.obs import slo as obs_slo

# -- flight ring -------------------------------------------------------------


def _rec(fl, i, *, steps=8, ms=8.0, compile=False, tokens=32, ts=None,
         program="decode_n", gap=0.0, sched=0.0, launch=0.0, sync=0.0):
    fl.record(program=program, steps=steps, dispatch_ms=ms,
              occupancy=0.5, queue_depth=i, kv_utilization=0.25,
              tokens=tokens, preemptions=0, compile=compile, ts=ts,
              gap_ms=gap, sched_ms=sched, launch_ms=launch, sync_ms=sync)


def test_ring_wraparound_keeps_newest():
    fl = FlightRecorder(4)
    for i in range(10):
        _rec(fl, i, ms=float(i))
    assert fl.count == 10
    snap = fl.snapshot()
    assert len(snap) == 4                       # capacity bound
    assert [r["dispatch_ms"] for r in snap] == [6.0, 7.0, 8.0, 9.0]
    assert [r["queue_depth"] for r in snap] == [6, 7, 8, 9]
    # oldest → newest ordering across the wrap point
    ts = [r["ts"] for r in snap]
    assert ts == sorted(ts)


def test_total_tokens_survives_wraparound():
    fl = FlightRecorder(2)
    for i in range(7):
        _rec(fl, i, tokens=10)
    assert fl.total_tokens == 70                # not just the resident 2


def test_percentile_math_matches_numpy():
    fl = FlightRecorder(64)
    ms = [4.0, 8.0, 12.0, 16.0, 40.0]
    for i, m in enumerate(ms):
        _rec(fl, i, steps=4, ms=m)
    pct = fl.percentiles()
    per_step = np.array(ms) / 4.0
    assert pct["samples"] == 5
    assert pct["step_ms_p50"] == pytest.approx(
        np.percentile(per_step, 50), abs=1e-3)
    assert pct["step_ms_p90"] == pytest.approx(
        np.percentile(per_step, 90), abs=1e-3)
    assert pct["step_ms_p99"] == pytest.approx(
        np.percentile(per_step, 99), abs=1e-3)


def test_percentiles_exclude_compile_and_spec_rows():
    fl = FlightRecorder(16)
    _rec(fl, 0, steps=1, ms=5000.0, compile=True)   # compile-bearing
    _rec(fl, 1, steps=0, ms=30.0, program="spec")   # spec window
    _rec(fl, 2, steps=10, ms=10.0)
    _rec(fl, 3, steps=10, ms=10.0)
    pct = fl.percentiles()
    assert pct["samples"] == 2
    assert pct["step_ms_p50"] == pytest.approx(1.0)
    assert pct["step_ms_p99"] == pytest.approx(1.0)
    # spec rows surface step_ms=None in snapshots (variable token yield)
    snap = fl.snapshot()
    assert snap[1]["step_ms"] is None
    assert snap[0]["compile"] is True


def test_percentiles_empty_and_windowed():
    fl = FlightRecorder(8)
    assert fl.percentiles() == {
        "step_ms_p50": None, "step_ms_p90": None, "step_ms_p99": None,
        "samples": 0,
    }
    _rec(fl, 0, steps=2, ms=2.0, ts=100.0)     # old
    _rec(fl, 1, steps=2, ms=20.0, ts=200.0)    # recent
    pct = fl.percentiles(window_s=50.0, now=210.0)
    assert pct["samples"] == 1
    assert pct["step_ms_p50"] == pytest.approx(10.0)


def test_snapshot_since_and_limit():
    fl = FlightRecorder(16)
    for i in range(6):
        _rec(fl, i, ts=100.0 + i)
    snap = fl.snapshot()
    mid = snap[2]["ts"]
    newer = fl.snapshot(since=mid)
    assert [r["queue_depth"] for r in newer] == [3, 4, 5]
    assert len(fl.snapshot(limit=2)) == 2
    assert fl.snapshot(limit=2)[-1]["queue_depth"] == 5
    assert fl.snapshot(since=106.0) == []


# -- dispatch anatomy (phase columns + obs.anatomy) --------------------------


def test_phase_columns_default_zero_and_survive_since_filter():
    fl = FlightRecorder(8)
    _rec(fl, 0, ts=100.0)                       # no phase kwargs
    _rec(fl, 1, ts=101.0, gap=1.0, sched=2.0, launch=3.0, sync=4.0)
    snap = fl.snapshot()
    for key in ("gap_ms", "sched_ms", "launch_ms", "sync_ms"):
        assert snap[0][key] == 0.0              # pre-anatomy degrade shape
    assert snap[1]["gap_ms"] == 1.0
    assert snap[1]["sync_ms"] == 4.0
    # the since-filtered view carries the same phase keys (satellite:
    # merged fleet rows must never KeyError on them)
    newer = fl.snapshot(since=100.5)
    assert len(newer) == 1
    assert newer[0]["sched_ms"] == 2.0 and newer[0]["launch_ms"] == 3.0


def test_phase_columns_survive_wraparound():
    fl = FlightRecorder(4)
    for i in range(10):
        _rec(fl, i, sync=float(i))
    snap = fl.snapshot()
    assert [r["sync_ms"] for r in snap] == [6.0, 7.0, 8.0, 9.0]
    ph = fl.phases()
    assert ph["samples"] == 4                   # resident rows only
    assert ph["sync_ms_total"] == pytest.approx(30.0)


def test_phases_percentile_math_matches_numpy():
    fl = FlightRecorder(64)
    gaps = [1.0, 2.0, 3.0, 4.0, 5.0]
    syncs = [0.5, 1.0, 1.5, 2.0, 2.5]
    for i, (g, s) in enumerate(zip(gaps, syncs)):
        _rec(fl, i, ms=20.0, gap=g, sched=0.5, launch=2.0, sync=s)
    ph = fl.phases()
    assert ph["samples"] == 5
    assert ph["gap_ms_p50"] == pytest.approx(
        np.percentile(gaps, 50), abs=1e-3)
    assert ph["gap_ms_p90"] == pytest.approx(
        np.percentile(gaps, 90), abs=1e-3)
    assert ph["sync_ms_p99"] == pytest.approx(
        np.percentile(syncs, 99), abs=1e-3)
    # host percentiles are over the per-record SUM (percentiles of
    # independent phases do not compose)
    host = np.array(gaps) + 0.5 + 2.0
    assert ph["host_ms_p50"] == pytest.approx(
        np.percentile(host, 50), abs=1e-3)
    # windowed totals + fractions
    assert ph["dispatch_ms_total"] == pytest.approx(100.0)
    assert ph["host_ms_total"] == pytest.approx(host.sum(), abs=1e-3)
    assert ph["host_overhead_fraction"] == pytest.approx(
        host.sum() / 100.0, abs=1e-3)
    bubble = np.maximum(0.0, host - np.array(syncs))
    assert ph["device_bubble_fraction"] == pytest.approx(
        bubble.sum() / 100.0, abs=1e-3)


def test_phases_exclude_compile_rows_and_window():
    fl = FlightRecorder(16)
    # a compile row's minutes of tracing must not drown the phases
    _rec(fl, 0, ms=5000.0, compile=True, gap=4000.0, sync=900.0, ts=100.0)
    _rec(fl, 1, ms=10.0, gap=6.0, sync=4.0, ts=100.0)
    _rec(fl, 2, ms=10.0, gap=2.0, sync=8.0, ts=200.0)
    ph = fl.phases()
    assert ph["samples"] == 2
    assert ph["dispatch_ms_total"] == pytest.approx(20.0)
    assert ph["gap_ms_total"] == pytest.approx(8.0)
    # window keeps only the recent row
    ph = fl.phases(window_s=50.0, now=210.0)
    assert ph["samples"] == 1
    assert ph["sync_ms_total"] == pytest.approx(8.0)
    assert ph["host_overhead_fraction"] == pytest.approx(0.2)


def test_phases_empty_returns_none_percentiles():
    fl = FlightRecorder(8)
    ph = fl.phases()
    assert ph["samples"] == 0
    for name in ("gap", "sched", "launch", "sync", "host"):
        assert ph[f"{name}_ms_p50"] is None
    assert ph["host_overhead_fraction"] is None
    assert ph["device_bubble_fraction"] is None
    assert ph["dispatch_ms_total"] == 0.0


def test_anatomy_breakdown_shares_and_quantiles():
    from localai_tpu.obs import anatomy

    fl = FlightRecorder(16)
    _rec(fl, 0, ms=10.0, gap=1.0, sched=2.0, launch=3.0, sync=4.0)
    _rec(fl, 1, ms=10.0)                        # fully unattributed
    b = anatomy.breakdown(fl, window_s=None)
    assert b["samples"] == 2
    assert b["phase_share"]["gap"] == pytest.approx(0.05)
    assert b["phase_share"]["sync"] == pytest.approx(0.2)
    # the all-zero record's wall lands in unattributed, not in a phase
    assert b["unattributed_ms_total"] == pytest.approx(10.0)
    assert b["unattributed_share"] == pytest.approx(0.5)
    q = anatomy.phase_quantiles(anatomy.summarize(fl, window_s=None))
    assert set(q) == set(anatomy.PHASES)
    assert set(q["gap"]) == {"p50", "p90", "p99"}
    assert q["launch"]["p99"] == pytest.approx(
        np.percentile([3.0, 0.0], 99), abs=1e-3)


# -- SLO observatory ---------------------------------------------------------


def _tracker(clock, **kw):
    kw.setdefault("targets", {"ttft_ms": 100.0})
    kw.setdefault("burn_threshold", 2.0)
    kw.setdefault("recover_burn", 1.0)
    kw.setdefault("min_events", 2)
    kw.setdefault("objective", 0.95)
    return SLOTracker(registry=Registry(), clock=clock, **kw)


def test_window_expiry_drops_old_events():
    t = {"now": 1000.0}
    slo = _tracker(lambda: t["now"])
    slo.observe("m", ttft_ms=500.0)            # bad
    assert slo.burn_rate("m", "1m") == pytest.approx(20.0)
    t["now"] += 90                              # out of the 1m window
    assert slo.burn_rate("m", "1m") == 0.0
    assert slo.burn_rate("m", "5m") == pytest.approx(20.0)
    t["now"] += 3600                            # past the 30m horizon too
    slo.observe("m", ttft_ms=10.0)             # prunes on the way in
    w = slo.windows("m")
    assert w["30m"]["count"] == 1 and w["30m"]["bad"] == 0


def test_burn_rate_is_bad_fraction_over_budget():
    t = {"now": 0.0}
    slo = _tracker(lambda: t["now"])
    for ttft in (50.0, 50.0, 50.0, 200.0):     # 1 bad of 4, budget 5%
        slo.observe("m", ttft_ms=ttft)
    assert slo.burn_rate("m", "1m") == pytest.approx(0.25 / 0.05)
    w = slo.windows("m")["1m"]
    assert w["count"] == 4 and w["bad"] == 1
    assert w["ttft_ms"]["p50"] == pytest.approx(50.0)


def test_error_counts_as_violation_and_percentiles_skip_none():
    t = {"now": 0.0}
    slo = _tracker(lambda: t["now"])
    slo.observe("m", ttft_ms=None, error=True)  # failed before first token
    w = slo.windows("m")["1m"]
    assert w["bad"] == 1 and w["ttft_ms"] is None


def test_shed_hysteresis_trip_and_recover():
    t = {"now": 1000.0}
    slo = _tracker(lambda: t["now"])
    # one bad event: burn is high but min_events (2) not met → no shed
    slo.observe("m", ttft_ms=500.0)
    assert not slo.should_shed("m")
    slo.observe("m", ttft_ms=500.0)
    assert slo.should_shed("m")                 # fast AND slow over 2.0
    assert slo.shedding("m")
    assert slo.shed("m") == slo.retry_after_s   # the 429 path records
    assert slo.shed_total("m") == 1
    # hysteresis: still shedding while the fast window stays hot
    t["now"] += 10
    assert slo.should_shed("m")
    # the fast window slides past the burst → automatic recovery ...
    t["now"] += 80
    assert not slo.should_shed("m")
    assert not slo.shedding("m")
    # ... even though the slow (5m) window still holds the bad events
    assert slo.burn_rate("m", "5m") > slo.burn_threshold


def test_shed_needs_both_windows_hot():
    t = {"now": 1000.0}
    slo = _tracker(lambda: t["now"])
    # two bad events, but 4m ago: slow window hot, fast window empty
    slo.observe("m", ttft_ms=500.0, now=760.0)
    slo.observe("m", ttft_ms=500.0, now=760.0)
    assert slo.burn_rate("m", "5m") > slo.burn_threshold
    assert slo.burn_rate("m", "1m") == 0.0
    assert not slo.should_shed("m")


def test_no_targets_never_sheds_and_unlatches():
    t = {"now": 0.0}
    slo = _tracker(lambda: t["now"])
    slo.observe("m", ttft_ms=500.0)
    slo.observe("m", ttft_ms=500.0)
    assert slo.should_shed("m")
    slo.configure(targets={})                   # operator clears the SLO
    assert not slo.should_shed("m")
    assert not slo.shedding("m")


def test_scrape_observes_recovery_without_traffic():
    """A shedding model whose clients all back off must still recover:
    the scrape/report paths re-run the state machine instead of echoing
    the latched flag (no request required to un-stick the gauge)."""
    t = {"now": 1000.0}
    reg = Registry()
    slo = SLOTracker(registry=reg, clock=lambda: t["now"],
                     targets={"ttft_ms": 1.0}, burn_threshold=1.0,
                     recover_burn=1.0, min_events=1)
    slo.observe("m", ttft_ms=50.0)
    assert slo.should_shed("m")
    t["now"] += 120                    # fast window drains, zero traffic
    slo.export_gauges()                # a scrape, not an admission
    assert 'localai_overload_shedding{model="m"} 0' in reg.render()
    assert slo.report()["models"]["m"]["shedding"] is False


def test_export_gauges_renders_series():
    t = {"now": 0.0}
    reg = Registry()
    slo = SLOTracker(registry=reg, clock=lambda: t["now"],
                     targets={"ttft_ms": 100.0}, burn_threshold=2.0,
                     min_events=1)
    slo.observe("m", ttft_ms=500.0)
    assert slo.should_shed("m")
    slo.shed("m")
    slo.export_gauges()
    text = reg.render()
    assert 'localai_slo_burn_rate{model="m",window="1m"} 20.0' in text
    assert 'localai_slo_burn_rate{model="m",window="30m"} 20.0' in text
    assert 'localai_overload_shedding{model="m"} 1' in text
    assert 'localai_requests_shed_total{model="m"} 1' in text


def test_reset_clears_state_and_gauges():
    reg = Registry()
    slo = SLOTracker(registry=reg, clock=lambda: 0.0,
                     targets={"ttft_ms": 1.0}, min_events=1,
                     burn_threshold=1.0)
    slo.observe("m", ttft_ms=50.0)
    assert slo.should_shed("m")
    slo.reset()
    assert not slo.shedding("m")
    assert slo.shed_total("m") == 0
    assert 'localai_overload_shedding{model="m"} 0' in reg.render()
    assert slo.report()["models"] == {}


def test_env_targets_parse(monkeypatch):
    monkeypatch.setenv("LOCALAI_SLO_TTFT_P95_MS", "250")
    monkeypatch.setenv("LOCALAI_SLO_TPOT_P95_MS", "0")      # disabled
    monkeypatch.setenv("LOCALAI_SLO_E2E_P95_MS", "garbage")  # ignored
    monkeypatch.delenv("LOCALAI_SLO_QUEUE_P95_MS", raising=False)
    assert obs_slo.env_targets() == {"ttft_ms": 250.0}


def test_targets_from_app_config():
    from localai_tpu.config.app_config import AppConfig

    cfg = AppConfig(slo_ttft_p95_ms=300.0, slo_e2e_p95_ms=2000.0)
    assert obs_slo.targets_from_config(cfg) == {
        "ttft_ms": 300.0, "e2e_ms": 2000.0,
    }


def test_report_shape():
    t = {"now": 0.0}
    slo = _tracker(lambda: t["now"])
    slo.observe("m", ttft_ms=50.0, tpot_ms=5.0, e2e_ms=80.0, queue_ms=1.0)
    rep = slo.report()
    assert rep["windows"] == ["1m", "5m", "30m"]
    assert rep["targets"] == {"ttft_ms": 100.0}
    m = rep["models"]["m"]
    assert m["shedding"] is False and m["shed_total"] == 0
    agg = m["windows"]["1m"]
    assert agg["count"] == 1 and agg["burn_rate"] == 0.0
    for metric in ("ttft_ms", "tpot_ms", "e2e_ms", "queue_ms"):
        assert set(agg[metric]) == {"p50", "p95", "p99"}
