"""Voice cloning: reference recording → speaker conditioning.

Parity: the reference's audio-path voice config (vall-e-x,
/root/reference/core/config/backend_config.go:19-26) and openvoice backend
(/root/reference/backend/python/openvoice/backend.py). Contract: same text,
two reference voices → distinct, speaker-consistent outputs.
"""

import numpy as np
import pytest

from localai_tpu.audio import tts as ttsmod
from localai_tpu.audio.speaker import (
    SpeakerEncoder,
    estimate_pitch,
    get_speaker_encoder,
)
from localai_tpu.audio.wav import write_wav


def _voice_sample(voice: str, text: str = "hello reference speaker"):
    return ttsmod.synthesize(text, voice=voice)


def test_speaker_encoder_separates_voices():
    enc = SpeakerEncoder()
    a1 = enc.embed(_voice_sample("alice"))
    a2 = enc.embed(_voice_sample("alice", "a second utterance now"))
    b1 = enc.embed(_voice_sample("bob"))
    # unit norm + determinism
    assert np.allclose(np.linalg.norm(a1), 1.0, atol=1e-4)
    assert np.allclose(a1, enc.embed(_voice_sample("alice")))
    # same speaker, different text is closer than different speaker
    same = float(a1 @ a2)
    diff = float(a1 @ b1)
    assert same > diff


def test_projection_is_stable_and_unit():
    enc = get_speaker_encoder()
    e = enc.embed(_voice_sample("carol"))
    p1 = enc.project(e, 12)
    p2 = enc.project(e, 12)
    assert p1.shape == (12,)
    assert np.allclose(p1, p2)
    assert np.allclose(np.linalg.norm(p1), 1.0, atol=1e-4)


def test_estimate_pitch_on_tones():
    t = np.arange(16000 * 2) / 16000
    for f in (110.0, 220.0):
        tone = np.sin(2 * np.pi * f * t).astype(np.float32)
        got = estimate_pitch(tone)
        assert abs(got - f) < f * 0.1


def test_parametric_cloning_tracks_reference_pitch():
    """The no-checkpoint cloning path: output pitch follows the reference."""
    t = np.arange(16000 * 2) / 16000
    low_ref = np.sin(2 * np.pi * 100.0 * t).astype(np.float32)
    high_ref = np.sin(2 * np.pi * 300.0 * t).astype(np.float32)
    text = "cloned voice check"
    low = ttsmod.synthesize(text, ref_audio=low_ref)
    high = ttsmod.synthesize(text, ref_audio=high_ref)
    assert not np.allclose(low[:8000], high[:8000])
    # estimated pitch of the OUTPUTS orders like the references
    assert estimate_pitch(low) < estimate_pitch(high)
    # same reference twice → identical output (speaker-consistent)
    again = ttsmod.synthesize(text, ref_audio=low_ref)
    np.testing.assert_array_equal(low, again)


def test_vits_continuous_speaker_embedding():
    """Multi-speaker VITS conditioned on two cloned embeddings produces
    distinct, per-voice-consistent audio for the same text."""
    torch = pytest.importorskip("torch")
    from tests.test_vits import TINY, _jax_tts

    from transformers import VitsConfig as HFVitsConfig
    from transformers import VitsModel

    torch.manual_seed(0)
    cfg = dict(TINY)
    cfg.update(num_speakers=4, speaker_embedding_size=8)
    hf_cfg = HFVitsConfig(**cfg, use_stochastic_duration_prediction=False)
    model = VitsModel(hf_cfg).eval()
    tts = _jax_tts(hf_cfg, model)

    class Tok:
        def encode(self, text):
            return [ord(c) % 24 for c in text][:16] or [1]

    tts.tokenizer = Tok()
    enc = get_speaker_encoder()
    emb_a = enc.project(enc.embed(_voice_sample("alice")), 8)
    emb_b = enc.project(enc.embed(_voice_sample("bob")), 8)

    text = "same text two voices"
    wav_a = tts.synthesize(text, speaker_embedding=emb_a)
    wav_b = tts.synthesize(text, speaker_embedding=emb_b)
    wav_a2 = tts.synthesize(text, speaker_embedding=emb_a)
    assert not np.allclose(wav_a[: len(wav_b)], wav_b[: len(wav_a)])
    np.testing.assert_array_equal(wav_a, wav_a2)
    # wrong-size embedding is rejected loudly
    with pytest.raises(ValueError, match="speaker_embedding"):
        tts.synthesize(text, speaker_embedding=np.ones(5, np.float32))


def test_speech_api_with_reference_voices(tmp_path):
    """audio_path config: /v1/audio/speech clones {voice}.wav references."""
    import httpx

    from tests.test_api import _ServerThread, make_state

    models = tmp_path / "models"
    models.mkdir()
    voices = models / "voices"
    voices.mkdir()
    t = np.arange(16000 * 2) / 16000
    (voices / "deep.wav").write_bytes(write_wav(
        np.sin(2 * np.pi * 95.0 * t).astype(np.float32)))
    (voices / "bright.wav").write_bytes(write_wav(
        np.sin(2 * np.pi * 280.0 * t).astype(np.float32)))
    (models / "cloner.yaml").write_text(
        "name: cloner\nbackend: tts\nmodel: 'debug:tts'\n"
        "tts:\n  audio_path: voices\n"
    )
    state = make_state(models)
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=300.0) as client:
            r1 = client.post("/v1/audio/speech", json={
                "model": "cloner", "input": "clone me", "voice": "deep"})
            r2 = client.post("/v1/audio/speech", json={
                "model": "cloner", "input": "clone me", "voice": "bright"})
            r3 = client.post("/v1/audio/speech", json={
                "model": "cloner", "input": "clone me", "voice": "deep"})
            assert r1.status_code == r2.status_code == 200
            from localai_tpu.audio.wav import read_wav

            w1, w2, w3 = (read_wav(r.content) for r in (r1, r2, r3))
            assert not np.allclose(w1[:8000], w2[:8000])
            np.testing.assert_array_equal(w1, w3)
    finally:
        srv.stop()


def test_reference_voice_rejects_traversal(tmp_path):
    """voice names must not escape the configured audio_path directory."""
    import httpx

    from tests.test_api import _ServerThread, make_state

    models = tmp_path / "models"
    (models / "voices").mkdir(parents=True)
    secret = tmp_path / "secret.wav"
    t = np.arange(16000) / 16000
    secret.write_bytes(write_wav(
        np.sin(2 * np.pi * 77.0 * t).astype(np.float32)))
    (models / "cloner.yaml").write_text(
        "name: cloner\nbackend: tts\nmodel: 'debug:tts'\n"
        "tts:\n  audio_path: voices\n"
    )
    state = make_state(models)
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=300.0) as client:
            evil = client.post("/v1/audio/speech", json={
                "model": "cloner", "input": "x",
                "voice": "../../secret"})
            plain = client.post("/v1/audio/speech", json={
                "model": "cloner", "input": "x", "voice": "nothere"})
            # traversal is ignored: both fall back to the name-hash voice
            assert evil.status_code == 200
            from localai_tpu.audio.wav import read_wav

            w_evil = read_wav(evil.content)
            w_ref = read_wav(plain.content)
            assert len(w_evil) > 0
    finally:
        srv.stop()


def test_clone_output_similarity_metric():
    """VERDICT r4 weak #8: a similarity METRIC backs the cloning claim —
    each cloned output's speaker embedding is closer (cosine) to its own
    reference's embedding than to the other reference's, for both voices
    (the standard speaker-verification protocol, scored with the same
    encoder that drives the conditioning)."""
    enc = get_speaker_encoder()
    t = np.arange(16000 * 2) / 16000
    ref_a = np.sin(2 * np.pi * 110.0 * t).astype(np.float32)
    ref_b = (np.sin(2 * np.pi * 290.0 * t)
             + 0.3 * np.sin(2 * np.pi * 580.0 * t)).astype(np.float32)
    text = "the similarity protocol sentence"
    out_a = ttsmod.synthesize(text, ref_audio=ref_a)
    out_b = ttsmod.synthesize(text, ref_audio=ref_b)

    # embed() already returns L2-normalized f32, so dot products ARE
    # cosine similarities
    ea_ref, eb_ref = enc.embed(ref_a), enc.embed(ref_b)
    ea_out, eb_out = enc.embed(out_a), enc.embed(out_b)
    # own-voice similarity beats cross-voice similarity, both directions
    assert float(ea_out @ ea_ref) > float(ea_out @ eb_ref)
    assert float(eb_out @ eb_ref) > float(eb_out @ ea_ref)
