"""Elastic capacity: the autoscale policy decision table (pure, no
fleet) and the controller's full lifecycle against a real in-process
fleet — spike scale-out, idle scale-in with zero lost requests,
scale-to-zero, and the cold re-onboard that serves the held request.

The policy tests pin the hysteresis contract: scale-out and scale-in
read different thresholds with separate cooldowns, overload always
overrides idleness, and the last replica only ever leaves through
scale_to_zero."""

import threading
import time

import pytest

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.scheduler import GenRequest
from localai_tpu.fleet.autoscale import (ACTIONS, AutoscaleConfig,
                                         AutoscaleController,
                                         AutoscalePolicy, ReplicaSignals,
                                         evict_lru_model, hbm_fraction)

# ---------------------------------------------------------------------------
# policy decision table (no fleet, no clock, no threads)


def _cfg(**kw):
    base = dict(min_replicas=1, max_replicas=4, in_idle_s=60.0,
                zero_idle_s=0.0, out_queue_depth=4.0, out_kv_util=0.85,
                out_step_p99_ms=0.0, out_burn=2.0, out_cooldown_s=30.0,
                in_cooldown_s=60.0)
    base.update(kw)
    return AutoscaleConfig(**base)


def _sig(rid="r0", **kw):
    return ReplicaSignals(rid=rid, **kw)


def test_below_min_self_heals_regardless_of_cooldown():
    pol = AutoscalePolicy(_cfg(min_replicas=2))
    pol.last_out_at = 100.0  # cooldown would normally suppress
    d = pol.decide([_sig()], now=101.0)
    assert (d.action, d.reason, d.target) == ("scale_out", "below_min", 2)
    # a booting replica counts toward the floor — no double-spawn
    d = pol.decide([_sig(), _sig("r1", state="starting")], now=101.0)
    assert d.action == "none"


def test_each_overload_signal_scales_out_with_its_reason():
    cases = [
        (dict(queue_depth=5.0), "queue_depth"),
        (dict(burn_1m=3.0), "slo_burn"),
        (dict(kv_util=0.9), "kv_pressure"),
    ]
    for kw, why in cases:
        d = AutoscalePolicy(_cfg()).decide([_sig(**kw)], now=0.0)
        assert (d.action, d.reason) == ("scale_out", why), kw
    # step p99 is opt-in: disabled (0) never fires, enabled does
    slow = [_sig(step_p99_ms=900.0)]
    assert AutoscalePolicy(_cfg()).decide(slow, now=0.0).action == "none"
    d = AutoscalePolicy(_cfg(out_step_p99_ms=500.0)).decide(slow, now=0.0)
    assert (d.action, d.reason) == ("scale_out", "step_p99")


def test_overload_holds_at_max_cooldown_and_boot_pending():
    hot = _sig(queue_depth=9.0)
    pol = AutoscalePolicy(_cfg(max_replicas=1))
    assert pol.decide([hot], now=0.0).reason == "at_max:queue_depth"

    pol = AutoscalePolicy(_cfg())
    pol.note("scale_out", 100.0)
    d = pol.decide([hot], now=110.0)  # inside the 30 s out-cooldown
    assert (d.action, d.reason) == ("none", "out_cooldown:queue_depth")
    d = pol.decide([hot], now=200.0)  # cooldown expired
    assert d.action == "scale_out"

    # a replica already booting absorbs the overload — don't stack spawns
    d = AutoscalePolicy(_cfg()).decide(
        [hot, _sig("r1", state="respawning")], now=0.0)
    assert d.reason == "boot_pending:queue_depth"


def test_scale_in_picks_idlest_and_never_takes_the_last_replica():
    fleet = [_sig("r0", idle_s=200.0), _sig("r1", idle_s=50.0),
             _sig("r2", idle_s=400.0)]
    d = AutoscalePolicy(_cfg()).decide(fleet, now=0.0)
    assert (d.action, d.rid, d.target) == ("scale_in", "r2", 2)

    # the floor is max(min_replicas, 1): even with min_replicas=0 the
    # last replica only leaves through scale_to_zero
    d = AutoscalePolicy(_cfg(min_replicas=0)).decide(
        [_sig(idle_s=9999.0)], now=0.0)
    assert (d.action, d.reason) == ("none", "steady")

    # in-cooldown suppresses; note() only arms it for the in-direction
    pol = AutoscalePolicy(_cfg())
    pol.note("scale_in", 100.0)
    assert pol.decide(fleet, now=110.0).reason == "in_cooldown"
    assert pol.decide(fleet, now=300.0).action == "scale_in"
    assert pol.last_out_at == float("-inf")  # untouched by scale_in


def test_overload_overrides_idleness():
    # long-idle replica but the other one is burning SLO budget: the
    # fleet adds capacity, it does not shed it
    fleet = [_sig("r0", idle_s=500.0), _sig("r1", burn_1m=5.0)]
    d = AutoscalePolicy(_cfg()).decide(fleet, now=0.0)
    assert (d.action, d.reason) == ("scale_out", "slo_burn")


def test_scale_to_zero_requires_every_replica_quiet_and_idle():
    cfg = _cfg(min_replicas=0, zero_idle_s=10.0, in_cooldown_s=5.0)
    idle = [_sig("r0", idle_s=20.0), _sig("r1", idle_s=15.0)]
    d = AutoscalePolicy(cfg).decide(idle, now=100.0)
    assert (d.action, d.target) == ("scale_to_zero", 0)

    # one replica with anything in flight (or queued) vetoes it
    busy = [_sig("r0", idle_s=20.0), _sig("r1", inflight=1)]
    assert AutoscalePolicy(cfg).decide(busy, now=100.0).action != \
        "scale_to_zero"
    queued = [_sig("r0", idle_s=20.0),
              _sig("r1", idle_s=20.0, queue_depth=1.0)]
    assert AutoscalePolicy(cfg).decide(queued, now=100.0).action != \
        "scale_to_zero"

    pol = AutoscalePolicy(cfg)
    pol.note("scale_to_zero", 99.0)
    assert pol.decide(idle, now=100.0).reason == "in_cooldown"

    # zero_idle_s=0 disables the path entirely
    d = AutoscalePolicy(_cfg(min_replicas=0)).decide(idle, now=100.0)
    assert d.action != "scale_to_zero"


def test_from_app_and_env_knobs(monkeypatch):
    app = AppConfig(autoscale_min=2, autoscale_max=6,
                    autoscale_interval_s=1.5, autoscale_in_idle_s=30.0,
                    autoscale_zero_idle_s=300.0,
                    autoscale_standby_hosts=["h1:50051"])
    monkeypatch.setenv("LOCALAI_AUTOSCALE_OUT_QUEUE", "2.5")
    monkeypatch.setenv("LOCALAI_AUTOSCALE_OUT_BURN", "nonsense")
    cfg = AutoscaleConfig.from_app(app)
    assert (cfg.min_replicas, cfg.max_replicas) == (2, 6)
    assert cfg.standby_hosts == ["h1:50051"]
    assert cfg.out_queue_depth == 2.5
    assert cfg.out_burn == 2.0  # unparseable env falls back to default
    assert set(ACTIONS) >= {"scale_out", "scale_in", "scale_to_zero",
                            "cold_start", "swap", "none"}


# ---------------------------------------------------------------------------
# density reaper (stub manager — no engines)


class _StubModel:
    def __init__(self, last_used, busy=False):
        self.last_used = last_used
        self._busy = busy

    @property
    def busy(self):
        return self._busy


class _StubManager:
    def __init__(self, models):
        self._models = dict(models)
        self._lock = threading.RLock()
        self.shut = []

    def shutdown_model(self, name, *, force=False, wait=5.0):
        self.shut.append(name)
        self._models.pop(name, None)
        return True


def test_evict_lru_model_spares_keep_and_busy():
    mgr = _StubManager({"old": _StubModel(10.0), "mid": _StubModel(20.0),
                        "hot": _StubModel(30.0)})
    # below threshold: no eviction
    assert evict_lru_model(mgr, threshold=0.9, fraction=0.5) is None
    # LRU goes first; the keep-set and busy models are untouchable
    assert evict_lru_model(mgr, keep=("old",), threshold=0.9,
                           fraction=0.95) == "mid"
    mgr._models["busy"] = _StubModel(1.0, busy=True)
    assert evict_lru_model(mgr, keep=("old",), threshold=0.9,
                           fraction=0.95) == "hot"
    assert evict_lru_model(mgr, keep=("old",), threshold=0.9,
                           fraction=0.95) is None  # only keep/busy left
    assert mgr.shut == ["mid", "hot"]


def test_hbm_fraction_env_override(monkeypatch):
    monkeypatch.setenv("LOCALAI_AUTOSCALE_HBM_FRACTION", "0.77")
    assert hbm_fraction() == pytest.approx(0.77)


def test_usage_report_ingests_autoscale_artifact(tmp_path):
    """tools/usage_report --ingest-autoscale replays the CI artifact's
    capacity trajectory at its recorded timestamps and folds decision
    counts into autoscale.* series; bad files are skipped, not fatal."""
    import json

    from localai_tpu.obs.history import History
    from tools.usage_report import build_report, ingest_autoscale

    doc = {
        "decisions": {"scale_out": 2, "none": 50},
        "peak_healthy": 3, "cold_start_ms": 2895.1,
        "target_series": {
            "series": "fleet_target_replicas.fleet-auto",
            "points": [{"ts": 100.0, "value": 1.0},
                       {"ts": 103.0, "value": 3.0}],
        },
    }
    (tmp_path / "autoscale_report.json").write_text(json.dumps(doc))
    (tmp_path / "autoscale_report_bad.json").write_text("{nope")

    h = History()
    n = ingest_autoscale(h, [str(tmp_path)])
    assert n == 6  # 2 trajectory points + 2 decisions + peak + cold
    rep = build_report(h, res=1)
    assert rep["fleet_target_replicas"]["fleet-auto"]["latest"] == 3.0
    assert rep["autoscale"]["decisions_scale_out"]["latest"] == 2.0
    assert rep["autoscale"]["peak_healthy"]["latest"] == 3.0


# ---------------------------------------------------------------------------
# controller lifecycle against a real in-process fleet

TINY = {
    "name": "astiny", "model": "debug:tiny", "context_size": 256,
    "parameters": {"temperature": 0.0, "max_tokens": 8},
    "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64, 128],
               "dtype": "float32", "kv_dtype": "float32",
               "kv_block_tokens": 16},
}


def _build_fleet(replicas=1):
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.models.manager import build_serving_model

    app = AppConfig()
    mcfg = ModelConfig.model_validate(TINY)

    def factory(rid, role):
        return InProcessReplica(
            rid, role, lambda: build_serving_model(mcfg, app))

    return FleetServingModel(mcfg, app, factory, replicas=replicas)


def _submit(fm, text, max_new=8):
    return fm.scheduler.submit(GenRequest(
        prompt=fm.tokenizer.encode(text), max_new_tokens=max_new,
        temperature=0.0))


def _tick_until(auto, pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        auto.tick()
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_controller_scales_out_under_burst_then_back_in():
    """Manual-tick e2e (no daemon thread — the test owns the clockwork):
    a queue burst scales a 1-replica fleet out, every request completes,
    and the idle fleet scales back in to exactly one replica — never
    zero, because scale-to-zero is disabled here and single scale-in
    refuses to take the last replica."""
    fm = _build_fleet(replicas=1)
    auto = AutoscaleController(fm, config=AutoscaleConfig(
        min_replicas=0, max_replicas=3, interval_s=0.1,
        in_idle_s=0.4, zero_idle_s=0.0, out_queue_depth=0.5,
        out_cooldown_s=0.2, in_cooldown_s=0.2))
    fm.autoscaler = auto
    pool = fm.pool
    try:
        # -- burst: queue depth over threshold forces a scale-out
        handles = [_submit(fm, f"elastic burst prompt {i}")
                   for i in range(8)]
        grew = _tick_until(
            auto, lambda: len(pool.healthy("decode")) >= 2)
        assert grew, "spike never scaled out"
        assert auto.decisions["scale_out"] >= 1
        for h in handles:
            h.result(timeout=120)
            assert h.finish_reason in ("stop", "length")

        # -- quiesce: surplus capacity drains away, every request above
        # already accounted for (nothing lost), and the shrink floors at 1
        shrank = _tick_until(
            auto, lambda: len(pool.healthy("decode")) == 1, timeout=60.0)
        assert shrank, "idle fleet never scaled in"
        assert auto.decisions["scale_in"] >= 1
        for _ in range(10):  # well past in_idle_s + in_cooldown_s
            auto.tick()
            time.sleep(0.1)
        assert len(pool.healthy("decode")) == 1
        assert auto.decisions["scale_to_zero"] == 0

        snap = auto.snapshot()
        assert snap["enabled"] and snap["max"] == 3
        assert snap["decisions"]["scale_out"] >= 1
    finally:
        auto.stop()
        fm.close()


def test_controller_scale_to_zero_then_cold_start_serves():
    """An all-idle fleet (scale-in disabled, zero enabled) collapses to
    zero replicas via scale_to_zero only, and the next request triggers
    the scheduler's on_cold hook: it waits for the cold re-onboard and
    completes — the caller never sees an error."""
    fm = _build_fleet(replicas=1)
    auto = AutoscaleController(fm, config=AutoscaleConfig(
        min_replicas=0, max_replicas=3, interval_s=0.1,
        in_idle_s=0.0, zero_idle_s=0.5, out_queue_depth=50.0,
        in_cooldown_s=0.2, cold_timeout_s=120.0))
    fm.autoscaler = auto
    pool = fm.pool
    try:
        h = _submit(fm, "one request so idle_s measures from real work")
        h.result(timeout=120)
        assert h.finish_reason in ("stop", "length")

        zeroed = _tick_until(
            auto, lambda: not pool.healthy("decode"), timeout=60.0)
        assert zeroed, "idle fleet never reached zero"
        assert auto.decisions["scale_to_zero"] >= 1
        assert auto.decisions["scale_in"] == 0  # only path to zero
        assert auto.target == 0

        h = _submit(fm, "the request that wakes the fleet back up")
        h.result(timeout=120)
        assert h.finish_reason in ("stop", "length")
        assert auto.decisions["cold_start"] >= 1
        assert len(pool.healthy("decode")) == 1 and auto.target >= 1
    finally:
        auto.stop()
        fm.close()


def test_hot_swap_replaces_generation_and_keeps_capacity():
    """fm.swap() (the POST /v1/fleet/{model}/swap backend) boots a new
    replica generation, drains the old one, and leaves capacity and
    serving intact — the deploy primitive in miniature."""
    fm = _build_fleet(replicas=2)
    try:
        for h in [_submit(fm, f"warm the pool {i}") for i in range(2)]:
            h.result(timeout=120)
        old = {r.id for r in fm.pool.healthy("decode")}
        res = fm.swap(timeout=30.0)
        assert res["ok"], res
        now = {r.id for r in fm.pool.healthy("decode")}
        assert now and not (now & old)
        assert len(now) == len(old)
        h = _submit(fm, "post-swap traffic still serves")
        h.result(timeout=120)
        assert h.finish_reason in ("stop", "length")
    finally:
        fm.close()
