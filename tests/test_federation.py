"""Federation router: registry, balancing, failover, announcement
(parity: /root/reference/core/p2p/federated.go:39-118 selection +
request table; federated_server.go proxy loop)."""

import asyncio
import threading
import time

import httpx
import pytest
from aiohttp import web

from localai_tpu.federation import FederatedServer, announce


class _AppThread:
    """Any aiohttp app on a random port, in its own loop thread."""

    def __init__(self, app: web.Application):
        self.port = None
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(app,), daemon=True
        )
        self._thread.start()
        assert self._started.wait(15), "app failed to start"

    def _run(self, app):
        asyncio.set_event_loop(self._loop)

        async def boot():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            self.port = runner.addresses[0][1]
            self._runner = runner
            self._started.set()

        self._loop.run_until_complete(boot())
        self._loop.run_forever()

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def stop(self):
        if getattr(self, "_stopped", False):
            return
        self._stopped = True

        async def down():
            await self._runner.cleanup()

        fut = asyncio.run_coroutine_threadsafe(down(), self._loop)
        fut.result(10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(10)


def _instance_app(name: str) -> web.Application:
    """A stub LocalAI instance: /healthz + an identifying endpoint + SSE."""
    app = web.Application()

    async def healthz(_):
        return web.json_response({"status": "ok"})

    async def whoami(request):
        return web.json_response({
            "instance": name, "path": str(request.rel_url),
            "echo": (await request.text()) or None,
        })

    async def sse(_):
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream"})
        await resp.prepare(_)
        for i in range(3):
            await resp.write(f"data: {name}-{i}\n\n".encode())
        await resp.write_eof()
        return resp

    app.router.add_get("/healthz", healthz)
    app.router.add_route("*", "/sse", sse)
    app.router.add_route("*", "/{tail:.*}", whoami)
    return app


@pytest.fixture()
def cluster():
    """Two stub instances + a router in front."""
    a = _AppThread(_instance_app("a"))
    b = _AppThread(_instance_app("b"))
    fed = FederatedServer([a.addr, b.addr], load_balanced=True,
                          health_interval=0.2)
    router = _AppThread(fed.create_app())
    yield a, b, fed, router
    for srv in (router, a, b):
        srv.stop()


# -- selection unit tests (federated.go:40-101) -----------------------------


def test_least_used_selection():
    fed = FederatedServer(["n1:1", "n2:1"], load_balanced=True)
    n1, n2 = fed.nodes()
    n1.requests_served = 5
    assert fed.select() is n2
    n2.requests_served = 9
    assert fed.select() is n1


def test_offline_nodes_excluded_and_target_pinning():
    fed = FederatedServer(["n1:1", "n2:1"], load_balanced=True)
    n1, n2 = fed.nodes()
    fed.mark_offline(n1)
    assert fed.select() is n2
    fed.mark_offline(n2)
    assert fed.select() is None

    pinned = FederatedServer(["n1:1", "n2:1"], worker_target="n2:1")
    assert pinned.select().id == "n2:1"
    pinned.mark_offline(pinned.select())
    assert pinned.select() is None  # target down ≠ silently rerouted


def test_register_is_idempotent_and_revives():
    fed = FederatedServer([])
    n = fed.register("127.0.0.1:9000")
    fed.mark_offline(n)
    again = fed.register("http://127.0.0.1:9000")
    assert again is n
    assert n.online
    assert len(fed.nodes()) == 1


# -- end-to-end proxy behavior ----------------------------------------------


def test_proxy_balances_over_instances(cluster):
    a, b, fed, router = cluster
    with httpx.Client(base_url=f"http://{router.addr}",
                      timeout=10.0) as c:
        seen = set()
        for _ in range(6):
            r = c.post("/v1/chat/completions", json={"x": 1})
            assert r.status_code == 200
            seen.add(r.json()["instance"])
            assert r.headers["X-Federated-Node"] in (a.addr, b.addr)
        # least-used over two equal nodes must use both
        assert seen == {"a", "b"}
        # body and path pass through untouched
        r = c.post("/v1/some/path?q=2", content=b"payload")
        assert r.json()["path"] == "/v1/some/path?q=2"
        assert r.json()["echo"] == "payload"


def test_proxy_streams_sse(cluster):
    _, _, _, router = cluster
    with httpx.Client(base_url=f"http://{router.addr}",
                      timeout=10.0) as c:
        with c.stream("GET", "/sse") as r:
            lines = [ln for ln in r.iter_lines() if ln]
        assert len(lines) == 3
        assert all(ln.startswith("data: ") for ln in lines)


def test_failover_when_node_dies(cluster):
    a, b, fed, router = cluster
    with httpx.Client(base_url=f"http://{router.addr}",
                      timeout=10.0) as c:
        b_node = next(n for n in fed.nodes() if n.id == b.addr)
        b.stop()
        # force selection toward the dead node first: it has fewer requests
        for n in fed.nodes():
            n.requests_served = 0
        b_node.requests_served = -1
        r = c.get("/v1/anything")
        assert r.status_code == 200
        assert r.json()["instance"] == "a"   # failed over transparently
        assert not b_node.online
        # with every node down, a clean 503 (not a hang)
        a.stop()
        r = c.get("/v1/anything")
        assert r.status_code == 503


def test_nodes_endpoint_and_registration_token(cluster):
    a, b, fed, router = cluster
    fed.peer_token = "sekrit"
    with httpx.Client(base_url=f"http://{router.addr}",
                      timeout=10.0) as c:
        nodes = c.get("/federated/nodes").json()["nodes"]
        assert {n["id"] for n in nodes} == {a.addr, b.addr}
        r = c.post("/federated/register",
                   json={"address": "127.0.0.1:1"})
        assert r.status_code == 401
        r = c.post("/federated/register",
                   json={"address": "127.0.0.1:1"},
                   headers={"Authorization": "Bearer sekrit"})
        assert r.status_code == 200
        assert len(fed.nodes()) == 3


def test_register_rejects_unroutable_addresses(cluster):
    """Hardened register: an advertised address that is unroutable BY
    CONSTRUCTION (empty host, missing/zero port, wildcard bind) is a 400,
    never a registry entry — it could only ever seed a permanently
    offline node."""
    a, b, fed, router = cluster
    with httpx.Client(base_url=f"http://{router.addr}",
                      timeout=10.0) as c:
        before = len(fed.nodes())
        for bad in (":8080",            # empty host
                    "127.0.0.1:0",      # port 0
                    "127.0.0.1",        # no port at all
                    "127.0.0.1:http",   # garbage port
                    "127.0.0.1:70000",  # out of range
                    "0.0.0.0:8080",     # wildcard bind address
                    "[::]:8080"):
            r = c.post("/federated/register", json={"address": bad})
            assert r.status_code == 400, (bad, r.status_code)
        assert len(fed.nodes()) == before
        # a well-formed address still lands (incl. IPv6 literal)
        assert c.post("/federated/register",
                      json={"address": "[::1]:9001"}).status_code == 200


def test_validate_advertised_address_unit():
    from localai_tpu.federation.server import validate_advertised_address

    assert validate_advertised_address("127.0.0.1:8080")
    assert validate_advertised_address("http://node-7:9090")
    assert validate_advertised_address("[::1]:9001")
    for bad in ("", ":1", "host:", "host:0", "0.0.0.0:5", "*:5",
                "https://:8080", "host:-1"):
        with pytest.raises(ValueError):
            validate_advertised_address(bad)


def test_evict_then_rejoin_resets_failure_count(cluster):
    """Offline-eviction parity with the fleet pool: a node's failure
    count survives while it is offline but RESETS the moment it rejoins
    (re-register or health-loop revival) — mirror of
    ReplicaPool._note_rejoined, so the next incident escalates from a
    clean slate."""
    a, b, fed, router = cluster
    node = next(n for n in fed.nodes() if n.id == a.addr)
    fed.mark_offline(node)
    fed.mark_offline(node)
    assert node.failures == 2 and not node.online
    # rejoin path 1: explicit re-register
    again = fed.register(a.addr)
    assert again is node and node.online and node.failures == 0

    # rejoin path 2: the health loop revives a node that answers again
    fed.mark_offline(node)
    assert node.failures == 1
    asyncio.run(_one_health_pass(fed))
    assert node.online and node.failures == 0


async def _one_health_pass(fed):
    from aiohttp import ClientSession

    async with ClientSession() as session:
        await fed.check_health(session)


def test_health_loop_counts_failures_while_offline():
    """Failed sweeps advance the failure count (the eviction signal);
    only a rejoin clears it."""
    fed = FederatedServer(["127.0.0.1:1"], health_interval=60)
    node = fed.nodes()[0]
    asyncio.run(_one_health_pass(fed))
    asyncio.run(_one_health_pass(fed))
    assert not node.online and node.failures == 2


def test_announce_retries_until_router_up():
    stub = _AppThread(_instance_app("solo"))
    fed = FederatedServer([], peer_token="tok", health_interval=0.2)
    router = _AppThread(fed.create_app())
    try:
        announce(f"http://{router.addr}", f"http://{stub.addr}",
                 peer_token="tok", retries=10, interval=0.1)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not fed.nodes():
            time.sleep(0.05)
        assert [n.id for n in fed.nodes()] == [stub.addr]
    finally:
        router.stop()
        stub.stop()


def test_explorer_renders_router_nodes():
    """`explorer` dashboard over a router's registry (parity:
    core/explorer + explorer.html, re-pointed at federation)."""
    from localai_tpu.federation.explorer import create_explorer_app

    fed = FederatedServer(["n1:9991", "n2:9992"], health_interval=60)
    router = _AppThread(fed.create_app())
    explorer = _AppThread(create_explorer_app(f"http://{router.addr}"))
    try:
        with httpx.Client(timeout=10.0) as c:
            page = c.get(f"http://{explorer.addr}/")
            assert page.status_code == 200
            assert "n1:9991" in page.text and "n2:9992" in page.text
            api = c.get(f"http://{explorer.addr}/api/nodes").json()
            assert len(api["nodes"]) == 2
    finally:
        explorer.stop()
        router.stop()


def test_explorer_multi_network_db_and_eviction(tmp_path):
    """VERDICT r4 #10: multi-router token database + dial-test monitor with
    failure-count eviction (parity: core/explorer/discovery.go:16-30)."""
    from localai_tpu.federation.explorer import DiscoveryMonitor, ExplorerDB

    db = ExplorerDB(tmp_path / "networks.json")
    db.add("http://127.0.0.1:1", name="dead")
    mon = DiscoveryMonitor(db, interval=3600, failure_threshold=3,
                           timeout=0.3)

    fed = FederatedServer(["live:9993"], health_interval=60)
    router = _AppThread(fed.create_app())
    try:
        db.add(f"http://{router.addr}", name="live-net")
        mon.poll_once()
        st = mon.state()
        assert st[f"http://{router.addr}"]["ok"]
        assert len(st[f"http://{router.addr}"]["nodes"]) == 1
        assert db.entries()["http://127.0.0.1:1"]["failures"] == 1
        # two more failed sweeps evict the dead network
        mon.poll_once()
        mon.poll_once()
        assert "http://127.0.0.1:1" not in db.routers()
        assert f"http://{router.addr}" in db.routers()
        # persistence survives a restart
        db2 = ExplorerDB(tmp_path / "networks.json")
        assert db2.routers() == [f"http://{router.addr}"]
    finally:
        router.stop()


def test_explorer_network_registration_api(tmp_path):
    from localai_tpu.federation.explorer import create_explorer_app

    fed = FederatedServer(["apinode:9994"], health_interval=60)
    router = _AppThread(fed.create_app())
    explorer = _AppThread(create_explorer_app(
        db_path=str(tmp_path / "db.json"), interval=3600))
    try:
        with httpx.Client(timeout=10.0) as c:
            base = f"http://{explorer.addr}"
            r = c.post(f"{base}/api/networks",
                       json={"url": f"http://{router.addr}",
                             "name": "test-net"})
            assert r.status_code == 200
            assert c.post(f"{base}/api/networks",
                          json={"url": "ftp://nope"}).status_code == 400
            # dashboard dial-tests on first render and shows the nodes
            page = c.get(f"{base}/")
            assert "test-net" in page.text and "apinode:9994" in page.text
            nets = c.get(f"{base}/api/networks").json()["networks"]
            assert len(nets) == 1 and nets[0]["ok"]
            assert c.delete(
                f"{base}/api/networks",
                params={"url": f"http://{router.addr}"}).status_code == 200
            assert c.get(f"{base}/api/networks").json()["networks"] == []
    finally:
        explorer.stop()
        router.stop()


def test_explorer_warmup_is_concurrent_and_deadline_bounded(tmp_path):
    """ADVICE r5 #2: the first-render warm-up dials unchecked routers
    concurrently under ONE overall deadline instead of 5 s sequential
    timeouts per dead router — several dead networks must not stall the
    dashboard for tens of seconds."""
    import time as _time

    from localai_tpu.federation.explorer import DiscoveryMonitor, ExplorerDB

    db = ExplorerDB(tmp_path / "warm.json")
    # RFC 5737 TEST-NET addresses: connects hang until the dial timeout
    dead = [f"http://192.0.2.{i}:9" for i in range(1, 5)]
    for u in dead:
        db.add(u)
    mon = DiscoveryMonitor(db, interval=3600, failure_threshold=3,
                           timeout=5.0)
    t0 = _time.monotonic()
    mon.warmup(set(dead), deadline=1.0, count_failures=False)
    elapsed = _time.monotonic() - t0
    # sequential dials would be ~4 × min(5, deadline); concurrent ones are
    # bounded by the single deadline (generous margin for slow CI)
    assert elapsed < 3.0, f"warmup took {elapsed:.1f}s — not concurrent"
    # page-load warm-ups never advance eviction counters
    for u in dead:
        assert db.entries()[u]["failures"] == 0
    assert u in db.routers()


def test_explorer_warmup_fills_state_for_live_router(tmp_path):
    from localai_tpu.federation.explorer import DiscoveryMonitor, ExplorerDB

    fed = FederatedServer(["warm:9995"], health_interval=60)
    router = _AppThread(fed.create_app())
    try:
        db = ExplorerDB(tmp_path / "warm2.json")
        url = f"http://{router.addr}"
        db.add(url, name="warm-net")
        mon = DiscoveryMonitor(db, interval=3600, failure_threshold=3,
                               timeout=5.0)
        assert mon.state() == {}
        mon.warmup({url}, deadline=3.0)
        st = mon.state()
        assert st[url]["ok"] and len(st[url]["nodes"]) == 1
    finally:
        router.stop()
