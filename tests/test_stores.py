"""Vector store + rerank: library semantics, gRPC worker, HTTP API.

Parity model: the reference's stores integration test spawns the real
local-store backend and drives Set/Get/Find via the client
(/root/reference/tests/integration/stores_test.go); here the same flow
runs against the StoreServicer over real gRPC plus the HTTP endpoints.
"""

import numpy as np
import pytest

from localai_tpu.stores import StoreRegistry, VectorStore


@pytest.fixture()
def store():
    return VectorStore()


def test_set_get_delete(store):
    store.set([[1, 0, 0], [0, 1, 0]], [b"a", b"b"])
    assert len(store) == 2

    keys, values = store.get([[1, 0, 0], [0, 0, 1]])
    assert values[0] == b"a"
    assert values[1] is None

    # upsert by exact key
    store.set([[1, 0, 0]], [b"a2"])
    assert len(store) == 2
    _, values = store.get([[1, 0, 0]])
    assert values[0] == b"a2"

    assert store.delete([[1, 0, 0]]) == 1
    assert store.delete([[1, 0, 0]]) == 0
    assert len(store) == 1


def test_find_cosine_order(store):
    store.set(
        [[1, 0, 0], [0.9, 0.1, 0], [0, 1, 0], [-1, 0, 0]],
        [b"east", b"mostly-east", b"north", b"west"],
    )
    keys, values, sims = store.find([1, 0, 0], 3)
    assert values == [b"east", b"mostly-east", b"north"]
    assert sims[0] == pytest.approx(1.0, abs=1e-5)
    assert sims == sorted(sims, reverse=True)
    # deleted rows never come back
    store.delete([[1, 0, 0]])
    _, values, _ = store.find([1, 0, 0], 3)
    assert b"east" not in values


def test_find_topk_larger_than_store(store):
    store.set([[1, 0]], [b"only"])
    keys, values, sims = store.find([1, 0], 10)
    assert values == [b"only"]


def test_dim_mismatch(store):
    store.set([[1, 0, 0]], [b"x"])
    with pytest.raises(ValueError, match="dim"):
        store.set([[1, 0]], [b"y"])


def test_growth_reuses_padding(store):
    rng = np.random.default_rng(0)
    for i in range(20):
        store.set([rng.normal(size=4)], [f"v{i}".encode()])
    _, values, sims = store.find(rng.normal(size=4), 5)
    assert len(values) == 5
    assert sims == sorted(sims, reverse=True)


def test_registry():
    reg = StoreRegistry()
    a = reg.get("a")
    assert reg.get("a") is a
    assert reg.get("b") is not a
    assert reg.drop("a")
    assert not reg.drop("a")


def test_store_worker_grpc():
    """The standalone store servicer over real gRPC."""
    from localai_tpu.worker import WorkerClient
    from localai_tpu.worker.server import StoreServicer, serve_worker

    server, port = serve_worker("127.0.0.1:0", servicer=StoreServicer(),
                                block=False)
    try:
        c = WorkerClient(f"127.0.0.1:{port}")
        assert c.health()
        c.stores_set([[1, 0], [0, 1]], [b"x", b"y"])
        got = c.stores_get([[1, 0]])
        assert got.values[0].bytes == b"x"
        found = c.stores_find([1, 0.1], 2)
        assert found.values[0].bytes == b"x"
        assert list(found.similarities) == sorted(found.similarities,
                                                  reverse=True)
        c.stores_delete([[1, 0]])
        assert len(c.stores_get([[1, 0]]).values) == 0
        c.close()
    finally:
        server.stop(grace=None)


def test_stores_and_rerank_http(tmp_path):
    from tests.test_api import _ServerThread, make_state
    import httpx

    state = make_state(tmp_path, write_tiny=True)
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=120.0) as client:
            r = client.post("/stores/set", json={
                "keys": [[1, 0], [0, 1]], "values": ["alpha", "beta"]})
            assert r.status_code == 200, r.text
            r = client.post("/stores/find", json={"key": [1, 0.2],
                                                  "topk": 1})
            assert r.json()["values"] == ["alpha"]
            r = client.post("/stores/get", json={"keys": [[0, 1]]})
            assert r.json()["values"] == ["beta"]
            r = client.post("/stores/delete", json={"keys": [[0, 1]]})
            assert r.status_code == 200
            r = client.post("/stores/get", json={"keys": [[0, 1]]})
            assert r.json()["values"] == []

            # rerank rides the tiny model's embedding path
            r = client.post("/v1/rerank", json={
                "model": "tiny",
                "query": "hello world",
                "documents": ["hello world", "completely different",
                              "hello there"],
                "top_n": 2,
            })
            assert r.status_code == 200, r.text
            body = r.json()
            assert len(body["results"]) == 2
            scores = [x["relevance_score"] for x in body["results"]]
            assert scores == sorted(scores, reverse=True)
            assert body["usage"]["total_tokens"] > 0

            r = client.post("/v1/rerank", json={"model": "tiny"})
            assert r.status_code == 400
    finally:
        srv.stop()
