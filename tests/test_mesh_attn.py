"""Pallas flash attention under a mesh + ring-attention serving path.

VERDICT r2 weak #1/#2: the flash kernels used to switch off the moment a
mesh appeared, and parallel.ring was reachable only from tests. Now the
kernels run per-device via shard_map (slots on 'data', heads on 'model')
and long prompts route through sp_prefill_forward into the slot cache.
"""

import jax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models.registry import resolve_model
from localai_tpu.parallel import sharding as shd
from localai_tpu.parallel.mesh import MeshPlan, build_mesh


@pytest.fixture(scope="module")
def small():
    return resolve_model("debug:small")


@pytest.fixture(scope="module")
def ref_seq(small):
    """Greedy reference from the single-device XLA runner."""
    r = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=512,
                    prefill_buckets=[64, 256])
    s = r.acquire_slot()
    p = list(range(1, 50))
    return [r.admit(s, p, temperature=0.0)] + [int(r.step()[s])
                                               for _ in range(6)]


def test_pallas_kernels_active_under_mesh(small):
    """attn_impl stays 'pallas' when heads divide the TP axis — the r2
    regression was a blanket mesh→XLA fallback."""
    mesh = build_mesh(MeshPlan(data=2, model=4))
    sp = shd.shard_params(small.params, small.cfg, mesh)
    r = ModelRunner(small.cfg, sp, num_slots=4, max_ctx=256,
                    prefill_buckets=[64], mesh=mesh,
                    attn_impl="pallas_interpret")
    assert r.attn_impl == "pallas"
    assert r.decode_attn_impl == "pallas"


def test_pallas_mesh_greedy_parity(small, ref_seq):
    mesh = build_mesh(MeshPlan(data=2, model=4))
    sp = shd.shard_params(small.params, small.cfg, mesh)
    r = ModelRunner(small.cfg, sp, num_slots=4, max_ctx=512,
                    prefill_buckets=[64, 256], mesh=mesh,
                    attn_impl="pallas_interpret")
    s = r.acquire_slot()
    p = list(range(1, 50))
    out = [r.admit(s, p, temperature=0.0)] + [int(r.step()[s])
                                              for _ in range(6)]
    assert out == ref_seq


def test_pallas_mesh_falls_back_when_heads_dont_divide(small):
    """debug:small has 4 kv heads; tp=8 can't split them — XLA path with a
    log, not a wrong kernel."""
    mesh = build_mesh(MeshPlan(model=8))
    sp = shd.shard_params(small.params, small.cfg, mesh)
    r = ModelRunner(small.cfg, sp, num_slots=8, max_ctx=256,
                    prefill_buckets=[64], mesh=mesh,
                    attn_impl="pallas_interpret")
    assert r.attn_impl == "xla"


def test_sp_prefill_serves_long_prompt(small):
    """Prompts ≥ sp_threshold on a seq-mesh take the ring-attention prefill
    (runner.last_prefill_path == 'sp') and continue bit-exact vs the
    single-device runner."""
    mesh = build_mesh(MeshPlan(seq=8))
    repl = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P())), small.params
    )
    r = ModelRunner(small.cfg, repl, num_slots=2, max_ctx=512,
                    prefill_buckets=[64, 256], mesh=mesh, sp_threshold=100)
    assert r.sp_enabled
    p = list(range(1, 201))
    s = r.acquire_slot()
    out = [r.admit(s, p, temperature=0.0)] + [int(r.step()[s])
                                              for _ in range(6)]
    assert r.last_prefill_path == "sp"

    rx = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=512,
                     prefill_buckets=[64, 256])
    s2 = rx.acquire_slot()
    ref = [rx.admit(s2, p, temperature=0.0)] + [int(rx.step()[s2])
                                                for _ in range(6)]
    assert rx.last_prefill_path == "full"
    assert out == ref


def test_sp_short_prompt_uses_full_prefill(small):
    mesh = build_mesh(MeshPlan(seq=8))
    repl = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P())), small.params
    )
    r = ModelRunner(small.cfg, repl, num_slots=2, max_ctx=512,
                    prefill_buckets=[64, 256], mesh=mesh, sp_threshold=100)
    s = r.acquire_slot()
    r.admit(s, list(range(1, 40)), temperature=0.0)
    assert r.last_prefill_path == "full"


def test_sp_through_build_serving_model(tmp_path):
    """sequence_parallel_size in the YAML opens the SP route end-to-end
    through the scheduler."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.models.manager import build_serving_model

    mcfg = ModelConfig(
        name="sp", model="debug:small", context_size=512,
        sharding={"sequence_parallel_size": 8},
        engine={"max_slots": 2, "prefill_buckets": [64, 256],
                "sp_prefill_threshold": 100},
    )
    sm = build_serving_model(mcfg, AppConfig(model_path=str(tmp_path)))
    try:
        assert sm.runner.sp_enabled
        h = sm.scheduler.submit(GenRequest(
            prompt=list(range(1, 201)), max_new_tokens=4, temperature=0.0,
        ))
        h.result(timeout=120)
        assert h.finish_reason in ("stop", "length")
        assert sm.runner.last_prefill_path == "sp"
    finally:
        sm.scheduler.shutdown()


def test_int8_engine_prefix_resume_under_mesh(small):
    """VERDICT r3 #10: the quantized engine and the prefix-resume admit
    path exercised under a 2×2 mesh — greedy output must match the
    unsharded int8 runner, and the second admit must reuse the prefix."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    from localai_tpu.models.quant import quantize_params

    qp = quantize_params(small.params)
    prompt1 = list(range(1, 50))
    prompt2 = prompt1 + [60, 61, 62, 63]

    def drive(runner):
        s = runner.acquire_slot()
        out1 = [runner.admit(s, prompt1, temperature=0.0)]
        out1 += [int(runner.step()[s]) for _ in range(4)]
        resident = prompt1 + out1
        runner.release(s)
        s2 = runner.acquire_slot(s)
        out2 = [runner.admit(s2, prompt2, resident=resident,
                             temperature=0.0)]
        out2 += [int(runner.step()[s2]) for _ in range(4)]
        return out1, out2, runner.last_prefix_reused

    ref1, ref2, _ = drive(ModelRunner(
        small.cfg, qp, num_slots=4, max_ctx=256, prefill_buckets=[64],
        kv_dtype="int8"))

    mesh = build_mesh(MeshPlan(data=2, model=2), devices=jax.devices()[:4])
    sp = shd.shard_params(qp, small.cfg, mesh)
    got1, got2, reused = drive(ModelRunner(
        small.cfg, sp, num_slots=4, max_ctx=256, prefill_buckets=[64],
        kv_dtype="int8", mesh=mesh))

    assert reused >= 16  # the resume path actually engaged under the mesh
    assert got1 == ref1
    assert got2 == ref2


def test_sp_prefill_composes_with_tp(small):
    """TP×SP at the engine level (VERDICT r4 #4): a seq=4 × model=2 mesh
    serves a long prompt through the ring-attention prefill with
    'model'-sharded weights, matching the unsharded greedy output."""
    mesh = build_mesh(MeshPlan(seq=4, model=2))
    sp = shd.shard_params(small.params, small.cfg, mesh)
    r = ModelRunner(small.cfg, sp, num_slots=2, max_ctx=512,
                    prefill_buckets=[64, 256], mesh=mesh, sp_threshold=100)
    assert r.sp_enabled
    p = list(range(1, 201))
    s = r.acquire_slot()
    out = [r.admit(s, p, temperature=0.0)] + [int(r.step()[s])
                                              for _ in range(6)]
    assert r.last_prefill_path == "sp"

    rx = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=512,
                     prefill_buckets=[64, 256])
    s2 = rx.acquire_slot()
    ref = [rx.admit(s2, p, temperature=0.0)] + [int(rx.step()[s2])
                                                for _ in range(6)]
    assert out == ref


def test_sp_tp_gate_closed_for_indivisible_heads(small):
    """A config whose head counts don't divide the 'model' axis must keep
    the SP route closed instead of serving a wrong shard layout."""
    import dataclasses

    mesh = build_mesh(MeshPlan(seq=2, model=4))
    cfg = dataclasses.replace(small.cfg, num_kv_heads=3, num_heads=6,
                              head_dim=32)
    from localai_tpu.models import llama as mdl

    params = mdl.init_params(jax.random.key(1), cfg)
    # param_specs itself refuses this layout; replicate instead — the
    # runner must still keep the SP route closed
    repl = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P())), params
    )
    r = ModelRunner(cfg, repl, num_slots=2, max_ctx=256,
                    prefill_buckets=[64], mesh=mesh, sp_threshold=100)
    assert not r.sp_enabled
