"""VITS neural TTS: numerical parity against the torch reference
implementation (transformers.VitsModel) on tiny random checkpoints, plus
loader/tokenizer behavior. This pins the JAX port layer-for-layer — the
strongest correctness evidence available without real voice downloads."""

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import VitsConfig as HFVitsConfig  # noqa: E402
from transformers import VitsModel  # noqa: E402

from localai_tpu.audio.vits import (  # noqa: E402
    VitsCharTokenizer,
    VitsConfig,
    VitsTTS,
    _P,
    load_hf_vits,
)

TINY = dict(
    vocab_size=24,
    hidden_size=16,
    num_hidden_layers=2,
    num_attention_heads=2,
    window_size=4,
    ffn_dim=32,
    flow_size=8,
    spectrogram_bins=9,
    prior_encoder_num_flows=2,
    prior_encoder_num_wavenet_layers=2,
    duration_predictor_num_flows=2,
    duration_predictor_filter_channels=16,
    depth_separable_num_layers=2,
    upsample_initial_channel=32,
    upsample_rates=[4, 4],
    upsample_kernel_sizes=[8, 8],
    resblock_kernel_sizes=[3, 5],
    resblock_dilation_sizes=[[1, 3], [1, 3]],
    sampling_rate=16000,
)


def _build_torch_model(use_sdp: bool, seed: int = 0):
    torch.manual_seed(seed)
    hf_cfg = HFVitsConfig(
        **TINY, use_stochastic_duration_prediction=use_sdp,
    )
    model = VitsModel(hf_cfg).eval()
    model.noise_scale = 0.0
    model.noise_scale_duration = 0.0
    return hf_cfg, model


def _jax_tts(hf_cfg, model) -> VitsTTS:
    state = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    cfg = VitsConfig.from_hf(hf_cfg.to_dict())
    return VitsTTS(cfg, _P(state), tokenizer=None)


@pytest.mark.parametrize("use_sdp", [False, True],
                         ids=["deterministic-dp", "stochastic-dp"])
def test_waveform_matches_torch(use_sdp):
    hf_cfg, model = _build_torch_model(use_sdp)
    tts = _jax_tts(hf_cfg, model)

    ids = torch.tensor([[1, 5, 9, 3, 7, 2, 11, 4]])
    with torch.no_grad():
        want = model(ids).waveform.numpy()[0]

    got = tts._forward(
        ids.numpy(), np.ones(ids.shape, np.float32),
        noise_scale=0.0, noise_scale_duration=0.0, speaking_rate=1.0,
        speaker_id=None, seed=0,
    )
    got = np.asarray(got[0], np.float32)
    assert got.shape == want.shape, (got.shape, want.shape)
    np.testing.assert_allclose(got, want, atol=2e-4)


def test_multispeaker_conditioning_matches_torch():
    torch.manual_seed(1)
    hf_cfg = HFVitsConfig(
        **TINY, use_stochastic_duration_prediction=False,
        num_speakers=3, speaker_embedding_size=8,
    )
    model = VitsModel(hf_cfg).eval()
    model.noise_scale = 0.0
    model.noise_scale_duration = 0.0
    tts = _jax_tts(hf_cfg, model)
    ids = torch.tensor([[2, 4, 6, 8]])
    for spk in (0, 2):
        with torch.no_grad():
            want = model(ids, speaker_id=spk).waveform.numpy()[0]
        got = np.asarray(tts._forward(
            ids.numpy(), np.ones(ids.shape, np.float32),
            noise_scale=0.0, noise_scale_duration=0.0,
            speaking_rate=1.0, speaker_id=spk, seed=0,
        )[0], np.float32)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=2e-4)


def test_checkpoint_dir_loading_and_synthesis(tmp_path):
    """Full load path: config.json + safetensors (with weight-norm keys
    as torch saves them) + vocab.json → audible output."""
    from safetensors.numpy import save_file

    hf_cfg, model = _build_torch_model(use_sdp=True)
    state = {k: v.detach().numpy().copy()
             for k, v in model.state_dict().items()}
    d = tmp_path / "voice"
    d.mkdir()
    save_file(state, d / "model.safetensors")
    (d / "config.json").write_text(json.dumps(
        {"model_type": "vits", **hf_cfg.to_dict()}, default=str))
    vocab = {ch: i for i, ch in enumerate("<pad> abcdefghijklmnopq")}
    vocab["<pad>"] = 0
    (d / "vocab.json").write_text(json.dumps(vocab))
    (d / "tokenizer_config.json").write_text(json.dumps({
        "do_lower_case": True, "add_blank": True, "pad_token": "<pad>",
    }))

    tts = load_hf_vits(d)
    wav = tts.synthesize("abc def", noise_scale=0.0,
                         noise_scale_duration=0.0)
    assert wav.dtype == np.float32
    assert wav.size > 100
    assert np.isfinite(wav).all()
    assert np.abs(wav).max() <= 1.0
    # deterministic at zero noise
    wav2 = tts.synthesize("abc def", noise_scale=0.0,
                          noise_scale_duration=0.0)
    np.testing.assert_array_equal(wav, wav2)


def test_char_tokenizer_interspersal(tmp_path):
    (tmp_path / "vocab.json").write_text(json.dumps(
        {"<pad>": 0, "a": 1, "b": 2}))
    (tmp_path / "tokenizer_config.json").write_text(json.dumps(
        {"do_lower_case": True, "add_blank": True, "pad_token": "<pad>"}))
    tok = VitsCharTokenizer(tmp_path)
    # blanks interspersed around every kept char; unknown chars dropped
    assert tok.encode("aB!") == [0, 1, 0, 2, 0]
    assert tok.encode("??") == [0, 0, 0]  # pad fallback, then blanks


def test_tts_endpoint_routes_to_vits(tmp_path):
    """A vits checkpoint config serves /v1/audio/speech through the
    neural path (parity: the piper TTS backend routing)."""
    import httpx
    from safetensors.numpy import save_file
    from test_api import _ServerThread, make_state

    hf_cfg, model = _build_torch_model(use_sdp=True)
    d = tmp_path / "voice-ckpt"
    d.mkdir()
    save_file({k: v.detach().numpy().copy()
               for k, v in model.state_dict().items()},
              d / "model.safetensors")
    (d / "config.json").write_text(json.dumps(
        {"model_type": "vits", **hf_cfg.to_dict()}, default=str))
    vocab = {ch: i for i, ch in enumerate("<pad> abcdefghijklmnopq")}
    vocab["<pad>"] = 0
    (d / "vocab.json").write_text(json.dumps(vocab))
    (tmp_path / "voice.yaml").write_text("name: voice\nmodel: voice-ckpt\n")
    srv = _ServerThread(make_state(tmp_path))
    try:
        # autodetect routed the bare YAML to the vits backend
        assert srv.state.loader.get("voice").backend == "vits"
        with httpx.Client(base_url=srv.base, timeout=120.0) as c:
            r = c.post("/tts", json={"model": "voice", "input": "abc"})
            assert r.status_code == 200, r.text
            assert r.content[:4] == b"RIFF"
            assert len(r.content) > 500
    finally:
        srv.stop()
