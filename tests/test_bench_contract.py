"""Regression tests for bench.py's one-JSON-line contract.

BENCH r03 crashed with rc=1 and NO metric line: the first eager device
dispatch — a ``convert_element_type`` cast on the quantized/bf16 boundary
inside synthetic weight generation — exploded on an unavailable backend
before any guard existed, and the traceback escaped the process. The
contract under test: **bench.py always exits 0 and always prints exactly
one JSON line**, with the failure diagnosed in ``note``/``device_health``
instead of a traceback. The quantized decode path itself (weight-gen →
int8-KV runner → batched decode, the chain the r03 cast sat on) is pinned
by an in-process CPU run.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_bench_prints_one_json_line_on_dead_backend():
    """The r03 failure shape: first dispatch raises on backend init."""
    env = dict(os.environ)
    env.update({
        # an unavailable platform whose init fails fast (no GPU plugin
        # here) — the same class of failure as r03's dead axon tunnel
        "BENCH_PLATFORM": "cuda",
        "JAX_PLATFORMS": "",
        "BENCH_BUDGET_S": "90",
        "BENCH_PROBE_TIMEOUT_S": "20",
        "BENCH_STALL_S": "30",
        "BENCH_COMPILE_CACHE": "0",
        "BENCH_WEIGHT_CACHE": "0",
    })
    out = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=150,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, out.stdout
    row = json.loads(lines[0])
    assert row["value"] == 0.0
    assert row["unit"] == "tok/s"
    # the probe must have diagnosed the dead backend, not burned budget
    assert row.get("note"), row
    health = row.get("device_health", {})
    assert health.get("ok") is False, row


def test_bench_quantized_decode_path_runs_on_cpu():
    """The exact chain r03 died on — synthetic int8 weight generation into
    a bf16-compute, int8-KV runner, then batched decode — must run clean
    (dtype boundaries included) on the CPU backend."""
    sys.path.insert(0, str(REPO))
    import bench

    tok_s, info = bench.run_decode_bench(
        "tiny", "int8", steps=2, multi=1, depth=1,
        num_slots=2, max_ctx=256,
    )
    assert tok_s > 0
    # phase-provenance fields (ISSUE 14): every decode line must say
    # which kernel and KV dtype produced its number
    assert info["kernel_impl"] in ("pallas", "lax")
    assert info["kv_dtype"] == "int8"
    assert info["tokens_per_dispatch"] == 2
