"""Scheduler tests: continuous batching, streaming, stop handling — on the
tiny debug model (no downloads; SURVEY.md §4 fixture strategy)."""

import numpy as np
import pytest

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import (
    PRIORITY_BATCH,
    GenRequest,
    Scheduler,
)
from localai_tpu.engine.stream import IncrementalDetokenizer, StopChecker
from localai_tpu.models.registry import resolve_model
from localai_tpu.utils.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def sched():
    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(
        tiny.cfg, tiny.params, num_slots=4, max_ctx=96,
        prefill_buckets=[16, 32], kv_dtype="float32",
    )
    s = Scheduler(runner, ByteTokenizer())
    yield s
    s.shutdown()


def _req(text: str, **kw) -> GenRequest:
    tok = ByteTokenizer()
    return GenRequest(prompt=tok.encode(text), **kw)


def test_basic_generation(sched):
    h = sched.generate(_req("hello", max_new_tokens=8, temperature=0.0))
    assert h.finish_reason in ("length", "stop")
    assert h.completion_tokens <= 8
    assert h.prompt_tokens == 5


def test_streaming_deltas_concatenate_to_text(sched):
    h = sched.submit(_req("stream me", max_new_tokens=12, temperature=0.0))
    parts = [item.delta for item in h]
    assert "".join(parts) == h.text
    assert h.finish_reason is not None


def test_concurrent_requests_batch(sched):
    handles = [
        sched.submit(_req(f"request number {i}", max_new_tokens=10,
                          temperature=0.0))
        for i in range(6)  # > num_slots: exercises queueing
    ]
    for h in handles:
        h.result(timeout=60)
        assert h.finish_reason is not None
    # same prompt → same greedy output regardless of batch composition
    a = sched.generate(_req("determinism", max_new_tokens=6, temperature=0.0))
    b = sched.generate(_req("determinism", max_new_tokens=6, temperature=0.0))
    assert a.token_ids == b.token_ids


def test_max_tokens_respected(sched):
    h = sched.generate(_req("abc", max_new_tokens=3, temperature=0.0))
    assert h.completion_tokens <= 3


def test_usage_metrics(sched):
    before = sched.metrics()["total_generated_tokens"]
    h = sched.generate(_req("usage", max_new_tokens=4, temperature=0.0))
    m = sched.metrics()
    assert m["total_generated_tokens"] >= before + h.completion_tokens
    assert m["num_slots"] == 4


def test_cancellation(sched):
    h = sched.submit(_req("cancel me", max_new_tokens=500, temperature=0.0))
    h.cancel()
    h.result(timeout=60)
    assert h.finish_reason == "cancelled"


def test_logit_bias_forces_token(sched):
    # +100 bias on one byte forces greedy decode to pick it every step
    h = sched.generate(
        _req("force", max_new_tokens=4, temperature=0.0,
             logit_bias={65: 100.0})
    )
    assert all(t == 65 for t in h.token_ids)
    assert "AAAA".startswith(h.text[:4])


def test_stop_sequence():
    det = IncrementalDetokenizer(ByteTokenizer().decode)
    out = "".join(det.push(b) for b in b"hello STOP world")
    assert out == "hello STOP world"

    sc = StopChecker(["STOP"])
    emitted = sc.push("hello ST")
    assert "STOP"[: len("hello ST") - len(emitted)]  # holdback active
    emitted += sc.push("OP world")
    assert sc.stopped == "STOP"
    assert emitted == "hello "


def test_stop_checker_no_false_holdback():
    sc = StopChecker(["\n\n"])
    assert sc.push("abc") == "abc"
    assert sc.push("d\n") == "d"      # holds back the lone newline
    assert sc.push("e") == "\ne"      # released once disambiguated
    assert sc.stopped is None
    assert sc.flush() == ""


def test_incremental_detok_utf8_boundary():
    det = IncrementalDetokenizer(ByteTokenizer().decode)
    snowman = "☃".encode()  # 3 bytes
    outs = [det.push(b) for b in snowman]
    assert outs[0] == "" and outs[1] == ""
    assert outs[2] == "☃"


def test_constraint_masking(sched):
    class OnlyToken:
        """Allow exactly token 66 for 3 steps, then done."""

        def __init__(self, vocab):
            self.row = np.full(vocab, -1e30, np.float32)
            self.row[66] = 0.0
            self.steps = 0

        def allowed_mask(self):
            return self.row

        def advance(self, tid):
            self.steps += 1

        @property
        def done(self):
            return self.steps >= 3

    c = OnlyToken(512)
    h = sched.generate(
        _req("constrained", max_new_tokens=10, temperature=0.0, constraint=c)
    )
    assert h.token_ids == [66, 66, 66]
    assert h.finish_reason == "stop"


def test_mixed_constrained_and_unconstrained_batch(sched):
    """Per-slot constraint gating: a constrained request sharing the batch
    with unconstrained ones (the step_frozen_n path) must produce exactly its
    masked tokens — no duplicates from the frozen rows — while the
    unconstrained requests complete normally."""

    class OnlyToken:
        def __init__(self, vocab, tid, steps):
            self.row = np.full(vocab, -1e30, np.float32)
            self.row[tid] = 0.0
            self.limit = steps
            self.steps = 0

        def allowed_mask(self):
            return self.row

        def advance(self, tid):
            self.steps += 1

        @property
        def done(self):
            return self.steps >= self.limit

    free = [
        sched.submit(_req(f"free {i}", max_new_tokens=20, temperature=0.0))
        for i in range(2)
    ]
    con = sched.submit(
        _req("tool", max_new_tokens=10, temperature=0.0,
             constraint=OnlyToken(512, 66, 5))
    )
    assert con.result(60).token_ids == [66, 66, 66, 66, 66]
    for h in free:
        h.result(60)
        assert h.finish_reason is not None
        assert h.completion_tokens > 0


def test_seeded_output_independent_of_batch_composition(sched):
    """A seeded sampled request must emit the same tokens whether it runs
    alone or concurrently with other requests (PRNG key advances == tokens
    sampled). The regression this pins: a seeded+constrained slot riding a
    step_frozen_n dispatch used to advance its key on every frozen inner
    step (multi_step advances per consumed token) instead of once."""

    class AllowBand:
        """Allow a 20-token band (sampled, not forced) for `limit` steps."""

        def __init__(self, vocab, limit):
            self.row = np.full(vocab, -1e30, np.float32)
            self.row[60:80] = 0.0
            self.limit = limit
            self.steps = 0

        def allowed_mask(self):
            return self.row

        def advance(self, tid):
            self.steps += 1

        @property
        def done(self):
            return self.steps >= self.limit

    def run_seeded():
        return sched.generate(
            _req("seeded", max_new_tokens=6, temperature=1.0, seed=1234,
                 constraint=AllowBand(512, 6))
        ).token_ids

    solo = run_seeded()
    # noise requests large enough to stay in flight for the whole seeded
    # run, so the seeded slot really takes the frozen path; cancelled after
    noise = [
        sched.submit(_req(f"noise {i}", max_new_tokens=500, temperature=0.0))
        for i in range(2)
    ]
    mixed = run_seeded()
    for h in noise:
        h.cancel()
    for h in noise:
        h.result(60)
    assert len(solo) == 6
    assert all(60 <= t < 80 for t in solo)
    assert mixed == solo


def test_slot_reuse_resets_sampling_params(sched):
    """A reused slot must not inherit the previous request's options
    (regression: with_slot used to skip None fields)."""
    # saturate all 4 slots with greedy requests, then run a default-sampling
    # request; if temperature leaked it would decode greedily every time
    for _ in range(4):
        sched.generate(_req("warm", max_new_tokens=2, temperature=0.0))
    outs = {
        tuple(
            sched.generate(_req("q", max_new_tokens=6, seed=i)).token_ids
        )
        for i in range(6)
    }
    assert len(outs) > 1  # default temperature=1.0 sampling, not greedy


def test_constraint_mask_cleared_when_none(sched):
    class MaskThenFree:
        """Token 66 for 2 steps, then unconstrained (mask=None)."""

        def __init__(self, vocab):
            self.row = np.full(vocab, -1e30, np.float32)
            self.row[66] = 0.0
            self.steps = 0

        def allowed_mask(self):
            return self.row if self.steps < 2 else None

        def advance(self, tid):
            self.steps += 1

        @property
        def done(self):
            return False

    h = sched.generate(
        _req("free region", max_new_tokens=8, temperature=0.0,
             constraint=MaskThenFree(512))
    )
    assert h.token_ids[:2] == [66, 66]
    # after the mask clears, greedy decode must be able to leave token 66
    assert any(t != 66 for t in h.token_ids[2:])


def test_constrained_generation_valid_json(sched):
    """End-to-end grammar constraint through the live engine: the tiny
    random-weight model MUST emit schema-valid JSON when masked."""
    import json

    from localai_tpu.functions import constraint_for_schema

    schema = {
        "type": "object",
        "properties": {
            "name": {"const": "answer"},
            "arguments": {
                "type": "object",
                "properties": {"message": {"type": "string",
                                           "maxLength": 12}},
            },
        },
    }
    c = constraint_for_schema(schema, ByteTokenizer())
    h = sched.generate(
        _req("call a tool", max_new_tokens=120, temperature=0.8, seed=7,
             constraint=c),
        timeout=120,
    )
    obj = json.loads(h.text)
    assert obj["name"] == "answer"
    assert "message" in obj["arguments"]


# ---------------------------------------------------------------------------
# two-lane admission (interactive vs background batch)


def _wait(pred, timeout=60.0):
    import time as _time

    deadline = _time.monotonic() + timeout
    while _time.monotonic() < deadline:
        if pred():
            return True
        _time.sleep(0.01)
    return False


def test_batch_priority_request_completes(sched):
    # long enough to span several 16-step dispatches, so at least one
    # drain records the slot while the batch request still occupies it
    h = sched.generate(_req("background", max_new_tokens=48,
                            temperature=0.0, ignore_eos=True,
                            priority=PRIORITY_BATCH))
    assert h.finish_reason in ("length", "stop")
    assert h.completion_tokens > 0
    # the lane is tagged through to the flight ring
    assert any(r["batch_slots"] > 0 for r in sched.flight.snapshot())


def test_interactive_admitted_before_batch_under_full_queue(sched):
    """Admit ordering: with every slot occupied and both lanes queued,
    freed slots go to EVERY waiting interactive request before any batch
    line — batch work only fills slots when interactive queue depth is
    zero."""
    hold = [
        sched.submit(_req(f"hold {i}", max_new_tokens=500, temperature=0.0))
        for i in range(4)
    ]
    assert _wait(lambda: len(sched.metrics()["active_slots"]) == 4)
    # queue batch FIRST, interactive second — FIFO would admit the batch
    # lines first, the lane policy must not
    batch = [
        sched.submit(_req(f"batch {i}", max_new_tokens=4, temperature=0.0,
                          priority=PRIORITY_BATCH))
        for i in range(3)
    ]
    inter = [
        sched.submit(_req(f"inter {i}", max_new_tokens=4, temperature=0.0))
        for i in range(2)
    ]
    m = sched.metrics()
    assert m["batch_queue_depth"] >= 1  # lanes are accounted separately
    for h in hold:
        h.cancel()
    for h in inter + batch + hold:
        h.result(60)
    assert all(h.admit_index is not None for h in inter + batch)
    assert max(h.admit_index for h in inter) < \
        min(h.admit_index for h in batch)


def test_busy_covers_batch_lane(sched):
    assert not sched.busy
    h = sched.submit(_req("lane busy", max_new_tokens=4, temperature=0.0,
                          priority=PRIORITY_BATCH))
    assert sched.busy  # queued on the batch lane counts as busy
    h.result(60)
    assert _wait(lambda: not sched.busy)


def test_metrics_report_batch_lane_fields(sched):
    assert _wait(lambda: not sched.busy)
    m = sched.metrics()
    assert m["batch_queue_depth"] == 0 and m["batch_slots"] == 0


# ---------------------------------------------------------------------------
# adaptive streaming dispatch (delivery-lag bound)


def _bare_scheduler(multi_step=16, pipeline_depth=2, target=0.1):
    """Scheduler shell for unit-testing _effective_steps without an engine
    thread (the logic reads only these fields)."""
    import threading

    s = Scheduler.__new__(Scheduler)
    s.multi_step = multi_step
    s.pipeline_depth = pipeline_depth
    s.stream_latency_target = target
    s._step_ema = None
    s._lock = threading.Lock()
    s._slots = {}
    return s


def _fake_slot(stream: bool):
    from types import SimpleNamespace

    return SimpleNamespace(
        handle=SimpleNamespace(request=SimpleNamespace(stream=stream))
    )


def test_effective_steps_full_size_without_streams():
    s = _bare_scheduler()
    assert s._effective_steps() == 16            # idle engine
    s._slots[0] = _fake_slot(stream=False)
    s._step_ema = 0.05                           # slow steps, but batch-only
    assert s._effective_steps() == 16


def test_effective_steps_shrinks_for_streams():
    s = _bare_scheduler()
    s._slots[0] = _fake_slot(stream=True)
    # no timing sample yet → latency-safe single step
    assert s._effective_steps() == 1
    # budget = 0.1/2 = 50ms per dispatch
    s._step_ema = 0.001   # 1ms/token → 50 tokens fit → capped at multi_step
    assert s._effective_steps() == 16
    s._step_ema = 0.010   # 10ms/token → 5 fit → round DOWN to power of two
    assert s._effective_steps() == 4
    s._step_ema = 0.050   # 50ms/token → single-step dispatches
    assert s._effective_steps() == 1
    # a mixed batch with one stream still bounds the lag for everyone
    s._slots[1] = _fake_slot(stream=False)
    assert s._effective_steps() == 1


def test_streaming_request_bounds_delivery_lag(sched):
    """End-to-end: with an SSE stream attached, inter-delta delivery lag
    stays bounded (the dispatch size adapts down from multi_step=16)."""
    import time as _time

    h = sched.submit(_req("stream latency", max_new_tokens=24,
                          temperature=0.0, ignore_eos=True, stream=True))
    arrivals = []
    for item in h:
        arrivals.append(_time.monotonic())
    assert h.finish_reason is not None
    # the engine must have taken the adaptive path (a power of two ≤ 16),
    # and its own lag model — steps×depth×ema — must fit the target with
    # the step size it chose
    steps = sched.last_dispatch_steps
    assert steps in (1, 2, 4, 8, 16)
    if sched._step_ema is not None and steps > 1:
        assert steps * sched.pipeline_depth * sched._step_ema <= \
            2 * sched.stream_latency_target
    gaps = [b - a for a, b in zip(arrivals, arrivals[1:])]
    # generous wall-clock bound (CPU test machine, first-compile excluded
    # via median): the old fixed 16×2 dispatch would burst, not trickle
    gaps.sort()
    assert gaps[len(gaps) // 2] < 1.0
