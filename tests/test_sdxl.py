"""SDXL-class pipeline: dual text encoders (penultimate hidden concat +
projected pooled), text_time micro-conditioning, per-level head counts
(parity: the reference's StableDiffusionXLPipeline routing,
/root/reference/backend/python/diffusers/backend.py:213-260)."""

import json

import numpy as np
import pytest

from localai_tpu.image.loader import load_diffusers_pipeline


def _write_sdxl_fixture(root):
    """Tiny random SDXL-layout checkpoint: unet with addition embeddings
    and per-level heads, two text encoders (the second with a pooled
    projection), shared tiny VAE."""
    from safetensors.numpy import save_file
    from test_image import _write_diffusers_fixture

    # start from the SD fixture (vae + text_encoder + unet), then replace
    # the unet with the addition-embed variant and add encoder 2
    _write_diffusers_fixture(root)
    rng = np.random.default_rng(7)

    def t(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05

    def conv(cin, cout, k=3):
        return t(cout, cin, k, k)

    u = {}
    u["conv_in.weight"], u["conv_in.bias"] = conv(4, 32), t(32)
    u["time_embedding.linear_1.weight"] = t(128, 32)
    u["time_embedding.linear_1.bias"] = t(128)
    u["time_embedding.linear_2.weight"] = t(128, 128)
    u["time_embedding.linear_2.bias"] = t(128)
    # text_time addition MLP: pooled(32) + 6*time_dim(8) = 80 → 128
    u["add_embedding.linear_1.weight"] = t(128, 80)
    u["add_embedding.linear_1.bias"] = t(128)
    u["add_embedding.linear_2.weight"] = t(128, 128)
    u["add_embedding.linear_2.bias"] = t(128)

    def res(prefix, cin, cout):
        u[f"{prefix}.norm1.weight"], u[f"{prefix}.norm1.bias"] = t(cin), t(cin)
        u[f"{prefix}.conv1.weight"] = conv(cin, cout)
        u[f"{prefix}.conv1.bias"] = t(cout)
        u[f"{prefix}.time_emb_proj.weight"] = t(cout, 128)
        u[f"{prefix}.time_emb_proj.bias"] = t(cout)
        u[f"{prefix}.norm2.weight"], u[f"{prefix}.norm2.bias"] = t(cout), t(cout)
        u[f"{prefix}.conv2.weight"] = conv(cout, cout)
        u[f"{prefix}.conv2.bias"] = t(cout)
        if cin != cout:
            u[f"{prefix}.conv_shortcut.weight"] = conv(cin, cout, 1)
            u[f"{prefix}.conv_shortcut.bias"] = t(cout)

    def st(prefix, ch, depth=1, ctx=96):
        u[f"{prefix}.norm.weight"], u[f"{prefix}.norm.bias"] = t(ch), t(ch)
        u[f"{prefix}.proj_in.weight"] = conv(ch, ch, 1)
        u[f"{prefix}.proj_in.bias"] = t(ch)
        u[f"{prefix}.proj_out.weight"] = conv(ch, ch, 1)
        u[f"{prefix}.proj_out.bias"] = t(ch)
        for d in range(depth):
            b = f"{prefix}.transformer_blocks.{d}"
            for ln in ("norm1", "norm2", "norm3"):
                u[f"{b}.{ln}.weight"], u[f"{b}.{ln}.bias"] = t(ch), t(ch)
            for attn, kv in (("attn1", ch), ("attn2", ctx)):
                u[f"{b}.{attn}.to_q.weight"] = t(ch, ch)
                u[f"{b}.{attn}.to_k.weight"] = t(ch, kv)
                u[f"{b}.{attn}.to_v.weight"] = t(ch, kv)
                u[f"{b}.{attn}.to_out.0.weight"] = t(ch, ch)
                u[f"{b}.{attn}.to_out.0.bias"] = t(ch)
            inner = ch * 4
            u[f"{b}.ff.net.0.proj.weight"] = t(inner * 2, ch)
            u[f"{b}.ff.net.0.proj.bias"] = t(inner * 2)
            u[f"{b}.ff.net.2.weight"] = t(ch, inner)
            u[f"{b}.ff.net.2.bias"] = t(ch)

    # SDXL shape: level 0 plain, level 1 cross-attn with depth 2
    res("down_blocks.0.resnets.0", 32, 32)
    u["down_blocks.0.downsamplers.0.conv.weight"] = conv(32, 32)
    u["down_blocks.0.downsamplers.0.conv.bias"] = t(32)
    res("down_blocks.1.resnets.0", 32, 64)
    st("down_blocks.1.attentions.0", 64, depth=2)
    res("mid_block.resnets.0", 64, 64)
    st("mid_block.attentions.0", 64, depth=2)
    res("mid_block.resnets.1", 64, 64)
    res("up_blocks.0.resnets.0", 64 + 64, 64)
    st("up_blocks.0.attentions.0", 64, depth=2)
    res("up_blocks.0.resnets.1", 64 + 32, 64)
    st("up_blocks.0.attentions.1", 64, depth=2)
    u["up_blocks.0.upsamplers.0.conv.weight"] = conv(64, 64)
    u["up_blocks.0.upsamplers.0.conv.bias"] = t(64)
    res("up_blocks.1.resnets.0", 64 + 32, 32)
    res("up_blocks.1.resnets.1", 32 + 32, 32)
    u["conv_norm_out.weight"], u["conv_norm_out.bias"] = t(32), t(32)
    u["conv_out.weight"], u["conv_out.bias"] = conv(32, 4), t(4)

    (root / "unet" / "model.safetensors").unlink()
    save_file(u, str(root / "unet" / "model.safetensors"))
    (root / "unet" / "config.json").write_text(json.dumps({
        "block_out_channels": [32, 64], "layers_per_block": 1,
        "down_block_types": ["DownBlock2D", "CrossAttnDownBlock2D"],
        "cross_attention_dim": 96, "attention_head_dim": [2, 4],
        "in_channels": 4, "out_channels": 4,
        "addition_embed_type": "text_time",
        "addition_time_embed_dim": 8,
        "projection_class_embeddings_input_dim": 80,
    }))

    # second text encoder: hidden 32 with a 32-dim pooled projection;
    # context = 64 (enc1) + 32 (enc2) = 96
    c2 = {}
    C2, I2 = 32, 64
    c2["text_model.embeddings.token_embedding.weight"] = t(100, C2)
    c2["text_model.embeddings.position_embedding.weight"] = t(16, C2)
    for i in range(2):
        b = f"text_model.encoder.layers.{i}"
        for ln in ("layer_norm1", "layer_norm2"):
            c2[f"{b}.{ln}.weight"], c2[f"{b}.{ln}.bias"] = t(C2), t(C2)
        for p in ("q_proj", "k_proj", "v_proj", "out_proj"):
            c2[f"{b}.self_attn.{p}.weight"] = t(C2, C2)
            c2[f"{b}.self_attn.{p}.bias"] = t(C2)
        c2[f"{b}.mlp.fc1.weight"], c2[f"{b}.mlp.fc1.bias"] = t(I2, C2), t(I2)
        c2[f"{b}.mlp.fc2.weight"], c2[f"{b}.mlp.fc2.bias"] = t(C2, I2), t(C2)
    c2["text_model.final_layer_norm.weight"] = t(C2)
    c2["text_model.final_layer_norm.bias"] = t(C2)
    c2["text_projection.weight"] = t(32, C2)
    (root / "text_encoder_2").mkdir()
    save_file(c2, str(root / "text_encoder_2" / "model.safetensors"))
    (root / "text_encoder_2" / "config.json").write_text(json.dumps({
        "vocab_size": 100, "hidden_size": C2, "intermediate_size": I2,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "max_position_embeddings": 16, "eos_token_id": 99,
        "projection_dim": 32,
        "architectures": ["CLIPTextModelWithProjection"],
    }))
    (root / "model_index.json").write_text(json.dumps(
        {"_class_name": "StableDiffusionXLPipeline"}
    ))


@pytest.fixture(scope="module")
def sdxl(tmp_path_factory):
    root = tmp_path_factory.mktemp("sdxl") / "model"
    _write_sdxl_fixture(root)
    return load_diffusers_pipeline(root, default_steps=2)


def test_sdxl_layout_detected(sdxl):
    assert sdxl.is_sdxl
    assert sdxl.unet_cfg.addition_embed
    assert sdxl.unet_cfg.heads_per_level == (2, 4)
    assert sdxl.unet_cfg.attn_levels == (1,)
    assert sdxl.unet_cfg.context_dim == 96
    assert "add_emb" in sdxl.unet_params
    assert "text_projection" in sdxl.text2_params
    # depth-2 transformer stacks loaded data-driven
    assert len(sdxl.unet_params["mid"]["attn"]["blocks"]) == 2


def test_sdxl_generation(sdxl):
    a = sdxl.generate("a castle", width=64, height=64, seed=5)
    assert a.image.shape == (64, 64, 3)
    assert a.image.dtype == np.uint8
    # deterministic per seed
    b = sdxl.generate("a castle", width=64, height=64, seed=5)
    np.testing.assert_array_equal(a.image, b.image)
    # prompt reaches the model through BOTH encoders
    c = sdxl.generate("a dog", width=64, height=64, seed=5)
    assert not np.array_equal(a.image, c.image)


def test_sdxl_conditioning_shapes(sdxl):
    cond = sdxl._prepare_cond("hello", "bad", 64, 64)
    assert cond["context"].shape == (2, 16, 96)
    assert cond["pooled"].shape == (2, 32)
    assert cond["time_ids"].shape == (2, 6)
    # pooled actually conditions the unet: zeroing it changes the output
    import jax.numpy as jnp

    x = jnp.zeros((1, 8, 8, 4), jnp.float32)
    d1 = sdxl._unet_step(x, jnp.float32(1.0), jnp.float32(500.0), cond,
                         jnp.float32(5.0))
    cond2 = dict(cond, pooled=cond["pooled"] * 0 + 1.0)
    d2 = sdxl._unet_step(x, jnp.float32(1.0), jnp.float32(500.0), cond2,
                         jnp.float32(5.0))
    assert not np.allclose(np.asarray(d1), np.asarray(d2))
