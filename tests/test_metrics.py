"""/metrics exposition tests: histogram bucket math, label escaping,
gauge typing, and the scrape-time engine-gauge refresh (obs.metrics)."""

import math
import re

from localai_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    escape_label_value,
    update_engine_gauges,
)


def _series(rendered: str, name: str) -> dict[str, float]:
    """name{labels} value → {labels-or-'': value} for one metric family."""
    out = {}
    for line in rendered.splitlines():
        if line.startswith("#"):
            continue
        m = re.match(rf"^{re.escape(name)}(?:\{{(.*)\}})? (.+)$", line)
        if m:
            out[m.group(1) or ""] = float(m.group(2))
    return out


def test_histogram_buckets_cumulative_and_inf_equals_count():
    h = Histogram("t_hist", "help", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v, path="/x")
    text = h.render()
    buckets = _series(text, "t_hist_bucket")
    # cumulative: each bucket includes everything below it
    assert buckets['path="/x",le="0.1"'] == 1
    assert buckets['path="/x",le="1.0"'] == 3
    assert buckets['path="/x",le="10.0"'] == 4
    assert buckets['path="/x",le="+Inf"'] == 5
    counts = _series(text, "t_hist_count")
    sums = _series(text, "t_hist_sum")
    assert buckets['path="/x",le="+Inf"'] == counts['path="/x"']
    assert math.isclose(sums['path="/x"'], 0.05 + 0.5 + 0.5 + 5.0 + 50.0)


def test_histogram_cumulative_never_decreases():
    h = Histogram("mono_hist", "help")
    for v in (0.001, 0.02, 0.3, 4.0, 70.0, 70.0):
        h.observe(v)
    vals = [v for line in h.render().splitlines()
            if (m := re.match(r"^mono_hist_bucket\{le=\"[^\"]+\"\} (\d+)$",
                              line))
            for v in [int(m.group(1))]]
    assert vals == sorted(vals) and vals[-1] == 6


def test_label_escaping_round_trips():
    # a label value with all three hazardous characters must render as
    # valid exposition and decode back to the original
    nasty = 'pa"th\\with\nnewline'
    escaped = escape_label_value(nasty)
    assert "\n" not in escaped

    # single-pass decoder (what a scraper does) proves no information loss
    def decode(s):
        out, i = [], 0
        while i < len(s):
            if s[i] == "\\" and i + 1 < len(s):
                out.append({"n": "\n", '"': '"', "\\": "\\"}[s[i + 1]])
                i += 2
            else:
                out.append(s[i])
                i += 1
        return "".join(out)

    assert decode(escaped) == nasty

    c = Counter("t_counter", "help")
    c.inc(path=nasty)
    lines = [ln for ln in c.render().splitlines() if not ln.startswith("#")]
    assert len(lines) == 1  # a raw newline would have split the sample
    assert escaped in lines[0]


def test_gauge_renders_gauge_type_and_set_overwrites():
    g = Gauge("t_gauge", "a counter of gauges")  # 'counter' in help text
    g.set(3.0, model="m")
    g.set(1.5, model="m")
    text = g.render()
    assert "# TYPE t_gauge gauge" in text
    assert _series(text, "t_gauge") == {'model="m"': 1.5}


def test_counter_set_total_is_monotone():
    c = Counter("t_total", "help")
    c.set_total(5.0, model="m")
    c.set_total(3.0, model="m")  # stale snapshot must not regress
    assert _series(c.render(), "t_total") == {'model="m"': 5.0}


def test_update_engine_gauges_from_scheduler_dict():
    reg = Registry()
    update_engine_gauges("tiny", {
        "active_slots": [{"slot": 0}, {"slot": 1}],
        "num_slots": 4,
        "occupancy": 0.5,
        "kv_utilization": 0.25,
        "queue_depth": 3,
        "total_prompt_tokens": 100,
        "total_generated_tokens": 40,
        "prefix_tokens_reused": 7,
        "dispatches": 12,
        "preemptions": 1,
        "prompt_cache": {"hits": 3, "misses": 1, "hit_tokens": 96},
        "spec_acceptance_rate": 0.8,
        "spec_windows": 5,
    }, registry=reg)
    text = reg.render()
    assert 'localai_batch_occupancy{model="tiny"} 0.5' in text
    assert 'localai_kv_slot_utilization{model="tiny"} 0.25' in text
    assert 'localai_prompt_cache_hit_rate{model="tiny"} 0.75' in text
    assert 'localai_speculative_accept_rate{model="tiny"} 0.8' in text
    assert 'localai_queue_depth{model="tiny"} 3' in text
    # preemptions are event-sourced by EngineTelemetry only — the scrape
    # path must NOT sync them (double-count); see obs/engine.finished
    assert 'localai_preemptions_total{model="tiny"}' not in text
    # an unreachable worker's error dict must not clobber anything
    update_engine_gauges("tiny", {"error": "connection refused"},
                         registry=reg)
    assert 'localai_batch_occupancy{model="tiny"} 0.5' in reg.render()


def test_registry_render_includes_engine_families_when_empty():
    # series-less families still expose HELP/TYPE (scrapers and the CI
    # smoke assert on family names before any traffic)
    text = Registry().render()
    for family in ("localai_ttft_seconds", "localai_tpot_seconds",
                   "localai_queue_wait_seconds", "localai_batch_occupancy",
                   "localai_prompt_cache_hit_rate",
                   "localai_speculative_accept_rate",
                   "localai_xla_compile_seconds_total"):
        assert f"# TYPE {family} " in text
