"""Web UI pages (parity: core/http/routes/ui.go + views/*.html — home,
gallery browser, chat, text2image, tts), content negotiation on /, the
disable_webui flag, and key-free page access with key-protected APIs."""

import httpx
import pytest
from test_api import _ServerThread, make_state


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    srv = _ServerThread(make_state(
        tmp_path_factory.mktemp("models"), write_tiny=True))
    yield srv
    srv.stop()


def test_home_content_negotiation(server):
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        as_api = c.get("/")  # httpx default Accept */*
        assert as_api.headers["content-type"].startswith("application/json")
        as_browser = c.get("/", headers={"Accept": "text/html"})
        assert as_browser.headers["content-type"].startswith("text/html")
        assert "tiny" in as_browser.text
        assert "LocalAI-TPU" in as_browser.text


def test_all_pages_render(server):
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        for path in ("/browse", "/chat/", "/chat/tiny", "/text2image/",
                     "/tts/", "/tts/tiny"):
            r = c.get(path)
            assert r.status_code == 200, path
            assert r.headers["content-type"].startswith("text/html"), path
        # the chat page preselects the path model
        assert 'selected>tiny' in c.get("/chat/tiny").text


def test_disable_webui(tmp_path):
    state = make_state(tmp_path, write_tiny=True)
    state.config.disable_webui = True
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            r = c.get("/", headers={"Accept": "text/html"})
            assert r.headers["content-type"].startswith("application/json")
            assert c.get("/browse").status_code == 404
    finally:
        srv.stop()


def test_pages_keyless_apis_protected(tmp_path):
    state = make_state(tmp_path, write_tiny=True)
    state.config.api_keys = ["sekrit"]
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            assert c.get("/chat/").status_code == 200     # page: key-free
            assert c.get("/v1/models").status_code == 401  # API: protected
            assert c.get("/models/available").status_code == 401
    finally:
        srv.stop()
