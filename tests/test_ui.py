"""Web UI pages (parity: core/http/routes/ui.go + views/*.html — home,
gallery browser, chat, text2image, tts), content negotiation on /, the
disable_webui flag, and key-free page access with key-protected APIs."""

import httpx
import pytest
from test_api import _ServerThread, make_state


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    srv = _ServerThread(make_state(
        tmp_path_factory.mktemp("models"), write_tiny=True))
    yield srv
    srv.stop()


def test_home_content_negotiation(server):
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        as_api = c.get("/")  # httpx default Accept */*
        assert as_api.headers["content-type"].startswith("application/json")
        as_browser = c.get("/", headers={"Accept": "text/html"})
        assert as_browser.headers["content-type"].startswith("text/html")
        assert "tiny" in as_browser.text
        assert "LocalAI-TPU" in as_browser.text


def test_all_pages_render(server):
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        for path in ("/browse", "/chat/", "/chat/tiny", "/text2image/",
                     "/tts/", "/tts/tiny"):
            r = c.get(path)
            assert r.status_code == 200, path
            assert r.headers["content-type"].startswith("text/html"), path
        # the chat page preselects the path model
        assert 'selected>tiny' in c.get("/chat/tiny").text


def test_disable_webui(tmp_path):
    state = make_state(tmp_path, write_tiny=True)
    state.config.disable_webui = True
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            r = c.get("/", headers={"Accept": "text/html"})
            assert r.headers["content-type"].startswith("application/json")
            assert c.get("/browse").status_code == 404
    finally:
        srv.stop()


def test_pages_keyless_apis_protected(tmp_path):
    state = make_state(tmp_path, write_tiny=True)
    state.config.api_keys = ["sekrit"]
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            assert c.get("/chat/").status_code == 200     # page: key-free
            assert c.get("/v1/models").status_code == 401  # API: protected
            assert c.get("/models/available").status_code == 401
    finally:
        srv.stop()


def test_swagger_spec_and_ui(server):
    """OpenAPI doc generated from the live route table + explorer page
    (parity: the /swagger handler, core/http/app.go:30)."""
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        spec = c.get("/swagger/doc.json").json()
        assert spec["openapi"].startswith("3.")
        assert "/v1/chat/completions" in spec["paths"]
        assert "post" in spec["paths"]["/v1/chat/completions"]
        body = spec["paths"]["/v1/chat/completions"]["post"]["requestBody"]
        assert "messages" in body["content"]["application/json"][
            "schema"]["properties"]
        # path params are declared
        assert spec["paths"]["/v1/files/{file_id}"]["get"]["parameters"][
            0]["name"] == "file_id"
        page = c.get("/swagger")
        assert page.status_code == 200
        assert "doc.json" in page.text


def test_swagger_reachable_with_api_keys(tmp_path):
    state = make_state(tmp_path, write_tiny=True)
    state.config.api_keys = ["sekrit"]
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            assert c.get("/swagger").status_code == 200
            assert c.get("/swagger/doc.json").status_code == 200
    finally:
        srv.stop()
