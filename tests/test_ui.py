"""Web UI pages (parity: core/http/routes/ui.go + views/*.html — home,
gallery browser, chat, text2image, tts), content negotiation on /, the
disable_webui flag, and key-free page access with key-protected APIs."""

import httpx
import pytest
from test_api import _ServerThread, make_state


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    srv = _ServerThread(make_state(
        tmp_path_factory.mktemp("models"), write_tiny=True))
    yield srv
    srv.stop()


def test_home_content_negotiation(server):
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        as_api = c.get("/")  # httpx default Accept */*
        assert as_api.headers["content-type"].startswith("application/json")
        as_browser = c.get("/", headers={"Accept": "text/html"})
        assert as_browser.headers["content-type"].startswith("text/html")
        assert "tiny" in as_browser.text
        assert "LocalAI-TPU" in as_browser.text


def test_all_pages_render(server):
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        for path in ("/browse", "/chat/", "/chat/tiny", "/text2image/",
                     "/tts/", "/tts/tiny"):
            r = c.get(path)
            assert r.status_code == 200, path
            assert r.headers["content-type"].startswith("text/html"), path
        # the chat page preselects the path model
        assert 'selected>tiny' in c.get("/chat/tiny").text


def test_disable_webui(tmp_path):
    state = make_state(tmp_path, write_tiny=True)
    state.config.disable_webui = True
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            r = c.get("/", headers={"Accept": "text/html"})
            assert r.headers["content-type"].startswith("application/json")
            assert c.get("/browse").status_code == 404
    finally:
        srv.stop()


def test_pages_keyless_apis_protected(tmp_path):
    state = make_state(tmp_path, write_tiny=True)
    state.config.api_keys = ["sekrit"]
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            assert c.get("/chat/").status_code == 200     # page: key-free
            assert c.get("/v1/models").status_code == 401  # API: protected
            assert c.get("/models/available").status_code == 401
    finally:
        srv.stop()


def test_swagger_spec_and_ui(server):
    """OpenAPI doc generated from the live route table + explorer page
    (parity: the /swagger handler, core/http/app.go:30)."""
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        spec = c.get("/swagger/doc.json").json()
        assert spec["openapi"].startswith("3.")
        assert "/v1/chat/completions" in spec["paths"]
        assert "post" in spec["paths"]["/v1/chat/completions"]
        body = spec["paths"]["/v1/chat/completions"]["post"]["requestBody"]
        assert "messages" in body["content"]["application/json"][
            "schema"]["properties"]
        # path params are declared
        assert spec["paths"]["/v1/files/{file_id}"]["get"]["parameters"][
            0]["name"] == "file_id"
        page = c.get("/swagger")
        assert page.status_code == 200
        assert "doc.json" in page.text


def test_swagger_reachable_with_api_keys(tmp_path):
    state = make_state(tmp_path, write_tiny=True)
    state.config.api_keys = ["sekrit"]
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            assert c.get("/swagger").status_code == 200
            assert c.get("/swagger/doc.json").status_code == 200
    finally:
        srv.stop()


def test_talk_and_swarm_pages_render(server):
    """VERDICT r3 #9: talk (voice) view + swarm status page exist."""
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        talk = c.get("/talk/")
        assert talk.status_code == 200
        # the full voice loop is wired client-side
        for probe in ("/v1/audio/transcriptions", "/v1/chat/completions",
                      "/v1/audio/speech", "wavBlob", "getUserMedia"):
            assert probe in talk.text
        swarm = c.get("/swarm")
        assert swarm.status_code == 200
        assert "/swarm/nodes" in swarm.text
        # nav links both pages from every page
        home = c.get("/", headers={"Accept": "text/html"}).text
        assert 'href="/talk/"' in home and 'href="/swarm"' in home


def test_swarm_nodes_proxy(server):
    """/swarm/nodes proxies a live federation router's registry."""
    import threading

    from localai_tpu.federation.server import FederatedServer

    router = FederatedServer(nodes=["127.0.0.1:9"], health_interval=3600)
    import asyncio

    from aiohttp import web as aioweb

    loop = asyncio.new_event_loop()
    started = threading.Event()
    port_box = {}

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            runner = aioweb.AppRunner(router.create_app())
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port_box["port"] = runner.addresses[0][1]
            port_box["runner"] = runner
            started.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(15)
    try:
        # allowlist the live router (as a trailing-slash variant: the
        # comparison is normalized scheme/host/port, not exact-string) plus
        # one dead router for the 502 path
        port = port_box["port"]
        server.state.config.swarm_routers = (
            f"HTTP://127.0.0.1:{port}/,http://127.0.0.1:9")
        with httpx.Client(base_url=server.base, timeout=30.0) as c:
            r = c.get("/swarm/nodes",
                      params={"router": f"http://127.0.0.1:{port}"})
            assert r.status_code == 200
            data = r.json()
            assert len(data["nodes"]) == 1
            assert data["nodes"][0]["address"] == "http://127.0.0.1:9"
            # bad router URL rejected; allowlisted-but-dead router is a 502
            assert c.get("/swarm/nodes",
                         params={"router": "ftp://x"}).status_code == 400
            assert c.get(
                "/swarm/nodes",
                params={"router": "http://127.0.0.1:9"},
            ).status_code == 502
            # non-loopback, non-configured routers are refused: the proxy
            # must not double as an internal-network probe
            assert c.get(
                "/swarm/nodes",
                params={"router": "http://10.99.0.1:8500"},
            ).status_code == 403
            # loopback is NOT a blanket exemption: only the server's own
            # port (colocated router) is allowed, so a key holder cannot
            # port-sweep 127.0.0.1 through the proxy (ADVICE r5 #3)
            assert c.get(
                "/swarm/nodes",
                params={"router": "http://127.0.0.1:1"},
            ).status_code == 403
            assert c.get(
                "/swarm/nodes",
                params={"router": f"http://localhost:{server.state.config.port}"},
            ).status_code in (200, 502)  # own port: allowed (may be dead)
            # userinfo must not smuggle a loopback-looking host past the
            # allowlist (urlopen would connect to 10.99.0.1)
            assert c.get(
                "/swarm/nodes",
                params={"router": "http://127.0.0.1:x@10.99.0.1:8500"},
            ).status_code == 400
    finally:
        # restore the shared module-scoped fixture even when an assert
        # above fails — a leaked allowlist would cascade into later tests
        server.state.config.swarm_routers = ""
        fut = asyncio.run_coroutine_threadsafe(
            port_box["runner"].cleanup(), loop)
        fut.result(10)
        loop.call_soon_threadsafe(loop.stop)
        t.join(10)


def test_swarm_nodes_protected_but_page_keyless(tmp_path):
    """The swarm PAGE is key-free; the /swarm/nodes proxy (server-side
    fetch of an operator-named router) requires the API key, and router
    URLs carrying a query/fragment are rejected."""
    state = make_state(tmp_path, write_tiny=True)
    state.config.api_keys = ["sekrit"]
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            assert c.get("/swarm").status_code == 200
            assert c.get("/swarm/nodes",
                         params={"router": "http://127.0.0.1:1"}
                         ).status_code == 401
            r = c.get("/swarm/nodes",
                      params={"router": "http://h/x?"},
                      headers={"Authorization": "Bearer sekrit"})
            assert r.status_code == 400
    finally:
        srv.stop()
