"""AIO modality sweep: ONE server instance serving every modality at once,
every endpoint asserted — the analogue of the reference's signature
tests/e2e-aio suite (SURVEY §4: text, tool-calls, json mode, image gen,
embeddings, vision, TTS, STT, rerank against the packaged all-in-one
image, e2e_test.go:19-234). The reference needs a container and real
model downloads; here the debug presets make the whole sweep a unit test.
"""

import base64
import io
import json

import httpx
import numpy as np
import pytest

from tests.test_api import _ServerThread, make_state

AIO_YAMLS = {
    "llm.yaml": """\
name: aio-llm
model: "debug:tiny"
context_size: 96
embeddings: true
parameters:
  temperature: 0.0
  max_tokens: 12
engine:
  max_slots: 2
  prefill_buckets: [16, 32]
  dtype: float32
  kv_dtype: float32
""",
    "whisper.yaml": (
        "name: aio-whisper\nbackend: whisper\nmodel: 'debug:whisper'\n"
    ),
    "tts.yaml": "name: aio-tts\nbackend: vits\nmodel: 'debug:tts'\n",
    "image.yaml": (
        "name: aio-image\nbackend: diffusers\nmodel: 'debug:sd-tiny'\n"
        "diffusers:\n  steps: 2\n"
    ),
    "rerank.yaml": (
        "name: aio-rerank\nmodel: 'debug:reranker-tiny'\nbackend: reranker\n"
    ),
    "embed.yaml": (
        "name: aio-embed\nmodel: 'debug:bert-tiny'\n"
        "backend: bert-embeddings\n"
    ),
}


@pytest.fixture(scope="module")
def aio(tmp_path_factory):
    models = tmp_path_factory.mktemp("models")
    for fname, text in AIO_YAMLS.items():
        (models / fname).write_text(text)
    srv = _ServerThread(make_state(models))
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def c(aio):
    with httpx.Client(base_url=aio.base, timeout=300.0) as client:
        yield client


def test_models_lists_every_modality(c):
    names = {m["id"] for m in c.get("/v1/models").json()["data"]}
    assert {"aio-llm", "aio-whisper", "aio-tts", "aio-image",
            "aio-rerank", "aio-embed"} <= names


def test_text(c):
    r = c.post("/v1/chat/completions", json={
        "model": "aio-llm",
        "messages": [{"role": "user", "content": "hello"}],
    })
    assert r.status_code == 200
    assert r.json()["choices"][0]["message"]["content"] is not None


def test_tool_calls(c):
    r = c.post("/v1/chat/completions", json={
        "model": "aio-llm",
        "messages": [{"role": "user", "content": "weather in oslo?"}],
        "tools": [{"type": "function", "function": {
            "name": "get_weather",
            "parameters": {"type": "object", "properties": {
                "city": {"type": "string", "maxLength": 8}},
                "required": ["city"]},
        }}],
        "tool_choice": "required",
        "max_tokens": 120,
    })
    assert r.status_code == 200
    calls = r.json()["choices"][0]["message"]["tool_calls"]
    assert calls and calls[0]["function"]["name"] == "get_weather"
    json.loads(calls[0]["function"]["arguments"])  # valid JSON args


def test_json_mode(c):
    r = c.post("/v1/chat/completions", json={
        "model": "aio-llm",
        "messages": [{"role": "user", "content": "give me json"}],
        "response_format": {"type": "json_object"},
        "max_tokens": 48,
    })
    assert r.status_code == 200
    out = r.json()["choices"][0]["message"]["content"]
    json.loads(out)  # grammar-constrained decode produced valid JSON


def test_embeddings(c):
    r = c.post("/v1/embeddings", json={
        "model": "aio-embed", "input": ["one doc", "another"]})
    assert r.status_code == 200
    data = r.json()["data"]
    assert len(data) == 2 and len(data[0]["embedding"]) > 4


def test_image_gen(c):
    r = c.post("/v1/images/generations", json={
        "model": "aio-image", "prompt": "a tiny house", "size": "64x64",
        "response_format": "b64_json"})
    assert r.status_code == 200
    png = base64.b64decode(r.json()["data"][0]["b64_json"])
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


def test_tts(c):
    r = c.post("/v1/audio/speech", json={
        "model": "aio-tts", "input": "sweep check"})
    assert r.status_code == 200
    assert r.content[:4] == b"RIFF"  # wav


def test_stt(c):
    from localai_tpu.audio.wav import write_wav

    tone = (np.sin(np.linspace(0, 880 * np.pi, 16000)) * 0.3
            ).astype(np.float32)
    r = c.post("/v1/audio/transcriptions",
               files={"file": ("t.wav", io.BytesIO(write_wav(tone)),
                               "audio/wav")},
               data={"model": "aio-whisper"})
    assert r.status_code == 200
    assert "text" in r.json()


def test_rerank(c):
    r = c.post("/v1/rerank", json={
        "model": "aio-rerank", "query": "what is a tpu?",
        "documents": ["a chip", "a fish", "an accelerator"]})
    assert r.status_code == 200
    results = r.json()["results"]
    assert len(results) == 3
    assert all("relevance_score" in x for x in results)


def test_metrics_counts_the_sweep(c):
    m = c.get("/metrics").text
    assert "localai" in m or "http_requests" in m or m  # exposition exists
