"""Speculative decoding: draft propose + target verify in one program.

Parity: DraftModel/NDraft (/root/reference/core/config/backend_config.go:143,
backend/backend.proto:210). The acceptance scan runs the real sampler chain,
so greedy spec output must equal greedy non-spec output exactly.
"""

import numpy as np
import pytest

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.speculative import SKIP, SpecDecoder
from localai_tpu.models.registry import resolve_model


@pytest.fixture(scope="module")
def small():
    return resolve_model("debug:small", dtype="float32")


@pytest.fixture(scope="module")
def tiny():
    return resolve_model("debug:tiny", dtype="float32")


def _mk(model, **kw):
    return ModelRunner(model.cfg, model.params, num_slots=2, max_ctx=128,
                       prefill_buckets=[32], **kw)


def _spec_tokens(spec, prompt, windows, slot):
    toks = [spec.admit(slot, prompt, temperature=0.0)]
    for _ in range(windows):
        rows = spec.step_spec()
        for t in range(rows.shape[0]):
            if rows[t, slot] != SKIP:
                toks.append(int(rows[t, slot]))
    return toks


def test_greedy_spec_matches_plain_decode(small, tiny):
    """Emitted tokens come from the target's own sampling chain, so greedy
    spec == greedy plain decode regardless of draft quality."""
    prompt = list(b"speculation target")
    plain = _mk(small)
    s = plain.acquire_slot()
    ref = [plain.admit(s, prompt, temperature=0.0)]
    for _ in range(12):
        ref.append(int(plain.step()[s]))

    spec = SpecDecoder(_mk(small), _mk(tiny), gamma=3)
    slot = spec.acquire_slot()
    got = _spec_tokens(spec, prompt, windows=12, slot=slot)
    assert got[: len(ref)] == ref


def test_self_draft_accepts_everything(small):
    """With the draft == the target, every proposal matches the target's
    greedy choice, so each window emits all gamma+1 tokens."""
    spec = SpecDecoder(_mk(small), _mk(small), gamma=3)
    slot = spec.acquire_slot()
    spec.admit(slot, list(b"identical twins"), temperature=0.0)
    rows = spec.step_spec()
    assert (rows[:, slot] != SKIP).all()
    # normalized by ACTIVE slot-windows: full acceptance reads 1.0 even
    # though slot 1 is idle
    assert spec.acceptance_rate == 1.0


def test_spec_positions_and_state_advance(small, tiny):
    spec = SpecDecoder(_mk(small), _mk(tiny), gamma=3)
    slot = spec.acquire_slot()
    prompt = list(b"position check")
    spec.admit(slot, prompt, temperature=0.0)
    p0 = spec.slot_position(slot)
    assert p0 == len(prompt)
    rows = spec.step_spec()
    emitted = int((rows[:, slot] != SKIP).sum())
    assert 1 <= emitted <= 4
    assert spec.slot_position(slot) == p0 + emitted
    # the draft frontier re-syncs lazily from the target's device state
    # at the START of the next draft window (ModelDrafter._draft_fn takes
    # the target's tokens/positions as fresh jit inputs — eager aliasing
    # of donated buffers would dangle); a second window must therefore
    # keep emitting from the rolled-back frontier
    rows2 = spec.step_spec()
    emitted2 = int((rows2[:, slot] != SKIP).sum())
    assert 1 <= emitted2 <= 4
    assert spec.slot_position(slot) == p0 + emitted + emitted2


def test_spec_int8_kv(small, tiny):
    """Spec verify writes through the scaled-int8 KV path."""
    spec = SpecDecoder(
        _mk(small, kv_dtype="int8"),
        _mk(tiny, kv_dtype="int8"),
        gamma=2,
    )
    slot = spec.acquire_slot()
    toks = _spec_tokens(spec, list(b"int8 spec"), windows=4, slot=slot)
    assert len(toks) >= 5
    assert all(0 <= t < small.cfg.vocab_size for t in toks)


def test_seeded_sampled_spec_matches_plain(small, tiny):
    """Keys advance once per emitted token, so a seeded sampled stream is
    reproducible through the speculative path too."""
    prompt = list(b"seeded stream")
    plain = _mk(small)
    s = plain.acquire_slot()
    ref = [plain.admit(s, prompt, temperature=0.8, seed=7)]
    for _ in range(10):
        ref.append(int(plain.step()[s]))

    spec = SpecDecoder(_mk(small), _mk(tiny), gamma=3)
    slot = spec.acquire_slot()
    got = [spec.admit(slot, prompt, temperature=0.8, seed=7)]
    for _ in range(10):
        rows = spec.step_spec()
        for t in range(rows.shape[0]):
            if rows[t, slot] != SKIP:
                got.append(int(rows[t, slot]))
    assert got[: len(ref)] == ref


def test_vocab_mismatch_rejected(small):
    import dataclasses

    import jax

    from localai_tpu.models.llama import init_params

    cfg = dataclasses.replace(small.cfg, vocab_size=256)
    params = init_params(jax.random.key(0), cfg)
    odd = ModelRunner(cfg, params, num_slots=2, max_ctx=128,
                      prefill_buckets=[32])
    with pytest.raises(ValueError, match="vocab"):
        SpecDecoder(_mk(small), odd, gamma=2)


def test_scheduler_with_spec_matches_plain(small, tiny):
    """End-to-end scheduler: spec-enabled greedy output equals plain."""
    from localai_tpu.engine.scheduler import GenRequest, Scheduler

    prompt = list(b"scheduler spec parity")
    plain_sched = Scheduler(_mk(small), small.tokenizer, multi_step=4)
    try:
        ref = plain_sched.generate(
            GenRequest(prompt=prompt, max_new_tokens=20, temperature=0.0,
                       ignore_eos=True), timeout=120,
        ).token_ids
    finally:
        plain_sched.shutdown()

    spec = SpecDecoder(_mk(small), _mk(tiny), gamma=3)
    sched = Scheduler(spec.target, small.tokenizer, multi_step=4, spec=spec)
    try:
        got = sched.generate(
            GenRequest(prompt=prompt, max_new_tokens=20, temperature=0.0,
                       ignore_eos=True), timeout=120,
        ).token_ids
        m = sched.metrics()
        assert m["spec_windows"] > 0
        assert m["spec_acceptance_rate"] > 0.0
    finally:
        sched.shutdown()
    assert got == ref


def test_scheduler_spec_with_constraint_interlude(small, tiny):
    """A grammar-constrained request forces plain dispatches; afterwards the
    drafts resync and speculative windows resume producing correct text."""
    from localai_tpu.engine.scheduler import GenRequest, Scheduler

    class OnlyTokens:
        """Constraint allowing a fixed token set for 4 tokens."""

        def __init__(self, allowed, n=4):
            self.allowed = allowed
            self.left = n

        def allowed_mask(self):
            import numpy as np

            row = np.full(small.cfg.vocab_size, -1e30, np.float32)
            row[self.allowed] = 0.0
            return row

        def advance(self, token_id):
            self.left -= 1

        @property
        def done(self):
            return self.left <= 0

    spec = SpecDecoder(_mk(small), _mk(tiny), gamma=3)
    sched = Scheduler(spec.target, small.tokenizer, multi_step=4, spec=spec)
    try:
        h1 = sched.generate(
            GenRequest(prompt=list(b"constrained"), max_new_tokens=8,
                       temperature=0.0, ignore_eos=True,
                       constraint=OnlyTokens([65, 66, 67])), timeout=120,
        )
        assert all(t in (65, 66, 67) for t in h1.token_ids)
        # after the constrained request, plain decode ran → drafts stale;
        # the next request must resync and still produce correct output
        h2 = sched.generate(
            GenRequest(prompt=list(b"after constraint"), max_new_tokens=12,
                       temperature=0.0, ignore_eos=True), timeout=120,
        )
        assert len(h2.token_ids) == 12
        assert sched.metrics()["spec_windows"] > 0
    finally:
        sched.shutdown()


def test_serving_model_with_draft_config(tmp_path):
    """Config → engine wiring: engine.draft_model builds a SpecDecoder."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.models.manager import build_serving_model

    mcfg = ModelConfig.model_validate({
        "name": "spec-small",
        "model": "debug:small",
        "context_size": 128,
        "parameters": {"max_tokens": 16},
        "engine": {
            "max_slots": 2,
            "prefill_buckets": [32],
            "dtype": "float32",
            "kv_dtype": "float32",
            "draft_model": "debug:tiny",
            "n_draft": 3,
        },
    })
    app = AppConfig(model_path=str(tmp_path))
    sm = build_serving_model(mcfg, app)
    try:
        assert sm.scheduler.spec is not None
        assert sm.scheduler.spec.gamma == 3
        from localai_tpu.engine.scheduler import GenRequest

        h = sm.scheduler.generate(
            GenRequest(prompt=list(b"hello draft"), max_new_tokens=10,
                       temperature=0.0, ignore_eos=True), timeout=120,
        )
        assert len(h.token_ids) == 10
    finally:
        sm.scheduler.shutdown()


def test_spec_under_mesh_matches_single_device(small, tiny):
    """Speculative decoding with dp×tp-sharded target AND draft must
    reproduce the single-device greedy stream."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from localai_tpu.parallel import sharding as shd
    from localai_tpu.parallel.mesh import MeshPlan, build_mesh

    prompt = list(b"mesh speculation")
    ref_spec = SpecDecoder(_mk(small), _mk(tiny), gamma=3)
    slot = ref_spec.acquire_slot()
    ref = _spec_tokens(ref_spec, prompt, windows=6, slot=slot)

    mesh = build_mesh(MeshPlan(data=2, model=4))

    def mk_mesh(model):
        sp = shd.shard_params(model.params, model.cfg, mesh)
        return ModelRunner(model.cfg, sp, num_slots=4, max_ctx=128,
                           prefill_buckets=[32], mesh=mesh)

    spec = SpecDecoder(mk_mesh(small), mk_mesh(tiny), gamma=3)
    slot = spec.acquire_slot()
    got = _spec_tokens(spec, prompt, windows=6, slot=slot)
    n = min(len(ref), len(got))
    assert got[:n] == ref[:n]
