"""Family-based chat-template guessing (VERDICT r4 #8; parity:
core/config/guesser.go:13-246)."""

import json

import pytest

from localai_tpu.config.guesser import (
    FAMILY_SETTINGS,
    guess_chat_defaults,
    identify_family,
)
from localai_tpu.config.model_config import ModelConfig


@pytest.mark.parametrize("hf,name,family", [
    ({"model_type": "llama", "eos_token_id": 128009}, "", "llama3"),
    ({"model_type": "qwen2"}, "", "chatml"),
    ({"model_type": "llama", "bos_token_id": 1, "eos_token_id": 2},
     "", "chatml"),                                      # Yi-style
    ({"model_type": "phi3"}, "", "phi3"),
    ({"model_type": "gemma2"}, "", "gemma"),
    ({"model_type": "llama"}, "gemma-ft", "gemma"),      # name fallback
    ({"model_type": "mistral"}, "", "mistral"),
    ({"model_type": "cohere", "eos_token_id": 255001}, "", "command-r"),
    ({"model_type": "deepseek_v2"}, "", "deepseek2"),
    ({"model_type": "llama", "eos_token_id": 128001}, "", None),
    ({"model_type": "gpt2"}, "", None),
])
def test_identify_family(hf, name, family):
    assert identify_family(hf, name) == family


def test_templates_render(tmp_path):
    """Every family template renders a chat and includes role content +
    its stop token's opening format."""
    from localai_tpu.templates.gotmpl import make_environment

    env = make_environment()
    msgs = [{"role": "system", "content": "SYS"},
            {"role": "user", "content": "USERQ"},
            {"role": "assistant", "content": "ANS"},
            {"role": "user", "content": "FOLLOWUP"}]
    for fam, st in FAMILY_SETTINGS.items():
        out = env.from_string(st["chat_template"]).render(
            messages=msgs, add_generation_prompt=True)
        assert "USERQ" in out and "ANS" in out and "FOLLOWUP" in out, fam
        # the generation prompt leaves the assistant turn open at the end
        assert not out.endswith("FOLLOWUP"), fam


def _ckpt(tmp_path, hf, tok_cfg=None):
    d = tmp_path / "m"
    d.mkdir(exist_ok=True)
    (d / "config.json").write_text(json.dumps(hf))
    if tok_cfg is not None:
        (d / "tokenizer_config.json").write_text(json.dumps(tok_cfg))
    return d


def test_guess_fills_template_and_stopwords(tmp_path):
    d = _ckpt(tmp_path, {"model_type": "llama", "eos_token_id": 128009})
    cfg = ModelConfig(name="m", model=str(d))
    guess_chat_defaults(cfg, tmp_path)
    assert cfg.template.chat_template == \
        FAMILY_SETTINGS["llama3"]["chat_template"]
    assert cfg.stopwords == ["<|eot_id|>"]


def test_guess_prefers_tokenizer_template(tmp_path):
    """A checkpoint carrying its own chat template wins over the family
    default — the STRING is carried (converted-GGUF tokenizers can't
    apply_chat_template themselves)."""
    d = _ckpt(tmp_path, {"model_type": "qwen2"},
              tok_cfg={"chat_template": "{{ messages }}"})
    cfg = ModelConfig(name="m", model=str(d))
    guess_chat_defaults(cfg, tmp_path)
    assert cfg.template.chat_template == "{{ messages }}"
    assert not cfg.template.use_tokenizer_template


def test_guess_respects_existing_config(tmp_path):
    d = _ckpt(tmp_path, {"model_type": "qwen2"})
    cfg = ModelConfig(name="m", model=str(d),
                      template={"chat": "mytmpl"},
                      stopwords=["X"])
    guess_chat_defaults(cfg, tmp_path)
    assert cfg.template.chat_template is None
    assert cfg.stopwords == ["X"]


def test_converted_gguf_gets_guessed_defaults(tmp_path):
    """The VERDICT contract: convert a synthetic chatml-family GGUF (no
    chat template in the source) → config load yields the right template
    + stopwords."""
    import numpy as np

    from test_gguf import write_gguf

    from localai_tpu.models.detect import autodetect_config
    from localai_tpu.utils import gguf as G

    rng = np.random.default_rng(5)
    D, F, L, H, V = 32, 64, 1, 4, 48

    def w(*shape):
        return (rng.normal(size=shape) * 0.05).astype(np.float32)

    tensors = {"token_embd.weight": (w(V, D), G.F32),
               "output_norm.weight": (np.ones(D, np.float32), G.F32),
               "output.weight": (w(V, D), G.F32)}
    for i in range(L):
        tensors[f"blk.{i}.attn_q.weight"] = (w(D, D), G.F32)
        tensors[f"blk.{i}.attn_k.weight"] = (w(D, D), G.F32)
        tensors[f"blk.{i}.attn_v.weight"] = (w(D, D), G.F32)
        tensors[f"blk.{i}.attn_output.weight"] = (w(D, D), G.F32)
        tensors[f"blk.{i}.ffn_gate.weight"] = (w(F, D), G.F32)
        tensors[f"blk.{i}.ffn_up.weight"] = (w(F, D), G.F32)
        tensors[f"blk.{i}.ffn_down.weight"] = (w(D, F), G.F32)
        tensors[f"blk.{i}.attn_norm.weight"] = (np.ones(D, np.float32),
                                                G.F32)
        tensors[f"blk.{i}.ffn_norm.weight"] = (np.ones(D, np.float32),
                                               G.F32)
    meta = [
        ("general.architecture", 8, "qwen2"),
        ("qwen2.vocab_size", 4, V),
        ("qwen2.embedding_length", 4, D),
        ("qwen2.feed_forward_length", 4, F),
        ("qwen2.block_count", 4, L),
        ("qwen2.attention.head_count", 4, H),
        ("qwen2.context_length", 4, 128),
        ("qwen2.rope.freq_base", 6, 10000.0),
    ]
    src = tmp_path / "q.gguf"
    write_gguf(src, meta, tensors)
    out = G.convert_gguf(src, tmp_path / "models" / "q", dtype="float32")
    assert json.loads((out / "config.json").read_text())[
        "model_type"] == "qwen2"

    cfg = ModelConfig(name="q", model="q")
    autodetect_config(cfg, tmp_path / "models")
    assert cfg.template.chat_template == \
        FAMILY_SETTINGS["chatml"]["chat_template"]
    assert "<|im_end|>" in cfg.stopwords
