"""Assistants + Files APIs: CRUD, attachments, and JSON persistence that
survives a server restart (parity:
/root/reference/core/http/endpoints/openai/assistant.go, files.go, and the
boot-time reload in app.go:152-154)."""

import httpx
import pytest

from localai_tpu.api.server import AppState
from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.loader import ConfigLoader
from test_api import TINY_YAML, _ServerThread


def _make_state(root) -> AppState:
    models = root / "models"
    models.mkdir(exist_ok=True)
    (models / "tiny.yaml").write_text(TINY_YAML)
    cfg = AppConfig(
        model_path=str(models),
        config_path=str(root / "conf"),
        upload_path=str(root / "uploads"),
    )
    loader = ConfigLoader(models)
    loader.load_from_path(context_size=cfg.context_size)
    return AppState(cfg, loader)


@pytest.fixture()
def server(tmp_path):
    srv = _ServerThread(_make_state(tmp_path))
    yield srv
    srv.stop()


def _upload(client, name="notes.txt", content=b"hello files",
            purpose="assistants"):
    return client.post("/v1/files", files={"file": (name, content)},
                       data={"purpose": purpose})


def test_file_upload_listing_content_delete(server):
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        r = _upload(c)
        assert r.status_code == 200, r.text
        f = r.json()
        assert f["object"] == "file"
        assert f["purpose"] == "assistants"
        assert f["bytes"] == len(b"hello files")

        # purpose filter (files.go:86-98)
        assert len(c.get("/v1/files").json()["data"]) == 1
        assert c.get("/v1/files", params={"purpose": "nope"}).json()[
            "data"] == []

        # metadata + raw content round trip
        fid = f["id"]
        assert c.get(f"/v1/files/{fid}").json()["filename"] == "notes.txt"
        assert c.get(f"/v1/files/{fid}/content").content == b"hello files"

        # duplicate filename rejected; purpose required
        assert _upload(c).status_code == 400
        r = c.post("/v1/files", files={"file": ("x.txt", b"y")})
        assert r.status_code == 400

        # delete removes metadata and bytes
        assert c.delete(f"/v1/files/{fid}").json()["deleted"] is True
        assert c.get(f"/v1/files/{fid}").status_code == 404
        assert c.get("/v1/files").json()["data"] == []


def test_upload_rejects_traversal_and_oversize(server):
    server.state.config.upload_limit_mb = 0  # 0 MB → everything oversize
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        assert _upload(c).status_code == 400
    server.state.config.upload_limit_mb = 15
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        # filename is flattened to its basename, not written outside
        r = _upload(c, name="../../evil.txt")
        assert r.status_code == 200
        assert r.json()["filename"] == "evil.txt"


def test_assistant_crud_and_files(server):
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        # unknown model rejected (assistant.go:86-89)
        r = c.post("/v1/assistants", json={"model": "missing"})
        assert r.status_code == 400

        r = c.post("/v1/assistants", json={
            "model": "tiny", "name": "helper",
            "instructions": "be brief",
            "tools": [{"type": "function"}],
        })
        assert r.status_code == 200, r.text
        a = r.json()
        assert a["object"] == "assistant"
        aid = a["id"]
        assert aid.startswith("asst_")

        # list + get + modify
        assert [x["id"] for x in c.get("/v1/assistants").json()] == [aid]
        assert c.get(f"/v1/assistants/{aid}").json()["name"] == "helper"
        r = c.post(f"/v1/assistants/{aid}", json={
            "model": "tiny", "name": "renamed",
        })
        assert r.json()["name"] == "renamed"
        assert r.json()["id"] == aid

        # attach an uploaded file
        fid = _upload(c).json()["id"]
        r = c.post(f"/v1/assistants/{aid}/files", json={"file_id": fid})
        assert r.status_code == 200
        assert r.json()["assistant_id"] == aid
        files = c.get(f"/v1/assistants/{aid}/files").json()["data"]
        assert [af["id"] for af in files] == [fid]
        assert c.get(f"/v1/assistants/{aid}").json()["file_ids"] == [fid]
        assert c.get(
            f"/v1/assistants/{aid}/files/{fid}").status_code == 200

        # attaching an unknown file 404s
        r = c.post(f"/v1/assistants/{aid}/files",
                   json={"file_id": "file-999"})
        assert r.status_code == 404

        # detach + delete
        assert c.delete(
            f"/v1/assistants/{aid}/files/{fid}").json()["deleted"] is True
        assert c.get(f"/v1/assistants/{aid}").json()["file_ids"] == []
        assert c.delete(f"/v1/assistants/{aid}").json()["deleted"] is True
        assert c.get("/v1/assistants").json() == []


def test_assistant_list_pagination(server):
    with httpx.Client(base_url=server.base, timeout=30.0) as c:
        ids = []
        for i in range(5):
            ids.append(c.post("/v1/assistants", json={
                "model": "tiny", "name": f"a{i}",
            }).json()["id"])
        out = c.get("/v1/assistants", params={"limit": 2}).json()
        assert len(out) == 2
        asc = c.get("/v1/assistants", params={"order": "asc"}).json()
        nums = [int(a["id"].removeprefix("asst_")) for a in asc]
        assert nums == sorted(nums)
        after = c.get("/v1/assistants",
                      params={"after": str(nums[2]), "order": "asc"}).json()
        assert all(int(a["id"].removeprefix("asst_")) > nums[2]
                   for a in after)


def test_persistence_survives_restart(tmp_path):
    srv = _ServerThread(_make_state(tmp_path))
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            fid = _upload(c).json()["id"]
            aid = c.post("/v1/assistants", json={
                "model": "tiny", "name": "persistent",
            }).json()["id"]
            c.post(f"/v1/assistants/{aid}/files", json={"file_id": fid})
    finally:
        srv.stop()

    # "restart": a fresh AppState over the same directories
    srv = _ServerThread(_make_state(tmp_path))
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            assistants = c.get("/v1/assistants").json()
            assert [a["name"] for a in assistants] == ["persistent"]
            assert assistants[0]["file_ids"] == [fid]
            files = c.get("/v1/files").json()["data"]
            assert [f["id"] for f in files] == [fid]
            assert c.get(
                f"/v1/files/{fid}/content").content == b"hello files"
            # id counters continue past persisted ids — no collisions
            new_aid = c.post("/v1/assistants", json={
                "model": "tiny", "name": "second",
            }).json()["id"]
            assert new_aid != assistants[0]["id"]
    finally:
        srv.stop()
