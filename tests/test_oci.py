"""OCI/Ollama registry pulls against an in-process mock registry
(parity: /root/reference/pkg/oci/{ollama,image,blob}.go — token auth,
manifest resolution, digest-verified blobs, model-layer convention,
layer extraction with traversal guard)."""

import gzip
import hashlib
import io
import json
import tarfile
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from localai_tpu.utils.oci import (
    RegistryClient,
    ollama_fetch_model,
    oci_extract_image,
    parse_image_ref,
)


def _digest(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


class _MockRegistry:
    """distribution-spec v2 server: Bearer token dance + manifests + blobs."""

    def __init__(self, *, require_auth: bool = True):
        self.blobs: dict[str, bytes] = {}
        self.manifests: dict[str, bytes] = {}
        self.require_auth = require_auth
        self.token = "test-token-123"
        registry = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _authed(self) -> bool:
                if not registry.require_auth:
                    return True
                return (self.headers.get("Authorization", "")
                        == f"Bearer {registry.token}")

            def do_GET(self):
                if self.path.startswith("/token"):
                    body = json.dumps({"token": registry.token}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if not self._authed():
                    self.send_response(401)
                    self.send_header(
                        "WWW-Authenticate",
                        f'Bearer realm="http://{self.headers["Host"]}'
                        f'/token",service="mock"',
                    )
                    self.end_headers()
                    return
                parts = self.path.split("/")
                # /v2/<name...>/manifests/<ref> | /v2/<name...>/blobs/<dg>
                if "manifests" in parts:
                    ref = parts[-1]
                    body = registry.manifests.get(ref)
                elif "blobs" in parts:
                    body = registry.blobs.get(parts[-1])
                else:
                    body = None
                if body is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    @property
    def host(self) -> str:
        return f"127.0.0.1:{self.port}"

    def add_blob(self, data: bytes) -> str:
        dg = _digest(data)
        self.blobs[dg] = data
        return dg

    def add_manifest(self, ref: str, manifest: dict) -> str:
        body = json.dumps(manifest).encode()
        self.manifests[ref] = body
        dg = _digest(body)
        self.manifests[dg] = body
        return dg

    def close(self):
        self._httpd.shutdown()


@pytest.fixture()
def registry():
    r = _MockRegistry()
    yield r
    r.close()


def test_parse_image_ref_defaults():
    r = parse_image_ref("gemma:2b", default_registry="registry.ollama.ai")
    assert (r.registry, r.repository, r.reference) == (
        "registry.ollama.ai", "library/gemma", "2b")
    r = parse_image_ref("quay.io/org/repo:v1")
    assert (r.registry, r.repository, r.reference) == (
        "quay.io", "org/repo", "v1")
    r = parse_image_ref("repo@sha256:abc")
    assert r.registry == "registry-1.docker.io"
    assert r.reference == "sha256:abc"
    r = parse_image_ref("http://localhost:5000/m:t")
    assert (r.scheme, r.registry) == ("http", "localhost:5000")


def test_ollama_model_pull(registry, tmp_path):
    weights = b"GGUF-fake-model-bytes" * 100
    dg = registry.add_blob(weights)
    registry.add_manifest("2b", {
        "mediaType": "application/vnd.oci.image.manifest.v1+json",
        "layers": [
            {"mediaType": "application/vnd.ollama.image.license",
             "digest": registry.add_blob(b"license"), "size": 7},
            {"mediaType": "application/vnd.ollama.image.model",
             "digest": dg, "size": len(weights)},
        ],
    })
    dest = tmp_path / "model.gguf"
    seen = []
    out = ollama_fetch_model(f"http://{registry.host}/gemma:2b", dest,
                             progress=lambda d, t: seen.append((d, t)))
    assert out.read_bytes() == weights
    assert seen[-1][0] == len(weights)


def test_blob_digest_verification(registry, tmp_path):
    data = b"payload"
    dg = registry.add_blob(data)
    registry.blobs[dg] = b"tampered"  # corrupt after digest computed
    ref = parse_image_ref(f"http://{registry.host}/m:t")
    client = RegistryClient(ref)
    with pytest.raises(ValueError, match="digest mismatch"):
        client.fetch_blob(dg, tmp_path / "out")
    assert not (tmp_path / "out").exists()


def test_anonymous_token_auth_flow(registry, tmp_path):
    """First request 401s with a challenge; the client fetches a token
    from the realm and retries."""
    data = b"authed-blob"
    dg = registry.add_blob(data)
    ref = parse_image_ref(f"http://{registry.host}/m:t")
    client = RegistryClient(ref)
    client.fetch_blob(dg, tmp_path / "b")
    assert (tmp_path / "b").read_bytes() == data
    assert client._token == registry.token


def _tar_bytes(entries: dict[str, bytes], gz: bool = False) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for name, data in entries.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    raw = buf.getvalue()
    return gzip.compress(raw) if gz else raw


def test_oci_image_extraction(registry, tmp_path):
    layer1 = _tar_bytes({"weights/model.safetensors": b"tensor-bytes"})
    layer2 = _tar_bytes({"config.json": b"{}"}, gz=True)
    registry.add_manifest("v1", {
        "mediaType": "application/vnd.oci.image.manifest.v1+json",
        "layers": [
            {"mediaType": "application/vnd.oci.image.layer.v1.tar",
             "digest": registry.add_blob(layer1), "size": len(layer1)},
            {"mediaType": "application/vnd.oci.image.layer.v1.tar+gzip",
             "digest": registry.add_blob(layer2), "size": len(layer2)},
        ],
    })
    out = oci_extract_image(f"http://{registry.host}/m:v1", tmp_path / "x")
    assert (out / "weights/model.safetensors").read_bytes() == b"tensor-bytes"
    assert (out / "config.json").read_bytes() == b"{}"


def test_oci_extraction_blocks_traversal(registry, tmp_path):
    evil = _tar_bytes({"../escape.txt": b"pwn"})
    registry.add_manifest("bad", {
        "mediaType": "application/vnd.oci.image.manifest.v1+json",
        "layers": [
            {"mediaType": "application/vnd.oci.image.layer.v1.tar",
             "digest": registry.add_blob(evil), "size": len(evil)},
        ],
    })
    with pytest.raises(ValueError, match="escapes"):
        oci_extract_image(f"http://{registry.host}/m:bad",
                          tmp_path / "safe")
    assert not (tmp_path / "escape.txt").exists()


def test_manifest_index_resolution(registry, tmp_path):
    """Manifest lists resolve to the linux/amd64 entry."""
    data = b"platform-blob"
    dg = registry.add_blob(data)
    child = registry.add_manifest("child", {
        "mediaType": "application/vnd.oci.image.manifest.v1+json",
        "layers": [{"mediaType": "application/vnd.ollama.image.model",
                    "digest": dg, "size": len(data)}],
    })
    registry.add_manifest("multi", {
        "mediaType": "application/vnd.oci.image.index.v1+json",
        "manifests": [
            {"digest": "sha256:deadbeef",
             "platform": {"os": "windows", "architecture": "amd64"}},
            {"digest": child,
             "platform": {"os": "linux", "architecture": "amd64"}},
        ],
    })
    out = ollama_fetch_model(f"http://{registry.host}/m:multi",
                             tmp_path / "m")
    assert out.read_bytes() == data


def test_downloader_routes_ollama_scheme(registry, tmp_path, monkeypatch):
    """download_uri dispatches ollama:// to the registry client (the
    NotImplementedError gate is gone)."""
    from localai_tpu.utils import downloader

    weights = b"model-via-downloader"
    dg = registry.add_blob(weights)
    registry.add_manifest("latest", {
        "mediaType": "application/vnd.oci.image.manifest.v1+json",
        "layers": [{"mediaType": "application/vnd.ollama.image.model",
                    "digest": dg, "size": len(weights)}],
    })
    dest = downloader.download_uri(
        f"ollama://http://{registry.host}/mymodel", tmp_path / "w.gguf"
    )
    assert dest.read_bytes() == weights
