"""Engine correctness tests on the virtual CPU mesh (tiny random models —
the analogue of the reference's tiny fixture models, SURVEY.md §4)."""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from localai_tpu.engine import sampling as smp
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models.registry import resolve_model


@pytest.fixture(scope="module")
def tiny():
    return resolve_model("debug:tiny", dtype="float32")


@pytest.fixture()
def runner(tiny):
    return ModelRunner(
        tiny.cfg, tiny.params, num_slots=4, max_ctx=96,
        prefill_buckets=[16, 32], kv_dtype="float32",
    )


def test_greedy_generation_deterministic(runner):
    prompt = list(b"hello world")
    s1 = runner.acquire_slot()
    t1 = runner.admit(s1, prompt, temperature=0.0)
    s2 = runner.acquire_slot()
    t2 = runner.admit(s2, prompt, temperature=0.0)
    assert t1 == t2
    outs1, outs2 = [t1], [t2]
    for _ in range(8):
        toks = runner.step()
        outs1.append(int(toks[s1]))
        outs2.append(int(toks[s2]))
    assert outs1 == outs2


def test_decode_matches_prefill_logits(tiny):
    """Next-token greedy choice must be identical whether the sequence is
    processed in one prefill or prefill+decode steps (KV-cache equivalence)."""
    prompt = list(b"abcdefgh")
    r_full = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=64,
                         prefill_buckets=[16], kv_dtype="float32")
    t_full = r_full.admit(0, prompt, temperature=0.0)

    r_inc = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=64,
                        prefill_buckets=[16], kv_dtype="float32")
    t_inc = r_inc.admit(0, prompt[:-1], temperature=0.0)
    # overwrite the sampled token with the true next prompt token, then decode
    r_inc.state = dataclasses.replace(
        r_inc.state, tokens=r_inc.state.tokens.at[0].set(prompt[-1])
    )
    toks = r_inc.step()
    assert int(toks[0]) == t_full


def test_slot_isolation(runner):
    """Generation in one slot must not change another slot's greedy output."""
    prompt_a = list(b"the quick brown fox")
    sa = runner.acquire_slot()
    runner.admit(sa, prompt_a, temperature=0.0)
    seq_solo = [int(runner.step()[sa]) for _ in range(6)]

    runner.release(sa)
    r2_slot_a = runner.acquire_slot()
    runner.admit(r2_slot_a, prompt_a, temperature=0.0)
    sb = runner.acquire_slot()
    runner.admit(sb, list(b"completely different text"), temperature=0.8, seed=7)
    seq_mixed = [int(runner.step()[r2_slot_a]) for _ in range(6)]
    assert seq_solo == seq_mixed


def test_seeded_sampling_reproducible(runner):
    prompt = list(b"sampling test")
    s1 = runner.acquire_slot()
    t1 = runner.admit(s1, prompt, temperature=1.0, seed=42)
    seq1 = [t1] + [int(runner.step()[s1]) for _ in range(5)]
    runner.release(s1)
    s2 = runner.acquire_slot()
    t2 = runner.admit(s2, prompt, temperature=1.0, seed=42)
    seq2 = [t2] + [int(runner.step()[s2]) for _ in range(5)]
    assert seq1 == seq2


def test_context_overflow_rejected(runner):
    s = runner.acquire_slot()
    with pytest.raises(ValueError, match="exceeds"):
        runner.admit(s, list(range(200)))


def test_sampling_top_k_and_penalties():
    V = 32
    logits = (
        jnp.zeros((2, V)).at[0, 5].set(10.0).at[1, 7].set(10.0).at[1, 2].set(5.0)
    )
    params = smp.SamplingParams.init(2)
    params = params.with_slot(0, temperature=0.0)
    params = params.with_slot(1, temperature=0.0, repeat_penalty=100.0)
    counts = jnp.zeros((2, V), jnp.int32).at[1, 7].set(1)
    keys = jax.random.split(jax.random.key(0), 2)
    toks, _ = smp.sample(logits, params, counts, keys)
    assert int(toks[0]) == 5          # plain greedy
    assert int(toks[1]) == 2          # repeat heavily penalized, competitor wins


def test_top_p_restricts_to_nucleus():
    V = 16
    # slot 0: two dominant tokens; top_p=0.5 must always pick the argmax
    logits = jnp.zeros((1, V)).at[0, 3].set(5.0).at[0, 9].set(4.9)
    params = smp.SamplingParams.init(1)
    params = params.with_slot(0, temperature=1.0, top_p=0.5, top_k=0)
    counts = jnp.zeros((1, V), jnp.int32)
    key = jax.random.split(jax.random.key(1), 1)
    for i in range(8):
        toks, key = smp.sample(logits, params, counts, key)
        key = key.reshape(1)
        assert int(toks[0]) == 3


def test_release_and_reuse(runner):
    s = runner.acquire_slot()
    runner.admit(s, list(b"abc"))
    runner.release(s)
    assert not runner.any_active
    s2 = runner.acquire_slot()
    t = runner.admit(s2, list(b"xyz"), temperature=0.0)
    assert isinstance(t, int)
    assert runner.any_active
