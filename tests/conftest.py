"""Test harness: force an 8-device virtual CPU mesh so all sharding paths are
exercised without TPU hardware (the driver separately dry-runs multi-chip via
__graft_entry__.dryrun_multichip). Mirrors the reference's strategy of gating
heavy backends out of unit tests (SURVEY.md §4)."""

import os

# The environment presets JAX_PLATFORMS=axon (the real TPU tunnel) and its
# sitecustomize imports jax at interpreter start, so env vars are captured
# before this file runs. Override via jax.config, which is honored until the
# backend is actually initialized (first device use).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture()
def tmp_models_dir(tmp_path):
    d = tmp_path / "models"
    d.mkdir()
    return d
