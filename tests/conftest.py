"""Test harness: force an 8-device virtual CPU mesh so all sharding paths are
exercised without TPU hardware (the driver separately dry-runs multi-chip via
__graft_entry__.dryrun_multichip). Mirrors the reference's strategy of gating
heavy backends out of unit tests (SURVEY.md §4)."""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def tmp_models_dir(tmp_path):
    d = tmp_path / "models"
    d.mkdir()
    return d
