"""Test harness: force an 8-device virtual CPU mesh so all sharding paths are
exercised without TPU hardware (the driver separately dry-runs multi-chip via
__graft_entry__.dryrun_multichip). Mirrors the reference's strategy of gating
heavy backends out of unit tests (SURVEY.md §4)."""

import os

# The environment presets JAX_PLATFORMS=axon (the real TPU tunnel) and its
# sitecustomize imports jax at interpreter start, so env vars are captured
# before this file runs. Override via jax.config, which is honored until the
# backend is actually initialized (first device use).
os.environ["JAX_PLATFORMS"] = "cpu"

# hermetic kernel tuning: a developer machine's ~/.cache tuning table must
# not change runner defaults (block size, impl) under test; the tuning
# tests opt back in with their own tmp-path tables
os.environ.setdefault("LOCALAI_TUNE_CACHE", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

if hasattr(jax.config, "jax_num_cpu_devices"):
    # JAX >= 0.5: first-class virtual CPU device count.
    jax.config.update("jax_num_cpu_devices", 8)
else:
    # Older JAX: the XLA flag is read at backend initialization (first
    # device use), not at import, so setting it here still works even
    # though jax is already imported — as long as no test ran yet.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import pytest  # noqa: E402

# Modules measured ≥ ~20 s on CPU CI (per-file wall clock, 2026-07) get the
# module-level `slow` marker, leaving a <2-minute inner-loop tier:
#   python -m pytest -m "not slow" -q     (fast tier)
#   python -m pytest -q                   (everything)
# Re-measure when adding heavy suites; pyproject registers the marker.
SLOW_MODULES = {
    "test_aio", "test_api", "test_audio", "test_cli", "test_controlnet",
    "test_engine",
    "test_flux", "test_hf_api", "test_image", "test_llama_torch",
    "test_lora",
    "test_mamba", "test_mesh_attn", "test_moe",
    "test_multihost", "test_musicgen", "test_ops", "test_prefix",
    "test_pipeline", "test_promptcache", "test_quant", "test_reranker",
    "test_ring",
    "test_rwkv", "test_sdxl", "test_selfextend", "test_sharding",
    "test_speculative",
    "test_vision", "test_vits", "test_voice_clone", "test_worker",
    "test_worker_serving",
}


def pytest_collection_modifyitems(config, items):
    import pathlib

    for item in items:
        if pathlib.Path(str(item.fspath)).stem in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture()
def tmp_models_dir(tmp_path):
    d = tmp_path / "models"
    d.mkdir()
    return d
