"""Audio subsystem: wav/mel/tts units, whisper engine, HTTP + worker.

Parity model: the reference's API suite drives /v1/audio/transcriptions
with a small real model (/root/reference/core/http/app_test.go whisper
cases); here the debug whisper preset (random weights) exercises the same
full pipeline — multipart upload → wav decode → mel → encoder/decoder →
segments — without downloads.
"""

import io

import numpy as np
import pytest

from localai_tpu.audio import mel as melmod
from localai_tpu.audio import tts as ttsmod
from localai_tpu.audio.wav import read_wav, write_wav
from localai_tpu.models import whisper as wh


def test_wav_roundtrip():
    x = np.sin(np.linspace(0, 440 * 2 * np.pi, 16000)).astype(np.float32)
    data = write_wav(x, 16000)
    back = read_wav(data)
    assert back.shape == x.shape
    assert np.abs(back - np.clip(x, -1, 1)).max() < 1e-3


def test_wav_resample_and_stereo():
    import wave

    x = (np.sin(np.linspace(0, 100, 8000)) * 32767).astype(np.int16)
    stereo = np.stack([x, x], axis=1).reshape(-1)
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(2)
        w.setsampwidth(2)
        w.setframerate(8000)
        w.writeframes(stereo.tobytes())
    back = read_wav(buf.getvalue(), target_rate=16000)
    assert abs(len(back) - 16000) < 10


def test_wav_garbage_rejected():
    with pytest.raises(ValueError, match="WAV"):
        read_wav(b"not a wav file at all")


def test_mel_shape_and_normalization():
    audio = np.random.default_rng(0).normal(
        size=melmod.CHUNK_SAMPLES).astype(np.float32)
    import jax.numpy as jnp

    filters = jnp.asarray(melmod.mel_filterbank())
    m = melmod.log_mel(jnp.asarray(audio), filters)
    assert m.shape == (melmod.N_MELS, melmod.CHUNK_FRAMES)
    assert np.isfinite(np.asarray(m)).all()
    # whisper normalization keeps values in a tight band
    assert float(np.asarray(m).max()) <= 4.0


def test_chunking():
    audio = np.zeros(melmod.CHUNK_SAMPLES * 2 + 100, np.float32)
    chunks = melmod.chunk_audio(audio)
    assert len(chunks) == 3
    assert all(len(c) == melmod.CHUNK_SAMPLES for c in chunks)


def test_tts_deterministic_and_voiced():
    a1 = ttsmod.synthesize("hello world", voice="alloy")
    a2 = ttsmod.synthesize("hello world", voice="alloy")
    b = ttsmod.synthesize("hello world", voice="onyx")
    np.testing.assert_array_equal(a1, a2)
    assert a1.shape == b.shape
    assert not np.array_equal(a1, b)      # voices differ
    assert np.abs(a1).max() <= 0.75       # normalized
    assert len(a1) > 8000                 # non-trivial duration


def test_sound_generation():
    s = ttsmod.generate_sound("ocean waves", duration=0.5)
    assert len(s) == 8000
    assert np.isfinite(s).all()


def test_whisper_debug_transcribe():
    model = wh.debug_model()
    audio = ttsmod.synthesize("abc", voice="alloy")[:16000]
    res = model.transcribe(audio)
    assert set(res) == {"text", "segments"}
    assert len(res["segments"]) == 1
    seg = res["segments"][0]
    assert seg["start"] == 0.0
    assert seg["end"] == pytest.approx(len(audio) / 16000, abs=0.1)
    # deterministic across calls
    res2 = model.transcribe(audio)
    assert res2["text"] == res["text"]


def test_audio_http_endpoints(tmp_path):
    from tests.test_api import _ServerThread, make_state
    import httpx

    models = tmp_path / "models"
    models.mkdir()
    (models / "whisper-test.yaml").write_text(
        "name: whisper-test\nbackend: whisper\nmodel: 'debug:whisper'\n"
    )
    state = make_state(models)
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=300.0) as client:
            # TTS → wav bytes
            r = client.post("/v1/audio/speech",
                            json={"input": "hi there", "voice": "alloy"})
            assert r.status_code == 200
            assert r.headers["content-type"].startswith("audio/wav")
            wav_bytes = r.content
            assert read_wav(wav_bytes).size > 0

            r2 = client.post("/tts", json={"text": "hi there"})
            assert r2.status_code == 200

            r = client.post("/v1/text-to-speech/rachel",
                            json={"text": "eleven"})
            assert r.status_code == 200

            r = client.post("/v1/sound-generation",
                            json={"text": "thunder", "duration_seconds": 0.5})
            assert r.status_code == 200
            assert len(read_wav(r.content)) == 8000

            # transcription: send the TTS output through debug whisper
            r = client.post(
                "/v1/audio/transcriptions",
                files={"file": ("speech.wav", wav_bytes, "audio/wav")},
                data={"model": "whisper-test"},
            )
            assert r.status_code == 200, r.text
            body = r.json()
            assert "text" in body and "segments" in body

            r = client.post(
                "/v1/audio/transcriptions",
                files={"file": ("x.mp3", b"garbage", "audio/mpeg")},
                data={"model": "whisper-test"},
            )
            assert r.status_code == 400

            r = client.post("/v1/audio/speech", json={"input": ""})
            assert r.status_code == 400
    finally:
        srv.stop()


def test_audio_worker_grpc(tmp_path):
    from localai_tpu.worker import WorkerClient
    from localai_tpu.worker.server import AudioServicer, serve_worker

    server, port = serve_worker("127.0.0.1:0", servicer=AudioServicer(),
                                block=False)
    try:
        c = WorkerClient(f"127.0.0.1:{port}")
        assert c.health()
        res = c.load_model(model="debug:whisper")
        assert res.success, res.message

        tts_res = c.tts("worker speech", voice="alloy")
        assert tts_res.success
        audio = read_wav(tts_res.audio)
        assert audio.size > 0

        dst = str(tmp_path / "out.wav")
        tts_res = c.tts("to file", dst=dst)
        assert tts_res.success and tts_res.message == dst
        assert read_wav(open(dst, "rb").read()).size > 0

        clip = audio[:16000]
        tr = c.transcribe(audio=write_wav(clip))
        expected_ns = int(len(clip) / 16000 * 1e9)
        assert abs(tr.segments[0].end - expected_ns) < 1e7

        snd = c.sound_generation("beep", duration=0.5)
        assert snd.success
        c.close()
    finally:
        server.stop(grace=None)


def test_audio_models_under_lifecycle_management(tmp_path):
    """Whisper/VITS models load through the ModelManager: they appear in
    loaded_names, expose metrics, and evict like every other model (the
    round-2 image-cache criticism, applied to audio)."""

    import httpx
    from test_api import _ServerThread, make_state

    (tmp_path / "w.yaml").write_text(
        "name: w\nmodel: 'debug:whisper-tiny'\nbackend: whisper\n"
        "known_usecases: [transcript]\n"
    )
    srv = _ServerThread(make_state(tmp_path))
    try:
        import io
        import wave

        import numpy as np

        buf = io.BytesIO()
        with wave.open(buf, "wb") as wf:
            wf.setnchannels(1)
            wf.setsampwidth(2)
            wf.setframerate(16000)
            wf.writeframes(np.zeros(16000, np.int16).tobytes())
        with httpx.Client(base_url=srv.base, timeout=300.0) as c:
            r = c.post("/v1/audio/transcriptions",
                       files={"file": ("a.wav", buf.getvalue())},
                       data={"model": "w"})
            assert r.status_code == 200, r.text
        assert "w" in srv.state.manager.loaded_names()
        sm = srv.state.manager.get_whisper("w")
        m = sm.engine_metrics()
        assert m["type"] == "whisper"
        assert m["requests_served"] == 1
        # manager-level eviction works
        assert srv.state.manager.shutdown_model("w")
        assert "w" not in srv.state.manager.loaded_names()
    finally:
        srv.stop()


def test_whisper_cached_greedy_matches_stepwise():
    """The ONE-dispatch KV-cached greedy decode (decode_greedy) must
    produce exactly the tokens of the naive full-recompute step loop it
    replaced (same argmax chain, cross-attn KV precomputed)."""
    import jax.numpy as jnp

    m = wh.debug_model()
    cfg = m.cfg
    rng = np.random.default_rng(4)
    from localai_tpu.audio import mel as melmod

    # full chunk length: log_mel frames CHUNK_SAMPLES — shorter input
    # would read clamped out-of-bounds garbage frames
    audio = np.zeros(melmod.CHUNK_SAMPLES, np.float32)
    audio[:16000] = (rng.normal(size=16000) * 0.2).astype(np.float32)
    mel_arr = melmod.log_mel(jnp.asarray(audio), m.filters,
                             n_mels=cfg.n_mels)
    enc = m._encode(m.params, mel_arr)

    prompt = [cfg.sot, wh.language_token(cfg, None), cfg.token_transcribe,
              cfg.token_notimestamps]
    limit = 12

    # reference: naive loop over decode_logits
    buf = np.zeros(cfg.max_target_positions, np.int32)
    buf[:len(prompt)] = prompt
    toks = jnp.asarray(buf)
    n = len(prompt)
    ref = []
    for _ in range(limit):
        nxt = int(jnp.argmax(wh.decode_logits(
            cfg, m.params, toks, jnp.int32(n), enc)))
        if nxt == cfg.eot:
            break
        ref.append(nxt)
        toks = toks.at[n].set(nxt)
        n += 1

    out_buf, n_total = wh.decode_greedy(
        cfg, m.params, jnp.asarray(buf), jnp.int32(len(prompt)), enc,
        jnp.int32(limit))
    got = list(np.asarray(out_buf)[len(prompt): int(n_total)])
    assert got == ref
