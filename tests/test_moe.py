"""Mixtral-class sparse MoE: torch parity, engine serving, expert-axis
sharding, and quantized serving (VERDICT r4 #5 — 'make the expert axis
real'). Parity surface: the reference serves Mixtral GGUFs through
llama.cpp (gallery mixtral entries)."""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models import llama as mdl
from localai_tpu.models.registry import DEBUG_PRESETS, resolve_model
from localai_tpu.parallel import sharding as shd
from localai_tpu.parallel.mesh import MeshPlan, build_mesh

torch = pytest.importorskip("torch")
from transformers import MixtralConfig as HFMixtralConfig  # noqa: E402
from transformers import MixtralForCausalLM  # noqa: E402

from localai_tpu.models.loader import load_llama_params  # noqa: E402


def _tiny_mixtral(tmp_path, seed=0):
    torch.manual_seed(seed)
    cfg = HFMixtralConfig(
        vocab_size=96, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=256, rope_theta=10000.0,
        sliding_window=None, tie_word_embeddings=False,
    )
    model = MixtralForCausalLM(cfg).eval()
    d = tmp_path / "mixtral"
    model.save_pretrained(d, safe_serialization=True)
    return model, d


def _load_f32(d):
    cfg, params = load_llama_params(d, dtype="float32")
    return dataclasses.replace(cfg, dtype="float32"), params


PROMPT = [5, 17, 3, 42, 9, 88, 1, 63]


def test_mixtral_logits_match_torch(tmp_path):
    model, d = _tiny_mixtral(tmp_path)
    cfg, params = _load_f32(d)
    assert cfg.num_experts == 4 and cfg.num_experts_per_tok == 2

    import jax.numpy as jnp

    from localai_tpu.engine import kvcache as kvc

    T = len(PROMPT)
    tokens = jnp.asarray(np.asarray(PROMPT, np.int32)[None])
    kv = kvc.init_cache(cfg, 1, 64, "float32")
    hidden, _ = mdl.forward(
        cfg, params, tokens, jnp.arange(T, dtype=jnp.int32)[None],
        kvc.prefill_write(jnp.int32(0), jnp.zeros((), jnp.int32)),
        kv.stacked(), kvc.prefill_mask(cfg, T, jnp.int32(T)),
        mdl.rope_table(cfg, 64),
    )
    ours = np.asarray(mdl.logits_from_hidden(cfg, params, hidden[0]))
    with torch.no_grad():
        ref = model(torch.tensor([PROMPT])).logits[0].float().numpy()
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-4)


def test_mixtral_engine_greedy_matches_torch(tmp_path):
    model, d = _tiny_mixtral(tmp_path)
    cfg, params = _load_f32(d)
    runner = ModelRunner(cfg, params, num_slots=2, max_ctx=64,
                         prefill_buckets=[16], kv_dtype="float32")
    s = runner.acquire_slot()
    ours = [runner.admit(s, PROMPT, temperature=0.0)]
    while len(ours) < 10:
        ours.append(int(runner.step()[s]))

    ids = list(PROMPT)
    with torch.no_grad():
        for _ in range(10):
            ids.append(int(model(torch.tensor([ids])).logits[0, -1].argmax()))
    assert ours == ids[len(PROMPT):]


def test_expert_axis_shards_weights_and_preserves_output():
    """data×expert×model mesh: expert weights REALLY shard over 'expert'
    (addressable shard carries E/ep experts) and greedy output matches the
    unsharded runner."""
    moe = resolve_model("debug:tiny-moe", dtype="float32")
    mesh = build_mesh(MeshPlan(data=2, expert=2, model=2))
    sp = shd.shard_params(moe.params, moe.cfg, mesh)

    wg = sp["layers"]["w_gate"]
    shard = wg.addressable_shards[0].data
    E = moe.cfg.num_experts
    assert wg.shape[1] == E
    assert shard.shape[1] == E // 2, "expert axis not actually sharded"
    assert shard.shape[3] == wg.shape[3] // 2, "ffn axis not TP-sharded"

    r = ModelRunner(moe.cfg, sp, num_slots=4, max_ctx=128,
                    prefill_buckets=[32], kv_dtype="float32", mesh=mesh)
    s = r.acquire_slot()
    out = [r.admit(s, PROMPT, temperature=0.0)] + [int(r.step()[s])
                                                   for _ in range(6)]

    rx = ModelRunner(moe.cfg, moe.params, num_slots=2, max_ctx=128,
                     prefill_buckets=[32], kv_dtype="float32")
    s2 = rx.acquire_slot()
    ref = [rx.admit(s2, PROMPT, temperature=0.0)] + [int(rx.step()[s2])
                                                     for _ in range(6)]
    assert out == ref


def test_quantized_moe_serving():
    """int8 quantization covers the expert weights (per-channel over the
    contraction axis) and the quantized engine still routes/serves."""
    from localai_tpu.models.quant import QuantizedTensor, quantize_params

    moe = resolve_model("debug:tiny-moe", dtype="float32")
    q = quantize_params(moe.params)
    wg = q["layers"]["w_gate"]
    assert isinstance(wg, QuantizedTensor) and wg.axis == 2
    L, E, D, F = moe.params["layers"]["w_gate"].shape
    assert wg.scale.shape == (L, E, F)
    assert not isinstance(q["layers"]["moe_gate"], QuantizedTensor)

    cfg = dataclasses.replace(moe.cfg, dtype="bfloat16")
    r = ModelRunner(cfg, q, num_slots=2, max_ctx=128,
                    prefill_buckets=[32], kv_dtype="int8")
    s = r.acquire_slot()
    first = r.admit(s, PROMPT, temperature=0.0)
    toks = [first] + [int(r.step()[s]) for _ in range(4)]
    assert all(0 <= t < cfg.vocab_size for t in toks)


def test_synthetic_quantized_moe_params():
    from localai_tpu.models.registry import synthetic_quantized_params

    cfg = dataclasses.replace(DEBUG_PRESETS["tiny-moe"], dtype="bfloat16")
    params = synthetic_quantized_params(cfg, "int8")
    assert params["layers"]["w_gate"].q.shape[1] == cfg.num_experts
    r = ModelRunner(cfg, params, num_slots=2, max_ctx=128,
                    prefill_buckets=[32], kv_dtype="int8")
    s = r.acquire_slot()
    r.admit(s, PROMPT, temperature=0.0)
    assert r.step().shape == (2,)


def test_moe_through_scheduler(tmp_path):
    """End-to-end: YAML → build_serving_model → scheduler generation on the
    MoE preset."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.models.manager import build_serving_model

    mcfg = ModelConfig(
        name="moe", model="debug:tiny-moe", context_size=256,
        engine={"max_slots": 2, "prefill_buckets": [32]},
    )
    sm = build_serving_model(mcfg, AppConfig(model_path=str(tmp_path)))
    try:
        h = sm.scheduler.submit(GenRequest(
            prompt=PROMPT, max_new_tokens=4, temperature=0.0,
        ))
        h.result(timeout=120)
        assert h.finish_reason in ("stop", "length")
    finally:
        sm.scheduler.shutdown()
