"""Fleet KV economy: prefix directory, HBM→host tiering, migration.

Directory and tier semantics run as pure-host units; routing integration
runs against stub replicas; spill→reload parity and the tier-residency
audit run against real paged runners (f32 AND nibble-packed int4); the
churn invariant — a stale directory entry costs one failed fetch, never
a request error — runs against a real 2-replica in-process fleet. The
acceptance matrix of ISSUE 17 on CPU."""

import threading

import numpy as np
import pytest

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import GenRequest
from localai_tpu.fleet.kveconomy import HostTier, PrefixDirectory
from localai_tpu.fleet.kveconomy.directory import directory_capacity_from_env
from localai_tpu.fleet.kveconomy.migration import (MigrationTicket,
                                                  continuation_request)
from localai_tpu.fleet.kveconomy.tiering import tier_from_env
from localai_tpu.fleet.router import Router, affinity_key
from localai_tpu.models.registry import resolve_model
from localai_tpu.utils.tokenizer import ByteTokenizer


def _payload(n=64):
    a = np.arange(n, dtype=np.float32)
    return {"k": a, "v": a + 1.0}


# ---------------------------------------------------------------------------
# host tier (pure numpy LRU)


def test_host_tier_put_take_discard():
    tier = HostTier(1 << 20)
    assert tier.put("a", _payload())
    assert tier.contains("a")
    got = tier.take("a")
    np.testing.assert_array_equal(got["k"], _payload()["k"])
    # take CONSUMES the spill: a chain is HBM-resident xor spilled
    assert not tier.contains("a") and tier.take("a") is None
    tier.put("b", _payload())
    tier.discard("b")
    st = tier.stats()
    assert st["entries"] == 0 and st["bytes"] == 0
    assert st["stores_total"] == 2 and st["takes_total"] == 1


def test_host_tier_byte_budget_evicts_lru():
    one = 2 * _payload()["k"].nbytes          # bytes of one payload
    tier = HostTier(2 * one)                  # room for exactly two
    tier.put("a", _payload())
    tier.put("b", _payload())
    tier.put("c", _payload())                 # budget → LRU "a" dropped
    assert not tier.contains("a")
    assert tier.contains("b") and tier.contains("c")
    st = tier.stats()
    assert st["budget_drops_total"] == 1 and st["bytes"] <= 2 * one
    # re-putting an existing key replaces, never double-counts
    tier.put("c", _payload())
    assert tier.stats()["bytes"] <= 2 * one


def test_host_tier_oversize_reject_and_env_knob(monkeypatch):
    tier = HostTier(16)                       # smaller than any payload
    assert not tier.put("big", _payload())
    st = tier.stats()
    assert st["oversize_rejects_total"] == 1 and st["entries"] == 0
    with pytest.raises(ValueError):
        HostTier(0)
    monkeypatch.delenv("LOCALAI_KV_TIER_MB", raising=False)
    assert tier_from_env() is None            # off by default (seed shape)
    monkeypatch.setenv("LOCALAI_KV_TIER_MB", "2")
    t = tier_from_env()
    assert t is not None and t.budget_bytes == 2 << 20
    monkeypatch.setenv("LOCALAI_KV_TIER_MB", "not-a-number")
    assert tier_from_env() is None


# ---------------------------------------------------------------------------
# prefix directory (pure host map)


def test_directory_note_lookup_prefers_freshest():
    d = PrefixDirectory(max_entries=64)
    d.note(1, "m/r0")
    d.note(1, "m/r1")                          # freshest holder
    assert d.lookup(1, ["m/r0", "m/r1"]) == "m/r1"
    assert d.lookup(1, ["m/r0"]) == "m/r0"     # eligibility filters
    assert d.lookup(1, ["m/r9"]) is None       # no eligible holder = miss
    assert d.lookup(2, ["m/r0"]) is None       # unknown key = miss
    assert d.lookup(None, ["m/r0"]) is None    # short prompt: no key
    st = d.stats()
    assert st["hits"] == 2 and st["misses"] == 2 and st["notes"] == 2


def test_directory_holder_is_counter_silent_and_excludes():
    d = PrefixDirectory(max_entries=64)
    d.note(7, "m/r0")
    d.note(7, "m/r1")
    assert d.holder(7, ["m/r0", "m/r1"], exclude=["m/r1"]) == "m/r0"
    assert d.holder(7, ["m/r1"], exclude=["m/r1"]) is None
    st = d.stats()
    assert st["hits"] == 0 and st["misses"] == 0


def test_directory_drop_and_drop_replica():
    d = PrefixDirectory(max_entries=64)
    for key in (1, 2, 3):
        d.note(key, "m/r0")
    d.note(2, "m/r1")
    d.drop(2, "m/r0")                          # stale holder forgotten…
    assert d.lookup(2, ["m/r0"]) is None
    assert d.lookup(2, ["m/r1"]) == "m/r1"     # …other holders survive
    d.drop(9, "m/r0")                          # unknown key: no-op
    touched = d.drop_replica("m/r0")           # death listener path
    assert touched == 2                        # keys 1 and 3
    assert d.lookup(1, ["m/r0"]) is None
    assert d.stats()["entries"] == 1           # key 2 via m/r1 remains
    assert d.stats()["invalidations"] == 1     # one whole-replica event
    assert d.drop_replica("m/r0") == 0         # idempotent, not recounted
    assert d.stats()["invalidations"] == 1


def test_directory_lru_cap_and_env(monkeypatch):
    d = PrefixDirectory(max_entries=4)
    for key in range(8):
        d.note(key, "m/r0")
    assert d.stats()["entries"] == 4
    assert d.lookup(0, ["m/r0"]) is None       # oldest keys fell off
    assert d.lookup(7, ["m/r0"]) == "m/r0"
    monkeypatch.setenv("LOCALAI_KV_DIR_ENTRIES", "32")
    assert directory_capacity_from_env() == 32
    monkeypatch.setenv("LOCALAI_KV_DIR_ENTRIES", "2")
    assert directory_capacity_from_env() == 16  # floor
    monkeypatch.setenv("LOCALAI_KV_DIR_ENTRIES", "junk")
    assert directory_capacity_from_env() == 4096


# ---------------------------------------------------------------------------
# router integration (stub replicas)


class _StubReplica:
    def __init__(self, rid, role="decode", queue_depth=0):
        self.id, self.role, self.state = rid, role, "healthy"
        self.inflight = 0
        self.dispatched = 0
        self.queue_depth = queue_depth

    @property
    def load(self):
        return (self.inflight, self.dispatched)


class _StubPool:
    def __init__(self, replicas):
        self.replicas = replicas

    def healthy(self, role="decode"):
        return [r for r in self.replicas
                if r.state == "healthy" and r.role == role]


def test_router_directory_overrides_ring_affinity():
    pool = _StubPool([_StubReplica(f"m/r{i}") for i in range(3)])
    prompt = [7] * 64
    ring_pick = Router(pool, None, block_tokens=16).route(prompt)[0].id
    warm = next(r.id for r in pool.replicas if r.id != ring_pick)
    directory = PrefixDirectory(max_entries=64)
    directory.note(affinity_key(prompt, block_tokens=16), warm)
    router = Router(pool, None, block_tokens=16, directory=directory)
    pick, reason = router.route(prompt)
    assert pick.id == warm and reason == "directory"
    assert router.routed["directory"] == 1
    # failover re-dispatch through a directory hit is tagged failover
    pick, reason = router.route(prompt, failover=True)
    assert pick.id == warm and reason == "failover"
    # the holder excluded (it just failed this request) → ring fallback
    pick, reason = router.route(prompt, exclude={warm})
    assert pick.id != warm and reason in ("affinity", "least_loaded")


def test_router_directory_respects_queue_override():
    drowning = _StubReplica("m/r0", queue_depth=9)
    drowning.inflight = 3                      # drowning ⇒ loaded
    idle = _StubReplica("m/r1")
    pool = _StubPool([drowning, idle])
    directory = PrefixDirectory(max_entries=64)
    prompt = [3] * 64
    directory.note(affinity_key(prompt, block_tokens=16), drowning.id)
    router = Router(pool, None, block_tokens=16, directory=directory,
                    queue_override=2)
    pick, reason = router.route(prompt)
    # warm KV never beats a drowning queue: fall through to placement
    # (the sibling fetch moves the KV to wherever the request lands)
    assert pick.id == idle.id and reason != "directory"


# ---------------------------------------------------------------------------
# migration primitives


def test_migration_ticket_fail_and_finish():
    t = MigrationTicket("m/r1")
    assert not t.ready.is_set() and not t.error
    t.fail("donor exploded")
    assert t.ready.is_set() and t.error == "donor exploded"
    done = {}

    def waiter():
        t.completed.wait(5.0)
        done["outcome"] = t.outcome

    th = threading.Thread(target=waiter)
    th.start()
    t.finish("fallback")
    th.join(5.0)
    assert done["outcome"] == "fallback"


def test_continuation_request_budget_and_record():
    req = GenRequest(prompt=[1, 2, 3], max_new_tokens=10,
                     temperature=0.0, correlation_id="c-1")
    cont = continuation_request(req, [1, 2, 3, 50, 51], donor_tokens=2)
    assert cont.prompt == [1, 2, 3, 50, 51]
    assert cont.max_new_tokens == 8
    assert cont.correlation_id == "c-1"       # identity carries over
    assert req.prompt == [1, 2, 3]            # original untouched
    # budget exhausted at the boundary clamps to zero, never negative
    spent = continuation_request(req, [1, 2, 3, 50], donor_tokens=99)
    assert spent.max_new_tokens == 0


# ---------------------------------------------------------------------------
# spill → reload against real paged runners


def _tiered_runner(kv_dtype):
    tiny = resolve_model("debug:tiny", dtype="float32")
    r = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=96,
                    prefill_buckets=[16, 32], kv_dtype=kv_dtype,
                    paged=True, kv_block_tokens=16, prefill_chunk=16,
                    kv_num_blocks=12)
    tier = HostTier(8 << 20)
    r.allocator.attach_tier(tier, pack=r.pack_block, load=r.load_block)
    return r, tier


def _generate(r, prompt, steps=5):
    s = r.acquire_slot()
    out = [r.admit(s, list(prompt), temperature=0.0)]
    out += [int(r.step()[s]) for _ in range(steps)]
    r.release(s)
    return out


@pytest.mark.parametrize("kv_dtype", ["float32", "int4"])
def test_spill_reload_preserves_greedy_output(kv_dtype):
    """A chain evicted to the host tier and re-onboarded on the next
    prefix hit must decode byte-identically to its first run — for the
    f32 pool AND the nibble-packed int4 pool (blocks spill packed)."""
    r, tier = _tiered_runner(kv_dtype)
    prompt = list(b"spill me to host ram and bring me back intact")
    ref = _generate(r, prompt)
    # distinct cold chains crush the 12-block pool: the reference chain
    # is the LRU victim and MUST spill instead of vanishing
    filler = 0
    while r.allocator.spills_total < 1 and filler < 12:
        _generate(r, [60 + filler] * 33, steps=2)
        filler += 1
    assert r.allocator.spills_total >= 1, "pool pressure never spilled"
    assert tier.stats()["entries"] >= 1
    again = _generate(r, prompt)
    assert r.allocator.reloads_total >= 1, "prefix hit never reloaded"
    assert again == ref
    assert not r.allocator.check_invariants()
    ts = r.allocator.tier_stats()
    assert ts["spills_total"] == r.allocator.spills_total
    assert ts["reloads_total"] == r.allocator.reloads_total


def test_tier_residency_audit_catches_violations():
    """check_invariants: a chain resident in the HBM pool AND the tier
    (a reload that forgot to consume its spill) and an over-budget tier
    are both flagged."""
    r, tier = _tiered_runner("float32")
    prompt = list(b"audit this chain for double residency today")
    _generate(r, prompt)
    assert not r.allocator.check_invariants()
    # forge the violation: park a payload under a LIVE pool chain's key
    key = next(iter(r.allocator._prefix))
    tier.put(key, _payload())
    problems = r.allocator.check_invariants()
    assert any("AND spilled" in p for p in problems)
    tier.take(key)
    assert not r.allocator.check_invariants()
    # over-budget accounting (internal poke: put() itself enforces the
    # budget, so only a bookkeeping bug can get the tier here)
    tier._bytes = tier.budget_bytes + 1
    assert any("over budget" in p for p in r.allocator.check_invariants())
    tier._bytes = 0


# ---------------------------------------------------------------------------
# directory churn against a real 2-replica fleet


def _fleet(name):
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.models.manager import build_serving_model

    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": name, "model": "debug:tiny", "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 8},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64, 128],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16},
    })

    def factory(rid, role):
        return InProcessReplica(
            rid, role, lambda: build_serving_model(mcfg, app))

    return FleetServingModel(mcfg, app, factory, replicas=2,
                             prefill_replicas=0, disagg_threshold=10_000)


def _req(text, **kw):
    kw.setdefault("temperature", 0.0)
    kw.setdefault("max_new_tokens", 6)
    return GenRequest(prompt=ByteTokenizer().encode(text), **kw)


def _raise_evicted(*a, **kw):
    raise RuntimeError("blocks evicted")


def test_stale_directory_entry_costs_one_fetch_never_a_request():
    """Churn invariant (ISSUE 17 satellite): a directory entry whose
    holder no longer has the prefix costs exactly one failed sibling
    fetch — the entry is dropped, the request re-prefills on its placed
    replica, and NO request ever errors."""
    fm = _fleet("kv-churn")
    try:
        head = "directory churn prefix family head " * 3   # > 4 blocks
        warm = fm.scheduler.submit(_req(head + " warm"))
        warm.result(180)
        assert warm.finish_reason in ("stop", "length")
        req = _req(head + " again")
        key = affinity_key(req.prompt, block_tokens=fm.router.block_tokens,
                           blocks=fm.router.affinity_blocks)
        ids = [r.id for r in fm.pool.replicas]
        holder_id = fm.scheduler.directory.holder(key, ids)
        assert holder_id is not None, "completed request never noted"
        holder = fm.pool.get(holder_id)
        other = next(r for r in fm.pool.replicas if r.id != holder_id)
        # churn: the holder evicted the family's blocks — every export
        # surface now fails (the shape a dying/LRU-thrashed donor shows)
        holder.export_cached = _raise_evicted
        holder.prefill_prefix = _raise_evicted
        # placement landed away from the warm KV → the fetch runs, fails
        # once, and the stale entry is gone
        assert not fm.scheduler._sibling_fetch(req, other, None)
        assert fm.scheduler.sibling_fallbacks == 1
        assert fm.scheduler.directory.holder(key, [holder_id]) is None
        # the REQUEST path stays clean: same family, plain re-prefill
        h = fm.scheduler.submit(req)
        h.result(180)
        assert h.finish_reason in ("stop", "length")
        assert fm.scheduler.sibling_fallbacks == 1   # one fetch, total
    finally:
        fm.close()
