"""Fleet router: placement, failover, shed route-around, disaggregation.

Placement invariants run against stub replicas (pure host arithmetic);
serving invariants run against a real 2-decode + 1-prefill in-process
fleet of the tiny debug model; the wire contract (PrefillPrefix →
TransferPrefix) runs against two real in-process gRPC workers — the
acceptance matrix of ISSUE 7 on CPU."""

import threading
import time

import numpy as np
import pytest

from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.engine.scheduler import GenRequest
from localai_tpu.fleet.prefix import PrefixCache, assemble_chunks, pack_chunks
from localai_tpu.fleet.replica import BaseReplica, _Reply
from localai_tpu.fleet.router import FleetUnavailable, Router, affinity_key

TINY = {
    "name": "ftiny", "model": "debug:tiny", "context_size": 256,
    "parameters": {"temperature": 0.0, "max_tokens": 8},
    "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64, 128],
               "dtype": "float32", "kv_dtype": "float32",
               "kv_block_tokens": 16},
}


# ---------------------------------------------------------------------------
# wire codec + prefix cache (no engines)


def _fake_arrays(n=24, bf16=False):
    k = np.arange(2 * 3 * n * 4, dtype=np.float32).reshape(2, 3, n, 4)
    out = {"k": k, "v": k + 1.0,
           "kv_dtype": np.asarray("float32"), "kv_rope": np.asarray("roped")}
    if bf16:
        out["k"] = out["k"].astype(np.uint16)
        out["k_bf16"] = np.int8(1)
    return out


def test_chunk_roundtrip_and_ordering():
    tokens = list(range(100, 140))
    arrays = _fake_arrays(bf16=True)
    chunks = list(pack_chunks(tokens, arrays, chunk_bytes=256))
    assert len(chunks) > 1 and chunks[-1]["last"]
    assert chunks[0]["tokens"] == tokens and chunks[0]["n_tokens"] == 24
    got_tokens, got = assemble_chunks(iter(chunks))
    assert got_tokens == tokens
    np.testing.assert_array_equal(got["k"], arrays["k"])
    np.testing.assert_array_equal(got["v"], arrays["v"])
    assert "k_bf16" in got  # dtype markers survive the wire

    # out-of-order and truncated streams are refused, not mis-assembled
    with pytest.raises(ValueError, match="out-of-order"):
        assemble_chunks(iter([chunks[1]]))
    with pytest.raises(ValueError, match="truncated"):
        assemble_chunks(iter(chunks[:-1]))


def test_prefix_cache_lcp_and_wait():
    cache = PrefixCache(min_prefix=8)
    tokens = list(range(24))
    cache.store(tokens, _fake_arrays())
    # full-prompt hit still leaves the 1-token recompute tail
    hit = cache.lookup(tokens + [99])
    assert hit is not None and hit.n == 24 and hit.tokens == tokens
    assert cache.lookup(list(range(50, 60))) is None  # no shared prefix
    # a store below min_prefix never lands
    cache.store([1, 2, 3], _fake_arrays(n=3))
    assert cache.stats()["stores"] == 1

    # wait_for unblocks a waiter when the writer thread stores
    got = {}

    def waiter():
        got["arrays"] = cache.wait_for(list(range(200, 224)), timeout=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    cache.store(list(range(200, 224)), _fake_arrays())
    t.join(5.0)
    assert got["arrays"] is not None


def test_assemble_rejects_corrupt_payload():
    # a garbled npz body must surface as ValueError (the TransferPrefix
    # handler maps that to INVALID_ARGUMENT), never zipfile.BadZipFile
    chunks = [{"transfer_id": "t", "seq": 0, "last": True,
               "data": b"PK\x03\x04 definitely not an npz",
               "tokens": list(range(20)), "n_tokens": 20}]
    with pytest.raises(ValueError, match="corrupt"):
        assemble_chunks(chunks)


def test_prefix_cache_byte_budget_and_disk_fallthrough(tmp_path):
    # byte budget: evict LRU past max_bytes, keep the newest entry even
    # when it alone exceeds the budget (the exporter blocks on it)
    small = PrefixCache(min_prefix=8, max_bytes=1)
    small.store(list(range(24)), _fake_arrays())
    assert small.stats()["entries"] == 1
    small.store(list(range(100, 124)), _fake_arrays())
    assert small.stats()["entries"] == 1  # first evicted, newest kept

    # fallthrough: stores forward to a disk tier; a RAM miss falls
    # through to it (a fleet replica with a configured disk prompt cache
    # keeps both reuse tiers — scheduler.attach_prompt_cache layer=True)
    from localai_tpu.engine.promptcache import PromptKVCache

    disk = PromptKVCache(tmp_path, min_prefix=8)
    ram = PrefixCache(min_prefix=8, fallthrough=disk, max_entries=1)
    ram.store(list(range(24)), _fake_arrays())
    assert disk.stats()["stores"] == 1
    ram.store(list(range(200, 224)), _fake_arrays())  # evicts the first
    hit = ram.lookup(list(range(24)) + [99])          # RAM miss → disk hit
    assert hit is not None and hit.n == 24


# ---------------------------------------------------------------------------
# router placement (stub replicas)


class _StubReplica:
    def __init__(self, rid, role="decode", state="healthy", inflight=0):
        self.id, self.role, self.state = rid, role, state
        self.inflight = inflight
        self.dispatched = 0

    @property
    def load(self):
        return (self.inflight, self.dispatched)


class _StubPool:
    def __init__(self, replicas):
        self.replicas = replicas

    def healthy(self, role="decode"):
        return [r for r in self.replicas
                if r.state == "healthy" and r.role == role]


def _prompt(seed, tail=0):
    return [seed] * 64 + list(range(tail))


def test_affinity_keeps_same_prefix_on_one_replica():
    pool = _StubPool([_StubReplica(f"m/r{i}") for i in range(3)])
    router = Router(pool, None, block_tokens=16)
    # same first blocks, different tails → same replica every time
    picks = {router.route(_prompt(7, tail=t))[0].id for t in (0, 5, 11, 23)}
    assert len(picks) == 1
    assert router.routed["affinity"] == 4
    # a short prompt (no full block) has no affinity signal
    _, reason = router.route([1, 2, 3])
    assert reason == "least_loaded"


def test_consistent_hashing_remaps_only_the_lost_replica():
    ids = [f"m/r{i}" for i in range(3)]
    full = _StubPool([_StubReplica(r) for r in ids])
    prompts = [_prompt(s) for s in range(40)]
    before = {tuple(p): Router(full, None, block_tokens=16).route(p)[0].id
              for p in prompts}
    lost = ids[2]
    smaller = _StubPool([_StubReplica(r) for r in ids[:2]])
    router = Router(smaller, None, block_tokens=16)
    moved = sum(
        1 for p in prompts
        if before[tuple(p)] != lost
        and router.route(p)[0].id != before[tuple(p)]
    )
    assert moved == 0  # only the lost replica's keys remap


def test_shed_replica_routed_around():
    pool = _StubPool([_StubReplica(f"m/r{i}") for i in range(3)])
    router = Router(pool, None, block_tokens=16)
    target = router.route(_prompt(3))[0]

    class _Shed:
        def __init__(self, shed):
            self.shed = shed

        def shedding(self, rid):
            return rid in self.shed

    router = Router(pool, _Shed({target.id}), block_tokens=16)
    pick, reason = router.route(_prompt(3))
    assert pick.id != target.id and reason == "affinity"
    assert router.routed_around == 1
    # every replica shedding: degrade to serving, not a fleet-wide 503
    router = Router(pool, _Shed({r.id for r in pool.replicas}),
                    block_tokens=16)
    assert router.route(_prompt(3))[0] is not None


def test_failover_excludes_and_exhausts():
    pool = _StubPool([_StubReplica("m/r0"), _StubReplica("m/r1")])
    router = Router(pool, None, block_tokens=16)
    p = _prompt(9)
    first = router.route(p)[0]
    second, reason = router.route(p, exclude={first.id}, failover=True)
    assert second.id != first.id and reason == "failover"
    with pytest.raises(FleetUnavailable):
        router.route(p, exclude={first.id, second.id})


def test_affinity_key_block_granularity():
    assert affinity_key(list(range(10)), block_tokens=16) is None
    a = affinity_key(list(range(100)), block_tokens=16, blocks=4)
    b = affinity_key(list(range(64)) + [999] * 36, block_tokens=16, blocks=4)
    assert a == b  # only the first K blocks participate
    assert a != affinity_key([5] + list(range(1, 100)), block_tokens=16)


# ---------------------------------------------------------------------------
# in-process fleet (real engines)


def _build_fleet(replicas=2, prefill=1, threshold=48):
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.models.manager import build_serving_model

    app = AppConfig()
    mcfg = ModelConfig.model_validate(TINY)

    def factory(rid, role):
        return InProcessReplica(
            rid, role, lambda: build_serving_model(mcfg, app))

    return FleetServingModel(mcfg, app, factory, replicas=replicas,
                             prefill_replicas=prefill,
                             disagg_threshold=threshold)


@pytest.fixture(scope="module")
def fleet():
    fm = _build_fleet()
    yield fm
    fm.close()


def _gen(fm, text, max_new=6, **kw):
    h = fm.scheduler.submit(GenRequest(
        prompt=fm.tokenizer.encode(text), max_new_tokens=max_new,
        temperature=0.0, **kw))
    h.result(timeout=300)
    return h


def test_fleet_affinity_placement_serves_one_replica(fleet):
    prompt = "the same shared prompt prefix, different request"  # ≥ 1 block
    texts = set()
    for _ in range(3):
        h = _gen(fleet, prompt)
        assert h.finish_reason in ("stop", "length")
        texts.add(h.text)
    assert len(texts) == 1  # greedy determinism through the fleet
    # all three landed on one replica (prefix reuse survives scale-out)
    served = [r for r in fleet.pool.replicas
              if r.role == "decode" and r.dispatched > 0]
    assert len(served) == 1
    # request 1 is a ring pick; repeats may route by the prefix
    # directory instead (same replica, reason "directory")
    routed = fleet.router.routed
    assert routed["affinity"] + routed.get("directory", 0) >= 3


def test_disaggregated_handoff_matches_single_engine(fleet):
    from localai_tpu.models.manager import build_serving_model

    long_prompt = "disaggregate this long prompt please " * 5  # ≥ threshold
    before = fleet.scheduler.prefix_transfers
    h = _gen(fleet, long_prompt, max_new=8)
    assert h.finish_reason in ("stop", "length")
    assert fleet.scheduler.prefix_transfers == before + 1
    assert fleet.scheduler.prefix_transfer_bytes > 0

    # byte-identical greedy completion vs one single paged engine
    single = build_serving_model(ModelConfig.model_validate(TINY),
                                 AppConfig())
    try:
        ref = single.scheduler.submit(GenRequest(
            prompt=single.tokenizer.encode(long_prompt),
            max_new_tokens=8, temperature=0.0))
        ref.result(timeout=300)
        assert ref.text == h.text
    finally:
        single.scheduler.shutdown()


def test_dead_replica_failover_and_respawn(fleet):
    prompt = "failover probe prompt, affinity-long"  # 1 block, < threshold
    target, _ = fleet.router.route(fleet.tokenizer.encode(prompt))
    target.kill()
    # dispatch to the corpse fails instantly (no tokens streamed) → the
    # request fails over and completes on another replica
    h = _gen(fleet, prompt)
    assert h.finish_reason in ("stop", "length")
    assert fleet.scheduler.failovers >= 1
    assert target.state in ("dead", "respawning", "healthy")
    # subsequent requests route around the dead replica
    if target.state != "healthy":
        pick, _ = fleet.router.route(fleet.tokenizer.encode(prompt))
        assert pick.id != target.id
    # ... until its respawn passes health and it rejoins the ring
    deadline = time.monotonic() + 180
    while target.state != "healthy" and time.monotonic() < deadline:
        time.sleep(0.1)
    assert target.state == "healthy"
    # the crash left an error burst in the replica's SLO window, so the
    # router keeps routing AROUND it (shedding) until the window drains —
    # prove both halves: traffic still lands somewhere healthy now, and
    # affinity returns the moment the burst is gone (reset = time passing)
    pick, _ = fleet.router.route(fleet.tokenizer.encode(prompt))
    assert pick.state == "healthy"
    fleet.slo.reset()
    pick, reason = fleet.router.route(fleet.tokenizer.encode(prompt))
    # the prefix directory may (correctly) keep preferring the replica
    # that served the failover traffic — ITS copy of the KV is the warm
    # one. Drop that record to prove the ring itself forgot nothing:
    if reason == "directory" and fleet.scheduler.directory is not None:
        fleet.scheduler.directory.drop_replica(pick.id)
        pick, reason = fleet.router.route(fleet.tokenizer.encode(prompt))
    assert pick.id == target.id  # ring affinity restored after recovery


def test_kill_mid_request_fleet_keeps_serving(fleet):
    prompt = "stream then die midway through here"  # 1 block, < threshold
    target, _ = fleet.router.route(fleet.tokenizer.encode(prompt))
    h = fleet.scheduler.submit(GenRequest(
        prompt=fleet.tokenizer.encode(prompt), max_new_tokens=200,
        temperature=0.0, ignore_eos=True, stream=True))
    for item in h:
        if item.delta:
            target.kill()
            break
    h.result(timeout=120)
    # the kill races the (fast) tiny engine: either it landed mid-stream
    # (clean error, streamed deltas still counted) or the stream had
    # already finished — never a hang, never a zero-token "success"
    assert h.finish_reason in ("error", "length", "stop")
    assert h.completion_tokens > 0
    # the fleet keeps serving while the corpse respawns
    h2 = _gen(fleet, "the fleet survives a replica death")
    assert h2.finish_reason in ("stop", "length")
    deadline = time.monotonic() + 180
    while target.state != "healthy" and time.monotonic() < deadline:
        time.sleep(0.1)
    assert target.state == "healthy"
    fleet.slo.reset()  # drain the crash burst for later tests


# ---------------------------------------------------------------------------
# failover semantics, pinned deterministically with scripted replicas


class _ScriptedReplica(BaseReplica):
    """Stub replica whose predict_stream plays a script: "delta" yields
    one message, "raise" dies mid-transport, anything else ends the
    stream with a usage reply."""

    def __init__(self, rid, role):
        super().__init__(rid, role)
        self.dead_flag = False
        self.script = []

    def start(self):
        pass

    def _dial(self, timeout):
        return not self.dead_flag

    def process_alive(self):
        return not self.dead_flag

    def predict_stream(self, opts, trace_id="", tenant=""):
        steps = self.script.pop(0) if self.script else ["final"]
        for step in steps:
            if step == "delta":
                yield _Reply(b"x")
            elif step == "raise":
                self.dead_flag = True
                raise RuntimeError("scripted transport death")
            else:
                yield _Reply(b"", 3, 5, "stop")

    def metrics(self):
        return {}

    def stop(self):
        pass


def _scripted_fleet():
    from types import SimpleNamespace

    from localai_tpu.fleet.pool import ReplicaPool
    from localai_tpu.fleet.serving import FleetScheduler
    from localai_tpu.obs.slo import SLOTracker

    pool = ReplicaPool("scripted", _ScriptedReplica, replicas=2,
                       health_interval=3600.0)
    pool.start()
    router = Router(pool, None, block_tokens=16)
    sched = FleetScheduler(
        SimpleNamespace(name="scripted"), pool, router,
        SLOTracker(targets={"e2e_ms": float("inf")}),
        disagg_threshold=1 << 30)
    return pool, router, sched


def test_prestream_death_fails_over_transparently():
    pool, router, sched = _scripted_fleet()
    try:
        prompt = list(range(32))
        target, _ = router.route(prompt)
        target.script = [["raise"]]          # dies before any delta
        h = sched.submit(GenRequest(prompt=prompt, max_new_tokens=4))
        h.result(timeout=30)
        assert h.finish_reason == "stop"     # the other replica finished it
        assert sched.failovers == 1
        assert target.state in ("dead", "respawning")
    finally:
        pool.shutdown()


def test_midstream_death_is_a_clean_error():
    pool, router, sched = _scripted_fleet()
    try:
        prompt = list(range(32))
        target, _ = router.route(prompt)
        target.script = [["delta", "delta", "raise"]]  # dies mid-stream
        h = sched.submit(GenRequest(prompt=prompt, max_new_tokens=4))
        h.result(timeout=30)
        # tokens already reached the client: not transparently resumable —
        # a clean error, with the streamed work still counted
        assert h.finish_reason == "error"
        assert h.completion_tokens == 2
        assert sched.failovers == 0
        # the fleet itself keeps serving on the survivor
        h2 = sched.submit(GenRequest(prompt=prompt, max_new_tokens=4))
        h2.result(timeout=30)
        assert h2.finish_reason == "stop"
    finally:
        pool.shutdown()


def test_fleet_metrics_and_gauges(fleet):
    from localai_tpu.obs.metrics import REGISTRY

    m = fleet.engine_metrics()
    assert m["total_generated_tokens"] > 0
    assert m["fleet"]["replicas"].get("healthy", 0) >= 1
    assert sum(m["fleet"]["routed"].values()) > 0
    status = fleet.fleet_status()
    assert {r["id"] for r in status["replicas"]} == \
        {r.id for r in fleet.pool.replicas}
    fleet.scheduler.export_gauges()
    expo = REGISTRY.render()
    assert 'localai_fleet_replicas{model="ftiny",state="healthy"}' in expo
    assert 'localai_fleet_routed_total{model="ftiny"' in expo
    assert ('localai_fleet_prefix_transfer_bytes_total{model="ftiny"}'
            in expo)


# ---------------------------------------------------------------------------
# the real thing: spawned worker processes, kill -9, respawn


@pytest.mark.slow
def test_worker_fleet_kill9_failover_and_respawn(tmp_path):
    """kill -9 of one worker replica mid-stream: the request fails over
    (or errors cleanly if tokens already streamed), the serving process
    stays up, subsequent requests route around the corpse, and the
    replica rejoins after its respawn passes health."""
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import WorkerReplica

    app = AppConfig(model_path=str(tmp_path),
                    worker_env={"JAX_PLATFORMS": "cpu"})
    mcfg = ModelConfig.model_validate({**TINY, "context_size": 96})

    def factory(rid, role):
        return WorkerReplica(rid, role, mcfg, app, env=app.worker_env)

    fm = FleetServingModel(mcfg, app, factory, replicas=2)
    try:
        prompt = "kill nine this worker replica mid-stream"
        target, _ = fm.router.route(fm.tokenizer.encode(prompt))
        h = fm.scheduler.submit(GenRequest(
            prompt=fm.tokenizer.encode(prompt), max_new_tokens=80,
            temperature=0.0, ignore_eos=True, stream=True))
        killed = False
        for item in h:
            if item.delta and not killed:
                target.kill()  # SIGKILL the worker process
                killed = True
            if item.finish_reason is not None:
                break
        assert killed
        h.result(timeout=240)
        # mid-stream → clean error; if the tiny engine outran the kill,
        # a natural finish — never a hang, never a 0-token success
        assert h.finish_reason in ("error", "length", "stop")
        assert h.completion_tokens > 0

        # the serving process survives and the fleet keeps serving
        h2 = _gen(fm, "the fleet is still serving after kill -9")
        assert h2.finish_reason in ("stop", "length")
        if target.state != "healthy":
            pick, _ = fm.router.route(fm.tokenizer.encode(prompt))
            assert pick.id != target.id  # routed around the corpse

        # ...until the respawned process passes health + LoadModel again
        deadline = time.monotonic() + 300
        while target.state != "healthy" and time.monotonic() < deadline:
            time.sleep(0.2)
        assert target.state == "healthy"
        fm.slo.reset()  # the crash burst has served its purpose
        h3 = _gen(fm, prompt)
        assert h3.finish_reason in ("stop", "length")
    finally:
        fm.close()


# ---------------------------------------------------------------------------
# the wire contract: PrefillPrefix → TransferPrefix across real gRPC workers


def test_prefix_transfer_over_grpc_workers():
    import yaml

    from localai_tpu.worker import WorkerClient
    from localai_tpu.worker import backend_pb2 as pb
    from localai_tpu.worker.server import BackendServicer, serve_worker

    cfg_yaml = yaml.safe_dump({**TINY, "context_size": 96})
    servers = []
    clients = []
    try:
        for _ in range(2):
            servicer = BackendServicer()
            server, port = serve_worker("127.0.0.1:0", servicer=servicer,
                                        block=False)
            client = WorkerClient(f"127.0.0.1:{port}")
            assert client.load_model(config_yaml=cfg_yaml).success
            servers.append((server, servicer))
            clients.append(client)
        prefill, decode = clients
        prompt = "transfer this prefix over the wire please!"  # > 16 tokens

        # prefill worker exports; the relay feeds the decode worker
        chunks = prefill.prefill_prefix(pb.PredictOptions(
            prompt=prompt, max_tokens=8, temperature=0.0))
        res = decode.transfer_prefix(chunks)
        assert res.success and "rows" in res.message

        # the decode worker resumes from the transferred prefix and emits
        # the same greedy completion as the prefill worker would natively
        got = decode.predict(pb.PredictOptions(
            prompt=prompt, max_tokens=6, temperature=0.0))
        ref = prefill.predict(pb.PredictOptions(
            prompt=prompt, max_tokens=6, temperature=0.0))
        assert got.message == ref.message
        assert got.finish_reason in ("stop", "length")
    finally:
        for c in clients:
            c.close()
        for server, servicer in servers:
            servicer.shutdown()
            server.stop(grace=None)


def test_queue_override_degrades_affinity_to_least_loaded():
    """A drowning affinity target (reported decode queue depth past
    LOCALAI_FLEET_QUEUE_OVERRIDE) loses its affinity claim: the request
    places least-loaded with reason queue_override; below the threshold
    the affinity placement stands."""
    pool = _StubPool([_StubReplica(f"m/r{i}") for i in range(3)])
    router = Router(pool, None, block_tokens=16, queue_override=4)
    p = _prompt(9)
    target = router.route(p)[0]
    assert router.routed["affinity"] == 1

    target.queue_depth = 4          # at the threshold: affinity holds
    assert router.route(p)[0] is target

    target.queue_depth = 5          # past it: least-loaded wins
    target.inflight = 3             # make the target clearly NOT least-loaded
    pick, reason = router.route(p)
    assert pick is not target and reason == "queue_override"
    assert router.routed["queue_override"] == 1

    # threshold off (0) ignores queue depth entirely
    router0 = Router(pool, None, block_tokens=16)
    assert router0.route(p)[0] is target


def test_queue_override_noop_when_target_is_least_loaded():
    """When the affinity target is simultaneously the least-loaded
    replica, the override keeps it (and keeps the affinity accounting —
    nothing actually moved)."""
    reps = [_StubReplica(f"m/r{i}", inflight=5) for i in range(3)]
    pool = _StubPool(reps)
    router = Router(pool, None, block_tokens=16, queue_override=1)
    p = _prompt(9)
    target = router.route(p)[0]
    target.queue_depth = 10
    target.inflight = 0             # drowning by depth, idle by inflight
    pick, reason = router.route(p)
    assert pick is target and reason == "affinity"


def test_pool_monitor_tracks_queue_depth():
    """With tracking on, the dial sweep refreshes each healthy replica's
    reported queue depth from its metrics dict."""
    from localai_tpu.fleet.pool import ReplicaPool

    class _R(BaseReplica):
        def __init__(self, rid):
            super().__init__(rid, "decode")
            self.state = "healthy"

        def start(self):
            pass

        def _dial(self, timeout):
            return True

        def process_alive(self):
            return True

        def metrics(self):
            return {"queue_depth": 7, "occupancy": 1.0}

        def stop(self):
            pass

    pool = ReplicaPool("m", lambda rid, role: _R(rid), replicas=0,
                       track_queue_depth=True)
    r = _R("m/r0")
    pool.replicas.append(r)
    pool.poll_once()
    assert r.queue_depth == 7


def test_respawn_backoff_grows_caps_and_resets():
    """A replica whose respawn keeps failing is retried on jittered
    exponential backoff (strictly growing across the first doublings,
    never past the cap, skipped until the hold expires); a successful
    rejoin resets the clock and zeroes the gauge."""
    from localai_tpu.fleet.pool import ReplicaPool
    from localai_tpu.obs.metrics import REGISTRY

    class _Flaky(BaseReplica):
        def __init__(self, rid, role="decode"):
            super().__init__(rid, role)
            self.fail_starts = 0
            self.up = True

        def start(self):
            if self.fail_starts > 0:
                self.fail_starts -= 1
                raise RuntimeError("boot refused")
            self.up = True

        def _dial(self, timeout):
            return self.up

        def process_alive(self):
            return self.up

        def metrics(self):
            return {}

        def stop(self):
            pass

    pool = ReplicaPool("backoff", lambda rid, role: _Flaky(rid, role),
                       replicas=1, health_interval=3600.0)
    pool.respawn_backoff_base = 0.05
    pool.respawn_backoff_cap = 0.15
    pool.start()
    try:
        r = pool.replicas[0]
        r.fail_starts = 3
        r.up = False
        pool.note_failure(r)
        backoffs = []
        deadline = time.monotonic() + 30
        while len(backoffs) < 3 and time.monotonic() < deadline:
            pool.poll_once()
            b = pool.respawn_backoff_s.get(r.id)
            if b is not None and (not backoffs or b != backoffs[-1]):
                backoffs.append(b)
            time.sleep(0.01)
        assert len(backoffs) == 3, backoffs
        # ±25% jitter bands of 0.05/0.10 are disjoint → strict growth;
        # the third doubling (0.20) must clip to the 0.15 cap
        assert backoffs[1] > backoffs[0], backoffs
        assert all(b <= pool.respawn_backoff_cap for b in backoffs)
        deadline = time.monotonic() + 30
        while r.state != "healthy" and time.monotonic() < deadline:
            pool.poll_once()
            time.sleep(0.01)
        assert r.state == "healthy"
        assert r.id not in pool.respawn_backoff_s  # clock reset on rejoin
        assert pool.snapshot()["respawn_backoff_s"] == {}
        text = REGISTRY.render()
        assert ('localai_fleet_respawn_backoff_s'
                '{model="backoff",replica="backoff/r0"} 0.0') in text
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# cross-host fleet: remote replica adoption, eviction/redial, RPC deadlines


def _grpc_workers(n):
    """n in-thread gRPC workers on 127.0.0.1 ports (the cross-host shape
    on loopback). Returns ([(server, servicer)], [addr])."""
    from localai_tpu.worker.server import BackendServicer, serve_worker

    workers, addrs = [], []
    for _ in range(n):
        sv = BackendServicer()
        server, port = serve_worker("127.0.0.1:0", servicer=sv,
                                    block=False)
        workers.append((server, sv))
        addrs.append(f"127.0.0.1:{port}")
    return workers, addrs


def _stop_grpc_workers(workers):
    for server, sv in workers:
        sv.shutdown()
        server.stop(grace=None)


def _remote_fleet(addrs, **kw):
    from localai_tpu.fleet import FleetServingModel

    app = AppConfig()
    mcfg = ModelConfig.model_validate({**TINY, "context_size": 96})
    return FleetServingModel(mcfg, app, lambda rid, role: None,
                             replicas=0, remote_hosts=list(addrs),
                             disagg_threshold=1 << 30, **kw)


def test_remote_adoption_from_fleet_hosts_and_registry_join():
    """Static adoption (the LOCALAI_FLEET_HOSTS path) boots remote
    workers into the pool as non-respawnable RemoteReplicas; a
    mid-traffic adopt_remote (the /federated/register path) joins
    another, under traffic, with the adoption counter moving and the
    newcomer taking least-loaded requests."""
    workers, addrs = _grpc_workers(2)
    fm = None
    try:
        fm = _remote_fleet(addrs[:1])
        assert [r.state for r in fm.pool.replicas] == ["healthy"]
        assert not fm.pool.replicas[0].respawnable
        h = _gen(fm, "served across the wire by an adopted remote")
        assert h.finish_reason in ("stop", "length")
        snap = fm.pool.snapshot()
        assert snap["replicas"][0]["remote"] is True
        assert snap["replicas"][0]["address"] == addrs[0]

        # registry join mid-traffic: requests keep completing around it
        h_live = fm.scheduler.submit(GenRequest(
            prompt=fm.tokenizer.encode("in flight during the join"),
            max_new_tokens=24, temperature=0.0))
        verdict = fm.adopt_remote(addrs[1])
        assert verdict["adopted"] and verdict["state"] == "healthy"
        assert fm.pool.adoptions == 2  # the static host counts too
        h_live.result(timeout=120)
        assert h_live.finish_reason in ("stop", "length")
        # a duplicate join is refused, not doubled
        assert fm.adopt_remote(addrs[1])["adopted"] is False
        # the fresh peer (0 dispatched) absorbs least-loaded traffic
        joined = fm.pool.get(verdict["id"])
        for i in range(3):
            assert _gen(fm, f"[{i}]", max_new=3).finish_reason in (
                "stop", "length")
        assert joined.dispatched >= 1
    finally:
        if fm is not None:
            fm.close()
        _stop_grpc_workers(workers)


def test_partition_evicts_remote_with_zero_lost_requests():
    """fleet.dial + fleet.transport faults against one remote = a
    network partition: every request completes via route-around, the
    victim is EVICTED (distinct from local death/respawn), and healing
    the partition redials it back with the backoff clock reset."""
    from localai_tpu import faults

    workers, addrs = _grpc_workers(2)
    fm = None
    try:
        fm = _remote_fleet(addrs)
        pool = fm.pool
        pool.redial_backoff_base = 0.1
        pool.redial_backoff_cap = 0.5
        for i in range(2):
            _gen(fm, f"[w{i}]")  # both peers warm
        victim = pool.replicas[0]
        faults.arm(faults.FaultSpec(site="fleet.transport", mode="raise",
                                    match=victim.id, times=0))
        faults.arm(faults.FaultSpec(site="fleet.dial", mode="raise",
                                    match=victim.id, times=0))
        handles = [fm.scheduler.submit(GenRequest(
            prompt=fm.tokenizer.encode(
                f"partitioned request {i} with a full block of prompt"),
            max_new_tokens=5, temperature=0.0)) for i in range(5)]
        for h in handles:
            h.result(timeout=120)
        assert all(h.finish_reason in ("stop", "length")
                   for h in handles), [h.finish_reason for h in handles]
        deadline = time.monotonic() + 30
        while victim.state != "evicted" and time.monotonic() < deadline:
            pool.poll_once()
            time.sleep(0.05)
        assert victim.state == "evicted"
        assert pool.evictions == 1
        # partition heals → backed-off redial rejoins and resets
        faults.clear()
        deadline = time.monotonic() + 60
        while victim.state != "healthy" and time.monotonic() < deadline:
            pool.poll_once()
            time.sleep(0.05)
        assert victim.state == "healthy"
        assert pool.redials == 1
        assert victim.id not in pool.redial_backoff_s
    finally:
        faults.clear()
        if fm is not None:
            fm.close()
        _stop_grpc_workers(workers)


def test_redial_backoff_grows_caps_and_resets():
    """An evicted remote whose redials keep failing walks the jittered
    exponential hold schedule (growing, capped) and a successful rejoin
    zeroes the gauge — the remote twin of respawn backoff."""
    from localai_tpu import faults
    from localai_tpu.fleet.pool import ReplicaPool
    from localai_tpu.obs.metrics import REGISTRY

    class _Remote(BaseReplica):
        respawnable = False

        def __init__(self, rid, role="decode"):
            super().__init__(rid, role)
            self.state = "healthy"

        def start(self):
            pass

        def _dial(self, timeout):
            return True

        def process_alive(self):
            return True

        def metrics(self):
            return {}

        def stop(self):
            pass

    pool = ReplicaPool("redial", lambda rid, role: None, replicas=0,
                       health_interval=3600.0)
    pool.redial_backoff_base = 0.05
    pool.redial_backoff_cap = 0.15
    r = _Remote("redial/peer")
    pool.replicas.append(r)
    pool._started = True
    try:
        faults.arm(faults.FaultSpec(site="fleet.dial", mode="raise",
                                    match=r.id, times=4))
        pool.note_failure(r)
        assert r.state == "evicted"
        backoffs = []
        deadline = time.monotonic() + 30
        while len(backoffs) < 3 and time.monotonic() < deadline:
            pool.poll_once()
            b = pool.redial_backoff_s.get(r.id)
            if b is not None and (not backoffs or b != backoffs[-1]):
                backoffs.append(b)
            time.sleep(0.01)
        assert len(backoffs) == 3, backoffs
        assert backoffs[1] > backoffs[0], backoffs
        assert all(b <= pool.redial_backoff_cap for b in backoffs)
        deadline = time.monotonic() + 30
        while r.state != "healthy" and time.monotonic() < deadline:
            pool.poll_once()
            time.sleep(0.01)
        assert r.state == "healthy"
        assert r.id not in pool.redial_backoff_s
        assert pool.evictions == 1 and pool.redials == 1
        text = REGISTRY.render()
        assert ('localai_fleet_redial_backoff_s'
                '{model="redial",replica="redial/peer"} 0.0') in text
        assert 'localai_fleet_evictions_total' in text
        assert 'localai_fleet_redials_total' in text
    finally:
        faults.clear()
        pool.shutdown()


def test_slow_link_deadline_fires_and_fails_over():
    """A replica whose stream stays silent past the fleet RPC deadline:
    the bounded pump raises, the dispatch fails over pre-stream, and the
    request completes on the healthy peer — a dead remote can never hang
    the dispatch thread."""
    from types import SimpleNamespace

    from localai_tpu.fleet.pool import ReplicaPool
    from localai_tpu.fleet.serving import FleetScheduler
    from localai_tpu.obs.slo import SLOTracker

    class _SlowReplica(_ScriptedReplica):
        slow = False

        def predict_stream(self, opts, trace_id="", tenant=""):
            if self.slow:
                time.sleep(5.0)  # silence, not an error — like a
                #                  partitioned peer
            yield _Reply(b"x")
            yield _Reply(b"", 3, 5, "stop")

    pool = ReplicaPool("slow", _SlowReplica, replicas=2,
                       health_interval=3600.0)
    pool.start()
    router = Router(pool, None, block_tokens=16)
    sched = FleetScheduler(
        SimpleNamespace(name="slow"), pool, router,
        SLOTracker(targets={"e2e_ms": float("inf")}),
        disagg_threshold=1 << 30, rpc_timeout_s=0.5)
    try:
        prompt = list(range(32))
        victim, _ = router.route(prompt)
        victim.slow = True
        t0 = time.monotonic()
        h = sched.submit(GenRequest(prompt=prompt, max_new_tokens=4))
        h.result(timeout=30)
        assert h.finish_reason == "stop"      # the healthy peer finished
        assert sched.failovers == 1
        assert time.monotonic() - t0 < 4.0    # deadline, not the 5 s nap
    finally:
        pool.shutdown()


def test_bounded_stream_deadline_and_passthrough():
    from localai_tpu.fleet import net

    # passthrough: items come through in order, completion is clean
    assert list(net.bounded_stream(iter([1, 2, 3]), 5.0)) == [1, 2, 3]

    # an upstream exception is relayed, not swallowed
    def boom():
        yield 1
        raise RuntimeError("mid-stream death")

    it = net.bounded_stream(boom(), 5.0)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="mid-stream death"):
        next(it)

    # silence past the deadline raises RpcDeadlineExceeded
    def stall():
        yield 1
        time.sleep(10.0)
        yield 2

    it = net.bounded_stream(stall(), 0.3, rid="m/slow")
    assert next(it) == 1
    with pytest.raises(net.RpcDeadlineExceeded, match="m/slow"):
        next(it)


def test_call_with_retries_is_bounded_and_jittered():
    from localai_tpu.fleet import net

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("flap")
        return "ok"

    assert net.call_with_retries(flaky, retries=3,
                                 base_delay=0.01) == "ok"
    assert calls["n"] == 3

    def always_down():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        net.call_with_retries(always_down, retries=2, base_delay=0.01)


# ---------------------------------------------------------------------------
# per-replica device pinning presets (--fleet-device-pinning)


def test_pinning_env_partitions_tpu_hosts():
    from localai_tpu.fleet.pinning import pinning_env

    envs = [pinning_env(i, 4, platform="tpu", n_devices=8)
            for i in range(4)]
    slices = [e["TPU_VISIBLE_DEVICES"] for e in envs]
    assert slices == ["0,1", "2,3", "4,5", "6,7"]  # disjoint, covering
    # pod-topology env must not leak into single-process workers
    assert all(e["TPU_PROCESS_BOUNDS"] == "" for e in envs)

    # uneven split: remainder devices stay unused, never skew one replica
    envs = [pinning_env(i, 3, platform="tpu", n_devices=8)
            for i in range(3)]
    assert [e["TPU_VISIBLE_DEVICES"] for e in envs] == \
        ["0,1", "2,3", "4,5"]


def test_pinning_env_cpu_and_unknown_platforms():
    from localai_tpu.fleet.pinning import pinning_env

    env = pinning_env(1, 2, platform="cpu", n_devices=8)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "device_count=4" in env["XLA_FLAGS"]
    # no convention for gpu plugins → unpinned (operator keeps worker_env)
    assert pinning_env(0, 2, platform="gpu", n_devices=8) == {}
    # more replicas than devices → unpinned rather than empty slices
    assert pinning_env(0, 4, platform="tpu", n_devices=2) == {}
    with pytest.raises(ValueError):
        pinning_env(5, 4, platform="tpu", n_devices=8)


def test_pinned_worker_env_operator_keys_win():
    from localai_tpu.fleet import pinning

    orig = pinning.derive_pinning_env
    pinning.derive_pinning_env = lambda i, n: {
        "TPU_VISIBLE_DEVICES": "0,1", "TPU_PROCESS_BOUNDS": ""}
    try:
        merged = pinning.pinned_worker_env(
            {"TPU_VISIBLE_DEVICES": "6,7", "MY_FLAG": "1"}, 0, 2)
    finally:
        pinning.derive_pinning_env = orig
    assert merged["TPU_VISIBLE_DEVICES"] == "6,7"  # explicit wins
    assert merged["MY_FLAG"] == "1"
    assert merged["TPU_PROCESS_BOUNDS"] == ""      # derived fills gaps


def test_pinning_env_declared_topology_beats_backend_probe(monkeypatch):
    """With LOCALAI_FLEET_PIN_PLATFORM/_DEVICES set, derivation never
    touches the parent's JAX backend — the server can run --platform cpu
    on a TPU host and still pin its workers to the real chips."""
    from localai_tpu.fleet import pinning

    monkeypatch.setenv("LOCALAI_FLEET_PIN_PLATFORM", "tpu")
    monkeypatch.setenv("LOCALAI_FLEET_PIN_DEVICES", "8")
    env = pinning.derive_pinning_env(1, 4)
    assert env["TPU_VISIBLE_DEVICES"] == "2,3"  # not this process's CPUs
