"""Usage/goodput accounting plane (obs.ledger).

The unit half of the round-18 observability surfaces: tenant derivation
safety (hashed buckets, never the raw key), LRU cardinality bounding,
the goodput-vs-waste decomposition and its flight-ring reconciliation
identity, and the two attribution paths — a real in-process engine and
the fleet dispatch tier (whose InProcessReplica must DROP the tenant so
shared-process fleets feed the ledger exactly once). The HTTP halves
(GET /v1/usage, /debug/history) live in test_api.py; the worker-process
gRPC metadata hop is covered by the telemetry smoke's check_usage.
"""

import pytest

from localai_tpu.obs import Registry
from localai_tpu.obs.ledger import (
    ANONYMOUS,
    FLIGHT_WASTE,
    LEDGER,
    OVERFLOW,
    TenantLedger,
    current_tenant,
    derive_tenant,
    kv_block_seconds,
    set_current_tenant,
)

# -- tenant derivation (label safety) ----------------------------------------


def test_derive_tenant_empty_key_is_anonymous():
    assert derive_tenant("") == ANONYMOUS


def test_derive_tenant_is_short_stable_hash():
    a = derive_tenant("sk-secret-key-123")
    assert a == derive_tenant("sk-secret-key-123")    # stable
    assert a.startswith("t-") and len(a) == 14         # t- + 12 hex
    assert a != derive_tenant("sk-secret-key-124")


def test_derive_tenant_never_contains_raw_key():
    key = "sk-very-secret"
    assert key not in derive_tenant(key)
    assert "secret" not in derive_tenant(key)


def test_tenant_contextvar_roundtrip():
    assert current_tenant() == ""
    token = set_current_tenant("t-abc")
    try:
        assert current_tenant() == "t-abc"
    finally:
        token.var.reset(token)
    assert current_tenant() == ""


# -- KV block-seconds --------------------------------------------------------


def test_kv_block_seconds_ceil_math():
    # 17 tokens over 16-token blocks = 2 blocks; × 3 s resident = 6
    assert kv_block_seconds(10, 7, 3.0, block_tokens=16) == 6.0
    assert kv_block_seconds(16, 0, 2.0, block_tokens=16) == 2.0
    assert kv_block_seconds(0, 0, 5.0) == 0.0
    assert kv_block_seconds(-3, 4, 1.0, block_tokens=4) == 1.0
    assert kv_block_seconds(4, 4, -1.0, block_tokens=4) == 0.0


# -- classification + decomposition ------------------------------------------


def _feed(led, *, tenant="t-a", model="m", lane="interactive",
          reason="stop", tokens=10, prompt=4):
    led.note_request(tenant=tenant, model=model, lane=lane, reason=reason,
                     tokens=tokens, prompt_tokens=prompt, dispatch_ms=5.0,
                     queue_wait_ms=1.0, kv_block_s=2.0)


def test_note_request_classifies_goodput_vs_waste():
    led = TenantLedger(max_tenants=8)
    _feed(led, reason="stop", tokens=10)
    _feed(led, reason="length", tokens=5)
    _feed(led, reason="cancelled", tokens=3)
    snap = led.snapshot()
    pane = snap["tenants"]["t-a"]["m/interactive"]
    assert pane["requests"] == 3
    assert pane["delivered_tokens"] == 15          # stop + length only
    assert pane["waste_tokens"] == 3
    assert pane["waste_requests"] == 1
    assert snap["goodput_tokens"] == {"m": 15}
    assert snap["waste"]["cancelled/m"] == {"tokens": 3, "requests": 1}


def test_unknown_terminal_reason_folds_into_error():
    led = TenantLedger(max_tenants=8)
    _feed(led, reason="exploded", tokens=2)
    assert led.snapshot()["waste"]["error/m"]["tokens"] == 2


def test_flight_reconciliation_identity():
    """goodput + cancelled/error/nan tokens == the ring's total; the
    out-of-ring classes (spec/shed/reprefill) stay outside the sum."""
    led = TenantLedger(max_tenants=8)
    _feed(led, reason="stop", tokens=10)
    _feed(led, reason="cancelled", tokens=4)
    _feed(led, reason="error", tokens=2)
    _feed(led, reason="nan_quarantine", tokens=1)
    led.note_waste("spec_rejected", model="m", tokens=7)
    led.note_waste("shed", model="m", requests=2)
    led.note_waste("failover_reprefill", model="m", tokens=9, requests=1)
    g = led.goodput_totals("m")
    assert g["delivered_tokens"] == 10
    assert g["flight_tokens"] == 10 + 4 + 2 + 1    # what the ring counted
    assert g["waste_tokens"] == 4 + 2 + 1 + 7 + 9  # every wasted token
    assert set(FLIGHT_WASTE) == {"cancelled", "error", "nan_quarantine"}
    assert g["goodput_ratio"] == pytest.approx(10 / (10 + 23))


def test_goodput_totals_scopes_by_model():
    led = TenantLedger(max_tenants=8)
    _feed(led, model="a", tokens=10)
    _feed(led, model="b", tokens=6)
    led.note_waste("spec_rejected", model="b", tokens=2)
    assert led.goodput_totals("a")["waste_tokens"] == 0
    assert led.goodput_totals("b")["waste_tokens"] == 2
    assert led.goodput_totals()["delivered_tokens"] == 16


def test_note_waste_tenant_attribution_is_best_effort():
    led = TenantLedger(max_tenants=8)
    led.note_waste("shed", model="m", tenant="t-x", requests=1)
    led.note_waste("shed", model="m", requests=1)   # engine-side, no tenant
    snap = led.snapshot()
    assert snap["waste"]["shed/m"]["requests"] == 2  # decomposition exact
    assert snap["tenants"]["t-x"]["m/interactive"]["waste_requests"] == 1


# -- tenant LRU (cardinality bound) ------------------------------------------


def test_lru_eviction_folds_into_overflow_and_conserves_totals():
    led = TenantLedger(max_tenants=3)
    for i in range(6):
        _feed(led, tenant=f"t-{i:02d}", tokens=10)
    snap = led.snapshot()
    assert len(snap["tenants"]) <= 3 + 1            # cap + overflow bucket
    assert snap["evictions_total"] > 0
    total = sum(p["delivered_tokens"]
                for panes in snap["tenants"].values()
                for p in panes.values())
    assert total == 60                               # folded, not dropped
    assert OVERFLOW in snap["tenants"]


def test_anonymous_and_overflow_are_never_evicted():
    led = TenantLedger(max_tenants=2)
    _feed(led, tenant=ANONYMOUS, tokens=1)
    for i in range(5):
        _feed(led, tenant=f"t-{i:02d}", tokens=1)
    snap = led.snapshot()
    assert ANONYMOUS in snap["tenants"]
    assert OVERFLOW in snap["tenants"]


def test_tenant_max_env_knob(monkeypatch):
    monkeypatch.setenv("LOCALAI_TENANT_MAX", "5")
    assert TenantLedger().max_tenants == 5
    monkeypatch.setenv("LOCALAI_TENANT_MAX", "junk")
    assert TenantLedger().max_tenants == 64
    monkeypatch.setenv("LOCALAI_TENANT_MAX", "0")
    assert TenantLedger().max_tenants == 2           # floor


# -- usage payload (GET /v1/usage body) --------------------------------------


def test_usage_payload_lifetime_shape():
    led = TenantLedger(max_tenants=8)
    _feed(led, tenant="t-a", tokens=10)
    _feed(led, tenant="t-b", reason="cancelled", tokens=2)
    p = led.usage_payload()
    assert p["object"] == "usage" and p["start_time"] is None
    rows = {r["tenant"]: r for r in p["data"]}
    assert rows["t-a"]["delivered_tokens"] == 10
    assert rows["t-b"]["waste_tokens"] == 2
    assert p["waste"][0]["reason"] == "cancelled"
    assert p["goodput"]["flight_tokens"] == 12
    assert p["tenant_lru"]["max_tenants"] == 8


def test_usage_payload_window_filters_the_event_ring():
    led = TenantLedger(max_tenants=8)
    _feed(led, tokens=10)
    everything = led.usage_payload(since=0.0)
    assert everything["events"] == 1
    assert everything["data"][0]["delivered_tokens"] == 10
    assert everything["coverage_start"] <= everything["end_time"]
    nothing = led.usage_payload(since=everything["end_time"] + 60.0)
    assert nothing["events"] == 0 and nothing["data"] == []


def test_event_ring_is_bounded():
    led = TenantLedger(max_tenants=8, events=4)
    for i in range(10):
        _feed(led, tokens=1)
    assert led.usage_payload(since=0.0)["events"] == 4


# -- registry export (exposition safety) -------------------------------------


def test_export_renders_hashed_buckets_never_raw_keys():
    led = TenantLedger(max_tenants=8)
    raw = "sk-super-secret-key"
    _feed(led, tenant=derive_tenant(raw), tokens=10)
    led.note_waste("spec_rejected", model="m", tokens=3)
    reg = Registry()
    led.export(reg)
    text = reg.render()
    assert raw not in text
    assert f'tenant="{derive_tenant(raw)}"' in text
    assert 'localai_goodput_tokens_total{model="m"} 10' in text
    assert ('localai_waste_tokens_total{model="m",reason="spec_rejected"}'
            ' 3' in text)
    assert 'localai_goodput_ratio{model="m"}' in text


def test_export_is_idempotent_max_merge():
    led = TenantLedger(max_tenants=8)
    _feed(led, tokens=10)
    reg = Registry()
    led.export(reg)
    led.export(reg)  # re-export must not double the monotone counters
    assert ('localai_tenant_tokens_total{lane="interactive",model="m",'
            'tenant="t-a"} 10' in reg.render())


# -- attribution through a real in-process engine ----------------------------


@pytest.fixture(scope="module")
def ledger_sched():
    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.engine.scheduler import Scheduler
    from localai_tpu.models.registry import resolve_model
    from localai_tpu.obs import EngineTelemetry
    from localai_tpu.utils.tokenizer import ByteTokenizer

    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(
        tiny.cfg, tiny.params, num_slots=2, max_ctx=96,
        prefill_buckets=[16, 32], kv_dtype="float32",
        paged=True, kv_block_tokens=16,
    )
    s = Scheduler(runner, ByteTokenizer(),
                  telemetry=EngineTelemetry(model="ledger-tiny"))
    yield s
    s.shutdown()


@pytest.fixture()
def clean_ledger():
    LEDGER.reset()
    yield LEDGER
    LEDGER.reset()


def test_engine_feeds_ledger_for_stamped_requests(ledger_sched,
                                                  clean_ledger):
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.utils.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    hs = [
        ledger_sched.submit(GenRequest(
            prompt=tok.encode(f"ledger smoke {i}"), max_new_tokens=6,
            temperature=0.0, tenant=derive_tenant(f"key-{i % 2}"),
        ))
        for i in range(4)
    ]
    for h in hs:
        h.result(timeout=300)
    snap = clean_ledger.snapshot()
    assert set(snap["tenants"]) == {derive_tenant("key-0"),
                                    derive_tenant("key-1")}
    for tenant, panes in snap["tenants"].items():
        pane = panes["ledger-tiny/interactive"]
        assert pane["requests"] == 2
        assert pane["delivered_tokens"] > 0
        assert pane["prompt_tokens"] > 0
        assert pane["dispatch_ms"] > 0
        assert pane["kv_block_seconds"] > 0


def test_unstamped_requests_stay_unattributed(ledger_sched, clean_ledger):
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.utils.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    ledger_sched.submit(GenRequest(
        prompt=tok.encode("no tenant here"), max_new_tokens=4,
        temperature=0.0,
    )).result(timeout=300)
    assert clean_ledger.snapshot()["tenants"] == {}


def test_engine_delivery_reconciles_with_flight_ring(ledger_sched,
                                                     clean_ledger):
    """The identity the decomposition docstring promises, on a real
    engine: with only natural completions, the ledger's delivered tokens
    for THIS batch equal the growth of the flight ring's token total."""
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.utils.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    before = ledger_sched.flight.total_tokens
    hs = [
        ledger_sched.submit(GenRequest(
            prompt=tok.encode(f"reconcile {i}"), max_new_tokens=5,
            temperature=0.0, tenant="t-reconcile",
        ))
        for i in range(3)
    ]
    for h in hs:
        h.result(timeout=300)
    g = clean_ledger.goodput_totals("ledger-tiny")
    assert g["waste_tokens"] == 0
    assert g["delivered_tokens"] == (
        ledger_sched.flight.total_tokens - before)


# -- attribution through the fleet dispatch tier -----------------------------


def test_fleet_dispatch_feeds_front_door_exactly_once(clean_ledger):
    """A shared-process fleet: the front-door WorkerScheduler stamps the
    feed and InProcessReplica DROPS the tenant on the inner resubmit —
    the pane must count every request once, not once per tier."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.models.manager import build_serving_model

    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": "ledger-fleet", "model": "debug:tiny",
        "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 6},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16},
    })

    def factory(rid, role):
        return InProcessReplica(
            rid, role, lambda: build_serving_model(mcfg, app))

    fm = FleetServingModel(mcfg, app, factory, replicas=2,
                           prefill_replicas=0, disagg_threshold=1 << 30)
    try:
        tok = fm.tokenizer
        hs = [
            fm.scheduler.submit(GenRequest(
                prompt=tok.encode(f"fleet ledger {i}"), max_new_tokens=5,
                temperature=0.0, tenant="t-fleet",
            ))
            for i in range(4)
        ]
        delivered = 0
        for h in hs:
            h.result(timeout=300)
            assert h.finish_reason in ("stop", "length")
            delivered += h.completion_tokens
        snap = clean_ledger.snapshot()
        panes = snap["tenants"]["t-fleet"]
        # ONLY the front door's pane: the inner engines saw no tenant
        assert set(panes) == {"ledger-fleet/interactive"}
        pane = panes["ledger-fleet/interactive"]
        assert pane["requests"] == 4                 # once, not twice
        assert pane["delivered_tokens"] == delivered
        assert snap["goodput_tokens"] == {"ledger-fleet": delivered}
    finally:
        fm.close()


def test_fleet_failover_charges_reprefill_waste(clean_ledger):
    """A replica death mid-dispatch re-prefills on the survivor; the
    decomposition must charge the prompt to failover_reprefill under the
    request's tenant."""
    from localai_tpu import faults
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.models.manager import build_serving_model

    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": "ledger-failover", "model": "debug:tiny",
        "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 6},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16},
    })

    def factory(rid, role):
        return InProcessReplica(
            rid, role, lambda: build_serving_model(mcfg, app))

    fm = FleetServingModel(mcfg, app, factory, replicas=2,
                           prefill_replicas=0, disagg_threshold=1 << 30)
    try:
        tok = fm.tokenizer
        # warm both replicas so the victim is known to the router
        fm.scheduler.submit(GenRequest(
            prompt=tok.encode("warm"), max_new_tokens=2, temperature=0.0,
        )).result(timeout=300)
        victim = fm.pool.replicas[0].id
        faults.arm(faults.FaultSpec(site="worker.stream", mode="raise",
                                    match=victim, times=1))
        try:
            prompt = tok.encode("failover ledger prompt")
            h = fm.scheduler.submit(GenRequest(
                prompt=prompt, max_new_tokens=4, temperature=0.0,
                tenant="t-failover",
            ))
            h.result(timeout=300)
            assert h.finish_reason in ("stop", "length")
        finally:
            faults.clear()
        snap = clean_ledger.snapshot()
        cell = snap["waste"].get("failover_reprefill/ledger-failover")
        if cell is not None:  # the victim may not win the first dispatch
            assert cell["tokens"] == len(prompt)
            assert cell["requests"] == 1
            pane = snap["tenants"]["t-failover"][
                "ledger-failover/interactive"]
            assert pane["waste_tokens"] >= len(prompt)
    finally:
        fm.close()
