"""HTTP API tests: full in-process server against the tiny debug model —
the analogue of the reference's in-process API suite
(/root/reference/core/http/app_test.go: boots the fiber app against a temp
models dir and drives it with real OpenAI clients)."""

import asyncio
import json
import threading

import httpx
import pytest

from localai_tpu.api.server import AppState, create_app
from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.loader import ConfigLoader

TINY_YAML = """\
name: tiny
model: "debug:tiny"
context_size: 96
embeddings: true
parameters:
  temperature: 0.0
  max_tokens: 16
engine:
  max_slots: 4
  prefill_buckets: [16, 32]
  dtype: float32
  kv_dtype: float32
"""


class _ServerThread:
    """Real aiohttp server on a random port, in its own loop thread."""

    def __init__(self, state: AppState):
        self.state = state
        self.port = None
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._started.wait(30), "server failed to start"

    def _run(self):
        from aiohttp import web

        asyncio.set_event_loop(self._loop)

        async def boot():
            app = create_app(self.state)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            self.port = runner.addresses[0][1]
            self._runner = runner
            self._started.set()

        self._loop.run_until_complete(boot())
        self._loop.run_forever()

    @property
    def base(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        async def down():
            await self._runner.cleanup()

        fut = asyncio.run_coroutine_threadsafe(down(), self._loop)
        fut.result(10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(10)


def make_state(models_dir, *, write_tiny: bool = False) -> AppState:
    """AppState over a models dir (shared with test_gallery). Upload and
    config dirs live NEXT TO the models dir (a tmp path) — the durable
    file/batch registries must never leak into the repo working dir."""
    from pathlib import Path

    models_dir = Path(models_dir)
    if write_tiny:
        (models_dir / "tiny.yaml").write_text(TINY_YAML)
    cfg = AppConfig(
        model_path=str(models_dir),
        # sibling dirs named after the (unique) tmp models dir, so states
        # built from different tmp paths never share durable registries
        upload_path=str(models_dir) + "_uploads",
        config_path=str(models_dir) + "_conf",
    )
    loader = ConfigLoader(models_dir)
    loader.load_from_path(context_size=cfg.context_size)
    return AppState(cfg, loader)


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    models = tmp_path_factory.mktemp("models")
    state = make_state(models, write_tiny=True)
    srv = _ServerThread(state)
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    with httpx.Client(base_url=server.base, timeout=120.0) as c:
        yield c


def test_welcome_and_health(client):
    assert client.get("/healthz").json()["status"] == "ok"
    r = client.get("/readyz").json()
    assert r["models_configured"] == 1
    root = client.get("/").json()
    assert "tiny" in root["models"]


def test_list_models(client):
    data = client.get("/v1/models").json()
    assert data["object"] == "list"
    assert [m["id"] for m in data["data"]] == ["tiny"]
    filtered = client.get("/v1/models", params={"filter": "nope"}).json()
    assert filtered["data"] == []


def test_chat_completion(client):
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello there"}],
        "max_tokens": 8,
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["object"] == "chat.completion"
    choice = body["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert choice["finish_reason"] in ("stop", "length")
    assert body["usage"]["prompt_tokens"] > 0
    assert body["usage"]["completion_tokens"] <= 8


def test_chat_default_model_resolution(client):
    r = client.post("/v1/chat/completions", json={
        "messages": [{"role": "user", "content": "no model given"}],
        "max_tokens": 4,
    })
    assert r.status_code == 200
    assert r.json()["model"] == "tiny"


def test_chat_streaming_sse(client):
    deltas, finals = [], []
    with client.stream("POST", "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "stream this"}],
        "max_tokens": 6,
        "stream": True,
    }) as r:
        assert r.status_code == 200
        assert r.headers["content-type"].startswith("text/event-stream")
        for line in r.iter_lines():
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                finals.append("DONE")
                continue
            chunk = json.loads(payload)
            assert chunk["object"] == "chat.completion.chunk"
            deltas.append(chunk["choices"][0])
    assert finals == ["DONE"]
    assert deltas[0]["delta"].get("role") == "assistant"
    assert deltas[-1]["finish_reason"] in ("stop", "length")


def test_chat_n_choices(client):
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "variants"}],
        "max_tokens": 4,
        "n": 2,
    })
    body = r.json()
    assert [c["index"] for c in body["choices"]] == [0, 1]


def test_chat_with_tools_returns_tool_calls(client):
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "weather in Oslo?"}],
        "max_tokens": 120,
        "temperature": 0.8,
        "seed": 11,
        "tools": [{
            "type": "function",
            "function": {
                "name": "get_weather",
                "parameters": {
                    "type": "object",
                    "properties": {"city": {"type": "string",
                                            "maxLength": 8}},
                    "required": ["city"],
                },
            },
        }],
    })
    assert r.status_code == 200, r.text
    choice = r.json()["choices"][0]
    msg = choice["message"]
    # grammar-constrained: either a real tool call or the no-action answer
    if msg.get("tool_calls"):
        assert choice["finish_reason"] == "tool_calls"
        call = msg["tool_calls"][0]["function"]
        assert call["name"] == "get_weather"
        json.loads(call["arguments"])
    else:
        assert msg["content"]


def test_chat_json_mode(client):
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "give me json"}],
        "max_tokens": 100,
        "temperature": 0.8,
        "seed": 3,
        "response_format": {"type": "json_object"},
    })
    content = r.json()["choices"][0]["message"]["content"]
    json.loads(content)  # must be valid JSON under the constraint


def test_completions(client):
    r = client.post("/v1/completions", json={
        "model": "tiny",
        "prompt": "Once upon a time",
        "max_tokens": 6,
    })
    body = r.json()
    assert body["object"] == "text_completion"
    assert body["choices"][0]["finish_reason"] in ("stop", "length")


def test_completions_echo_and_list_prompt(client):
    r = client.post("/v1/completions", json={
        "model": "tiny",
        "prompt": ["alpha", "beta"],
        "max_tokens": 3,
        "echo": True,
    })
    choices = r.json()["choices"]
    assert len(choices) == 2
    assert choices[0]["text"].startswith("alpha")
    assert choices[1]["text"].startswith("beta")


def test_edits(client):
    r = client.post("/v1/edits", json={
        "model": "tiny",
        "prompt": "helo wrld",
        "instruction": "fix spelling",
        "max_tokens": 6,
    })
    assert r.json()["object"] == "edit"


def test_embeddings(client):
    r = client.post("/v1/embeddings", json={
        "model": "tiny",
        "input": ["first text", "second text"],
    })
    body = r.json()
    assert body["object"] == "list"
    assert len(body["data"]) == 2
    dim = len(body["data"][0]["embedding"])
    assert dim == 64  # tiny hidden size
    assert body["data"][1]["index"] == 1
    # deterministic: same input → same vector
    r2 = client.post("/v1/embeddings", json={
        "model": "tiny", "input": "first text",
    })
    assert r2.json()["data"][0]["embedding"] == pytest.approx(
        body["data"][0]["embedding"]
    )


def test_tokenize(client):
    r = client.post("/v1/tokenize", json={
        "model": "tiny", "content": "hi",
    })
    assert r.json()["tokens"] == [104, 105]


def test_system_and_metrics(client):
    sysinfo = client.get("/system").json()
    assert sysinfo["devices"]
    assert "tiny" in sysinfo["configured_models"]
    metrics = client.get("/metrics").text
    assert "localai_api_call_seconds" in metrics
    assert 'path="/v1/chat/completions"' in metrics


def test_backend_monitor_and_shutdown(client):
    mon = client.post("/backend/monitor", json={"model": "tiny"}).json()
    assert mon["loaded"] is True
    assert mon["num_slots"] == 4
    shut = client.post("/backend/shutdown", json={"model": "tiny"}).json()
    assert shut["shutdown"] is True
    mon = client.post("/backend/monitor", json={"model": "tiny"}).json()
    assert mon["loaded"] is False
    # next request transparently reloads
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "reload"}],
        "max_tokens": 2,
    })
    assert r.status_code == 200


def test_unknown_model_404(client):
    r = client.post("/v1/chat/completions", json={
        "model": "missing",
        "messages": [{"role": "user", "content": "x"}],
    })
    assert r.status_code == 404
    assert r.json()["error"]["type"] == "invalid_request_error"


def test_bad_json_400(client):
    r = client.post("/v1/chat/completions", content=b"{not json")
    assert r.status_code == 400


def test_schema_mismatch_400(client):
    """Valid JSON, wrong shape → 400 invalid_request_error, never a 500."""
    r = client.post("/v1/chat/completions", json={"messages": "hi"})
    assert r.status_code == 400


def test_metrics_token_series(client):
    client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "count me"}],
        "max_tokens": 4,
    })
    r = client.get("/metrics")
    assert r.status_code == 200
    body = r.text
    assert 'localai_tokens_generated_total{model="tiny"}' in body
    assert 'localai_prompt_tokens_total{model="tiny"}' in body
    # histogram series must be labeled by route pattern, not raw path
    assert 'path="/v1/chat/completions"' in body


def test_metrics_engine_series(client):
    """/metrics carries the obs engine series after a generation: batch
    occupancy, cache-hit-rate family, speculative family, compile time."""
    client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "occupancy"}],
        "max_tokens": 4,
    })
    body = client.get("/metrics").text
    assert 'localai_batch_occupancy{model="tiny"}' in body
    assert 'localai_kv_slot_utilization{model="tiny"}' in body
    assert 'localai_ttft_seconds_count{model="tiny"}' in body
    assert 'localai_queue_wait_seconds_count{model="tiny"}' in body
    assert 'localai_requests_total{' in body
    assert 'localai_decode_dispatches_total{model="tiny"}' in body
    # compile time recorded by the runner's watched jit entry points —
    # the paged default prefills through the chunk program, contiguous
    # engines through "prefill"
    assert ('localai_xla_compile_seconds_total{program="prefill_chunk"}' in body
            or 'localai_xla_compile_seconds_total{program="prefill"}' in body)
    # family names present even with no series yet (scrape stability)
    assert "# TYPE localai_prompt_cache_hit_rate gauge" in body
    assert "# TYPE localai_speculative_accept_rate gauge" in body


def test_traces_endpoint_returns_span_tree(client):
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "trace tree"}],
        "max_tokens": 6,
    }, headers={"X-Trace-ID": "trace-span-tree"})
    assert r.status_code == 200
    assert r.headers.get("X-Trace-ID") == "trace-span-tree"
    data = client.get("/v1/traces", params={"limit": 100}).json()
    mine = [t for t in data["traces"] if t["trace_id"] == "trace-span-tree"]
    kinds = {t["kind"] for t in mine}
    assert "request" in kinds and "http" in kinds
    engine = next(t for t in mine if t["kind"] == "request")
    names = [c["name"] for c in engine["children"]]
    for phase in ("queued", "prefill", "decode"):
        assert phase in names
    assert engine["attrs"]["ttft_ms"] is not None
    assert engine["attrs"]["tpot_ms"] is not None
    assert engine["attrs"]["finish_reason"] in ("stop", "length")


def test_debug_timeline_merges_http_and_engine(client):
    client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "timeline"}],
        "max_tokens": 4,
    }, headers={"X-Trace-ID": "trace-timeline-1"})
    r = client.get("/debug/timeline/trace-timeline-1")
    assert r.status_code == 200
    body = r.json()
    sources = {e["kind"] for e in body["timeline"]}
    assert sources == {"http", "request"}
    offsets = [e["offset_ms"] for e in body["timeline"]]
    assert offsets == sorted(offsets) and offsets[0] == 0.0
    # unknown ids 404 rather than returning an empty timeline
    assert client.get("/debug/timeline/never-seen").status_code == 404


def test_streaming_first_token_event_recorded(client):
    with client.stream("POST", "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "first token"}],
        "max_tokens": 6,
        "stream": True,
    }, headers={"X-Trace-ID": "trace-sse-first"}) as r:
        assert r.status_code == 200
        for _line in r.iter_lines():
            pass
    body = client.get("/debug/timeline/trace-sse-first").json()
    assert any(e["name"] == "first_sse_write" for e in body["timeline"])


def test_auth_enforced(tmp_path):
    models = tmp_path / "models"
    models.mkdir()
    (models / "tiny.yaml").write_text(TINY_YAML)
    cfg = AppConfig(model_path=str(models), api_keys=["sekret"])
    loader = ConfigLoader(models)
    loader.load_from_path()
    state = AppState(cfg, loader)
    srv = _ServerThread(state)
    try:
        with httpx.Client(base_url=srv.base, timeout=30.0) as c:
            assert c.get("/healthz").status_code == 200  # exempt
            r = c.get("/v1/models")
            assert r.status_code == 401
            r = c.get("/v1/models",
                      headers={"Authorization": "Bearer wrong"})
            assert r.status_code == 401
            r = c.get("/v1/models",
                      headers={"Authorization": "Bearer sekret"})
            assert r.status_code == 200
    finally:
        srv.stop()


def test_completions_streaming_list_prompt_serves_all(client):
    """A list prompt streams EVERY prompt, each on its own choice index
    (previously only templated[0] streamed and the rest silently dropped)."""
    seen = {}
    finishes = {}
    usage = None
    with client.stream("POST", "/v1/completions", json={
        "model": "tiny",
        "prompt": ["alpha", "beta"],
        "max_tokens": 6,
        "stream": True,
    }) as r:
        assert r.status_code == 200
        for line in r.iter_lines():
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            chunk = json.loads(payload)
            ch = chunk["choices"][0]
            idx = ch["index"]
            if ch["finish_reason"] is not None:
                finishes[idx] = ch["finish_reason"]
            else:
                seen[idx] = seen.get(idx, "") + ch["text"]
    assert set(finishes) == {0, 1}
    assert all(f in ("stop", "length") for f in finishes.values())
    assert set(seen) <= {0, 1}


def test_correlation_id_echoed_and_traced(client):
    """X-Correlation-ID flows from the request header into the scheduler's
    request (visible in engine metrics) and back out on the response
    (parity: chat.go:164-169)."""
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "trace me"}],
        "max_tokens": 4,
    }, headers={"X-Correlation-ID": "trace-abc-123"})
    assert r.status_code == 200
    assert r.headers.get("X-Correlation-ID") == "trace-abc-123"
    # without the header, the generated request id is echoed instead
    r2 = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "no header"}],
        "max_tokens": 4,
    })
    assert r2.headers.get("X-Correlation-ID", "").startswith("chatcmpl-")


def test_chat_streaming_n_choices(client):
    """stream + n>1: every choice streams on its own index and finishes."""
    finishes = {}
    usage = None
    roles = set()
    with client.stream("POST", "/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "variants"}],
        "max_tokens": 5,
        "n": 3,
        "stream": True,
    }) as r:
        assert r.status_code == 200
        for line in r.iter_lines():
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            frame = json.loads(payload)
            if not frame["choices"]:
                usage = frame.get("usage")
                continue
            ch = frame["choices"][0]
            if ch["delta"].get("role"):
                roles.add(ch["index"])
            if ch["finish_reason"] is not None:
                finishes[ch["index"]] = ch["finish_reason"]
    assert set(finishes) == {0, 1, 2}
    assert roles == {0, 1, 2}
    assert all(f in ("stop", "length") for f in finishes.values())
    # one usage frame, prompt tokens counted once
    assert usage is not None
    assert usage["completion_tokens"] <= 15
    assert 0 < usage["prompt_tokens"] < 40


def test_backend_trace_capture(tmp_path):
    """POST /backend/trace captures a jax profiler trace to disk; bad
    input is a client error (400), a concurrent capture a conflict (409)."""
    state = make_state(tmp_path, write_tiny=True)
    srv = _ServerThread(state)
    try:
        import httpx

        with httpx.Client(base_url=srv.base, timeout=120.0) as c:
            r = c.post("/backend/trace", json={"seconds": 0.2})
            assert r.status_code == 200
            out = r.json()["trace_dir"]
            import pathlib

            assert pathlib.Path(out).exists()
            assert c.post("/backend/trace",
                          json={"seconds": 999}).status_code == 400
            assert c.post("/backend/trace",
                          json={"seconds": 0.2, "dir": "../../x"}
                          ).status_code == 400
            # malformed JSON body → 400, not an unhandled 500
            r = c.post("/backend/trace", content=b"{not json",
                       headers={"Content-Type": "application/json"})
            assert r.status_code == 400
            assert c.post("/backend/trace",
                          json=[1, 2]).status_code == 400
            assert c.post("/backend/trace",
                          json={"seconds": "soon"}).status_code == 400
            # one capture at a time: the profiler's shared capture lock
            # held (an anomaly capture in flight) → 409 Conflict
            from localai_tpu.obs.profiler import PROFILER

            assert PROFILER.acquire_capture()
            try:
                r = c.post("/backend/trace", json={"seconds": 0.2})
                assert r.status_code == 409
                assert "already running" in r.json()["error"]["message"]
            finally:
                PROFILER.release_capture()
    finally:
        srv.stop()


# -- introspection endpoints (obs round 6) -----------------------------------


def test_debug_devices_reports_health_and_census(client):
    r = client.get("/debug/devices", params={"probe_timeout": 60})
    assert r.status_code == 200
    data = r.json()
    assert data["devices"] and data["devices"][0]["platform"] == "cpu"
    # the CPU backend has no allocator stats; the field must be present
    # (and null) rather than absent, so dashboards can key on it
    assert "memory" in data["devices"][0]
    census = data["census"]
    assert census["arrays"] > 0
    # the loaded tiny model's weights and KV cache are attributed
    assert census["by_category"]["weights"] > 0
    assert census["by_category"]["kv_cache"] > 0
    assert data["probe"]["ok"] is True
    assert data["probe"]["seconds"] > 0
    assert data["roofline"]["peak_gbps"] > 0
    assert isinstance(data["watchdog"], dict)


def test_debug_devices_probe_skippable(client):
    data = client.get("/debug/devices", params={"probe": "0"}).json()
    assert "probe" not in data
    assert client.get(
        "/debug/devices", params={"probe_timeout": "nan-ish"}
    ).status_code == 400


def test_debug_programs_reports_cost_and_roofline_fraction(client):
    # make sure the decode program has dispatched + has a latency sample
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "cost catalog"}],
        "max_tokens": 24,
    })
    assert r.status_code == 200
    data = client.get("/debug/programs").json()
    assert data["roofline"]["peak_gbps"] > 0
    programs = data["programs"]
    assert programs
    decode = [p for p in programs
              if p["program"].startswith("decode") and p.get("flops")]
    assert decode, f"no decode cost entry in {programs}"
    d = decode[0]
    # the acceptance criterion: nonzero FLOPs/bytes and an achieved
    # bandwidth fraction for the decode-step program on the CPU test mesh
    assert d["flops"] > 0 and d["bytes_accessed"] > 0
    withfrac = [p for p in decode
                if p.get("bandwidth_fraction") is not None]
    assert withfrac, "no decode entry joined with a measured latency"
    assert withfrac[0]["bandwidth_fraction"] >= 0
    # filter to live instances: the backend-shutdown test earlier in this
    # module unloads/reloads the model, leaving dead catalog entries
    # (cost_error="program no longer live") next to the live ones.
    # Paged engines (the serving default) compile their prefill under the
    # chunked-prefill label; contiguous engines under "prefill".
    prefill = [p for p in programs
               if p["program"] in ("prefill", "prefill_chunk")
               and p.get("flops")]
    assert prefill and prefill[0]["flops"] > 0


def test_debug_stacks_lists_threads(client):
    data = client.get("/debug/stacks").json()
    assert data["threads"]
    names = {t["thread"] for t in data["threads"]}
    assert "MainThread" in names
    assert all("stack" in t for t in data["threads"])


def test_simulated_hung_dispatch_full_stall_lifecycle(client):
    """Acceptance: a test-injected blocking callable trips the watchdog
    within its deadline, sets engine_stalled=1 at /metrics, records a
    thread-stack forensic span retrievable via GET /v1/traces, and clears
    on recovery."""
    import threading as _threading
    import time as _time

    from localai_tpu.obs import Watchdog

    # default registry/store = the process-wide ones the server exposes
    wd = Watchdog(deadline=0.15, poll_interval=0.03)
    wd.start()
    release = _threading.Event()
    tripped = _threading.Event()
    wd.on_stall(lambda e: e.kind == "stall" and tripped.set())

    def hung_dispatch():
        with wd.guard("hung-dispatch"):
            release.wait(10.0)

    t = _threading.Thread(target=hung_dispatch, daemon=True)
    t.start()
    try:
        assert tripped.wait(3.0), "watchdog did not trip within deadline"
        text = client.get("/metrics").text
        assert 'localai_engine_stalled{channel="hung-dispatch"} 1' in text
        assert 'localai_stalls_total{channel="hung-dispatch"}' in text
        traces = client.get(
            "/v1/traces", params={"kind": "stall", "limit": 20}).json()
        mine = [tr for tr in traces["traces"]
                if tr["attrs"].get("channel") == "hung-dispatch"]
        assert mine, "forensic stall span not retrievable via /v1/traces"
        dump = mine[0]
        assert dump["attrs"]["threads"] >= 1
        stacks = [c["attrs"]["stack"] for c in dump["children"]
                  if c["name"] == "thread"]
        assert any("hung_dispatch" in s for s in stacks), (
            "stack dump must show the hung frame")
    finally:
        release.set()
        t.join(5.0)
    deadline = _time.monotonic() + 3.0
    while wd.stalled("hung-dispatch") and _time.monotonic() < deadline:
        _time.sleep(0.02)
    assert not wd.stalled("hung-dispatch")
    assert ('localai_engine_stalled{channel="hung-dispatch"} 0'
            in client.get("/metrics").text)
    wd.stop()


def test_metrics_exposes_device_health_series(client):
    text = client.get("/metrics").text
    # scrape-time refresh: live-bytes census always present; device_ok
    # appears once any probe ran (the /debug/devices test above)
    assert "# TYPE localai_hbm_live_bytes gauge" in text
    assert 'localai_hbm_live_bytes{category="kv_cache"}' in text
    assert "# TYPE localai_engine_stalled gauge" in text


# -- flight recorder + SLO observatory (obs round 7) -------------------------


def test_debug_flight_reports_dispatch_records(client):
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "flight record"}],
        "max_tokens": 24,
    })
    assert r.status_code == 200
    data = client.get("/debug/flight").json()
    assert "tiny" in data["models"]
    ring = data["models"]["tiny"]
    assert ring["records"], "flight ring empty after a generation"
    rec = ring["records"][-1]
    for key in ("ts", "ts_unix", "program", "steps", "dispatch_ms",
                "occupancy", "queue_depth", "kv_utilization", "tokens",
                "preemptions", "compile"):
        assert key in rec
    assert ring["dispatches"] >= len(ring["records"])
    assert ring["tokens_total"] > 0
    assert ring["capacity"] > 0
    assert "step_ms_p50" in ring["percentiles"]
    # ?since= windows the poll: everything before "now" filters out
    later = client.get("/debug/flight",
                       params={"since": data["now_monotonic"] + 100}).json()
    assert later["models"].get("tiny", {}).get("records") == []
    mid = rec["ts"] - 1e-9
    newer = client.get("/debug/flight", params={"since": mid}).json()
    assert newer["models"]["tiny"]["records"]
    assert client.get("/debug/flight",
                      params={"since": "soon"}).status_code == 400
    assert client.get("/debug/flight",
                      params={"limit": "many"}).status_code == 400


def test_trace_detail_stitched_waterfall(client):
    # a single-engine model still renders the one-waterfall view (no
    # replica panes to harvest; front-door + engine spans untagged)
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "stitch detail"}],
        "max_tokens": 6,
    }, headers={"X-Trace-ID": "trace-detail-1"})
    assert r.status_code == 200
    body = client.get("/v1/traces/trace-detail-1").json()
    assert body["trace_id"] == "trace-detail-1"
    assert body["replicas"] == {}
    names = [e["name"] for e in body["waterfall"]]
    assert "decode" in names
    offsets = [e["offset_ms"] for e in body["waterfall"]]
    assert offsets == sorted(offsets)
    assert all(e["replica"] == "" for e in body["waterfall"])
    # unknown trace id → 404, not an empty waterfall
    assert client.get("/v1/traces/trace-nope-404").status_code == 404


def test_debug_fleet_flight_and_profiles(client):
    # no fleet-served model loaded: the merged view answers with an
    # empty models map (never errors), and the profile manifest renders
    # its (disarmed) state
    data = client.get("/debug/fleet/flight").json()
    assert data["models"] == {}
    assert client.get("/debug/fleet/flight",
                      params={"since": "soon"}).status_code == 400
    assert client.get("/debug/fleet/flight",
                      params={"limit": "many"}).status_code == 400
    prof = client.get("/debug/profiles").json()
    assert prof["enabled"] is False  # LOCALAI_PROFILE_ON_ANOMALY unset
    assert prof["profiles"] == [] and "cooldown_s" in prof


def test_metrics_exports_trace_ring_size(client):
    body = client.get("/metrics").text
    assert "localai_trace_ring_size 256" in body


def test_debug_kv_reports_block_audit(client):
    client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "kv audit"}],
        "max_tokens": 4,
    })
    data = client.get("/debug/kv").json()
    tiny = data["models"]["tiny"]
    blocks = tiny["blocks"]
    # conservation holds with all traffic drained
    assert blocks["free"] + blocks["used"] + blocks["cached"] \
        == blocks["total"]
    assert tiny["invariant_violations"] == []
    assert tiny["block_tokens"] >= 8
    assert "violations_seen" in tiny


def test_debug_faults_arm_list_clear(client):
    from localai_tpu import faults

    try:
        data = client.get("/debug/faults").json()
        assert data["active"] is False and data["armed"] == []
        assert "engine.drain" in data["sites"]
        # the in-process supervisor attached by build_serving_model shows
        assert data["supervisors"]["tiny"]["failed"] is False
        r = client.post("/debug/faults", json={
            "site": "engine.dispatch", "mode": "raise", "after": 3,
            "times": 1, "match": "decode"})
        assert r.status_code == 200
        data = client.get("/debug/faults").json()
        assert data["active"] is True
        assert data["armed"][0]["site"] == "engine.dispatch"
        assert client.post("/debug/faults", json={
            "site": "no.such.site"}).status_code == 400
        assert client.post("/debug/faults", json={
            "site": "engine.dispatch", "bogus": 1}).status_code == 400
        assert client.post("/debug/faults", json=[1, 2]).status_code == 400
        cleared = client.delete("/debug/faults").json()
        assert cleared["cleared"] == 1
        assert client.get("/debug/faults").json()["active"] is False
    finally:
        faults.clear()


def test_v1_slo_reports_windows(client):
    client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "slo window"}],
        "max_tokens": 4,
    })
    data = client.get("/v1/slo").json()
    assert data["windows"] == ["1m", "5m", "30m"]
    assert "targets" in data and "burn_threshold" in data
    tiny = data["models"]["tiny"]
    assert tiny["shedding"] is False
    agg = tiny["windows"]["1m"]
    assert agg["count"] >= 1
    assert agg["ttft_ms"] is not None and agg["ttft_ms"]["p95"] > 0
    assert agg["e2e_ms"]["p95"] >= agg["ttft_ms"]["p50"]


def test_overload_sheds_with_429_and_recovers(client):
    """Acceptance: a simulated overload (impossible TTFT target) flips
    localai_overload_shedding, 429s new generation work with Retry-After,
    counts the shed at /metrics and in the scheduler's metrics dict, and
    admits again once the observatory recovers."""
    from localai_tpu.obs import slo as obs_slo

    SLO = obs_slo.SLO
    saved = dict(targets=dict(SLO.targets), burn_threshold=SLO.burn_threshold,
                 recover_burn=SLO.recover_burn, min_events=SLO.min_events)
    SLO.reset()
    SLO.configure(targets={"ttft_ms": 1e-6}, burn_threshold=1.0,
                  recover_burn=1.0, min_events=2)
    try:
        # two completions violate the impossible target → both windows hot
        for i in range(2):
            r = client.post("/v1/chat/completions", json={
                "model": "tiny",
                "messages": [{"role": "user", "content": f"burn {i}"}],
                "max_tokens": 2,
            })
            assert r.status_code == 200
        r = client.post("/v1/chat/completions", json={
            "model": "tiny",
            "messages": [{"role": "user", "content": "shed me"}],
            "max_tokens": 2,
        })
        assert r.status_code == 429
        assert r.headers.get("Retry-After") == str(SLO.retry_after_s)
        assert "shedding load" in r.json()["error"]["message"]
        # streaming completions shed identically (same admission hook)
        r = client.post("/v1/completions", json={
            "model": "tiny", "prompt": "shed", "max_tokens": 2,
        })
        assert r.status_code == 429
        text = client.get("/metrics").text
        assert 'localai_overload_shedding{model="tiny"} 1' in text
        assert 'localai_requests_shed_total{model="tiny"} 2' in text
        assert 'localai_slo_burn_rate{model="tiny",window="1m"}' in text
        # the scheduler's JSON mirror counted both refusals
        em = client.get("/backend/metrics").json()
        assert em["tiny"]["shed_total"] == 2
        assert client.get("/v1/slo").json()["models"]["tiny"]["shedding"]
        # recovery: clear the objectives (operator action) → admitted again
        SLO.configure(targets={})
        r = client.post("/v1/chat/completions", json={
            "model": "tiny",
            "messages": [{"role": "user", "content": "recovered"}],
            "max_tokens": 2,
        })
        assert r.status_code == 200
        assert ('localai_overload_shedding{model="tiny"} 0'
                in client.get("/metrics").text)
    finally:
        SLO.configure(**saved)
        SLO.reset()


def test_slo_ui_page_served(client):
    r = client.get("/slo", headers={"Accept": "text/html"})
    assert r.status_code == 200
    assert "SLO observatory" in r.text
    assert "Flight recorder" in r.text


def test_debug_devices_probe_timeout_validated(client):
    # NaN/zero/negative → 400; inf is accepted but clamped server-side so
    # a wedged device can't pin an executor thread forever
    for bad in ("nan", "0", "-3"):
        assert client.get("/debug/devices",
                          params={"probe_timeout": bad}).status_code == 400
    assert client.get("/debug/devices",
                      params={"probe_timeout": "inf"}).status_code == 200


# ---------------------------------------------------------------------------
# offline batch API (localai_tpu.batch)


def _upload_batch_file(client, lines, name="batch_input.jsonl"):
    payload = ("\n".join(json.dumps(l) for l in lines) + "\n").encode()
    r = client.post("/v1/files", files={"file": (name, payload)},
                    data={"purpose": "batch"})
    assert r.status_code == 200, r.text
    return r.json()


def test_batch_api_end_to_end(client):
    """Acceptance: a job submitted via /v1/files + /v1/batches runs to
    completed with a downloadable per-line output file, while a concurrent
    interactive request keeps being served."""
    import time as _time

    f = _upload_batch_file(client, [
        {"custom_id": f"req-{i}", "method": "POST",
         "url": "/v1/chat/completions",
         "body": {"model": "tiny", "max_tokens": 4, "temperature": 0.0,
                  "messages": [{"role": "user",
                                "content": f"batch line {i}"}]}}
        for i in range(5)
    ])
    assert f["purpose"] == "batch"
    r = client.post("/v1/batches", json={
        "endpoint": "/v1/chat/completions",
        "input_file_id": f["id"],
        "metadata": {"suite": "test_api"},
    })
    assert r.status_code == 200, r.text
    job = r.json()
    assert job["object"] == "batch" and job["status"] == "validating"
    # a concurrent interactive request is admitted ahead of pending batch
    # lines (the lane policy) — and must simply succeed here
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "interactive wins"}],
        "max_tokens": 4,
    })
    assert r.status_code == 200
    deadline = _time.monotonic() + 120
    while _time.monotonic() < deadline:
        job = client.get(f"/v1/batches/{job['id']}").json()
        if job["status"] in ("completed", "failed", "cancelled", "expired"):
            break
        _time.sleep(0.2)
    assert job["status"] == "completed", job
    assert job["request_counts"] == {"total": 5, "completed": 5,
                                     "failed": 0}
    # listed, and the per-line output downloads through the file registry
    listed = client.get("/v1/batches").json()
    assert job["id"] in [j["id"] for j in listed["data"]]
    out = client.get(f"/v1/files/{job['output_file_id']}/content")
    assert out.status_code == 200
    records = [json.loads(l) for l in out.text.splitlines()]
    assert {rec["custom_id"] for rec in records} == {f"req-{i}"
                                                     for i in range(5)}
    for rec in records:
        assert rec["response"]["status_code"] == 200
        body = rec["response"]["body"]
        assert body["choices"][0]["message"]["content"] is not None
    meta = client.get(f"/v1/files/{job['output_file_id']}").json()
    assert meta["purpose"] == "batch_output"
    # batch series render at /metrics; the lane is not paused
    text = client.get("/metrics").text
    assert 'localai_batch_jobs{state="completed"} 1' in text
    assert 'localai_batch_lane_paused 0' in text
    # cancel on a terminal job is a no-op, unknown id is 404
    r = client.post(f"/v1/batches/{job['id']}/cancel")
    assert r.status_code == 200 and r.json()["status"] == "completed"
    assert client.post("/v1/batches/batch_999/cancel").status_code == 404


def test_batch_create_validation(client):
    r = client.post("/v1/batches", json={"endpoint": "/v1/images",
                                         "input_file_id": "file-1"})
    assert r.status_code == 400
    r = client.post("/v1/batches", json={
        "endpoint": "/v1/chat/completions", "input_file_id": "file-999"})
    assert r.status_code == 404
    # a file uploaded for assistants cannot seed a batch job
    payload = b'{"custom_id": "a"}\n'
    f = client.post("/v1/files",
                    files={"file": ("not_batch.jsonl", payload)},
                    data={"purpose": "assistants"}).json()
    r = client.post("/v1/batches", json={
        "endpoint": "/v1/chat/completions", "input_file_id": f["id"]})
    assert r.status_code == 400
    assert "purpose" in r.json()["error"]["message"]
    assert client.get("/v1/batches/batch_999").status_code == 404
    # list limit must be a positive integer (limit=-1 would silently
    # drop the newest job)
    assert client.get("/v1/batches",
                      params={"limit": "-1"}).status_code == 400
    assert client.get("/v1/batches",
                      params={"limit": "x"}).status_code == 400


def test_batches_ui_page_served(client):
    r = client.get("/batches", headers={"Accept": "text/html"})
    assert r.status_code == 200
    assert "Batch jobs" in r.text


def test_fleet_register_endpoint_guards(server, client):
    """POST /federated/register on the serving instance (fleet-tier
    registry join): unroutable-by-construction addresses are 400, the
    peer_token guard answers 401, and with no fleet-served model loaded
    a well-formed join is a clean 409 — never a silent no-op."""
    # constructionally unroutable: rejected before any model is consulted
    for bad in ("127.0.0.1:0", ":8080", "0.0.0.0:1234", "host:nope"):
        r = client.post("/federated/register", json={"address": bad})
        assert r.status_code == 400, (bad, r.status_code)
    assert client.post("/federated/register",
                       json={}).status_code == 400
    r = client.post("/federated/register",
                    json={"address": "127.0.0.1:19999",
                          "role": "supervisor"})
    assert r.status_code == 400  # unknown role
    # no fleet-served model in this (single-engine) server
    r = client.post("/federated/register",
                    json={"address": "127.0.0.1:19999"})
    assert r.status_code == 409
    # the shared peer_token guards the join exactly like the router's
    # registry guards registration
    server.state.config.peer_token = "sekrit"
    try:
        r = client.post("/federated/register",
                        json={"address": "127.0.0.1:19999"})
        assert r.status_code == 401
        r = client.post("/federated/register",
                        json={"address": "127.0.0.1:19999"},
                        headers={"Authorization": "Bearer sekrit"})
        assert r.status_code == 409  # authorized, still no fleet model
    finally:
        server.state.config.peer_token = ""


def test_fleet_swap_endpoint_guards(server, client):
    """POST /v1/fleet/{model}/swap guard matrix: peer_token answers 401,
    malformed bodies are 400, an unknown model is 404, and a loaded but
    single-engine (non-fleet) model is a clean 409 — the deploy
    primitive never silently no-ops."""
    # malformed bodies are rejected before any model is consulted
    r = client.post("/v1/fleet/tiny/swap", content=b"{not json",
                    headers={"Content-Type": "application/json"})
    assert r.status_code == 400
    assert client.post("/v1/fleet/tiny/swap",
                       json=["checkpoint"]).status_code == 400
    assert client.post("/v1/fleet/tiny/swap",
                       json={"checkpoint": 7}).status_code == 400
    # unknown model
    assert client.post("/v1/fleet/nope/swap",
                       json={}).status_code == 404
    # loaded single-engine model has no fleet to swap
    server.state.manager.get("tiny")
    r = client.post("/v1/fleet/tiny/swap", json={})
    assert r.status_code == 409
    assert "not fleet-served" in r.json()["error"]
    # the shared peer_token guards the swap like every capacity mutation
    server.state.config.peer_token = "sekrit"
    try:
        assert client.post("/v1/fleet/tiny/swap",
                           json={}).status_code == 401
        r = client.post("/v1/fleet/tiny/swap", json={},
                        headers={"Authorization": "Bearer sekrit"})
        assert r.status_code == 409  # authorized, still not fleet-served
    finally:
        server.state.config.peer_token = ""


def test_embeddings_and_rerank_shed_under_overload(client):
    """Satellite: the SLO admission hook covers embeddings and rerank too,
    with the same preserved Retry-After header."""
    from localai_tpu.obs import slo as obs_slo

    SLO = obs_slo.SLO
    saved = dict(targets=dict(SLO.targets),
                 burn_threshold=SLO.burn_threshold,
                 recover_burn=SLO.recover_burn, min_events=SLO.min_events)
    SLO.reset()
    SLO.configure(targets={"ttft_ms": 1e-6}, burn_threshold=1.0,
                  recover_burn=1.0, min_events=2)
    try:
        for i in range(2):  # violate the impossible target → both windows
            assert client.post("/v1/chat/completions", json={
                "model": "tiny",
                "messages": [{"role": "user", "content": f"burn {i}"}],
                "max_tokens": 2,
            }).status_code == 200
        r = client.post("/v1/embeddings", json={
            "model": "tiny", "input": "refuse me"})
        assert r.status_code == 429
        assert r.headers.get("Retry-After") == str(SLO.retry_after_s)
        r = client.post("/v1/rerank", json={
            "model": "tiny", "query": "q", "documents": ["a", "b"]})
        assert r.status_code == 429
        assert r.headers.get("Retry-After") == str(SLO.retry_after_s)
        # recovery readmits both endpoints
        SLO.configure(targets={})
        assert client.post("/v1/embeddings", json={
            "model": "tiny", "input": "ok now"}).status_code == 200
    finally:
        SLO.configure(**saved)
        SLO.reset()


# -- usage accounting plane (/v1/usage, /debug/history, /usage UI) -----------
# The LEDGER/HISTORY singletons are process-global and fed by every test
# in this run, so these assert presence and shape, never exact counts.


def test_v1_usage_reports_anonymous_pane(client):
    """Auth-off traffic lands in the ``anonymous`` tenant bucket with the
    full cost pane (delivered tokens, dispatch ms, queue wait, KV-block-
    seconds) plus the goodput/waste decomposition."""
    r = client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "bill me"}],
        "max_tokens": 4,
    })
    assert r.status_code == 200
    d = client.get("/v1/usage").json()
    assert d["object"] == "usage"
    for key in ("data", "waste", "goodput", "tenant_lru"):
        assert key in d, key
    panes = [p for p in d["data"]
             if p["tenant"] == "anonymous" and p["model"] == "tiny"]
    assert panes, d["data"]
    pane = panes[0]
    assert pane["lane"] == "interactive"
    assert pane["requests"] >= 1
    assert pane["delivered_tokens"] >= 1
    for key in ("prompt_tokens", "dispatch_ms", "queue_wait_ms",
                "kv_block_seconds", "waste_tokens", "waste_requests"):
        assert key in pane, key
    g = d["goodput"]
    assert 0.0 <= g["goodput_ratio"] <= 1.0
    assert g["delivered_tokens"] >= pane["delivered_tokens"]
    lru = d["tenant_lru"]
    assert lru["max_tenants"] >= lru["tenants"] >= 1


def test_v1_usage_windowed_and_bad_params(client):
    d = client.get("/v1/usage", params={"window": 3600}).json()
    assert d["object"] == "usage"
    # the windowed answer says how far back its event ring reaches
    assert "coverage_start" in d and "events" in d
    assert d["start_time"] is not None
    for bad in ({"since": "soon"}, {"window": "wat"}):
        assert client.get("/v1/usage", params=bad).status_code == 400


def test_authenticated_tenant_is_hashed_never_raw(client, server):
    """With API keys on, the auth middleware stamps derive_tenant(key) —
    the raw key must never appear in /v1/usage or the exposition."""
    from localai_tpu.obs.ledger import derive_tenant

    key = "sk-usage-raw-key-material"
    server.state.config.api_keys = [key]
    hdr = {"Authorization": f"Bearer {key}"}
    try:
        r = client.post("/v1/chat/completions", json={
            "model": "tiny",
            "messages": [{"role": "user", "content": "tenant bill"}],
            "max_tokens": 4,
        }, headers=hdr)
        assert r.status_code == 200
        # the key gates /v1/usage too
        assert client.get("/v1/usage").status_code == 401
        d = client.get("/v1/usage", headers=hdr).json()
        metrics = client.get("/metrics", headers=hdr).text
    finally:
        server.state.config.api_keys = []
    bucket = derive_tenant(key)
    assert bucket.startswith("t-") and key not in bucket
    panes = [p for p in d["data"] if p["tenant"] == bucket]
    assert panes and panes[0]["requests"] >= 1
    assert key not in json.dumps(d)
    assert key not in metrics
    assert (f'localai_tenant_tokens_total{{lane="interactive",'
            f'model="tiny",tenant="{bucket}"}}') in metrics


def test_metrics_exports_tenant_and_goodput_series(client):
    client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "export me"}],
        "max_tokens": 4,
    })
    body = client.get("/metrics").text
    assert ('localai_tenant_requests_total{lane="interactive",'
            'model="tiny",tenant="anonymous"}') in body
    assert 'localai_goodput_tokens_total{model="tiny"}' in body
    assert 'localai_goodput_ratio{model="tiny"}' in body
    assert "# TYPE localai_waste_tokens_total counter" in body
    assert "# TYPE localai_tenant_lru_evictions_total counter" in body


def test_debug_history_index_and_series(client):
    """Every /metrics scrape doubles as a history sampling tick — after
    one, the ring geometry and the curated engine/ledger series must be
    queryable at every resolution."""
    client.post("/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "history"}],
        "max_tokens": 4,
    })
    client.get("/metrics")                       # the sampling tick
    idx = client.get("/debug/history").json()
    assert idx["resolutions_s"] == [1, 10, 300]
    assert idx["capacity"] == {"1": 600, "10": 720, "300": 576}
    assert "tokens_generated.tiny" in idx["series"]
    assert "tenant_tokens.anonymous" in idx["series"]
    q = client.get("/debug/history/tokens_generated.tiny",
                   params={"res": 1}).json()
    assert q["kind"] == "counter"
    assert q["resolution_s"] == 1 and q["capacity"] == 600
    assert q["points"] and q["points"][-1]["value"] >= 1
    # res snaps to the nearest ring rather than erroring
    snapped = client.get("/debug/history/tokens_generated.tiny",
                         params={"res": 7}).json()
    assert snapped["resolution_s"] == 10
    assert client.get("/debug/history/no-such-series").status_code == 404
    assert client.get("/debug/history/tokens_generated.tiny",
                      params={"res": "x"}).status_code == 400
    assert client.get("/debug/history/tokens_generated.tiny",
                      params={"since": "x"}).status_code == 400


def test_usage_ui_page_served(client):
    r = client.get("/usage", headers={"Accept": "text/html"})
    assert r.status_code == 200
    assert "Usage" in r.text
    assert "Waste decomposition" in r.text
