"""Pallas-path guarantees (VERDICT r4 #9): for the hardware shapes that
matter, the engine's attention-impl decision must land on the flash
kernels — a silent Pallas→XLA fallback regression fails HERE instead of
surfacing as a bench slowdown. The decision is a pure function
(ops.select_attn_impl) evaluated as-if on TPU (backend='tpu'), so these
assertions hold on CPU CI."""

import pytest

from localai_tpu.ops import select_attn_impl

# Llama-3-8B: 32 q heads / 8 kv heads / head_dim 128 — the north-star
# serving config (BENCH, debug:llama3-8b)
L8B = dict(num_heads=32, num_kv_heads=8, head_dim=128)


@pytest.mark.parametrize("tp", [1, 4, 8])
@pytest.mark.parametrize("ctx", [1024, 8192])
def test_llama8b_lands_on_pallas_on_tpu(tp, ctx):
    impl, interpret, why = select_attn_impl(
        "auto", **L8B, max_ctx=ctx, tp=tp, backend="tpu")
    assert impl == "pallas" and not interpret, why
    assert why == ""


def test_llama1b_hd64_falls_back_with_reason():
    """debug:1b has head_dim 64 — documented XLA fallback, with a reason."""
    impl, _, why = select_attn_impl(
        "auto", num_heads=32, num_kv_heads=8, head_dim=64,
        max_ctx=1024, backend="tpu")
    assert impl == "xla" and "128-aligned" in why


def test_unaligned_ctx_falls_back():
    impl, _, why = select_attn_impl(
        "auto", **L8B, max_ctx=1000, backend="tpu")
    assert impl == "xla" and "128-aligned" in why


def test_indivisible_heads_fall_back_under_tp():
    impl, _, why = select_attn_impl(
        "auto", num_heads=32, num_kv_heads=8, head_dim=128,
        max_ctx=1024, tp=3, backend="tpu")
    assert impl == "xla" and "divisible" in why


def test_cpu_auto_is_xla_but_interpret_available():
    impl, interpret, _ = select_attn_impl(
        "auto", **L8B, max_ctx=1024, backend="cpu")
    assert impl == "xla"
    impl, interpret, _ = select_attn_impl(
        "pallas_interpret", **L8B, max_ctx=1024, backend="cpu")
    assert impl == "pallas" and interpret


def test_runner_exposes_decision(tiny_runner=None):
    """The runner's attn_impl reflects select_attn_impl verbatim."""
    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import resolve_model

    tiny = resolve_model("debug:tiny", dtype="float32")
    r = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                    prefill_buckets=[64], attn_impl="pallas_interpret")
    assert r.attn_impl == "pallas" and r._attn_interpret
    r2 = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=128,
                     prefill_buckets=[64], attn_impl="xla")
    assert r2.attn_impl == "xla"
