"""tools.jaxlint: every rule gets a must-flag fixture, a near-miss that
must stay silent, plus suppression and baseline round-trips and the CLI
self-check this repo's CI runs."""

import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.jaxlint import Baseline, lint_paths  # noqa: E402

ENGINE_MOD = "localai_tpu/engine/mod.py"


def lint_snippet(tmp_path, code, relpath=ENGINE_MOD):
    f = tmp_path / relpath
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(code))
    return lint_paths([str(tmp_path)])


def rules_of(findings):
    return [f.rule for f in findings]


# -- host-sync-in-hot-path -------------------------------------------------

HOT_SYNC = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def decode_step(state, xs):
        for x in xs:
            v = state.tokens.item()
            w = int(jnp.sum(x))
            h = np.asarray(x)
            g = jax.device_get(x)
        return v, w, h, g
"""


def test_host_sync_flags_in_hot_loop(tmp_path):
    found = lint_snippet(tmp_path, HOT_SYNC)
    assert rules_of(found) == ["host-sync-in-hot-path"] * 4


def test_host_sync_ignores_cold_files(tmp_path):
    # byte-identical code outside engine//worker-serving: silent
    found = lint_snippet(tmp_path, HOT_SYNC, "localai_tpu/api/mod.py")
    assert found == []


def test_host_sync_near_misses(tmp_path):
    found = lint_snippet(tmp_path, """
        import jax.numpy as jnp
        import numpy as np

        def decode_step(prompt, x):
            n = int(len(prompt))       # len() is host-side already
            m = int("42")              # literal
            d = jnp.asarray(x)         # device put, not a sync
            return n, m, d

        def admit(prompt):
            return np.asarray(prompt)  # not a hot function, not a loop
    """)
    assert found == []


def test_host_sync_on_serving_state_anywhere_in_file(tmp_path):
    # direct materialization of self.state/self.kv flags even outside
    # loops/step functions — these arrays are donated and in flight
    found = lint_snippet(tmp_path, """
        import numpy as np

        class Runner:
            def frontier(self, slot):
                return int(self.state.positions[slot])

            def cache_rows(self):
                return np.asarray(self.kv.k)
    """)
    assert rules_of(found) == ["host-sync-in-hot-path"] * 2


# -- jit-in-loop -----------------------------------------------------------

def test_jit_in_loop_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        import jax

        def serve(xs, fn):
            out = []
            for x in xs:
                f = jax.jit(fn)          # fresh cache per iteration
                out.append(f(x))
            return out

        def once(f, x):
            return jax.jit(f)(x)         # immediately invoked
    """)
    assert rules_of(found) == ["jit-in-loop"] * 2


def test_jit_at_init_is_fine(tmp_path):
    found = lint_snippet(tmp_path, """
        import jax
        from functools import partial

        class Runner:
            def __init__(self, fn):
                self._decode = jax.jit(fn, donate_argnums=(1, 2))

        @partial(jax.jit, static_argnames=("n",))
        def step_n(x, n):
            return x * n
    """)
    assert found == []


# -- tracer-control-flow ---------------------------------------------------

def test_tracer_control_flow_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def f(x, y):
            if x > 0:
                return y
            while y.any():
                y = y - 1
            return x
    """)
    assert rules_of(found) == ["tracer-control-flow"] * 2


def test_tracer_control_flow_near_misses(tmp_path):
    found = lint_snippet(tmp_path, """
        import jax
        from functools import partial

        @jax.jit
        def f(x, flag=None):
            if x.ndim == 2:          # static under trace
                x = x[None]
            if flag is None:         # identity test is static
                return x
            if isinstance(x, tuple): # type test is static
                return x[0]
            return x

        @partial(jax.jit, static_argnames=("k",))
        def g(x, k):
            if k > 3:                # static arg
                return x
            return -x

        def not_jitted(x):
            if x > 0:                # no @jit: plain Python is fine
                return x
            return -x
    """)
    assert found == []


# -- rng-key-reuse ---------------------------------------------------------

def test_rng_key_reuse_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        import jax

        def bad(key):
            a = jax.random.normal(key)
            b = jax.random.uniform(key)
            return a + b

        def bad_loop(key):
            out = 0.0
            for _ in range(4):
                out = out + jax.random.normal(key)
            return out

        def bad_after_split(key):
            k1, k2 = jax.random.split(key)
            return jax.random.normal(key)
    """)
    assert rules_of(found) == ["rng-key-reuse"] * 3


def test_rng_key_split_patterns_are_fine(tmp_path):
    found = lint_snippet(tmp_path, """
        import jax

        def ok(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1)
            b = jax.random.uniform(k2)
            return a + b

        def ok_carry(key):
            total = 0.0
            for _ in range(4):
                key, sub = jax.random.split(key)
                total = total + jax.random.normal(sub)
            return total

        def ok_vmap(keys):
            return jax.vmap(lambda kk: jax.random.split(kk, 2))(keys)
    """)
    assert found == []


# -- unknown-jax-config ----------------------------------------------------

def test_unknown_jax_config_flags_bogus_options(tmp_path):
    # an option no JAX release has; a valid option must stay silent
    found = lint_snippet(tmp_path, """
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_definitely_not_an_option", 8)
    """, "tests/conftest.py")
    assert rules_of(found) == ["unknown-jax-config"]
    assert "jax_definitely_not_an_option" in found[0].message


def test_unknown_jax_config_tracks_the_installed_jax(tmp_path):
    # the exact line that once made the whole suite die at conftest
    # import: flagged exactly when the RUNNING JAX rejects it (that is
    # the rule's contract — newer JAX accepts the option, so the same
    # line is then legitimately silent)
    import jax

    found = lint_snippet(tmp_path, """
        import jax

        jax.config.update("jax_num_cpu_devices", 8)
    """, "tests/conftest.py")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        assert found == []
    else:
        assert rules_of(found) == ["unknown-jax-config"]
        assert "jax_num_cpu_devices" in found[0].message


def test_unknown_jax_config_capability_guard_is_fine(tmp_path):
    found = lint_snippet(tmp_path, """
        import jax

        if hasattr(jax.config, "jax_num_cpu_devices"):
            jax.config.update("jax_num_cpu_devices", 8)

        if not hasattr(jax.config, "jax_num_cpu_devices"):
            pass
        else:
            jax.config.update("jax_num_cpu_devices", 8)
    """, "tests/conftest.py")
    assert found == []


def test_unknown_jax_config_wrong_branch_still_flags(tmp_path):
    # the update sits exactly where the capability probe FAILED
    found = lint_snippet(tmp_path, """
        import jax

        if hasattr(jax.config, "jax_definitely_not_an_option"):
            pass
        else:
            jax.config.update("jax_definitely_not_an_option", 8)
    """, "tests/conftest.py")
    assert rules_of(found) == ["unknown-jax-config"]


# -- lockcheck: lock-guarded-attr ------------------------------------------

LOCKED_COUNTER = """
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self.respawns = 0

        def bump(self):
            with self._lock:
                self.respawns += 1

        def snapshot(self):
            return {"respawns": self.respawns}
"""


def test_lockcheck_flags_unlocked_read_of_guarded_attr(tmp_path):
    found = lint_snippet(tmp_path, LOCKED_COUNTER, "localai_tpu/mod.py")
    assert rules_of(found) == ["lock-guarded-attr"]
    assert "respawns" in found[0].message


def test_lockcheck_flags_unlocked_write(tmp_path):
    found = lint_snippet(tmp_path, """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def locked_bump(self):
                with self._lock:
                    self.n += 1

            def racy_bump(self):
                self.n += 1
    """, "localai_tpu/mod.py")
    assert rules_of(found) == ["lock-guarded-attr"]
    assert "write to 'n'" in found[0].message


def test_lockcheck_near_misses_stay_silent(tmp_path):
    # consistent locking, init-time writes, unguarded attrs, and
    # sync-primitive attrs (Event/Queue) are all fine
    found = lint_snippet(tmp_path, """
        import queue
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._wake = threading.Event()
                self._q = queue.Queue()
                self.n = 0
                self.config = "x"     # never written under the lock

            def bump(self):
                with self._lock:
                    self.n += 1

            def read(self):
                with self._lock:
                    return self.n

            def poke(self):
                self._wake.set()      # Event is its own synchronization
                self._q.put(1)
                return self.config
    """, "localai_tpu/mod.py")
    assert found == []


def test_lockcheck_nested_def_runs_lock_free(tmp_path):
    # a closure defined inside a locked region runs LATER (thread
    # target): its lock-free access must still be flagged
    found = lint_snippet(tmp_path, """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def spawn(self):
                with self._lock:
                    self.n += 1

                    def worker():
                        self.n += 1
                    threading.Thread(target=worker).start()
    """, "localai_tpu/mod.py")
    assert rules_of(found) == ["lock-guarded-attr"]


def test_lockcheck_guarded_by_annotation(tmp_path):
    # a def-line guarded-by(<lock>) asserts "callers hold the lock";
    # an attribute-init annotation declares the guard explicitly
    found = lint_snippet(tmp_path, """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0  # jaxlint: guarded-by(_lock)

            def _bump_locked(self):  # jaxlint: guarded-by(_lock)
                self.n += 1

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def racy(self):
                return self.n
    """, "localai_tpu/mod.py")
    assert rules_of(found) == ["lock-guarded-attr"]
    assert found[0].text == "return self.n"


def test_lockcheck_method_scoped_waiver(tmp_path):
    # a disable on the def line waives the whole method (the documented
    # idiom for single-owner-thread structures)
    found = lint_snippet(tmp_path, """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            # engine-thread-only mirror read
            def snapshot(self):  # jaxlint: disable=lock-guarded-attr
                return {"n": self.n, "m": self.n + 1}
    """, "localai_tpu/mod.py")
    assert found == []


# -- lockcheck: blocking-under-lock ----------------------------------------

def test_blocking_under_lock_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        import threading
        import time

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self.replicas = []

            def sweep(self, replica):
                with self._lock:
                    time.sleep(0.1)
                    m = replica.metrics()
                    r = self._stub.Predict(m)
                return m, r
    """, "localai_tpu/mod.py")
    assert rules_of(found) == ["blocking-under-lock"] * 3


def test_blocking_outside_lock_is_fine(tmp_path):
    found = lint_snippet(tmp_path, """
        import threading
        import time

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self.last = 0

            def sweep(self, replica):
                m = replica.metrics()   # RPC outside the critical section
                time.sleep(0.1)
                with self._lock:
                    self.last = m
                return self.metrics()   # a method on self is local
    """, "localai_tpu/mod.py")
    assert found == []


# -- shardcheck ------------------------------------------------------------

MESH_FIXTURE = """
    AXES = ("data", "model")
"""


def write_mesh(tmp_path, axes_src=MESH_FIXTURE):
    f = tmp_path / "localai_tpu" / "parallel" / "mesh.py"
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(axes_src))


def test_shardcheck_flags_unknown_axis(tmp_path):
    write_mesh(tmp_path)
    found = lint_snippet(tmp_path, """
        from jax.sharding import PartitionSpec as P

        GOOD = P("data", None, "model")
        BAD = P("modle")
        TUPLED = P(("data", "modell"))
    """, "localai_tpu/parallel/sharding.py")
    assert rules_of(found) == ["unknown-mesh-axis"] * 2
    assert "modle" in found[0].message


def test_shardcheck_validates_named_helper(tmp_path):
    write_mesh(tmp_path)
    found = lint_snippet(tmp_path, """
        from localai_tpu.parallel.mesh import named

        def shard(mesh, x):
            return named(mesh, "data", "sequence")
    """, "localai_tpu/engine/mod.py")
    assert rules_of(found) == ["unknown-mesh-axis"]
    assert "sequence" in found[0].message


def test_shard_map_arity_mismatch(tmp_path):
    write_mesh(tmp_path)
    found = lint_snippet(tmp_path, """
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def f(a, b):
            return a + b

        def build(mesh):
            ok = shard_map(f, mesh=mesh, in_specs=(P("data"), P()),
                           out_specs=P("data"))
            bad = shard_map(f, mesh=mesh, in_specs=(P("data"),),
                            out_specs=P("data"))
            return ok, bad
    """, "localai_tpu/engine/mod.py")
    assert rules_of(found) == ["shard-map-arity"]
    assert "2 positional" in found[0].message and "1 spec" in found[0].message


def test_host_sync_on_sharded_value(tmp_path):
    write_mesh(tmp_path)
    found = lint_snippet(tmp_path, """
        import numpy as np
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def run(mesh, f, x):
            out = shard_map(f, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data"))(x)
            host = np.asarray(out)
            frontier = out.item()
            return host, float(out), frontier
    """, "localai_tpu/parallel/mod.py")
    assert rules_of(found) == ["host-sync-on-sharded"] * 3


def test_host_sync_on_sharded_silent_in_tests_and_on_host_values(tmp_path):
    write_mesh(tmp_path)
    code = """
        import numpy as np
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def run(mesh, f, x, y):
            out = shard_map(f, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data"))(x)
            fine = np.asarray(y)       # y never held a sharded value
            return out, fine
    """
    assert lint_snippet(tmp_path, code, "localai_tpu/parallel/mod.py") == []
    # the same gather in a test file is parity-checking, not a hot path
    gather = code.replace("fine = np.asarray(y)", "fine = np.asarray(out)")
    assert lint_snippet(tmp_path, gather, "tests/test_mod.py") == []


# -- metriccheck -----------------------------------------------------------

METRICS_FIXTURE = """
    class Registry:
        def __init__(self):
            self.ttft = Histogram("localai_ttft_seconds", "ttft")
            self.requests = Counter("localai_requests_total", "requests")
            self.depth = Gauge("localai_queue_depth", "depth")
"""


def metric_tree(tmp_path, test_code, readme="`localai_queue_depth`\\n"):
    (tmp_path / "localai_tpu" / "obs").mkdir(parents=True, exist_ok=True)
    (tmp_path / "localai_tpu" / "obs" / "metrics.py").write_text(
        textwrap.dedent(METRICS_FIXTURE))
    (tmp_path / "README.md").write_text(readme)
    return lint_snippet(tmp_path, test_code, "tests/test_mod.py")


def test_metriccheck_flags_dead_reference(tmp_path):
    found = metric_tree(tmp_path, """
        def test_exposition(body):
            assert 'localai_requests_total{model="m"}' in body
            assert 'localai_ttft_seconds_count{model="m"}' in body
            assert 'TYPO' in body   # typo'd series
    """.replace("TYPO", "local" + "ai_reqests_total"))
    assert rules_of(found) == ["metric-name-drift"]
    assert "ai_reqests_total" in found[0].message


def test_metriccheck_flags_unreferenced_registry_series(tmp_path):
    # localai_queue_depth is only in the README — referenced; the other
    # two are asserted by the test; drop one assertion and it flags
    found = metric_tree(tmp_path, """
        def test_exposition(body):
            assert 'localai_requests_total' in body
    """)
    assert rules_of(found) == ["metric-name-drift"]
    assert "localai_ttft_seconds" in found[0].message
    assert found[0].file.endswith("obs/metrics.py")


def test_metriccheck_readme_counts_and_prefixes_resolve(tmp_path):
    # histogram suffixes and trailing-underscore/star prefixes resolve
    found = metric_tree(tmp_path, """
        def test_exposition(body):
            assert 'localai_ttft_seconds_bucket' in body
            assert 'localai_requests_total' in body
    """, readme="`localai_queue_*` gauges\\n")
    assert found == []


# -- suppressions ----------------------------------------------------------

def test_inline_suppression(tmp_path):
    found = lint_snippet(tmp_path, """
        import numpy as np

        def decode_step(tokens):
            a = np.asarray(tokens)  # jaxlint: disable=host-sync-in-hot-path
            b = np.asarray(tokens)  # jaxlint: disable=all
            c = np.asarray(tokens)  # jaxlint: disable=jit-in-loop
            return a, b, c
    """)
    # wrong rule id on line c does not suppress
    assert len(found) == 1
    assert found[0].line == 7


# -- unknown-suppression ---------------------------------------------------

def waiver(rule_id):
    """A disable comment assembled at runtime: the repo's own self-check
    scans THIS file's raw source, so a bogus rule id must never appear
    as a literal waiver here (the metriccheck TYPO precedent)."""
    return "# jax" + "lint: disable=" + rule_id


def test_unknown_suppression_flags_typos(tmp_path):
    found = lint_snippet(tmp_path, """
        import numpy as np

        def decode_step(tokens):
            return np.asarray(tokens)  WAIVER
    """.replace("WAIVER", waiver("host-sync-in-hot-pth")))
    rules = rules_of(found)
    # the typo'd waiver is flagged AND suppresses nothing: the finding
    # it meant to silence still fires
    assert sorted(rules) == ["host-sync-in-hot-path", "unknown-suppression"]
    msg = next(f for f in found if f.rule == "unknown-suppression").message
    assert "host-sync-in-hot-pth" in msg
    assert "did you mean 'host-sync-in-hot-path'" in msg


def test_unknown_suppression_checks_every_id_in_a_list(tmp_path):
    found = lint_snippet(tmp_path, """
        import numpy as np

        def decode_step(tokens):
            return np.asarray(tokens)  WAIVER
    """.replace("WAIVER",
                waiver("host-sync-in-hot-path,jit-in-looop")))
    assert rules_of(found) == ["unknown-suppression"]
    assert "jit-in-looop" in found[0].message


def test_valid_waivers_and_all_stay_silent(tmp_path):
    found = lint_snippet(tmp_path, """
        import numpy as np

        def decode_step(tokens):
            a = np.asarray(tokens)  # jaxlint: disable=host-sync-in-hot-path
            b = np.asarray(tokens)  # jaxlint: disable=all
            return a, b
    """)
    assert found == []


# -- baseline --------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    found = lint_snippet(tmp_path, HOT_SYNC)
    assert len(found) == 4
    baseline = Baseline.from_findings(found)

    # unchanged findings are fully absorbed
    new, stale = baseline.filter(found)
    assert new == [] and stale == []

    # a NEW violation surfaces even with the baseline in place; shifted
    # line numbers alone don't (keys are file/rule/text, not line)
    f = tmp_path / ENGINE_MOD
    f.write_text("import jax\n\n\n" + f.read_text().replace(
        "return v, w, h, g",
        "return v, w, h, g, state.active.item()",
    ))
    found2 = lint_paths([str(tmp_path)])
    new, stale = baseline.filter(found2)
    assert [n.text for n in new] == ["return v, w, h, g, state.active.item()"]

    # fixing a finding leaves a stale entry (reported, not fatal)
    f.write_text("import jax\n")
    new, stale = baseline.filter(lint_paths([str(tmp_path)]))
    assert new == [] and len(stale) == 4


def test_baseline_file_round_trip(tmp_path):
    found = lint_snippet(tmp_path, HOT_SYNC)
    path = tmp_path / "baseline.json"
    Baseline.from_findings(found).write(path)
    loaded = Baseline.load(path)
    new, stale = loaded.filter(found)
    assert new == [] and stale == []


def test_lint_paths_with_dotdot_and_absolute_paths(tmp_path):
    lint_snippet(tmp_path, HOT_SYNC)
    # '..' in the target must not trip the hidden-dir filter into
    # silently scanning zero files
    dotted = tmp_path / "sub" / ".." / "localai_tpu"
    (tmp_path / "sub").mkdir()
    assert len(lint_paths([str(dotted)])) == 4
    assert len(lint_paths([str(tmp_path / "localai_tpu")])) == 4


def test_lint_file_skips_project_rules(tmp_path):
    # lint_file runs per-module rules only: ProjectRules (metriccheck)
    # need the whole scanned set, which a single-file call can't supply —
    # it must skip them, not AttributeError on the missing check()
    from tools.jaxlint.core import lint_file
    from tools.jaxlint.rules import ALL_RULES

    f = tmp_path / "mod.py"
    f.write_text("x = 1\n")
    assert lint_file(f, ALL_RULES) == []


def test_finding_paths_are_cwd_relative(tmp_path, monkeypatch):
    # absolute CLI paths must produce the same baseline keys as
    # relative ones, or baselined findings resurface as new
    lint_snippet(tmp_path, HOT_SYNC)
    monkeypatch.chdir(tmp_path)
    rel = lint_paths(["localai_tpu"])
    ab = lint_paths([str(tmp_path / "localai_tpu")])
    assert [f.file for f in ab] == [f.file for f in rel]
    assert all(f.file.startswith("localai_tpu/") for f in ab)
    new, stale = Baseline.from_findings(rel).filter(ab)
    assert new == [] and stale == []


# -- CLI / self-check ------------------------------------------------------

def run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "tools.jaxlint", *args],
        cwd=cwd, capture_output=True, text=True,
        env={"PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin",
             "JAX_PLATFORMS": "cpu"},
    )


def test_cli_self_check_is_clean():
    """The CI gate: the repo lints clean against its own baseline."""
    res = run_cli(["localai_tpu", "tests"], cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_fails_on_regression(tmp_path):
    bad = tmp_path / "localai_tpu" / "engine" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\n"
        'jax.config.update("jax_definitely_not_an_option", 8)\n'
    )
    res = run_cli(["--no-baseline", "localai_tpu"], cwd=tmp_path)
    assert res.returncode == 1
    assert "unknown-jax-config" in res.stdout

    # --write-baseline accepts it; the next run is green
    res = run_cli(["--write-baseline", "localai_tpu"], cwd=tmp_path)
    assert res.returncode == 0
    res = run_cli(["localai_tpu"], cwd=tmp_path)
    assert res.returncode == 0, res.stdout + res.stderr


def test_parse_errors_cannot_be_baselined(tmp_path):
    bad = tmp_path / "localai_tpu" / "engine" / "broken.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("def oops(:\n")
    found = lint_paths([str(tmp_path)])
    assert rules_of(found) == ["parse-error"]

    # from_findings drops it; filter never absorbs it
    new, _ = Baseline.from_findings(found).filter(found)
    assert rules_of(new) == ["parse-error"]

    # --write-baseline refuses to launder it: still exits 1, and the
    # next plain run still fails
    res = run_cli(["--write-baseline", "localai_tpu"], cwd=tmp_path)
    assert res.returncode == 1
    res = run_cli(["--baseline", "tools/jaxlint/baseline.json",
                   "localai_tpu"], cwd=tmp_path)
    assert res.returncode == 1
    assert "parse-error" in res.stdout


def test_cli_list_rules():
    res = run_cli(["--list-rules"], cwd=REPO)
    assert res.returncode == 0
    for rule in ("host-sync-in-hot-path", "jit-in-loop",
                 "tracer-control-flow", "rng-key-reuse",
                 "unknown-jax-config", "lock-guarded-attr",
                 "blocking-under-lock", "unknown-mesh-axis",
                 "shard-map-arity", "host-sync-on-sharded",
                 "metric-name-drift", "unknown-suppression",
                 "blocking-in-async", "blocking-in-stream",
                 "async-lock-blocking-await", "coroutine-not-awaited"):
        assert rule in res.stdout


def test_cli_prune_baseline_round_trip(tmp_path):
    bad = tmp_path / "localai_tpu" / "engine" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\n"
        'jax.config.update("jax_definitely_not_an_option", 8)\n'
        'jax.config.update("jax_also_not_an_option", 9)\n'
    )
    res = run_cli(["--write-baseline", "localai_tpu"], cwd=tmp_path)
    assert res.returncode == 0

    # fix ONE finding: its baseline entry goes stale — reported (not
    # fatal) with the prune hint
    bad.write_text(
        "import jax\n"
        'jax.config.update("jax_definitely_not_an_option", 8)\n'
    )
    res = run_cli(["localai_tpu"], cwd=tmp_path)
    assert res.returncode == 0
    assert "stale baseline entr" in res.stderr
    assert "--prune-baseline" in res.stderr

    res = run_cli(["--prune-baseline", "localai_tpu"], cwd=tmp_path)
    assert res.returncode == 0
    assert "pruned 1 stale entry" in res.stdout

    # pruned: no stale note, the surviving finding is still absorbed
    res = run_cli(["localai_tpu"], cwd=tmp_path)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "stale" not in res.stderr
    assert "(1 baselined)" in res.stderr

    # pruning never ADDS entries: a fresh regression still fails
    bad.write_text(bad.read_text()
                   + 'jax.config.update("jax_third_bogus_option", 1)\n')
    res = run_cli(["localai_tpu"], cwd=tmp_path)
    assert res.returncode == 1


def test_cli_prune_baseline_needs_a_baseline_file(tmp_path):
    (tmp_path / "localai_tpu").mkdir()
    (tmp_path / "localai_tpu" / "mod.py").write_text("x = 1\n")
    res = run_cli(["--prune-baseline", "localai_tpu"], cwd=tmp_path)
    assert res.returncode == 1
    assert "needs a baseline file" in res.stderr


def test_lockcheck_findings_are_baselineable(tmp_path):
    # the waiver path the ISSUE prescribes: a finding accepted into the
    # baseline stays absorbed until its line changes
    found = lint_snippet(tmp_path, LOCKED_COUNTER, "localai_tpu/mod.py")
    baseline = Baseline.from_findings(found)
    new, stale = baseline.filter(
        lint_snippet(tmp_path, LOCKED_COUNTER, "localai_tpu/mod.py"))
    assert new == [] and stale == []
