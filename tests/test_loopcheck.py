"""tools.jaxlint loopcheck: the call-graph-aware event-loop rules.

Per rule: a must-flag fixture, a near-miss that stays silent, the
waiver paths (`# jaxlint: offloaded`, `# jaxlint: disable=`), and the
baseline round-trip — plus the acceptance cross-check: one injected
``time.sleep`` in an async handler caught by BOTH the static pass and
the runtime sanitizer (tools.loopsan).
"""

import asyncio
import sys
import textwrap
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.jaxlint import Baseline, lint_paths  # noqa: E402

API_MOD = "localai_tpu/api/mod.py"


def lint_snippet(tmp_path, code, relpath=API_MOD):
    f = tmp_path / relpath
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(code))
    return lint_paths([str(tmp_path)])


def rules_of(findings):
    return [f.rule for f in findings]


# -- blocking-in-async: direct sites ----------------------------------------

def test_direct_blocking_in_async_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        import time
        from PIL import Image

        async def handler(request, path):
            time.sleep(0.1)
            img = Image.open(path)
            data = path.read_bytes()
            return img, data
    """)
    assert rules_of(found) == ["blocking-in-async"] * 3
    assert "event loop" in found[0].message


def test_awaited_and_offloaded_calls_are_fine(tmp_path):
    found = lint_snippet(tmp_path, """
        import asyncio
        import time

        def _decode(data):
            time.sleep(0.1)      # sync helper: fine on its own
            return data

        async def handler(request, data):
            loop = asyncio.get_running_loop()
            out = await loop.run_in_executor(None, _decode, data)
            more = await asyncio.to_thread(_decode, data)
            return out, more
    """)
    assert found == []


def test_executor_closure_is_not_inline(tmp_path):
    # a nested def handed to run_in_executor runs OFF the loop — its
    # blocking body must not taint the enclosing async def
    found = lint_snippet(tmp_path, """
        import asyncio

        async def handler(request, path):
            loop = asyncio.get_running_loop()

            def build():
                return path.read_bytes()

            return await loop.run_in_executor(None, build)
    """)
    assert found == []


# -- blocking-in-async: transitive through project helpers ------------------

def test_transitive_blocking_through_helper_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        import time

        def _resize(img):
            return _encode(img)

        def _encode(img):
            time.sleep(0.05)
            return img

        async def handler(request, img):
            return _resize(img)
    """)
    assert rules_of(found) == ["blocking-in-async"]
    # the witness chain names every hop down to the blocking leaf
    assert "_resize" in found[0].message
    assert "_encode" in found[0].message
    assert "time.sleep" in found[0].message


def test_offloaded_def_annotation_clears_taint(tmp_path):
    found = lint_snippet(tmp_path, """
        import time

        # runs only via state.executor (see handler)
        def _encode(img):  # jaxlint: offloaded (executor-side only)
            time.sleep(0.05)
            return img

        async def handler(request, img):
            return _encode(img)
    """)
    assert found == []


def test_offloaded_statement_annotation_clears_call(tmp_path):
    found = lint_snippet(tmp_path, """
        import time

        def _encode(img):
            time.sleep(0.05)
            return img

        async def handler(request, img):
            return _encode(img)  # jaxlint: offloaded (wrapped upstream)
    """)
    assert found == []


def test_inline_disable_waives_loopcheck_finding(tmp_path):
    found = lint_snippet(tmp_path, """
        import time

        async def handler(request):
            time.sleep(0.1)  # jaxlint: disable=blocking-in-async
    """)
    assert found == []


def test_loopcheck_skips_test_files(tmp_path):
    # tests block loops on purpose (fixtures simulating slow handlers)
    found = lint_snippet(tmp_path, """
        import time

        async def handler(request):
            time.sleep(0.1)
    """, "tests/test_mod.py")
    assert found == []


# -- blocking-in-stream -----------------------------------------------------

def test_blocking_in_async_generator_flags_as_stream(tmp_path):
    found = lint_snippet(tmp_path, """
        import time

        async def stream_tokens(chunks):
            for c in chunks:
                time.sleep(0.01)
                yield c
    """)
    assert rules_of(found) == ["blocking-in-stream"]
    assert "between chunks" in found[0].message


def test_blocking_in_async_for_body_flags_as_stream(tmp_path):
    found = lint_snippet(tmp_path, """
        async def pump(source, sink):
            async for item in source:
                sink.write_bytes(item)
    """)
    assert rules_of(found) == ["blocking-in-stream"]


def test_clean_async_generator_is_fine(tmp_path):
    found = lint_snippet(tmp_path, """
        import asyncio

        async def stream_tokens(handle):
            while True:
                delta = await handle.next_delta()
                if delta is None:
                    return
                yield delta
                await asyncio.sleep(0)
    """)
    assert found == []


# -- async-lock-blocking-await ----------------------------------------------

def test_asyncio_lock_across_executor_await_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        import asyncio

        class Cache:
            def __init__(self):
                self._lock = asyncio.Lock()

            async def refresh(self, loop, fn):
                async with self._lock:
                    self.value = await loop.run_in_executor(None, fn)
    """)
    assert rules_of(found) == ["async-lock-blocking-await"]
    assert "self._lock" in found[0].message


def test_await_outside_lock_span_is_fine(tmp_path):
    found = lint_snippet(tmp_path, """
        import asyncio

        class Cache:
            def __init__(self):
                self._lock = asyncio.Lock()

            async def refresh(self, loop, fn):
                fresh = await loop.run_in_executor(None, fn)
                async with self._lock:
                    self.value = fresh
    """)
    assert found == []


def test_lock_across_slow_async_callee_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        import asyncio

        class Cache:
            def __init__(self):
                self._lock = asyncio.Lock()

            async def _rebuild(self, loop, fn):
                return await loop.run_in_executor(None, fn)

            async def refresh(self, loop, fn):
                async with self._lock:
                    self.value = await self._rebuild(loop, fn)
    """)
    assert rules_of(found) == ["async-lock-blocking-await"]
    assert "_rebuild" in found[0].message


def test_lock_across_fast_await_is_fine(tmp_path):
    # awaiting a quick project coroutine under the lock is the normal
    # critical-section pattern, not a pinned-lock hazard
    found = lint_snippet(tmp_path, """
        import asyncio

        class Cache:
            def __init__(self):
                self._lock = asyncio.Lock()

            async def _bump(self):
                self.n = getattr(self, "n", 0) + 1
                return self.n

            async def refresh(self):
                async with self._lock:
                    return await self._bump()
    """)
    assert found == []


# -- coroutine-not-awaited --------------------------------------------------

def test_discarded_coroutine_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        async def notify(subscribers, event):
            for s in subscribers:
                await s.send(event)

        async def handler(subs, event):
            notify(subs, event)
            return True
    """)
    assert rules_of(found) == ["coroutine-not-awaited"]
    assert "never runs" in found[0].message


def test_awaited_and_task_wrapped_coroutines_are_fine(tmp_path):
    found = lint_snippet(tmp_path, """
        import asyncio

        async def notify(subscribers, event):
            for s in subscribers:
                await s.send(event)

        async def handler(subs, event):
            await notify(subs, event)
            task = asyncio.create_task(notify(subs, event))
            return task
    """)
    assert found == []


# -- upgraded blocking-under-lock: transitive through helpers ---------------

def test_blocking_under_lock_through_helper_flags(tmp_path):
    found = lint_snippet(tmp_path, """
        import threading
        import time

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def _respawn(self):
                time.sleep(0.5)

            def sweep(self):
                with self._lock:
                    self._respawn()
    """, "localai_tpu/mod.py")
    assert rules_of(found) == ["blocking-under-lock"]
    assert "_respawn" in found[0].message
    assert "time.sleep" in found[0].message


def test_lock_domain_ignores_loop_only_leaves(tmp_path):
    # file I/O is loop-fatal but fine under a startup lock: the async
    # domain tags must not leak into the lock pass
    found = lint_snippet(tmp_path, """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def _read_config(self, path):
                return path.read_text()

            def reload(self, path):
                with self._lock:
                    self.cfg = self._read_config(path)
    """, "localai_tpu/mod.py")
    assert found == []


def test_transitive_lock_finding_is_waivable(tmp_path):
    found = lint_snippet(tmp_path, """
        import threading
        import time

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def _respawn(self):
                time.sleep(0.5)

            def sweep(self):
                with self._lock:
                    # load-once barrier: callers must wait
                    self._respawn()  # jaxlint: disable=blocking-under-lock
    """, "localai_tpu/mod.py")
    assert found == []


# -- upgraded host-sync-on-sharded: transitive producers --------------------

def write_mesh(tmp_path):
    f = tmp_path / "localai_tpu" / "parallel" / "mesh.py"
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text('AXES = ("data", "model")\n')


def test_host_sync_on_sharded_via_producer_function(tmp_path):
    write_mesh(tmp_path)
    found = lint_snippet(tmp_path, """
        import numpy as np
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def make_sharded(mesh, f, x):
            out = shard_map(f, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data"))(x)
            return out

        def consume(mesh, f, x):
            y = make_sharded(mesh, f, x)
            return np.asarray(y)
    """, "localai_tpu/parallel/mod.py")
    assert rules_of(found) == ["host-sync-on-sharded"]


def test_non_sharded_producer_stays_silent(tmp_path):
    write_mesh(tmp_path)
    found = lint_snippet(tmp_path, """
        import numpy as np

        def make_host(x):
            return [v + 1 for v in x]

        def consume(x):
            y = make_host(x)
            return np.asarray(y)
    """, "localai_tpu/parallel/mod.py")
    assert found == []


# -- baseline ---------------------------------------------------------------

def test_loopcheck_findings_are_baselineable(tmp_path):
    code = """
        import time

        async def handler(request):
            time.sleep(0.1)
    """
    found = lint_snippet(tmp_path, code)
    assert rules_of(found) == ["blocking-in-async"]
    baseline = Baseline.from_findings(found)
    new, stale = baseline.filter(lint_snippet(tmp_path, code))
    assert new == [] and stale == []


# -- the acceptance cross-check ---------------------------------------------

INJECTED = """
    import time

    async def sse_handler(request):
        time.sleep(0.2)     # deliberate: both halves must catch this
        return request
"""


def test_injected_sleep_caught_by_both_halves(tmp_path):
    # static half: loopcheck flags the handler from source alone
    found = lint_snippet(tmp_path, INJECTED)
    assert rules_of(found) == ["blocking-in-async"]
    assert "time.sleep" in found[0].message

    # runtime half: the same handler shape, actually dispatched on a
    # live loop, is caught by the sanitizer with its wall time
    from tools.loopsan import LoopSanitizer

    async def sse_handler():
        time.sleep(0.2)

    san = LoopSanitizer(threshold_ms=50.0)
    with san:
        asyncio.run(sse_handler())
    stalls = san.stalls()
    assert len(stalls) == 1
    assert stalls[0].duration_ms >= 150.0
    assert "sse_handler" in stalls[0].label
