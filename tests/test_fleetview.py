"""Fleet telemetry plane (ISSUE 15): GetTelemetry harvest, skew-anchored
trace stitching, merged fleet flight view.

Unit tier: anchoring math, anchor/replica-id extraction, stitch dedup +
unreachable panes against fake payloads. Wire tier: the GetTelemetry RPC
against an in-process gRPC worker. Serving tier: a real in-process fleet
stitched end-to-end (fast), and a worker-PROCESS fleet with a
disaggregated request showing prefill+decode replicas in one waterfall
(slow)."""

import time
from types import SimpleNamespace

import pytest

from localai_tpu.obs import fleetview
from localai_tpu.obs.flight import FlightRecorder
from localai_tpu.obs.trace import RequestTrace, TraceStore

TINY = {
    "name": "fvt", "model": "debug:tiny", "context_size": 256,
    "parameters": {"temperature": 0.0, "max_tokens": 8},
    "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64, 128],
               "dtype": "float32", "kv_dtype": "float32",
               "kv_block_tokens": 16},
}

TINY_YAML = """\
name: tiny
model: "debug:tiny"
context_size: 96
engine:
  max_slots: 2
  prefill_buckets: [16]
  dtype: float32
  kv_dtype: float32
"""


def _trace_dict(trace_id="t1", request_id="req-0", model="m", start=100.0,
                spans=(), attrs=None):
    return {
        "trace_id": trace_id, "request_id": request_id, "kind": "request",
        "model": model, "name": "request", "start_unix": start,
        "duration_ms": 10.0, "finished": True, "attrs": dict(attrs or {}),
        "children": [
            {"name": n, "start_unix": s, "duration_ms": d,
             "attrs": dict(a)} for n, s, d, a in spans
        ],
    }


# ---------------------------------------------------------------------------
# skew anchoring


def test_anchor_trace_shifts_rigidly():
    # remote clock is ~49 minutes ahead; anchoring pins the root to the
    # local rpc start and shifts every child by the SAME offset
    remote = _trace_dict(start=5000.0, spans=(
        ("queued", 5000.0, 0.5, {}),
        ("decode", 5000.25, 3.0, {}),
    ))
    out = fleetview.anchor_trace(remote, 100.5, replica="m/r0")
    assert out["start_unix"] == pytest.approx(100.5)
    assert out["children"][0]["start_unix"] == pytest.approx(100.5)
    assert out["children"][1]["start_unix"] == pytest.approx(100.75)
    # durations and relative ordering untouched
    assert out["children"][1]["duration_ms"] == 3.0
    assert out["attrs"]["skew_anchored"] is True
    assert out["attrs"]["skew_offset_ms"] == pytest.approx(-4899500.0)
    assert out["attrs"]["replica"] == "m/r0"
    assert all(c["attrs"]["replica"] == "m/r0" for c in out["children"])
    # the input dict is never mutated
    assert remote["start_unix"] == 5000.0
    assert "skew_anchored" not in remote["attrs"]


def test_replica_anchors_and_ids():
    local = [_trace_dict(
        model="m", attrs={"replica": "m/r1", "prefill_replica": "m/p0"},
        spans=(
            ("route", 100.0, 0.1, {"replica": "m/r1"}),
            ("prefix_transfer", 100.2, 2.0,
             {"prefill": "m/p0", "decode": "m/r1"}),
            ("rpc", 102.5, 5.0, {"replica": "m/r1"}),
        ))]
    anchors = fleetview.replica_anchors(local)
    # first span naming the replica wins: r1 anchors at the route span,
    # p0 at the prefix_transfer span
    assert anchors == {"m/r1": 100.0, "m/p0": 100.2}
    assert fleetview.replica_ids_for_trace(local) == {"m/r1", "m/p0"}


def test_stitch_dedup_unreachable_and_tagging():
    local = [_trace_dict(
        trace_id="tx", request_id="front-0", model="m",
        attrs={"replica": "m/r0"},
        spans=(("rpc", 100.0, 5.0, {"replica": "m/r0"}),
               ("route", 99.9, 0.05, {"replica": "m/r1"})))]
    dup = _trace_dict(trace_id="tx", request_id="front-0", model="m")
    remote = _trace_dict(trace_id="tx", request_id="m/r0-0", model="m/r0",
                         start=7777.0,
                         spans=(("decode", 7777.5, 2.0, {}),))
    out = fleetview.stitch("tx", local, {
        "m/r0": {"traces": [dup, remote], "shared_store": True},
        "m/r1": {"error": "deadline", "unreachable": True},
    })
    # the duplicate (same trace id + request id as a local trace —
    # in-process replicas share the store and say so) is dropped
    assert len(out["replicas"]["m/r0"]["traces"]) == 1
    assert out["replicas"]["m/r1"]["unreachable"] is True
    # remote decode span anchored into the local rpc window + tagged;
    # front-door spans stay untagged
    events = {(e["replica"], e["name"]): e for e in out["waterfall"]}
    assert ("m/r0", "decode") in events
    assert ("", "rpc") in events and ("", "route") in events
    decode = events[("m/r0", "decode")]
    rpc = events[("", "rpc")]
    assert decode["offset_ms"] == pytest.approx(rpc["offset_ms"] + 500.0)
    # waterfall is time-ordered
    offsets = [e["offset_ms"] for e in out["waterfall"]]
    assert offsets == sorted(offsets)


def test_stitch_never_dedupes_cross_process_panes():
    # request ids are per-process counters: a WORKER's "m-0" must not be
    # mistaken for the front door's "m-0" (only shared_store panes dedup)
    local = [_trace_dict(trace_id="tz", request_id="m-0", model="m",
                         spans=(("rpc", 10.0, 5.0, {"replica": "m/r0"}),))]
    worker_half = _trace_dict(trace_id="tz", request_id="m-0", model="m",
                              start=9000.0,
                              spans=(("decode", 9000.2, 2.0, {}),))
    out = fleetview.stitch("tz", local, {
        "m/r0": {"traces": [worker_half]},  # no shared_store marker
    })
    assert len(out["replicas"]["m/r0"]["traces"]) == 1
    assert ("m/r0", "decode") in {(e["replica"], e["name"])
                                  for e in out["waterfall"]}


def test_stitch_fallback_anchor_for_unnamed_replica():
    # a harvested pane for a replica the local spans never named anchors
    # at the earliest local root instead of crashing
    local = [_trace_dict(trace_id="ty", request_id="front-1", start=50.0)]
    remote = _trace_dict(trace_id="ty", request_id="m/r9-3", model="m/r9",
                         start=9999.0, spans=(("decode", 9999.1, 1.0, {}),))
    out = fleetview.stitch("ty", local, {"m/r9": {"traces": [remote]}})
    anchored = out["replicas"]["m/r9"]["traces"][0]
    assert anchored["start_unix"] == pytest.approx(50.0)


# ---------------------------------------------------------------------------
# payload builder (what GetTelemetry serves; shared by both replica kinds)


def _fake_scheduler(metrics=None):
    flight = FlightRecorder(8)
    flight.record(program="decode_n", steps=4, dispatch_ms=8.0,
                  occupancy=0.5, queue_depth=0, kv_utilization=0.1,
                  tokens=4)
    return SimpleNamespace(flight=flight,
                           metrics=lambda: metrics or {"num_slots": 2})


def test_telemetry_payload_trace_filter_and_flight():
    store = TraceStore(8)
    tr = RequestTrace("trace-abc", "eng-0", model="m")
    tr.begin("decode")
    store.start(tr)
    store.finish(tr)
    other = RequestTrace("trace-zzz", "eng-1", model="m")
    store.start(other)
    store.finish(other)
    payload = fleetview.telemetry_payload(
        _fake_scheduler(), trace_id="trace-abc", store=store)
    assert [t["trace_id"] for t in payload["traces"]] == ["trace-abc"]
    assert len(payload["flight"]["records"]) == 1
    assert payload["flight"]["capacity"] == 8
    assert payload["metrics"]["num_slots"] == 2
    # trace-id-less harvest: recent request traces, bounded
    payload = fleetview.telemetry_payload(
        _fake_scheduler(), recent=1, store=store)
    assert len(payload["traces"]) == 1


def test_telemetry_payload_no_scheduler_and_metrics_error():
    store = TraceStore(4)
    payload = fleetview.telemetry_payload(None, store=store)
    assert payload["flight"] is None and payload["metrics"] == {}

    def boom():
        raise RuntimeError("stats broke")

    sched = SimpleNamespace(flight=None, metrics=boom)
    payload = fleetview.telemetry_payload(sched, store=store)
    assert payload["metrics"] == {"error": "stats broke"}


# ---------------------------------------------------------------------------
# wire tier: GetTelemetry against an in-process gRPC worker


@pytest.fixture(scope="module")
def worker():
    from localai_tpu.worker import WorkerClient
    from localai_tpu.worker.server import serve_worker

    server, port = serve_worker("127.0.0.1:0", block=False)
    client = WorkerClient(f"127.0.0.1:{port}")
    res = client.load_model(config_yaml=TINY_YAML)
    assert res.success, res.message
    yield client
    client.close()
    server.stop(grace=None)


def test_get_telemetry_rpc(worker):
    from localai_tpu.worker import backend_pb2 as pb

    list(worker.predict_stream(pb.PredictOptions(
        prompt="harvest me", max_tokens=6, temperature=0.0),
        trace_id="trace-rpc-harvest"))
    t = worker.get_telemetry(trace_id="trace-rpc-harvest")
    assert [tr["trace_id"] for tr in t["traces"]] == ["trace-rpc-harvest"]
    names = [s["name"] for s in t["traces"][0]["children"]]
    assert "prefill" in names and "decode" in names
    assert t["flight"]["records"], "flight ring empty after a generation"
    assert t["metrics"]["num_slots"] == 2
    # trace-id-less harvest returns the recent window
    t = worker.get_telemetry(recent=5)
    assert t["traces"]


def test_get_telemetry_flight_since_windowing(worker):
    # the engine thread may still be writing a trailing drain record
    # when the previous test's stream ends — wait for the ring to quiesce
    last_ts = worker.get_telemetry()["flight"]["records"][-1]["ts"]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        time.sleep(0.05)
        ts = worker.get_telemetry()["flight"]["records"][-1]["ts"]
        if ts == last_ts:
            break
        last_ts = ts
    # feeding back the last seen ts returns only newer records (none yet)
    t2 = worker.get_telemetry(since=last_ts)
    assert t2["flight"]["records"] == []


# ---------------------------------------------------------------------------
# serving tier: in-process fleet stitched end-to-end


@pytest.fixture(scope="module")
def fleet():
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.models.manager import build_serving_model

    app = AppConfig()
    mcfg = ModelConfig.model_validate(TINY)

    def factory(rid, role):
        # per-replica identity, like the manager's real factory: the
        # stitcher keys in-process engine traces by model == rid
        rcfg = mcfg.model_copy(update={"name": rid})
        return InProcessReplica(
            rid, role, lambda: build_serving_model(rcfg, app))

    fm = FleetServingModel(mcfg, app, factory, replicas=2,
                           prefill_replicas=1, disagg_threshold=48)
    yield fm
    fm.close()


def _run(fm, text, trace_id, timeout=180):
    from localai_tpu.engine.scheduler import GenRequest

    h = fm.scheduler.submit(GenRequest(
        prompt=fm.tokenizer.encode(text), max_new_tokens=6,
        temperature=0.0, trace_id=trace_id))
    h.result(timeout=timeout)
    assert h.finish_reason in ("stop", "length")
    return h


def test_fleet_stitched_waterfall(fleet):
    from localai_tpu.obs.trace import STORE

    _run(fleet, "stitch this request", "trace-fv-short")
    local = [t.to_dict() for t in STORE.find("trace-fv-short")]
    out = fleetview.stitched_trace(fleet, "trace-fv-short", local)
    pairs = {(e["replica"], e["name"]) for e in out["waterfall"]}
    # ONE waterfall: untagged front-door spans + replica-tagged engine
    # spans (in-process replicas: deduped from the shared store)
    assert ("", "route") in pairs and ("", "rpc") in pairs
    assert any(r.startswith("fvt/r") and n == "decode" for r, n in pairs)


def test_fleet_stitched_disagg_two_replicas(fleet):
    from localai_tpu.obs.trace import STORE

    before = fleet.scheduler.prefix_transfers
    _run(fleet, "fleet disaggregated long prompt " * 6, "trace-fv-disagg")
    assert fleet.scheduler.prefix_transfers == before + 1
    local = [t.to_dict() for t in STORE.find("trace-fv-disagg")]
    rids = fleetview.replica_ids_for_trace(local)
    assert any(r.startswith("fvt/p") for r in rids), rids
    out = fleetview.stitched_trace(fleet, "trace-fv-disagg", local)
    tagged = {e["replica"] for e in out["waterfall"] if e["replica"]}
    # prefill AND decode replicas appear in the ONE waterfall
    assert any(r.startswith("fvt/p") for r in tagged), tagged
    assert any(r.startswith("fvt/r") for r in tagged), tagged


def test_fleet_flight_merges_replicas(fleet):
    out = fleetview.fleet_flight(fleet)
    with_records = [rid for rid, p in out["replicas"].items()
                    if p.get("records")]
    assert len(with_records) >= 2, out["replicas"]
    assert out["count"] == len(out["records"]) > 0
    assert all(r["replica"] for r in out["records"])
    # wall-ordered merge
    ts = [r["ts_unix"] for r in out["records"]]
    assert ts == sorted(ts)
    # percentile panes ride along
    assert all("percentiles" in p for p in
               (out["replicas"][rid] for rid in with_records))
    # dispatch-anatomy columns on every merged row, fraction gauges per
    # replica pane (the per-replica bubble columns on /debug/fleet/flight)
    for rec in out["records"]:
        for ph in ("gap_ms", "sched_ms", "launch_ms", "sync_ms"):
            assert ph in rec
    assert all("host_overhead_fraction" in out["replicas"][rid]
               and "device_bubble_fraction" in out["replicas"][rid]
               for rid in with_records)


def test_fleet_flight_tolerates_replicas_without_phase_columns():
    """A mixed-version fleet: a replica whose payload predates the
    anatomy columns merges with BLANK phase cells and None fractions —
    never a KeyError (round-19 satellite)."""

    class LegacyReplica:
        id = "legacy/r0"
        state = "healthy"

        def telemetry(self, trace_id="", since=0.0, limit=64, recent=0):
            return {"flight": {
                "records": [{"ts": 1.0, "ts_unix": 100.0,
                             "program": "decode_n", "dispatch_ms": 5.0}],
                "percentiles": None, "dispatches": 1, "tokens_total": 8,
            }}

    class Pool:
        def members(self):
            return [LegacyReplica()]

    class SM:
        pool = Pool()

    out = fleetview.fleet_flight(SM())
    assert out["count"] == 1
    row = out["records"][0]
    assert row["replica"] == "legacy/r0"
    for ph in ("gap_ms", "sched_ms", "launch_ms", "sync_ms"):
        assert row[ph] is None
    pane = out["replicas"]["legacy/r0"]
    assert pane["host_overhead_fraction"] is None
    assert pane["device_bubble_fraction"] is None
    assert pane["anatomy"] is None


def test_replica_telemetry_never_raises(fleet):
    r = fleet.pool.members()[0]
    pane = r.telemetry(trace_id="trace-fv-short")
    assert pane.get("traces") is not None
    # a dead in-process replica degrades to an unreachable pane
    from localai_tpu.fleet.replica import InProcessReplica

    dead = InProcessReplica("fvt/dead", "decode", lambda: None)
    dead._killed = True
    pane = dead.telemetry()
    assert pane["unreachable"] is True and "error" in pane


def test_fleet_status_has_per_replica_percentiles(fleet):
    status = fleet.fleet_status()
    engines = [r.get("engine", {}) for r in status["replicas"]
               if r["state"] == "healthy"]
    assert engines and all("step_ms_p50" in e and "spec_accept_rate" in e
                           for e in engines if e)


# ---------------------------------------------------------------------------
# worker-process fleet: the REAL cross-process stitch (slow tier)


@pytest.mark.slow
def test_worker_fleet_stitch_cross_process(tmp_path):
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import WorkerReplica
    from localai_tpu.obs.trace import STORE

    app = AppConfig()
    mcfg = ModelConfig.model_validate({**TINY, "name": "fvw"})

    def factory(rid, role):
        return WorkerReplica(rid, role, mcfg, app,
                             env={"JAX_PLATFORMS": "cpu"})

    fm = FleetServingModel(mcfg, app, factory, replicas=2,
                           prefill_replicas=1, disagg_threshold=48)
    try:
        _run(fm, "cross process stitch", "trace-fvw-short", timeout=300)
        local = [t.to_dict() for t in STORE.find("trace-fvw-short")]
        out = fleetview.stitched_trace(fm, "trace-fvw-short", local)
        pairs = {(e["replica"], e["name"]) for e in out["waterfall"]}
        assert ("", "rpc") in pairs
        assert any(r.startswith("fvw/r") and n == "decode"
                   for r, n in pairs), pairs
        # the worker half came over the wire and is skew-anchored
        panes = [p for p in out["replicas"].values() if p.get("traces")]
        assert panes, out["replicas"]
        assert panes[0]["traces"][0]["attrs"]["skew_anchored"] is True

        # disagg: prefill + decode replicas in ONE cross-process trace
        _run(fm, "fleet disaggregated long prompt " * 6,
             "trace-fvw-disagg", timeout=300)
        assert fm.scheduler.prefix_transfers >= 1
        local = [t.to_dict() for t in STORE.find("trace-fvw-disagg")]
        out = fleetview.stitched_trace(fm, "trace-fvw-disagg", local)
        tagged = {e["replica"] for e in out["waterfall"] if e["replica"]}
        assert any(r.startswith("fvw/p") for r in tagged), tagged
        assert any(r.startswith("fvw/r") for r in tagged), tagged

        # merged flight across worker processes
        flight = fleetview.fleet_flight(fm)
        with_records = [rid for rid, p in flight["replicas"].items()
                        if p.get("records")]
        assert len(with_records) >= 2

        # a SIGKILLed worker degrades its pane, never raises
        victim = next(r for r in fm.pool.members()
                      if r.role == "decode")
        victim.kill()
        time.sleep(0.5)
        pane = victim.telemetry()
        assert pane.get("unreachable") is True
    finally:
        fm.close()
