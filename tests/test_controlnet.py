"""ControlNet guidance (parity:
/root/reference/backend/python/diffusers/backend.py:192-208 — a
ControlNetModel loaded next to the SD pipeline; the request image becomes
the control condition)."""

import json

import numpy as np
import pytest

from localai_tpu.image.loader import load_diffusers_pipeline


def _write_controlnet_fixture(root):
    """Tiny ControlNetModel matching test_image's SD fixture shapes
    (block_out [32,64], attn on level 0, 1 res block, vae downscale 2 →
    one stride-2 cond block)."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(11)

    def t(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05

    def conv(cin, cout, k=3):
        return t(cout, cin, k, k)

    c = {}
    c["conv_in.weight"], c["conv_in.bias"] = conv(4, 32), t(32)
    c["time_embedding.linear_1.weight"] = t(128, 32)
    c["time_embedding.linear_1.bias"] = t(128)
    c["time_embedding.linear_2.weight"] = t(128, 128)
    c["time_embedding.linear_2.bias"] = t(128)

    ce = "controlnet_cond_embedding"
    c[f"{ce}.conv_in.weight"], c[f"{ce}.conv_in.bias"] = conv(3, 16), t(16)
    c[f"{ce}.blocks.0.weight"], c[f"{ce}.blocks.0.bias"] = conv(16, 16), t(16)
    c[f"{ce}.blocks.1.weight"], c[f"{ce}.blocks.1.bias"] = conv(16, 32), t(32)
    c[f"{ce}.conv_out.weight"], c[f"{ce}.conv_out.bias"] = conv(32, 32), t(32)

    def res(prefix, cin, cout):
        c[f"{prefix}.norm1.weight"], c[f"{prefix}.norm1.bias"] = t(cin), t(cin)
        c[f"{prefix}.conv1.weight"] = conv(cin, cout)
        c[f"{prefix}.conv1.bias"] = t(cout)
        c[f"{prefix}.time_emb_proj.weight"] = t(cout, 128)
        c[f"{prefix}.time_emb_proj.bias"] = t(cout)
        c[f"{prefix}.norm2.weight"], c[f"{prefix}.norm2.bias"] = t(cout), t(cout)
        c[f"{prefix}.conv2.weight"] = conv(cout, cout)
        c[f"{prefix}.conv2.bias"] = t(cout)
        if cin != cout:
            c[f"{prefix}.conv_shortcut.weight"] = conv(cin, cout, 1)
            c[f"{prefix}.conv_shortcut.bias"] = t(cout)

    def st(prefix, ch, ctx=64):
        c[f"{prefix}.norm.weight"], c[f"{prefix}.norm.bias"] = t(ch), t(ch)
        c[f"{prefix}.proj_in.weight"] = conv(ch, ch, 1)
        c[f"{prefix}.proj_in.bias"] = t(ch)
        c[f"{prefix}.proj_out.weight"] = conv(ch, ch, 1)
        c[f"{prefix}.proj_out.bias"] = t(ch)
        b = f"{prefix}.transformer_blocks.0"
        for ln in ("norm1", "norm2", "norm3"):
            c[f"{b}.{ln}.weight"], c[f"{b}.{ln}.bias"] = t(ch), t(ch)
        for attn, kv in (("attn1", ch), ("attn2", ctx)):
            c[f"{b}.{attn}.to_q.weight"] = t(ch, ch)
            c[f"{b}.{attn}.to_k.weight"] = t(ch, kv)
            c[f"{b}.{attn}.to_v.weight"] = t(ch, kv)
            c[f"{b}.{attn}.to_out.0.weight"] = t(ch, ch)
            c[f"{b}.{attn}.to_out.0.bias"] = t(ch)
        inner = ch * 4
        c[f"{b}.ff.net.0.proj.weight"] = t(inner * 2, ch)
        c[f"{b}.ff.net.0.proj.bias"] = t(inner * 2)
        c[f"{b}.ff.net.2.weight"] = t(ch, inner)
        c[f"{b}.ff.net.2.bias"] = t(ch)

    res("down_blocks.0.resnets.0", 32, 32)
    st("down_blocks.0.attentions.0", 32)
    c["down_blocks.0.downsamplers.0.conv.weight"] = conv(32, 32)
    c["down_blocks.0.downsamplers.0.conv.bias"] = t(32)
    res("down_blocks.1.resnets.0", 32, 64)
    res("mid_block.resnets.0", 64, 64)
    st("mid_block.attentions.0", 64)
    res("mid_block.resnets.1", 64, 64)
    # zero convs: one per skip [32, 32, 32, 64] + mid 64
    for j, ch in enumerate([32, 32, 32, 64]):
        c[f"controlnet_down_blocks.{j}.weight"] = conv(ch, ch, 1)
        c[f"controlnet_down_blocks.{j}.bias"] = t(ch)
    c["controlnet_mid_block.weight"] = conv(64, 64, 1)
    c["controlnet_mid_block.bias"] = t(64)

    root.mkdir(parents=True)
    save_file(c, str(root / "model.safetensors"))
    (root / "config.json").write_text(json.dumps({
        "block_out_channels": [32, 64], "layers_per_block": 1,
        "down_block_types": ["CrossAttnDownBlock2D", "DownBlock2D"],
        "cross_attention_dim": 64, "attention_head_dim": 4,
        "in_channels": 4,
    }))


@pytest.fixture(scope="module")
def controlled(tmp_path_factory):
    from test_image import _write_diffusers_fixture

    base = tmp_path_factory.mktemp("cn")
    _write_diffusers_fixture(base / "model")
    _write_controlnet_fixture(base / "cn-model")
    pipe = load_diffusers_pipeline(base / "model", default_steps=2)
    pipe.attach_controlnet(str(base / "cn-model"))
    return pipe


def test_control_image_steers_generation(controlled):
    ctrl = np.zeros((64, 64, 3), np.uint8)
    ctrl[:, 32:] = 255  # half-white condition
    a = controlled.generate("a cat", width=64, height=64, seed=3,
                            control_image=ctrl)
    no_ctrl = controlled.generate("a cat", width=64, height=64, seed=3)
    assert a.image.shape == no_ctrl.image.shape
    assert not np.array_equal(a.image, no_ctrl.image)
    # scale 0 ≡ no control (zero residuals)
    zero = controlled.generate("a cat", width=64, height=64, seed=3,
                               control_image=ctrl, control_scale=0.0)
    np.testing.assert_array_equal(zero.image, no_ctrl.image)
    # a different condition image produces a different result
    b = controlled.generate("a cat", width=64, height=64, seed=3,
                            control_image=255 - ctrl)
    assert not np.array_equal(a.image, b.image)


def test_controlnet_via_config_and_api(tmp_path):
    """`diffusers.control_net` in the model YAML loads the ControlNet and
    the request image guides generation."""
    import base64
    import io

    import httpx
    from PIL import Image
    from test_api import _ServerThread, make_state
    from test_image import _write_diffusers_fixture

    _write_diffusers_fixture(tmp_path / "sd-ckpt")
    _write_controlnet_fixture(tmp_path / "cn-ckpt")
    (tmp_path / "img.yaml").write_text(
        "name: img\nmodel: sd-ckpt\nbackend: diffusers\n"
        "known_usecases: [image]\n"
        "diffusers:\n  steps: 2\n  control_net: cn-ckpt\n"
    )
    srv = _ServerThread(make_state(tmp_path))
    try:
        buf = io.BytesIO()
        Image.new("RGB", (64, 64), (255, 0, 0)).save(buf, format="PNG")
        with httpx.Client(base_url=srv.base, timeout=300.0) as c:
            r = c.post("/v1/images/generations", json={
                "model": "img", "prompt": "a house", "size": "64x64",
                "response_format": "b64_json",
                "file": base64.b64encode(buf.getvalue()).decode(),
                "seed": 1,
            })
            assert r.status_code == 200, r.text
            png = base64.b64decode(r.json()["data"][0]["b64_json"])
            assert png[:4] == b"\x89PNG"
    finally:
        srv.stop()
