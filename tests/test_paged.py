"""Paged KV cache tests: block allocator, prefix-block sharing, paged
attention parity vs the contiguous path, chunked-prefill scheduling, and
pool-exhaustion admission control. All on the CPU backend (the Pallas
paged kernel runs in interpret mode)."""

import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu import ops
from localai_tpu.engine.paged import BlockAllocator
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import GenRequest, Scheduler
from localai_tpu.models.registry import resolve_model
from localai_tpu.obs.flight import FlightRecorder
from localai_tpu.utils.tokenizer import ByteTokenizer


# ---------------------------------------------------------------------------
# BlockAllocator (host bookkeeping)
# ---------------------------------------------------------------------------


def test_allocator_alloc_free_accounting():
    a = BlockAllocator(num_blocks=9, block_tokens=4, max_blocks_per_seq=8)
    st = a.stats()
    assert st.total == 8 and st.free == 8 and st.used == 0

    assert a.allocate(0, tokens=10) == 0          # 3 blocks, no sharing
    assert a.allocate(1, tokens=4) == 0           # 1 block
    st = a.stats()
    assert st.used == 4 and st.free == 4
    assert len(a.tables[0]) == 3 and len(a.tables[1]) == 1
    assert 0 not in a.tables[0] + a.tables[1]     # trash block never handed out

    a.release(0)
    a.release(1)
    st = a.stats()
    # no pool registration happened — everything returns to the free list
    assert st.free == 8 and st.used == 0 and st.cached == 0

    # interleaved alloc/free must never leak or double-free blocks
    # (paging has no external fragmentation; accounting is the invariant)
    rng = np.random.default_rng(0)
    live = {}
    for i in range(200):
        if live and rng.random() < 0.5:
            seq = rng.choice(list(live))
            a.release(int(seq))
            del live[seq]
        else:
            seq = 100 + i
            if a.allocate(seq, tokens=int(rng.integers(1, 20))) is not None:
                live[seq] = True
    for seq in live:
        a.release(int(seq))
    st = a.stats()
    assert st.free == 8 and st.used == 0


def test_allocator_exhaustion_and_extend():
    a = BlockAllocator(num_blocks=5, block_tokens=4, max_blocks_per_seq=4)
    assert a.allocate(0, tokens=12) == 0          # 3 of 4 blocks
    assert a.allocate(1, tokens=8) is None        # needs 2, only 1 free
    assert a.allocate(1, tokens=4) == 0
    assert not a.extend(0, tokens=16)             # no blocks left
    a.release(1)
    assert a.extend(0, tokens=16)
    assert len(a.tables[0]) == 4


def test_allocator_prefix_sharing_and_refcounts():
    a = BlockAllocator(num_blocks=17, block_tokens=4, max_blocks_per_seq=8)
    prompt = list(range(100, 111))                # 11 tokens → 2 full blocks
    assert a.allocate(0, tokens=16, prompt=prompt) == 0
    assert a.register_prefix(0, prompt) == 2
    st = a.stats()
    assert st.cached == 0                         # cached but still referenced
    shared_blocks = a.tables[0][:2]

    # a second sequence with the same prompt shares both full blocks
    assert a.allocate(1, tokens=16, prompt=prompt) == 8
    assert a.tables[1][:2] == shared_blocks
    assert a.shared_blocks[1] == 2

    # diverging prompt shares only the first block
    div = prompt[:6] + [999, 998, 997, 996, 995]
    assert a.allocate(2, tokens=16, prompt=div) == 4
    assert a.tables[2][0] == shared_blocks[0]
    assert a.tables[2][1] not in shared_blocks

    a.release(0)
    a.release(1)
    a.release(2)
    st = a.stats()
    assert st.cached == 2                         # pool keeps the prefix
    assert st.used == 0

    # pool-cached blocks are reclaimed under pressure (LRU eviction)
    assert a.allocate(3, tokens=16 * 4) == 0      # forces eviction
    assert a.evictions_total >= 1


def test_allocator_eviction_never_steals_matched_shared_block():
    """A pool-only (ref==1) block matched as shared prefix for the very
    allocation being built must not be picked as an LRU eviction victim —
    it would land in the table twice (read-only AND writable)."""
    a = BlockAllocator(num_blocks=6, block_tokens=4, max_blocks_per_seq=8)
    pa = list(range(10, 18))                     # prompt A: 1 cacheable block
    pb = list(range(50, 58))                     # prompt B: 1 cacheable block
    a.allocate(0, tokens=8, prompt=pa)
    a.register_prefix(0, pa)
    a.allocate(1, tokens=8, prompt=pb)
    a.register_prefix(1, pb)
    blk_a = a.tables[0][0]
    blk_b = a.tables[1][0]
    a.release(0)
    a.release(1)
    st = a.stats()
    assert st.cached == 2 and st.free == 3

    # needs 5 blocks: 1 shared (A's cached block, LRU-oldest) + 4 fresh —
    # only 3 free, so one eviction must fire and it must pick B's block
    shared = a.allocate(2, tokens=20, prompt=pa)
    assert shared == 4
    table = a.tables[2]
    assert table[0] == blk_a
    assert table.count(blk_a) == 1, "shared block was also handed out fresh"
    assert blk_b in table[1:]                    # B's block was the victim
    assert a.evictions_total == 1
    a.release(2)
    st = a.stats()
    assert st.used == 0 and st.free + st.cached == 5


def test_allocator_never_shares_final_prompt_token_block():
    a = BlockAllocator(num_blocks=9, block_tokens=4, max_blocks_per_seq=8)
    prompt = list(range(8))                       # exactly 2 blocks
    a.allocate(0, tokens=12, prompt=prompt)
    a.register_prefix(0, prompt)
    # (n-1)//bt = 1: the block holding the final token is never shared —
    # its logits must be recomputed to seed sampling
    assert a.match_prefix(prompt) == a.tables[0][:1]


# ---------------------------------------------------------------------------
# paged attention parity (the acceptance-criteria check)
# ---------------------------------------------------------------------------


def test_paged_attention_matches_contiguous_two_lengths():
    """Two sequences at different lengths sharing one block pool: paged
    decode attention (lax reference AND Pallas interpret kernel) must
    match the contiguous flash/XLA path to <= 1e-2."""
    rng = np.random.default_rng(7)
    S, Hq, Hkv, hd, bt, MB = 2, 8, 4, 32, 16, 4
    max_ctx = MB * bt
    N = S * MB + 1
    positions = jnp.asarray([13, 55], jnp.int32)   # different lengths

    q = jnp.asarray(rng.normal(size=(S, Hq, hd)), jnp.float32)
    pool_k = jnp.asarray(rng.normal(size=(N, Hkv, bt, hd)), jnp.float32)
    pool_v = jnp.asarray(rng.normal(size=(N, Hkv, bt, hd)), jnp.float32)
    # interleaved physical blocks: slot 0 and 1 alternate through the pool
    tables = jnp.asarray([[1, 3, 5, 7], [2, 4, 6, 8]], jnp.int32)

    # contiguous mirror of the same logical rows
    contig_k = np.zeros((S, Hkv, max_ctx, hd), np.float32)
    contig_v = np.zeros((S, Hkv, max_ctx, hd), np.float32)
    for s in range(S):
        for b in range(MB):
            blk_k = np.asarray(pool_k[int(tables[s, b])])  # [H, bt, hd]
            blk_v = np.asarray(pool_v[int(tables[s, b])])
            contig_k[s, :, b * bt:(b + 1) * bt] = blk_k
            contig_v[s, :, b * bt:(b + 1) * bt] = blk_v

    ref_contig = ops.decode_attention(
        q, jnp.asarray(contig_k), jnp.asarray(contig_v), positions,
        interpret=True)
    out_lax = ops.paged_decode_attention_ref(
        q, pool_k, pool_v, tables, positions)
    out_pallas = ops.paged_decode_attention(
        q, pool_k, pool_v, tables, positions, interpret=True)
    assert float(jnp.max(jnp.abs(out_lax - ref_contig))) <= 1e-2
    assert float(jnp.max(jnp.abs(out_pallas - ref_contig))) <= 1e-2


def test_paged_runner_matches_contiguous_greedy():
    """End-to-end engine parity: same weights, two prompts of different
    lengths sharing the paged pool — greedy decode must match the
    contiguous runner token-for-token."""
    tiny = resolve_model("debug:tiny", dtype="float32")
    rc = ModelRunner(tiny.cfg, tiny.params, num_slots=4, max_ctx=96,
                     prefill_buckets=[16, 32], kv_dtype="float32")
    rp = ModelRunner(tiny.cfg, tiny.params, num_slots=4, max_ctx=96,
                     prefill_buckets=[16, 32], kv_dtype="float32",
                     paged=True, kv_block_tokens=16, prefill_chunk=16)
    assert rp.paged
    pa = list(b"the quick brown fox jumps over the dog")  # chunked: 3 chunks
    pb = list(b"hi")
    seqs = {}
    for name, r in (("contig", rc), ("paged", rp)):
        s1 = r.acquire_slot()
        t1 = r.admit(s1, pa, temperature=0.0)
        s2 = r.acquire_slot()
        t2 = r.admit(s2, pb, temperature=0.0)
        a, b = [t1], [t2]
        for _ in range(8):
            toks = r.step()
            a.append(int(toks[s1]))
            b.append(int(toks[s2]))
        seqs[name] = (a, b)
    assert seqs["paged"] == seqs["contig"]


def test_paged_runner_pallas_kernel_matches_xla_end_to_end():
    """The Pallas paged-decode kernel (interpret mode on CPU) wired
    through the runner must reproduce the gather+XLA paged path."""
    tiny = resolve_model("debug:tiny", dtype="float32")
    outs = {}
    for impl in ("xla", "pallas_interpret"):
        r = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=64,
                        prefill_buckets=[16], kv_dtype="float32",
                        paged=True, kv_block_tokens=16, prefill_chunk=16,
                        attn_impl=impl)
        assert r.paged_attn_impl == ("pallas" if impl != "xla" else "xla")
        s = r.acquire_slot()
        t = r.admit(s, list(b"kernel parity"), temperature=0.0)
        outs[impl] = [t] + [int(r.step()[s]) for _ in range(6)]
    assert outs["pallas_interpret"] == outs["xla"]


def test_paged_runner_int8_kv_matches_contiguous():
    """Scaled-int8 pool: paged quantized decode must track the contiguous
    quantized path (identical quantization grid → identical tokens)."""
    tiny = resolve_model("debug:tiny", dtype="float32")
    rc = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=64,
                     prefill_buckets=[16], kv_dtype="int8")
    rp = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=64,
                     prefill_buckets=[16], kv_dtype="int8",
                     paged=True, kv_block_tokens=16, prefill_chunk=16)
    prompt = list(b"quantized kv")
    outs = {}
    for name, r in (("contig", rc), ("paged", rp)):
        s = r.acquire_slot()
        t = r.admit(s, prompt, temperature=0.0)
        outs[name] = [t] + [int(r.step()[s]) for _ in range(6)]
    assert outs["paged"] == outs["contig"]


def test_paged_prefix_pool_reuse_preserves_output():
    """Pool-shared prefix blocks must not change greedy output, and the
    second admission must actually reuse blocks."""
    tiny = resolve_model("debug:tiny", dtype="float32")
    r = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=96,
                    prefill_buckets=[16, 32], kv_dtype="float32",
                    paged=True, kv_block_tokens=16, prefill_chunk=16)
    prompt = list(b"shared system prompt here plus tail")
    s = r.acquire_slot()
    first = [r.admit(s, prompt, temperature=0.0)]
    first += [int(r.step()[s]) for _ in range(5)]
    r.release(s)
    assert r.allocator.stats().cached > 0

    s2 = r.acquire_slot()
    second = [r.admit(s2, prompt, temperature=0.0)]
    assert r.last_prefix_reused >= r.block_tokens
    assert r.last_prefill_path == "paged_shared"
    second += [int(r.step()[s2]) for _ in range(5)]
    assert second == first


# ---------------------------------------------------------------------------
# chunked prefill scheduling + admission control
# ---------------------------------------------------------------------------


def _paged_sched(tiny, flight=None, **kw):
    runner = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=96,
                         prefill_buckets=[16, 32], kv_dtype="float32",
                         paged=True, kv_block_tokens=16, prefill_chunk=16,
                         **kw)
    return Scheduler(runner, ByteTokenizer(), flight=flight)


@pytest.fixture(scope="module")
def tiny():
    return resolve_model("debug:tiny", dtype="float32")


def test_chunked_prefill_interleaves_with_decode(tiny):
    """A long prompt's chunks must not stall an active slot: decode
    dispatches appear BETWEEN its prefill_chunk dispatches in the flight
    timeline."""
    flight = FlightRecorder(256)
    s = _paged_sched(tiny, flight=flight)
    try:
        a = s.submit(GenRequest(prompt=list(b"warm"), max_new_tokens=48,
                                temperature=0.0))
        # wait until A is actively decoding
        while a.completion_tokens < 2:
            pass
        long_prompt = list(b"x" * 80)              # 5 chunks of 16
        b = s.submit(GenRequest(prompt=long_prompt, max_new_tokens=4,
                                temperature=0.0))
        a.result(timeout=60)
        b.result(timeout=60)
    finally:
        s.shutdown()
    progs = [rec["program"] for rec in flight.snapshot(limit=256)]
    chunk_idx = [i for i, p in enumerate(progs) if p == "prefill_chunk"]
    assert len(chunk_idx) >= 5, progs
    interleaved = any(
        any(p != "prefill_chunk" for p in progs[i + 1:j])
        for i, j in zip(chunk_idx, chunk_idx[1:])
    )
    assert interleaved, progs
    assert s.total_prefill_chunks >= 5


def test_pool_exhaustion_holds_request_until_blocks_free(tiny):
    """With a pool too small for two concurrent reservations, the second
    request waits (held, not errored) and completes after the first frees
    its blocks."""
    # 7 allocatable blocks of 16 = 112 rows; each request reserves
    # prompt + max_new + 1 capped at max_ctx (96 rows = 6 blocks)
    s = _paged_sched(tiny, kv_num_blocks=8)
    try:
        a = s.submit(GenRequest(prompt=list(b"first request"),
                                max_new_tokens=90, temperature=0.0))
        b = s.submit(GenRequest(prompt=list(b"second request"),
                                max_new_tokens=90, temperature=0.0))
        ra = a.result(timeout=120)
        rb = b.result(timeout=120)
        assert ra.finish_reason is not None
        assert rb.finish_reason is not None
        assert a.admit_index < b.admit_index
    finally:
        s.shutdown()


def test_cancel_races_pool_exhaustion_hold(tiny):
    """A request cancelled while parked in the scheduler's pool-
    exhaustion hold (``_held``) must resolve ``cancelled``, release its
    head-of-line place, and let a successor admit — with every block
    conserved afterwards."""
    import time

    s = _paged_sched(tiny, kv_num_blocks=8)
    try:
        a = s.submit(GenRequest(prompt=list(b"pool filler request"),
                                max_new_tokens=90, temperature=0.0))
        held = s.submit(GenRequest(prompt=list(b"about to be held"),
                                   max_new_tokens=90, temperature=0.0))
        deadline = time.monotonic() + 30
        while s._held is not held and time.monotonic() < deadline:
            time.sleep(0.005)
        assert s._held is held, "second request never parked in the hold"
        held.cancel()
        successor = s.submit(GenRequest(prompt=list(b"held successor"),
                                        max_new_tokens=8, temperature=0.0))
        held.result(timeout=60)
        assert held.finish_reason == "cancelled"
        a.result(timeout=120)
        successor.result(timeout=120)
        assert a.finish_reason is not None
        assert successor.finish_reason in ("stop", "length")
        # the cancelled hold left nothing behind: all blocks return and
        # the allocator's conservation invariants hold
        st = s.runner.allocator.stats()
        assert st.free + st.cached == st.total
        assert s.runner.allocator.check_invariants() == []
    finally:
        s.shutdown()


def test_paged_metrics_export_block_gauges(tiny):
    s = _paged_sched(tiny)
    try:
        s.generate(GenRequest(prompt=list(b"metrics"), max_new_tokens=4,
                              temperature=0.0), timeout=60)
        m = s.metrics()
        assert m["kv_block_tokens"] == 16
        assert m["kv_blocks_total"] > 0
        assert m["kv_blocks_free"] + m["kv_blocks_used"] == m["kv_blocks_total"]
        assert m["prefill_chunks"] >= 1
        assert "prefill_chunk_queue_depth" in m
        assert 0.0 <= m["kv_utilization"] <= 1.0

        from localai_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.Registry()
        obs_metrics.update_engine_gauges("tiny", m, registry=reg)
        text = reg.render()
        assert 'localai_kv_blocks_free{model="tiny"}' in text
        assert 'localai_kv_blocks_used{model="tiny"}' in text
        assert 'localai_prefill_chunk_queue_depth{model="tiny"}' in text
    finally:
        s.shutdown()


def test_disk_prefix_export_transfers_across_layouts(tiny):
    """The disk prompt-cache export format is layout-independent: rows
    exported from a paged pool load into a contiguous cache and vice
    versa, and the resumed generation matches the original."""
    def mk(paged):
        kw = ({"kv_block_tokens": 16, "prefill_chunk": 16} if paged else {})
        return ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=96,
                           prefill_buckets=[16, 32], kv_dtype="float32",
                           paged=paged, **kw)

    prompt = list(b"a long shared system prompt for the cache")
    src = mk(True)
    s = src.acquire_slot()
    base = [src.admit(s, prompt, temperature=0.0)]
    base += [int(src.step()[s]) for _ in range(5)]
    arrays = src.export_prefix(s, len(prompt))

    for paged in (True, False):
        dst = mk(paged)
        s2 = dst.acquire_slot()
        assert dst.load_prefix(s2, arrays, len(prompt))
        t = dst.admit(s2, prompt, temperature=0.0,
                      resident=list(prompt), valid_n=len(prompt))
        assert dst.last_prefix_reused == len(prompt) - 1
        out = [t] + [int(dst.step()[s2]) for _ in range(5)]
        assert out == base, (paged, out, base)


def test_spec_decoder_accepts_paged_runner(tiny):
    """The PR 6 'SpecDecoder rejects paged runners' guard is gone: the
    block-native lane (localai_tpu.spec) verifies draft windows straight
    through the paged table mirror. Only a PAGED DRAFT stays rejected —
    its window scans run over contiguous slot rows."""
    from localai_tpu.engine.speculative import SKIP, SpecDecoder

    rp = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=64,
                     prefill_buckets=[16], kv_dtype="float32", paged=True)
    rc = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=64,
                     prefill_buckets=[16], kv_dtype="float32", paged=False)
    spec = SpecDecoder(rp, rc, gamma=2)
    slot = spec.acquire_slot()
    spec.admit(slot, list(b"paged spec"), temperature=0.0)
    rows = spec.step_spec()
    assert 1 <= int((rows[:, slot] != SKIP).sum()) <= 3
    assert not rp.allocator.check_invariants()

    rp2 = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=64,
                      prefill_buckets=[16], kv_dtype="float32", paged=True)
    with pytest.raises(ValueError, match="contiguous"):
        SpecDecoder(rc, rp2)


# ---------------------------------------------------------------------------
# meshed paged serving (ISSUE 8): the block pool sharded over a CPU mesh
# ---------------------------------------------------------------------------


def _tp_mesh():
    """data=4 × model=2 over the conftest's 8 virtual CPU devices: tiny's
    2 kv heads split over 'model', 4 slots over 'data'."""
    from localai_tpu.parallel.mesh import MeshPlan, build_mesh

    return build_mesh(MeshPlan(data=4, model=2))


def test_runner_accepts_mesh_with_paged(tiny):
    """mesh != None with paged=True is a supported configuration (the PR 6
    'mesh forces contiguous' incompatibility is gone); only pipeline
    parallelism still forces the slot-contiguous layout."""
    from localai_tpu.parallel import sharding as shd
    from localai_tpu.parallel.mesh import MeshPlan, build_mesh

    mesh = _tp_mesh()
    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    r = ModelRunner(tiny.cfg, params, num_slots=4, max_ctx=64,
                    prefill_buckets=[16], kv_dtype="float32", mesh=mesh,
                    paged=True, kv_block_tokens=16)
    assert r.paged and r.mesh is mesh

    from localai_tpu.parallel.pipeline import shard_params_pp

    import jax

    pp_mesh = build_mesh(MeshPlan(pipe=2), devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="pipeline parallelism"):
        ModelRunner(tiny.cfg, shard_params_pp(tiny.params, tiny.cfg, pp_mesh),
                    num_slots=2, max_ctx=64, prefill_buckets=[16],
                    kv_dtype="float32", mesh=pp_mesh, paged=True)


def test_meshed_paged_matches_single_device_greedy(tiny):
    """Greedy parity: the head-sharded pool + data-sharded table mirror
    must reproduce the single-device paged engine token-for-token, two
    prompts of different lengths sharing the pool (chunked + short)."""
    from localai_tpu.parallel import sharding as shd

    mesh = _tp_mesh()
    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    kw = dict(num_slots=4, max_ctx=96, prefill_buckets=[16, 32],
              kv_dtype="float32", paged=True, kv_block_tokens=16,
              prefill_chunk=16)
    pa = list(b"the quick brown fox jumps over the dog")  # 3 chunks
    pb = list(b"hi")
    seqs = {}
    for name, r in (
        ("single", ModelRunner(tiny.cfg, tiny.params, **kw)),
        ("mesh", ModelRunner(tiny.cfg, params, mesh=mesh, **kw)),
    ):
        s1 = r.acquire_slot()
        t1 = r.admit(s1, pa, temperature=0.0)
        s2 = r.acquire_slot()
        t2 = r.admit(s2, pb, temperature=0.0)
        a, b = [t1], [t2]
        for _ in range(8):
            toks = r.step()
            a.append(int(toks[s1]))
            b.append(int(toks[s2]))
        seqs[name] = (a, b)
    assert seqs["mesh"] == seqs["single"]


def test_meshed_paged_int8_matches_single_device(tiny):
    """Scaled-int8 pool under the mesh: the f32 scale pool shards
    alongside the int8 values (same spec minus head_dim) and greedy
    decode tracks the single-device quantized path."""
    from localai_tpu.parallel import sharding as shd

    mesh = _tp_mesh()
    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    kw = dict(num_slots=4, max_ctx=64, prefill_buckets=[16, 32],
              kv_dtype="int8", paged=True, kv_block_tokens=16,
              prefill_chunk=16)
    prompt = list(b"quantized kv under a mesh")
    outs = {}
    for name, r in (
        ("single", ModelRunner(tiny.cfg, tiny.params, **kw)),
        ("mesh", ModelRunner(tiny.cfg, params, mesh=mesh, **kw)),
    ):
        s = r.acquire_slot()
        t = r.admit(s, prompt, temperature=0.0)
        outs[name] = [t] + [int(r.step()[s]) for _ in range(6)]
    assert outs["mesh"] == outs["single"]


def test_ring_paged_prefill_matches_contiguous_sp(tiny):
    """A long prompt on a 'seq' mesh takes the ring-attention paged path
    (one dispatch over all chips, K/V scattered through the block table)
    and must emit the same greedy stream as the contiguous SP engine —
    both prefills run the identical ring math, so this pins the paged
    scatter + paged decode halves."""
    import jax

    from localai_tpu.parallel import sharding as shd
    from localai_tpu.parallel.mesh import MeshPlan, build_mesh

    mesh = build_mesh(MeshPlan(data=2, seq=2, model=2))
    params = shd.shard_params(tiny.params, tiny.cfg, mesh)
    rc = ModelRunner(tiny.cfg, params, num_slots=4, max_ctx=128,
                     prefill_buckets=[64], kv_dtype="float32", mesh=mesh,
                     sp_threshold=32)
    rp = ModelRunner(tiny.cfg, params, num_slots=4, max_ctx=128,
                     prefill_buckets=[64], kv_dtype="float32", mesh=mesh,
                     sp_threshold=32, paged=True, kv_block_tokens=16,
                     prefill_chunk=16)
    assert rp.sp_enabled
    prompt = list(range(1, 45))
    sc = rc.acquire_slot()
    tc = rc.admit(sc, prompt, temperature=0.0)
    assert rc.last_prefill_path == "sp"
    sp = rp.acquire_slot()
    tp = rp.admit(sp, prompt, temperature=0.0)
    assert rp.last_prefill_path == "paged_sp"
    a = [tc] + [int(rc.step()[sc]) for _ in range(6)]
    b = [tp] + [int(rp.step()[sp]) for _ in range(6)]
    assert a == b

    # short prompts stay on the chunked path (no seq-wide dispatch for a
    # prompt that fits one chunk)
    s2 = rp.acquire_slot()
    rp.admit(s2, list(b"short"), temperature=0.0)
    assert rp.last_prefill_path == "paged"


def test_kv_overcommit_ratio_scales_default_pool(tiny, monkeypatch):
    """LOCALAI_KV_OVERCOMMIT scales the default pool past (or under) the
    contiguous footprint; explicit kv_num_blocks still wins."""
    kw = dict(num_slots=2, max_ctx=64, prefill_buckets=[16],
              kv_dtype="float32", paged=True, kv_block_tokens=16)
    base = ModelRunner(tiny.cfg, tiny.params, **kw)
    assert base.kv_overcommit == 1.0
    contiguous_blocks = 2 * base.max_blocks + 1

    monkeypatch.setenv("LOCALAI_KV_OVERCOMMIT", "1.5")
    grown = ModelRunner(tiny.cfg, tiny.params, **kw)
    assert grown.kv_overcommit == 1.5
    assert grown.allocator.num_blocks == int(
        2 * base.max_blocks * 1.5) + 1 > contiguous_blocks

    monkeypatch.setenv("LOCALAI_KV_OVERCOMMIT", "0.5")
    shrunk = ModelRunner(tiny.cfg, tiny.params, **kw)
    assert shrunk.allocator.num_blocks < contiguous_blocks

    explicit = ModelRunner(tiny.cfg, tiny.params, kv_num_blocks=7, **kw)
    assert explicit.allocator.num_blocks == 7  # absolute count wins

    sched = Scheduler(base, ByteTokenizer())
    try:
        assert sched.metrics()["kv_overcommit_ratio"] == 1.0
    finally:
        sched.shutdown()
