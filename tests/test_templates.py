"""Template subsystem tests. The llama3/chatML Go templates and expected
outputs mirror the reference's own template tests
(/root/reference/pkg/model/template_test.go) — byte-for-byte parity."""

import pytest

from localai_tpu.config.model_config import ModelConfig
from localai_tpu.templates import (
    TemplateCache,
    TemplateType,
    build_chat_prompt,
    build_completion_prompt,
    build_edit_prompt,
    go_template_to_jinja,
    multimodal_placeholders,
)

LLAMA3 = """<|start_header_id|>{{if eq .RoleName "assistant"}}assistant{{else if eq .RoleName "system"}}system{{else if eq .RoleName "tool"}}tool{{else if eq .RoleName "user"}}user{{end}}<|end_header_id|>

{{ if .FunctionCall -}}
Function call:
{{ else if eq .RoleName "tool" -}}
Function response:
{{ end -}}
{{ if .Content -}}
{{.Content -}}
{{ else if .FunctionCall -}}
{{ toJson .FunctionCall -}}
{{ end -}}
<|eot_id|>"""

CHATML = """<|im_start|>{{if eq .RoleName "assistant"}}assistant{{else if eq .RoleName "system"}}system{{else if eq .RoleName "tool"}}tool{{else if eq .RoleName "user"}}user{{end}}
{{- if .FunctionCall }}
<tool_call>
{{- else if eq .RoleName "tool" }}
<tool_response>
{{- end }}
{{- if .Content}}
{{.Content }}
{{- end }}
{{- if .FunctionCall}}
{{toJson .FunctionCall}}
{{- end }}
{{- if .FunctionCall }}
</tool_call>
{{- else if eq .RoleName "tool" }}
</tool_response>
{{- end }}<|im_end|>"""


@pytest.fixture()
def cache(tmp_path):
    return TemplateCache(tmp_path)


def _eval_msg(cache, tmpl, **data):
    base = {
        "SystemPrompt": "", "Role": "", "RoleName": "", "FunctionName": "",
        "Content": "", "MessageIndex": 0, "Function": False,
        "FunctionCall": None, "LastMessage": False,
    }
    base.update(data)
    return cache.evaluate(TemplateType.CHAT_MESSAGE, tmpl, base)


# -- parity cases from /root/reference/pkg/model/template_test.go ----------

def test_llama3_user(cache):
    out = _eval_msg(cache, LLAMA3, RoleName="user", Role="user",
                    Content="A long time ago in a galaxy far, far away...")
    assert out == ("<|start_header_id|>user<|end_header_id|>\n\n"
                   "A long time ago in a galaxy far, far away...<|eot_id|>")


def test_llama3_function_call(cache):
    out = _eval_msg(cache, LLAMA3, RoleName="assistant", Role="assistant",
                    FunctionCall={"function": "test"})
    assert out == ("<|start_header_id|>assistant<|end_header_id|>\n\n"
                   "Function call:\n{\"function\":\"test\"}<|eot_id|>")


def test_llama3_function_response(cache):
    out = _eval_msg(cache, LLAMA3, RoleName="tool", Role="tool",
                    Content="Response from tool")
    assert out == ("<|start_header_id|>tool<|end_header_id|>\n\n"
                   "Function response:\nResponse from tool<|eot_id|>")


def test_chatml_user(cache):
    out = _eval_msg(cache, CHATML, RoleName="user", Role="user",
                    Content="A long time ago in a galaxy far, far away...")
    assert out == ("<|im_start|>user\n"
                   "A long time ago in a galaxy far, far away...<|im_end|>")


def test_chatml_function_call(cache):
    out = _eval_msg(cache, CHATML, RoleName="assistant", Role="assistant",
                    FunctionCall={"function": "test"})
    assert out == ("<|im_start|>assistant\n<tool_call>\n"
                   "{\"function\":\"test\"}\n</tool_call><|im_end|>")


def test_chatml_function_response(cache):
    out = _eval_msg(cache, CHATML, RoleName="tool", Role="tool",
                    Content="Response from tool")
    assert out == ("<|im_start|>tool\n<tool_response>\n"
                   "Response from tool\n</tool_response><|im_end|>")


# -- file templates, inline templates, traversal guard ---------------------

def test_file_template_loads(cache, tmp_path):
    (tmp_path / "completion.tmpl").write_text("### Prompt:\n{{.Input}}\n### Response:")
    out = cache.evaluate(TemplateType.COMPLETION, "completion",
                         {"Input": "hello"})
    assert out == "### Prompt:\nhello\n### Response:"


def test_inline_template_used_when_no_file(cache):
    out = cache.evaluate(TemplateType.COMPLETION, "PRE {{.Input}} POST",
                         {"Input": "x"})
    assert out == "PRE x POST"


def test_jinja_template_passthrough(cache, tmp_path):
    (tmp_path / "j.jinja").write_text("A {{ Input }} B")
    assert cache.evaluate(TemplateType.COMPLETION, "j", {"Input": "y"}) == "A y B"


def test_traversal_rejected(tmp_path):
    nested = tmp_path / "tpl"
    nested.mkdir()
    outside = tmp_path / "evil.tmpl"
    outside.write_text("{{.Input}}")
    cache = TemplateCache(nested)
    # a name resolving to a file OUTSIDE the templates dir is refused
    # (parity: cache.go:81-83 VerifyPath error)
    with pytest.raises(ValueError, match="escapes"):
        cache.evaluate(TemplateType.COMPLETION, "../evil", {"Input": "x"})


# -- chat prompt construction (chat.go loop parity) ------------------------

def test_build_chat_prompt_with_message_template():
    cfg = ModelConfig(name="m")
    cfg.template.chat_message = CHATML
    cfg.template.chat = "{{.Input}}\n<|im_start|>assistant\n"
    cache = TemplateCache("/nonexistent")
    out = build_chat_prompt(cache, cfg, [
        {"role": "system", "content": "You are helpful."},
        {"role": "user", "content": "Hi!"},
    ])
    assert out == ("<|im_start|>system\nYou are helpful.<|im_end|>\n"
                   "<|im_start|>user\nHi!<|im_end|>\n"
                   "<|im_start|>assistant\n")


def test_build_chat_prompt_role_fallback():
    cfg = ModelConfig(name="m", roles={"user": "USER: ", "assistant": "ASSISTANT: "})
    cache = TemplateCache("/nonexistent")
    out = build_chat_prompt(cache, cfg, [
        {"role": "user", "content": "question"},
        {"role": "assistant", "content": "answer"},
    ])
    assert out == "USER: question\nASSISTANT: answer"


def test_build_chat_prompt_tool_calls_marshalled():
    cfg = ModelConfig(name="m")
    cache = TemplateCache("/nonexistent")
    out = build_chat_prompt(cache, cfg, [
        {"role": "assistant",
         "tool_calls": [{"id": "1", "function": {"name": "f", "arguments": "{}"}}]},
    ])
    assert out == '[{"id":"1","function":{"name":"f","arguments":"{}"}}]'


def test_multipart_content_flattened():
    cfg = ModelConfig(name="m")
    cache = TemplateCache("/nonexistent")
    out = build_chat_prompt(cache, cfg, [
        {"role": "user", "content": [
            {"type": "text", "text": "look at "},
            {"type": "image_url", "image_url": {"url": "http://x/i.png"}},
            {"type": "text", "text": "this"},
        ]},
    ])
    assert out == "look at this"


def test_completion_and_edit_prompts():
    cfg = ModelConfig(name="m")
    cfg.template.completion = "C:{{.Input}}"
    cfg.template.edit = "E:{{.Instruction}}|{{.Input}}"
    cache = TemplateCache("/nonexistent")
    assert build_completion_prompt(cache, cfg, "in") == "C:in"
    assert build_edit_prompt(cache, cfg, "text", "fix it") == "E:fix it|text"


def test_multimodal_placeholders():
    out = multimodal_placeholders("", "describe", n_images=2)
    assert out == "[img-0][img-1]describe"
    out = multimodal_placeholders(
        "{{ range .Images }}<image>{{end}}{{.Text}}", "hi", n_images=1
    )
    assert out == "<image>hi"


def test_gotmpl_range_and_nested():
    j = go_template_to_jinja("{{ range .Items }}[{{.Name}}]{{ end }}")
    assert "for _it in Items" in j
    from localai_tpu.templates.gotmpl import make_environment
    env = make_environment()
    assert env.from_string(j).render(Items=[{"Name": "a"}, {"Name": "b"}]) == "[a][b]"
