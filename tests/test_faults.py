"""Fault injection + self-healing: registry predicates, the NaN decode
guard + slot quarantine, block-pool invariants, and the supervisor's
stall → rebuild → failed escalation — on the tiny debug model."""

import time

import pytest

from localai_tpu import faults
from localai_tpu.engine.paged import BlockAllocator
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import GenRequest, Scheduler
from localai_tpu.faults import EngineSupervisor, FaultInjected, FaultSpec
from localai_tpu.models.registry import resolve_model
from localai_tpu.obs.engine import EngineTelemetry
from localai_tpu.obs.metrics import Registry
from localai_tpu.obs.slo import SLOTracker
from localai_tpu.obs.trace import TraceStore
from localai_tpu.obs.watchdog import Watchdog
from localai_tpu.utils.tokenizer import ByteTokenizer


@pytest.fixture(autouse=True)
def clean_registry():
    faults.clear()
    yield
    faults.clear()
    assert faults.active() is False


@pytest.fixture(scope="module")
def tiny():
    return resolve_model("debug:tiny", dtype="float32")


def _engine(tiny, name="faults", *, watchdog=None, registry=None,
            store=None, **kw):
    registry = registry or Registry()
    runner = ModelRunner(tiny.cfg, tiny.params, num_slots=4, max_ctx=256,
                         prefill_buckets=[16, 32], kv_dtype="float32",
                         paged=True, kv_block_tokens=16, prefill_chunk=16,
                         **kw)
    sched = Scheduler(
        runner, ByteTokenizer(),
        telemetry=EngineTelemetry(
            model=name, registry=registry, store=store or TraceStore(),
            slo=SLOTracker(registry=registry, targets={})),
        watchdog=watchdog,
    )
    return runner, sched


def _req(text, **kw):
    kw.setdefault("temperature", 0.0)
    return GenRequest(prompt=ByteTokenizer().encode(text), **kw)


# -- registry ------------------------------------------------------------


def test_registry_arm_sets_and_clear_resets_active():
    assert faults.active() is False
    faults.arm(FaultSpec(site="engine.dispatch"))
    assert faults.active() is True
    assert faults.clear() == 1
    assert faults.active() is False


def test_registry_rejects_unknown_site_and_bad_fields():
    with pytest.raises(ValueError):
        faults.arm(FaultSpec(site="engine.dipsatch"))
    with pytest.raises(ValueError):
        faults.arm(FaultSpec(site="engine.dispatch", after=-1))
    assert faults.active() is False


def test_fire_predicate_after_times_match():
    faults.arm(FaultSpec(site="engine.dispatch", after=2, times=2,
                         match="decode"))
    assert faults.fire("engine.dispatch", key="prefill") is None  # no match
    assert faults.fire("engine.drain", key="decode") is None      # site
    assert faults.fire("engine.dispatch", key="decode") is None   # skip 1
    assert faults.fire("engine.dispatch", key="decode") is None   # skip 2
    assert faults.fire("engine.dispatch", key="decode") is not None
    assert faults.fire("engine.dispatch", key="decode") is not None
    assert faults.fire("engine.dispatch", key="decode") is None   # exhausted
    snap = faults.snapshot()[0]
    assert snap["fired"] == 2 and snap["hits"] == 5


def test_apply_raise_and_sleep_modes():
    faults.arm(FaultSpec(site="engine.dispatch", mode="raise", times=1))
    with pytest.raises(FaultInjected):
        faults.apply("engine.dispatch", key="decode")
    faults.clear()
    faults.arm(FaultSpec(site="engine.drain", mode="hang", delay_s=0.05,
                         times=1))
    t0 = time.monotonic()
    assert faults.apply("engine.drain").mode == "hang"
    assert time.monotonic() - t0 >= 0.05


def test_parse_spec_and_env_install():
    spec = faults.parse_spec(
        "engine.drain", "mode=hang,delay_s=1.5,after=2,times=3,match=x")
    assert (spec.mode, spec.delay_s, spec.after, spec.times, spec.match) \
        == ("hang", 1.5, 2, 3, "x")
    with pytest.raises(ValueError):
        faults.parse_spec("engine.drain", "bogus_field=1")
    armed = faults.install_from_env({
        "LOCALAI_FAULT_ENGINE_DISPATCH": "mode=raise,times=1",
        "LOCALAI_FAULT_NO_SUCH_SITE": "mode=raise",   # ignored, logged
        "OTHER_VAR": "x",
    })
    assert armed == 1
    assert faults.snapshot()[0]["site"] == "engine.dispatch"


# -- block-pool invariants ----------------------------------------------


def test_check_invariants_clean_allocator():
    a = BlockAllocator(num_blocks=10, block_tokens=16, max_blocks_per_seq=8)
    assert a.check_invariants() == []
    a.allocate(0, 48, prompt=list(range(40)))
    a.allocate(1, 32)
    assert a.check_invariants() == []
    a.register_prefix(0, list(range(40)))
    assert a.check_invariants() == []
    a.release(0)
    a.release(1)
    assert a.check_invariants() == []
    st = a.stats()
    assert st.free + st.cached == st.total


def test_check_invariants_detects_corruption():
    a = BlockAllocator(num_blocks=10, block_tokens=16, max_blocks_per_seq=8)
    a.allocate(0, 48)
    a._ref[a.tables[0][0]] = 0            # leaked refcount
    assert any("refcount" in p for p in a.check_invariants())
    a = BlockAllocator(num_blocks=10, block_tokens=16, max_blocks_per_seq=8)
    a._free.append(a._free[-1])           # duplicate free entry
    assert any("duplicate" in p for p in a.check_invariants())
    a = BlockAllocator(num_blocks=10, block_tokens=16, max_blocks_per_seq=8)
    bid = a._free.pop()                   # vanished block (leak)
    assert any(f"block {bid} leaked" in p for p in a.check_invariants())
    a = BlockAllocator(num_blocks=10, block_tokens=16, max_blocks_per_seq=8)
    bid = a._free.pop()
    a._ref[bid] = 1                       # refcounted but unreachable
    assert any("no table or pool entry" in p
               for p in a.check_invariants())


def test_injected_pool_exhaustion():
    a = BlockAllocator(num_blocks=10, block_tokens=16, max_blocks_per_seq=8)
    faults.arm(FaultSpec(site="paged.allocate", mode="exhaust", times=1))
    assert a.allocate(0, 32) is None      # injected: pool reports full
    assert a.allocate(0, 32) is not None  # schedule exhausted: real answer
    assert a.check_invariants() == []


# -- NaN/inf decode guard ------------------------------------------------


def test_nan_guard_fails_only_poisoned_slot_and_quarantines(tiny):
    reg = Registry()
    runner, sched = _engine(tiny, "nan", registry=reg)
    try:
        ref = sched.generate(_req("co-batched survivor", max_new_tokens=16),
                             timeout=120)
        faults.arm(FaultSpec(site="decode.nan", mode="nan",
                             match="poison-me", times=1))
        poisoned = sched.submit(_req("poison target", max_new_tokens=300,
                                     correlation_id="poison-me"))
        survivor = sched.submit(_req("co-batched survivor",
                                     max_new_tokens=16))
        poisoned.result(120)
        survivor.result(120)
        # only the poisoned request fails; the co-batched one is
        # byte-identical to the unpoisoned greedy reference
        assert poisoned.finish_reason == "error"
        assert survivor.finish_reason in ("stop", "length")
        assert survivor.token_ids == ref.token_ids
        assert sched.nan_rows == 1
        m = sched.metrics()
        assert m["nan_rows"] == 1
        assert m["quarantined_slots"] == 1
        assert 'localai_nan_rows_total{model="nan"} 1' in reg.render()
        # the quarantined slot is out of admission now, and returns to
        # service after the quarantine window of dispatches passes
        deadline = time.monotonic() + 60
        while sched._quarantined and time.monotonic() < deadline:
            sched.generate(_req("quarantine drain", max_new_tokens=40),
                           timeout=120)
        assert not sched._quarantined
        assert runner.allocator.check_invariants() == []
    finally:
        sched.shutdown()


def test_quarantine_gauge_exported():
    from localai_tpu.obs.metrics import update_engine_gauges

    reg = Registry()
    update_engine_gauges("m", {"quarantined_slots": 2}, registry=reg)
    assert 'localai_quarantined_slots{model="m"} 2' in reg.render()


# -- self-healing supervisor --------------------------------------------


def _supervised(tiny, name, **sup_kw):
    reg = Registry()
    store = TraceStore()
    wd = Watchdog(deadline=0.4, registry=reg, store=store,
                  poll_interval=0.1)
    runner, sched = _engine(tiny, name, watchdog=wd, registry=reg,
                            store=store)
    sup_kw.setdefault("max_rebuilds", 3)
    sup_kw.setdefault("backoff_s", 0.05)
    sup_kw.setdefault("probe_timeout_s", 60.0)
    sup = EngineSupervisor(sched, registry=reg, **sup_kw)
    return reg, wd, runner, sched, sup


def test_stall_escalates_to_rebuild_and_recovers(tiny):
    reg, wd, runner, sched, sup = _supervised(tiny, "rebuild")
    try:
        wedged = sched.submit(_req("about to wedge", max_new_tokens=400))
        deadline = time.monotonic() + 60
        while wedged.t_first_token is None and time.monotonic() < deadline:
            time.sleep(0.02)
        faults.arm(FaultSpec(site="engine.drain", mode="hang",
                             delay_s=2.0, times=1))
        wedged.result(90)
        assert wedged.finish_reason == "error"   # drained with clean error
        deadline = time.monotonic() + 60
        while sched.rebuilds == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sched.rebuilds == 1
        assert not sched.failed
        faults.clear()
        # probe passed and the fresh engine thread serves again
        after = sched.generate(_req("after rebuild", max_new_tokens=8),
                               timeout=120)
        assert after.finish_reason in ("stop", "length")
        assert runner.allocator.check_invariants() == []
        assert 'localai_engine_rebuilds_total{model="rebuild"} 1' \
            in reg.render()
        # a healthy completion reset the incident budget
        assert sup.attempts == 0
    finally:
        sched.shutdown()
        wd.stop()


def test_rebuild_exhaustion_marks_model_failed(tiny):
    # every rebuild's probe dispatch is forced to fail (the allocator
    # reports exhaustion forever), so the supervisor must walk its whole
    # bounded ladder and then latch the failed state
    reg, wd, runner, sched, sup = _supervised(
        tiny, "doomed", max_rebuilds=2, probe_timeout_s=10.0)
    try:
        wedged = sched.submit(_req("wedge me", max_new_tokens=400))
        deadline = time.monotonic() + 60
        while wedged.t_first_token is None and time.monotonic() < deadline:
            time.sleep(0.02)
        faults.arm(FaultSpec(site="engine.drain", mode="hang",
                             delay_s=2.0, times=1))
        faults.arm(FaultSpec(site="paged.allocate", mode="exhaust",
                             times=0))  # unlimited: every probe fails
        wedged.result(90)
        assert wedged.finish_reason == "error"
        deadline = time.monotonic() + 90
        while not sched.failed and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sched.failed
        assert sched.rebuilds == 0               # no attempt succeeded
        assert 'localai_engine_failed{model="doomed"} 1' in reg.render()
        faults.clear()
        # failed engines refuse new work with a clean, instant error
        h = sched.submit(_req("too late", max_new_tokens=4))
        h.result(10)
        assert h.finish_reason == "error"
        assert sched.metrics()["engine_state"] == "failed"
    finally:
        sched.shutdown()
        wd.stop()


def test_supervisor_rejects_spec_engines(tiny):
    class FakeSched:
        spec = object()

    with pytest.raises(ValueError):
        EngineSupervisor(FakeSched())


def test_abandoned_engine_thread_exits_without_touching_new_state(tiny):
    """The fenced-off thread must exit once its blocked round-trip
    returns — and the rebuilt engine keeps serving afterwards."""
    reg, wd, runner, sched, sup = _supervised(tiny, "fence")
    try:
        old_thread = sched._thread
        wedged = sched.submit(_req("wedge for fence", max_new_tokens=400))
        deadline = time.monotonic() + 60
        while wedged.t_first_token is None and time.monotonic() < deadline:
            time.sleep(0.02)
        faults.arm(FaultSpec(site="engine.drain", mode="hang",
                             delay_s=1.5, times=1))
        wedged.result(90)
        deadline = time.monotonic() + 60
        while sched.rebuilds == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sched._thread is not old_thread
        old_thread.join(timeout=30)      # wakes from the hang, sees the
        assert not old_thread.is_alive()  # fence, exits without damage
        faults.clear()
        after = sched.generate(_req("post fence", max_new_tokens=8),
                               timeout=120)
        assert after.finish_reason in ("stop", "length")
    finally:
        sched.shutdown()
        wd.stop()


# -- zero overhead while disarmed ----------------------------------------


def test_disarmed_hot_path_is_one_boolean():
    """The contract perf_smoke relies on: with nothing armed, injection
    sites reduce to a module-attribute truthiness check."""
    assert faults.active() is False
    # the scheduler/runner sites all gate on this exact attribute; a
    # regression to per-dispatch env reads would show up here
    import localai_tpu.engine.paged as paged_mod
    import localai_tpu.engine.scheduler as sched_mod
    import localai_tpu.obs.compile as compile_mod

    for mod in (sched_mod, paged_mod, compile_mod):
        assert mod._faults is faults.registry or \
            mod._faults.__name__ == "localai_tpu.faults.registry"


def test_watchdog_remove_callback():
    wd = Watchdog(deadline=60.0, registry=Registry(), store=TraceStore())
    seen = []
    cb = seen.append
    wd.on_stall(cb)
    wd.remove_callback(cb)
    wd.remove_callback(cb)  # idempotent
    wd._fire(object())
    assert seen == []


def test_watchdog_reset_clears_leaked_armed_count():
    """rebuild() abandons a thread parked inside a guard it will never
    exit; reset() must drop the channel so the leaked armed count can't
    fire spurious stalls forever — and the abandoned thread's eventual
    disarm() on the recreated channel must be a harmless no-op."""
    wd = Watchdog(deadline=0.01, registry=Registry(), store=TraceStore())
    wd.arm("leaky")
    assert wd.check(now=time.monotonic() + 1.0)  # trips while armed
    wd.reset("leaky")
    assert not wd.stalled("leaky")
    assert wd.check(now=time.monotonic() + 100.0) == []  # nothing armed
    wd.disarm("leaky")  # the abandoned thread finally returns: no-op
    assert wd.status()["leaky"]["armed"] == 0


def test_supervisor_detach_stops_reacting(tiny):
    reg, wd, runner, sched, sup = _supervised(tiny, "detached")
    try:
        sup.detach()
        from localai_tpu.obs.watchdog import StallEvent

        sup._on_event(StallEvent(sched._wd_channel, "stall", 1.0))
        time.sleep(0.2)
        assert sched.rebuilds == 0
    finally:
        sched.shutdown()
        wd.stop()


def test_anatomy_phases_attribute_injected_delays(tiny):
    """Dispatch-anatomy attribution pin: a host-side sleep injected at
    the engine.dispatch site (loop body, BEFORE the device issue) must
    land in the record's gap/sched phases, while a delay injected at the
    engine.drain site (inside the result-fetch watchdog guard, AFTER the
    sync mark) must land in sync_ms — the decomposition blames the right
    side of the dispatch, and every record keeps the tiling invariant
    gap+sched+launch+sync <= dispatch_ms."""
    runner, sched = _engine(tiny, "anatomy")
    tokzr = ByteTokenizer()

    def run_one(text):
        h = sched.generate(GenRequest(
            prompt=tokzr.encode(text), max_new_tokens=16,
            temperature=0.0, ignore_eos=True))
        assert h.finish_reason == "length"

    def rows_after(base_ts):
        return [r for r in sched.flight.snapshot()
                if not r["compile"] and r["ts"] > base_ts]

    keeper = None
    try:
        # warm-up: compile-bearing dispatches are flagged (and excluded
        # from phases()); the injected runs below measure steady state
        run_one("warm me up")

        # keep a long request in flight across both injections: if the
        # engine loop goes idle between requests it drops its last-drain
        # anchor, and a pre-issue delay on the NEXT dispatch lands
        # nowhere (dt falls back to issue→drain) — steady decode keeps
        # every drain pipelined, so attribution is deterministic
        keeper = sched.submit(GenRequest(
            prompt=tokzr.encode("keeper"), max_new_tokens=224,
            temperature=0.0, ignore_eos=True))
        deadline = time.monotonic() + 30.0
        while keeper.t_first_token is None and time.monotonic() < deadline:
            time.sleep(0.005)
        assert keeper.t_first_token is not None

        # host-side: 120 ms sleep before a decode dispatch
        base = sched.flight.snapshot()[-1]["ts"]
        faults.arm(FaultSpec(site="engine.dispatch", mode="sleep",
                             delay_s=0.12, times=1, match="decode"))
        run_one("host-side delay")
        hit = max(rows_after(base),
                  key=lambda r: r["gap_ms"] + r["sched_ms"])
        assert hit["gap_ms"] + hit["sched_ms"] >= 100.0
        assert hit["sync_ms"] < 100.0
        faults.clear()

        # device-side: 120 ms delay at the result fetch
        base = sched.flight.snapshot()[-1]["ts"]
        faults.arm(FaultSpec(site="engine.drain", mode="sleep",
                             delay_s=0.12, times=1))
        run_one("device-side delay")
        hit = max(rows_after(base), key=lambda r: r["sync_ms"])
        assert hit["sync_ms"] >= 100.0
        keeper.cancel()
        keeper.result(timeout=30.0)
        keeper = None

        # the tiling invariant holds ring-wide (5e-3 slack: snapshot
        # rounds each phase column to 3 decimals)
        for r in sched.flight.snapshot():
            total = (r["gap_ms"] + r["sched_ms"] + r["launch_ms"]
                     + r["sync_ms"])
            assert total <= r["dispatch_ms"] + 5e-3, r
    finally:
        if keeper is not None:
            keeper.cancel()
        sched.shutdown()
