"""GGUF ingestion: reader, block dequantizers, and convert→serve.

Parity: /root/reference/pkg/model/initializers.go:271-407 (GGUF serving)
and core/config/guesser.go:13-246 (GGUF metadata autoconfig). The tests
write real GGUF binaries (v3 layout) with q4_0/q8_0/f16/f32 tensors and
verify decode against the quantization formulas, then convert a tiny
llama GGUF and serve it through the normal engine.
"""

import json
import struct
from pathlib import Path

import numpy as np

from localai_tpu.utils import gguf as G


# -- fixture writer: encode GGUF v3 with a few block formats ---------------

def _enc_q8_0(w: np.ndarray) -> bytes:
    blocks = w.reshape(-1, 32)
    out = b""
    for blk in blocks:
        d = np.abs(blk).max() / 127.0 or 1e-8
        q = np.clip(np.round(blk / d), -127, 127).astype(np.int8)
        out += np.float16(d).tobytes() + q.tobytes()
    return out


def _enc_q4_0(w: np.ndarray) -> bytes:
    blocks = w.reshape(-1, 32)
    out = b""
    for blk in blocks:
        amax_i = np.abs(blk).argmax()
        d = blk[amax_i] / -8.0 or 1e-8
        q = np.clip(np.round(blk / d + 8), 0, 15).astype(np.uint8)
        packed = (q[:16] | (q[16:] << 4)).astype(np.uint8)
        out += np.float16(d).tobytes() + packed.tobytes()
    return out


def _enc_f16(w):
    return w.astype(np.float16).tobytes()


def _enc_f32(w):
    return w.astype(np.float32).tobytes()


_ENCODERS = {G.Q8_0: _enc_q8_0, G.Q4_0: _enc_q4_0,
             G.F16: _enc_f16, G.F32: _enc_f32}


def _w_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("<Q", len(b)) + b


def _w_kv(key: str, vtype: int, value) -> bytes:
    out = _w_str(key) + struct.pack("<I", vtype)
    if vtype == 4:      # u32
        out += struct.pack("<I", value)
    elif vtype == 6:    # f32
        out += struct.pack("<f", value)
    elif vtype == 8:    # string
        out += _w_str(value)
    elif vtype == 9:    # array of strings
        out += struct.pack("<IQ", 8, len(value))
        for v in value:
            out += _w_str(v)
    else:
        raise ValueError(vtype)
    return out


def write_gguf(path: Path, metadata: list, tensors: dict):
    """tensors: name → (np_array, ggml_dtype). GGUF v3, alignment 32."""
    header = b"GGUF" + struct.pack("<IQQ", 3, len(tensors), len(metadata))
    kv = b"".join(_w_kv(*m) for m in metadata)
    blobs, infos, off = [], b"", 0
    for name, (arr, dt) in tensors.items():
        data = _ENCODERS[dt](arr)
        dims = list(reversed(arr.shape))  # ggml ne[]: innermost first
        infos += _w_str(name) + struct.pack("<I", len(dims))
        infos += b"".join(struct.pack("<Q", d) for d in dims)
        infos += struct.pack("<IQ", dt, off)
        off += len(data) + (-len(data)) % 32
        blobs.append(data)
    body = header + kv + infos
    pad = (-len(body)) % 32
    with open(path, "wb") as f:
        f.write(body + b"\0" * pad)
        for d in blobs:
            f.write(d + b"\0" * ((-len(d)) % 32))


def test_q8_0_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8, 64)).astype(np.float32)
    write_gguf(tmp_path / "t.gguf", [], {"x": (w, G.Q8_0)})
    gg = G.GGUFFile(tmp_path / "t.gguf")
    got = gg.load_tensor("x")
    assert got.shape == w.shape
    # error bounded by half a quantization step per element
    step = np.abs(w.reshape(-1, 32)).max(1, keepdims=True) / 127.0
    assert (np.abs((got - w).reshape(-1, 32)) <= step / 2 + 1e-6).all()


def test_q4_0_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    w = rng.normal(size=(4, 96)).astype(np.float32)
    write_gguf(tmp_path / "t.gguf", [], {"x": (w, G.Q4_0)})
    got = G.GGUFFile(tmp_path / "t.gguf").load_tensor("x")
    # q4_0 anchors the scale at the max-magnitude element (q=0); values at
    # the opposite extreme clip from 16 to 15, costing up to a FULL step
    step = np.abs(w.reshape(-1, 32)).max(1, keepdims=True) / 8.0
    assert (np.abs((got - w).reshape(-1, 32)) <= step + 1e-5).all()


def test_f16_f32_and_metadata(tmp_path):
    w32 = np.arange(12, dtype=np.float32).reshape(3, 4)
    w16 = (np.arange(8, dtype=np.float32) / 7).reshape(2, 4)
    write_gguf(
        tmp_path / "t.gguf",
        [("general.architecture", 8, "llama"),
         ("llama.block_count", 4, 2),
         ("llama.rope.freq_base", 6, 10000.0)],
        {"a": (w32, G.F32), "b": (w16, G.F16)},
    )
    gg = G.GGUFFile(tmp_path / "t.gguf")
    assert gg.metadata["general.architecture"] == "llama"
    assert gg.metadata["llama.block_count"] == 2
    np.testing.assert_array_equal(gg.load_tensor("a"), w32)
    np.testing.assert_allclose(gg.load_tensor("b"), w16, atol=1e-3)


def _tiny_llama_gguf(path: Path):
    """A real 2-layer llama GGUF (q8_0 attn/mlp weights, f32 norms)."""
    rng = np.random.default_rng(7)
    D, F, L, H, HKV, V = 64, 128, 2, 4, 2, 96
    hd = D // H

    def w(*shape):
        return (rng.normal(size=shape) * 0.05).astype(np.float32)

    def permute(x, heads):
        # llama.cpp's ACTUAL HF→GGUF permute (convert_hf_to_gguf.py):
        # reshape(head, 2, hd/2).swapaxes(1, 2) — the converter must invert
        # exactly this, so the fixture must not use the inverse form
        return (x.reshape(heads, 2, x.shape[0] // heads // 2, x.shape[1])
                .swapaxes(1, 2).reshape(x.shape))

    tensors = {"token_embd.weight": (w(V, D), G.Q8_0),
               "output_norm.weight": (np.ones(D, np.float32), G.F32),
               "output.weight": (w(V, D), G.Q8_0)}
    ref = {}
    for i in range(L):
        q, k = w(H * hd, D), w(HKV * hd, D)
        tensors[f"blk.{i}.attn_q.weight"] = (permute(q, H), G.Q8_0)
        tensors[f"blk.{i}.attn_k.weight"] = (permute(k, HKV), G.Q8_0)
        tensors[f"blk.{i}.attn_v.weight"] = (w(HKV * hd, D), G.Q8_0)
        tensors[f"blk.{i}.attn_output.weight"] = (w(D, H * hd), G.Q8_0)
        tensors[f"blk.{i}.ffn_gate.weight"] = (w(F, D), G.Q8_0)
        tensors[f"blk.{i}.ffn_up.weight"] = (w(F, D), G.Q8_0)
        tensors[f"blk.{i}.ffn_down.weight"] = (w(D, F), G.Q8_0)
        tensors[f"blk.{i}.attn_norm.weight"] = (
            np.ones(D, np.float32), G.F32)
        tensors[f"blk.{i}.ffn_norm.weight"] = (
            np.ones(D, np.float32), G.F32)
        ref[i] = (q, k)
    meta = [
        ("general.architecture", 8, "llama"),
        ("llama.vocab_size", 4, V),
        ("llama.embedding_length", 4, D),
        ("llama.feed_forward_length", 4, F),
        ("llama.block_count", 4, L),
        ("llama.attention.head_count", 4, H),
        ("llama.attention.head_count_kv", 4, HKV),
        ("llama.context_length", 4, 256),
        ("llama.rope.freq_base", 6, 10000.0),
        ("llama.attention.layer_norm_rms_epsilon", 6, 1e-5),
        ("tokenizer.ggml.tokens", 9, [f"<t{i}>" for i in range(V)]),
    ]
    write_gguf(path, meta, tensors)
    return ref


def test_convert_and_serve(tmp_path):
    """The VERDICT contract: a q8 GGUF fixture converts and serves."""
    src = tmp_path / "tiny.gguf"
    ref_qk = _tiny_llama_gguf(src)
    out = G.convert_gguf(src, tmp_path / "tiny", dtype="float32")

    cfg_json = json.loads((out / "config.json").read_text())
    assert cfg_json["num_hidden_layers"] == 2
    assert cfg_json["num_key_value_heads"] == 2
    assert (out / "tokenizer.json").exists()

    # q/k rows must be un-permuted back to the HF convention
    from safetensors import safe_open

    with safe_open(str(out / "model.safetensors"), framework="numpy") as h:
        q0 = h.get_tensor("model.layers.0.self_attn.q_proj.weight")
    step = np.abs(ref_qk[0][0]).max() / 127.0
    assert np.abs(q0 - ref_qk[0][0]).max() <= step + 1e-5

    # serve end to end through the normal engine
    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import resolve_model

    model = resolve_model(str(out), dtype="float32")
    assert model.cfg.num_layers == 2
    r = ModelRunner(model.cfg, model.params, num_slots=2, max_ctx=64,
                    prefill_buckets=[16])
    s = r.acquire_slot()
    toks = [r.admit(s, [1, 2, 3, 4], temperature=0.0)]
    toks += [int(r.step()[s]) for _ in range(4)]
    assert all(0 <= t < model.cfg.vocab_size for t in toks)


def test_convert_cli(tmp_path):
    from localai_tpu.cli.main import main

    src = tmp_path / "m.gguf"
    _tiny_llama_gguf(src)
    rc = main(["util", "convert", str(src), str(tmp_path / "out")])
    assert rc == 0
    assert (tmp_path / "out" / "model.safetensors").exists()


def test_q4k_q6k_structural(tmp_path):
    """K-quant decoders: correct sizes, finite values, scale response.
    (No independent encoder exists in this environment; formula-level
    verification is limited to structure + monotonicity in d.)"""
    rng = np.random.default_rng(3)
    for dt, bpb in ((G.Q4_K, 144), (G.Q6_K, 210)):
        blocks = 4
        raw = rng.integers(0, 256, blocks * bpb, dtype=np.uint8)
        raw = raw.tobytes()
        vals = G._DEQUANT[dt](raw, blocks)
        assert vals.shape == (blocks * 256,)
        assert np.isfinite(vals).all()


def test_unpermute_inverts_llamacpp_permute():
    """P (HF→GGUF) is not an involution; _unpermute must be its true
    inverse for every head_dim, not P applied twice."""
    rng = np.random.default_rng(5)
    for heads, hd in ((4, 8), (2, 16)):
        w = rng.normal(size=(heads * hd, 12)).astype(np.float32)
        permuted = (w.reshape(heads, 2, hd // 2, 12)
                    .swapaxes(1, 2).reshape(w.shape))
        assert not np.array_equal(permuted, w)
        np.testing.assert_array_equal(G._unpermute(permuted, heads), w)


def test_config_maps_rope_scaling_and_head_dim():
    """GGUF rope-scaling metadata and a non-default head_dim must survive
    into the emitted HF config (ADVICE r4: a Llama-3.1-class GGUF otherwise
    serves silently wrong RoPE beyond the base context)."""
    meta = {
        "general.architecture": "llama",
        "llama.embedding_length": 64,
        "llama.attention.head_count": 4,
        "llama.attention.head_count_kv": 2,
        "llama.attention.key_length": 32,          # != 64 // 4
        "llama.rope.scaling.type": "yarn",
        "llama.rope.scaling.factor": 4.0,
        "llama.rope.scaling.original_context_length": 4096,
        "llama.rope.scaling.attn_factor": 1.2,
    }
    cfg = G.gguf_to_hf_config(meta)
    assert cfg["head_dim"] == 32
    rs = cfg["rope_scaling"]
    assert rs["rope_type"] == "yarn"
    assert rs["factor"] == 4.0
    assert rs["original_max_position_embeddings"] == 4096
    assert rs["attention_factor"] == 1.2
    # default head_dim is omitted; unsupported scaling type is dropped
    cfg2 = G.gguf_to_hf_config({
        "general.architecture": "llama",
        "llama.embedding_length": 64,
        "llama.attention.head_count": 4,
        "llama.attention.key_length": 16,
        "llama.rope.scaling.type": "longrope",
    })
    assert "head_dim" not in cfg2      # 16 == 64 // 4, the derived default
    assert "rope_scaling" not in cfg2
    cfg3 = G.gguf_to_hf_config({
        "general.architecture": "llama",
        "llama.embedding_length": 64,
        "llama.attention.head_count": 4,
        "llama.attention.key_length": 16,
        "llama.rope.scaling.type": "linear",
        "llama.rope.scaling.factor": 2.0,
    })
    assert cfg3["rope_scaling"] == {"rope_type": "linear", "factor": 2.0}


def test_convert_moe_gguf(tmp_path):
    """A Mixtral-style GGUF (stacked ffn_*_exps + ffn_gate_inp router +
    expert_count metadata) converts to per-expert Mixtral tensor names and
    an MoE config the native loader serves."""
    rng = np.random.default_rng(11)
    D, F, L, H, HKV, V, E = 32, 48, 2, 4, 2, 64, 4
    hd = D // H

    def w(*shape):
        return (rng.normal(size=shape) * 0.05).astype(np.float32)

    tensors = {"token_embd.weight": (w(V, D), G.F32),
               "output_norm.weight": (np.ones(D, np.float32), G.F32),
               "output.weight": (w(V, D), G.F32)}
    for i in range(L):
        tensors[f"blk.{i}.attn_q.weight"] = (w(H * hd, D), G.F32)
        tensors[f"blk.{i}.attn_k.weight"] = (w(HKV * hd, D), G.F32)
        tensors[f"blk.{i}.attn_v.weight"] = (w(HKV * hd, D), G.F32)
        tensors[f"blk.{i}.attn_output.weight"] = (w(D, H * hd), G.F32)
        tensors[f"blk.{i}.ffn_gate_inp.weight"] = (w(E, D), G.F32)
        tensors[f"blk.{i}.ffn_gate_exps.weight"] = (w(E, F, D), G.F32)
        tensors[f"blk.{i}.ffn_up_exps.weight"] = (w(E, F, D), G.F32)
        tensors[f"blk.{i}.ffn_down_exps.weight"] = (w(E, D, F), G.F32)
        tensors[f"blk.{i}.attn_norm.weight"] = (np.ones(D, np.float32), G.F32)
        tensors[f"blk.{i}.ffn_norm.weight"] = (np.ones(D, np.float32), G.F32)
    meta = [
        ("general.architecture", 8, "llama"),
        ("llama.vocab_size", 4, V),
        ("llama.embedding_length", 4, D),
        ("llama.feed_forward_length", 4, F),
        ("llama.block_count", 4, L),
        ("llama.attention.head_count", 4, H),
        ("llama.attention.head_count_kv", 4, HKV),
        ("llama.expert_count", 4, E),
        ("llama.expert_used_count", 4, 2),
        ("llama.context_length", 4, 128),
        ("llama.rope.freq_base", 6, 10000.0),
        ("llama.attention.layer_norm_rms_epsilon", 6, 1e-5),
    ]
    src = tmp_path / "moe.gguf"
    write_gguf(src, meta, tensors)
    out = G.convert_gguf(src, tmp_path / "moe", dtype="float32")

    cfg_json = json.loads((out / "config.json").read_text())
    assert cfg_json["num_local_experts"] == E
    assert cfg_json["num_experts_per_tok"] == 2

    from safetensors import safe_open

    with safe_open(str(out / "model.safetensors"), framework="numpy") as h:
        names = set(h.keys())
    assert "model.layers.0.block_sparse_moe.gate.weight" in names
    assert "model.layers.1.block_sparse_moe.experts.3.w2.weight" in names

    from localai_tpu.models.loader import load_llama_params

    cfg, params = load_llama_params(out, dtype="float32")
    assert cfg.num_experts == E
    assert params["layers"]["w_gate"].shape == (L, E, D, F)
    # the stacked GGUF expert slice equals the per-expert HF tensor
    exp0 = tensors["blk.0.ffn_gate_exps.weight"][0][0]     # [F, D]
    np.testing.assert_allclose(
        np.asarray(params["layers"]["w_gate"][0, 0]), exp0.T, atol=1e-6)
