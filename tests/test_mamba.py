"""Mamba SSM models: numerical parity against transformers' torch slow
path on tiny random checkpoints, O(1)-state decode equivalence, and
serving through the normal endpoints (parity:
/root/reference/backend/python/mamba/backend.py)."""

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import MambaConfig as HFMambaConfig  # noqa: E402
from transformers import MambaForCausalLM  # noqa: E402

from localai_tpu.models.mamba import (  # noqa: E402
    MambaConfig,
    MambaLM,
    forward_prefill,
    forward_step,
    resolve_mamba,
)

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    state_size=8,
    conv_kernel=4,
    num_hidden_layers=2,
    time_step_rank=4,
    use_cache=True,
)


def _torch_model(seed=0):
    torch.manual_seed(seed)
    hf_cfg = HFMambaConfig(**TINY)
    model = MambaForCausalLM(hf_cfg).eval()
    return hf_cfg, model


def _params_from(model):
    import jax.numpy as jnp

    return {k: jnp.asarray(v.detach().numpy())
            for k, v in model.state_dict().items()}


def test_prefill_logits_match_torch():
    hf_cfg, model = _torch_model()
    cfg = MambaConfig.from_hf(hf_cfg.to_dict())
    params = _params_from(model)
    ids = torch.tensor([[3, 14, 15, 9, 26, 5]])
    with torch.no_grad():
        want = model(ids).logits.numpy()
    got = np.asarray(forward_prefill(params, cfg, ids.numpy())[0])
    np.testing.assert_allclose(got, want, atol=2e-4)


def test_step_matches_prefill():
    """Decode with rolling conv + SSM states is bit-equivalent to
    re-running the full prefix — the O(1)-state contract."""
    hf_cfg, model = _torch_model(seed=2)
    cfg = MambaConfig.from_hf(hf_cfg.to_dict())
    params = _params_from(model)
    prefix = np.asarray([[7, 21, 3, 44]])
    logits, states = forward_prefill(params, cfg, prefix)
    nxt = np.asarray([11], np.int32)
    step_logits, states = forward_step(params, cfg, nxt, states)
    full = forward_prefill(
        params, cfg, np.concatenate([prefix, nxt[None]], 1))[0]
    np.testing.assert_allclose(
        np.asarray(step_logits)[0], np.asarray(full)[0, -1], atol=2e-4)


def test_generate_greedy_matches_torch():
    hf_cfg, model = _torch_model(seed=3)
    cfg = MambaConfig.from_hf(hf_cfg.to_dict())
    lm = MambaLM(cfg, _params_from(model), tokenizer=None)
    prompt = [5, 9, 13]
    with torch.no_grad():
        want = model.generate(
            torch.tensor([prompt]), max_new_tokens=8, do_sample=False,
        ).numpy()[0][len(prompt):]
    got = lm.generate(prompt, max_new_tokens=8, temperature=0.0,
                      eos_ids=set())
    assert got == [int(t) for t in want]


def test_debug_preset_generates():
    lm = resolve_mamba("debug:mamba-tiny")
    toks = lm.generate(list(b"hello"), max_new_tokens=6, temperature=0.0)
    assert len(toks) <= 6
    # deterministic
    assert toks == lm.generate(list(b"hello"), max_new_tokens=6,
                               temperature=0.0)


def test_serving_via_http(tmp_path):
    """`backend: mamba` (autodetected from debug ref name) serves chat."""
    import httpx
    from test_api import _ServerThread, make_state

    (tmp_path / "m.yaml").write_text(
        "name: m\nmodel: 'debug:mamba-tiny'\n"
        "parameters: {temperature: 0.0, max_tokens: 8}\n"
    )
    srv = _ServerThread(make_state(tmp_path))
    try:
        assert srv.state.loader.get("m").backend == "mamba"
        with httpx.Client(base_url=srv.base, timeout=120.0) as c:
            r = c.post("/v1/chat/completions", json={
                "model": "m",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 6,
            })
            assert r.status_code == 200, r.text
            body = r.json()
            assert body["choices"][0]["finish_reason"] in ("stop",
                                                           "length")
            # streaming path
            with c.stream("POST", "/v1/chat/completions", json={
                "model": "m",
                "messages": [{"role": "user", "content": "stream"}],
                "max_tokens": 6, "stream": True,
            }) as s:
                frames = [ln for ln in s.iter_lines()
                          if ln.startswith("data: ")]
            assert frames[-1] == "data: [DONE]"
    finally:
        srv.stop()


def test_hf_checkpoint_dir_loads(tmp_path):
    from safetensors.numpy import save_file

    hf_cfg, model = _torch_model(seed=4)
    d = tmp_path / "mamba-ckpt"
    d.mkdir()
    save_file({k: v.detach().numpy().copy()
               for k, v in model.state_dict().items()},
              d / "model.safetensors")
    (d / "config.json").write_text(json.dumps(
        {"model_type": "mamba", **{k: v for k, v in
                                   hf_cfg.to_dict().items()
                                   if isinstance(v, (int, float, str,
                                                     bool, list))}}))
    # byte-ish vocab tokenizer stand-in
    (d / "tokenizer.json").write_text(json.dumps({
        "version": "1.0", "truncation": None, "padding": None,
        "added_tokens": [], "normalizer": None,
        "pre_tokenizer": {"type": "Whitespace"},
        "post_processor": None, "decoder": None,
        "model": {"type": "WordLevel",
                  "vocab": {"a": 1, "b": 2, "[UNK]": 0},
                  "unk_token": "[UNK]"},
    }))
    lm = resolve_mamba(str(d))
    toks = lm.generate([1, 2], max_new_tokens=4, temperature=0.0,
                       eos_ids=set())
    assert len(toks) == 4
