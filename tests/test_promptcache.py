"""Disk prompt-KV persistence (prompt_cache_path / _all / _ro).

Parity: /root/reference/core/config/backend_config.go:120-122 — llama.cpp
persists session KV to disk and reloads it to skip recomputing a shared
prefix across process restarts. The contract test: a COLD-START scheduler
(fresh runner, same cache dir) must reuse the stored prefix and produce
identical greedy output.
"""

import numpy as np
import pytest

from localai_tpu.engine.promptcache import PromptKVCache
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.engine.scheduler import GenRequest, Scheduler
from localai_tpu.models.registry import resolve_model


@pytest.fixture(scope="module")
def small():
    return resolve_model("debug:small", dtype="float32")


def _mk(model, **kw):
    kw.setdefault("kv_dtype", "float32")
    return ModelRunner(model.cfg, model.params, num_slots=2, max_ctx=128,
                       prefill_buckets=[32, 64], **kw)


def _sched(model, cache, **kw):
    return Scheduler(_mk(model, **kw.pop("runner_kw", {})), model.tokenizer,
                     multi_step=4, prompt_cache=cache, **kw)


PROMPT = list(b"the shared system prompt that should be cached once")


def test_cold_start_reuses_disk_cache(small, tmp_path):
    cache = PromptKVCache(tmp_path / "pc")
    s1 = _sched(small, cache)
    try:
        ref = s1.generate(GenRequest(prompt=PROMPT, max_new_tokens=8,
                                     temperature=0.0, ignore_eos=True),
                          timeout=120).token_ids
    finally:
        s1.shutdown()
    assert cache.stores == 1

    # brand-new runner + scheduler (cold start), same cache dir
    cache2 = PromptKVCache(tmp_path / "pc")
    s2 = _sched(small, cache2)
    try:
        got = s2.generate(GenRequest(prompt=PROMPT, max_new_tokens=8,
                                     temperature=0.0, ignore_eos=True),
                          timeout=120).token_ids
        assert cache2.hits == 1
        # the runner really skipped prefix recompute
        assert s2.runner.total_prefix_reused >= len(PROMPT) - 33
    finally:
        s2.shutdown()
    assert got == ref


def test_prompt_cache_all_stores_generation(small, tmp_path):
    cache = PromptKVCache(tmp_path / "pc")
    s1 = _sched(small, cache, prompt_cache_all=True)
    try:
        h = s1.generate(GenRequest(prompt=PROMPT, max_new_tokens=8,
                                   temperature=0.0, ignore_eos=True),
                        timeout=120)
    finally:
        s1.shutdown()
    key = next(iter(cache._index))
    stored = cache._index[key]
    # prompt + generated tokens (minus the final unfed one) are all cached
    assert len(stored) > len(PROMPT)


def test_read_only_cache_never_writes(small, tmp_path):
    cache = PromptKVCache(tmp_path / "pc", read_only=True)
    s1 = _sched(small, cache)
    try:
        s1.generate(GenRequest(prompt=PROMPT, max_new_tokens=4,
                               temperature=0.0, ignore_eos=True), timeout=120)
    finally:
        s1.shutdown()
    assert cache.stores == 0
    assert not (tmp_path / "pc").exists() or not list(
        (tmp_path / "pc").glob("*.npz")
    )


def test_int8_kv_roundtrip(small, tmp_path):
    """Scaled-int8 caches persist their scales and reload bit-exact."""
    cache = PromptKVCache(tmp_path / "pc")
    s1 = _sched(small, cache, runner_kw={"kv_dtype": "int8"})
    try:
        ref = s1.generate(GenRequest(prompt=PROMPT, max_new_tokens=6,
                                     temperature=0.0, ignore_eos=True),
                          timeout=120).token_ids
    finally:
        s1.shutdown()

    cache2 = PromptKVCache(tmp_path / "pc")
    s2 = _sched(small, cache2, runner_kw={"kv_dtype": "int8"})
    try:
        got = s2.generate(GenRequest(prompt=PROMPT, max_new_tokens=6,
                                     temperature=0.0, ignore_eos=True),
                          timeout=120).token_ids
        assert cache2.hits == 1
    finally:
        s2.shutdown()
    assert got == ref


def test_dtype_mismatch_falls_back(small, tmp_path):
    """An int8 entry must not load into a bf16 cache — admit falls back to
    a full prefill instead of corrupting the slot."""
    cache = PromptKVCache(tmp_path / "pc")
    s1 = _sched(small, cache, runner_kw={"kv_dtype": "int8"})
    try:
        s1.generate(GenRequest(prompt=PROMPT, max_new_tokens=4,
                               temperature=0.0, ignore_eos=True), timeout=120)
    finally:
        s1.shutdown()

    cache2 = PromptKVCache(tmp_path / "pc")
    s2 = _sched(small, cache2)  # float32 KV
    try:
        h = s2.generate(GenRequest(prompt=PROMPT, max_new_tokens=4,
                                   temperature=0.0, ignore_eos=True),
                        timeout=120)
        assert len(h.token_ids) == 4
        assert s2.runner.total_prefix_reused == 0
    finally:
        s2.shutdown()


def test_bf16_kv_roundtrip_bitview(small, tmp_path):
    """bfloat16 rows survive the uint16 bit-view serialization."""
    model = resolve_model("debug:small")  # bf16 default
    cache = PromptKVCache(tmp_path / "pc")
    r1 = ModelRunner(model.cfg, model.params, num_slots=2, max_ctx=128,
                     prefill_buckets=[64])
    s = r1.acquire_slot()
    r1.admit(s, PROMPT, temperature=0.0)
    blob = r1.export_prefix(s)
    cache.store(PROMPT, blob)

    r2 = ModelRunner(model.cfg, model.params, num_slots=2, max_ctx=128,
                     prefill_buckets=[64])
    hit = cache.lookup(PROMPT + [5])
    assert hit is not None
    s2 = r2.acquire_slot()
    assert r2.load_prefix(s2, hit.arrays, hit.n)
    k1 = np.asarray(r1.kv.k[:, s, :, :hit.n].astype(np.float32))
    k2 = np.asarray(r2.kv.k[:, s2, :, :hit.n].astype(np.float32))
    np.testing.assert_array_equal(k1, k2)


def test_eviction_caps_entries(small, tmp_path):
    cache = PromptKVCache(tmp_path / "pc", max_entries=2, min_prefix=4)
    r = _mk(small)
    s = r.acquire_slot()
    for i in range(4):
        prompt = [10 + i] * 8
        r.admit(s, prompt, temperature=0.0)
        cache.store(prompt, r.export_prefix(s, 8))
        r.release(s)
        s = r.acquire_slot()
    assert len(cache._index) == 2
    assert len(list((tmp_path / "pc").glob("*.npz"))) == 2
