"""Native C runtime components (localai_tpu/native): on-demand compile,
parity with the Python fallback, graceful degradation without a
compiler."""

import numpy as np

from localai_tpu.functions import constraint as cst
from localai_tpu.functions.constraint import TokenTrie, cached_dfa
from localai_tpu.utils.tokenizer import ByteTokenizer


def test_native_module_compiles_and_loads():
    from localai_tpu.native import load

    lib = load("fsm_walk")
    assert lib is not None, "cc/gcc exist in this image; compile must work"
    # second load hits the cache
    assert load("fsm_walk") is lib


def test_walk_native_matches_numpy():
    """The C single-pass walk must be bit-identical to the per-level
    numpy gather for every reachable DFA state."""
    dfa = cached_dfa(r'\{"name": "[a-z]{1,8}"\}')
    trie = TokenTrie.for_tokenizer(ByteTokenizer())

    def numpy_walk(state):
        states = np.zeros(trie.n_nodes, dtype=np.int32)
        states[0] = state
        cls = dfa.byte_class
        for nodes in trie.levels:
            states[nodes] = dfa.trans[
                states[trie.parent[nodes]], cls[trie.edge[nodes]]
            ]
        return states

    assert cst._native_fsm() is not None
    for state in range(dfa.trans.shape[0]):
        np.testing.assert_array_equal(trie.walk(dfa, state),
                                      numpy_walk(state))


def test_fallback_without_compiler(monkeypatch, tmp_path):
    """No compiler → load() returns None and the constraint machinery
    still works through the numpy path."""
    import localai_tpu.native as native

    monkeypatch.setattr(native, "_cache", {})
    monkeypatch.setenv("LOCALAI_NATIVE_CACHE", str(tmp_path))
    monkeypatch.setenv("PATH", str(tmp_path))  # no cc/gcc/clang here
    assert native.load("fsm_walk") is None

    monkeypatch.setattr(cst, "_native_lib", None)  # force numpy path
    dfa = cached_dfa(r"[ab]{2}")
    trie = TokenTrie.for_tokenizer(ByteTokenizer())
    states = trie.walk(dfa, dfa.start)
    assert states.shape == (trie.n_nodes,)
    monkeypatch.setattr(cst, "_native_lib", cst._NATIVE_SENTINEL)


def test_constrained_generation_uses_native(tmp_path):
    """End-to-end: grammar-constrained decode through the engine with the
    native walk produces schema-valid output (same contract as the
    existing scheduler grammar test)."""
    import json

    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.engine.scheduler import GenRequest, Scheduler
    from localai_tpu.functions import constraint_for_schema
    from localai_tpu.models.registry import resolve_model

    assert cst._native_fsm() is not None
    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=96,
                         prefill_buckets=[16, 32], kv_dtype="float32")
    sched = Scheduler(runner, ByteTokenizer())
    try:
        schema = {"type": "object",
                  "properties": {"x": {"type": "integer"}}}
        c = constraint_for_schema(schema, ByteTokenizer())
        h = sched.generate(GenRequest(
            prompt=ByteTokenizer().encode("emit json"),
            max_new_tokens=60, temperature=0.8, seed=5, constraint=c,
        ), timeout=120)
        json.loads(h.text)  # must parse
    finally:
        sched.shutdown()


def test_mask_native_matches_numpy():
    """fsm_mask (fused C mask build) is bit-identical to the numpy path
    for every DFA state."""
    from localai_tpu.functions.constraint import (
        DFA,
        NEG,
        FSMConstraint,
    )

    tok = ByteTokenizer()
    dfa = cached_dfa(r'\{"x": [0-9]{1,3}\}')
    assert cst._native_fsm() is not None
    c = FSMConstraint(dfa, tok)
    for state in range(dfa.trans.shape[0]):
        got = np.array(c._row(state))
        finals = c.trie.walk(dfa, state)
        tok_final = finals[c.trie.leaf_of_token]
        allowed = c.trie.token_ok & (tok_final != DFA.DEAD)
        want = np.where(allowed, np.float32(0.0), NEG).astype(np.float32)
        if bool(dfa.accept[state]) or not allowed.any():
            for e in c.eos_ids:
                want[e] = 0.0
        np.testing.assert_array_equal(got, want)
