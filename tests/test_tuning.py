"""Per-shape kernel tuning table (ops.tuning) + its consumers.

Pins: JSON round-trip, corrupt-file → defaults (never an error), the
select_paged_attn_impl consult order (explicit > env > tuned > backend
default, hard shape gates over everything), and the runner picking up
tuned block_tokens / num_buffers at construction.
"""

import json

import pytest

from localai_tpu import ops
from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models.registry import resolve_model
from localai_tpu.ops import tuning


@pytest.fixture(autouse=True)
def _fresh_table(monkeypatch, tmp_path):
    """Each test gets its own cache path and a cleared singleton."""
    path = tmp_path / "tuning.json"
    monkeypatch.setenv(tuning.ENV_CACHE, str(path))
    tuning.reset()
    yield path
    tuning.reset()


def test_table_roundtrip(_fresh_table):
    t = tuning.TuningTable(path=str(_fresh_table))
    key = tuning.shape_key(128, 8, "int8", 2)
    assert key == "hd128_kv8_int8_tp2"
    t.put(key, tuning.TuneEntry(impl="pallas", block_tokens=64,
                                num_buffers=3, us=412.5))
    t.save()
    back = tuning.TuningTable.load(str(_fresh_table))
    e = back.lookup(key)
    assert e == tuning.TuneEntry(impl="pallas", block_tokens=64,
                                 num_buffers=3, us=412.5)
    # the singleton sees the saved file too
    assert tuning.lookup(128, 8, "int8", 2) == e
    assert tuning.lookup(128, 8, "int4", 2) is None


def test_corrupt_file_falls_back_to_defaults(_fresh_table):
    _fresh_table.write_text("{ not json !!!")
    t = tuning.TuningTable.load(str(_fresh_table))
    assert t.entries == {}
    assert tuning.lookup(128, 8, "int8", 1) is None  # no crash

    # a valid file with one malformed entry drops ONLY that entry
    _fresh_table.write_text(json.dumps({
        "hd128_kv8_int8_tp1": {"impl": "pallas", "block_tokens": 64},
        "bad1": {"impl": "warp-drive"},
        "bad2": {"block_tokens": "lots"},
        "bad3": [1, 2, 3],
    }))
    tuning.reset()
    t = tuning.TuningTable.load(str(_fresh_table))
    assert set(t.entries) == {"hd128_kv8_int8_tp1"}


def test_missing_and_disabled_paths(_fresh_table, monkeypatch):
    assert tuning.TuningTable.load(str(_fresh_table)).entries == {}
    monkeypatch.setenv(tuning.ENV_CACHE, "0")
    tuning.reset()
    assert tuning.cache_path() == ""
    assert tuning.lookup(128, 8, "int8", 1) is None


def _write_table(path, key, **entry):
    path.write_text(json.dumps({key: entry}))
    tuning.reset()


def test_select_consults_tuned_impl(_fresh_table):
    """A tuned impl drives the auto decision on the shape it was measured
    for — and ONLY that shape. Off-TPU a tuned "pallas" is IGNORED (it
    would mean the Pallas interpreter — the table is an automatic source,
    not an interpret opt-in), while a tuned "xla" is honored anywhere."""
    _write_table(_fresh_table, tuning.shape_key(128, 8, "bfloat16", 1),
                 impl="pallas", block_tokens=64)
    impl, interpret, why = ops.select_paged_attn_impl(
        "auto", num_heads=32, num_kv_heads=8, head_dim=128,
        block_tokens=64, kv_dtype="bfloat16", backend="tpu")
    assert (impl, interpret, why) == ("pallas", False, "")
    # the same tuned "pallas" off-TPU falls back to the backend default
    impl, interpret, _ = ops.select_paged_attn_impl(
        "auto", num_heads=32, num_kv_heads=8, head_dim=128,
        block_tokens=64, kv_dtype="bfloat16", backend="cpu")
    assert (impl, interpret) == ("xla", False)
    # a tuned "xla" overrides the TPU default
    _write_table(_fresh_table, tuning.shape_key(128, 8, "bfloat16", 1),
                 impl="xla")
    impl, _, _ = ops.select_paged_attn_impl(
        "auto", num_heads=32, num_kv_heads=8, head_dim=128,
        block_tokens=64, kv_dtype="bfloat16", backend="tpu")
    assert impl == "xla"
    # a different shape misses the table → backend default (xla on cpu)
    impl, _, _ = ops.select_paged_attn_impl(
        "auto", num_heads=32, num_kv_heads=4, head_dim=128,
        block_tokens=64, kv_dtype="bfloat16", backend="cpu")
    assert impl == "xla"


def test_select_reuses_caller_tuned_entry(_fresh_table):
    """A caller-supplied TuneEntry (the runner's single-lookup path)
    bypasses the internal table consult entirely."""
    from localai_tpu.obs.metrics import REGISTRY

    def lookups():
        s = REGISTRY.autotune_lookups._series  # noqa: SLF001
        return sum(s.values())

    n0 = lookups()
    impl, _, _ = ops.select_paged_attn_impl(
        "auto", num_heads=32, num_kv_heads=8, head_dim=128,
        block_tokens=64, kv_dtype="bfloat16", backend="tpu",
        tuned=tuning.TuneEntry(impl="xla"))
    assert impl == "xla"
    impl, _, _ = ops.select_paged_attn_impl(
        "auto", num_heads=32, num_kv_heads=8, head_dim=128,
        block_tokens=64, kv_dtype="bfloat16", backend="tpu",
        tuned=tuning.TuneEntry())  # empty = looked up, no preference
    assert impl == "pallas"
    assert lookups() == n0  # no second receipt from either call


def test_hard_gates_override_tuned_pallas(_fresh_table):
    """A tuned "pallas" on a Mosaic-untileable shape still falls back —
    the table can prefer, never force, a kernel the hardware rejects."""
    _write_table(_fresh_table, tuning.shape_key(100, 8, "bfloat16", 1),
                 impl="pallas")
    impl, _, why = ops.select_paged_attn_impl(
        "auto", num_heads=32, num_kv_heads=8, head_dim=100,
        block_tokens=64, kv_dtype="bfloat16", backend="tpu")
    assert impl == "xla" and "tileable" in why


def test_env_override_beats_tuned(_fresh_table, monkeypatch):
    _write_table(_fresh_table, tuning.shape_key(128, 8, "bfloat16", 1),
                 impl="pallas")
    monkeypatch.setenv("LOCALAI_PAGED_ATTN_IMPL", "xla")
    impl, _, _ = ops.select_paged_attn_impl(
        "auto", num_heads=32, num_kv_heads=8, head_dim=128,
        block_tokens=64, kv_dtype="bfloat16", backend="tpu")
    assert impl == "xla"


def test_explicit_request_beats_everything(_fresh_table):
    _write_table(_fresh_table, tuning.shape_key(128, 8, "bfloat16", 1),
                 impl="pallas")
    impl, _, _ = ops.select_paged_attn_impl(
        "xla", num_heads=32, num_kv_heads=8, head_dim=128,
        block_tokens=64, kv_dtype="bfloat16", backend="tpu")
    assert impl == "xla"


def test_runner_consults_tuned_block_tokens(_fresh_table, monkeypatch):
    model = resolve_model("debug:tiny", dtype="float32")
    cfg = model.cfg
    _write_table(_fresh_table,
                 tuning.shape_key(cfg.hd, cfg.num_kv_heads, "float32", 1),
                 impl="xla", block_tokens=32, num_buffers=3)
    monkeypatch.delenv("LOCALAI_KV_BLOCK_TOKENS", raising=False)
    r = ModelRunner(cfg, model.params, num_slots=2, max_ctx=128,
                    prefill_buckets=[64], kv_dtype="float32", paged=True)
    assert r.block_tokens == 32
    assert r.paged_num_buffers == 3
    # explicit kwarg wins over the table
    r2 = ModelRunner(cfg, model.params, num_slots=2, max_ctx=128,
                     prefill_buckets=[64], kv_dtype="float32", paged=True,
                     kv_block_tokens=16)
    assert r2.block_tokens == 16
    # env wins over the table too
    monkeypatch.setenv("LOCALAI_KV_BLOCK_TOKENS", "64")
    r3 = ModelRunner(cfg, model.params, num_slots=2, max_ctx=128,
                     prefill_buckets=[64], kv_dtype="float32", paged=True)
    assert r3.block_tokens == 64


def test_lookup_metric_receipts(_fresh_table):
    from localai_tpu.obs.metrics import REGISTRY

    _write_table(_fresh_table, tuning.shape_key(64, 8, "int8", 1),
                 impl="xla", block_tokens=64)

    def total(result):
        return REGISTRY.autotune_lookups._series.get(  # noqa: SLF001
            (("result", result),), 0.0)

    h0, m0 = total("hit"), total("miss")
    assert tuning.lookup(64, 8, "int8", 1) is not None
    assert tuning.lookup(64, 8, "int4", 1) is None
    assert total("hit") == h0 + 1
    assert total("miss") == m0 + 1


def test_autotune_smoke_cli(tmp_path, monkeypatch):
    """The CI smoke path end-to-end: a tiny sweep produces a loadable
    table whose entries the gate machinery accepts."""
    import tools.autotune as at

    out = tmp_path / "table.json"
    monkeypatch.setenv(tuning.ENV_CACHE, str(out))
    tuning.reset()
    rc = at.main(["--preset", "tiny", "--kv-dtypes", "float32",
                  "--tp", "1", "--blocks", "8", "--buffers", "2",
                  "--ctx", "32", "--out", str(out)])
    assert rc == 0
    table = tuning.TuningTable.load(str(out))
    key = tuning.shape_key(16, 2, "float32", 1)
    entry = table.lookup(key)
    assert entry is not None and entry.block_tokens == 8
    assert entry.impl in ("xla", "pallas")
