"""Pallas int8-dequant matmul kernel (ops.qmatmul) — exactness vs the XLA
w8 path and engine-level equivalence under the env opt-in."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.models import quant as qnt
from localai_tpu.ops import qmatmul


@pytest.fixture()
def w8_kernel_env():
    # the kernel block is per-tensor now (QuantizedTensor.kernel_ok, set by
    # meshed runners on THEIR params) — a meshed runner elsewhere in the
    # process can no longer disable the kernel for this test's tensors
    os.environ["LOCALAI_W8_KERNEL"] = "interpret"
    yield
    os.environ.pop("LOCALAI_W8_KERNEL", None)


@pytest.mark.parametrize("M,K,N", [(8, 256, 384), (1, 128, 128),
                                   (16, 512, 256)])
def test_matches_xla_w8(M, K, N):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(M, K)), jnp.float32)
    w = rng.normal(size=(K, N)).astype(np.float32) * 0.02
    qt = qnt.quantize_tensor(w, axis=0)
    ref = np.asarray(qnt.matmul(x, qt))
    out = np.asarray(qmatmul.w8_matmul(x, qt.q, qt.scale, interpret=True))
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_matches_xla_w8_transposed():
    """The tied-embedding lm_head orientation: x @ table.T, per-row scale."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 256)), jnp.float32)
    tbl = rng.normal(size=(384, 256)).astype(np.float32) * 0.02
    qt = qnt.quantize_tensor(tbl, axis=1)
    ref = np.asarray(qnt.matmul_t(x, qt))
    out = np.asarray(qmatmul.w8_matmul(x, qt.q, qt.scale,
                                       transpose_w=True, interpret=True))
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_eligibility_gates():
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.integers(-127, 127, (256, 384)), jnp.int8)
    s = jnp.ones(384, jnp.float32)
    assert qmatmul.eligible((8, 256), q, s, False)
    assert not qmatmul.eligible((8, 100), q, s, False)      # K mismatch
    assert not qmatmul.eligible((512, 256), q, s, False)    # prefill-sized M
    q_odd = jnp.asarray(rng.integers(-127, 127, (250, 384)), jnp.int8)
    assert not qmatmul.eligible((8, 250), q_odd, s, False)  # unaligned K
    s2 = jnp.ones((2, 384), jnp.float32)
    assert not qmatmul.eligible((8, 256), q, s2, False)     # grouped scale


def test_engine_greedy_identical_under_kernel(w8_kernel_env):
    """int8 serving with the kernel enabled produces the same greedy
    stream as the XLA w8 path (kernel-aligned dims: D/N multiples of 128)."""
    import dataclasses

    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models import llama as mdl
    from localai_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                      intermediate_size=256, num_layers=2, num_heads=2,
                      num_kv_heads=2, max_position_embeddings=256,
                      tie_word_embeddings=True, dtype="float32")
    params = mdl.init_params(jax.random.key(0), cfg)
    q = qnt.quantize_params(params)
    prompt = list(range(1, 30))

    def greedy():
        r = ModelRunner(dataclasses.replace(cfg, dtype="float32"), q,
                        num_slots=2, max_ctx=128, prefill_buckets=[32],
                        kv_dtype="float32")
        s = r.acquire_slot()
        return [r.admit(s, prompt, temperature=0.0)] + \
            [int(r.step()[s]) for _ in range(6)]

    with_kernel = greedy()
    os.environ["LOCALAI_W8_KERNEL"] = ""
    without = greedy()
    assert with_kernel == without


def test_w4_matches_xla(w8_kernel_env):
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(8, 256)), jnp.float32)
    w = rng.normal(size=(256, 384)).astype(np.float32) * 0.02
    qt = qnt.quantize_tensor4(w, axis=0, group=128)
    os.environ["LOCALAI_W8_KERNEL"] = ""
    ref = np.asarray(qnt.matmul(x, qt))
    out = np.asarray(qmatmul.w4_matmul(x, qt.q, qt.scale,
                                       interpret=True))
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
    # env-gated routing through qnt.matmul
    os.environ["LOCALAI_W8_KERNEL"] = "interpret"
    out2 = np.asarray(qnt.matmul(x, qt))
    np.testing.assert_allclose(out2, ref, atol=1e-4, rtol=1e-4)


def test_w4_eligibility():
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.integers(-7, 7, (256, 384)), jnp.int4)
    s = jnp.ones((2, 384), jnp.float32)       # group 128
    assert qmatmul.w4_eligible((8, 256), q, s)
    s_fine = jnp.ones((8, 384), jnp.float32)  # group 32: not 128-aligned
    assert not qmatmul.w4_eligible((8, 256), q, s_fine)
    assert not qmatmul.w4_eligible((512, 256), q, s)  # prefill-sized M


def test_engine_greedy_identical_under_w4_kernel(w8_kernel_env):
    """int4 serving with the kernel enabled matches the XLA w4 path."""
    import dataclasses

    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models import llama as mdl
    from localai_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                      intermediate_size=256, num_layers=2, num_heads=2,
                      num_kv_heads=2, max_position_embeddings=256,
                      tie_word_embeddings=True, dtype="float32")
    params = mdl.init_params(jax.random.key(2), cfg)
    q = qnt.quantize_params(params, "int4", group=128)
    prompt = list(range(1, 30))

    def greedy():
        r = ModelRunner(dataclasses.replace(cfg, dtype="float32"), q,
                        num_slots=2, max_ctx=128, prefill_buckets=[32],
                        kv_dtype="float32")
        s = r.acquire_slot()
        return [r.admit(s, prompt, temperature=0.0)] + \
            [int(r.step()[s]) for _ in range(6)]

    with_kernel = greedy()
    os.environ["LOCALAI_W8_KERNEL"] = ""
    without = greedy()
    assert with_kernel == without


def test_w4_eligibility_requires_native_int4_dtype():
    """ADVICE r5 #4: a mode='w4' tensor stored as int8 (e.g. an imported
    GGUF q4 kept unpacked) must not take the int4 kernel — its Mosaic
    tiling assumptions differ. Mirrors eligible()'s int8 gate."""
    rng = np.random.default_rng(5)
    vals = rng.integers(-7, 7, (256, 384))
    s = jnp.ones((2, 384), jnp.float32)  # group 128
    assert qmatmul.w4_eligible((8, 256), jnp.asarray(vals, jnp.int4), s)
    assert not qmatmul.w4_eligible((8, 256), jnp.asarray(vals, jnp.int8), s)
    assert not qmatmul.w4_eligible(
        (8, 256), jnp.asarray(vals, jnp.float32), s)
