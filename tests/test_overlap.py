"""Collective/compute overlap (parallel.overlap): the manual-TP meshed
decode trunk with chunked psum_scatter+all_gather reductions.

The load-bearing pin: on the 2-virtual-device CPU mesh the overlap
decomposition is BYTE-IDENTICAL to the plain-psum manual path (one
addition per element on a 2-wide axis — no summation-tree freedom), and
greedy output matches the GSPMD path token-for-token.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from localai_tpu.engine.runner import ModelRunner
from localai_tpu.models.registry import resolve_model
from localai_tpu.parallel import overlap as ovl
from localai_tpu.parallel import sharding as shd
from localai_tpu.parallel.mesh import MeshPlan, build_mesh
from localai_tpu.utils.jaxcompat import shard_map

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >=2 virtual devices")


def _tp_mesh(n=2):
    return build_mesh(MeshPlan(model=n), devices=jax.devices()[:n])


def test_make_reduce_matches_psum_bytewise():
    mesh = _tp_mesh(2)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(4, 1, 64)), jnp.float32)

    def run(reduce_fn):
        return shard_map(
            lambda v: reduce_fn(v * (1.0 + jax.lax.axis_index("model"))),
            mesh=mesh, in_specs=(P(),), out_specs=P(),
            check_vma=False)(x)

    plain = run(ovl.make_reduce("psum", 2))
    for chunks in (1, 2, 4):
        got = run(ovl.make_reduce("overlap", 2, chunks=chunks))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(plain))
    # indivisible chunk/tp splits degrade to the plain psum, not an error
    odd = jnp.ones((4, 1, 6), jnp.float32)
    got = shard_map(
        ovl.make_reduce("overlap", 2, chunks=4), mesh=mesh,
        in_specs=(P(),), out_specs=P(), check_vma=False)(odd)
    np.testing.assert_array_equal(np.asarray(got), 2 * np.asarray(odd))


def test_resolve_mode_gates():
    tiny = resolve_model("debug:tiny", dtype="float32").cfg
    mesh = _tp_mesh(2)
    assert ovl.resolve_mode(tiny, mesh, "auto") == ("overlap", "")
    assert ovl.resolve_mode(tiny, mesh, "psum") == ("psum", "")
    assert ovl.resolve_mode(tiny, mesh, "0") == ("", "")
    assert ovl.resolve_mode(tiny, None, "auto") == ("", "")
    # dp>1 meshes stay on GSPMD (pool writes of distinct data shards
    # cannot be reconciled manually)
    if len(jax.devices()) >= 4:
        dp_mesh = build_mesh(MeshPlan(data=2, model=2),
                             devices=jax.devices()[:4])
        mode, why = ovl.resolve_mode(tiny, dp_mesh, "auto")
        assert mode == "" and "data" in why
    # MoE stays on GSPMD
    moe = resolve_model("debug:tiny-moe", dtype="float32").cfg
    mode, why = ovl.resolve_mode(moe, mesh, "auto")
    assert mode == "" and "MoE" in why
    # indivisible heads
    import dataclasses

    odd = dataclasses.replace(tiny, num_heads=3, num_kv_heads=3)
    mode, why = ovl.resolve_mode(odd, mesh, "auto")
    assert mode == "" and "divisible" in why


def test_overlap_intermediate_spec():
    assert shd.overlap_intermediate_spec() == P(None, None, "model")


def _meshed_tokens(monkeypatch, mode, kv_dtype="float32", steps=12):
    monkeypatch.setenv("LOCALAI_MESH_OVERLAP", mode)
    model = resolve_model("debug:tiny", dtype="float32")
    mesh = _tp_mesh(2)
    params = shd.shard_params(model.params, model.cfg, mesh)
    runner = ModelRunner(
        model.cfg, params, num_slots=2, max_ctx=128,
        prefill_buckets=[64], kv_dtype=kv_dtype, paged=True,
        kv_block_tokens=16, mesh=mesh)
    want = {"0": "", "psum": "psum", "auto": "overlap"}[mode]
    assert runner.overlap_mode == want
    slot = runner.acquire_slot()
    toks = [runner.admit(slot, list(range(1, 40)), temperature=0.0)]
    for _ in range(steps // 4):
        toks.extend(np.asarray(runner.step_n(4))[:, slot].tolist())
    return toks


def test_overlap_vs_psum_greedy_byte_identical(monkeypatch):
    """THE tentpole parity pin: the chunked psum_scatter+all_gather
    decomposition emits byte-identical greedy tokens to the undecomposed
    manual psum on the 2-device mesh."""
    psum = _meshed_tokens(monkeypatch, "psum")
    over = _meshed_tokens(monkeypatch, "auto")
    assert psum == over


def test_overlap_vs_gspmd_greedy_parity(monkeypatch):
    gspmd = _meshed_tokens(monkeypatch, "0")
    over = _meshed_tokens(monkeypatch, "auto")
    assert gspmd == over


def test_overlap_int4_pool(monkeypatch):
    """int4 composes with the overlap trunk (packed pool sharded on its
    kv-head axis, scales riding the same specs)."""
    i4 = _meshed_tokens(monkeypatch, "auto", kv_dtype="int4")
    f32 = _meshed_tokens(monkeypatch, "auto", kv_dtype="float32")
    assert i4 == f32  # debug-model argmax margins dwarf int4 noise


def test_overlap_multi_slot_and_release(monkeypatch):
    """The overlap trunk serves the multi-slot lifecycle (admit, decode,
    release, re-admit) identically to GSPMD."""

    def run(mode):
        monkeypatch.setenv("LOCALAI_MESH_OVERLAP", mode)
        model = resolve_model("debug:tiny", dtype="float32")
        mesh = _tp_mesh(2)
        params = shd.shard_params(model.params, model.cfg, mesh)
        r = ModelRunner(model.cfg, params, num_slots=2, max_ctx=128,
                        prefill_buckets=[64], kv_dtype="float32",
                        paged=True, kv_block_tokens=16, mesh=mesh)
        s0, s1 = r.acquire_slot(), r.acquire_slot()
        out = [r.admit(s0, list(range(1, 30)), temperature=0.0),
               r.admit(s1, list(range(5, 40)), temperature=0.0)]
        out.extend(np.asarray(r.step_n(4)).ravel().tolist())
        r.release(s0)
        s2 = r.acquire_slot()
        out.append(r.admit(s2, list(range(9, 60)), temperature=0.0))
        out.extend(np.asarray(r.step_n(4)).ravel().tolist())
        return out

    assert run("auto") == run("0")
