"""engine/stream.py stop-sequence handling: holdback of stop strings split
across token boundaries, and flush() emitting the held tail exactly once."""

from localai_tpu.engine.stream import StopChecker


def test_stop_split_across_token_boundaries_is_withheld():
    sc = StopChecker(["STOP"])
    emitted = sc.push("hello ST")  # "ST" could begin "STOP" — held back
    assert emitted == "hello "
    emitted += sc.push("OP ignored tail")
    assert sc.stopped == "STOP"
    assert emitted == "hello "          # the stop text itself never leaks
    assert sc.flush() == ""             # after a hit there is no tail


def test_three_way_split_stop():
    sc = StopChecker(["<|end|>"])
    out = sc.push("abc<|") + sc.push("en") + sc.push("d|>xyz")
    assert out == "abc"
    assert sc.stopped == "<|end|>"


def test_flush_emits_held_tail_exactly_once():
    sc = StopChecker(["STOP"])
    out = sc.push("partial ST")        # "ST" held back as a possible prefix
    assert out == "partial "
    assert sc.flush() == "ST"          # no stop hit → the tail is real text
    assert sc.flush() == ""            # second flush must not re-emit


def test_false_prefix_released_when_disproven():
    sc = StopChecker(["STOP"])
    out = sc.push("S") + sc.push("T") + sc.push("ART")
    # "START" disproves the "ST" prefix; everything must come through,
    # except a suffix that could still begin a new stop ("T" here is not
    # a prefix of STOP, so nothing is held)
    out += sc.flush()
    assert out == "START"
    assert sc.stopped is None


def test_multiple_stops_hold_longest_candidate():
    sc = StopChecker(["\n\n", "###"])
    out = sc.push("text##")
    assert out == "text"               # "##" could begin "###"
    out += sc.push("#")
    assert sc.stopped == "###"
    assert out == "text"


def test_no_stops_passthrough():
    sc = StopChecker([])
    assert sc.push("anything at all") == "anything at all"
    assert sc.flush() == ""
    assert sc.stopped is None
