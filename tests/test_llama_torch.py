"""Cross-framework numerics: the llama engine and whisper against their
torch/transformers reference implementations on tiny random checkpoints.

This closes the flagship-path correctness blind spot (VERDICT r4 #3): the
repo torch-verifies mamba/rwkv/vits/musicgen/image, but the two
highest-traffic paths — the llama serving engine and whisper — were pinned
only by self-consistency tests. Pattern follows tests/test_vits.py: build a
tiny random HF model, save_pretrained → the repo's own loader → compare.

Covers: plain llama, GQA + llama3-type rope scaling, qwen2 attention bias
(the reference serves all three families through llama.cpp — gallery
index.yaml llama3/qwen2 entries), prefill logits, and greedy decode through
the real ModelRunner (KV cache + bucketed prefill + on-device sampling).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import LlamaConfig as HFLlamaConfig  # noqa: E402
from transformers import LlamaForCausalLM  # noqa: E402
from transformers import Qwen2Config as HFQwen2Config  # noqa: E402
from transformers import Qwen2ForCausalLM  # noqa: E402

from localai_tpu.models.loader import load_llama_params  # noqa: E402


def _load_f32(d):
    import dataclasses

    cfg, params = load_llama_params(d, dtype="float32")
    # the loader keeps the config's serving dtype (bfloat16); numerics
    # comparison wants the whole forward in f32
    return dataclasses.replace(cfg, dtype="float32"), params


def _save(model, tmp_path, name):
    d = tmp_path / name
    model.save_pretrained(d, safe_serialization=True)
    return d


def _tiny_llama(seed=0, **kw):
    torch.manual_seed(seed)
    base = dict(
        vocab_size=96, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=256, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    base.update(kw)
    return LlamaForCausalLM(HFLlamaConfig(**base)).eval()


def _tiny_qwen2(seed=3):
    torch.manual_seed(seed)
    cfg = HFQwen2Config(
        vocab_size=96, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    return Qwen2ForCausalLM(cfg).eval()   # qkv bias on by default


def _our_prefill_logits(cfg, params, prompt, max_ctx=64):
    """Logits for every prompt position through the engine's own forward
    (same mask/rope/kv plumbing as ModelRunner._prefill_fn)."""
    import jax.numpy as jnp

    from localai_tpu.engine import kvcache as kvc
    from localai_tpu.models import llama as mdl

    bucket = len(prompt)
    tokens = jnp.asarray(np.asarray(prompt, np.int32)[None])
    positions = jnp.arange(bucket, dtype=jnp.int32)[None]
    kv = kvc.init_cache(cfg, 1, max_ctx, "float32")
    mask = kvc.prefill_mask(cfg, bucket, jnp.int32(bucket))
    write = kvc.prefill_write(jnp.int32(0), jnp.zeros((), jnp.int32))
    rope = mdl.rope_table(cfg, max_ctx)
    hidden, _ = mdl.forward(
        cfg, params, tokens, positions, write, kv.stacked(), mask, rope
    )
    return np.asarray(mdl.logits_from_hidden(cfg, params, hidden[0]))


def _torch_logits(model, prompt):
    with torch.no_grad():
        return model(torch.tensor([prompt])).logits[0].float().numpy()


def _greedy_torch(model, prompt, n):
    ids = list(prompt)
    with torch.no_grad():
        for _ in range(n):
            logits = model(torch.tensor([ids])).logits[0, -1]
            ids.append(int(logits.argmax()))
    return ids[len(prompt):]


def _greedy_ours(cfg, params, prompt, n):
    from localai_tpu.engine.runner import ModelRunner

    runner = ModelRunner(
        cfg, params, num_slots=2, max_ctx=64, prefill_buckets=[16, 32],
        kv_dtype="float32",
    )
    slot = runner.acquire_slot()
    out = [runner.admit(slot, list(prompt), temperature=0.0)]
    while len(out) < n:
        out.append(int(runner.step()[slot]))
    return out


CASES = [
    ("llama", {}),
    ("llama_gqa_rope3", dict(
        num_key_value_heads=2,
        rope_scaling={
            "rope_type": "llama3", "factor": 4.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 64,
        },
    )),
    ("qwen2_bias", None),
]


@pytest.mark.parametrize("name,kw", CASES)
def test_prefill_logits_match_torch(name, kw, tmp_path):
    model = _tiny_qwen2() if kw is None else _tiny_llama(**kw)
    d = _save(model, tmp_path, name)
    cfg, params = _load_f32(d)
    if kw is None:
        assert cfg.attention_bias
    prompt = [5, 17, 3, 42, 9, 88, 1, 63]
    ours = _our_prefill_logits(cfg, params, prompt)
    ref = _torch_logits(model, prompt)
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("name,kw", CASES)
def test_engine_greedy_decode_matches_torch(name, kw, tmp_path):
    model = _tiny_qwen2() if kw is None else _tiny_llama(**kw)
    d = _save(model, tmp_path, name)
    cfg, params = _load_f32(d)
    prompt = [5, 17, 3, 42, 9, 88, 1, 63]
    n = 12
    assert _greedy_ours(cfg, params, prompt, n) == \
        _greedy_torch(model, prompt, n)


def test_whisper_matches_torch(tmp_path):
    """Encoder + teacher-forced decoder logits against HF whisper."""
    from transformers import WhisperConfig as HFWhisperConfig
    from transformers import WhisperForConditionalGeneration

    from localai_tpu.models import whisper as wh

    torch.manual_seed(1)
    hf_cfg = HFWhisperConfig(
        vocab_size=128, num_mel_bins=16, d_model=32,
        encoder_layers=2, encoder_attention_heads=2,
        decoder_layers=2, decoder_attention_heads=2,
        encoder_ffn_dim=64, decoder_ffn_dim=64,
        max_source_positions=40, max_target_positions=24,
        pad_token_id=0, bos_token_id=1, eos_token_id=2,
        decoder_start_token_id=1, suppress_tokens=[],
        begin_suppress_tokens=[],
    )
    model = WhisperForConditionalGeneration(hf_cfg).eval()
    d = tmp_path / "whisper"
    model.save_pretrained(d, safe_serialization=True)
    ours = wh.load_hf_whisper(d)

    rng = np.random.default_rng(0)
    # HF conv2 stride-2 halves the frame axis: feed 2*max_source_positions
    mel = rng.normal(size=(16, 80)).astype(np.float32) * 0.3
    dec_ids = [3, 7, 11, 2]
    with torch.no_grad():
        enc_ref = model.model.encoder(
            torch.tensor(mel[None])).last_hidden_state[0].numpy()
        logits_ref = model(
            input_features=torch.tensor(mel[None]),
            decoder_input_ids=torch.tensor([dec_ids]),
        ).logits[0].numpy()

    import jax.numpy as jnp

    enc = wh.encode(ours.cfg, ours.params, jnp.asarray(mel))
    np.testing.assert_allclose(np.asarray(enc), enc_ref, atol=2e-4, rtol=2e-4)
    # decode_logits returns the logits at position length-1 of a padded
    # token buffer — teacher-force each prefix length
    padded = jnp.asarray(np.asarray(dec_ids, np.int32))
    for ln in range(1, len(dec_ids) + 1):
        logits = wh.decode_logits(
            ours.cfg, ours.params, padded, jnp.int32(ln), enc
        )
        np.testing.assert_allclose(
            np.asarray(logits), logits_ref[ln - 1], atol=2e-4, rtol=2e-4
        )
