"""Vision/multimodal input: CLIP ViT tower, embedding injection, chat API.

Parity targets: image_url/base64 ingestion in chat
(/root/reference/core/http/endpoints/openai/chat.go:296-441,
pkg/utils/base64.go:18-60) and CLIP/LLaVA embedding injection into the
token stream (backend/cpp/llama/grpc-server.cpp:1397-1424).
"""

import base64
import io
import json

import numpy as np
import pytest

from localai_tpu.models.registry import resolve_model
from localai_tpu.models.vision import resolve_vision_tower


def _png_bytes(seed: int = 0, size: int = 40) -> bytes:
    from PIL import Image

    arr = (np.random.RandomState(seed).rand(size, size, 3) * 255).astype(
        np.uint8
    )
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


@pytest.fixture(scope="module")
def small():
    return resolve_model("debug:small")


@pytest.fixture(scope="module")
def tower(small):
    return resolve_vision_tower(
        "debug:vit", projection_dim=small.cfg.hidden_size
    )


# -- vision tower -----------------------------------------------------------


def test_encode_shapes(tower, small):
    imgs = [(np.random.RandomState(i).rand(50, 30, 3) * 255).astype(np.uint8)
            for i in range(2)]
    emb = tower.encode(imgs)
    assert emb.shape == (2, tower.n_patches, small.cfg.hidden_size)
    assert np.isfinite(emb).all()
    # different images → different embeddings
    assert not np.allclose(emb[0], emb[1])


def test_preprocess_handles_grayscale_and_rgba(tower):
    gray = (np.random.rand(20, 20) * 255).astype(np.uint8)
    rgba = (np.random.rand(20, 20, 4) * 255).astype(np.uint8)
    emb = tower.encode([gray, rgba])
    assert emb.shape[0] == 2


# -- media fetching ---------------------------------------------------------


def test_fetch_image_data_uri_and_raw_base64():
    from localai_tpu.utils.media import fetch_image

    png = _png_bytes()
    b64 = base64.b64encode(png).decode()
    for ref in (f"data:image/png;base64,{b64}", b64):
        img = fetch_image(ref)
        assert img.shape == (40, 40, 3)
        assert img.dtype == np.uint8


def test_fetch_image_rejects_garbage():
    from localai_tpu.utils.media import MediaError, fetch_image

    with pytest.raises(MediaError):
        fetch_image("certainly not base64 !!!")
    with pytest.raises(MediaError):
        fetch_image(base64.b64encode(b"not an image").decode())


# -- prompt expansion -------------------------------------------------------


def test_expand_image_placeholders(small, tower):
    from localai_tpu.api.inference import expand_image_placeholders

    class SM:  # minimal ServingModel surface
        tokenizer = small.tokenizer
        image_token_id = 7

    emb = np.ones((2, tower.n_patches, small.cfg.hidden_size), np.float32)
    emb[1] *= 2
    prompt = "look: [img-0] and [img-1] what?"
    tokens, flat, pos = expand_image_placeholders(SM(), prompt, emb)
    n = tower.n_patches
    assert flat.shape == (2 * n, small.cfg.hidden_size)
    assert len(pos) == 2 * n
    # placeholder spans hold the image token id
    toks = np.asarray(tokens)
    assert (toks[pos] == 7).all()
    # embedding rows line up with their placeholders in order
    assert (flat[:n] == 1).all() and (flat[n:] == 2).all()
    # text between the images survived
    assert "and" in small.tokenizer.decode([t for t in tokens if t != 7])


def test_placeholder_ids_are_global_across_messages(small, tower):
    from localai_tpu.api.inference import prepare_multimodal
    from localai_tpu.api.schema import OpenAIRequest
    from localai_tpu.config.model_config import ModelConfig

    png = base64.b64encode(_png_bytes()).decode()

    class SM:
        name = "t"
        tokenizer = small.tokenizer
        vision = None  # placeholders only; no encode
        image_token_id = 0

    req = OpenAIRequest(model="t", messages=[
        {"role": "user", "content": [
            {"type": "text", "text": "first"},
            {"type": "image_url", "image_url": {"url": png}},
        ]},
        {"role": "user", "content": [
            {"type": "text", "text": "second"},
            {"type": "image_url", "image_url": {"url": png}},
        ]},
    ])
    messages, embeds = prepare_multimodal(SM(), ModelConfig(name="t"), req)
    assert "[img-0]" in messages[0]["content"]
    assert "[img-1]" in messages[1]["content"]
    assert embeds is None  # no tower → text-only fallback


# -- engine injection -------------------------------------------------------


def test_injection_reaches_kv_cache(small, tower):
    """Injected embeddings must change exactly the image span's KV entries
    (text prefix KV identical ⇒ only the placeholder positions were
    overridden)."""
    from localai_tpu.engine.runner import ModelRunner

    img = (np.random.RandomState(3).rand(32, 32, 3) * 255).astype(np.uint8)
    emb = tower.encode([img])[0]
    n = tower.n_patches
    prompt = list(range(1, 10)) + [0] * n + list(range(10, 20))
    pos = np.arange(9, 9 + n, dtype=np.int32)

    def kv_after(mm):
        r = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=256,
                        prefill_buckets=[64])
        s = r.acquire_slot()
        kwargs = dict(mm_embeds=emb, mm_positions=pos) if mm else {}
        r.admit(s, prompt, temperature=0.0, **kwargs)
        return np.asarray(r.kv.k[0, s], np.float32)

    k_img, k_txt = kv_after(True), kv_after(False)
    assert not np.allclose(k_img[:, 9:25], k_txt[:, 9:25])
    assert np.allclose(k_img[:, 0:9], k_txt[:, 0:9])


def test_injection_changes_generation(small, tower):
    """Distinct image content must steer greedy decode (embeddings amplified
    so the tiny random debug model reacts deterministically)."""
    from localai_tpu.engine.runner import ModelRunner

    r = ModelRunner(small.cfg, small.params, num_slots=2, max_ctx=256,
                    prefill_buckets=[64])
    n = tower.n_patches
    prompt = list(range(1, 10)) + [0] * n + list(range(10, 20))
    pos = np.arange(9, 9 + n, dtype=np.int32)
    img_a = (np.random.RandomState(3).rand(32, 32, 3) * 255).astype(np.uint8)
    img_b = (np.random.RandomState(7).rand(32, 32, 3) * 255).astype(np.uint8)
    embs = tower.encode([img_a, img_b]) * 40.0  # amplify vs 0.02-scale embeds

    seqs = []
    for e in embs:
        s = r.acquire_slot()
        t = r.admit(s, prompt, temperature=0.0, mm_embeds=e, mm_positions=pos)
        seqs.append([t] + [int(r.step()[s]) for _ in range(8)])
        r.release(s)
    assert seqs[0] != seqs[1]


# -- llava checkpoint ingestion --------------------------------------------


def _write_tiny_llava(tmp_path):
    """Fake llava-hf checkpoint: tiny text + vision configs, classic
    language_model.model.* / vision_tower.vision_model.* tensor names."""
    from safetensors.numpy import save_file

    D, F, L, H = 64, 128, 2, 4          # text dims
    VC, VI, VL, VP, VS = 32, 64, 2, 8, 16  # vision dims (patch 8, img 16)
    V = 512
    cfg = {
        "model_type": "llava",
        "image_token_index": 31,
        "vision_feature_layer": -1,
        "text_config": {
            "vocab_size": V, "hidden_size": D, "intermediate_size": F,
            "num_hidden_layers": L, "num_attention_heads": H,
            "num_key_value_heads": H, "max_position_embeddings": 128,
        },
        "vision_config": {
            "image_size": VS, "patch_size": VP, "hidden_size": VC,
            "intermediate_size": VI, "num_hidden_layers": VL,
            "num_attention_heads": 4,
        },
    }
    (tmp_path / "config.json").write_text(json.dumps(cfg))
    rng = np.random.RandomState(0)

    def t(*shape):
        return rng.randn(*shape).astype(np.float32) * 0.02

    tensors = {
        "language_model.model.embed_tokens.weight": t(V, D),
        "language_model.model.norm.weight": np.ones(D, np.float32),
        "language_model.lm_head.weight": t(V, D),
        "vision_tower.vision_model.embeddings.class_embedding": t(VC),
        "vision_tower.vision_model.embeddings.patch_embedding.weight":
            t(VC, 3, VP, VP),
        "vision_tower.vision_model.embeddings.position_embedding.weight":
            t((VS // VP) ** 2 + 1, VC),
        "vision_tower.vision_model.pre_layrnorm.weight": np.ones(VC, np.float32),
        "vision_tower.vision_model.pre_layrnorm.bias": np.zeros(VC, np.float32),
        "multi_modal_projector.linear_1.weight": t(D, VC),
        "multi_modal_projector.linear_1.bias": np.zeros(D, np.float32),
        "multi_modal_projector.linear_2.weight": t(D, D),
        "multi_modal_projector.linear_2.bias": np.zeros(D, np.float32),
    }
    for i in range(L):
        P = f"language_model.model.layers.{i}."
        tensors.update({
            P + "input_layernorm.weight": np.ones(D, np.float32),
            P + "post_attention_layernorm.weight": np.ones(D, np.float32),
            P + "self_attn.q_proj.weight": t(D, D),
            P + "self_attn.k_proj.weight": t(D, D),
            P + "self_attn.v_proj.weight": t(D, D),
            P + "self_attn.o_proj.weight": t(D, D),
            P + "mlp.gate_proj.weight": t(F, D),
            P + "mlp.up_proj.weight": t(F, D),
            P + "mlp.down_proj.weight": t(D, F),
        })
    for i in range(VL):
        P = f"vision_tower.vision_model.encoder.layers.{i}."
        tensors.update({
            P + "layer_norm1.weight": np.ones(VC, np.float32),
            P + "layer_norm1.bias": np.zeros(VC, np.float32),
            P + "layer_norm2.weight": np.ones(VC, np.float32),
            P + "layer_norm2.bias": np.zeros(VC, np.float32),
            P + "self_attn.q_proj.weight": t(VC, VC),
            P + "self_attn.q_proj.bias": np.zeros(VC, np.float32),
            P + "self_attn.k_proj.weight": t(VC, VC),
            P + "self_attn.k_proj.bias": np.zeros(VC, np.float32),
            P + "self_attn.v_proj.weight": t(VC, VC),
            P + "self_attn.v_proj.bias": np.zeros(VC, np.float32),
            P + "self_attn.out_proj.weight": t(VC, VC),
            P + "self_attn.out_proj.bias": np.zeros(VC, np.float32),
            P + "mlp.fc1.weight": t(VI, VC),
            P + "mlp.fc1.bias": np.zeros(VI, np.float32),
            P + "mlp.fc2.weight": t(VC, VI),
            P + "mlp.fc2.bias": np.zeros(VC, np.float32),
        })
    save_file(tensors, str(tmp_path / "model.safetensors"))
    # byte-level tokenizer marker so load_tokenizer falls back cleanly
    return tmp_path


def test_llava_checkpoint_loads(tmp_path):
    llava_dir = _write_tiny_llava(tmp_path)
    from localai_tpu.models.loader import load_llama_params
    from localai_tpu.models.vision import load_llava_vision

    cfg, params = load_llama_params(llava_dir)
    assert cfg.vocab_size == 512 and cfg.num_layers == 2
    assert params["embed"].shape == (512, 64)
    assert "lm_head" in params

    vt = load_llava_vision(llava_dir, projection_dim=64)
    assert vt.n_patches == 4
    img = (np.random.rand(16, 16, 3) * 255).astype(np.uint8)
    emb = vt.encode([img])
    assert emb.shape == (1, 4, 64)
    assert np.isfinite(emb).all()


# -- end-to-end through the API --------------------------------------------


MM_YAML = """\
name: mm
model: debug:small
context_size: 256
mmproj: "debug:vit"
engine:
  max_slots: 2
  prefill_buckets: [128]
parameters:
  temperature: 0.0
  max_tokens: 8
"""


@pytest.fixture(scope="module")
def vision_server(tmp_path_factory):
    from tests.test_api import _ServerThread, make_state

    models = tmp_path_factory.mktemp("models")
    (models / "mm.yaml").write_text(MM_YAML)
    state = make_state(models)
    srv = _ServerThread(state)
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def vision_client(vision_server):
    import httpx

    with httpx.Client(base_url=vision_server.base, timeout=180.0) as c:
        yield c


def test_chat_with_image(vision_client):
    b64 = base64.b64encode(_png_bytes(seed=1)).decode()
    body = {
        "model": "mm",
        "temperature": 0,
        "max_tokens": 8,
        "messages": [{
            "role": "user",
            "content": [
                {"type": "text", "text": "what is this?"},
                {"type": "image_url",
                 "image_url": {"url": f"data:image/png;base64,{b64}"}},
            ],
        }],
    }
    r = vision_client.post("/v1/chat/completions", json=body)
    assert r.status_code == 200, r.text
    data = r.json()
    assert data["choices"][0]["message"]["role"] == "assistant"
    with_img_usage = data["usage"]["prompt_tokens"]

    # same prompt without the image: fewer prompt tokens (no patch span)
    body["messages"][0]["content"] = [{"type": "text", "text": "what is this?"}]
    r = vision_client.post("/v1/chat/completions", json=body)
    assert r.status_code == 200
    # debug:vit is 16 patches; the image span must account for exactly that
    assert with_img_usage == r.json()["usage"]["prompt_tokens"] + 16


def test_chat_with_image_streaming(vision_client):
    b64 = base64.b64encode(_png_bytes(seed=2)).decode()
    body = {
        "model": "mm",
        "max_tokens": 4,
        "stream": True,
        "messages": [{
            "role": "user",
            "content": [
                {"type": "text", "text": "describe"},
                {"type": "image_url",
                 "image_url": {"url": f"data:image/png;base64,{b64}"}},
            ],
        }],
    }
    with vision_client.stream(
        "POST", "/v1/chat/completions", json=body
    ) as resp:
        assert resp.status_code == 200
        lines = [ln for ln in resp.iter_lines() if ln.startswith("data: ")]
    assert lines[-1] == "data: [DONE]"


def test_chat_with_bad_image_is_400(vision_client):
    body = {
        "model": "mm",
        "messages": [{
            "role": "user",
            "content": [
                {"type": "image_url", "image_url": {"url": "!!not-an-image"}},
            ],
        }],
    }
    r = vision_client.post("/v1/chat/completions", json=body)
    assert r.status_code == 400


# -- video input ------------------------------------------------------------


def _gif_bytes(n_frames: int = 6, size: int = 32) -> bytes:
    from PIL import Image

    frames = [
        Image.fromarray(
            (np.random.RandomState(i).rand(size, size, 3) * 255
             ).astype(np.uint8))
        for i in range(n_frames)
    ]
    buf = io.BytesIO()
    frames[0].save(buf, format="GIF", save_all=True,
                   append_images=frames[1:], duration=50, loop=0)
    return buf.getvalue()


def test_decode_video_frames_samples_uniformly():
    from localai_tpu.utils.media import decode_video_frames

    frames = decode_video_frames(_gif_bytes(10), max_frames=4)
    assert len(frames) == 4
    assert frames[0].shape == (32, 32, 3)
    # fewer frames than the cap: all of them
    assert len(decode_video_frames(_gif_bytes(3), max_frames=8)) == 3
    # single-frame media degrades to one frame
    assert len(decode_video_frames(_png_bytes(), max_frames=8)) == 1


def test_decode_video_rejects_unknown_container():
    from localai_tpu.utils.media import MediaError, decode_video_frames

    with pytest.raises(MediaError, match="cannot decode video"):
        decode_video_frames(b"\x00\x00\x00\x18ftypmp42not-a-real-mp4")


def test_video_part_expands_to_frame_embeddings(small, tower):
    """A video_url part renders a [vid-N] placeholder whose span injects
    every sampled frame's patch embeddings (parity: vLLM backend video
    multimodal path)."""
    from localai_tpu.api.inference import prepare_multimodal
    from localai_tpu.api.schema import OpenAIRequest
    from localai_tpu.config.model_config import ModelConfig

    gif = "data:image/gif;base64," + base64.b64encode(
        _gif_bytes(6)).decode()
    png = base64.b64encode(_png_bytes()).decode()

    class SM:
        name = "t"
        tokenizer = small.tokenizer
        vision = tower
        image_token_id = 7

    req = OpenAIRequest(model="t", messages=[
        {"role": "user", "content": [
            {"type": "text", "text": "compare"},
            {"type": "image_url", "image_url": {"url": png}},
            {"type": "video_url", "video_url": {"url": gif}},
        ]},
    ])
    cfg = ModelConfig(name="t")
    messages, mm = prepare_multimodal(SM(), cfg, req)
    assert "[img-0]" in messages[0]["content"]
    assert "[vid-0]" in messages[0]["content"]
    assert mm.video_groups == [(1, 6)]          # rows 1..6 after the image
    assert mm.embeds.shape[0] == 7              # 1 image + 6 frames

    from localai_tpu.templates.chat import multimodal_placeholders

    prompt = multimodal_placeholders(
        cfg.template.multimodal or "", "compare",
        n_images=1, n_video=1)
    from localai_tpu.api.inference import expand_image_placeholders

    tokens, flat, pos = expand_image_placeholders(SM(), prompt, mm)
    n = tower.n_patches
    assert flat.shape == (7 * n, small.cfg.hidden_size)
    assert len(pos) == 7 * n
    toks = np.asarray(tokens)
    assert (toks[pos] == 7).all()
