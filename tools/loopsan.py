"""loopsan: a runtime event-loop stall sanitizer.

The static loopcheck pass (tools/jaxlint) reasons over the project call
graph; it cannot see blocking behind attribute-of-attribute receivers,
dynamic dispatch, or third-party internals. This harness sees exactly
that: :class:`LoopSanitizer` wraps asyncio's callback dispatch
(``Handle._run`` — every task step and ``call_soon`` callback on every
loop goes through it) and records per-callback wall time with the
owning task/handler name. Any callback that holds the loop longer than
the threshold (default 50 ms — at 8 concurrent SSE streams that is a
visible hiccup on every one of them) is reported as a *stall*, with the
mid-stall Python stack captured by a sampler thread so the report names
the blocking line, not just the handler.

The pairing mirrors racecheck (static lockcheck + runtime LockMonitor,
PR 9): CI drives the full 2-replica fleet + loadgen lifecycle under it
(``python -m tools.telemetry_smoke --loopsan``) and fails on any stall.
For a demonstration of what a report looks like:

    python tools/loopsan.py --demo
"""

from __future__ import annotations

import asyncio
import asyncio.events
import sys
import threading
import time
import traceback

# the genuine dispatch, captured before any sanitizer patches it —
# TimerHandle inherits it, so timer callbacks are covered too
_REAL_HANDLE_RUN = asyncio.events.Handle._run


def _label(handle) -> str:
    """Owning task/handler name for a dispatched handle. A task step's
    callback is the bound ``Task.__step`` — name the task and its coro;
    anything else is a plain ``call_soon``/timer callback."""
    cb = getattr(handle, "_callback", None)
    owner = getattr(cb, "__self__", None)
    if isinstance(owner, asyncio.Task):
        coro = owner.get_coro()
        qn = getattr(coro, "__qualname__", None) or repr(coro)
        return f"task {owner.get_name()} ({qn})"
    qn = getattr(cb, "__qualname__", None) or repr(cb)
    return f"callback {qn}"


def _format_frame_stack(frame, limit: int) -> list[str]:
    frames = traceback.extract_stack(frame, limit=limit)
    return [f"{fr.filename.rsplit('/', 1)[-1]}:{fr.lineno} in {fr.name}"
            for fr in frames]


class Stall:
    """One callback that held the event loop past the threshold."""

    def __init__(self, label: str, duration_ms: float,
                 stack: list[str]):
        self.label = label
        self.duration_ms = duration_ms
        self.stack = stack

    def render(self) -> str:
        out = [f"{self.duration_ms:8.1f} ms  {self.label}"]
        out.extend(f"    {line}" for line in self.stack)
        return "\n".join(out)

    def to_dict(self) -> dict:
        return {"label": self.label,
                "duration_ms": round(self.duration_ms, 2),
                "stack": list(self.stack)}


class LoopSanitizer:
    """Process-wide event-loop stall detector.

    ``install()`` patches ``Handle._run``; every loop in the process
    (on any thread) is covered from that moment. A daemon sampler
    thread polls the in-flight dispatch table and snapshots the running
    thread's Python stack once a callback crosses the threshold — the
    stack is captured MID-stall, pointing at the blocking call itself.
    Short of the sampler's poll period (a stall that finishes between
    polls), the report still carries the duration and owner, just
    without a stack.
    """

    def __init__(self, threshold_ms: float = 50.0,
                 poll_ms: float = 5.0, stack_limit: int = 14):
        self.threshold_ms = float(threshold_ms)
        self.poll_ms = float(poll_ms)
        self.stack_limit = stack_limit
        self._meta = threading.Lock()
        # thread id -> stack of [handle, t0, sampled_stack|None]
        # (a stack, not a single slot: run_until_complete inside a
        # callback re-enters dispatch on the same thread)
        self._active: dict[int, list[list]] = {}
        self._stalls: list[Stall] = []
        self._installed = False
        self._stop = threading.Event()
        self._sampler: threading.Thread | None = None
        self.callbacks_seen = 0

    # -- patching ----------------------------------------------------------

    def install(self) -> "LoopSanitizer":
        if self._installed:
            return self
        san = self

        def _run(handle):
            tid = threading.get_ident()
            entry = [handle, time.perf_counter(), None]
            with san._meta:
                san.callbacks_seen += 1
                san._active.setdefault(tid, []).append(entry)
            try:
                return _REAL_HANDLE_RUN(handle)
            finally:
                dt_ms = (time.perf_counter() - entry[1]) * 1000.0
                with san._meta:
                    stack = san._active.get(tid)
                    if stack and stack[-1] is entry:
                        stack.pop()
                if dt_ms >= san.threshold_ms:
                    san._note_stall(handle, dt_ms, entry[2])

        asyncio.events.Handle._run = _run  # type: ignore[method-assign]
        self._stop.clear()
        self._sampler = threading.Thread(
            target=self._sample_loop, name="loopsan-sampler", daemon=True)
        self._sampler.start()
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Restore the real dispatch and stop the sampler. Stalls
        recorded so far stay available for report()."""
        if not self._installed:
            return
        asyncio.events.Handle._run = _REAL_HANDLE_RUN  # type: ignore
        self._stop.set()
        if self._sampler is not None:
            self._sampler.join(timeout=2.0)
            self._sampler = None
        self._installed = False

    def __enter__(self) -> "LoopSanitizer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- sampler -----------------------------------------------------------

    def _sample_loop(self) -> None:
        while not self._stop.wait(self.poll_ms / 1000.0):
            now = time.perf_counter()
            with self._meta:
                pending = [(tid, stack[-1])
                           for tid, stack in self._active.items() if stack]
            for tid, entry in pending:
                if entry[2] is not None:
                    continue
                if (now - entry[1]) * 1000.0 < self.threshold_ms:
                    continue
                frame = sys._current_frames().get(tid)
                if frame is not None:
                    # formatted outside _meta: extract_stack reads source
                    entry[2] = _format_frame_stack(frame, self.stack_limit)

    # -- recording / analysis ----------------------------------------------

    def _note_stall(self, handle, dt_ms: float, stack) -> None:
        if stack is None:
            stack = ["<stall shorter than a sampler poll; "
                     "no mid-stall stack captured>"]
        s = Stall(_label(handle), dt_ms, stack)
        with self._meta:
            self._stalls.append(s)

    def stalls(self) -> list[Stall]:
        with self._meta:
            return list(self._stalls)

    def reset(self) -> None:
        """Drop recorded stalls/counters (e.g. after a deliberate
        self-check stall) without disturbing the installed patch."""
        with self._meta:
            self._stalls.clear()
            self.callbacks_seen = 0

    def report(self) -> str:
        stalls = self.stalls()
        head = (f"loopsan: {self.callbacks_seen} callbacks dispatched, "
                f"{len(stalls)} stall(s) >= {self.threshold_ms:g} ms")
        if not stalls:
            return head
        return "\n".join([head, ""] + [s.render() for s in stalls])

    def snapshot(self) -> dict:
        return {
            "threshold_ms": self.threshold_ms,
            "callbacks_seen": self.callbacks_seen,
            "stalls": [s.to_dict() for s in self.stalls()],
        }


# -- CLI demo ---------------------------------------------------------------

def _demo() -> int:
    """Provoke a textbook loop stall (time.sleep in an async handler)
    next to a clean awaited workload, and print the report (this is
    what a failing CI loopsan step looks like)."""
    san = LoopSanitizer(threshold_ms=50.0)

    async def blocking_handler():
        time.sleep(0.2)     # the bug: sync sleep on the event loop

    async def clean_handler():
        await asyncio.sleep(0.05)   # yields: never holds the loop

    async def main():
        await asyncio.gather(clean_handler(), blocking_handler())

    with san:
        asyncio.run(main())
    print(san.report())
    return 1 if san.stalls() else 0


if __name__ == "__main__":
    if "--demo" in sys.argv:
        sys.exit(_demo())
    print(__doc__)
    sys.exit(0)
