"""Per-shape paged-attention autotuner (ops.tuning's writer).

Sweeps the paged decode attention dispatch over its real tuning axes —
kernel impl (Pallas flash vs gather+XLA ref), pool ``block_tokens``, DMA
``num_buffers`` — on REAL timings at the shapes a model family serves,
and persists the winner per ``(head_dim, kv_heads, kv_dtype, tp)`` key to
the tuning table (``LOCALAI_TUNE_CACHE`` / ``--out``). The engine then
picks the tuned configuration automatically: ``select_paged_attn_impl``
honors the tuned impl and ``ModelRunner`` the tuned block size / buffer
depth, each lookup leaving a ``localai_autotune_*`` metric receipt.

Tensor-parallel keys (``--tp``) are measured at the per-device LOCAL
shapes (heads/tp) — under ``shard_map`` the kernel body IS the
single-device kernel, so the local measurement is the honest one and no
multi-device dispatch is needed to tune for a mesh.

Usage:
    python tools/autotune.py                      # 1b + 8b shapes, this
                                                  # backend's impl set
    python tools/autotune.py --preset tiny --kv-dtypes float32,int4 \
        --tp 1,2 --interpret --out tuning.json    # CI smoke (CPU: the
                                                  # Pallas points run in
                                                  # interpret mode)
    python tools/autotune.py --smoke              # the CI sweep above

Output: one JSON line per measured point plus a final summary line; the
table file is the artifact CI uploads.
"""

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# the shapes worth tuning out of the box: the bench/serving presets
PRESET_SHAPES = {
    "tiny": (16, 2),          # debug:tiny (tests, CI smoke)
    "small": (32, 4),
    "1b": (64, 8),            # debug:1b
    "llama3-8b": (128, 8),    # the north-star dims
}


def _timeit(fn, *args, n=10, warmup=2):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def measure_point(head_dim: int, kv_heads: int, kv_dtype: str, *,
                  impl: str, block_tokens: int, num_buffers: int,
                  group: int = 4, slots: int = 4, ctx: int = 512,
                  interpret: bool = False, reps: int = 3) -> float:
    """Best-of-``reps`` microseconds for one paged decode attention
    dispatch at the given configuration (local, single-device shapes)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from localai_tpu import ops
    from localai_tpu.models.quant import quantize_lastdim, quantize_lastdim4

    rng = np.random.default_rng(0)
    bt = block_tokens
    mb = -(-ctx // bt)
    n_blocks = slots * mb + 1
    num_heads = kv_heads * group
    q = jnp.asarray(
        rng.normal(size=(slots, num_heads, head_dim)), jnp.float32)
    kf = jnp.asarray(
        rng.normal(size=(n_blocks, kv_heads, bt, head_dim)), jnp.float32)
    vf = jnp.asarray(
        rng.normal(size=(n_blocks, kv_heads, bt, head_dim)), jnp.float32)
    tables = jnp.asarray(
        np.arange(1, n_blocks).reshape(slots, mb), jnp.int32)
    positions = jnp.full((slots,), ctx - 2, jnp.int32)

    k_scale = v_scale = None
    if kv_dtype == "int8":
        kf, k_scale = quantize_lastdim(kf)
        vf, v_scale = quantize_lastdim(vf)
    elif kv_dtype == "int4":
        kf, k_scale = quantize_lastdim4(kf)
        vf, v_scale = quantize_lastdim4(vf)
    elif kv_dtype == "bfloat16":
        kf, vf = kf.astype(jnp.bfloat16), vf.astype(jnp.bfloat16)

    if impl == "pallas":
        def fn(q, k, v, t, p, ks, vs):
            return ops.paged_decode_attention(
                q, k, v, t, p, ks, vs, interpret=interpret,
                num_buffers=num_buffers)
    else:
        def fn(q, k, v, t, p, ks, vs):
            return ops.paged_decode_attention_ref(q, k, v, t, p, ks, vs)

    jitted = jax.jit(fn)
    dt = min(
        _timeit(jitted, q, kf, vf, tables, positions, k_scale, v_scale)
        for _ in range(reps))
    return dt * 1e6


def sweep(shapes, kv_dtypes, tps, *, block_candidates, buffer_candidates,
          impls, ctx: int, interpret: bool, table) -> list[dict]:
    """Measure every point, install the per-key winners into ``table``,
    and return the point records."""
    from localai_tpu.ops import tuning

    records = []
    for hd, kv in shapes:
        for kv_dtype in kv_dtypes:
            if kv_dtype == "int4" and hd % 2:
                continue
            for tp in tps:
                if kv % tp or tp < 1:
                    continue
                key = tuning.shape_key(hd, kv, kv_dtype, tp)
                t_key = time.monotonic()
                best = None
                for impl in impls:
                    bufs = buffer_candidates if impl == "pallas" else [2]
                    for bt in block_candidates:
                        if bt > ctx:
                            continue
                        for nb in bufs:
                            try:
                                us = measure_point(
                                    hd, kv // tp, kv_dtype, impl=impl,
                                    block_tokens=bt, num_buffers=nb,
                                    ctx=ctx, interpret=interpret)
                            except Exception as e:  # noqa: BLE001
                                rec = {"key": key, "impl": impl,
                                       "block_tokens": bt,
                                       "num_buffers": nb,
                                       "error": f"{type(e).__name__}: "
                                                f"{e}"[:200]}
                                records.append(rec)
                                print(json.dumps(rec))
                                continue
                            rec = {"key": key, "impl": impl,
                                   "block_tokens": bt, "num_buffers": nb,
                                   "us": round(us, 1)}
                            records.append(rec)
                            print(json.dumps(rec))
                            if best is None or us < best[0]:
                                best = (us, impl, bt, nb)
                if best is None:
                    continue
                us, impl, bt, nb = best
                table.put(key, tuning.TuneEntry(
                    impl=impl, block_tokens=bt, num_buffers=nb,
                    us=round(us, 1)))
                _note_sweep(key, time.monotonic() - t_key)
    return records


def _note_sweep(key: str, seconds: float) -> None:
    try:
        from localai_tpu.obs.metrics import REGISTRY

        REGISTRY.autotune_sweep_seconds.set(seconds, key=key)
    except Exception:  # noqa: BLE001
        pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--preset", action="append", default=[],
                    choices=sorted(PRESET_SHAPES),
                    help="model shape preset(s) to tune (default: 1b + "
                         "llama3-8b; repeatable)")
    ap.add_argument("--kv-dtypes", default="bfloat16,int8,int4",
                    help="comma list of KV dtypes to tune")
    ap.add_argument("--tp", default="1",
                    help="comma list of tensor-parallel widths to key")
    ap.add_argument("--blocks", default="16,32,64,128",
                    help="block_tokens candidates")
    ap.add_argument("--buffers", default="2,3",
                    help="num_buffers candidates (pallas only)")
    ap.add_argument("--ctx", type=int, default=512,
                    help="context rows per measured slot")
    ap.add_argument("--interpret", action="store_true",
                    help="include Pallas points in interpret mode off-TPU "
                         "(CI machinery smoke; timings are not "
                         "hardware-representative)")
    ap.add_argument("--out", default="",
                    help="table path (default LOCALAI_TUNE_CACHE)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI sweep: tiny shape, float32+int4, "
                         "tp 1+2, blocks 8/16, interpret")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu").split(",")[0])

    from localai_tpu.ops import tuning

    if args.smoke:
        shapes = [PRESET_SHAPES["tiny"]]
        kv_dtypes = ["float32", "int4"]
        tps = [1, 2]
        blocks = [8, 16]
        buffers = [2, 3]
        args.interpret = True
        ctx = 64
    else:
        presets = args.preset or ["1b", "llama3-8b"]
        shapes = [PRESET_SHAPES[p] for p in presets]
        kv_dtypes = [d for d in args.kv_dtypes.split(",") if d]
        tps = [int(t) for t in args.tp.split(",") if t]
        blocks = [int(b) for b in args.blocks.split(",") if b]
        buffers = [int(b) for b in args.buffers.split(",") if b]
        ctx = args.ctx

    on_tpu = jax.default_backend() == "tpu"
    impls = ["xla"]
    if on_tpu or args.interpret:
        impls.append("pallas")

    path = args.out or tuning.cache_path()
    table = tuning.TuningTable.load(path)
    t0 = time.monotonic()
    records = sweep(shapes, kv_dtypes, tps, block_candidates=blocks,
                    buffer_candidates=buffers, impls=impls, ctx=ctx,
                    interpret=not on_tpu, table=table)
    if not path:
        print(json.dumps({"error": "no table path (LOCALAI_TUNE_CACHE=0 "
                                   "and no --out)"}))
        return 1
    saved = table.save(path)
    tuning.reset()  # a fresh lookup sees the new entries
    print(json.dumps({
        "table": saved,
        "entries": len(table.entries),
        "points_measured": sum(1 for r in records if "us" in r),
        "points_failed": sum(1 for r in records if "error" in r),
        "backend": jax.default_backend(),
        "interpret": not on_tpu,
        "sweep_s": round(time.monotonic() - t0, 1),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
