"""racecheck: an opt-in instrumented-lock harness for lock-order races.

The static lockcheck pass (tools/jaxlint) sees one class at a time; it
cannot see that the fleet dispatch thread takes the router lock inside
the pool lock while the monitor thread takes them the other way round.
This harness sees exactly that: :class:`LockMonitor` replaces
``threading.Lock``/``RLock`` so every lock created afterwards records,
per thread, the stack of locks currently held. Acquiring B while
holding A adds the directed edge A→B to the process-wide lock-order
graph; a cycle in that graph is a deadlock waiting for the right
interleaving — the classic ABBA inversion is its 2-node case.

Lock identity is the CONSTRUCTION SITE (file:line), not the instance:
`obs/metrics.py:52` names every Histogram's lock at once, so an
ordering violation between two instances of the same class is caught
even when each individual pair of instances deadlocks only once a year.
Same-site edges (instance i1 of a class locked inside instance i2 of
the same class) are tracked at instance granularity and flagged only
when BOTH orders of one instance pair are observed — nesting two
sibling locks in a consistent order is legal.

CI runs this over the telemetry smoke's full fleet + batch + shed
lifecycle (``python -m tools.telemetry_smoke --racecheck``) and fails
on any inversion. For a demonstration of what a report looks like:

    python tools/racecheck.py --demo
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Iterator, Optional

# the genuine primitives, captured before any monitor patches them —
# the monitor's own bookkeeping must never recurse through a wrapper
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


def _creation_site() -> tuple[str, int]:
    """(file, line) of the frame that called threading.Lock() — skipping
    threading.py internals (Condition/Event/Queue built on Lock should
    blame THEIR caller, the object that owns them)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(("threading.py", "queue.py")):
            return (fn, f.f_lineno)
        f = f.f_back
    return ("<unknown>", 0)


class _TracedLock:
    """Wraps one real lock; reports acquisition ordering to the monitor."""

    _recursive = False

    def __init__(self, monitor: "LockMonitor", site: tuple[str, int]):
        self._lock = (_REAL_RLOCK() if self._recursive else _REAL_LOCK())
        self._mon = monitor
        self.site = site
        # process-unique, never recycled — same-site instance pairs key on
        # this, not id(): CPython reuses ids after GC, and a recycled id
        # would fabricate a phantom both-orders inversion (or mask a real
        # one) between instances that never coexisted
        self.serial = monitor._next_serial()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            # order is recorded BEFORE blocking: the edge exists the
            # moment this thread commits to waiting while holding others
            self._mon._note_wait(self)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._mon._note_acquired(self)
        return ok

    def release(self) -> None:
        self._mon._note_released(self)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def _at_fork_reinit(self) -> None:
        # concurrent.futures.thread dereferences this at IMPORT time
        # (os.register_at_fork(after_in_child=lock._at_fork_reinit)) —
        # without the delegation a lazy ThreadPoolExecutor import while
        # the monitor is installed dies with AttributeError
        self._lock._at_fork_reinit()

    def __repr__(self) -> str:
        return f"<traced {type(self).__name__} @ {self.site[0]}:{self.site[1]}>"


class _TracedRLock(_TracedLock):
    _recursive = True

    def locked(self) -> bool:  # RLock has no .locked() pre-3.12
        fn = getattr(self._lock, "locked", None)
        return fn() if fn is not None else False

    # threading.Condition's duck-typed RLock protocol. Without these it
    # falls back to an acquire(False) ownership probe, which an RLock's
    # reentrancy answers WRONG ("not owned" while owned) — Condition()
    # (default RLock) must keep working under instrumentation.
    def _is_owned(self) -> bool:
        return self._lock._is_owned()

    def _release_save(self):
        # full release of every recursion level: drop all held entries,
        # remembering how many so _acquire_restore can re-add them all —
        # restoring just one would make the monitor forget the lock after
        # the first post-wait release() while the thread still owns it
        held = self._mon._held()
        count = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                count += 1
        return (count, self._lock._release_save())

    def _acquire_restore(self, state) -> None:
        count, inner = state
        self._lock._acquire_restore(inner)
        held = self._mon._held()
        for _ in range(max(1, count)):
            held.append(self)


class Inversion:
    """One lock-order cycle, with a sample acquisition stack per edge."""

    def __init__(self, cycle: list[str], stacks: dict[tuple, list[str]]):
        self.cycle = cycle          # site labels, cycle[0] == cycle[-1]
        self.stacks = stacks        # (a_label, b_label) -> stack lines

    def render(self) -> str:
        out = [" -> ".join(self.cycle)]
        for (a, b), stack in self.stacks.items():
            out.append(f"  edge {a} -> {b} first acquired at:")
            out.extend(f"    {line}" for line in stack)
        return "\n".join(out)


class LockMonitor:
    """Process-wide lock-order graph built from traced acquisitions."""

    def __init__(self, stack_limit: int = 14):
        self.stack_limit = stack_limit
        self._meta = _REAL_LOCK()
        self._tls = threading.local()
        # site -> stable label
        self._sites: dict[tuple[str, int], str] = {}
        # (site_a, site_b) [a != b] -> sample stack (first observation)
        self._edges: dict[tuple, list[str]] = {}
        self._edge_count: dict[tuple, int] = {}
        # same-site nesting: site -> {(serial_a, serial_b): sample stack}
        self._same_site: dict[tuple, dict[tuple, list[str]]] = {}
        self._installed = False
        self.locks_created = 0
        self._serial = 0

    def _next_serial(self) -> int:
        with self._meta:
            self._serial += 1
            return self._serial

    # -- patching ----------------------------------------------------------

    def install(self) -> "LockMonitor":
        """Patch ``threading.Lock``/``RLock``; only locks created AFTER
        this call are traced (install before importing the system under
        test)."""
        if self._installed:
            return self
        mon = self

        def make_lock():
            site = _creation_site()
            mon._register(site)
            return _TracedLock(mon, site)

        def make_rlock():
            site = _creation_site()
            mon._register(site)
            return _TracedRLock(mon, site)

        threading.Lock = make_lock          # type: ignore[assignment]
        threading.RLock = make_rlock        # type: ignore[assignment]
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Restore the real primitives. Already-created traced locks keep
        working (and keep reporting) — only new creations stop."""
        if self._installed:
            threading.Lock = _REAL_LOCK     # type: ignore[assignment]
            threading.RLock = _REAL_RLOCK   # type: ignore[assignment]
            self._installed = False

    def __enter__(self) -> "LockMonitor":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- tracing callbacks (hot; keep allocation-free when possible) -------

    def _register(self, site) -> None:
        with self._meta:
            self.locks_created += 1
            if site not in self._sites:
                short = site[0]
                for marker in ("/localai_tpu/", "/tools/", "/tests/"):
                    i = short.rfind(marker)
                    if i >= 0:
                        short = short[i + 1:]
                        break
                else:
                    short = short.rsplit("/", 1)[-1]
                self._sites[site] = f"{short}:{site[1]}"

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_wait(self, lock: _TracedLock) -> None:
        held = self._held()
        if not held:
            return
        if any(h is lock for h in held):
            return  # reentrant RLock acquire cannot block
        stack: Optional[list[str]] = None
        for h in held:
            if h.site == lock.site:
                key = (h.serial, lock.serial)
                bucket = self._same_site.setdefault(lock.site, {})
                if key not in bucket:
                    if stack is None:
                        stack = self._stack()
                    with self._meta:
                        bucket.setdefault(key, stack)
            else:
                key = (h.site, lock.site)
                if key not in self._edges:
                    if stack is None:
                        stack = self._stack()
                    with self._meta:
                        self._edges.setdefault(key, stack)
                with self._meta:
                    self._edge_count[key] = self._edge_count.get(key, 0) + 1

    def _note_acquired(self, lock: _TracedLock) -> None:
        self._held().append(lock)

    def _note_released(self, lock: _TracedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def _stack(self) -> list[str]:
        frames = traceback.extract_stack(sys._getframe(3),
                                         limit=self.stack_limit)
        return [f"{fr.filename.rsplit('/', 1)[-1]}:{fr.lineno} "
                f"in {fr.name}" for fr in frames]

    # -- analysis ----------------------------------------------------------

    def edges(self) -> dict[tuple, int]:
        """(site_label_a, site_label_b) -> observation count."""
        with self._meta:
            return {
                (self._sites[a], self._sites[b]): n
                for (a, b), n in self._edge_count.items()
            }

    def inversions(self) -> list[Inversion]:
        """Every elementary lock-order cycle observed, plus same-site
        instance pairs seen in both orders."""
        with self._meta:
            adj: dict[tuple, set] = {}
            for a, b in self._edges:
                adj.setdefault(a, set()).add(b)
            edges = dict(self._edges)
            same = {s: dict(pairs) for s, pairs in self._same_site.items()}
            labels = dict(self._sites)
        out: list[Inversion] = []
        for cycle in _cycles(adj):
            stacks = {}
            for a, b in zip(cycle, cycle[1:]):
                stacks[(labels[a], labels[b])] = edges.get((a, b), [])
            out.append(Inversion([labels[s] for s in cycle], stacks))
        for site, pairs in same.items():
            seen = set(pairs)
            for (ia, ib), stack in pairs.items():
                if (ib, ia) in seen and ia < ib:  # report each pair once
                    lbl = labels[site]
                    out.append(Inversion(
                        [f"{lbl}<instance A>", f"{lbl}<instance B>",
                         f"{lbl}<instance A>"],
                        {(f"{lbl}<A>", f"{lbl}<B>"): stack,
                         (f"{lbl}<B>", f"{lbl}<A>"): pairs[(ib, ia)]},
                    ))
        return out

    def report(self) -> str:
        inv = self.inversions()
        with self._meta:
            n_sites = len(self._sites)
            n_edges = len(self._edge_count)
        head = (f"racecheck: {self.locks_created} locks from {n_sites} "
                f"sites, {n_edges} ordered edges, "
                f"{len(inv)} inversion(s)")
        if not inv:
            return head
        return "\n".join([head, ""] + [i.render() for i in inv])


def _cycles(adj: dict[tuple, set]) -> Iterator[list]:
    """Elementary cycles via DFS from each SCC (bounded and simple: the
    lock graphs here are tiny). Each cycle is reported once, anchored at
    its smallest node."""
    sccs = _tarjan(adj)
    for scc in sccs:
        if len(scc) < 2:
            continue
        scc_set = set(scc)
        anchor = min(scc)
        # one representative cycle through the anchor
        path = [anchor]
        seen_cycle = None

        def dfs(node, visited):
            nonlocal seen_cycle
            if seen_cycle is not None:
                return
            for nxt in sorted(adj.get(node, ())):
                if nxt == anchor and len(path) > 1:
                    seen_cycle = path + [anchor]
                    return
                if nxt in scc_set and nxt not in visited:
                    path.append(nxt)
                    visited.add(nxt)
                    dfs(nxt, visited)
                    if seen_cycle is not None:
                        return
                    visited.discard(nxt)
                    path.pop()

        dfs(anchor, {anchor})
        if seen_cycle is not None:
            yield seen_cycle


def _tarjan(adj: dict) -> list[list]:
    """Iterative Tarjan SCC (no recursion limit surprises)."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list[list] = []
    counter = [0]
    nodes = set(adj)
    for vs in adj.values():
        nodes.update(vs)

    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs


# -- CLI demo ---------------------------------------------------------------

def _demo() -> int:
    """Provoke a textbook ABBA inversion and print the report (this is
    what a failing CI racecheck step looks like)."""
    mon = LockMonitor().install()
    try:
        lock_a = threading.Lock()
        lock_b = threading.Lock()
    finally:
        mon.uninstall()

    # the ORDER is the race: the graph records A→B then B→A even though
    # the threads never actually interleave into the deadlock
    def t1():
        with lock_a:
            with lock_b:
                pass

    def t2():
        with lock_b:
            with lock_a:
                pass

    for fn in (t1, t2):
        th = threading.Thread(target=fn)
        th.start()
        th.join()
    print(mon.report())
    return 1 if mon.inversions() else 0


if __name__ == "__main__":
    if "--demo" in sys.argv:
        sys.exit(_demo())
    print(__doc__)
    sys.exit(0)
