"""CI perf smoke gate: fail the PR on a decode-throughput regression.

The north-star bench (bench.py) needs real TPU hardware, so PRs used to
land speed regressions blind (ROADMAP Open item 1). This gate runs the
bench_micro decode measurement on the CI runner's CPU — contiguous AND
paged KV layouts — and fails when either regresses more than
``PERF_SMOKE_TOL`` (default 10%) against the committed floor in
``BASELINE.json``'s ``perf_smoke`` entry.

Raw tok/s numbers do not transfer between machines, so the committed
floor is *normalized*: tok/s divided by a machine-speed index (a fixed
jitted matmul loop's effective GFLOP/s, ``bench_micro.machine_index``)
measured in the same process. The paged/contiguous *ratio* is additionally
gated — it is machine-independent and catches a paged-path regression
even if the normalization drifts. A speculative-lane smoke rides along:
the n-gram self-drafter on a repetitive prompt must keep accept-rate > 0
and tokens-per-dispatch > 1 (absolute gates — acceptance arithmetic is
hardware-independent).

Usage:
    python tools/perf_smoke.py              # gate (CI)
    PERF_SMOKE_UPDATE=1 python tools/perf_smoke.py   # rewrite the floor

Output: one JSON line with the measurements and verdicts; exit 1 on any
gate failure.
"""

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# THE paged/contiguous decode-throughput floor — the ratchet ROADMAP
# item 2 tracks (0.70 → 0.85 with the int4/overlap/autotune round). One
# named constant: the recorded-baseline writer and the absent-key gate
# fallback read the same value, so the floor can never drift between the
# two paths again (ISSUE 14 satellite).
PAGED_OVER_CONTIG_MIN = 0.85
# int4 pays pack/unpack VPU work for its bandwidth saving; on CPU (no
# HBM to save) the honest expectation is "not off a cliff", not "faster"
INT4_OVER_PAGED_MIN = 0.30
# host-overhead ceiling for the pipelined paged decode smoke
# (bench_micro.anatomy_smoke → obs.anatomy host_overhead_fraction): the
# ratchet the fused k-step dispatch work will drive DOWN. The absolute
# cap is deliberately a hair under 1.0: CPU JAX hides device time from
# the sync probe so the estimator saturates ~0.997 there (run-to-run
# spread ~3e-4) — the cap still catches full saturation while the
# recorded observed+headroom value becomes the real gate on hardware
# where the fraction is meaningfully below 1.
HOST_OVERHEAD_CEILING = 0.9995
# additive noise headroom over the observed fraction when recording the
# baseline ceiling (fractions move additively with scheduling jitter,
# unlike throughput's multiplicative noise)
HOST_OVERHEAD_HEADROOM = 0.08

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# two virtual host devices for the meshed-paged smoke (must land before
# the first jax import; the jax_num_cpu_devices config is version-gated,
# so the XLA flag is the portable spelling — single-device measurements
# still run on device 0 only and are unaffected)
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2"
                               ).strip()


def check_bench_fallback() -> list[str]:
    """Hard-fail the gate when the LATEST hardware bench round carries the
    ``paged_fallback`` marker (ROADMAP item 1 calls it a P0: the paged
    Pallas decode kernel died on Mosaic and bench silently measured the
    contiguous layout — the number on the board is not the configuration
    we ship). Only the newest BENCH_r*.json is checked: older rounds are
    history, not the current state of the kernel."""
    rounds = sorted(
        REPO.glob("BENCH_r*.json"),
        key=lambda p: int("".join(ch for ch in p.stem if ch.isdigit()) or 0),
    )
    if not rounds:
        return []
    latest = rounds[-1]
    try:
        data = json.loads(latest.read_text())
    except (OSError, ValueError):
        return []
    blob = json.dumps(data.get("parsed", data))
    if "paged_fallback" in blob:
        return [
            f"{latest.name}: bench fell back to the contiguous KV layout "
            f"(paged_fallback marker) — the paged Pallas kernel is broken "
            f"on hardware (P0)"
        ]
    return []


def _spec_smoke() -> dict:
    """Speculative-lane smoke (ISSUE 11 gate): the n-gram self-drafter
    over a paged tiny engine on a repetitive prompt must achieve a
    positive draft accept-rate and >1 emitted token per verify dispatch
    — the whole point of the verify-k window is amortizing the per-step
    host round-trip, and a regression to ≤1 means the lane is dead
    weight. Deterministic: greedy debug-model decode enters a cycle the
    prompt-lookup drafter picks up."""
    import numpy as np

    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.models.registry import resolve_model
    from localai_tpu.spec import NGramDrafter, SpecEngine

    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(
        tiny.cfg, tiny.params, num_slots=2, max_ctx=256,
        prefill_buckets=[64], kv_dtype="float32",
        paged=True, kv_block_tokens=16,
    )
    eng = SpecEngine(runner, NGramDrafter(2, gamma=4))
    slot = eng.acquire_slot()
    eng.admit(slot, list(b"abc abc abc abc abc abc"), temperature=0.0)
    iters = 0
    while eng.total_windows < 8 and iters < 80:
        iters += 1
        rows = eng.step_spec_async()
        if rows is None:  # lookup miss — plain decode grows the history
            tok = int(runner.step()[slot])
            eng.drafter.observe(slot, [tok])
            continue
        eng.observe_window(np.asarray(rows))
    return {
        "spec_windows": eng.total_windows,
        "spec_accept_rate": round(eng.accept_rate, 4),
        "spec_tokens_per_dispatch": round(eng.tokens_per_dispatch, 4),
        "spec_invariants": runner.allocator.check_invariants(),
    }


def _measure(tol: float) -> dict:
    import jax

    import bench_micro

    idx = bench_micro.machine_index()
    contig = bench_micro.decode_smoke(paged=False)
    paged = bench_micro.decode_smoke(paged=True)
    # int4 decode smoke: the nibble-packed paged pool + fused dequant on
    # the same shape — ratio-gated against the f32 paged number (machine-
    # independent) so a pack/unpack regression or a broken int4 scatter
    # fails the PR even though CPU sees no bandwidth win
    int4 = bench_micro.decode_smoke(paged=True, kv_dtype="int4")
    # dispatch-anatomy smoke (obs.anatomy): host-overhead fraction of the
    # pipelined paged decode — the per-token Python cost ratchet
    anat = bench_micro.anatomy_smoke()
    out = {
        "machine_gflops": round(idx, 2),
        "decode_tok_s_contig": round(contig, 1),
        "decode_tok_s_paged": round(paged, 1),
        "decode_tok_s_int4": round(int4, 1),
        "normalized_contig": round(contig / idx, 4),
        "normalized_paged": round(paged / idx, 4),
        "paged_over_contig": round(paged / contig, 4),
        "int4_over_paged": round(int4 / paged, 4),
        "host_overhead_fraction": anat["host_overhead_fraction"],
        "host_ms_p50": anat["host_ms_p50"],
        "sync_ms_p50": anat["sync_ms_p50"],
        "device_bubble_fraction": anat["device_bubble_fraction"],
        "anatomy_samples": anat["samples"],
        "tolerance": tol,
    }
    # meshed-paged smoke: the same paged decode under a 2-device
    # tensor-parallel mesh (shard_map/pjit serving path). Ratio-gated
    # against the single-device paged number — machine-independent, like
    # paged_over_contig. Skips clean when the runner has <2 devices.
    if len(jax.devices()) >= 2:
        meshed = bench_micro.decode_smoke(paged=True, mesh_devices=2)
        out["decode_tok_s_meshed"] = round(meshed, 1)
        out["meshed_over_paged"] = round(meshed / paged, 4)
    else:
        out["meshed"] = "skipped (<2 devices)"
    out.update(_spec_smoke())
    return out


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    tol = float(os.environ.get("PERF_SMOKE_TOL", "0.10"))

    fallback = check_bench_fallback()
    if fallback:
        # a hardware-confirmed paged fallback fails the PR outright — no
        # amount of CPU-side throughput can excuse shipping the broken
        # kernel configuration
        print(json.dumps({"failures": fallback}))
        print("PERF SMOKE GATE FAILED:", "; ".join(fallback),
              file=sys.stderr)
        return 1

    result = _measure(tol)

    baseline_path = REPO / "BASELINE.json"
    data = json.loads(baseline_path.read_text())
    floor = data.get("perf_smoke")

    if os.environ.get("PERF_SMOKE_UPDATE") == "1" or floor is None:
        # record the floor 8% under the observed value: run-to-run noise on
        # shared CI runners is ~5%, so gating the raw observation at 10%
        # tolerance would flake — the discount keeps the effective gate at
        # ~18% while the machine-independent paged/contig ratio still
        # catches paged-path regressions tightly
        headroom = 0.92
        data["perf_smoke"] = {
            "normalized_contig": round(result["normalized_contig"]
                                       * headroom, 4),
            "normalized_paged": round(result["normalized_paged"]
                                      * headroom, 4),
            "paged_over_contig_min": PAGED_OVER_CONTIG_MIN,
            "int4_over_paged_min": INT4_OVER_PAGED_MIN,
            # ceiling, not floor: observed + additive headroom, capped at
            # the loose absolute — drives DOWN as dispatch overhead shrinks
            "host_overhead_max": round(
                min(HOST_OVERHEAD_CEILING,
                    (result["host_overhead_fraction"] or 1.0)
                    + HOST_OVERHEAD_HEADROOM), 4),
            "note": ("decode tok/s per machine-index GFLOP/s "
                     "(tools/perf_smoke.py), recorded with 8% noise "
                     "headroom; refresh with PERF_SMOKE_UPDATE=1"),
        }
        if os.environ.get("PERF_SMOKE_UPDATE") == "1":
            baseline_path.write_text(json.dumps(data, indent=2) + "\n")
            result["updated_baseline"] = True
        else:
            result["no_baseline"] = True  # first run: record nothing, pass
        print(json.dumps(result))
        return 0

    def gate(res: dict) -> list[str]:
        failures = []
        for key in ("normalized_contig", "normalized_paged"):
            base = floor.get(key)
            if base and res[key] < base * (1 - tol):
                failures.append(
                    f"{key} {res[key]:.4f} < floor {base:.4f} "
                    f"(-{(1 - res[key] / base) * 100:.1f}%)")
        # absent-key fallback is the SAME constant the baseline writer
        # records — the 0.70-written/0.75-assumed drift class is closed
        ratio_min = floor.get("paged_over_contig_min",
                              PAGED_OVER_CONTIG_MIN)
        if res["paged_over_contig"] < ratio_min:
            failures.append(
                f"paged_over_contig {res['paged_over_contig']:.3f} "
                f"< {ratio_min} (paged decode path regressed)")
        # host-overhead ceiling (dispatch anatomy): a new Python cost on
        # the per-dispatch hot path shows up here even when throughput
        # noise hides it. None / zero-sample means the anatomy smoke
        # itself broke — fail loudly rather than skip the gate.
        host_max = floor.get("host_overhead_max", HOST_OVERHEAD_CEILING)
        hof = res.get("host_overhead_fraction")
        if not res.get("anatomy_samples"):
            failures.append(
                "anatomy smoke recorded 0 dispatches "
                "(host-overhead gate has nothing to measure)")
        elif hof is None:
            failures.append(
                "host_overhead_fraction is None (anatomy smoke produced "
                "no attributable dispatch wall time)")
        elif hof > host_max:
            failures.append(
                f"host_overhead_fraction {hof:.4f} > ceiling {host_max} "
                f"(per-dispatch host work regressed)")
        int4_min = floor.get("int4_over_paged_min", INT4_OVER_PAGED_MIN)
        if res.get("int4_over_paged", 0.0) < int4_min:
            failures.append(
                f"int4_over_paged {res.get('int4_over_paged')} "
                f"< {int4_min} (int4 paged decode path regressed)")
        # meshed-paged gate: CPU-mesh decode pays real collective overhead
        # (psum per layer over virtual devices), so the floor is loose —
        # it catches the path BREAKING or falling off a cliff, not noise.
        # Absent when <2 devices (skip-clean).
        meshed_min = floor.get("meshed_over_paged_min", 0.15)
        if ("meshed_over_paged" in res
                and res["meshed_over_paged"] < meshed_min):
            failures.append(
                f"meshed_over_paged {res['meshed_over_paged']:.3f} "
                f"< {meshed_min} (meshed-paged decode path regressed)")
        # speculative-lane gate: absolute (no machine normalization
        # needed — acceptance arithmetic is hardware-independent)
        if res.get("spec_accept_rate", 0.0) <= 0.0:
            failures.append(
                "spec_accept_rate is 0 (the n-gram self-drafter never "
                "got a draft accepted)")
        if res.get("spec_tokens_per_dispatch", 0.0) <= 1.0:
            failures.append(
                f"spec_tokens_per_dispatch "
                f"{res.get('spec_tokens_per_dispatch')} <= 1 (the "
                "verify-k window no longer amortizes dispatches)")
        if res.get("spec_invariants"):
            failures.append(
                f"spec smoke violated block invariants: "
                f"{res['spec_invariants']}")
        return failures

    failures = gate(result)
    if failures:
        # one full re-measurement before failing the PR: a contention
        # spike that survived best-of-N rarely survives a second window
        retry = _measure(tol)
        retry_failures = gate(retry)
        result = {**retry, "first_attempt": result,
                  "retried_after_failure": failures}
        failures = retry_failures
    result["failures"] = failures
    print(json.dumps(result))
    if failures:
        print("PERF SMOKE GATE FAILED:", "; ".join(failures),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
