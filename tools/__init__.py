"""Repo-local developer tooling (not shipped with the localai_tpu package)."""
