"""CI chaos smoke: drive the full stack through scripted faults and prove
the self-healing paths actually heal.

Every scenario arms a deterministic fault schedule (localai_tpu.faults),
runs real traffic through a real engine (the tiny debug model — no
downloads, CPU only), and then asserts the recovery invariants:

  * **no request lost** — every submitted request resolves to tokens or a
    clean ``error`` finish (nothing hangs, nothing disappears);
  * **block conservation** — ``BlockAllocator.check_invariants()`` is
    empty after the dust settles AND every block is back
    (free + cached == total) once all requests drained;
  * **no deadlock** — each scenario completes inside its own deadline
    (the harness itself is the timeout);
  * **shedding recovers** — the SLO admission-control lifecycle trips and
    then clears once the fast window slides;
  * **respawn backoff observed** — a replica whose respawn keeps failing
    is retried on growing, capped holds, and the clock resets on rejoin.

Scenarios (≥6, see ``SCENARIOS``):

  nan_poison        one co-batched request's logits forced NaN → it fails
                    ``error``, its slot quarantines, the OTHER request
                    finishes with byte-identical greedy output
  engine_rebuild    a dispatch wedged past the stall deadline → watchdog
                    trips → supervisor drains handles with clean errors,
                    re-inits the runner, probe dispatch passes, a fresh
                    engine thread serves the next request
  dispatch_raise    a device dispatch raises mid-decode → active requests
                    fail ``error``, the engine keeps serving
  compile_fail      the first dispatch of a program raises (compile
                    failure) → clean errors, next traffic compiles fine
  pool_exhaustion   a tiny block pool holds admissions; a held request
                    cancelled mid-hold releases its place and a successor
                    admits; everything resolves, blocks conserve
  spec_divergence   every speculative draft proposal garbled mid-serving
                    → acceptance collapses but co-batched greedy streams
                    stay byte-identical (per-slot rollback) and the
                    block pool conserves
  fleet_failover    a 2-replica fleet loses one replica pre-stream → the
                    router fails over and the request completes
  live_migration    an in-flight request is migrated between replicas
                    mid-generation (fleet.kveconomy) → greedy output
                    byte-identical to an unmigrated run, zero tokens
                    lost, blocks conserved on BOTH replicas
  sibling_fetch_donor_death
                    a directory-known donor dies mid-TransferPrefix →
                    the stale entry drops, the request re-prefills
                    locally and completes (never errors)
  respawn_backoff   respawns forced to fail → jittered exponential holds
                    grow (and cap), then clear on successful rejoin
  shed_recover      burn-rate shedding trips under a synthetic overload
                    and recovers when the window slides (injected clock)

Cross-host network scenarios (real gRPC workers on 127.0.0.1 ports,
adopted as RemoteReplicas — the fleet's cross-host shape on loopback):

  network_partition one remote's link drops every message and refuses
                    every dial → traffic routes around it with ZERO lost
                    requests, the peer is EVICTED (never respawned), and
                    once the partition heals a backed-off redial rejoins
                    it
  slow_link         one remote delivers each reply slower than
                    LOCALAI_FLEET_RPC_TIMEOUT_S → the dispatch deadline
                    fires, the request fails over (affinity degrades to
                    the healthy peer), nothing is lost
  flapping_peer     a remote evicts, fails several redials (holds grow,
                    capped), rejoins, then flaps AGAIN → the second
                    incident's backoff restarts from the base (reset
                    proven by observation, not trust)
  registry_join     a second remote registers mid-traffic (the
                    /federated/register adoption path) → in-flight and
                    subsequent requests all complete and the newcomer
                    starts taking traffic

Usage:  python -m tools.chaos_smoke [--out chaos_report.json]
        python -m tools.chaos_smoke --only nan_poison,engine_rebuild
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _build_engine(name: str, *, watchdog=None, registry=None, store=None,
                  max_ctx: int = 512, num_slots: int = 4,
                  kv_num_blocks=None, supervisor: bool = False,
                  sup_kwargs=None, spec_gamma: int = 0,
                  multi_step: int = 16):
    """A paged tiny-model engine with isolated telemetry (the process
    registry stays clean for the exposition checks at the end)."""
    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.engine.scheduler import Scheduler
    from localai_tpu.models.registry import resolve_model
    from localai_tpu.obs.engine import EngineTelemetry
    from localai_tpu.obs.metrics import REGISTRY
    from localai_tpu.obs.slo import SLOTracker
    from localai_tpu.obs.trace import TraceStore
    from localai_tpu.utils.tokenizer import ByteTokenizer

    registry = registry or REGISTRY
    store = store or TraceStore()
    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(
        tiny.cfg, tiny.params, num_slots=num_slots, max_ctx=max_ctx,
        prefill_buckets=[16, 32], kv_dtype="float32",
        paged=True, kv_block_tokens=16, prefill_chunk=16,
        kv_num_blocks=kv_num_blocks,
    )
    sched = Scheduler(
        runner, ByteTokenizer(),
        telemetry=EngineTelemetry(
            model=name, store=store, registry=registry,
            slo=SLOTracker(registry=registry, targets={})),
        watchdog=watchdog,
        multi_step=multi_step,
        spec=_spec_engine(runner, spec_gamma) if spec_gamma else None,
    )
    if supervisor:
        from localai_tpu.faults import EngineSupervisor

        EngineSupervisor(sched, registry=registry, **(sup_kwargs or {}))
    return runner, sched


def _spec_engine(runner, gamma: int):
    """Self-drafting speculation lane over the paged runner (the serving
    default shape; localai_tpu.spec)."""
    from localai_tpu.spec import NGramDrafter, SpecEngine

    return SpecEngine(runner, NGramDrafter(runner.num_slots, gamma))


def _req(text: str, **kw):
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.utils.tokenizer import ByteTokenizer

    kw.setdefault("temperature", 0.0)
    return GenRequest(prompt=ByteTokenizer().encode(text), **kw)


def _resolved(handles) -> list[str]:
    """Invariant: no request lost — every handle reached a terminal
    finish. Returns problems."""
    problems = []
    for h in handles:
        if h.finish_reason is None:
            problems.append(f"request {h.id} never resolved")
        elif h.finish_reason not in ("stop", "length", "error", "cancelled"):
            problems.append(
                f"request {h.id} finished {h.finish_reason!r}")
    return problems


def _blocks_conserved(runner) -> list[str]:
    """Invariant: the allocator conserves its pool and, with all traffic
    drained, holds zero live reservations."""
    problems = list(runner.allocator.check_invariants())
    st = runner.allocator.stats()
    if st.free + st.cached != st.total:
        problems.append(
            f"blocks leaked after drain: free {st.free} + cached "
            f"{st.cached} != total {st.total} (used {st.used})")
    return problems


# -- scenarios -------------------------------------------------------------

def scenario_nan_poison() -> dict:
    """One slot's logits poisoned NaN mid-decode: the per-row guard fails
    ONLY that request; a co-batched request must finish byte-identical
    to an unpoisoned run; the slot quarantines and later returns."""
    from localai_tpu import faults

    runner, sched = _build_engine("chaos-nan")
    try:
        ref = sched.generate(_req("co-batched survivor", max_new_tokens=24),
                             timeout=120)
        faults.arm(faults.FaultSpec(site="decode.nan", mode="nan",
                                    match="chaos-poison", times=1))
        poisoned = sched.submit(_req("poison target", max_new_tokens=400,
                                     correlation_id="chaos-poison"))
        survivor = sched.submit(_req("co-batched survivor",
                                     max_new_tokens=24))
        poisoned.result(120)
        survivor.result(120)
        problems = _resolved([poisoned, survivor])
        if poisoned.finish_reason != "error":
            problems.append(
                f"poisoned request finished {poisoned.finish_reason!r}, "
                "not error")
        if survivor.finish_reason not in ("stop", "length"):
            problems.append(
                f"survivor finished {survivor.finish_reason!r}")
        if survivor.token_ids != ref.token_ids:
            problems.append(
                "co-batched survivor's greedy output diverged from the "
                "unpoisoned reference")
        if sched.nan_rows < 1:
            problems.append("nan_rows counter never moved")
        if not sched._quarantined and sched.metrics()[
                "quarantined_slots"] == 0:
            problems.append("poisoned slot was not quarantined")
        # quarantine must RELEASE: run traffic past the window and check
        # all slots admit again
        for _ in range(3):
            sched.generate(_req("post-poison traffic", max_new_tokens=40),
                           timeout=120)
        deadline = time.monotonic() + 30
        while sched._quarantined and time.monotonic() < deadline:
            sched.generate(_req("quarantine drain", max_new_tokens=40),
                           timeout=120)
        if sched._quarantined:
            problems.append("slot never left quarantine")
        problems += _blocks_conserved(runner)
        return {"problems": problems,
                "nan_rows": sched.nan_rows,
                "poisoned_tokens": poisoned.completion_tokens}
    finally:
        faults.clear()
        sched.shutdown()


def scenario_engine_rebuild() -> dict:
    """A dispatch wedged past the stall deadline: the watchdog trips, the
    supervisor drains the stuck handle with a clean error, re-inits the
    runner, the probe dispatch passes, and a subsequent request completes
    on the fresh engine thread — the full escalation ladder."""
    from localai_tpu import faults
    from localai_tpu.obs.metrics import REGISTRY
    from localai_tpu.obs.trace import TraceStore
    from localai_tpu.obs.watchdog import Watchdog

    store = TraceStore()
    wd = Watchdog(deadline=0.5, registry=REGISTRY, store=store,
                  poll_interval=0.1)
    runner, sched = _build_engine(
        "chaos-rebuild", watchdog=wd, store=store, supervisor=True,
        sup_kwargs={"max_rebuilds": 3, "backoff_s": 0.05,
                    "probe_timeout_s": 60.0})
    try:
        warm = sched.generate(_req("warm up", max_new_tokens=8), timeout=120)
        wedged = sched.submit(_req("about to wedge", max_new_tokens=400))
        # arm only once the request is actively decoding: otherwise the
        # hang can fire on a leftover pipelined drain of the warmup and
        # the rebuild drains an empty batch instead of this handle
        deadline = time.monotonic() + 60
        while wedged.t_first_token is None and time.monotonic() < deadline:
            time.sleep(0.02)
        faults.arm(faults.FaultSpec(site="engine.drain", mode="hang",
                                    delay_s=3.0, times=1))
        wedged.result(90)
        problems = _resolved([warm, wedged])
        if wedged.finish_reason != "error":
            problems.append(
                f"wedged request finished {wedged.finish_reason!r}, "
                "not a clean error")
        deadline = time.monotonic() + 60
        while sched.rebuilds == 0 and time.monotonic() < deadline:
            time.sleep(0.1)
        if sched.rebuilds != 1:
            problems.append(f"expected 1 rebuild, saw {sched.rebuilds}")
        if sched.failed:
            problems.append("engine marked failed on a recoverable stall")
        faults.clear()
        after = sched.generate(_req("after rebuild", max_new_tokens=8),
                               timeout=120)
        if after.finish_reason not in ("stop", "length"):
            problems.append(
                f"post-rebuild request finished {after.finish_reason!r}")
        stall_traces = [t for t in store.recent(limit=10, kind="stall")]
        if not stall_traces:
            problems.append("no forensic stall trace recorded")
        problems += _blocks_conserved(runner)
        return {"problems": problems, "rebuilds": sched.rebuilds,
                "post_rebuild_tokens": after.completion_tokens}
    finally:
        faults.clear()
        sched.shutdown()
        wd.stop()


def scenario_dispatch_raise() -> dict:
    """A device dispatch raising mid-decode: the engine's catch-all fails
    the active requests cleanly and keeps serving."""
    from localai_tpu import faults

    runner, sched = _build_engine("chaos-raise")
    try:
        faults.arm(faults.FaultSpec(site="engine.dispatch", mode="raise",
                                    after=2, times=1))
        handles = [sched.submit(_req(f"dispatch victim {i}",
                                     max_new_tokens=200))
                   for i in range(2)]
        for h in handles:
            h.result(120)
        problems = _resolved(handles)
        if not any(h.finish_reason == "error" for h in handles):
            problems.append("no request saw the injected dispatch error")
        after = sched.generate(_req("after dispatch error",
                                    max_new_tokens=8), timeout=120)
        if after.finish_reason not in ("stop", "length"):
            problems.append(
                f"post-error request finished {after.finish_reason!r}")
        problems += _blocks_conserved(runner)
        return {"problems": problems,
                "finishes": [h.finish_reason for h in handles]}
    finally:
        faults.clear()
        sched.shutdown()


def scenario_compile_fail() -> dict:
    """The first dispatch of the decode program raises (a compile
    failure): clean errors, and the NEXT dispatch compiles and serves."""
    from localai_tpu import faults

    faults.arm(faults.FaultSpec(site="engine.compile", mode="raise",
                                match="decode", times=1))
    runner, sched = _build_engine("chaos-compile")
    try:
        first = sched.submit(_req("compile victim", max_new_tokens=16))
        first.result(120)
        problems = _resolved([first])
        if first.finish_reason != "error":
            problems.append(
                f"compile-failure request finished "
                f"{first.finish_reason!r}, not error")
        faults.clear()
        after = sched.generate(_req("after compile failure",
                                    max_new_tokens=8), timeout=120)
        if after.finish_reason not in ("stop", "length"):
            problems.append(
                f"post-compile-failure request finished "
                f"{after.finish_reason!r}")
        problems += _blocks_conserved(runner)
        return {"problems": problems}
    finally:
        faults.clear()
        sched.shutdown()


def scenario_pool_exhaustion() -> dict:
    """Block-pool exhaustion holds admissions; a cancel racing the hold
    queue releases its place and a successor admits; every request
    resolves and every block returns."""
    runner, sched = _build_engine("chaos-pool", max_ctx=256,
                                  kv_num_blocks=25)  # 24 allocatable
    try:
        # each request reserves ceil((prompt+new+1)/16) blocks; two ~12-
        # block reservations fill the 24-block pool, the third holds
        big = [sched.submit(_req("pool filler " * 4, max_new_tokens=150))
               for _ in range(2)]
        held = sched.submit(_req("held by exhaustion", max_new_tokens=150))
        time.sleep(0.5)
        if held.finish_reason is not None:
            return {"problems": ["third request was not held "
                                 f"({held.finish_reason})"]}
        # cancel while parked in the hold queue: its place frees and a
        # successor admits once the pool drains
        held.cancel()
        successor = sched.submit(_req("held successor", max_new_tokens=8))
        held.result(120)
        for h in big:
            h.result(180)
        successor.result(180)
        problems = _resolved(big + [held, successor])
        if held.finish_reason != "cancelled":
            problems.append(
                f"cancelled held request finished {held.finish_reason!r}")
        if successor.finish_reason not in ("stop", "length"):
            problems.append(
                f"successor finished {successor.finish_reason!r}")
        problems += _blocks_conserved(runner)
        st = runner.allocator.stats()
        return {"problems": problems,
                "watermark": st.high_watermark, "total": st.total}
    finally:
        sched.shutdown()


def scenario_spec_divergence() -> dict:
    """spec.draft chaos: every drafter proposal replaced with divergent
    garbage tokens mid-serving. Acceptance collapses, but the accept scan
    emits the target's own samples — so BOTH co-batched greedy streams
    must stay byte-identical to the no-fault reference, the per-slot
    rollback must conserve blocks (check_invariants clean after every
    drain via LOCALAI_KV_CHECK), and nothing may leak once drained."""
    from localai_tpu import faults

    # a huge logit bias forces a cyclic greedy stream so the n-gram
    # self-drafter actually proposes (deterministic windows to garble).
    # multi_step=4: the speculation pre-gate reads resident records that
    # lag by the in-flight dispatch, so with the default 16-step
    # dispatches a 32-token request would finish before the lookup
    # candidate becomes visible — real generations are orders of
    # magnitude longer, chaos requests are not
    kw_a = dict(max_new_tokens=32, logit_bias={97: 1e4}, ignore_eos=True)
    kw_b = dict(max_new_tokens=32, logit_bias={98: 1e4}, ignore_eos=True)

    runner, sched = _build_engine("chaos-spec-ref", spec_gamma=4,
                                  multi_step=4)
    try:
        ra = sched.submit(_req("spec target stream", **kw_a))
        rb = sched.submit(_req("co-batched bystander", **kw_b))
        ra.result(120)
        rb.result(120)
        ref = (ra.token_ids, rb.token_ids)
        ref_windows = sched.spec.total_windows
    finally:
        sched.shutdown()

    runner, sched = _build_engine("chaos-spec", spec_gamma=4,
                                  multi_step=4)
    try:
        faults.arm(faults.FaultSpec(site="spec.draft", mode="garble",
                                    times=0))
        ga = sched.submit(_req("spec target stream", **kw_a))
        gb = sched.submit(_req("co-batched bystander", **kw_b))
        ga.result(120)
        gb.result(120)
        problems = _resolved([ga, gb])
        if (ga.token_ids, gb.token_ids) != ref:
            problems.append(
                "greedy streams diverged under garbled drafts (rollback "
                "must make rejected windows invisible)")
        if ref_windows < 1:
            problems.append("reference run never dispatched a spec window")
        if sched.spec.total_windows < 1:
            problems.append("garbled run never dispatched a spec window")
        fired = sum(s["fired"] for s in faults.snapshot()
                    if s["site"] == "spec.draft")
        if fired < 1:
            problems.append("spec.draft fault never fired")
        inv = runner.allocator.check_invariants()
        if inv:
            problems.append(f"invariants after garbled windows: {inv}")
        if sched.kv_invariant_violations:
            problems.append(
                f"{sched.kv_invariant_violations} per-drain invariant "
                "violations during the garbled run")
        problems += _blocks_conserved(runner)
        return {"problems": problems,
                "ref_windows": ref_windows,
                "garbled_windows": sched.spec.total_windows,
                "garbled_accept_rate": round(sched.spec.accept_rate, 4),
                "faults_fired": fired}
    finally:
        faults.clear()
        sched.shutdown()


def _build_fleet(name: str, *, replicas: int = 2):
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.models.manager import build_serving_model

    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": name, "model": "debug:tiny", "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 8},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16},
    })

    def factory(rid, role):
        return InProcessReplica(
            rid, role, lambda: build_serving_model(mcfg, app))

    return FleetServingModel(mcfg, app, factory, replicas=replicas,
                             prefill_replicas=0, disagg_threshold=10_000)


def scenario_fleet_failover() -> dict:
    """One replica's stream dies before it ever yields: the fleet
    scheduler fails over to the surviving replica and the request
    completes — then the dead replica's respawn rejoins it."""
    from localai_tpu import faults

    fm = _build_fleet("chaos-fleet")
    try:
        warm = fm.scheduler.submit(_req("fleet warmup", max_new_tokens=6))
        warm.result(180)
        # kill whichever replica the next request routes to, pre-stream:
        # raise on the FIRST reply of either replica's next stream
        faults.arm(faults.FaultSpec(site="worker.stream", mode="raise",
                                    times=1))
        victim = fm.scheduler.submit(
            _req("failover me please", max_new_tokens=6))
        victim.result(180)
        problems = _resolved([warm, victim])
        if victim.finish_reason not in ("stop", "length"):
            problems.append(
                f"failover request finished {victim.finish_reason!r} "
                f"(failovers={fm.scheduler.failovers})")
        if fm.scheduler.failovers < 1:
            problems.append("no failover recorded")
        return {"problems": problems,
                "failovers": fm.scheduler.failovers,
                "routed": dict(fm.router.routed)}
    finally:
        faults.clear()
        fm.close()


def _fleet_blocks_conserved(fm, timeout: float = 10.0) -> list[str]:
    """Invariant: with all traffic drained, EVERY in-process replica's
    allocator conserves its pool. Donor-side release after a cancel or
    migration drains asynchronously — poll until clean or timeout."""
    deadline = time.monotonic() + timeout
    while True:
        problems = []
        for r in fm.pool.replicas:
            runner = getattr(getattr(r, "sm", None), "runner", None)
            if runner is not None:
                problems += [f"{r.id}: {p}"
                             for p in _blocks_conserved(runner)]
        if not problems or time.monotonic() > deadline:
            return problems
        time.sleep(0.1)


def scenario_live_migration() -> dict:
    """An in-flight request is migrated between replicas mid-generation
    (fleet.kveconomy live slot migration): the donor snapshots its KV at
    a dispatch boundary, the destination resumes from the transferred
    prefix + full token record, and the greedy output is byte-identical
    to an unmigrated run — zero tokens lost, usage spliced across both
    halves, blocks conserved on BOTH replicas."""
    prompt = "migrate this request between replicas mid-generation"
    fm = _build_fleet("chaos-migrate")
    try:
        ref = fm.scheduler.submit(_req(prompt, max_new_tokens=64))
        ref.result(180)
        problems = _resolved([ref])
        migrated = False
        h = ref
        for _ in range(4):  # racing generation: retry if it finishes first
            h = fm.scheduler.submit(_req(prompt, max_new_tokens=64))
            deadline = time.monotonic() + 60
            while h.t_first_token is None and time.monotonic() < deadline:
                time.sleep(0.005)
            if fm.scheduler.migrate_inflight(h):
                migrated = True
                break
            h.result(180)  # finished before the migration landed — retry
        h.result(180)
        problems += _resolved([h])
        if not migrated:
            problems.append("migrate_inflight never landed mid-generation")
        if h.finish_reason not in ("stop", "length"):
            problems.append(
                f"migrated request finished {h.finish_reason!r}")
        if h.text != ref.text:
            problems.append(
                f"migrated output diverged from the unmigrated run: "
                f"{h.text!r} != {ref.text!r}")
        if h.completion_tokens != ref.completion_tokens:
            problems.append(
                f"usage splice lost tokens: {h.completion_tokens} != "
                f"{ref.completion_tokens}")
        if migrated and fm.scheduler.migrations < 1:
            problems.append("migration counter never incremented")
        problems += _fleet_blocks_conserved(fm)
        return {"problems": problems,
                "migrations": fm.scheduler.migrations,
                "migration_fallbacks": fm.scheduler.migration_fallbacks,
                "completion_tokens": h.completion_tokens}
    finally:
        fm.close()


def scenario_sibling_fetch_donor_death() -> dict:
    """The directory routes a request at the replica whose warm KV it
    tracks; that holder dies pre-stream (forcing a failover away from
    the warm KV) and dies AGAIN mid-TransferPrefix when the failover
    replica tries to pull the prefix from it as a sibling donor: the
    stale directory entry is dropped, the request re-prefills locally
    and completes — a dying donor never becomes a request error."""
    from localai_tpu import faults
    from localai_tpu.fleet.router import affinity_key
    from localai_tpu.utils.tokenizer import ByteTokenizer

    head = ("shared prefix head " * 5).strip()  # 94 tokens > 4×16 blocks
    fm = _build_fleet("chaos-donor")
    try:
        warm = fm.scheduler.submit(_req(head + " warm", max_new_tokens=6))
        warm.result(180)
        problems = _resolved([warm])
        tokens = ByteTokenizer().encode(head + " again")
        key = affinity_key(tokens, block_tokens=fm.router.block_tokens,
                           blocks=fm.router.affinity_blocks)
        holder = fm.scheduler.directory.holder(
            key, [r.id for r in fm.pool.replicas])
        if holder is None:
            problems.append("warm request never registered in directory")
            return {"problems": problems}
        faults.arm(faults.FaultSpec(site="worker.stream", mode="raise",
                                    match=holder, times=1))
        faults.arm(faults.FaultSpec(site="fleet.sibling", mode="raise",
                                    match=holder, times=1))
        h = fm.scheduler.submit(_req(head + " again", max_new_tokens=6))
        h.result(180)
        problems += _resolved([h])
        if h.finish_reason not in ("stop", "length"):
            problems.append(
                f"request finished {h.finish_reason!r} — a dead donor "
                f"must degrade to a re-prefill, never an error")
        if fm.scheduler.sibling_fallbacks < 1:
            problems.append("sibling fetch never fell back")
        if fm.scheduler.directory.holder(key, [holder]) is not None:
            problems.append("stale directory entry survived the fallback")
        fired = {s["site"]: s["fired"] for s in faults.snapshot()}
        if not fired.get("fleet.sibling"):
            problems.append(f"fleet.sibling fault never fired: {fired}")
        problems += _fleet_blocks_conserved(fm)
        return {"problems": problems,
                "sibling_fallbacks": fm.scheduler.sibling_fallbacks,
                "directory": fm.scheduler.directory.stats(),
                "routed": dict(fm.router.routed)}
    finally:
        faults.clear()
        fm.close()


def scenario_respawn_backoff() -> dict:
    """A dead replica whose respawn keeps failing: retries are spaced by
    growing jittered-exponential holds (capped), and a successful rejoin
    resets the backoff to zero."""
    from localai_tpu import faults

    fm = _build_fleet("chaos-respawn")
    pool = fm.pool
    try:
        pool.respawn_backoff_base = 0.2
        pool.respawn_backoff_cap = 1.0
        victim = pool.replicas[0]
        faults.arm(faults.FaultSpec(site="fleet.respawn", mode="raise",
                                    match=victim.id, times=3))
        victim.kill()
        pool.note_failure(victim)
        backoffs = []
        deadline = time.monotonic() + 60
        while len(backoffs) < 3 and time.monotonic() < deadline:
            pool.poll_once()
            b = pool.respawn_backoff_s.get(victim.id)
            if b is not None and (not backoffs or b != backoffs[-1]):
                backoffs.append(b)
            time.sleep(0.1)
        problems = []
        if len(backoffs) < 3:
            problems.append(
                f"expected 3 failed-respawn holds, saw {backoffs}")
        else:
            if not backoffs[1] > backoffs[0]:
                problems.append(f"backoff did not grow: {backoffs}")
            if any(b > pool.respawn_backoff_cap for b in backoffs):
                problems.append(f"backoff exceeded cap: {backoffs}")
        # the schedule is exhausted (times=3): the next retry succeeds
        # and must reset the backoff clock
        deadline = time.monotonic() + 60
        while (victim.state != "healthy"
               and time.monotonic() < deadline):
            pool.poll_once()
            time.sleep(0.1)
        if victim.state != "healthy":
            problems.append(
                f"replica never rejoined (state {victim.state})")
        if pool.respawn_backoff_s.get(victim.id):
            problems.append("backoff did not reset on rejoin")
        h = fm.scheduler.submit(_req("post respawn", max_new_tokens=6))
        h.result(180)
        problems += _resolved([h])
        return {"problems": problems, "backoffs": backoffs,
                "respawns": pool.respawns}
    finally:
        faults.clear()
        fm.close()


# -- cross-host network scenarios ------------------------------------------
# (real gRPC workers bound to 127.0.0.1 ports, adopted as RemoteReplicas:
# the same dial/stream/LoadModel path a real NIC carries, on loopback)


def _remote_fleet(name: str, n: int = 2, *, rpc_timeout_s=None):
    """``n`` in-thread gRPC workers + a FleetServingModel that adopts
    them as remotes (0 local replicas). Returns (fm, workers, addrs);
    ``workers`` keeps the servicers so the scenarios can audit each
    peer's BlockAllocator after the dust settles."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.worker.server import BackendServicer, serve_worker

    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": name, "model": "debug:tiny", "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 8},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16,
                   # the network scenarios measure LINK behavior under a
                   # chaos-scale deadline; speculation's lazy verify-
                   # program compile would add seconds of legitimate
                   # first-window silence (spec chaos coverage lives in
                   # scenario_spec_divergence)
                   "spec": False},
    })
    workers = []
    addrs = []
    for _ in range(n):
        sv = BackendServicer()
        server, port = serve_worker("127.0.0.1:0", servicer=sv,
                                    block=False)
        workers.append((server, sv))
        addrs.append(f"127.0.0.1:{port}")
    fm = FleetServingModel(mcfg, app, lambda rid, role: None, replicas=0,
                           remote_hosts=addrs, disagg_threshold=1 << 30,
                           rpc_timeout_s=rpc_timeout_s)
    return fm, workers, addrs


def _stop_workers(workers) -> None:
    for server, sv in workers:
        try:
            sv.shutdown()
        finally:
            server.stop(grace=None)


def _remote_blocks_conserved(workers, settle_s: float = 15.0) -> list[str]:
    """Block conservation on every PEER's allocator — a partition must
    not leak reservations on either side of the wire. An abandoned
    dispatch's cancel propagates asynchronously (the peer's engine may
    still be draining its last batch), so transient occupancy gets
    ``settle_s`` to clear before it counts as a leak."""
    deadline = time.monotonic() + settle_s
    while True:
        problems = []
        for _, sv in workers:
            sm = sv._sm
            if sm is None:
                continue
            for p in _blocks_conserved(sm.runner):
                problems.append(f"peer {sm.name}: {p}")
        if not problems or time.monotonic() >= deadline:
            return problems
        time.sleep(0.25)


def scenario_network_partition() -> dict:
    """A partition eats every message to (and dial of) one remote: all
    traffic completes via route-around — zero lost requests — the peer is
    evicted (not respawned), and after the partition heals a backed-off
    redial returns it to the ring."""
    from localai_tpu import faults

    fm, workers, _ = _remote_fleet("chaos-partition")
    pool = fm.pool
    pool.redial_backoff_base = 0.2
    pool.redial_backoff_cap = 1.0
    try:
        warm = [fm.scheduler.submit(
            _req(f"pre-partition warmup {i}", max_new_tokens=6))
            for i in range(2)]
        for h in warm:
            h.result(120)
        victim = pool.replicas[0]
        # the partition: every stream message dropped, every dial refused
        faults.arm(faults.FaultSpec(site="fleet.transport", mode="raise",
                                    match=victim.id, times=0))
        faults.arm(faults.FaultSpec(site="fleet.dial", mode="raise",
                                    match=victim.id, times=0))
        traffic = [fm.scheduler.submit(
            _req(f"partitioned traffic {i} with enough prompt length",
                 max_new_tokens=6)) for i in range(6)]
        for h in traffic:
            h.result(120)
        problems = _resolved(warm + traffic)
        lost = [h.id for h in traffic
                if h.finish_reason not in ("stop", "length")]
        if lost:
            problems.append(
                f"requests lost to the partition: {lost} "
                f"({[h.finish_reason for h in traffic]})")
        deadline = time.monotonic() + 30
        while victim.state != "evicted" and time.monotonic() < deadline:
            pool.poll_once()
            time.sleep(0.05)
        if victim.state != "evicted":
            problems.append(
                f"partitioned remote is {victim.state!r}, not evicted")
        if pool.evictions < 1:
            problems.append("eviction counter never moved")
        # requests keep landing on the survivor while the victim is out
        pick, _ = fm.router.route(
            _req("route check prompt, long enough for a block").prompt)
        if pick.id == victim.id:
            problems.append("router still places traffic on the "
                            "partitioned remote")
        # heal the partition: the next redial (past its hold) rejoins
        faults.clear()
        deadline = time.monotonic() + 60
        while victim.state != "healthy" and time.monotonic() < deadline:
            pool.poll_once()
            time.sleep(0.05)
        if victim.state != "healthy":
            problems.append(
                f"remote never rejoined after the partition healed "
                f"(state {victim.state})")
        if pool.redials < 1:
            problems.append("redial counter never moved")
        if pool.redial_backoff_s.get(victim.id):
            problems.append("redial backoff did not reset on rejoin")
        after = fm.scheduler.submit(_req("post-heal request",
                                         max_new_tokens=6))
        after.result(120)
        problems += _resolved([after])
        problems += _remote_blocks_conserved(workers)
        return {"problems": problems,
                "evictions": pool.evictions, "redials": pool.redials,
                "failovers": fm.scheduler.failovers}
    finally:
        faults.clear()
        fm.close()
        _stop_workers(workers)


def scenario_slow_link() -> dict:
    """One remote's link crawls: each reply arrives slower than the RPC
    deadline. The dispatch deadline fires (localai_fleet_rpc_deadline_
    exceeded_total), the request fails over — affinity degrades to the
    healthy peer — and nothing is lost."""
    from localai_tpu import faults
    from localai_tpu.obs.metrics import REGISTRY
    from localai_tpu.worker.serving import predict_options

    fm, workers, _ = _remote_fleet("chaos-slowlink", rpc_timeout_s=0.75)
    try:
        # warm BOTH peers directly on the exact prompt SHAPES the chaos
        # traffic uses — twice per peer, because the paged engine
        # compiles lazily per shape: the first family member takes the
        # fresh chunked-prefill program, the second takes the prefix-
        # SHARED resume program (the warm prompt seeded the block pool's
        # prefix sharing). Either compile is seconds of legitimate
        # silence, and a cold peer would trip the tight chaos-scale
        # deadline for reasons that are not the link under test — the
        # production sizing rule is deadline > worst-case queue wait +
        # TTFT
        for r in fm.pool.replicas:
            for tag in ("98", "99"):
                opts = predict_options(_req(
                    f"slow link shared prompt prefix for affinity {tag}",
                    max_new_tokens=5))
                for _ in r.predict_stream(opts):
                    pass
        # the victim must be the affinity TARGET of the traffic family —
        # otherwise the slow link sits on a replica the prompts never
        # reach and nothing is under test
        victim, _ = fm.router.route(_req(
            "slow link shared prompt prefix for affinity 00").prompt)
        faults.arm(faults.FaultSpec(site="fleet.transport", mode="sleep",
                                    delay_s=2.0, match=victim.id, times=0))
        # an affinity-length prompt family: the same prefix keeps hashing
        # to the same ring slot, so if that slot is the victim the
        # deadline + failover path runs every time. Sequential on
        # purpose: the inactivity deadline covers queue wait too, so a
        # concurrent stampede of failovers onto the 2-slot survivor
        # would trip ITS deadline by starvation — that is the deadline-
        # sizing rule (deadline > worst-case queue wait + TTFT), not the
        # slow link under test
        traffic = []
        for i in range(5):
            # constant prompt length (same compiled shapes as the
            # warmup); the differing digits sit past the full-block
            # affinity window, so the family shares one ring key
            h = fm.scheduler.submit(
                _req(f"slow link shared prompt prefix for affinity {i:02d}",
                     max_new_tokens=5))
            h.result(120)
            traffic.append(h)
        problems = _resolved(traffic)
        lost = [h.id for h in traffic
                if h.finish_reason not in ("stop", "length")]
        if lost:
            problems.append(f"requests lost to the slow link: {lost}")
        if fm.scheduler.failovers < 1:
            problems.append(
                "no failover — the slow link never tripped the deadline "
                f"(victim dispatched={victim.dispatched})")
        expo = REGISTRY.render()
        if "localai_fleet_rpc_deadline_exceeded_total" not in expo:
            problems.append(
                "localai_fleet_rpc_deadline_exceeded_total never rendered")
        problems += _remote_blocks_conserved(workers)
        return {"problems": problems,
                "failovers": fm.scheduler.failovers,
                "routed": dict(fm.router.routed)}
    finally:
        faults.clear()
        fm.close()
        _stop_workers(workers)


def scenario_flapping_peer() -> dict:
    """A flapping remote: evicted, fails several redials (holds grow and
    cap), rejoins — then flaps again. The second incident's first hold
    must start back at the base: a reset that isn't observed is a reset
    that doesn't exist."""
    from localai_tpu import faults

    fm, workers, _ = _remote_fleet("chaos-flap")
    pool = fm.pool
    pool.redial_backoff_base = 0.2
    pool.redial_backoff_cap = 0.6
    try:
        victim = pool.replicas[0]

        def flap(n_fails: int) -> list[float]:
            # dial refusals: 1 for note_failure's confirm + n_fails
            # failed redial attempts, then the schedule exhausts and the
            # next redial succeeds
            faults.arm(faults.FaultSpec(site="fleet.dial", mode="raise",
                                        match=victim.id,
                                        times=1 + n_fails))
            pool.note_failure(victim)
            holds: list[float] = []
            deadline = time.monotonic() + 60
            while victim.state != "healthy" and time.monotonic() < deadline:
                pool.poll_once()
                b = pool.redial_backoff_s.get(victim.id)
                if b is not None and (not holds or b != holds[-1]):
                    holds.append(b)
                time.sleep(0.05)
            return holds

        problems = []
        first = flap(3)
        if victim.state != "healthy":
            problems.append("victim never rejoined after first flap")
        if len(first) < 3:
            problems.append(f"expected 3 growing holds, saw {first}")
        else:
            if not first[1] > first[0]:
                problems.append(f"backoff did not grow: {first}")
            if any(b > pool.redial_backoff_cap for b in first):
                problems.append(f"backoff exceeded cap: {first}")
        if pool.redial_backoff_s.get(victim.id):
            problems.append("backoff did not reset after first rejoin")
        second = flap(1)
        if victim.state != "healthy":
            problems.append("victim never rejoined after second flap")
        # ±25% jitter bands: base 0.2 → ≤0.25; second doubling ≥0.3 — a
        # leaked failure count would start the second flap past the base
        if second and second[0] > pool.redial_backoff_base * 1.25:
            problems.append(
                f"second incident started at {second[0]:.2f}s — the "
                "backoff clock did not reset on rejoin")
        if pool.evictions < 2:
            problems.append(f"expected 2 evictions, saw {pool.evictions}")
        if pool.redials < 2:
            problems.append(f"expected 2 redials, saw {pool.redials}")
        h = fm.scheduler.submit(_req("post-flap request", max_new_tokens=6))
        h.result(120)
        problems += _resolved([h])
        problems += _remote_blocks_conserved(workers)
        return {"problems": problems, "first": first, "second": second,
                "evictions": pool.evictions, "redials": pool.redials}
    finally:
        faults.clear()
        fm.close()
        _stop_workers(workers)


def scenario_registry_join() -> dict:
    """A second remote registers mid-traffic (the /federated/register
    adoption path): nothing in flight is disturbed, the consistent-hash
    ring remaps only its share, and the newcomer starts taking traffic."""
    import threading

    from localai_tpu.worker.server import BackendServicer, serve_worker

    fm, workers, _ = _remote_fleet("chaos-join", n=1)
    extra = None
    try:
        problems = []
        handles = []
        stop = threading.Event()

        def traffic() -> None:
            i = 0
            while not stop.is_set() and i < 12:
                h = fm.scheduler.submit(
                    _req(f"join traffic {i}", max_new_tokens=4))
                handles.append(h)
                h.result(120)
                i += 1

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        time.sleep(0.3)  # traffic in flight before the join
        sv = BackendServicer()
        server, port = serve_worker("127.0.0.1:0", servicer=sv,
                                    block=False)
        extra = (server, sv)
        verdict = fm.adopt_remote(f"127.0.0.1:{port}")
        t.join(120)
        stop.set()
        if not verdict["adopted"] or verdict["state"] != "healthy":
            problems.append(f"mid-traffic join failed: {verdict}")
        problems += _resolved(handles)
        lost = [h.id for h in handles
                if h.finish_reason not in ("stop", "length")]
        if lost:
            problems.append(f"requests lost across the join: {lost}")
        if fm.pool.adoptions < 1:
            problems.append("adoption counter never moved")
        # short prompts place least-loaded: the fresh peer (0 dispatched)
        # must start absorbing traffic
        joined = fm.pool.get(verdict["id"])
        for i in range(4):
            h = fm.scheduler.submit(_req(f"[{i}]", max_new_tokens=3))
            h.result(120)
            problems += _resolved([h])
        if joined is None or joined.dispatched < 1:
            problems.append("joined remote never served a request")
        problems += _remote_blocks_conserved(workers + [extra])
        return {"problems": problems, "verdict": verdict,
                "joined_dispatched": joined.dispatched if joined else 0,
                "requests": len(handles)}
    finally:
        fm.close()
        _stop_workers(workers)
        if extra is not None:
            _stop_workers([extra])


def scenario_shed_recover() -> dict:
    """SLO burn-rate shedding trips under a synthetic overload and
    recovers once the fast window slides (injected clock) — the
    admission-control half of the recovery story."""
    from localai_tpu.obs.metrics import Registry
    from localai_tpu.obs.slo import SLOTracker

    reg = Registry()
    t = {"now": 1000.0}
    slo = SLOTracker(registry=reg, clock=lambda: t["now"],
                     targets={"ttft_ms": 0.001}, burn_threshold=1.0,
                     recover_burn=1.0, min_events=3)
    problems = []
    for _ in range(4):
        slo.observe("chaos-shed", ttft_ms=50.0, e2e_ms=80.0)
    if not slo.should_shed("chaos-shed"):
        problems.append("overload did not trip shedding")
    slo.shed("chaos-shed")
    t["now"] += 120.0
    if slo.should_shed("chaos-shed"):
        problems.append("shedding did not recover after the window slid")
    return {"problems": problems}


def scenario_scale_out_under_spike() -> dict:
    """A 1-replica autoscaled fleet rides a seeded arrival spike: the
    controller's queue-depth signal adds capacity, every request resolves
    cleanly, and every replica's block pool conserves after the drain."""
    from localai_tpu.fleet.autoscale import (AutoscaleConfig,
                                             AutoscaleController)
    from tools.loadgen import EngineSink, LoadGen

    fm = _build_fleet("chaos-spike", replicas=1)
    auto = AutoscaleController(fm, config=AutoscaleConfig(
        min_replicas=1, max_replicas=3, interval_s=0.1,
        in_idle_s=0.0, zero_idle_s=0.0,   # scale-out only: no retirement
        out_queue_depth=1.5, out_cooldown_s=0.5))
    fm.autoscaler = auto
    try:
        auto.start()
        gen = LoadGen(mix={"chat": 1.0}, rate=6.0, seed=23, max_tokens=6,
                      profile="spike", spike_start_s=0.3, spike_len_s=3.0,
                      spike_mult=8.0)
        summary = gen.run(EngineSink(fm, max_tokens=6), total=24,
                          timeout_s=300.0)
        problems = []
        bad = {r: n for r, n in summary["outcomes"].items()
               if r not in ("stop", "length")}
        if bad or summary["errors"]:
            problems.append(f"spike traffic failed: {bad} "
                            f"{summary['errors'][:3]}")
        if auto.decisions["scale_out"] < 1:
            problems.append(
                f"no scale-out under the spike ({auto.decisions})")
        healthy = len(fm.pool.healthy("decode"))
        if healthy < 2:
            problems.append(f"fleet still at {healthy} replica(s) after "
                            f"the spike")
        problems += _fleet_blocks_conserved(fm)
        return {"problems": problems, "decisions": dict(auto.decisions),
                "healthy": healthy, "outcomes": summary["outcomes"]}
    finally:
        auto.stop()
        fm.close()


def scenario_scale_in_zero_lost() -> dict:
    """Drain-based scale-in mid-traffic: a replica is retired while it
    serves an in-flight request — the drain live-migrates the slot to
    the survivor, the request completes (nothing lost, nothing errored),
    and BOTH replicas' block pools conserve."""
    fm = _build_fleet("chaos-scalein")
    try:
        warm = fm.scheduler.submit(_req("scale-in warmup",
                                        max_new_tokens=6))
        warm.result(180)
        problems = []
        victim = None
        victim_h = None
        res = {}
        # the drain migrates mid-GENERATION (KV exports at a dispatch
        # boundary): wait for the first token, and retry if the racing
        # generation finishes before the drain lands
        for _ in range(4):
            victim_h = fm.scheduler.submit(
                _req("drain me to the survivor", max_new_tokens=64))
            deadline = time.monotonic() + 60.0
            while (victim_h.t_first_token is None
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            entry = fm.scheduler._active.get(victim_h.id)
            if entry is None or victim_h.finish_reason is not None:
                victim_h.result(180)
                continue
            victim = entry[1]
            res = fm.scheduler.drain(victim.id)
            if res.get("moved"):
                break
            victim_h.result(180)  # finished first — retry
            victim = None
        if victim is None:
            problems.append(
                "drain never moved a mid-generation request")
            victim_h.result(180)
            return {"problems": problems + _resolved([warm, victim_h])}
        deadline = time.monotonic() + 15.0
        while victim.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        if victim.inflight > 0:
            problems.append(f"{victim.id} still busy after drain {res}")
        if res.get("failed"):
            problems.append(f"drain failed to move requests: {res}")
        # the victim must be clean BEFORE retirement (its engine closes
        # on remove, taking its allocator with it)
        runner = getattr(getattr(victim, "sm", None), "runner", None)
        if runner is not None:
            conserve_deadline = time.monotonic() + 10.0
            vp = _blocks_conserved(runner)
            while vp and time.monotonic() < conserve_deadline:
                time.sleep(0.1)
                vp = _blocks_conserved(runner)
            problems += [f"victim {victim.id}: {p}" for p in vp]
        if not fm.pool.remove(victim.id):
            problems.append(f"pool.remove({victim.id}) found nothing")
        victim_h.result(180)
        problems += _resolved([warm, victim_h])
        if victim_h.finish_reason not in ("stop", "length"):
            problems.append(
                f"drained request finished {victim_h.finish_reason!r} — "
                f"a scale-in lost a request")
        healthy = [r.id for r in fm.pool.healthy("decode")]
        if victim.id in healthy or len(healthy) != 1:
            problems.append(f"pool after scale-in: {healthy}")
        problems += _fleet_blocks_conserved(fm)
        return {"problems": problems, "drain": res,
                "victim": victim.id, "survivors": healthy,
                "migrations": fm.scheduler.migrations}
    finally:
        fm.close()


def scenario_hot_swap_mid_traffic() -> dict:
    """Hot weight swap under live load: fresh replicas boot, the router
    shifts, the old generation drains — every in-flight request
    completes (no errors = the HTTP tier would have sent no 5xx) and the
    new generation conserves its blocks."""
    fm = _build_fleet("chaos-swap")
    try:
        warm = fm.scheduler.submit(_req("swap warmup", max_new_tokens=6))
        warm.result(180)
        old_ids = {r.id for r in fm.pool.healthy("decode")}
        handles = [fm.scheduler.submit(
            _req(f"ride out the swap {i}", max_new_tokens=32))
            for i in range(4)]
        swap = fm.swap(timeout=30.0)
        for h in handles:
            h.result(300)
        problems = _resolved([warm] + handles)
        errored = [h.id for h in handles
                   if h.finish_reason not in ("stop", "length")]
        if errored:
            problems.append(
                f"requests {errored} errored across the swap")
        if not swap.get("ok"):
            problems.append(f"hot swap failed: {swap}")
        healthy = {r.id for r in fm.pool.healthy("decode")}
        if healthy & old_ids:
            problems.append(f"old replicas {healthy & old_ids} survived "
                            f"the swap")
        if len(healthy) != len(old_ids):
            problems.append(
                f"swap changed capacity: {old_ids} → {healthy}")
        problems += _fleet_blocks_conserved(fm)
        return {"problems": problems, "swap": swap,
                "old": sorted(old_ids), "new": sorted(healthy)}
    finally:
        fm.close()


SCENARIOS = {
    "nan_poison": scenario_nan_poison,
    "engine_rebuild": scenario_engine_rebuild,
    "dispatch_raise": scenario_dispatch_raise,
    "compile_fail": scenario_compile_fail,
    "pool_exhaustion": scenario_pool_exhaustion,
    "spec_divergence": scenario_spec_divergence,
    "fleet_failover": scenario_fleet_failover,
    "live_migration": scenario_live_migration,
    "sibling_fetch_donor_death": scenario_sibling_fetch_donor_death,
    "respawn_backoff": scenario_respawn_backoff,
    "shed_recover": scenario_shed_recover,
    "network_partition": scenario_network_partition,
    "slow_link": scenario_slow_link,
    "flapping_peer": scenario_flapping_peer,
    "registry_join": scenario_registry_join,
    "scale_out_under_spike": scenario_scale_out_under_spike,
    "scale_in_zero_lost": scenario_scale_in_zero_lost,
    "hot_swap_mid_traffic": scenario_hot_swap_mid_traffic,
}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="chaos_report.json")
    parser.add_argument("--only", default="",
                        help="comma-separated scenario subset")
    args = parser.parse_args(argv)

    # every chaos engine also runs the per-drain block-leak sweep
    # (Scheduler reads the flag at construction) — a leak under fault
    # load shows up as localai_kv_invariant_violations_total, not just
    # at the end-of-scenario audit
    import os

    os.environ.setdefault("LOCALAI_KV_CHECK", "1")

    from localai_tpu import faults
    from localai_tpu.obs.metrics import REGISTRY

    names = [n for n in args.only.split(",") if n] or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenarios: {unknown}; have {sorted(SCENARIOS)}")
        return 2
    report = {"scenarios": {}, "ok": True}
    for name in names:
        t0 = time.monotonic()
        print(f"=== chaos scenario: {name}")
        try:
            result = SCENARIOS[name]()
        except Exception as e:  # noqa: BLE001 — a crash IS a failure
            import traceback

            traceback.print_exc()
            result = {"problems": [f"scenario crashed: {e}"]}
        finally:
            faults.clear()  # a failed scenario must not arm the next
        result["seconds"] = round(time.monotonic() - t0, 2)
        result["ok"] = not result["problems"]
        report["scenarios"][name] = result
        report["ok"] = report["ok"] and result["ok"]
        status = "ok" if result["ok"] else "FAIL"
        print(f"    {status} in {result['seconds']}s"
              + (f": {result['problems']}" if result["problems"] else ""))
    # the fault receipts: every armed schedule above must have fired
    # through the real injection sites and landed in the counter family
    exposition = REGISTRY.render()
    if "localai_faults_injected_total{" not in exposition:
        report["ok"] = False
        report["scenarios"].setdefault("_exposition", {})[
            "problems"] = ["localai_faults_injected_total never rendered"]
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    n_ok = sum(1 for r in report["scenarios"].values() if r.get("ok"))
    print(f"{'OK' if report['ok'] else 'FAIL'}: {n_ok}/{len(names)} "
          f"scenarios green; report → {args.out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
