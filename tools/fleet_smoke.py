"""CI cross-host fleet smoke: a real 2-process fleet on 127.0.0.1 ports.

Where tests/test_fleet.py proves the pieces and tools/chaos_smoke.py the
recovery invariants, this smoke proves the WHOLE cross-host shape end to
end with real process and network boundaries:

  1. spawn two worker *processes* (``python -m localai_tpu.worker.server``
     each on its own 127.0.0.1 port — separate interpreters, real gRPC
     over a real socket: the cross-host topology on loopback);
  2. adopt worker #1 statically (the ``LOCALAI_FLEET_HOSTS`` path) and
     worker #2 dynamically mid-traffic (the ``POST /federated/register``
     adoption path, ``FleetServingModel.adopt_remote``);
  3. run mixed traffic — short least-loaded prompts and a shared-prefix
     affinity family — across both;
  4. inject ONE network partition against a victim peer (``fleet.dial`` +
     ``fleet.transport`` faults): every in-flight and subsequent request
     must still complete (route-around, zero lost), the victim must be
     EVICTED (never respawned — we do not own its process);
  5. heal the partition: the backed-off redial must rejoin the victim
     and reset its hold;
  6. assert the new ``localai_fleet_*`` eviction/redial series actually
     rendered: adoptions, evictions, redials, redial-backoff gauge.

Usage:  python -m tools.fleet_smoke [--out fleet_smoke.json]
Exit code 0 = every gate passed.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="fleet_smoke.json")
    args = parser.parse_args(argv)

    from localai_tpu import faults
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.obs.metrics import REGISTRY
    from localai_tpu.worker.process import WorkerProcess

    problems: list[str] = []
    report: dict = {"problems": problems}

    mcfg = ModelConfig.model_validate({
        "name": "fsmoke", "model": "debug:tiny", "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 8},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16},
    })
    app = AppConfig()

    # -- 1. two real worker processes on loopback ports -------------------
    print("fleet_smoke: spawning 2 worker processes")
    wps = [WorkerProcess(f"fsmoke-host{i}",
                         env={"JAX_PLATFORMS": "cpu"}) for i in range(2)]
    fm = None
    try:
        for wp in wps:
            wp.start()
        addrs = [f"127.0.0.1:{wp.port}" for wp in wps]
        report["hosts"] = addrs

        # -- 2a. static adoption (the LOCALAI_FLEET_HOSTS path) -----------
        fm = FleetServingModel(mcfg, app, lambda rid, role: None,
                               replicas=0, remote_hosts=addrs[:1],
                               disagg_threshold=1 << 30)
        fm.pool.redial_backoff_base = 0.2
        fm.pool.redial_backoff_cap = 1.0

        def gen(text: str, n: int = 5):
            h = fm.scheduler.submit(GenRequest(
                prompt=fm.tokenizer.encode(text), max_new_tokens=n,
                temperature=0.0))
            h.result(timeout=180)
            return h

        def run_mix(tag: str, count: int = 6) -> list:
            handles = []
            for i in range(count):
                if i % 2 == 0:  # affinity family: one shared block prefix
                    text = ("shared affinity prefix for the smoke run "
                            f"padded out to a full block {tag} {i}")
                else:           # short prompt: least-loaded placement
                    text = f"[{tag}{i}]"
                handles.append(gen(text))
            return handles

        # -- 2b. dynamic adoption mid-traffic (register path) -------------
        print("fleet_smoke: adopting second host mid-traffic")
        first = run_mix("warm", 4)
        verdict = fm.adopt_remote(addrs[1])
        report["join"] = verdict
        if not verdict["adopted"] or verdict["state"] != "healthy":
            problems.append(f"dynamic adoption failed: {verdict}")
        second = run_mix("joined", 6)

        # -- 3/4. one injected partition under traffic --------------------
        victim = fm.pool.get(verdict["id"]) or fm.pool.replicas[0]
        print(f"fleet_smoke: partitioning {victim.id}")
        faults.arm(faults.FaultSpec(site="fleet.transport", mode="raise",
                                    match=victim.id, times=0))
        faults.arm(faults.FaultSpec(site="fleet.dial", mode="raise",
                                    match=victim.id, times=0))
        partitioned = run_mix("partitioned", 6)
        lost = [h.id for h in first + second + partitioned
                if h.finish_reason not in ("stop", "length")]
        if lost:
            problems.append(f"requests lost: {lost}")
        deadline = time.monotonic() + 30
        while victim.state != "evicted" and time.monotonic() < deadline:
            fm.pool.poll_once()
            time.sleep(0.05)
        if victim.state != "evicted":
            problems.append(
                f"victim is {victim.state!r}, not evicted")
        if fm.pool.evictions < 1:
            problems.append("no eviction recorded")

        # -- 5. heal → backed-off redial rejoins --------------------------
        print("fleet_smoke: healing the partition")
        faults.clear()
        deadline = time.monotonic() + 60
        while victim.state != "healthy" and time.monotonic() < deadline:
            fm.pool.poll_once()
            time.sleep(0.05)
        if victim.state != "healthy":
            problems.append(f"victim never redialed back in "
                            f"(state {victim.state})")
        if fm.pool.redials < 1:
            problems.append("no redial recorded")
        if fm.pool.redial_backoff_s.get(victim.id):
            problems.append("redial backoff did not reset on rejoin")
        final = gen("after the partition healed")
        if final.finish_reason not in ("stop", "length"):
            problems.append(
                f"post-heal request finished {final.finish_reason!r}")

        # -- 6. the series must be OBSERVABLE, not just incremented -------
        fm.scheduler.export_gauges()
        expo = REGISTRY.render()
        for series in ("localai_fleet_adoptions_total",
                       "localai_fleet_evictions_total",
                       "localai_fleet_redials_total",
                       "localai_fleet_redial_backoff_s",
                       "localai_fleet_routed_total"):
            if series not in expo:
                problems.append(f"{series} missing from the exposition")
        report["counters"] = {
            "adoptions": fm.pool.adoptions,
            "evictions": fm.pool.evictions,
            "redials": fm.pool.redials,
            "failovers": fm.scheduler.failovers,
            "routed": dict(fm.router.routed),
        }
    except Exception as e:  # noqa: BLE001 — a crash IS a failure
        import traceback

        traceback.print_exc()
        problems.append(f"smoke crashed: {e}")
    finally:
        faults.clear()
        if fm is not None:
            fm.close()
        for wp in wps:
            try:
                wp.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                pass

    report["ok"] = not problems
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    print(f"{'OK' if report['ok'] else 'FAIL'}: cross-host fleet smoke"
          + (f" — {problems}" if problems else "")
          + f"; report → {args.out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
