"""Minimal mixed-workload load generator (ROADMAP item 5 names it).

One reusable traffic source for the perf gate, the chaos harness, and the
telemetry smoke: a configurable **kind mix** (chat / embeddings /
background-batch), a **tenant mix** (weighted — the seed of per-tenant
QoS testing), and a Poisson **arrival process** (seeded, so a CI run is
reproducible).  The generator is sink-agnostic: it drives whatever
surface the caller adapts — an in-process ServingModel, a fleet facade,
or an HTTP client — through three optional callables:

  * ``sink.chat(text, *, tenant, trace_id, background=False)`` →
    handle with ``result(timeout)`` + ``finish_reason`` (``background``
    marks batch-lane traffic: PRIORITY_BATCH on an engine sink);
  * ``sink.embedding(text, *, tenant)`` → vector (called inline on a
    worker thread);

Kinds the sink does not provide drop out of the mix (a fleet facade has
no ``embed`` — its mix renormalizes over chat+batch instead of failing).

Used by ``tools/telemetry_smoke.py`` as the fleet traffic source (the
stitched traces and the merged fleet flight view need realistic
*concurrent* load, not one sequential request per assertion) and runnable
standalone against the in-process debug model:

    python -m tools.loadgen --total 64 --rate 16 --seed 7
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import random
import sys
import threading
import time
from typing import Any, Optional

PROMPTS = (
    "summarize the maintenance runbook",
    "write a haiku about block tables",
    "what changed in the last deploy",
    "translate 'hello fleet' to french",
    "explain paged attention in one line",
    "draft a status update for the oncall",
)

# prefix-heavy profile: a few long shared "system prompt" heads with tiny
# unique tails — every request in a family shares its first several
# KV blocks, which is what exercises the whole fleet KV economy (router
# affinity + prefix directory hits, block-level sharing, HBM→host spills
# of cold families and their reloads). Each head is long enough to span
# multiple 16-token blocks on the byte tokenizer.
PREFIX_PROMPTS = (
    "You are the on-call assistant for the fleet serving tier. Answer "
    "tersely, cite runbook sections when relevant, and never invent "
    "replica names. Operator question follows:",
    "System: translate the user's message to French, preserving any "
    "inline code spans and replica identifiers verbatim. Do not add "
    "commentary or notes of any kind. User message:",
    "Context: the paged KV allocator shares whole blocks between "
    "requests with identical token prefixes; cold cached blocks spill "
    "to host RAM and reload on a hit. Explain for the question:",
    "Instructions: produce a one-line status update for the deploy "
    "channel based on the report below, leading with the headline "
    "metric and ending with the owning team. Report:",
)

DEFAULT_MIX = {"chat": 0.6, "embeddings": 0.2, "batch": 0.2}


def _latency_summary(vals: list[float]) -> Optional[dict]:
    """p50/p95/count over client-observed latencies (ms). None when no
    request yielded a usable timestamp pair — the summary key stays
    present so consumers need no existence check, only a None check."""
    if not vals:
        return None
    xs = sorted(vals)

    def pct(p: float) -> float:
        return xs[min(len(xs) - 1, int(p * len(xs)))]

    return {"p50": round(pct(0.50), 3), "p95": round(pct(0.95), 3),
            "count": len(xs)}


@dataclasses.dataclass
class Tenant:
    """One traffic source: requests carry its name (the correlation /
    trace prefix) and arrive in proportion to its weight."""

    name: str
    weight: float = 1.0


def parse_tenants(spec: str) -> list[Tenant]:
    """``"free:3,pro:1"`` → [Tenant(free, 3), Tenant(pro, 1)]."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        out.append(Tenant(name, float(w) if w else 1.0))
    return out or [Tenant("default")]


class LoadGen:
    def __init__(self, *, mix: Optional[dict[str, float]] = None,
                 tenants: Optional[list[Tenant]] = None,
                 rate: float = 8.0, seed: int = 0,
                 max_tokens: int = 8, profile: str = "mixed",
                 spike_start_s: float = 2.0, spike_len_s: float = 4.0,
                 spike_mult: float = 8.0):
        self.mix = {k: float(v) for k, v in (mix or DEFAULT_MIX).items()
                    if float(v) > 0}
        self.tenants = list(tenants or [Tenant("default")])
        self.rate = max(0.1, rate)        # mean arrivals per second
        self.rng = random.Random(seed)
        self.max_tokens = max_tokens
        if profile not in ("mixed", "prefix_heavy", "spike"):
            raise ValueError(f"unknown load profile {profile!r}")
        self.profile = profile
        # spike profile: Poisson baseline at ``rate``, multiplied by
        # ``spike_mult`` inside the [start, start+len) wall-clock window —
        # the deterministic burst the autoscale smoke/chaos scenarios
        # drive scale-out with (seeded, so CI sees the same arrivals)
        self.spike_start_s = max(0.0, spike_start_s)
        self.spike_len_s = max(0.0, spike_len_s)
        self.spike_mult = max(1.0, spike_mult)

    def _prompt(self, tenant: Tenant, i: int) -> str:
        if self.profile == "prefix_heavy":
            # long shared head + tiny unique tail: block-aligned prefix
            # reuse across the family, distinct completions per request
            return (self.rng.choice(PREFIX_PROMPTS)
                    + f" [{tenant.name}/{i}]")
        return self.rng.choice(PROMPTS) + f" [{tenant.name}/{i}]"

    def _pick(self, weighted: list[tuple[Any, float]]) -> Any:
        total = sum(w for _, w in weighted)
        x = self.rng.random() * total
        for item, w in weighted:
            x -= w
            if x <= 0:
                return item
        return weighted[-1][0]

    def run(self, sink: Any, *, total: int = 32,
            timeout_s: float = 300.0) -> dict:
        """Issue ``total`` requests with Poisson gaps at ``rate``/s and
        wait for every one. Returns the per-kind/per-tenant/outcome
        summary. Never raises on a failed request — failures are counted
        (the chaos harness injects them on purpose)."""
        kinds = [(k, w) for k, w in self.mix.items()
                 if k == "embeddings" and getattr(sink, "embedding", None)
                 or k in ("chat", "batch") and getattr(sink, "chat", None)]
        if not kinds:
            raise ValueError("sink provides neither chat nor embedding")
        tenants = [(t, t.weight) for t in self.tenants]
        counts: dict[str, int] = {}
        by_tenant: dict[str, int] = {}
        outcomes: dict[str, int] = {}
        handles: list[tuple[Any, str]] = []
        threads: list[threading.Thread] = []
        errors: list[str] = []
        trace_ids: list[str] = []
        t0 = time.monotonic()
        for i in range(total):
            kind = self._pick(kinds)
            tenant = self._pick(tenants)
            counts[kind] = counts.get(kind, 0) + 1
            by_tenant[tenant.name] = by_tenant.get(tenant.name, 0) + 1
            text = self._prompt(tenant, i)
            trace_id = f"loadgen-{tenant.name}-{i}"
            if kind == "embeddings":
                def embed(text=text, tenant=tenant):
                    try:
                        sink.embedding(text, tenant=tenant.name)
                    except Exception as e:  # noqa: BLE001 — counted below
                        errors.append(f"embedding: {e}")

                th = threading.Thread(target=embed, daemon=True)
                th.start()
                threads.append(th)
            else:
                try:
                    h = sink.chat(text, tenant=tenant.name,
                                  trace_id=trace_id,
                                  background=(kind == "batch"))
                    handles.append((h, kind))
                    trace_ids.append(trace_id)
                except Exception as e:  # noqa: BLE001 — counted below
                    errors.append(f"{kind}: {e}")
            rate = self.rate
            if self.profile == "spike":
                elapsed = time.monotonic() - t0
                if (self.spike_start_s <= elapsed
                        < self.spike_start_s + self.spike_len_s):
                    rate *= self.spike_mult
            time.sleep(self.rng.expovariate(rate))
        deadline = time.monotonic() + timeout_s
        client_ttft: list[float] = []
        client_e2e: list[float] = []
        for h, kind in handles:
            try:
                h.result(timeout=max(1.0, deadline - time.monotonic()))
                reason = h.finish_reason or "none"
                # client-observed latency: the handle's own submit/first-
                # token/done stamps (GenHandle and _HttpChatHandle both
                # carry them) — what the CALLER waited, queueing included,
                # which the server-side histogram cannot see on its own
                ts = getattr(h, "t_submit", None)
                tf = getattr(h, "t_first_token", None)
                td = getattr(h, "t_done", None)
                if ts is not None and td is not None and td >= ts:
                    client_e2e.append((td - ts) * 1e3)
                if ts is not None and tf is not None and tf >= ts:
                    client_ttft.append((tf - ts) * 1e3)
            except Exception as e:  # noqa: BLE001 — failures are COUNTED,
                # never raised: the chaos harness injects them on purpose
                errors.append(f"{kind}: {e}")
                reason = "exception"
            outcomes[reason] = outcomes.get(reason, 0) + 1
        for th in threads:
            th.join(timeout=max(1.0, deadline - time.monotonic()))
        return {
            "total": total,
            "wall_s": round(time.monotonic() - t0, 2),
            "kinds": counts,
            "tenants": by_tenant,
            "outcomes": outcomes,
            "errors": errors,
            "trace_ids": trace_ids,
            "client_ttft_ms": _latency_summary(client_ttft),
            "client_e2e_ms": _latency_summary(client_e2e),
        }


class EngineSink:
    """Adapter over any scheduler-shaped facade (in-process ServingModel,
    WorkerServingModel, FleetServingModel): chat submits GenRequests
    (batch kind at PRIORITY_BATCH), embeddings go through the runner when
    it has one."""

    def __init__(self, sm: Any, *, max_tokens: int = 8):
        self.sm = sm
        self.max_tokens = max_tokens
        if getattr(getattr(sm, "runner", None), "embed", None) is None:
            self.embedding = None  # fleet/worker facades: chat+batch only

    def chat(self, text: str, *, tenant: str = "default",
             trace_id: str = "", background: bool = False):
        from localai_tpu.engine.scheduler import PRIORITY_BATCH, GenRequest
        from localai_tpu.obs.ledger import derive_tenant

        return self.sm.scheduler.submit(GenRequest(
            prompt=self.sm.tokenizer.encode(text),
            max_new_tokens=self.max_tokens, temperature=0.0,
            trace_id=trace_id, correlation_id=f"{tenant}:{trace_id}",
            priority=PRIORITY_BATCH if background else 0,
            # the tenant stamp the auth middleware would apply: hashed
            # bucket, never the raw name — the usage smoke asserts the
            # per-tenant shares land under these buckets
            tenant=derive_tenant(tenant),
        ))

    def embedding(self, text: str, *, tenant: str = "default"):
        return self.sm.runner.embed(self.sm.tokenizer.encode(text))


class _HttpChatHandle:
    """Handle-shaped view of one in-flight HTTP chat POST: a worker
    thread owns the request; ``result()`` joins it (the GenHandle
    surface LoadGen expects)."""

    def __init__(self):
        self.finish_reason: Optional[str] = None
        # client-observed stamps matching the GenHandle surface. The chat
        # endpoint is non-streaming, so the first byte the client sees IS
        # the full body: t_first_token == t_done by construction (an honest
        # upper bound on TTFT, noted in the README anatomy runbook).
        self.t_submit: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        self._text = ""
        self._error: Optional[str] = None
        self._done = threading.Event()

    def result(self, timeout: Optional[float] = None) -> str:
        if not self._done.wait(timeout):
            raise TimeoutError("HTTP chat request did not complete")
        if self._error is not None:
            raise RuntimeError(self._error)
        return self._text


class HttpSink:
    """LoadGen sink over a LIVE HTTP API: each ``chat()`` POSTs
    ``/v1/chat/completions`` from its own worker thread (the arrival
    process never blocks on a response), returning a handle whose
    ``result()`` joins the POST. No embedding surface — the kind mix
    renormalizes to chat+batch, and ``background`` traffic shares the
    endpoint (HTTP carries no lane flag; lane QoS belongs to the engine
    sink). Used by ``telemetry_smoke --loopsan`` so the event-loop
    sanitizer sees real aiohttp handler dispatch, not in-process
    scheduler calls."""

    def __init__(self, base_url: str, model: str, *,
                 max_tokens: int = 8, timeout: float = 120.0,
                 api_key: str = ""):
        import httpx

        headers = {"Authorization": f"Bearer {api_key}"} if api_key else None
        self._client = httpx.Client(base_url=base_url, timeout=timeout,
                                    headers=headers)
        self.model = model
        self.max_tokens = max_tokens

    def chat(self, text: str, *, tenant: str = "default",
             trace_id: str = "", background: bool = False):
        h = _HttpChatHandle()
        h.t_submit = time.monotonic()

        def post():
            try:
                r = self._client.post("/v1/chat/completions", json={
                    "model": self.model, "max_tokens": self.max_tokens,
                    "temperature": 0.0,
                    "messages": [{"role": "user", "content": text}],
                })
                r.raise_for_status()
                choice = r.json()["choices"][0]
                h.finish_reason = choice.get("finish_reason")
                h._text = choice["message"].get("content") or ""
            except Exception as e:  # noqa: BLE001 — surfaced via result()
                h._error = f"{tenant}/{trace_id}: {e}"
                h.finish_reason = "exception"
            finally:
                h.t_done = time.monotonic()
                if h._error is None:
                    h.t_first_token = h.t_done  # non-streaming: first
                    # byte == full body
                h._done.set()

        threading.Thread(target=post, daemon=True,
                         name=f"loadgen-http-{trace_id}").start()
        return h

    def close(self) -> None:
        self._client.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--total", type=int, default=32)
    parser.add_argument("--rate", type=float, default=8.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--max-tokens", type=int, default=8)
    parser.add_argument("--tenants", default="default:1",
                        help='weighted tenant mix, e.g. "free:3,pro:1"')
    parser.add_argument("--mix", default="",
                        help='kind mix, e.g. "chat:0.5,embeddings:0.3,'
                             'batch:0.2" (default 0.6/0.2/0.2)')
    parser.add_argument("--profile", default="mixed",
                        choices=("mixed", "prefix_heavy", "spike"),
                        help="prompt/arrival profile: mixed short "
                             "prompts; prefix_heavy (long shared heads + "
                             "unique tails — drives prefix sharing, the "
                             "fleet directory, and KV tier spill/reload); "
                             "spike (mixed prompts, Poisson baseline with "
                             "a burst window — drives the autoscaler)")
    parser.add_argument("--spike-start-s", type=float, default=2.0,
                        help="spike profile: burst window start (s)")
    parser.add_argument("--spike-len-s", type=float, default=4.0,
                        help="spike profile: burst window length (s)")
    parser.add_argument("--spike-mult", type=float, default=8.0,
                        help="spike profile: arrival-rate multiplier "
                             "inside the burst window")
    args = parser.parse_args(argv)

    mix = None
    if args.mix:
        mix = {}
        for part in args.mix.split(","):
            k, _, w = part.strip().partition(":")
            mix[k] = float(w or 1.0)

    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.models.manager import build_serving_model

    mcfg = ModelConfig.model_validate({
        "name": "loadgen", "model": "debug:tiny", "context_size": 256,
        "engine": {"max_slots": 4, "prefill_buckets": [16, 32, 64],
                   "dtype": "float32", "kv_dtype": "float32"},
    })
    sm = build_serving_model(mcfg, AppConfig())
    try:
        gen = LoadGen(mix=mix, tenants=parse_tenants(args.tenants),
                      rate=args.rate, seed=args.seed,
                      max_tokens=args.max_tokens, profile=args.profile,
                      spike_start_s=args.spike_start_s,
                      spike_len_s=args.spike_len_s,
                      spike_mult=args.spike_mult)
        summary = gen.run(EngineSink(sm, max_tokens=args.max_tokens),
                          total=args.total)
    finally:
        sm.scheduler.shutdown()
    print(json.dumps(summary, indent=2, sort_keys=True))
    bad = [r for r in summary["outcomes"]
           if r not in ("stop", "length")] or summary["errors"]
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
