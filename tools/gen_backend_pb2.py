"""Regenerate worker/backend_pb2.py without protoc/grpc_tools.

The image ships protobuf but no protoc, so backend_pb2.py cannot be
regenerated the usual way. This script edits the schema at the
FileDescriptorProto level instead: it loads the serialized descriptor
embedded in the CURRENT backend_pb2.py, applies the declarative additions
below (new messages / new service methods — keep them in sync with
backend.proto, which stays the human-readable source of truth), and
rewrites backend_pb2.py around the new serialized blob.

Usage:  python tools/gen_backend_pb2.py          # rewrite in place
        python tools/gen_backend_pb2.py --check  # verify blob is current
"""

from __future__ import annotations

import sys
from pathlib import Path

from google.protobuf import descriptor_pb2

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

OUT = REPO / "localai_tpu" / "worker" / "backend_pb2.py"

F = descriptor_pb2.FieldDescriptorProto

# message name -> [(field name, number, type, label), ...]
MESSAGES = {
    "PrefixChunk": [
        ("transfer_id", 1, F.TYPE_STRING, F.LABEL_OPTIONAL),
        ("seq", 2, F.TYPE_INT32, F.LABEL_OPTIONAL),
        ("data", 3, F.TYPE_BYTES, F.LABEL_OPTIONAL),
        ("last", 4, F.TYPE_BOOL, F.LABEL_OPTIONAL),
        ("tokens", 5, F.TYPE_INT32, F.LABEL_REPEATED),
        ("n_tokens", 6, F.TYPE_INT32, F.LABEL_OPTIONAL),
    ],
    "TelemetryRequest": [
        ("trace_id", 1, F.TYPE_STRING, F.LABEL_OPTIONAL),
        ("since", 2, F.TYPE_DOUBLE, F.LABEL_OPTIONAL),
        ("limit", 3, F.TYPE_INT32, F.LABEL_OPTIONAL),
        ("recent", 4, F.TYPE_INT32, F.LABEL_OPTIONAL),
    ],
    "TelemetryResponse": [
        ("json", 1, F.TYPE_STRING, F.LABEL_OPTIONAL),
    ],
}

# method name -> (input type, output type, client_streaming, server_streaming)
METHODS = {
    "PrefillPrefix": ("PredictOptions", "PrefixChunk", False, True),
    "TransferPrefix": ("PrefixChunk", "Result", True, False),
    "GetTelemetry": ("TelemetryRequest", "TelemetryResponse", False, False),
}

TEMPLATE = '''# -*- coding: utf-8 -*-
# Generated protocol buffer code (tools/gen_backend_pb2.py — the image has
# no protoc; the descriptor blob is edited at the FileDescriptorProto
# level from backend.proto's declarative twin in that script). DO NOT EDIT.
# source: backend.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'backend_pb2', globals())
# @@protoc_insertion_point(module_scope)
'''


def build_file_proto() -> descriptor_pb2.FileDescriptorProto:
    """Current embedded descriptor + the declarative additions above
    (idempotent: re-running against an already-updated blob is a no-op)."""
    from localai_tpu.worker import backend_pb2

    fd = descriptor_pb2.FileDescriptorProto()
    fd.MergeFromString(backend_pb2.DESCRIPTOR.serialized_pb)

    have_msgs = {m.name for m in fd.message_type}
    for name, fields in MESSAGES.items():
        if name in have_msgs:
            continue
        msg = fd.message_type.add()
        msg.name = name
        for fname, number, ftype, label in fields:
            f = msg.field.add()
            f.name = fname
            f.number = number
            f.type = ftype
            f.label = label

    svc = next(s for s in fd.service if s.name == "Backend")
    have_methods = {m.name for m in svc.method}
    for name, (inp, out, cstream, sstream) in METHODS.items():
        if name in have_methods:
            continue
        m = svc.method.add()
        m.name = name
        m.input_type = f".{fd.package}.{inp}"
        m.output_type = f".{fd.package}.{out}"
        m.client_streaming = cstream
        m.server_streaming = sstream
    return fd


def main() -> int:
    fd = build_file_proto()
    blob = fd.SerializeToString()
    text = TEMPLATE.format(blob=blob)
    if "--check" in sys.argv:
        if OUT.read_text() != text:
            print("backend_pb2.py is stale; run tools/gen_backend_pb2.py")
            return 1
        print("backend_pb2.py is current")
        return 0
    OUT.write_text(text)
    print(f"wrote {OUT} ({len(blob)} descriptor bytes)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
