"""CI telemetry smoke: prove the obs subsystem observes a real generation.

Boots the tiny debug model in-process (no downloads, no HTTP), runs a few
generations through the continuous-batching scheduler, then:

  1. asserts the engine series appear in the /metrics exposition
     (batch occupancy, KV utilization, TTFT/TPOT/queue-wait histograms,
     compile time) — a regression here means the subsystem went blind;
  2. asserts the round-6 introspection surfaces: the device liveness probe
     + HBM census render their gauges, and a SIMULATED stall (a blocking
     callable under a short-deadline watchdog) trips ``engine_stalled``,
     records a thread-stack forensic span, and clears on recovery;
  3. asserts the round-7 SLO observatory + flight recorder: the synthetic
     load leaves a non-empty flight ring with computable step-time
     percentiles, the SLO burn-rate/shedding gauges render, and a
     simulated overload (tight targets against a scratch tracker) trips
     shedding, counts a shed request, then recovers as the fast window
     slides past the burst;
  4. asserts the round-8 offline batch subsystem end-to-end: a 5-line
     JSONL job submitted through the FileRegistry + BatchStore runs to
     terminal ``completed`` through the scheduler's BACKGROUND lane
     (every line at ``PRIORITY_BATCH``), the ``localai_batch_jobs`` /
     ``localai_batch_lines_total`` / ``localai_batch_lane_paused``
     series render, and the per-line result file is written
     (``--batch-out`` — CI uploads it as a build artifact);
  5. asserts the round-10 fleet router end-to-end: a 2-replica (+1
     prefill) in-process fleet of the tiny model serves mixed traffic
     through the affinity router, one long prompt takes the
     disaggregated prefill→TransferPrefix→decode path, and the
     ``localai_fleet_*`` replica/routing/transfer series render;
  6. writes a TTFT/TPOT summary JSON (``--out``) that CI uploads as a
     build artifact — the seed of the serving-latency bench trajectory
     (BENCH_*.json tracks throughput; this tracks latency per PR) — and
     the flight-ring snapshot (``--flight-out``) so every CI run carries
     the engine timeline it measured.

  7. asserts the round-15 fleet telemetry plane end-to-end: a 2-replica
     WORKER-PROCESS fleet serves a mixed tenant workload from
     ``tools.loadgen``, one request's trace renders as ONE stitched
     waterfall (front-door spans untagged, worker-side engine spans
     harvested over the GetTelemetry RPC, skew-anchored and
     ``replica=``-tagged), the merged fleet flight view
     (``--fleet-flight-out``, a CI artifact) carries ≥2 replicas' rings
     with a ``replica`` column, and an injected ``engine.drain`` stall
     auto-captures a jax.profiler trace into the profile manifest
     (``--profile-dir``) with its triggering trace id — while a second
     stall inside the cooldown does NOT capture;

  9. under ``--loopsan``, boots the REAL aiohttp API tier over a
     2-replica in-process fleet of the tiny model and runs it under
     ``tools.loopsan``'s event-loop stall sanitizer: first a deliberate
     ``time.sleep(0.2)`` injected onto the loop must be caught (the
     sanitizer's own self-check — a detector that can't see a 200 ms
     stall proves nothing), then mixed ``tools.loadgen`` HTTP traffic
     plus one live SSE stream must complete with ZERO callbacks holding
     the loop ≥ 50 ms — the runtime proof that the API layer's executor
     offloads (the static loopcheck contract) actually hold under load.
     The stall report lands in ``--loopsan-out`` (a CI artifact);

  8. asserts the round-18 usage accounting plane end-to-end: a 2-replica
     worker-process fleet serves a 3:1 weighted tenant mix, the ledger
     attributes every request to the right HASHED tenant bucket (raw
     names never reach a label), each worker's delivered + flight-class
     waste tokens reconcile against its own flight ring, the history
     store survives a disk snapshot round trip, and the
     ``/v1/usage``-shaped payload lands in ``--usage-out`` (a CI
     artifact);

 11. asserts the round-19 dispatch anatomy: extra ``tools.loadgen``
     traffic through the smoke engine leaves every flight-ring record
     with gap/sched/launch/sync phases summing within its
     ``dispatch_ms`` (the interval-tiling invariant), the derived
     host-overhead fraction in (0, 1), the
     ``localai_dispatch_phase_ms`` / ``localai_host_overhead_fraction``
     / ``localai_device_bubble_fraction`` series rendering, and the
     client-observed TTFT p95 agreeing with the server-side histogram;
     the breakdown lands in ``--anatomy-out`` (a CI artifact);

 12. asserts the round-20 elastic capacity loop: a 1-replica autoscaled
     fleet scales OUT under a seeded loadgen spike (queue-depth signal),
     hot-swaps its replicas mid-life with zero failed requests, scales
     to ZERO after the traffic quiesces, and cold-re-onboards a replica
     for the next request — which waits for the boot and completes; the
     capacity trajectory lands in ``--autoscale-out`` (a CI artifact);

 10. under ``--racecheck``, runs the WHOLE lifecycle above with
     ``tools.racecheck``'s instrumented locks installed (every
     ``threading.Lock``/``RLock`` the serving stack creates records its
     acquisition ordering) and fails if the observed lock-order graph
     contains a cycle — an ABBA inversion across the fleet pool/router,
     batch executor, scheduler, and obs planes is a deadlock waiting
     for load, exactly what this smoke's mixed traffic provokes.

Usage:  python -m tools.telemetry_smoke [--out telemetry_summary.json]
                                        [--flight-out flight_snapshot.json]
                                        [--batch-out batch_result.jsonl]
                                        [--usage-out usage_snapshot.json]
                                        [--racecheck]
                                        [--loopsan]
                                        [--loopsan-out loopsan_report.json]
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


REQUIRED_SERIES = (
    'localai_batch_occupancy{model="smoke"}',
    'localai_kv_slot_utilization{model="smoke"}',
    'localai_ttft_seconds_count{model="smoke"}',
    'localai_tpot_seconds_count{model="smoke"}',
    'localai_queue_wait_seconds_count{model="smoke"}',
    'localai_requests_total{',
    'localai_decode_dispatches_total{model="smoke"}',
    # the smoke engine runs the paged KV cache (the serving default), so
    # prefill compiles under the chunked-prefill program label
    'localai_xla_compile_total{program="prefill_chunk"}',
    'localai_xla_compile_seconds_total{program="decode',
    # paged block-pool gauges (round 9)
    'localai_kv_blocks_free{model="smoke"}',
    'localai_kv_blocks_used{model="smoke"}',
    'localai_prefill_chunk_queue_depth{model="smoke"}',
    'localai_prefill_chunks_total{model="smoke"}',
)
REQUIRED_FAMILIES = (
    "# TYPE localai_prompt_cache_hit_rate gauge",
    "# TYPE localai_speculative_accept_rate gauge",
    "# TYPE localai_prefix_tokens_reused_total counter",
)
# device-health + stall series the smoke provokes explicitly (probe +
# census + a simulated stall) before checking the exposition
REQUIRED_INTROSPECTION = (
    "localai_device_ok 1",
    "localai_device_probe_seconds",
    'localai_hbm_live_bytes{category="kv_cache"}',
    'localai_hbm_live_bytes{category="weights"}',
    'localai_engine_stalled{channel="smoke-stall"} 0',
    'localai_stalls_total{channel="smoke-stall"} 1',
)
# SLO observatory + flight recorder series (round 7): windowed step-time
# percentiles from the ring, burn-rate gauges from the real run, and the
# simulated-overload lifecycle (shed → counted → recovered)
REQUIRED_SLO = (
    'localai_step_time_ms{model="smoke",quantile="p50"}',
    'localai_step_time_ms{model="smoke",quantile="p99"}',
    'localai_slo_burn_rate{model="smoke",window="1m"}',
    'localai_slo_burn_rate{model="smoke",window="5m"}',
    'localai_overload_shedding{model="smoke"} 0',
    'localai_overload_shedding{model="smoke-overload"} 0',
    'localai_requests_shed_total{model="smoke-overload"} 1',
)
# offline batch subsystem series (round 8): the 5-line job the smoke
# submits through the background lane must land every line and leave the
# lane un-paused
REQUIRED_BATCH = (
    'localai_batch_jobs{state="completed"} 1',
    'localai_batch_jobs{state="failed"} 0',
    'localai_batch_lines_total{result="completed"} 5',
    "localai_batch_lane_paused 0",
)
# fleet router series (round 10): the 2-replica in-process fleet the smoke
# boots must leave every replica healthy, a routed mix, and exactly one
# disaggregated prefix transfer (one long prompt crosses the threshold)
REQUIRED_FLEET = (
    'localai_fleet_replicas{model="fleet-smoke",state="healthy"} 3',
    'localai_fleet_replicas{model="fleet-smoke",state="dead"} 0',
    'localai_fleet_routed_total{model="fleet-smoke",reason="affinity"}',
    'localai_fleet_prefix_transfers_total{model="fleet-smoke"} 1',
    'localai_fleet_prefix_transfer_bytes_total{model="fleet-smoke"}',
)
# fleet KV-economy series (round 17): the 2-replica tiered fleet must
# render directory traffic, at least one sibling prefix transfer, and a
# real HBM→host spill→reload round trip (values asserted in-code by
# check_kveconomy; the exposition check pins the series names)
REQUIRED_KVECONOMY = (
    'localai_fleet_directory_entries{model="fleet-kv"}',
    'localai_fleet_directory_hits_total{model="fleet-kv"}',
    'localai_fleet_sibling_transfers_total{model="fleet-kv"}',
    'localai_fleet_sibling_transfer_bytes_total{model="fleet-kv"}',
    'localai_kv_tier_blocks{model="fleet-kv"}',
    'localai_kv_tier_spills_total{model="fleet-kv"}',
    'localai_kv_tier_reloads_total{model="fleet-kv"}',
)
# fleet telemetry plane series (round 15): the worker-process fleet must
# come up healthy, the anomaly profiler must capture EXACTLY one stall-
# triggered profile (the cooldown eats the second), and the trace-ring
# sizing receipt must render
REQUIRED_FLEETVIEW = (
    'localai_fleet_replicas{model="fleet-grpc",state="healthy"} 2',
    'localai_profiles_captured_total{trigger="stall"} 1',
    "localai_trace_ring_size",
)
# usage accounting plane series (round 18): after check_usage exports the
# ledger, the tenant/goodput/waste families must render with HASHED
# tenant buckets only (the in-code check pins the exact t-… series and
# the absence of raw tenant names)
REQUIRED_USAGE = (
    "# TYPE localai_tenant_requests_total counter",
    "# TYPE localai_tenant_tokens_total counter",
    "# TYPE localai_tenant_kv_block_seconds_total counter",
    "# TYPE localai_tenant_lru_evictions_total counter",
    'localai_goodput_tokens_total{model="fleet-usage"}',
    'localai_goodput_ratio{model="fleet-usage"}',
)
# dispatch-anatomy series (round 19): after real traffic through the
# smoke engine, every phase column must render a windowed percentile and
# both derived fractions must be present (values asserted in-code by
# check_anatomy; the exposition check pins the series names)
REQUIRED_ANATOMY = (
    'localai_dispatch_phase_ms{model="smoke",phase="gap",quantile="p50"}',
    'localai_dispatch_phase_ms{model="smoke",phase="sched",quantile="p50"}',
    'localai_dispatch_phase_ms{model="smoke",phase="launch",quantile="p50"}',
    'localai_dispatch_phase_ms{model="smoke",phase="sync",quantile="p99"}',
    'localai_host_overhead_fraction{model="smoke"}',
    'localai_device_bubble_fraction{model="smoke"}',
)
# elastic-capacity series (round 20): the autoscaled fleet must record a
# spike-driven scale-out, the quiesce-driven scale-to-zero, the cold
# re-onboard that served the held request, and one hot weight swap
# (values asserted in-code by check_autoscale; the exposition check pins
# the series names — labels render alphabetically)
REQUIRED_AUTOSCALE = (
    'localai_autoscale_decisions_total{action="scale_out",'
    'model="fleet-auto"}',
    'localai_autoscale_decisions_total{action="scale_to_zero",'
    'model="fleet-auto"}',
    'localai_autoscale_decisions_total{action="cold_start",'
    'model="fleet-auto"}',
    'localai_autoscale_decisions_total{action="swap",model="fleet-auto"}',
    'localai_fleet_target_replicas{model="fleet-auto"}',
    'localai_model_swaps_total{model="fleet-auto"} 1',
)


def check_introspection(runner, registry, store) -> list[str]:
    """Probe the device, census its HBM, and simulate one stall →
    returns the list of failures (empty = healthy)."""
    import threading

    from localai_tpu.obs import Watchdog
    from localai_tpu.obs import device as obs_device

    problems: list[str] = []
    probe = obs_device.probe_device(timeout=60.0, registry=registry)
    if not probe.ok:
        problems.append(f"device probe failed: {probe.error}")
    obs_device.update_device_gauges([runner], registry=registry)

    wd = Watchdog(deadline=0.1, registry=registry, store=store,
                  poll_interval=0.02)
    wd.start()
    release = threading.Event()
    tripped = threading.Event()
    wd.on_stall(lambda e: e.kind == "stall" and tripped.set())

    def hung():
        with wd.guard("smoke-stall"):
            release.wait(10.0)

    t = threading.Thread(target=hung, daemon=True)
    t.start()
    if not tripped.wait(5.0):
        problems.append("simulated stall did not trip the watchdog")
    release.set()
    t.join(5.0)
    deadline = time.monotonic() + 3.0
    while wd.stalled("smoke-stall") and time.monotonic() < deadline:
        time.sleep(0.02)
    if wd.stalled("smoke-stall"):
        problems.append("stall did not clear on recovery")
    wd.stop()
    forensic = [tr for tr in store.recent(limit=20, kind="stall")
                if tr.attrs.get("channel") == "smoke-stall"]
    if not forensic:
        problems.append("no forensic stall span recorded")
    elif not any("stack" in s.attrs for s in forensic[0].spans()):
        problems.append("forensic span carries no thread stacks")
    return problems


def check_slo_overload(registry) -> list[str]:
    """Simulated overload: a scratch tracker with tight targets sheds,
    counts the refusal, then recovers once the fast window drains —
    the full load-shedding lifecycle without waiting a real minute
    (injected clock)."""
    from localai_tpu.obs.slo import SLOTracker

    problems: list[str] = []
    t = {"now": 1000.0}
    slo = SLOTracker(registry=registry, clock=lambda: t["now"],
                     targets={"ttft_ms": 0.001}, burn_threshold=1.0,
                     recover_burn=1.0, min_events=3)
    for _ in range(4):
        slo.observe("smoke-overload", ttft_ms=50.0, e2e_ms=80.0)
    if not slo.should_shed("smoke-overload"):
        problems.append("simulated overload did not trip shedding")
    if 'localai_overload_shedding{model="smoke-overload"} 1' \
            not in registry.render():
        problems.append("shedding gauge not set during overload")
    slo.shed("smoke-overload")  # what the API's 429 path records
    t["now"] += 120.0           # the fast window slides past the burst
    if slo.should_shed("smoke-overload"):
        problems.append("shedding did not recover after the window slid")
    return problems


def check_batch(sched, registry, batch_out: str) -> list[str]:
    """Submit a 5-line batch job end-to-end through the background lane:
    file upload → job create → executor drain → terminal ``completed`` →
    per-line result file copied to ``batch_out`` (the CI artifact)."""
    import json as jsonlib
    import shutil
    import tempfile
    from pathlib import Path
    from types import SimpleNamespace

    from localai_tpu.batch import BatchExecutor, BatchStore, FileRegistry
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.obs.slo import SLOTracker
    from localai_tpu.templates.cache import TemplateCache
    from localai_tpu.utils.tokenizer import ByteTokenizer

    problems: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        reg = FileRegistry(Path(tmp) / "uploads")
        store = BatchStore(reg.upload_dir, reg)
        lines = "\n".join(jsonlib.dumps({
            "custom_id": f"smoke-{i}", "method": "POST",
            "url": "/v1/chat/completions",
            "body": {"model": "smoke", "max_tokens": 8, "temperature": 0.0,
                     "messages": [{"role": "user",
                                   "content": f"batch smoke line {i}"}]},
        }) for i in range(5))
        f = reg.register_bytes("smoke_input.jsonl",
                               (lines + "\n").encode(), "batch")
        job = store.create(endpoint="/v1/chat/completions",
                           input_file_id=f["id"])
        sm = SimpleNamespace(tokenizer=ByteTokenizer(), scheduler=sched,
                             templates=TemplateCache(tmp))
        mcfg = ModelConfig(name="smoke")
        ex = BatchExecutor(
            store, lambda name: (sm, mcfg), poll_s=0.02,
            registry=registry,
            slo=SLOTracker(registry=registry, targets={}),
        )
        ex.start()
        deadline = time.monotonic() + 300
        while (store.get(job["id"])["status"]
               not in ("completed", "failed", "cancelled", "expired")
               and time.monotonic() < deadline):
            time.sleep(0.05)
        ex.stop()
        job = store.get(job["id"])
        if job["status"] != "completed":
            problems.append(
                f"batch job ended {job['status']!r}, not completed "
                f"({job['request_counts']})")
            return problems
        if job["request_counts"]["completed"] != 5:
            problems.append(
                f"batch counts wrong: {job['request_counts']}")
        out_path = reg.content_path(job["output_file_id"])
        records = [jsonlib.loads(l)
                   for l in out_path.read_text().splitlines()]
        if {r["custom_id"] for r in records} != {f"smoke-{i}"
                                                for i in range(5)}:
            problems.append("batch output file misses custom_ids")
        store.export_gauges(registry)
        shutil.copy(out_path, batch_out)
    return problems


def check_fleet(registry) -> list[str]:
    """Boot a 2-replica (+1 prefill) in-process fleet of the tiny debug
    model, run mixed traffic through the router (short prompts +
    one long prompt over the disaggregation threshold), and assert the
    routing/transfer accounting — the localai_fleet_* exposition strings
    are checked by REQUIRED_FLEET after this returns."""
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.models.manager import build_serving_model

    problems: list[str] = []
    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": "fleet-smoke", "model": "debug:tiny", "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 8},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64, 128],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16},
    })

    def factory(rid, role):
        return InProcessReplica(
            rid, role, lambda: build_serving_model(mcfg, app))

    fm = FleetServingModel(mcfg, app, factory, replicas=2,
                           prefill_replicas=1, disagg_threshold=48)
    try:
        tok = fm.tokenizer
        handles = [
            fm.scheduler.submit(GenRequest(
                prompt=tok.encode(f"fleet smoke request {i} " * (1 + i % 2)),
                max_new_tokens=6, temperature=0.0,
            ))
            for i in range(5)
        ]
        # ONE prompt over the disaggregation threshold: prefill replica →
        # TransferPrefix → decode replica
        handles.append(fm.scheduler.submit(GenRequest(
            prompt=tok.encode("fleet disaggregated long prompt " * 6),
            max_new_tokens=6, temperature=0.0,
        )))
        for h in handles:
            h.result(timeout=300)
        bad = [h.finish_reason for h in handles
               if h.finish_reason not in ("stop", "length")]
        if bad:
            problems.append(f"fleet requests finished {bad}")
        if sum(fm.router.routed.values()) != len(handles):
            problems.append(
                f"router placed {sum(fm.router.routed.values())} of "
                f"{len(handles)} requests: {fm.router.routed}")
        if fm.router.routed["affinity"] < 1:
            problems.append(
                f"no affinity placements in {fm.router.routed}")
        if fm.scheduler.prefix_transfers != 1:
            problems.append(
                f"{fm.scheduler.prefix_transfers} prefix transfers "
                f"(expected 1; {fm.scheduler.disagg_fallbacks} fallbacks)")
        if fm.scheduler.prefix_transfer_bytes <= 0:
            problems.append("prefix transfer moved 0 bytes")
        fm.scheduler.export_gauges()
    finally:
        fm.close()
    return problems


def check_kveconomy(registry) -> list[str]:
    """Round-17 fleet KV economy: a 2-replica fleet with a deliberately
    small block pool and the host-RAM tier armed (LOCALAI_KV_TIER_MB)
    serves a tools.loadgen prefix-heavy workload. Asserts the three
    planes end-to-end: the prefix directory takes routing hits, a
    replica loss forces at least one sibling TransferPrefix warm-up on
    the failover path, and prefix-pool pressure drives at least one
    HBM→host spill that a later family re-request reloads. The
    localai_fleet_directory_* / localai_fleet_sibling_* /
    localai_kv_tier_* exposition strings are checked by
    REQUIRED_KVECONOMY after this returns."""
    import os

    from localai_tpu import faults
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.fleet.router import affinity_key
    from localai_tpu.models.manager import build_serving_model
    from localai_tpu.obs.metrics import update_engine_gauges
    from tools.loadgen import PREFIX_PROMPTS, EngineSink, LoadGen, Tenant

    problems: list[str] = []
    prev_tier = os.environ.get("LOCALAI_KV_TIER_MB")
    os.environ["LOCALAI_KV_TIER_MB"] = "8"
    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": "fleet-kv", "model": "debug:tiny", "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 6},
        # 40-block prefix pool per replica: the four prefix-heavy
        # families (~12 blocks each) plus their unique tails overflow it,
        # so cold chains MUST spill to the tier instead of vanishing
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64, 128],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16, "kv_num_blocks": 40},
    })

    def factory(rid, role):
        return InProcessReplica(
            rid, role, lambda: build_serving_model(mcfg, app))

    fm = FleetServingModel(mcfg, app, factory, replicas=2,
                           prefill_replicas=0, disagg_threshold=10_000)
    tok = fm.tokenizer

    def submit(text):
        return fm.scheduler.submit(GenRequest(
            prompt=tok.encode(text), max_new_tokens=6, temperature=0.0))

    try:
        # -- directory traffic: prefix-heavy families repeat, so every
        # repeat after the first routes on a directory hit
        gen = LoadGen(mix={"chat": 1.0}, rate=50.0, max_tokens=6,
                      profile="prefix_heavy",
                      tenants=[Tenant("kv-a"), Tenant("kv-b")])
        summary = gen.run(EngineSink(fm, max_tokens=6), total=16,
                          timeout_s=300.0)
        if summary.get("errors"):
            problems.append(f"prefix-heavy load errors: {summary['errors']}")
        # -- sibling transfer: kill the directory-known holder of one
        # family pre-stream; the failover replica must pull the family's
        # warm prefix from the holder over TransferPrefix before
        # dispatching (placement away from warm KV ≠ a cold re-prefill)
        warm = submit(PREFIX_PROMPTS[0] + " [sibling/warm]")
        warm.result(300)
        key = affinity_key(tok.encode(PREFIX_PROMPTS[0] + " [sibling/hit]"),
                           block_tokens=fm.router.block_tokens,
                           blocks=fm.router.affinity_blocks)
        holder = fm.scheduler.directory.holder(
            key, [r.id for r in fm.pool.replicas])
        if holder is None:
            problems.append("prefix family never registered in directory")
        else:
            faults.arm(faults.FaultSpec(site="worker.stream", mode="raise",
                                        match=holder, times=1))
            try:
                h = submit(PREFIX_PROMPTS[0] + " [sibling/hit]")
                h.result(300)
                if h.finish_reason not in ("stop", "length"):
                    problems.append(
                        f"sibling-path request finished {h.finish_reason!r}")
            finally:
                faults.clear()
        # -- spill→reload round trip: a dozen cold filler families crush
        # both replicas' 40-block pools (the prefix families become LRU
        # victims → spill to host RAM), then every family re-request
        # re-onboards its spilled chain
        fillers = [
            submit(f"cold filler family {k:02d} keeps the prefix pool "
                   f"under sustained eviction pressure " * 3)
            for k in range(12)
        ]
        for h in fillers:
            h.result(300)
        for i, head in enumerate(PREFIX_PROMPTS):
            submit(head + f" [reload/{i}]").result(300)
        # -- assertions across both replicas' allocators
        spills = reloads = 0
        for r in fm.pool.replicas:
            ts = r.sm.runner.allocator.tier_stats()
            if ts is None:
                problems.append(f"{r.id}: tier never attached "
                                f"(LOCALAI_KV_TIER_MB ignored)")
                continue
            spills += ts["spills_total"]
            reloads += ts["reloads_total"]
        if spills < 1:
            problems.append("no HBM→host spills under pool pressure")
        if reloads < 1:
            problems.append(
                f"no spill→reload round trip ({spills} spills)")
        st = fm.scheduler.directory.stats()
        if st["hits"] < 1:
            problems.append(f"directory took no routing hits: {st}")
        if fm.scheduler.sibling_transfers < 1:
            problems.append(
                f"no sibling prefix transfer "
                f"({fm.scheduler.sibling_fallbacks} fallbacks)")
        if fm.scheduler.sibling_transfer_bytes <= 0 \
                and fm.scheduler.sibling_transfers > 0:
            problems.append("sibling transfer moved 0 bytes")
        # scrape-time refresh, exactly what GET /metrics does: the tier
        # roll-up rides the engine gauges, the directory its own pane
        update_engine_gauges("fleet-kv", fm.scheduler.metrics())
        fm.scheduler.export_gauges()
    finally:
        faults.clear()
        fm.close()
        if prev_tier is None:
            os.environ.pop("LOCALAI_KV_TIER_MB", None)
        else:
            os.environ["LOCALAI_KV_TIER_MB"] = prev_tier
    return problems


def check_fleetview(registry, fleet_flight_out: str) -> list[str]:
    """Round-15 fleet telemetry plane: a 2-replica WORKER-PROCESS fleet
    under a tools.loadgen mixed tenant workload → one request stitched
    into ONE waterfall (front-door + worker spans, worker side harvested
    over the real GetTelemetry gRPC and skew-anchored) + the merged
    fleet flight view written as a CI artifact."""
    import json as jsonlib

    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import WorkerReplica
    from localai_tpu.obs import fleetview
    from localai_tpu.obs.trace import STORE
    from tools.loadgen import EngineSink, LoadGen, Tenant

    problems: list[str] = []
    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": "fleet-grpc", "model": "debug:tiny", "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 6},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64, 128],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16},
    })

    def factory(rid, role):
        return WorkerReplica(rid, role, mcfg, app,
                             env={"JAX_PLATFORMS": "cpu"})

    fm = FleetServingModel(mcfg, app, factory, replicas=2,
                           prefill_replicas=0, disagg_threshold=1 << 30)
    try:
        gen = LoadGen(mix={"chat": 0.7, "batch": 0.3},
                      tenants=[Tenant("free", 3), Tenant("pro", 1)],
                      rate=10.0, seed=3, max_tokens=6)
        summary = gen.run(EngineSink(fm, max_tokens=6), total=8)
        bad = {r: n for r, n in summary["outcomes"].items()
               if r not in ("stop", "length")}
        if bad or summary["errors"]:
            problems.append(
                f"loadgen traffic failed: {bad} {summary['errors']}")
        stitched = None
        for tid in summary["trace_ids"]:
            local = [t.to_dict() for t in STORE.find(tid)]
            if not local:
                continue
            s = fleetview.stitched_trace(fm, tid, local)
            if any(e["replica"] for e in s["waterfall"]):
                stitched = s
                break
        if stitched is None:
            problems.append(
                "no loadgen trace stitched a worker-side half "
                "(GetTelemetry harvest returned nothing)")
        else:
            worker_spans = {e["name"] for e in stitched["waterfall"]
                            if e["replica"]}
            front_spans = {e["name"] for e in stitched["waterfall"]
                           if not e["replica"]}
            if not {"prefill", "decode"} & worker_spans:
                problems.append(
                    f"worker-side engine spans missing: {worker_spans}")
            if "rpc" not in front_spans:
                problems.append(
                    f"front-door rpc span missing: {front_spans}")
            panes = [p for p in stitched["replicas"].values()
                     if p.get("traces")]
            if not panes or not panes[0]["traces"][0]["attrs"].get(
                    "skew_anchored"):
                problems.append("harvested worker trace is not "
                                "skew-anchored")
        flight = fleetview.fleet_flight(fm)
        with_records = [rid for rid, p in flight["replicas"].items()
                        if p.get("records")]
        if len(with_records) < 2:
            problems.append(
                f"merged fleet flight covers {with_records} "
                f"(need >=2 replicas): {flight['replicas']}")
        if flight["count"] == 0 or any(
                "replica" not in r for r in flight["records"]):
            problems.append("merged fleet flight rows miss the replica "
                            "column")
        with open(fleet_flight_out, "w") as f:
            jsonlib.dump(flight, f, indent=2, sort_keys=True)
        fm.scheduler.export_gauges()
    finally:
        fm.close()
    return problems


def check_usage(registry, usage_out: str) -> list[str]:
    """Round-18 usage accounting plane: a 2-replica WORKER-PROCESS fleet
    serves a weighted tenant mix from tools.loadgen, then the ledger must
    (a) attribute every request to the right HASHED tenant bucket (exact
    against what loadgen actually sent, and within tolerance of the
    configured mix), (b) reconcile per worker process: delivered +
    flight-class waste tokens == that worker's flight-ring total, with
    the front door's own ledger summing to the workers' (no double feed,
    no dropped feed), (c) round-trip the history store through a disk
    snapshot, and (d) export to /metrics WITHOUT any raw tenant name.
    The ``/v1/usage``-shaped payload lands in ``usage_out`` (a CI
    artifact)."""
    import json as jsonlib
    import tempfile

    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.replica import WorkerReplica
    from localai_tpu.obs import fleetview
    from localai_tpu.obs.history import History
    from localai_tpu.obs.ledger import FLIGHT_WASTE, LEDGER, derive_tenant
    from tools.loadgen import EngineSink, LoadGen, Tenant

    problems: list[str] = []
    # the ledger is process-global and earlier rounds' loadgen traffic
    # fed it; this round asserts exact attribution, so start clean
    LEDGER.reset()
    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": "fleet-usage", "model": "debug:tiny", "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 6},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64, 128],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16},
    })

    def factory(rid, role):
        return WorkerReplica(rid, role, mcfg, app,
                             env={"JAX_PLATFORMS": "cpu"})

    fm = FleetServingModel(mcfg, app, factory, replicas=2,
                           prefill_replicas=0, disagg_threshold=1 << 30)
    mix = {"usage-free": 3, "usage-pro": 1}
    try:
        gen = LoadGen(mix={"chat": 1.0},
                      tenants=[Tenant(n, w) for n, w in mix.items()],
                      rate=20.0, seed=7, max_tokens=6)
        summary = gen.run(EngineSink(fm, max_tokens=6), total=24,
                          timeout_s=300.0)
        bad = {r: n for r, n in summary["outcomes"].items()
               if r not in ("stop", "length")}
        if bad or summary["errors"]:
            problems.append(
                f"usage traffic failed: {bad} {summary['errors']}")
        payload = LEDGER.usage_payload()
        by_tenant: dict[str, int] = {}
        for row in payload["data"]:
            by_tenant[row["tenant"]] = (by_tenant.get(row["tenant"], 0)
                                        + row["requests"])
        # exact attribution: the ledger's per-tenant request counts must
        # equal what loadgen actually sent under each name's hash
        for name, sent in summary["tenants"].items():
            got = by_tenant.get(derive_tenant(name), 0)
            if got != sent:
                problems.append(
                    f"tenant {name}: ledger counted {got} of {sent} "
                    f"requests")
        # …and the realized shares must sit near the configured 3:1 mix
        total = sum(summary["tenants"].values())
        weight = sum(mix.values())
        for name, w in mix.items():
            share = by_tenant.get(derive_tenant(name), 0) / max(1, total)
            want = w / weight
            if abs(share - want) > 0.25:
                problems.append(
                    f"tenant {name} share {share:.2f} vs configured "
                    f"{want:.2f} (tolerance 0.25)")
        leaked = [t for t in by_tenant if t.startswith("usage-")]
        if leaked:
            problems.append(
                f"raw tenant names leaked into the ledger: {leaked}")
        # windowed view: every finished request is inside the last hour,
        # so the ring-backed aggregation must see all of them
        windowed = LEDGER.usage_payload(window=3600.0)
        if windowed["events"] != total:
            problems.append(
                f"windowed usage saw {windowed['events']} of {total} "
                f"events")
        # per-engine-process reconciliation: each worker's ledger
        # (harvested over GetTelemetry) must balance its own flight ring
        usage_panes = fleetview.fleet_usage(fm)
        flight = fleetview.fleet_flight(fm)
        reconciled = 0
        for rid, pane in usage_panes.items():
            if "goodput_tokens" not in pane:
                problems.append(
                    f"{rid}: no worker usage pane harvested: {pane}")
                continue
            delivered = sum(pane["goodput_tokens"].values())
            waste = sum(
                cell["tokens"] for key, cell in pane["waste"].items()
                if key.partition("/")[0] in FLIGHT_WASTE)
            ftotal = (flight["replicas"].get(rid) or {}).get("tokens_total")
            if ftotal is None:
                problems.append(f"{rid}: no flight pane to reconcile "
                                f"against")
            elif delivered + waste != ftotal:
                problems.append(
                    f"{rid}: ledger {delivered} delivered + {waste} "
                    f"flight-waste != flight ring {ftotal} tokens")
            else:
                reconciled += 1
        if reconciled < 2:
            problems.append(
                f"reconciled {reconciled} worker ledger(s), need 2")
        # the front door counted every delivered token exactly once —
        # its total equals the workers' (one feed per tier, no overlap)
        front = LEDGER.goodput_totals("fleet-usage")
        worker_delivered = sum(
            sum(p.get("goodput_tokens", {}).values())
            for p in usage_panes.values())
        if front["delivered_tokens"] != worker_delivered:
            problems.append(
                f"front-door delivered {front['delivered_tokens']} != "
                f"workers' {worker_delivered}")
        # history round-trip: ledger series → disk snapshot → fresh store
        h = History()
        h.observe_ledger(LEDGER)
        with tempfile.TemporaryDirectory() as td:
            h.save(td)
            h2 = History()
            if not h2.load(td):
                problems.append("history snapshot did not restore")
            elif h2.series_names() != h.series_names():
                problems.append(
                    f"restored history lost series: "
                    f"{set(h.series_names()) - set(h2.series_names())}")
            else:
                name = f"tenant_tokens.{derive_tenant('usage-free')}"
                q = h2.query(name, res=1)
                if not q or not q["points"]:
                    problems.append(
                        f"restored history has no points for {name}")
        # export + exposition safety: hashed buckets render, raw names
        # never do (REQUIRED_USAGE pins the family lines)
        LEDGER.export(registry)
        expo = registry.render()
        tser = (f'localai_tenant_tokens_total{{lane="interactive",'
                f'model="fleet-usage",'
                f'tenant="{derive_tenant("usage-free")}"}}')
        if tser not in expo:
            problems.append(f"tenant series missing from /metrics: {tser}")
        for raw in mix:
            if raw in expo:
                problems.append(
                    f"raw tenant name {raw!r} leaked into /metrics")
        with open(usage_out, "w") as f:
            jsonlib.dump({
                "payload": payload,
                "windowed": windowed,
                "replicas": usage_panes,
                "loadgen": {k: v for k, v in summary.items()
                            if k != "trace_ids"},
            }, f, indent=2, sort_keys=True)
        fm.scheduler.export_gauges()
    finally:
        fm.close()
    return problems


def check_anatomy(sched, tok, registry, anatomy_out: str) -> list[str]:
    """Round-19 dispatch anatomy: drive extra client traffic through the
    REAL smoke engine, then assert the phase decomposition holds record
    by record (gap+sched+launch+sync ≤ dispatch_ms — the interval-tiling
    invariant ``Scheduler._take_anat`` guarantees by clamp order), the
    derived ``host_overhead_fraction`` is a genuine fraction in (0, 1),
    and the client-observed TTFT p95 from ``tools.loadgen`` agrees with
    the server-side ``localai_ttft_seconds`` histogram (same submit /
    first-token stamps, so gross disagreement means one side is lying —
    the tolerance only absorbs bucket granularity and the earlier smoke
    requests sharing the histogram). Writes the breakdown + cross-check
    receipt to ``anatomy_out`` (a CI artifact)."""
    import json as jsonlib
    import re
    import types

    from localai_tpu.obs import anatomy as obs_anatomy
    from localai_tpu.obs.metrics import update_engine_gauges
    from tools.loadgen import EngineSink, LoadGen

    problems = []

    def ttft_buckets():
        # cumulative (upper_bound_s, count) pairs for model="smoke" out
        # of the rendered exposition — the same text a scrape would see
        pat = re.compile(r'localai_ttft_seconds_bucket\{model="smoke",'
                         r'le="([^"]+)"\} (\d+)')
        return [(float("inf") if le == "+Inf" else float(le), int(c))
                for le, c in pat.findall(registry.ttft.render())]

    # chat-only mix: the batch lane is excluded from the TTFT histogram
    # by design, so every client latency sample must have a server twin.
    # Snapshot the histogram FIRST: the earlier smoke requests paid the
    # compile, and diffing bucket counts is what isolates the server-side
    # view of exactly this traffic.
    before = dict(ttft_buckets())
    sm = types.SimpleNamespace(scheduler=sched, tokenizer=tok, runner=None)
    gen = LoadGen(mix={"chat": 1.0}, rate=64.0, seed=19, max_tokens=8)
    summary = gen.run(EngineSink(sm, max_tokens=8), total=8)
    if summary["errors"]:
        problems.append(f"anatomy loadgen traffic errored: "
                        f"{summary['errors'][:3]}")

    # (a) per-record tiling invariant over the live ring
    rows = sched.flight.snapshot()
    decode_rows = [r for r in rows if not r["compile"]]
    if not decode_rows:
        problems.append("anatomy: flight ring has no post-compile rows")
    for r in decode_rows:
        phase_sum = (r["gap_ms"] + r["sched_ms"] + r["launch_ms"]
                     + r["sync_ms"])
        # 5e-3 slack: snapshot rounds each column to 3 decimals, so four
        # rounded-up phases can nominally exceed a rounded-down dispatch
        if phase_sum > r["dispatch_ms"] + 5e-3:
            problems.append(
                f"anatomy: phase sum {phase_sum:.3f}ms exceeds "
                f"dispatch_ms {r['dispatch_ms']:.3f} "
                f"(program={r['program']})")
            break

    # (b) derived fractions: genuine open-interval fractions
    anat = obs_anatomy.summarize(sched.flight, window_s=None)
    hof = anat["host_overhead_fraction"]
    bubble = anat["device_bubble_fraction"]
    if not anat["samples"]:
        problems.append("anatomy: summarize() saw zero samples")
    elif hof is None or not (0.0 < hof < 1.0):
        problems.append(
            f"anatomy: host_overhead_fraction {hof} outside (0, 1)")
    if bubble is not None and not (0.0 <= bubble <= 1.0):
        problems.append(
            f"anatomy: device_bubble_fraction {bubble} outside [0, 1]")

    # (c) client-vs-server latency cross-check: diff the histogram around
    # the loadgen run (isolating exactly this traffic's server view),
    # then the client p95 must land inside the delta-histogram's p95
    # bucket — both sides derive from the same handle stamps, so the
    # slack only absorbs bucket granularity
    client = summary.get("client_ttft_ms")
    cross = {"client_ttft_ms": client}
    if not client:
        problems.append("anatomy: loadgen produced no client TTFT samples")
    else:
        delta = [(ub, cum - before.get(ub, 0))
                 for ub, cum in ttft_buckets()]
        total = delta[-1][1] if delta else 0
        if total < client["count"]:
            problems.append(
                f"anatomy: server ttft histogram gained {total} samples "
                f"but the client observed {client['count']}")
        else:
            lo, hi = 0.0, float("inf")
            for ub, cum in delta:
                if cum >= 0.95 * total:
                    hi = ub
                    break
                lo = ub
            client_p95_s = client["p95"] / 1e3
            if (client_p95_s < lo / 2 - 0.05
                    or client_p95_s > hi * 2 + 0.05):
                problems.append(
                    f"anatomy: client ttft p95 {client_p95_s:.3f}s "
                    f"disagrees with server histogram p95 bucket "
                    f"({lo}, {hi}]s")
            cross.update(server_p95_bucket_lo_s=lo,
                         server_p95_bucket_hi_s=(
                             None if hi == float("inf") else hi),
                         server_samples=total)

    # re-export so the phase gauges reflect the anatomy traffic, exactly
    # what a scrape after this load would show
    update_engine_gauges("smoke", sched.metrics())
    with open(anatomy_out, "w") as f:
        jsonlib.dump({
            "breakdown": obs_anatomy.breakdown(sched.flight,
                                               window_s=None),
            "client_cross_check": cross,
            "loadgen": {k: v for k, v in summary.items()
                        if k != "trace_ids"},
        }, f, indent=2, sort_keys=True)
    return problems


def check_autoscale(registry, autoscale_out: str) -> list[str]:
    """Round 20 — elastic capacity end-to-end: a 1-replica autoscaled
    in-process fleet rides a seeded spike (tools.loadgen profile=spike)
    into a telemetry-driven scale-out, hot-swaps its replicas mid-life,
    quiesces into scale-to-zero, and cold-re-onboards a replica for the
    next request (which waits and completes — never errors). The
    capacity trajectory lands in ``autoscale_out`` (a CI artifact,
    ingestible by ``tools/usage_report.py --ingest-autoscale``)."""
    import json as jsonlib
    import threading

    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.engine.scheduler import GenRequest
    from localai_tpu.fleet import FleetServingModel
    from localai_tpu.fleet.autoscale import (AutoscaleConfig,
                                             AutoscaleController)
    from localai_tpu.fleet.replica import InProcessReplica
    from localai_tpu.models.manager import build_serving_model
    from localai_tpu.obs.history import HISTORY
    from tools.loadgen import EngineSink, LoadGen

    problems: list[str] = []
    app = AppConfig()
    mcfg = ModelConfig.model_validate({
        "name": "fleet-auto", "model": "debug:tiny", "context_size": 256,
        "parameters": {"temperature": 0.0, "max_tokens": 6},
        "engine": {"max_slots": 2, "prefill_buckets": [16, 32, 64, 128],
                   "dtype": "float32", "kv_dtype": "float32",
                   "kv_block_tokens": 16},
    })

    def factory(rid, role):
        return InProcessReplica(
            rid, role, lambda: build_serving_model(mcfg, app))

    fm = FleetServingModel(mcfg, app, factory, replicas=1)
    auto = AutoscaleController(fm, config=AutoscaleConfig(
        min_replicas=0, max_replicas=3, interval_s=0.1,
        in_idle_s=1.0, zero_idle_s=1.5, out_queue_depth=1.5,
        out_cooldown_s=0.5, in_cooldown_s=0.3, cold_timeout_s=120.0))
    fm.autoscaler = auto
    peak = {"healthy": 0}
    sampling = threading.Event()

    def sample():
        while not sampling.wait(0.05):
            peak["healthy"] = max(peak["healthy"],
                                  len(fm.pool.healthy("decode")))

    sampler = threading.Thread(target=sample, daemon=True)
    report: dict = {}
    try:
        auto.start()
        sampler.start()
        # phase 1 — spike: seeded Poisson baseline, 6× burst window; the
        # burst queues behind the single replica and the controller adds
        # capacity (queue-depth signal)
        gen = LoadGen(mix={"chat": 1.0}, rate=6.0, seed=11, max_tokens=6,
                      profile="spike", spike_start_s=0.5, spike_len_s=4.0,
                      spike_mult=8.0)
        summary = gen.run(EngineSink(fm, max_tokens=6), total=36,
                          timeout_s=300.0)
        bad = {r: n for r, n in summary["outcomes"].items()
               if r not in ("stop", "length")}
        if bad or summary["errors"]:
            problems.append(
                f"autoscale: spike traffic failed: {bad} "
                f"{summary['errors'][:3]}")
        deadline = time.monotonic() + 30.0
        while (auto.decisions["scale_out"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
        if auto.decisions["scale_out"] < 1:
            problems.append(
                f"autoscale: no scale-out under the spike "
                f"(decisions {auto.decisions})")
        if peak["healthy"] < 2:
            problems.append(
                f"autoscale: fleet never exceeded 1 healthy replica "
                f"(peak {peak['healthy']})")
        # phase 2 — hot weight swap while capacity is up: every local
        # replica is replaced by a freshly booted one, traffic shifts,
        # the old generation drains clean
        swap = fm.swap()
        report["swap"] = swap
        if not swap.get("ok"):
            problems.append(f"autoscale: hot swap failed: {swap}")
        # phase 3 — quiesce: all replicas idle past zero_idle_s → the
        # model scales to ZERO
        deadline = time.monotonic() + 60.0
        while (fm.pool.healthy("decode")
               and time.monotonic() < deadline):
            time.sleep(0.1)
        if fm.pool.healthy("decode"):
            problems.append(
                f"autoscale: fleet did not scale to zero after quiesce "
                f"(decisions {auto.decisions})")
        if auto.decisions["scale_to_zero"] < 1:
            problems.append(
                f"autoscale: no scale_to_zero decision recorded "
                f"({auto.decisions})")
        # phase 4 — cold re-onboard: the next request finds ZERO
        # replicas, waits out the cold boot, and completes
        t0 = time.monotonic()
        h = fm.scheduler.submit(GenRequest(
            prompt=fm.tokenizer.encode("wake the scaled-to-zero fleet"),
            max_new_tokens=6, temperature=0.0))
        h.result(timeout=300)
        cold_ms = (time.monotonic() - t0) * 1e3
        if h.finish_reason not in ("stop", "length"):
            problems.append(
                f"autoscale: held request finished "
                f"{h.finish_reason!r} instead of being served by the "
                f"cold re-onboard")
        if auto.decisions["cold_start"] < 1:
            problems.append(
                f"autoscale: no cold_start recorded ({auto.decisions})")
        fm.scheduler.export_gauges()
        report.update({
            "loadgen": summary,
            "decisions": dict(auto.decisions),
            "peak_healthy": peak["healthy"],
            "cold_start_ms": round(cold_ms, 1),
            "last_decision": auto.last_decision,
            "target_series": HISTORY.query(
                "fleet_target_replicas.fleet-auto", res=1),
        })
    finally:
        sampling.set()
        sampler.join(2)
        auto.stop()
        fm.close()
    with open(autoscale_out, "w") as f:
        jsonlib.dump(report, f, indent=2, sort_keys=True)
    return problems


def check_anomaly_capture(registry, profile_dir: str) -> list[str]:
    """Round-15 anomaly profiler: an injected ``engine.drain`` stall
    trips the watchdog and auto-captures a (real) jax.profiler trace
    with the stall's forensic trace id; a second stall inside the
    cooldown is refused. Scratch watchdog + scratch manager — hermetic,
    no env fiddling."""
    from pathlib import Path

    from localai_tpu import faults
    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.engine.scheduler import GenRequest, Scheduler
    from localai_tpu.models.registry import resolve_model
    from localai_tpu.obs import EngineTelemetry, TraceStore, Watchdog
    from localai_tpu.obs.profiler import ProfileManager
    from localai_tpu.obs.slo import SLOTracker
    from localai_tpu.utils.tokenizer import ByteTokenizer

    problems: list[str] = []
    store = TraceStore()
    wd = Watchdog(deadline=0.8, registry=registry, store=store,
                  poll_interval=0.1)
    wd.start()
    pm = ProfileManager(enabled=True, seconds=0.2, out_dir=profile_dir,
                        max_per_hour=10, cooldown_s=3600.0,
                        registry=registry)
    pm.install(watchdog=wd, slo=SLOTracker(registry=registry, targets={}))
    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(tiny.cfg, tiny.params, num_slots=2, max_ctx=64,
                         prefill_buckets=[16], kv_dtype="float32",
                         paged=True, kv_block_tokens=16)
    sched = Scheduler(
        runner, ByteTokenizer(), watchdog=wd,
        telemetry=EngineTelemetry(model="stall-anomaly", store=store,
                                  slo=SLOTracker(registry=registry,
                                                 targets={})))
    tok = ByteTokenizer()
    try:
        for _ in range(2):  # second stall lands inside the cooldown
            faults.arm(faults.FaultSpec(
                site="engine.drain", mode="hang", delay_s=3.0, times=1,
                match="stall-anomaly"))
            h = sched.submit(GenRequest(prompt=tok.encode("stall me"),
                                        max_new_tokens=4, temperature=0.0))
            h.result(timeout=120)
        pm.wait_idle(30.0)
        stalls = [e for e in pm.entries() if e["trigger"] == "stall"]
        if len(stalls) != 1:
            problems.append(
                f"expected exactly 1 stall capture (cooldown eats the "
                f"second), got {len(stalls)}")
        else:
            if not stalls[0]["trace_id"].startswith("stall-"):
                problems.append(
                    f"capture carries no triggering trace id: {stalls[0]}")
            if not stalls[0].get("ok"):
                problems.append(
                    f"profiler capture failed: {stalls[0].get('error')}")
        if pm.report()["skipped"].get("cooldown", 0) < 1:
            problems.append("second stall inside the cooldown was not "
                            "refused")
        if not (Path(profile_dir) / "manifest.json").exists():
            problems.append("no profile manifest written")
    finally:
        faults.clear("engine.drain")
        sched.shutdown()
        pm.stop()
        wd.stop()
    return problems


# the fleet-served model for the --loopsan phase: NO embeddings usecase
# (embeddings-capable models keep the single-engine path — manager._load),
# so with fleet_replicas=2 this serves from a 2-replica in-process fleet
LOOPSAN_YAML = """\
name: fleet-http
model: "debug:tiny"
context_size: 96
parameters:
  temperature: 0.0
  max_tokens: 8
engine:
  max_slots: 2
  prefill_buckets: [16, 32]
  dtype: float32
  kv_dtype: float32
"""


def check_loopsan(loopsan_out: str) -> list[str]:
    """Round-16 event-loop sanitizer: boot the real aiohttp API over a
    2-replica in-process fleet, install ``tools.loopsan``, prove the
    detector catches a deliberately injected ``time.sleep(0.2)`` on the
    loop, reset, then drive mixed loadgen HTTP traffic plus one SSE
    stream and require ZERO ≥ 50 ms stalls. The earlier phases run the
    engine/fleet stack on plain threads — the event loop only exists in
    the API tier, so this phase is where the sanitizer has something to
    watch."""
    import asyncio
    import json as jsonlib
    import tempfile
    import threading
    from pathlib import Path

    import httpx

    from localai_tpu.api.server import AppState, create_app
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.loader import ConfigLoader
    from tools.loadgen import HttpSink, LoadGen, Tenant
    from tools.loopsan import LoopSanitizer

    problems: list[str] = []
    selfcheck: dict = {}
    with tempfile.TemporaryDirectory() as tmp:
        models = Path(tmp) / "models"
        models.mkdir()
        (models / "fleet-http.yaml").write_text(LOOPSAN_YAML)
        cfg = AppConfig(
            model_path=str(models),
            upload_path=str(Path(tmp) / "uploads"),
            config_path=str(Path(tmp) / "conf"),
            fleet_replicas=2, fleet_backend="inprocess",
        )
        loader = ConfigLoader(models)
        loader.load_from_path(context_size=cfg.context_size)
        state = AppState(cfg, loader)

        boot: dict = {}
        started = threading.Event()

        def serve():
            from aiohttp import web

            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            boot["loop"] = loop

            async def up():
                app = create_app(state)
                runner = web.AppRunner(app)
                await runner.setup()
                site = web.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                boot["port"] = runner.addresses[0][1]
                boot["runner"] = runner
                started.set()

            loop.run_until_complete(up())
            loop.run_forever()

        th = threading.Thread(target=serve, daemon=True, name="loopsan-api")
        th.start()
        if not started.wait(60):
            return ["loopsan: API server failed to start"]
        base = f"http://127.0.0.1:{boot['port']}"
        loop = boot["loop"]

        def chat_body(text, **extra):
            return {"model": "fleet-http", "max_tokens": 6,
                    "temperature": 0.0,
                    "messages": [{"role": "user", "content": text}],
                    **extra}

        try:
            # warm up BEFORE the sanitizer installs: the first request
            # builds both fleet replicas (jit compile in executor
            # threads); measuring loop health while compiles monopolize
            # CPU would report scheduler noise, not handler stalls
            with httpx.Client(base_url=base, timeout=300.0) as c:
                r = c.post("/v1/chat/completions",
                           json=chat_body("loopsan warmup"))
                if r.status_code != 200:
                    return [f"loopsan: warmup request failed "
                            f"{r.status_code}: {r.text[:200]}"]

            san = LoopSanitizer(threshold_ms=50.0)
            san.install()
            try:
                # self-check: a sync sleep dispatched onto the live loop
                # is EXACTLY the bug class the sanitizer exists for — it
                # must be caught before a clean run means anything
                loop.call_soon_threadsafe(time.sleep, 0.2)
                deadline = time.monotonic() + 10.0
                while not san.stalls() and time.monotonic() < deadline:
                    time.sleep(0.02)
                injected = san.stalls()
                if len(injected) != 1:
                    problems.append(
                        f"loopsan self-check: injected 200 ms sleep "
                        f"produced {len(injected)} stall(s), expected 1")
                else:
                    s = injected[0]
                    if "sleep" not in s.label or s.duration_ms < 150.0:
                        problems.append(
                            f"loopsan self-check: stall misattributed: "
                            f"{s.label} ({s.duration_ms:.1f} ms)")
                    selfcheck = s.to_dict()
                san.reset()

                sink = HttpSink(base, "fleet-http", max_tokens=6)
                try:
                    gen = LoadGen(mix={"chat": 0.7, "batch": 0.3},
                                  tenants=[Tenant("free", 3),
                                           Tenant("pro", 1)],
                                  rate=12.0, seed=5, max_tokens=6)
                    summary = gen.run(sink, total=10)
                finally:
                    sink.close()
                bad = {r: n for r, n in summary["outcomes"].items()
                       if r not in ("stop", "length")}
                if bad or summary["errors"]:
                    problems.append(f"loopsan: HTTP traffic failed: "
                                    f"{bad} {summary['errors']}")
                # one live SSE stream: the chunked writer must yield
                # between deltas, never hold the loop for a whole reply
                events = []
                with httpx.Client(base_url=base, timeout=120.0) as c:
                    with c.stream(
                            "POST", "/v1/chat/completions",
                            json=chat_body("stream smoke", stream=True),
                    ) as resp:
                        status = resp.status_code
                        for line in resp.iter_lines():
                            if line.startswith("data: "):
                                events.append(line)
                if status != 200 or len(events) < 2:
                    problems.append(f"loopsan: SSE stream broke: status "
                                    f"{status}, {len(events)} events")
                stalls = san.stalls()
                snap = san.snapshot()
            finally:
                san.uninstall()
        finally:
            fut = asyncio.run_coroutine_threadsafe(
                boot["runner"].cleanup(), loop)
            fut.result(30)
            loop.call_soon_threadsafe(loop.stop)
            th.join(15)

    if snap["callbacks_seen"] == 0:
        problems.append("loopsan: sanitizer observed no loop callbacks — "
                        "the Handle._run patch is not active")
    snap["injected_selfcheck"] = selfcheck
    with open(loopsan_out, "w") as f:
        jsonlib.dump(snap, f, indent=2, sort_keys=True)
    if stalls:
        print(san.report())
        problems.append(
            f"loopsan: {len(stalls)} event-loop stall(s) >= "
            f"{san.threshold_ms:g} ms during the fleet HTTP lifecycle "
            f"(report → {loopsan_out})")
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="telemetry_summary.json")
    parser.add_argument("--flight-out", default="flight_snapshot.json")
    parser.add_argument("--batch-out", default="batch_result.jsonl")
    parser.add_argument("--fleet-flight-out", default="fleet_flight.json")
    parser.add_argument("--usage-out", default="usage_snapshot.json")
    parser.add_argument("--anatomy-out", default="anatomy_report.json")
    parser.add_argument("--autoscale-out", default="autoscale_report.json")
    parser.add_argument("--profile-dir", default="profile_manifest")
    parser.add_argument("--requests", type=int, default=4)
    # two dispatch-rounds past the compile-bearing first one, so the
    # flight ring has post-compile samples and step_ms percentiles exist
    parser.add_argument("--max-tokens", type=int, default=40)
    parser.add_argument(
        "--racecheck", action="store_true",
        help="run the lifecycle under tools.racecheck instrumented locks "
             "and fail on any observed lock-order inversion")
    parser.add_argument(
        "--loopsan", action="store_true",
        help="boot the real HTTP API over a 2-replica fleet under "
             "tools.loopsan and fail on any event-loop stall >= 50 ms")
    parser.add_argument("--loopsan-out", default="loopsan_report.json")
    args = parser.parse_args(argv)

    monitor = None
    if args.racecheck:
        # install BEFORE the localai imports below: module import is when
        # the process-wide locks (trace store, registry, watchdog) are
        # constructed, and only post-install locks are traced
        from tools.racecheck import LockMonitor

        monitor = LockMonitor().install()

    from localai_tpu.engine.runner import ModelRunner
    from localai_tpu.engine.scheduler import GenRequest, Scheduler
    from localai_tpu.models.registry import resolve_model
    from localai_tpu.obs import REGISTRY, EngineTelemetry, TraceStore
    from localai_tpu.obs.metrics import update_engine_gauges
    from localai_tpu.obs.slo import SLOTracker
    from localai_tpu.utils.tokenizer import ByteTokenizer

    t_boot = time.monotonic()
    tiny = resolve_model("debug:tiny", dtype="float32")
    runner = ModelRunner(
        tiny.cfg, tiny.params, num_slots=4, max_ctx=96,
        prefill_buckets=[16, 32], kv_dtype="float32",
        # the serving default: paged block pool + chunked prefill — the
        # smoke must exercise (and assert) the block gauges end-to-end
        paged=True, kv_block_tokens=16, prefill_chunk=16,
    )
    store = TraceStore()
    # a dedicated observatory (no env targets) so the smoke is hermetic;
    # it still writes the shared REGISTRY the exposition check reads
    slo = SLOTracker(registry=REGISTRY, targets={})
    sched = Scheduler(
        runner, ByteTokenizer(),
        telemetry=EngineTelemetry(model="smoke", store=store, slo=slo),
    )
    tok = ByteTokenizer()
    try:
        handles = [
            sched.submit(GenRequest(
                prompt=tok.encode(f"telemetry smoke request {i}"),
                max_new_tokens=args.max_tokens, temperature=0.0,
                trace_id=f"smoke-{i}",
            ))
            for i in range(args.requests)
        ]
        for h in handles:
            h.result(timeout=300)
        # scrape-time refresh, exactly what GET /metrics does
        engine_metrics = sched.metrics()
        update_engine_gauges("smoke", engine_metrics)
        slo.export_gauges()
        problems = check_introspection(runner, REGISTRY, store)
        problems += check_slo_overload(REGISTRY)
        problems += check_batch(sched, REGISTRY, args.batch_out)
        problems += check_fleet(REGISTRY)
        problems += check_kveconomy(REGISTRY)
        problems += check_fleetview(REGISTRY, args.fleet_flight_out)
        problems += check_usage(REGISTRY, args.usage_out)
        problems += check_anatomy(sched, tok, REGISTRY, args.anatomy_out)
        problems += check_autoscale(REGISTRY, args.autoscale_out)
        problems += check_anomaly_capture(REGISTRY, args.profile_dir)
        if args.loopsan:
            problems += check_loopsan(args.loopsan_out)
        # scrape-time trace-ring sizing receipt, exactly what GET /metrics
        # exports (LOCALAI_TRACE_CAPACITY satellite)
        from localai_tpu.obs.trace import STORE as TRACE_STORE

        REGISTRY.trace_ring_size.set(TRACE_STORE.capacity)
        flight_pct = sched.flight.percentiles()
        flight_snapshot = {
            "model": "smoke",
            "dispatches": sched.flight.count,
            "tokens_total": sched.flight.total_tokens,
            "percentiles": flight_pct,
            "records": sched.flight.snapshot(),
        }
        if sched.flight.count == 0:
            problems.append("flight ring is empty after synthetic load")
        if flight_pct["step_ms_p50"] is None:
            problems.append(
                "flight ring has no post-compile step-time samples")
    finally:
        sched.shutdown()

    racecheck_summary = None
    if monitor is not None:
        monitor.uninstall()
        inversions = monitor.inversions()
        print(monitor.report())
        if inversions:
            print("FAIL: lock-order inversions observed across the "
                  "fleet+batch+shed lifecycle (see report above)")
            return 1
        racecheck_summary = {
            "locks_created": monitor.locks_created,
            "ordered_edges": len(monitor.edges()),
            "inversions": 0,
        }

    exposition = REGISTRY.render()
    missing = [s for s in (REQUIRED_SERIES + REQUIRED_FAMILIES
                           + REQUIRED_INTROSPECTION + REQUIRED_SLO
                           + REQUIRED_BATCH + REQUIRED_FLEET
                           + REQUIRED_KVECONOMY + REQUIRED_FLEETVIEW
                           + REQUIRED_USAGE + REQUIRED_ANATOMY
                           + REQUIRED_AUTOSCALE)
               if s not in exposition]
    if missing or problems:
        print("FAIL: missing engine telemetry in /metrics exposition:")
        for s in missing:
            print(f"  - {s}")
        for p in problems:
            print(f"  - {p}")
        return 1

    traces = [t.to_dict() for t in store.recent(limit=args.requests * 2)
              if t.kind == "request"]
    ttfts = [t["attrs"]["ttft_ms"] for t in traces
             if t["attrs"].get("ttft_ms") is not None]
    tpots = [t["attrs"]["tpot_ms"] for t in traces
             if t["attrs"].get("tpot_ms") is not None]
    if not ttfts or not tpots:
        print("FAIL: completed traces carry no TTFT/TPOT")
        return 1

    def stats(vals):
        return {
            "n": len(vals),
            "mean_ms": round(statistics.mean(vals), 3),
            "min_ms": round(min(vals), 3),
            "max_ms": round(max(vals), 3),
            "median_ms": round(statistics.median(vals), 3),
        }

    summary = {
        "model": "debug:tiny",
        "requests": args.requests,
        "max_tokens": args.max_tokens,
        "wall_seconds": round(time.monotonic() - t_boot, 2),
        "ttft": stats(ttfts),
        "tpot": stats(tpots),
        "tokens_per_second": [
            t["attrs"].get("tokens_per_second") for t in traces
        ],
        "engine": {
            k: v for k, v in engine_metrics.items() if k != "active_slots"
        },
    }
    if racecheck_summary is not None:
        summary["racecheck"] = racecheck_summary
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
    with open(args.flight_out, "w") as f:
        json.dump(flight_snapshot, f, indent=2, sort_keys=True)
    print(f"OK: engine telemetry present; summary → {args.out}, "
          f"flight ring → {args.flight_out}, "
          f"batch result → {args.batch_out}, "
          f"fleet flight → {args.fleet_flight_out}, "
          f"usage → {args.usage_out}, "
          f"anatomy → {args.anatomy_out}, "
          f"autoscale → {args.autoscale_out}, "
          f"profiles → {args.profile_dir}/manifest.json"
          + (f", loopsan → {args.loopsan_out}" if args.loopsan else ""))
    print(f"    ttft mean {summary['ttft']['mean_ms']}ms  "
          f"tpot mean {summary['tpot']['mean_ms']}ms  "
          f"over {len(ttfts)} requests; "
          f"step p50 {flight_pct['step_ms_p50']}ms "
          f"p99 {flight_pct['step_ms_p99']}ms "
          f"over {flight_pct['samples']} dispatches")
    return 0


if __name__ == "__main__":
    sys.exit(main())
