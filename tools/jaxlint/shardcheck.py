"""shardcheck: PartitionSpec / shard_map specs vs the declared mesh.

The mesh axis names are a string-typed API: a ``PartitionSpec("modle")``
typo compiles fine and silently serves an unsharded (or wrongly
sharded) layout. This pass validates every axis string against the
axes the project actually declares (``AXES`` in
``localai_tpu/parallel/mesh.py``, discovered relative to the scanned
tree so fixtures can carry their own), checks ``shard_map`` spec arity
against the wrapped function's signature, and flags host
materialization of values produced by ``shard_map``/sharded
``device_put`` — each of those gathers the full global array through
one host.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator, Optional

from tools.jaxlint.core import Finding, Module

# fallback when no mesh.py is reachable from the scanned tree
DEFAULT_AXES = ("data", "seq", "pipe", "expert", "model")

MESH_REL_PATHS = (
    Path("localai_tpu") / "parallel" / "mesh.py",
    Path("parallel") / "mesh.py",
)

HOST_SYNC_FNS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                 "jax.device_get"}


def _axes_from_source(path: Path) -> Optional[tuple]:
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "AXES"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            vals = [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
            if vals:
                return tuple(vals)
    return None


class _AxisRegistry:
    """Discovers the declared mesh axes for a scanned file by walking up
    from the file toward a ``parallel/mesh.py``; results cached per
    directory so a whole-tree lint parses mesh.py once."""

    def __init__(self):
        self._by_dir: dict[Path, tuple] = {}

    def axes_for(self, module_path: str) -> tuple:
        d = Path(module_path).resolve().parent
        probe = d
        seen = []
        while True:
            if probe in self._by_dir:
                axes = self._by_dir[probe]
                break
            seen.append(probe)
            for rel in MESH_REL_PATHS:
                cand = probe / rel
                if cand.is_file():
                    axes = _axes_from_source(cand) or DEFAULT_AXES
                    break
            else:
                if probe.parent == probe:
                    axes = DEFAULT_AXES
                    break
                probe = probe.parent
                continue
            break
        for p in seen:
            self._by_dir[p] = axes
        return axes


_REGISTRY = _AxisRegistry()


def _is_partition_spec(module: Module, func) -> bool:
    name = module.dotted(func) or ""
    return name.endswith("PartitionSpec") or name in ("P", "jax.P")


def _is_named_helper(module: Module, func) -> bool:
    """The repo's ``named(mesh, *spec)`` NamedSharding helper."""
    name = module.dotted(func) or ""
    return name == "named" or name.endswith(".named")


def _is_shard_map(module: Module, func) -> bool:
    name = module.dotted(func) or ""
    return name == "shard_map" or name.endswith(".shard_map")


class MeshAxisSpec:
    """Axis names in PartitionSpec / named() not declared on the mesh."""

    id = "unknown-mesh-axis"
    doc = ("PartitionSpec/named() axis string not among the mesh axes "
           "declared in parallel/mesh.py (AXES)")

    def check(self, module: Module) -> Iterator[Finding]:
        axes = None
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_partition_spec(module, node.func):
                args = node.args
            elif _is_named_helper(module, node.func):
                args = node.args[1:]  # named(mesh, *spec)
            else:
                continue
            for arg in args:
                for bad in self._bad_axes(module, arg):
                    if axes is None:
                        axes = _REGISTRY.axes_for(module.path)
                    if bad in axes:
                        continue
                    yield module.finding(
                        node, self.id,
                        f"axis {bad!r} is not a declared mesh axis "
                        f"{_REGISTRY.axes_for(module.path)}; a typo here "
                        f"silently mis-shards the array",
                    )

    def _bad_axes(self, module, arg) -> Iterator[str]:
        """String constants inside one spec element (axis or axis tuple);
        every string is a candidate (validity is judged by the caller)."""
        for n in ast.walk(arg):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                yield n.value


class ShardMapArity:
    """shard_map in_specs arity vs the wrapped function's signature."""

    id = "shard-map-arity"
    doc = ("shard_map(f, in_specs=...) spec count does not match the "
           "wrapped function's positional signature")

    def check(self, module: Module) -> Iterator[Finding]:
        # index module-level + nested function defs by name for resolution
        defs: dict[str, ast.AST] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and _is_shard_map(module, node.func)):
                continue
            in_specs = None
            for kw in node.keywords:
                if kw.arg == "in_specs":
                    in_specs = kw.value
            if in_specs is None or not isinstance(
                    in_specs, (ast.Tuple, ast.List)):
                continue  # single spec or opaque expression: no arity
            n_specs = len(in_specs.elts)
            target = node.args[0] if node.args else None
            params = self._positional_params(target, defs)
            if params is None or params == n_specs:
                continue
            name = (getattr(target, "id", None)
                    or ("<lambda>" if isinstance(target, ast.Lambda)
                        else "<fn>"))
            yield module.finding(
                node, self.id,
                f"shard_map wraps {name} taking {params} positional "
                f"argument(s) but in_specs has {n_specs} spec(s); the "
                f"mismatch raises only at trace time",
            )

    def _positional_params(self, target, defs) -> Optional[int]:
        fn = None
        if isinstance(target, ast.Lambda):
            fn = target
        elif isinstance(target, ast.Name):
            fn = defs.get(target.id)
        if fn is None:
            return None
        args = fn.args
        if args.vararg is not None:
            return None  # *args absorbs any arity
        return len(args.posonlyargs) + len(args.args)


class HostSyncOnSharded:
    """Host materialization of a sharded value.

    ``.item()`` / ``np.asarray`` / ``float()`` on a value produced by
    ``shard_map`` (or placed with a NamedSharding) gathers every shard
    through one host — on a real mesh that is an all-device sync plus a
    full-array device→host copy on the hot path.

    A ProjectRule since the loopcheck PR: a local assigned from a
    project function that *returns* a sharded value (directly or
    transitively — the call graph tracks it) counts as sharded too, so
    ``out = build_sharded(x)`` one helper away no longer hides the
    gather.
    """

    id = "host-sync-on-sharded"
    doc = (".item()/np.asarray/float() on a value produced by shard_map "
           "or sharded device_put — gathers all shards through the host")

    SHARDED_SRC = re.compile(
        r"\b(shard_map\s*\(|NamedSharding\s*\(|device_put\s*\(.*"
        r"(named\s*\(|NamedSharding\s*\(|P\s*\())")

    def __init__(self):
        self._modules: list[Module] = []

    def collect(self, module: Module) -> None:
        self._modules.append(module)

    def finalize(self) -> Iterator[Finding]:
        from tools.jaxlint.callgraph import build_graph

        graph = build_graph(self._modules)
        for module in self._modules:
            if Path(module.path).name.startswith(("test_", "conftest")):
                continue  # tests gather sharded outputs on purpose
            scopes = [module.tree] + [
                n for n in ast.walk(module.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            for scope in scopes:
                yield from self._check_scope(module, scope, graph)

    @staticmethod
    def _scope_cls(module: Module, scope) -> Optional[str]:
        for anc in module.ancestors(scope):
            if isinstance(anc, ast.ClassDef):
                return anc.name
        return None

    @staticmethod
    def _scope_nodes(scope):
        """Walk ``scope`` without descending into nested function defs
        (each scope is analyzed exactly once)."""
        own = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, own):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_scope(self, module, scope, graph) -> Iterator[Finding]:
        cls = self._scope_cls(module, scope)
        sharded: set[str] = set()
        for node in self._scope_nodes(scope):
            if isinstance(node, ast.Assign):
                try:
                    src = ast.unparse(node.value)
                except Exception:
                    continue
                produced = bool(self.SHARDED_SRC.search(src))
                if not produced:
                    # a call (possibly `f(...)(x)`) whose project callee
                    # returns a sharded value — helper indirection
                    call = node.value
                    if (isinstance(call, ast.Call)
                            and isinstance(call.func, ast.Call)):
                        call = call.func
                    if isinstance(call, ast.Call):
                        key = graph.resolve_call(module, cls, call)
                        produced = (key is not None
                                    and graph.returns_sharded(key))
                if produced:
                    for t in node.targets:
                        elts = (t.elts if isinstance(t, (ast.Tuple,
                                                         ast.List))
                                else [t])
                        sharded.update(e.id for e in elts
                                       if isinstance(e, ast.Name))
        if not sharded:
            return
        for node in self._scope_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            hit = self._sync_arg(module, node)
            if hit is None:
                continue
            what, arg = hit
            root = arg
            while isinstance(root, (ast.Subscript, ast.Attribute)):
                root = root.value
            if isinstance(root, ast.Name) and root.id in sharded:
                yield module.finding(
                    node, self.id,
                    f"{what} on {root.id!r}, which holds a sharded value "
                    f"(assigned from shard_map/NamedSharding in this "
                    f"scope); gather once off the hot path or keep it "
                    f"device-side",
                )

    def _sync_arg(self, module, node):
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr == "item"
                and not node.args):
            return "`.item()`", func.value
        name = module.dotted(func)
        if name in HOST_SYNC_FNS and node.args:
            return f"`{name}(...)`", node.args[0]
        if (isinstance(func, ast.Name) and func.id in ("int", "float")
                and len(node.args) == 1
                and not isinstance(node.args[0], ast.Constant)):
            return f"`{func.id}()`", node.args[0]
        return None
