"""CLI: ``python -m tools.jaxlint [paths...]``.

Exit status: 0 when every finding is covered by the baseline (or there
are none), 1 when new findings (or parse errors) exist. Run with
``--write-baseline`` after an intentional change to re-accept the
current findings.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

from tools.jaxlint.core import Baseline, lint_paths
from tools.jaxlint.rules import ALL_RULES

DEFAULT_BASELINE = Path("tools/jaxlint/baseline.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="JAX-aware static analysis (host syncs, re-jits, "
                    "tracer control flow, PRNG reuse, config drift).",
    )
    ap.add_argument("paths", nargs="*", default=["."],
                    help="files or directories to lint (default: .)")
    ap.add_argument("--baseline", type=Path, default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} "
                         f"when it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring any baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current findings into the baseline file")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite the baseline dropping stale entries "
                         "(fixed findings) — never adds new ones")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule ids and one-line docs, then exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}: {rule.doc}")
        return 0

    findings = lint_paths(args.paths)

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        parse_errors = [f for f in findings if f.rule == "parse-error"]
        baseline_path.parent.mkdir(parents=True, exist_ok=True)
        Baseline.from_findings(findings).write(baseline_path)
        print(f"jaxlint: wrote {len(findings) - len(parse_errors)} "
              f"finding(s) to {baseline_path}")
        for f in parse_errors:
            print(f.render())
        if parse_errors:
            print("jaxlint: parse errors cannot be baselined — fix them",
                  file=sys.stderr)
            return 1
        return 0

    stale: list[tuple] = []
    if not args.no_baseline and baseline_path.is_file():
        baseline = Baseline.load(baseline_path)
        new, stale = baseline.filter(findings)
        suppressed = len(findings) - len(new)
    else:
        baseline, new, suppressed = None, findings, 0

    if args.prune_baseline:
        if baseline is None:
            print("jaxlint: --prune-baseline needs a baseline file",
                  file=sys.stderr)
            return 1
        if stale:
            for k in stale:
                baseline.entries.pop(k, None)
            # keep absorbed counts exact: re-derive from what actually
            # matched this run (a partially-stale multi-count entry
            # shrinks rather than disappearing)
            matched = Baseline.from_findings(
                [f for f in findings if f not in new])
            baseline.entries = {
                k: min(c, matched.entries.get(k, 0))
                for k, c in baseline.entries.items()
                if matched.entries.get(k, 0) > 0
            }
            baseline.write(baseline_path)
        print(f"jaxlint: pruned {len(stale)} stale entr"
              f"{'y' if len(stale) == 1 else 'ies'} from "
              f"{baseline_path}")
        stale = []

    for f in new:
        print(f.render())
    if stale:
        note = (f"{len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} (fixed findings) "
                f"— run --prune-baseline")
        print(f"jaxlint: note: {note}", file=sys.stderr)
        if os.environ.get("GITHUB_ACTIONS"):
            # surfaces as an annotation on the workflow run
            print(f"::warning title=jaxlint stale baseline::{note}")
            for file, rule, text in stale:
                print(f"::warning file={file},title=stale baseline "
                      f"entry::{rule}: {text}")
    tail = f" ({suppressed} baselined)" if suppressed else ""
    print(f"jaxlint: {len(new)} finding(s){tail}", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
